"""In-process mock HTTP servers for io/serving/cognitive suites — the
reference pattern of starting real servers and hitting them with real
clients (``io/split2/HTTPv2Suite.scala``, ``DistributedHTTPSuite``)."""

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer


class MockService:
    """Configurable echo/JSON server. ``behavior(path, body_dict) -> (status,
    payload_dict, extra_headers)``."""

    def __init__(self, behavior=None):
        self.behavior = behavior or (lambda path, body: (200, {"echo": body}, {}))
        self.requests = []
        self._lock = threading.Lock()
        mock = self

        class Handler(BaseHTTPRequestHandler):
            def _respond(self):
                length = int(self.headers.get("Content-Length", 0))
                raw = self.rfile.read(length)
                try:
                    body = json.loads(raw) if raw else None
                except json.JSONDecodeError:
                    body = raw.decode("utf-8", "replace")
                with mock._lock:
                    mock.requests.append({
                        "path": self.path,
                        "method": self.command,
                        "headers": dict(self.headers),
                        "body": body,
                    })
                status, payload, extra = mock.behavior(self.path, body)
                data = json.dumps(payload).encode("utf-8")
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                for k, v in extra.items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(data)

            do_POST = do_GET = do_PUT = _respond

            def log_message(self, *args):
                pass

        self._httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.port = self._httpd.server_address[1]
        self.url = f"http://127.0.0.1:{self.port}"

    def start(self):
        threading.Thread(target=self._httpd.serve_forever, daemon=True).start()
        return self

    def stop(self):
        self._httpd.shutdown()
        self._httpd.server_close()

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
