"""graftlint rule tests: per rule a positive (violation), a negative
(clean), and a suppressed fixture, plus driver/CLI behavior."""

import subprocess
import sys

import pytest

from mmlspark_tpu.analysis import all_rules
from mmlspark_tpu.analysis.lint import lint_paths, lint_source, main


def rules_of(violations):
    return [v.rule for v in violations]


# ---------------------------------------------------------------------------
# jit-purity
# ---------------------------------------------------------------------------


class TestJitPurity:
    def test_flags_time_and_print_in_jitted(self):
        src = (
            "import time\n"
            "import jax\n"
            "@jax.jit\n"
            "def f(x):\n"
            "    t = time.time()\n"
            "    print(x)\n"
            "    return x + t\n"
        )
        found = rules_of(lint_source(src, select=["jit-purity"]))
        assert found == ["jit-purity", "jit-purity"]

    def test_flags_global_mutation(self):
        src = (
            "import jax\n"
            "_STATE = 0\n"
            "@jax.jit\n"
            "def f(x):\n"
            "    global _STATE\n"
            "    _STATE = 1\n"
            "    return x\n"
        )
        assert rules_of(lint_source(src, select=["jit-purity"])) == ["jit-purity"]

    def test_flags_random_in_callsite_jit(self):
        src = (
            "import jax, random\n"
            "def f(x):\n"
            "    return x * random.random()\n"
            "g = jax.jit(f)\n"
        )
        assert rules_of(lint_source(src, select=["jit-purity"])) == ["jit-purity"]

    def test_clean_outside_jit(self):
        src = (
            "import time\n"
            "def host():\n"
            "    print(time.time())\n"
        )
        assert lint_source(src, select=["jit-purity"]) == []

    def test_suppressed(self):
        src = (
            "import time, jax\n"
            "@jax.jit\n"
            "def f(x):\n"
            "    t = time.time()  # graftlint: disable=jit-purity\n"
            "    return x + t\n"
        )
        assert lint_source(src, select=["jit-purity"]) == []


# ---------------------------------------------------------------------------
# numpy-in-traced-code
# ---------------------------------------------------------------------------


class TestNumpyInTraced:
    def test_flags_np_in_jitted(self):
        src = (
            "import jax\n"
            "import numpy as np\n"
            "@jax.jit\n"
            "def f(x):\n"
            "    return np.sum(x)\n"
        )
        assert rules_of(lint_source(src, select=["numpy-in-traced-code"])) == [
            "numpy-in-traced-code"
        ]

    def test_flags_np_reached_through_call_chain(self):
        src = (
            "import jax\n"
            "import numpy as np\n"
            "def helper(x):\n"
            "    return np.abs(x)\n"
            "@jax.jit\n"
            "def f(x):\n"
            "    return helper(x)\n"
        )
        assert rules_of(lint_source(src, select=["numpy-in-traced-code"])) == [
            "numpy-in-traced-code"
        ]

    def test_lru_cache_is_a_host_boundary(self):
        src = (
            "import functools, jax\n"
            "import numpy as np\n"
            "import jax.numpy as jnp\n"
            "@functools.lru_cache(maxsize=8)\n"
            "def table(n):\n"
            "    return np.arange(n)\n"
            "@jax.jit\n"
            "def f(x):\n"
            "    return x + jnp.asarray(table(4))\n"
        )
        assert lint_source(src, select=["numpy-in-traced-code"]) == []

    def test_dtype_accessors_allowed(self):
        src = (
            "import jax\n"
            "import numpy as np\n"
            "@jax.jit\n"
            "def f(x):\n"
            "    return x.astype(np.float32)\n"
        )
        assert lint_source(src, select=["numpy-in-traced-code"]) == []

    def test_pallas_kernel_covered(self):
        src = (
            "from jax.experimental import pallas as pl\n"
            "import numpy as np\n"
            "import jax, jax.numpy as jnp\n"
            "def kern(x_ref, o_ref):\n"
            "    o_ref[...] = np.maximum(x_ref[...], 0)\n"
            "def run(x):\n"
            "    return pl.pallas_call(kern, out_shape=x)(x)\n"
        )
        assert rules_of(lint_source(src, select=["numpy-in-traced-code"])) == [
            "numpy-in-traced-code"
        ]

    def test_suppressed(self):
        src = (
            "import jax\n"
            "import numpy as np\n"
            "@jax.jit\n"
            "def f(x):\n"
            "    return np.sum(x)  # graftlint: disable=numpy-in-traced-code\n"
        )
        assert lint_source(src, select=["numpy-in-traced-code"]) == []


# ---------------------------------------------------------------------------
# pallas-tile-alignment
# ---------------------------------------------------------------------------


class TestPallasTileAlignment:
    def test_flags_misaligned_lane(self):
        src = (
            "from jax.experimental import pallas as pl\n"
            "spec = pl.BlockSpec((8, 100), lambda i: (i, 0))\n"
        )
        assert rules_of(
            lint_source(src, select=["pallas-tile-alignment"])
        ) == ["pallas-tile-alignment"]

    def test_flags_misaligned_sublane(self):
        src = (
            "from jax.experimental import pallas as pl\n"
            "spec = pl.BlockSpec(block_shape=(5, 128), index_map=lambda i: (i, 0))\n"
        )
        assert rules_of(
            lint_source(src, select=["pallas-tile-alignment"])
        ) == ["pallas-tile-alignment"]

    def test_aligned_and_constant_resolution(self):
        src = (
            "from jax.experimental import pallas as pl\n"
            "_LANE = 128\n"
            "_SUB = 8\n"
            "def build():\n"
            "    tn = _LANE * 2\n"
            "    return pl.BlockSpec((_SUB, tn), lambda i: (i, 0))\n"
        )
        assert lint_source(src, select=["pallas-tile-alignment"]) == []

    def test_size_one_dims_allowed(self):
        src = (
            "from jax.experimental import pallas as pl\n"
            "spec = pl.BlockSpec((1, 128), lambda i: (i, 0))\n"
        )
        assert lint_source(src, select=["pallas-tile-alignment"]) == []

    def test_unresolved_dims_not_flagged(self):
        src = (
            "from jax.experimental import pallas as pl\n"
            "def build(bw):\n"
            "    return pl.BlockSpec((8, bw), lambda i: (i, 0))\n"
        )
        assert lint_source(src, select=["pallas-tile-alignment"]) == []

    def test_suppressed(self):
        src = (
            "from jax.experimental import pallas as pl\n"
            "spec = pl.BlockSpec((8, 3), lambda i: (i, 0))"
            "  # graftlint: disable=pallas-tile-alignment\n"
        )
        assert lint_source(src, select=["pallas-tile-alignment"]) == []


# ---------------------------------------------------------------------------
# lock-discipline
# ---------------------------------------------------------------------------


class TestLockDiscipline:
    PATH = "mmlspark_tpu/runtime/fake.py"  # rule only applies there

    def test_flags_sleep_under_lock(self):
        src = (
            "import threading, time\n"
            "lock = threading.Lock()\n"
            "def f():\n"
            "    with lock:\n"
            "        time.sleep(1)\n"
        )
        assert rules_of(
            lint_source(src, path=self.PATH, select=["lock-discipline"])
        ) == ["lock-discipline"]

    def test_flags_join_and_queue_get(self):
        src = (
            "import threading\n"
            "lock = threading.Lock()\n"
            "def f(t, q):\n"
            "    with lock:\n"
            "        t.join()\n"
            "        q.get(timeout=5)\n"
        )
        assert (
            len(lint_source(src, path=self.PATH, select=["lock-discipline"]))
            == 2
        )

    def test_str_join_and_dict_get_not_flagged(self):
        src = (
            "import threading\n"
            "lock = threading.Lock()\n"
            "def f(d):\n"
            "    with lock:\n"
            "        s = ','.join(['a', 'b'])\n"
            "        v = d.get('key')\n"
            "    return s, v\n"
        )
        assert lint_source(src, path=self.PATH, select=["lock-discipline"]) == []

    def test_streaming_paths_covered(self):
        src = (
            "import threading, time\n"
            "lock = threading.Lock()\n"
            "def f():\n"
            "    with lock:\n"
            "        time.sleep(1)\n"
        )
        assert rules_of(
            lint_source(
                src,
                path="mmlspark_tpu/streaming/fake.py",
                select=["lock-discipline"],
            )
        ) == ["lock-discipline"]

    def test_sweep_paths_covered(self):
        # the many-models plane shares journals and process gangs; a
        # blocking call under one of its locks would stall every bucket
        src = (
            "import threading, time\n"
            "lock = threading.Lock()\n"
            "def f():\n"
            "    with lock:\n"
            "        time.sleep(1)\n"
        )
        assert rules_of(
            lint_source(
                src,
                path="mmlspark_tpu/sweep/fake.py",
                select=["lock-discipline"],
            )
        ) == ["lock-discipline"]

    def test_outside_runtime_serving_not_flagged(self):
        src = (
            "import threading, time\n"
            "lock = threading.Lock()\n"
            "def f():\n"
            "    with lock:\n"
            "        time.sleep(1)\n"
        )
        assert (
            lint_source(
                src, path="mmlspark_tpu/ops/fake.py", select=["lock-discipline"]
            )
            == []
        )

    def test_suppressed(self):
        src = (
            "import threading, time\n"
            "lock = threading.Lock()\n"
            "def f():\n"
            "    with lock:\n"
            "        time.sleep(1)  # graftlint: disable=lock-discipline\n"
        )
        assert lint_source(src, path=self.PATH, select=["lock-discipline"]) == []


# ---------------------------------------------------------------------------
# bare-except-policy
# ---------------------------------------------------------------------------


class TestBareExceptPolicy:
    def test_flags_silent_swallow(self):
        src = (
            "def f():\n"
            "    try:\n"
            "        work()\n"
            "    except Exception:\n"
            "        pass\n"
        )
        assert rules_of(lint_source(src, select=["bare-except-policy"])) == [
            "bare-except-policy"
        ]

    def test_reraise_ok(self):
        src = (
            "def f():\n"
            "    try:\n"
            "        work()\n"
            "    except Exception:\n"
            "        cleanup()\n"
            "        raise\n"
        )
        assert lint_source(src, select=["bare-except-policy"]) == []

    def test_logging_ok(self):
        src = (
            "import logging\n"
            "logger = logging.getLogger(__name__)\n"
            "def f():\n"
            "    try:\n"
            "        work()\n"
            "    except Exception as e:\n"
            "        logger.warning('failed: %s', e)\n"
        )
        assert lint_source(src, select=["bare-except-policy"]) == []

    def test_narrow_exception_ok(self):
        src = (
            "def f():\n"
            "    try:\n"
            "        work()\n"
            "    except ValueError:\n"
            "        pass\n"
        )
        assert lint_source(src, select=["bare-except-policy"]) == []

    def test_noqa_justification(self):
        src = (
            "def f():\n"
            "    try:\n"
            "        work()\n"
            "    except Exception:  # noqa: BLE001 — best-effort cleanup\n"
            "        pass\n"
        )
        assert lint_source(src, select=["bare-except-policy"]) == []

    def test_graftlint_suppression(self):
        src = (
            "def f():\n"
            "    try:\n"
            "        work()\n"
            "    except Exception:  # graftlint: disable=bare-except-policy\n"
            "        pass\n"
        )
        assert lint_source(src, select=["bare-except-policy"]) == []


# ---------------------------------------------------------------------------
# socket-deadline-policy
# ---------------------------------------------------------------------------


class TestSocketDeadlinePolicy:
    PATH = "mmlspark_tpu/serving/fake.py"  # rule only applies there

    def test_flags_urlopen_without_timeout(self):
        src = (
            "import urllib.request\n"
            "def f(url):\n"
            "    return urllib.request.urlopen(url).read()\n"
        )
        assert rules_of(
            lint_source(src, path=self.PATH,
                        select=["socket-deadline-policy"])
        ) == ["socket-deadline-policy"]

    def test_urlopen_with_timeout_ok(self):
        src = (
            "import urllib.request\n"
            "def f(url):\n"
            "    return urllib.request.urlopen(url, timeout=5).read()\n"
        )
        assert lint_source(
            src, path=self.PATH, select=["socket-deadline-policy"]
        ) == []

    def test_flags_create_connection_without_timeout(self):
        src = (
            "import socket\n"
            "def f(port):\n"
            "    return socket.create_connection(('127.0.0.1', port))\n"
        )
        assert rules_of(
            lint_source(src, path=self.PATH,
                        select=["socket-deadline-policy"])
        ) == ["socket-deadline-policy"]

    def test_create_connection_with_timeout_ok(self):
        src = (
            "import socket\n"
            "def f(port):\n"
            "    return socket.create_connection(('x', port), timeout=1.0)\n"
        )
        assert lint_source(
            src, path=self.PATH, select=["socket-deadline-policy"]
        ) == []

    def test_flags_settimeout_none(self):
        src = (
            "def f(conn):\n"
            "    conn.settimeout(None)\n"
        )
        assert rules_of(
            lint_source(src, path="mmlspark_tpu/runtime/fake.py",
                        select=["socket-deadline-policy"])
        ) == ["socket-deadline-policy"]

    def test_settimeout_value_ok(self):
        src = (
            "def f(conn):\n"
            "    conn.settimeout(30.0)\n"
        )
        assert lint_source(
            src, path=self.PATH, select=["socket-deadline-policy"]
        ) == []

    def test_outside_runtime_serving_not_flagged(self):
        src = (
            "import urllib.request\n"
            "def f(url):\n"
            "    return urllib.request.urlopen(url).read()\n"
        )
        assert lint_source(
            src, path="mmlspark_tpu/ops/fake.py",
            select=["socket-deadline-policy"],
        ) == []


# ---------------------------------------------------------------------------
# driver / registry / CLI
# ---------------------------------------------------------------------------


class TestDriver:
    def test_all_builtin_rules_registered(self):
        assert set(all_rules()) == {
            # v1: framework contracts
            "jit-purity",
            "numpy-in-traced-code",
            "pallas-tile-alignment",
            "lock-discipline",
            "bare-except-policy",
            "socket-deadline-policy",
            # v2: concurrency & distributed protocols
            "lock-order",
            "lock-blocking",
            "collective-deadline",
            "collective-rank-branch",
            "wal-before-commit",
            "journal-before-store",
            "tmp-rename-atomicity",
            "onset-recovery-pairing",
        }

    def test_bare_disable_silences_all(self):
        src = (
            "import jax, time\n"
            "@jax.jit\n"
            "def f(x):\n"
            "    return x + time.time()  # graftlint: disable\n"
        )
        assert lint_source(src) == []

    def test_unknown_rule_rejected(self):
        with pytest.raises(KeyError):
            lint_source("x = 1\n", select=["no-such-rule"])

    def test_parse_error_reported_not_raised(self, tmp_path):
        bad = tmp_path / "bad.py"
        bad.write_text("def broken(:\n")
        violations, suppressed, errors = lint_paths([str(bad)])
        assert violations == [] and len(errors) == 1

    def test_main_exit_codes(self, tmp_path):
        clean = tmp_path / "clean.py"
        clean.write_text("x = 1\n")
        dirty = tmp_path / "dirty.py"
        dirty.write_text(
            "import jax, time\n"
            "@jax.jit\n"
            "def f(x):\n"
            "    return x + time.time()\n"
        )
        assert main([str(clean)]) == 0
        assert main([str(dirty), "--fail-on-violation", "-q"]) == 1
        assert main([]) == 2

    @pytest.mark.slow
    def test_module_cli_on_package_is_clean(self):
        proc = subprocess.run(
            [sys.executable, "-m", "mmlspark_tpu.analysis.lint", "mmlspark_tpu/"],
            capture_output=True,
            text=True,
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr


class TestCrossModule:
    def test_jit_reaches_imported_module(self, tmp_path):
        pkg = tmp_path / "mmlspark_tpu"
        pkg.mkdir()
        (pkg / "__init__.py").write_text("")
        (pkg / "kernels.py").write_text(
            "import numpy as np\n"
            "def inner(x):\n"
            "    return np.sum(x)\n"
        )
        (pkg / "driver.py").write_text(
            "import jax\n"
            "from mmlspark_tpu.kernels import inner\n"
            "@jax.jit\n"
            "def step(x):\n"
            "    return inner(x)\n"
        )
        violations, _, errors = lint_paths(
            [str(pkg)], select=["numpy-in-traced-code"]
        )
        assert errors == []
        assert [v.rule for v in violations] == ["numpy-in-traced-code"]
        assert violations[0].path.endswith("kernels.py")
