"""cognitive/ tests against in-process mocks (live Azure endpoints need
egress; the reference tags those suites flaky/secret-gated —
``pipeline.yaml:270-275``)."""

import numpy as np
import pytest

from http_mock import MockService
from mmlspark_tpu.cognitive import (
    AddDocuments,
    BingImageSearch,
    DetectAnomalies,
    TextSentiment,
)
from mmlspark_tpu.data.table import Table


class TestTextSentiment:
    def test_request_shape_and_key_header(self):
        def behavior(path, body):
            assert body["documents"][0]["language"] == "en"
            return 200, {"documents": [{"id": "0", "score": 0.9}]}, {}

        with MockService(behavior) as svc:
            t = Table({"text": np.array(["great product", "awful"], dtype=object)})
            out = TextSentiment(
                url=svc.url, subscriptionKey="k123", textCol="text",
                outputCol="sentiment",
            ).transform(t)
            assert out["sentiment"][0]["documents"][0]["score"] == 0.9
            sent = svc.requests[0]
            assert sent["headers"]["Ocp-Apim-Subscription-Key"] == "k123"

    def test_language_from_column(self):
        with MockService(lambda p, b: (200, b, {})) as svc:
            t = Table({
                "text": np.array(["hola", "hello"], dtype=object),
                "lang": np.array(["es", "en"], dtype=object),
            })
            ts = TextSentiment(url=svc.url, textCol="text", outputCol="o")
            ts.set_vector("language", "lang")
            out = ts.transform(t)
            langs = sorted(r["documents"][0]["language"] for r in out["o"])
            assert langs == ["en", "es"]


class TestDetectAnomalies:
    def test_series_body(self):
        def behavior(path, body):
            assert body["granularity"] == "daily"
            assert len(body["series"]) == 3
            return 200, {"isAnomaly": [False, False, True]}, {}

        series = [
            [{"timestamp": f"2026-01-0{i}", "value": float(v)} for i, v in
             enumerate([1, 1, 99], start=1)]
        ]
        with MockService(behavior) as svc:
            t = Table({"series": np.array(series, dtype=object)})
            out = DetectAnomalies(
                url=svc.url, seriesCol="series", outputCol="anomalies"
            ).transform(t)
            assert out["anomalies"][0]["isAnomaly"][-1] is True


class TestBingImageSearch:
    def test_get_with_query_param(self):
        with MockService(lambda p, b: (200, {"value": []}, {})) as svc:
            t = Table({"q": np.array(["cats"], dtype=object)})
            BingImageSearch(url=svc.url, queryCol="q", outputCol="imgs",
                            count=5).transform(t)
            sent = svc.requests[0]
            assert sent["method"] == "GET"
            assert "q=cats" in sent["path"] and "count=5" in sent["path"]


class TestAddDocuments:
    def test_batched_upload(self):
        with MockService(lambda p, b: (200, {"value": []}, {})) as svc:
            t = Table({
                "id": np.array(["a", "b", "c"], dtype=object),
                "score": np.array([1.0, 2.0, 3.0]),
            })
            out = AddDocuments(
                url=svc.url, subscriptionKey="key", batchSize=2
            ).transform(t)
            assert list(out["indexStatus"]) == [200, 200, 200]
            assert len(svc.requests) == 2  # 2 + 1 docs
            first = svc.requests[0]["body"]["value"]
            assert first[0]["@search.action"] == "upload"
            assert first[0]["id"] == "a" and first[0]["score"] == 1.0
            headers = {k.lower(): v for k, v in svc.requests[0]["headers"].items()}
            assert headers["api-key"] == "key"  # header names are case-insensitive


class TestAsyncPolling:
    def test_recognize_text_polls_operation_location(self):
        """202 + Operation-Location -> poll until succeeded (the real
        ComputerVision.scala async flow)."""
        from mmlspark_tpu.cognitive import RecognizeText

        state = {"polls": 0}

        def behavior(path, body):
            if path.startswith("/op"):
                state["polls"] += 1
                if state["polls"] < 3:
                    return 200, {"status": "Running"}, {}
                return 200, {
                    "status": "Succeeded",
                    "recognitionResult": {"lines": [{"text": "hello tpu"}]},
                }, {}
            return 202, {}, {"Operation-Location": state["base"].rstrip("/") + "/op/1"}

        with MockService(behavior) as svc:
            state["base"] = svc.url
            t = Table({"url": np.array(["http://img/1.png"], dtype=object)})
            rt = RecognizeText(
                url=svc.url, subscriptionKey="k", outputCol="text",
                pollingIntervalMs=5,
            )
            out = rt.transform(t)
            payload = out["text"][0]
            assert payload["status"] == "Succeeded"
            assert payload["recognitionResult"]["lines"][0]["text"] == "hello tpu"
            assert state["polls"] == 3
            # poll requests carry the key header
            poll_reqs = [r for r in svc.requests if r["path"].startswith("/op")]
            assert all(
                r["headers"].get("Ocp-Apim-Subscription-Key") == "k"
                for r in poll_reqs
            )

    def test_polling_timeout_raises(self):
        from mmlspark_tpu.cognitive import RecognizeText

        def behavior(path, body):
            if path.startswith("/op"):
                return 200, {"status": "Running"}, {}
            return 202, {}, {"Operation-Location": behavior.base.rstrip("/") + "/op/1"}

        with MockService(behavior) as svc:
            behavior.base = svc.url
            t = Table({"url": np.array(["x"], dtype=object)})
            rt = RecognizeText(
                url=svc.url, outputCol="o", pollingIntervalMs=1, maxPollingRetries=3,
                errorCol="err",
            )
            out = rt.transform(t)
            # polling timeout surfaces via the error column, not a crash
            assert out["o"][0] is None
            assert "terminal status" in str(out["err"][0])

    def test_column_bound_key_rejected_for_polling(self):
        from mmlspark_tpu.cognitive import RecognizeText

        t = Table({
            "url": np.array(["x"], dtype=object),
            "k": np.array(["key1"], dtype=object),
        })
        rt = RecognizeText(url="http://localhost:1/", outputCol="o")
        rt.set_vector("subscriptionKey", "k")
        with pytest.raises(ValueError, match="constant subscriptionKey"):
            rt.transform(t)


class TestTypedResponses:
    def test_sentiment_typed(self):
        from mmlspark_tpu.cognitive import TextSentiment, schemas

        def behavior(path, body):
            return 200, {"documents": [{"id": "0", "score": 0.83}], "errors": []}, {}

        with MockService(behavior) as svc:
            t = Table({"text": np.array(["nice"], dtype=object)})
            out = TextSentiment(
                url=svc.url, outputCol="s", typed=True
            ).transform(t)
            resp = out["s"][0]
            assert isinstance(resp, schemas.TAResponse)
            assert resp.documents[0].score == 0.83

    def test_face_detect_typed_bare_array(self):
        from mmlspark_tpu.cognitive import DetectFace, schemas

        def behavior(path, body):
            return 200, [
                {"faceId": "f1", "faceRectangle": {"top": 1, "left": 2, "width": 3, "height": 4}}
            ], {}

        with MockService(behavior) as svc:
            t = Table({"url": np.array(["http://img"], dtype=object)})
            out = DetectFace(url=svc.url, outputCol="faces", typed=True).transform(t)
            resp = out["faces"][0]
            assert isinstance(resp, schemas.FaceListResponse)
            assert resp.faces[0].faceId == "f1"
            assert resp.faces[0].faceRectangle.width == 3


class TestFaceServices:
    def test_identify_group_verify_bodies(self):
        from mmlspark_tpu.cognitive import GroupFaces, IdentifyFaces, VerifyFaces

        with MockService(lambda p, b: (200, {"echo": b}, {})) as svc:
            ids = np.empty(1, dtype=object)
            ids[0] = ["f1", "f2"]
            t = Table({"faceIds": ids})
            IdentifyFaces(
                url=svc.url, outputCol="o", personGroupId="grp",
                maxNumOfCandidatesReturned=2,
            ).transform(t)
            body = svc.requests[-1]["body"]
            assert body["faceIds"] == ["f1", "f2"]
            assert body["personGroupId"] == "grp"
            assert body["maxNumOfCandidatesReturned"] == 2

            GroupFaces(url=svc.url, outputCol="o").transform(t)
            assert svc.requests[-1]["body"] == {"faceIds": ["f1", "f2"]}

            t2 = Table({
                "faceId1": np.array(["a"], dtype=object),
                "faceId2": np.array(["b"], dtype=object),
            })
            VerifyFaces(url=svc.url, outputCol="o").transform(t2)
            assert svc.requests[-1]["body"] == {"faceId1": "a", "faceId2": "b"}

    def test_describe_and_tag_image(self):
        from mmlspark_tpu.cognitive import DescribeImage, TagImage, schemas

        def behavior(path, body):
            return 200, {
                "description": {"captions": [{"text": "a cat", "confidence": 0.9}]},
                "tags": [{"name": "cat", "confidence": 0.95}],
            }, {}

        with MockService(behavior) as svc:
            t = Table({"url": np.array(["http://img"], dtype=object)})
            d = DescribeImage(url=svc.url, outputCol="d", typed=True).transform(t)
            assert d["d"][0].description.captions[0].text == "a cat"
            g = TagImage(url=svc.url, outputCol="g", typed=True).transform(t)
            assert g["g"][0].tags[0].name == "cat"


class TestSearchIndex:
    def test_ensure_index_creates_when_missing(self):
        from mmlspark_tpu.cognitive import SearchIndexClient

        def behavior(path, body):
            if body is None:  # GET existence check
                return 404, {"error": "not found"}, {}
            return 201, {"name": body["name"]}, {}

        with MockService(behavior) as svc:
            client = SearchIndexClient(svc.url, api_key="sk")
            created = client.ensure_index({
                "name": "idx1",
                "fields": [
                    {"name": "id", "type": "Edm.String", "key": True},
                    {"name": "text", "type": "Edm.String"},
                ],
            })
            assert created
            put = svc.requests[-1]
            assert put["method"] == "PUT"
            assert put["path"].endswith("/indexes/idx1")
            headers = {k.lower(): v for k, v in put["headers"].items()}
            assert headers["api-key"] == "sk"

    def test_ensure_index_skips_existing(self):
        from mmlspark_tpu.cognitive import SearchIndexClient

        with MockService(lambda p, b: (200, {"name": "idx1"}, {})) as svc:
            client = SearchIndexClient(svc.url)
            created = client.ensure_index({
                "name": "idx1",
                "fields": [{"name": "id", "key": True}],
            })
            assert not created
            assert all(r["method"] == "GET" for r in svc.requests)

    def test_key_field_validation(self):
        from mmlspark_tpu.cognitive import SearchIndexClient

        client = SearchIndexClient("http://localhost:1")
        with pytest.raises(ValueError, match="key field"):
            client.create_index({"name": "x", "fields": [{"name": "a"}]})


class TestPowerBI:
    def test_batched_writes(self):
        from mmlspark_tpu.io import PowerBIWriter

        with MockService(lambda p, b: (200, {}, {})) as svc:
            t = Table({
                "a": np.arange(5, dtype=np.float64),
                "b": np.array(list("vwxyz"), dtype=object),
            })
            out = PowerBIWriter(url=svc.url, batchSize=2).transform(t)
            assert out is t  # pass-through
            bodies = [r["body"] for r in svc.requests]
            assert [len(b) for b in bodies] == [2, 2, 1]
            assert bodies[0][0] == {"a": 0.0, "b": "v"}

    def test_failure_raises(self):
        from mmlspark_tpu.io import write_to_powerbi
        from mmlspark_tpu.io.http.clients import HTTPClient

        with MockService(lambda p, b: (403, {"error": "denied"}, {})) as svc:
            t = Table({"a": np.arange(2, dtype=np.float64)})
            with pytest.raises(RuntimeError, match="403"):
                write_to_powerbi(t, svc.url, client=HTTPClient(retries=()))


class TestPortForwarding:
    def test_relay_round_trip(self):
        import json as _json
        import urllib.request

        from mmlspark_tpu.io.http import PortForwarder

        with MockService(lambda p, b: (200, {"via": "forwarder"}, {})) as svc:
            host, port = svc.url.replace("http://", "").rstrip("/").split(":")
            with PortForwarder(host, int(port)) as fwd:
                req = urllib.request.Request(
                    fwd.url, data=b"{}", method="POST",
                    headers={"Content-Type": "application/json"},
                )
                with urllib.request.urlopen(req, timeout=5) as r:
                    assert _json.loads(r.read()) == {"via": "forwarder"}
