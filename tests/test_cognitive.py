"""cognitive/ tests against in-process mocks (live Azure endpoints need
egress; the reference tags those suites flaky/secret-gated —
``pipeline.yaml:270-275``)."""

import numpy as np
import pytest

from http_mock import MockService
from mmlspark_tpu.cognitive import (
    AddDocuments,
    BingImageSearch,
    DetectAnomalies,
    TextSentiment,
)
from mmlspark_tpu.data.table import Table


class TestTextSentiment:
    def test_request_shape_and_key_header(self):
        def behavior(path, body):
            assert body["documents"][0]["language"] == "en"
            return 200, {"documents": [{"id": "0", "score": 0.9}]}, {}

        with MockService(behavior) as svc:
            t = Table({"text": np.array(["great product", "awful"], dtype=object)})
            out = TextSentiment(
                url=svc.url, subscriptionKey="k123", textCol="text",
                outputCol="sentiment",
            ).transform(t)
            assert out["sentiment"][0]["documents"][0]["score"] == 0.9
            sent = svc.requests[0]
            assert sent["headers"]["Ocp-Apim-Subscription-Key"] == "k123"

    def test_language_from_column(self):
        with MockService(lambda p, b: (200, b, {})) as svc:
            t = Table({
                "text": np.array(["hola", "hello"], dtype=object),
                "lang": np.array(["es", "en"], dtype=object),
            })
            ts = TextSentiment(url=svc.url, textCol="text", outputCol="o")
            ts.set_vector("language", "lang")
            out = ts.transform(t)
            langs = sorted(r["documents"][0]["language"] for r in out["o"])
            assert langs == ["en", "es"]


class TestDetectAnomalies:
    def test_series_body(self):
        def behavior(path, body):
            assert body["granularity"] == "daily"
            assert len(body["series"]) == 3
            return 200, {"isAnomaly": [False, False, True]}, {}

        series = [
            [{"timestamp": f"2026-01-0{i}", "value": float(v)} for i, v in
             enumerate([1, 1, 99], start=1)]
        ]
        with MockService(behavior) as svc:
            t = Table({"series": np.array(series, dtype=object)})
            out = DetectAnomalies(
                url=svc.url, seriesCol="series", outputCol="anomalies"
            ).transform(t)
            assert out["anomalies"][0]["isAnomaly"][-1] is True


class TestBingImageSearch:
    def test_get_with_query_param(self):
        with MockService(lambda p, b: (200, {"value": []}, {})) as svc:
            t = Table({"q": np.array(["cats"], dtype=object)})
            BingImageSearch(url=svc.url, queryCol="q", outputCol="imgs",
                            count=5).transform(t)
            sent = svc.requests[0]
            assert sent["method"] == "GET"
            assert "q=cats" in sent["path"] and "count=5" in sent["path"]


class TestAddDocuments:
    def test_batched_upload(self):
        with MockService(lambda p, b: (200, {"value": []}, {})) as svc:
            t = Table({
                "id": np.array(["a", "b", "c"], dtype=object),
                "score": np.array([1.0, 2.0, 3.0]),
            })
            out = AddDocuments(
                url=svc.url, subscriptionKey="key", batchSize=2
            ).transform(t)
            assert list(out["indexStatus"]) == [200, 200, 200]
            assert len(svc.requests) == 2  # 2 + 1 docs
            first = svc.requests[0]["body"]["value"]
            assert first[0]["@search.action"] == "upload"
            assert first[0]["id"] == "a" and first[0]["score"] == 1.0
            headers = {k.lower(): v for k, v in svc.requests[0]["headers"].items()}
            assert headers["api-key"] == "key"  # header names are case-insensitive
