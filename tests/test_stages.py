"""Generic pipeline stages (reference ``stages/`` test suites, SURVEY.md §2.11)."""

import numpy as np
import pytest

from mmlspark_tpu.data.table import Table
from mmlspark_tpu.stages import (
    Cacher,
    ClassBalancer,
    DropColumns,
    DynamicMiniBatchTransformer,
    EnsembleByKey,
    Explode,
    FixedMiniBatchTransformer,
    FlattenBatch,
    Lambda,
    MultiColumnAdapter,
    RenameColumn,
    Repartition,
    SelectColumns,
    StratifiedRepartition,
    SummarizeData,
    TextPreprocessor,
    TimeIntervalMiniBatchTransformer,
    Timer,
    UDFTransformer,
    UnicodeNormalize,
    get_value_at,
    to_vector,
)


def test_select_drop_rename(basic_table):
    t = SelectColumns(cols=["numbers", "words"]).transform(basic_table)
    assert t.columns == ["numbers", "words"]
    t = DropColumns(cols=["doubles"]).transform(basic_table)
    assert t.columns == ["numbers", "words"]
    with pytest.raises(KeyError):
        DropColumns(cols=["nope"]).transform(basic_table)
    t = RenameColumn(inputCol="words", outputCol="instruments").transform(basic_table)
    assert "instruments" in t.columns and "words" not in t.columns


def test_cacher_repartition(basic_table):
    assert Cacher().transform(basic_table) is basic_table
    t = Repartition(n=2).transform(basic_table)
    assert t.num_partitions == 2
    assert Repartition(n=2, disable=True).transform(basic_table).num_partitions == 1


def test_stratified_repartition():
    # 8 rows of label 0, 4 of label 1, 4 partitions: every partition must
    # contain both labels afterwards (reference VerifyStratifiedRepartition).
    labels = np.array([0] * 8 + [1] * 4)
    t = Table({"label": labels, "x": np.arange(12)}, num_partitions=4)
    out = StratifiedRepartition(labelCol="label").transform(t)
    for part in out.partitions():
        assert set(np.unique(part["label"])) == {0, 1}
    # 'mixed' partially upsamples the minority; every source row id is valid.
    assert set(out["x"]) <= set(range(12))

    # 'original' keeps the row multiset exactly.
    out = StratifiedRepartition(labelCol="label", mode="original").transform(t)
    assert sorted(out["x"]) == list(range(12))
    for part in out.partitions():
        assert set(np.unique(part["label"])) == {0, 1}

    # 'equal' upsamples with replacement so label counts match.
    out = StratifiedRepartition(labelCol="label", mode="equal").transform(t)
    counts = {v: int((out["label"] == v).sum()) for v in (0, 1)}
    assert counts[0] == counts[1] == 8


def test_stratified_repartition_uneven_split():
    # Row count not divisible by partitions: coverage must still hold
    # (regression: round-robin dealing misaligned with linspace bounds).
    t = Table(
        {"label": np.array(["a", "a", "a", "b", "b"], dtype=object), "x": np.arange(5)},
        num_partitions=2,
    )
    out = StratifiedRepartition(labelCol="label", mode="original").transform(t)
    for part in out.partitions():
        assert set(part["label"]) == {"a", "b"}
    assert sorted(out["x"]) == list(range(5))


def test_text_preprocessor_length_changing_fold():
    # 'İ'.lower() is two chars; offsets must not shift (regression).
    t = Table({"text": np.array(["İstanbul is big"], dtype=object)})
    out = TextPreprocessor(
        inputCol="text", outputCol="out", map={"big": "huge"}, normFunc="lowerCase"
    ).transform(t)
    assert list(out["out"]) == ["İstanbul is huge"]


def test_class_balancer():
    t = Table({"label": np.array([0, 0, 0, 1])})
    model = ClassBalancer(inputCol="label").fit(t)
    out = model.transform(t)
    np.testing.assert_allclose(out["weight"], [1.0, 1.0, 1.0, 3.0])


def test_explode():
    t = Table({"k": np.array([1, 2]), "vals": [[10, 20, 30], [40]]})
    out = Explode(inputCol="vals").transform(t)
    assert list(out["k"]) == [1, 1, 1, 2]
    assert list(out["vals"]) == [10, 20, 30, 40]


def test_lambda_and_udf(basic_table):
    lam = Lambda(transformFunc=lambda t: t.with_column("twice", t["numbers"] * 2))
    out = lam.transform(basic_table)
    np.testing.assert_array_equal(out["twice"], [0, 2, 4, 6])

    u = UDFTransformer(inputCol="doubles", outputCol="plus1", udf=lambda c: c + 1)
    np.testing.assert_allclose(u.transform(basic_table)["plus1"], [1.0, 2.5, 3.5, 4.5])

    u2 = UDFTransformer(
        inputCols=["numbers", "doubles"], outputCol="sum", udf=lambda a, b: a + b
    )
    np.testing.assert_allclose(u2.transform(basic_table)["sum"], [0.0, 2.5, 4.5, 6.5])


def test_multi_column_adapter(basic_table):
    base = UDFTransformer(udf=lambda c: c.astype(np.float64) * 10)
    adapter = MultiColumnAdapter(
        baseStage=base,
        inputCols=["numbers", "doubles"],
        outputCols=["n10", "d10"],
    )
    out = adapter.transform(basic_table)
    np.testing.assert_allclose(out["n10"], [0, 10, 20, 30])
    np.testing.assert_allclose(out["d10"], [0, 15, 25, 35])


def test_text_preprocessor():
    t = Table({"text": np.array(["The Happy sad", "JE T'aime"], dtype=object)})
    out = TextPreprocessor(
        inputCol="text",
        outputCol="out",
        map={"Happy": "glad", "sad": "blue", "je t'aime": "i love you"},
        normFunc="lowerCase",
    ).transform(t)
    # Keys are normalized like the text; unmatched spans keep original casing.
    assert list(out["out"]) == ["The glad blue", "i love you"]


def test_unicode_normalize():
    t = Table({"text": np.array(["Ça va Bien", "ﬁne"], dtype=object)})
    out = UnicodeNormalize(inputCol="text", outputCol="out", form="NFKD").transform(t)
    assert "fine" in list(out["out"])[1]


def test_timer(basic_table, caplog):
    import logging

    stage = UDFTransformer(inputCol="numbers", outputCol="n2", udf=lambda c: c * 2)
    with caplog.at_level(logging.INFO, logger="mmlspark_tpu.stages"):
        model = Timer(stage=stage).fit(basic_table)
        out = model.transform(basic_table)
    np.testing.assert_array_equal(out["n2"], [0, 2, 4, 6])
    assert any("transform took" in r.message for r in caplog.records)


def test_ensemble_by_key():
    t = Table(
        {
            "key": np.array(["a", "a", "b"], dtype=object),
            "score": np.array([1.0, 3.0, 5.0]),
            "vec": np.array([[1.0, 0.0], [3.0, 2.0], [5.0, 4.0]]),
        }
    )
    out = EnsembleByKey(keys=["key"], cols=["score", "vec"]).transform(t)
    assert out.num_rows == 2
    by_key = {out["key"][i]: i for i in range(2)}
    assert out["mean(score)"][by_key["a"]] == 2.0
    np.testing.assert_allclose(out["mean(vec)"][by_key["a"]], [2.0, 1.0])
    # Non-collapsed: aggregate broadcast back to rows.
    out2 = EnsembleByKey(keys=["key"], cols=["score"], collapseGroup=False).transform(t)
    np.testing.assert_allclose(out2["mean(score)"], [2.0, 2.0, 5.0])


def test_summarize_data(basic_table):
    out = SummarizeData().transform(basic_table)
    assert out.num_rows == 3
    row = {out["Feature"][i]: i for i in range(3)}
    assert out["Count"][row["numbers"]] == 4.0
    assert out["Mean"][row["doubles"]] == pytest.approx(1.875)
    assert np.isnan(out["Mean"][row["words"]])


def test_fixed_minibatch_roundtrip(basic_table):
    batched = FixedMiniBatchTransformer(batchSize=3).transform(basic_table)
    assert batched.num_rows == 2
    assert len(batched["numbers"][0]) == 3 and len(batched["numbers"][1]) == 1
    flat = FlattenBatch().transform(batched)
    np.testing.assert_array_equal(flat["numbers"], basic_table["numbers"])
    assert list(flat["words"]) == list(basic_table["words"])


def test_dynamic_minibatch():
    t = Table({"x": np.arange(10)}, num_partitions=2)
    batched = DynamicMiniBatchTransformer().transform(t)
    assert batched.num_rows == 2  # one batch per partition
    batched = DynamicMiniBatchTransformer(maxBatchSize=3).transform(t)
    assert [len(b) for b in batched["x"]] == [3, 2, 3, 2]


def test_time_interval_minibatch():
    ts = np.array([0, 10, 20, 5000, 5010], dtype=np.int64)
    t = Table({"ts": ts, "x": np.arange(5)})
    batched = TimeIntervalMiniBatchTransformer(
        millisToWait=1000, timestampCol="ts"
    ).transform(t)
    assert [len(b) for b in batched["x"]] == [3, 2]


def test_vector_batched_roundtrip():
    t = Table({"vec": np.arange(12, dtype=np.float64).reshape(6, 2)})
    batched = FixedMiniBatchTransformer(batchSize=4).transform(t)
    assert batched["vec"][0].shape == (4, 2)
    flat = FlattenBatch().transform(batched)
    np.testing.assert_allclose(flat["vec"], t["vec"])


def test_udfs_helpers():
    col = np.array([[1.0, 2.0], [3.0, 4.0]])
    np.testing.assert_allclose(get_value_at(col, 1), [2.0, 4.0])
    ragged = np.empty(2, dtype=object)
    ragged[0], ragged[1] = [1.0, 2.0], [3.0, 4.0]
    np.testing.assert_allclose(get_value_at(ragged, 0), [1.0, 3.0])
    np.testing.assert_allclose(to_vector([[1, 2], [3, 4]]), [[1.0, 2.0], [3.0, 4.0]])


def test_stage_serialization_roundtrip(tmp_path, basic_table, table_equal):
    stages = [
        SelectColumns(cols=["numbers", "doubles"]),
        FixedMiniBatchTransformer(batchSize=2),
        FlattenBatch(),
        UnicodeNormalize(inputCol="words", outputCol="norm"),
        TextPreprocessor(inputCol="words", outputCol="pp", map={"drums": "beats"}),
        EnsembleByKey(keys=["words"], cols=["doubles"]),
        SummarizeData(),
    ]
    from mmlspark_tpu.core.pipeline import PipelineStage

    for i, stage in enumerate(stages):
        p = str(tmp_path / f"stage_{i}")
        stage.save(p)
        loaded = PipelineStage.load(p)
        assert type(loaded) is type(stage)
        table_equal(loaded.transform(basic_table), stage.transform(basic_table))
