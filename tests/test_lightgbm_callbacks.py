"""Training delegate/callback hooks (``LightGBMDelegate.scala``; dynamic LR
per ``TrainUtils.scala:211-218`` and ``VerifyLightGBMClassifier.scala:394``)."""

import numpy as np
import pytest

from mmlspark_tpu.data.table import Table
from mmlspark_tpu.lightgbm.binning import bin_dataset
from mmlspark_tpu.lightgbm.callbacks import (
    CallbackEnv,
    LearningRateSchedule,
    TrainingCallback,
)
from mmlspark_tpu.lightgbm.classifier import LightGBMClassifier
from mmlspark_tpu.lightgbm.train import TrainOptions, train


def _data(n=800, f=6, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f))
    y = ((X[:, 0] + X[:, 1] * X[:, 2]) > 0).astype(np.float64)
    return X, y


def _opts(**kw):
    base = dict(objective="binary", num_iterations=6, num_leaves=7, max_bin=31)
    base.update(kw)
    return TrainOptions(**base)


class TestLearningRateSchedule:
    def test_decayed_lr_equals_retrained_constant_lr_per_tree(self):
        """A constant schedule must reproduce the plain fit exactly (the
        schedule rides the scan fast path as data, not as a new program)."""
        X, y = _data()
        bins, mapper = bin_dataset(X, max_bin=31)
        r_plain = train(bins, y, _opts(), mapper=mapper)
        r_sched = train(
            bins, y, _opts(), mapper=mapper,
            callbacks=[LearningRateSchedule(lambda it: 0.1)],
        )
        np.testing.assert_allclose(
            r_sched.booster.leaf_values, r_plain.booster.leaf_values, rtol=1e-6
        )

    def test_decay_changes_later_trees_only(self):
        """Iteration 0 trains identically under lr(0)=0.1; the decayed rates
        reshape subsequent trees."""
        X, y = _data()
        bins, mapper = bin_dataset(X, max_bin=31)
        r_plain = train(bins, y, _opts(), mapper=mapper)
        r_decay = train(
            bins, y, _opts(), mapper=mapper,
            callbacks=[LearningRateSchedule(lambda it: 0.1 * (0.5 ** it))],
        )
        np.testing.assert_allclose(
            r_decay.booster.leaf_values[0], r_plain.booster.leaf_values[0], rtol=1e-6
        )
        assert not np.allclose(
            r_decay.booster.leaf_values[1], r_plain.booster.leaf_values[1]
        )

    def test_list_schedule_and_scaling(self):
        """lr=0.2 throughout == leaf values exactly 2x the lr=0.1 first tree
        (leaf value is linear in lr)."""
        X, y = _data()
        bins, mapper = bin_dataset(X, max_bin=31)
        r1 = train(bins, y, _opts(num_iterations=1), mapper=mapper)
        r2 = train(
            bins, y, _opts(num_iterations=1), mapper=mapper,
            callbacks=[LearningRateSchedule([0.2])],
        )
        np.testing.assert_allclose(
            r2.booster.leaf_values, r1.booster.leaf_values * 2.0, rtol=1e-5
        )


class TestIterationHooks:
    def test_hooks_fire_in_order_with_env(self):
        X, y = _data()
        bins, mapper = bin_dataset(X, max_bin=31)
        log = []

        class Recorder(TrainingCallback):
            def before_training(self, env):
                log.append(("before_training", env.iteration))

            def before_iteration(self, env):
                log.append(("before", env.iteration))

            def after_iteration(self, env):
                log.append(("after", env.iteration))
                return None

            def after_training(self, env):
                log.append(("after_training", env.iteration))

        train(bins, y, _opts(num_iterations=3), mapper=mapper, callbacks=[Recorder()])
        assert log[0] == ("before_training", 0)
        assert log[-1] == ("after_training", 2)
        inner = log[1:-1]
        assert inner == [
            ("before", 0), ("after", 0),
            ("before", 1), ("after", 1),
            ("before", 2), ("after", 2),
        ]

    def test_after_iteration_stop_truncates_training(self):
        X, y = _data()
        bins, mapper = bin_dataset(X, max_bin=31)

        class StopAt2(TrainingCallback):
            def after_iteration(self, env):
                return env.iteration >= 1  # stop after the 2nd tree

        r = train(bins, y, _opts(num_iterations=10), mapper=mapper,
                  callbacks=[StopAt2()])
        assert r.booster.num_trees == 2

    def test_delegate_stop_composes_with_metric_early_stopping(self):
        """Dynamic-LR delegate + metric early stopping together — the
        VerifyLightGBMClassifier.scala:394 interaction. The delegate's LR
        decay must not break the metric early-stop bookkeeping."""
        X, y = _data(n=1200)
        bins, mapper = bin_dataset(X, max_bin=31)
        vb, _ = bin_dataset(X[:300], mapper=mapper)

        seen = []

        class Spy(TrainingCallback):
            def get_learning_rate(self, it):
                return 0.3 * (0.8 ** it)

            def after_iteration(self, env):
                seen.append(env.evals["v"]["auc"][-1])
                return None

        r = train(
            bins, y, _opts(num_iterations=40, early_stopping_round=3),
            mapper=mapper,
            valid_sets=[("v", vb, y[:300], None)],
            callbacks=[Spy()],
        )
        # the callback saw every recorded eval, and early stopping engaged
        assert seen == r.evals["v"]["auc"]
        assert r.booster.num_trees <= 40


class TestEstimatorSurface:
    def test_set_delegate_threads_into_fit(self):
        X, y = _data(n=400)
        t = Table({
            "features": list(X.astype(np.float64)),
            "label": y,
        })
        hits = []

        class Hook(TrainingCallback):
            def after_iteration(self, env):
                hits.append(env.iteration)
                return None

        clf = LightGBMClassifier(numIterations=3, numLeaves=7).set_delegate(Hook())
        clf.fit(t)
        assert hits == [0, 1, 2]

    def test_delegates_do_not_serialize(self, tmp_path):
        X, y = _data(n=300)
        t = Table({"features": list(X.astype(np.float64)), "label": y})
        clf = LightGBMClassifier(numIterations=2).set_delegate(TrainingCallback())
        model = clf.fit(t)
        p = str(tmp_path / "m")
        model.save(p)  # must not try to serialize the live delegate
        type(model).load(p)


def test_lr_schedule_with_bagging_scan_layout():
    """Bagging masks + LR schedule ride the scan together (4-tuple xs
    layout); a constant schedule must still reproduce the plain bagged fit
    exactly."""
    X, y = _data()
    bins, mapper = bin_dataset(X, max_bin=31)
    kw = dict(bagging_fraction=0.7, bagging_freq=1, seed=3)
    r_plain = train(bins, y, _opts(**kw), mapper=mapper)
    r_sched = train(
        bins, y, _opts(**kw), mapper=mapper,
        callbacks=[LearningRateSchedule(lambda it: 0.1)],
    )
    np.testing.assert_allclose(
        r_sched.booster.leaf_values, r_plain.booster.leaf_values, rtol=1e-6
    )
