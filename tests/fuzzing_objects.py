"""Per-stage test fixtures for the fuzzing meta-suite.

The reference's ``Fuzzing.scala`` traits require every exported stage to
provide ``testObjects()`` — a stage instance plus fit/transform frames —
and ``FuzzingTest.scala:27-197`` reflectively asserts no stage escapes
coverage. Same contract: every concrete public PipelineStage subclass must
appear in TEST_OBJECTS, be named as a fixture's ``fit_produces`` model, or
carry an EXEMPT entry with a reason. ``tests/test_fuzzing.py`` enforces it.

Fixtures are zero-arg callables so stage/table construction stays lazy.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Optional

import numpy as np

from mmlspark_tpu.data.table import Table


@dataclasses.dataclass
class TestObject:
    stage: Any
    table: Table
    transform_table: Optional[Table] = None  # defaults to `table`
    check_transform: bool = True  # False: construct/serde only (needs a live server)
    fit_produces: Optional[str] = None  # qualname of the model class fit() returns


def _rng(seed=0):
    return np.random.default_rng(seed)


def _numeric_table(n=40, f=4, seed=0):
    rng = _rng(seed)
    X = rng.normal(size=(n, f))
    y = (X[:, 0] > 0).astype(np.float64)
    return Table({"features": X, "label": y})


def _mixed_table():
    rng = _rng(1)
    n = 30
    return Table(
        {
            "num": rng.normal(size=n),
            "cat": np.array([["red", "green", "blue"][i % 3] for i in range(n)], dtype=object),
            "label": (rng.random(n) > 0.5).astype(np.float64),
        }
    )


def _image_table():
    rng = _rng(2)
    images = np.empty(3, dtype=object)
    for i in range(3):
        images[i] = rng.integers(0, 256, size=(16, 16, 3), dtype=np.uint8)
    return Table({"id": np.arange(3), "image": images})


def _text_table():
    return Table(
        {
            "text": np.array(
                ["the quick brown fox", "jumps over the dog", "hello world again"],
                dtype=object,
            ),
            "label": np.array([1.0, 0.0, 1.0]),
        }
    )


def _events_table():
    users, items = [], []
    for u, its in [(0, [0, 1, 2]), (1, [0, 1, 2]), (2, [3, 4]), (3, [3, 4, 0])]:
        for i in its:
            users.append(u)
            items.append(i)
    return Table(
        {
            "user": np.array(users, dtype=np.int64),
            "item": np.array(items, dtype=np.int64),
            "rating": np.ones(len(users)),
        }
    )


def _http_request_table():
    req = np.empty(2, dtype=object)
    req[0] = {"url": "http://localhost:1/x", "method": "GET"}
    req[1] = {"url": "http://localhost:1/y", "method": "GET"}
    return Table({"req": req, "payload": np.array(["a", "b"], dtype=object)})


def _dnn_apply(params, inputs):
    x = inputs["x"] if isinstance(inputs, dict) else inputs
    return {"y": x * 2.0}


from mmlspark_tpu.core.params import Param as _Param
from mmlspark_tpu.core.params import to_str as _to_str
from mmlspark_tpu.core.pipeline import Transformer as _Transformer


class _FuzzLinearModel(_Transformer):
    """Inner model for LIME fixtures: y = x @ w. State lives in Params so
    the stage-serializer (save_stage persists params only) roundtrips it."""

    weights = _Param("weight vector", is_complex=True, default=None)
    col = _Param("input column", default="features", converter=_to_str)

    def __init__(self, w=None, **kw):
        super().__init__(**kw)
        if w is not None:
            self.set("weights", np.asarray(w, dtype=np.float64))

    def transform(self, table):
        w = np.asarray(self.getWeights(), dtype=np.float64)
        X = np.asarray(
            [np.asarray(r, dtype=np.float64).ravel() for r in table.column(self.getCol())]
        )
        X = X[:, : len(w)]
        return table.with_column("prediction", X @ w)


class _FuzzImageModel(_Transformer):
    """ImageLIME inner model: mean intensity per image."""

    col = _Param("input column", default="image", converter=_to_str)

    def transform(self, table):
        scores = np.asarray(
            [float(np.asarray(x, dtype=np.float64).mean()) for x in table.column(self.getCol())]
        )
        return table.with_column("prediction", scores)


def _udf_double(c):
    return c * 2


def _lambda_fn(t):
    return t.with_column("twice", t.column("num") * 2)


def _custom_in(row):
    return {"url": "http://localhost:1/z", "method": "GET", "body": str(row)}


def _custom_out(resp):
    return str(resp)


def _make_test_objects() -> Dict[str, Callable[[], TestObject]]:
    reg: Dict[str, Callable[[], TestObject]] = {}

    def add(qualname: str, fn: Callable[[], TestObject]):
        reg[qualname] = fn

    # --- lightgbm -----------------------------------------------------------
    def lgbm_clf():
        from mmlspark_tpu.lightgbm import LightGBMClassifier

        return TestObject(
            LightGBMClassifier(numIterations=5, numLeaves=5, parallelism="serial"),
            _numeric_table(),
            fit_produces="mmlspark_tpu.lightgbm.classifier.LightGBMClassificationModel",
        )

    add("mmlspark_tpu.lightgbm.classifier.LightGBMClassifier", lgbm_clf)

    def lgbm_reg():
        from mmlspark_tpu.lightgbm import LightGBMRegressor

        t = _numeric_table(seed=3)
        t = t.with_column("label", t.column("features")[:, 0] * 2.0)
        return TestObject(
            LightGBMRegressor(numIterations=5, numLeaves=5, parallelism="serial"),
            t,
            fit_produces="mmlspark_tpu.lightgbm.regressor.LightGBMRegressionModel",
        )

    add("mmlspark_tpu.lightgbm.regressor.LightGBMRegressor", lgbm_reg)

    def lgbm_ranker():
        from mmlspark_tpu.lightgbm import LightGBMRanker

        rng = _rng(4)
        n = 24
        t = Table(
            {
                "features": rng.normal(size=(n, 3)),
                "label": rng.integers(0, 3, size=n).astype(np.float64),
                "group": np.repeat(np.arange(4), 6),
            }
        )
        return TestObject(
            LightGBMRanker(
                numIterations=4, numLeaves=5, groupCol="group", parallelism="serial"
            ),
            t,
            fit_produces="mmlspark_tpu.lightgbm.ranker.LightGBMRankerModel",
        )

    add("mmlspark_tpu.lightgbm.ranker.LightGBMRanker", lgbm_ranker)

    # --- vw -----------------------------------------------------------------
    def vw_clf():
        from mmlspark_tpu.vw import VowpalWabbitClassifier

        return TestObject(
            VowpalWabbitClassifier(numPasses=1),
            _numeric_table(seed=5),
            fit_produces="mmlspark_tpu.vw.classifier.VowpalWabbitClassificationModel",
        )

    add("mmlspark_tpu.vw.classifier.VowpalWabbitClassifier", vw_clf)

    def vw_reg():
        from mmlspark_tpu.vw import VowpalWabbitRegressor

        t = _numeric_table(seed=6)
        t = t.with_column("label", t.column("features")[:, 0])
        return TestObject(
            VowpalWabbitRegressor(numPasses=1),
            t,
            fit_produces="mmlspark_tpu.vw.regressor.VowpalWabbitRegressionModel",
        )

    add("mmlspark_tpu.vw.regressor.VowpalWabbitRegressor", vw_reg)

    def vw_feat():
        from mmlspark_tpu.vw import VowpalWabbitFeaturizer

        return TestObject(
            VowpalWabbitFeaturizer(inputCols=["text"], outputCol="features", stringSplit=True),
            _text_table(),
        )

    add("mmlspark_tpu.vw.featurizer.VowpalWabbitFeaturizer", vw_feat)

    def vw_inter():
        from mmlspark_tpu.vw import VowpalWabbitFeaturizer, VowpalWabbitInteractions

        t = _text_table()
        t = VowpalWabbitFeaturizer(inputCols=["text"], outputCol="fa", numBits=10, stringSplit=True).transform(t)
        t = VowpalWabbitFeaturizer(inputCols=["label"], outputCol="fb", numBits=10).transform(t)
        return TestObject(
            VowpalWabbitInteractions(inputCols=["fa", "fb"], outputCol="cross", numBits=10),
            t,
        )

    add("mmlspark_tpu.vw.interactions.VowpalWabbitInteractions", vw_inter)

    # --- featurize ----------------------------------------------------------
    def clean():
        from mmlspark_tpu.featurize import CleanMissingData

        rng = _rng(7)
        a = rng.normal(size=20)
        a[::4] = np.nan
        return TestObject(
            CleanMissingData(inputCols=["a"], cleaningMode="Mean"),
            Table({"a": a}),
            fit_produces="mmlspark_tpu.featurize.clean.CleanMissingDataModel",
        )

    add("mmlspark_tpu.featurize.clean.CleanMissingData", clean)

    def conv():
        from mmlspark_tpu.featurize import DataConversion

        return TestObject(
            DataConversion(inputCols=["x"], convertTo="double"),
            Table({"x": np.arange(5, dtype=np.int64)}),
        )

    add("mmlspark_tpu.featurize.conversion.DataConversion", conv)

    def assemble():
        from mmlspark_tpu.featurize import AssembleFeatures

        return TestObject(
            AssembleFeatures(inputCols=["num", "label"]),
            _mixed_table(),
            fit_produces="mmlspark_tpu.featurize.featurize.FeaturizeModel",
        )

    add("mmlspark_tpu.featurize.featurize.AssembleFeatures", assemble)

    def featurize():
        from mmlspark_tpu.featurize import Featurize

        return TestObject(
            Featurize(inputCols=["num", "cat"], outputCol="features"),
            _mixed_table(),
            fit_produces="mmlspark_tpu.featurize.featurize.FeaturizeModel",
        )

    add("mmlspark_tpu.featurize.featurize.Featurize", featurize)

    def value_indexer():
        from mmlspark_tpu.featurize import ValueIndexer

        return TestObject(
            ValueIndexer(inputCol="cat", outputCol="idx"),
            _mixed_table(),
            fit_produces="mmlspark_tpu.featurize.indexers.ValueIndexerModel",
        )

    add("mmlspark_tpu.featurize.indexers.ValueIndexer", value_indexer)

    def index_to_value():
        from mmlspark_tpu.featurize import ValueIndexer, IndexToValue

        t = _mixed_table()
        t2 = ValueIndexer(inputCol="cat", outputCol="idx").fit(t).transform(t)
        return TestObject(IndexToValue(inputCol="idx", outputCol="orig"), t2)

    add("mmlspark_tpu.featurize.indexers.IndexToValue", index_to_value)

    def text_featurizer():
        from mmlspark_tpu.featurize import TextFeaturizer

        return TestObject(
            TextFeaturizer(inputCol="text", outputCol="features"),
            _text_table(),
            fit_produces="mmlspark_tpu.featurize.text.TextFeaturizerModel",
        )

    add("mmlspark_tpu.featurize.text.TextFeaturizer", text_featurizer)

    def multi_ngram():
        from mmlspark_tpu.featurize import MultiNGram

        t = _text_table()
        toks = np.empty(t.num_rows, dtype=object)
        for i, s in enumerate(t.column("text")):
            toks[i] = s.split()
        return TestObject(
            MultiNGram(inputCol="tokens", outputCol="grams", lengths=[1, 2]),
            t.with_column("tokens", toks),
        )

    add("mmlspark_tpu.featurize.text.MultiNGram", multi_ngram)

    def page_splitter():
        from mmlspark_tpu.featurize import PageSplitter

        return TestObject(
            PageSplitter(inputCol="text", outputCol="pages", maximumPageLength=10),
            _text_table(),
        )

    add("mmlspark_tpu.featurize.text.PageSplitter", page_splitter)

    # --- image --------------------------------------------------------------
    def image_transformer():
        from mmlspark_tpu.image import ImageTransformer

        return TestObject(
            ImageTransformer(inputCol="image", outputCol="out").resize(8, 8),
            _image_table(),
        )

    add("mmlspark_tpu.image.transforms.ImageTransformer", image_transformer)

    def image_augmenter():
        from mmlspark_tpu.image import ImageSetAugmenter

        return TestObject(
            ImageSetAugmenter(inputCol="image", outputCol="image"), _image_table()
        )

    add("mmlspark_tpu.image.transforms.ImageSetAugmenter", image_augmenter)

    def unroll():
        from mmlspark_tpu.image import UnrollImage

        return TestObject(UnrollImage(inputCol="image", outputCol="vec"), _image_table())

    add("mmlspark_tpu.image.unroll.UnrollImage", unroll)

    def image_featurizer():
        from mmlspark_tpu.image import ImageFeaturizer
        from mmlspark_tpu.models import init_resnet

        params = init_resnet(variant="resnet18", num_classes=4, small_inputs=True)
        return TestObject(
            ImageFeaturizer(
                inputCol="image", outputCol="features", modelParams=params,
                inputHeight=32, inputWidth=32, batchSize=2,
            ),
            _image_table(),
        )

    add("mmlspark_tpu.image.featurizer.ImageFeaturizer", image_featurizer)

    def superpixel():
        from mmlspark_tpu.lime import SuperpixelTransformer

        return TestObject(
            SuperpixelTransformer(inputCol="image", cellSize=8), _image_table()
        )

    add("mmlspark_tpu.lime.superpixel.SuperpixelTransformer", superpixel)

    # --- lime ---------------------------------------------------------------
    def tabular_lime():
        from mmlspark_tpu.lime import TabularLIME

        return TestObject(
            TabularLIME(
                model=_FuzzLinearModel(np.array([1.0, -1.0, 0.5, 0.0])),
                inputCol="features", outputCol="weights", nSamples=60, seed=1,
            ),
            _numeric_table(seed=8),
            fit_produces="mmlspark_tpu.lime.lime.TabularLIMEModel",
        )

    add("mmlspark_tpu.lime.lime.TabularLIME", tabular_lime)

    def image_lime():
        from mmlspark_tpu.lime import ImageLIME

        return TestObject(
            ImageLIME(
                model=_FuzzImageModel(), inputCol="image", outputCol="weights",
                nSamples=8, cellSize=8, seed=1,
            ),
            _image_table(),
        )

    add("mmlspark_tpu.lime.lime.ImageLIME", image_lime)

    # --- nn -----------------------------------------------------------------
    def knn():
        from mmlspark_tpu.nn import KNN

        rng = _rng(9)
        t = Table(
            {
                "features": rng.normal(size=(30, 4)),
                "values": np.arange(30).astype(np.float64),
            }
        )
        return TestObject(
            KNN(k=3, outputCol="matches"),
            t,
            fit_produces="mmlspark_tpu.nn.knn.KNNModel",
        )

    add("mmlspark_tpu.nn.knn.KNN", knn)

    def cknn():
        from mmlspark_tpu.nn import ConditionalKNN

        rng = _rng(10)
        labels = np.array([["a", "b"][i % 2] for i in range(30)], dtype=object)
        t = Table(
            {
                "features": rng.normal(size=(30, 4)),
                "values": np.arange(30).astype(np.float64),
                "labels": labels,
            }
        )
        q = Table(
            {
                "features": rng.normal(size=(5, 4)),
                "conditioner": np.array([["a"]] * 5, dtype=object),
            }
        )
        return TestObject(
            ConditionalKNN(k=2, labelCol="labels", outputCol="matches"),
            t,
            transform_table=q,
            fit_produces="mmlspark_tpu.nn.knn.ConditionalKNNModel",
        )

    add("mmlspark_tpu.nn.knn.ConditionalKNN", cknn)

    # --- isolation forest ---------------------------------------------------
    def iforest():
        from mmlspark_tpu.isolationforest import IsolationForest

        return TestObject(
            IsolationForest(numEstimators=10),
            _numeric_table(seed=11),
            fit_produces="mmlspark_tpu.isolationforest.forest.IsolationForestModel",
        )

    add("mmlspark_tpu.isolationforest.forest.IsolationForest", iforest)

    # --- recommendation -----------------------------------------------------
    def sar():
        from mmlspark_tpu.recommendation import SAR

        return TestObject(
            SAR(supportThreshold=1),
            _events_table(),
            fit_produces="mmlspark_tpu.recommendation.sar.SARModel",
        )

    add("mmlspark_tpu.recommendation.sar.SAR", sar)

    def rec_indexer():
        from mmlspark_tpu.recommendation import RecommendationIndexer

        t = Table(
            {
                "customer": np.array(["alice", "bob", "alice"], dtype=object),
                "product": np.array(["x", "y", "y"], dtype=object),
            }
        )
        return TestObject(
            RecommendationIndexer(
                userInputCol="customer", userOutputCol="user",
                itemInputCol="product", itemOutputCol="item",
            ),
            t,
            fit_produces="mmlspark_tpu.recommendation.ranking.RecommendationIndexerModel",
        )

    add("mmlspark_tpu.recommendation.ranking.RecommendationIndexer", rec_indexer)

    def ranking_adapter():
        from mmlspark_tpu.recommendation import RankingAdapter, SAR

        return TestObject(
            RankingAdapter(recommender=SAR(supportThreshold=1), k=2),
            _events_table(),
            fit_produces="mmlspark_tpu.recommendation.ranking.RankingAdapterModel",
        )

    add("mmlspark_tpu.recommendation.ranking.RankingAdapter", ranking_adapter)

    def ranking_tvs():
        from mmlspark_tpu.recommendation import (
            RankingEvaluator,
            RankingTrainValidationSplit,
            SAR,
        )

        return TestObject(
            RankingTrainValidationSplit(
                estimator=SAR(supportThreshold=1),
                evaluator=RankingEvaluator(k=2, nItems=5),
                trainRatio=0.6,
                seed=7,
            ),
            _events_table(),
            fit_produces="mmlspark_tpu.recommendation.ranking.RankingTrainValidationSplitModel",
        )

    add("mmlspark_tpu.recommendation.ranking.RankingTrainValidationSplit", ranking_tvs)

    # --- stages -------------------------------------------------------------
    def _words_table():
        return Table(
            {
                "num": np.arange(6, dtype=np.float64),
                "words": np.array(list("abcdef"), dtype=object),
                "label": np.array([0, 1, 0, 1, 0, 1], dtype=np.float64),
            }
        )

    simple = {
        "Cacher": lambda S: TestObject(S(), _words_table()),
        "DropColumns": lambda S: TestObject(S(cols=["num"]), _words_table()),
        "SelectColumns": lambda S: TestObject(S(cols=["num", "words"]), _words_table()),
        "RenameColumn": lambda S: TestObject(S(inputCol="words", outputCol="w2"), _words_table()),
        "Repartition": lambda S: TestObject(S(n=2), _words_table()),
        "StratifiedRepartition": lambda S: TestObject(S(labelCol="label"), _words_table()),
        "SummarizeData": lambda S: TestObject(S(), _words_table()),
        "UnicodeNormalize": lambda S: TestObject(S(inputCol="words", outputCol="norm"), _words_table()),
        "Explode": lambda S: TestObject(
            S(inputCol="vals"),
            Table({"vals": np.array([[1, 2], [3]], dtype=object)}),
        ),
        "UDFTransformer": lambda S: TestObject(
            S(inputCol="num", outputCol="n2", udf=_udf_double), _words_table()
        ),
        "Lambda": lambda S: TestObject(S(transformFunc=_lambda_fn), _words_table()),
        "TextPreprocessor": lambda S: TestObject(
            S(inputCol="words", outputCol="pp", map={"a": "z"}), _words_table()
        ),
    }
    for name, maker in simple.items():
        qual = f"mmlspark_tpu.stages.basic.{name}"

        def fx(maker=maker, name=name):
            import mmlspark_tpu.stages.basic as basic

            return maker(getattr(basic, name))

        add(qual, fx)

    def class_balancer():
        from mmlspark_tpu.stages.basic import ClassBalancer

        return TestObject(
            ClassBalancer(inputCol="label"),
            _words_table(),
            fit_produces="mmlspark_tpu.stages.basic.ClassBalancerModel",
        )

    add("mmlspark_tpu.stages.basic.ClassBalancer", class_balancer)

    def ensemble_by_key():
        from mmlspark_tpu.stages.basic import EnsembleByKey

        t = Table(
            {
                "key": np.array(["a", "a", "b"], dtype=object),
                "score": np.array([1.0, 3.0, 5.0]),
            }
        )
        return TestObject(EnsembleByKey(keys=["key"], cols=["score"]), t)

    add("mmlspark_tpu.stages.basic.EnsembleByKey", ensemble_by_key)

    def multi_column_adapter():
        from mmlspark_tpu.stages.basic import MultiColumnAdapter, UDFTransformer

        return TestObject(
            MultiColumnAdapter(
                baseStage=UDFTransformer(udf=_udf_double),
                inputCols=["num", "label"],
                outputCols=["num2", "label2"],
            ),
            _words_table(),
        )

    add("mmlspark_tpu.stages.basic.MultiColumnAdapter", multi_column_adapter)

    def timer():
        from mmlspark_tpu.stages.basic import Timer, UDFTransformer

        return TestObject(
            Timer(stage=UDFTransformer(inputCol="num", outputCol="n2", udf=_udf_double)),
            _words_table(),
            fit_produces="mmlspark_tpu.stages.basic.TimerModel",
        )

    add("mmlspark_tpu.stages.basic.Timer", timer)

    def fixed_batcher():
        from mmlspark_tpu.stages.batching import FixedMiniBatchTransformer

        return TestObject(FixedMiniBatchTransformer(batchSize=2), _words_table())

    add("mmlspark_tpu.stages.batching.FixedMiniBatchTransformer", fixed_batcher)

    def dynamic_batcher():
        from mmlspark_tpu.stages.batching import DynamicMiniBatchTransformer

        return TestObject(DynamicMiniBatchTransformer(maxBatchSize=3), _words_table())

    add("mmlspark_tpu.stages.batching.DynamicMiniBatchTransformer", dynamic_batcher)

    def time_batcher():
        from mmlspark_tpu.stages.batching import TimeIntervalMiniBatchTransformer

        return TestObject(
            TimeIntervalMiniBatchTransformer(millisToWait=5), _words_table()
        )

    add("mmlspark_tpu.stages.batching.TimeIntervalMiniBatchTransformer", time_batcher)

    def flatten_batch():
        from mmlspark_tpu.stages.batching import FixedMiniBatchTransformer, FlattenBatch

        t = FixedMiniBatchTransformer(batchSize=2).transform(_words_table())
        return TestObject(FlattenBatch(), t)

    add("mmlspark_tpu.stages.batching.FlattenBatch", flatten_batch)

    # --- train --------------------------------------------------------------
    def train_classifier():
        from mmlspark_tpu.lightgbm import LightGBMClassifier
        from mmlspark_tpu.train import TrainClassifier

        return TestObject(
            TrainClassifier(
                model=LightGBMClassifier(numIterations=4, numLeaves=5, parallelism="serial"),
                labelCol="label",
            ),
            _mixed_table(),
            fit_produces="mmlspark_tpu.train.trainers.TrainedClassifierModel",
        )

    add("mmlspark_tpu.train.trainers.TrainClassifier", train_classifier)

    def train_regressor():
        from mmlspark_tpu.lightgbm import LightGBMRegressor
        from mmlspark_tpu.train import TrainRegressor

        t = _mixed_table()
        t = t.with_column("label", t.column("num") * 2.0)
        return TestObject(
            TrainRegressor(
                model=LightGBMRegressor(numIterations=4, numLeaves=5, parallelism="serial"),
                labelCol="label",
            ),
            t,
            fit_produces="mmlspark_tpu.train.trainers.TrainedRegressorModel",
        )

    add("mmlspark_tpu.train.trainers.TrainRegressor", train_regressor)

    def compute_stats():
        from mmlspark_tpu.lightgbm import LightGBMClassifier
        from mmlspark_tpu.train import ComputeModelStatistics, TrainClassifier

        t = _mixed_table()
        out = (
            TrainClassifier(
                model=LightGBMClassifier(numIterations=4, numLeaves=5, parallelism="serial"),
                labelCol="label",
            )
            .fit(t)
            .transform(t)
        )
        return TestObject(ComputeModelStatistics(labelCol="label"), out)

    add("mmlspark_tpu.train.statistics.ComputeModelStatistics", compute_stats)

    def per_instance_stats():
        from mmlspark_tpu.lightgbm import LightGBMClassifier
        from mmlspark_tpu.train import ComputePerInstanceStatistics, TrainClassifier

        t = _mixed_table()
        out = (
            TrainClassifier(
                model=LightGBMClassifier(numIterations=4, numLeaves=5, parallelism="serial"),
                labelCol="label",
            )
            .fit(t)
            .transform(t)
        )
        return TestObject(ComputePerInstanceStatistics(labelCol="label"), out)

    add("mmlspark_tpu.train.statistics.ComputePerInstanceStatistics", per_instance_stats)

    # --- dnn ----------------------------------------------------------------
    def dnn_model():
        from mmlspark_tpu.dnn import DNNModel

        return TestObject(
            DNNModel(
                applyFn=_dnn_apply,
                modelParams={},
                feedDict={"x": "features"},
                fetchDict={"out": "y"},
                batchSize=4,
            ),
            _numeric_table(seed=12),
        )

    add("mmlspark_tpu.dnn.model.DNNModel", dnn_model)

    # --- io/http (client stack: pure parsers transform; live-server stages
    # are serde-only here, exercised end-to-end in tests/test_http.py) -------
    def json_input_parser():
        from mmlspark_tpu.io.http import JSONInputParser

        return TestObject(
            JSONInputParser(url="http://localhost:1/api", inputCol="payload", outputCol="req"),
            _http_request_table(),
        )

    add("mmlspark_tpu.io.http.transformers.JSONInputParser", json_input_parser)

    def custom_input_parser():
        from mmlspark_tpu.io.http import CustomInputParser

        return TestObject(
            CustomInputParser(inputCol="payload", outputCol="req", udf=_custom_in),
            _http_request_table(),
        )

    add("mmlspark_tpu.io.http.transformers.CustomInputParser", custom_input_parser)

    def custom_output_parser():
        from mmlspark_tpu.io.http import CustomOutputParser

        return TestObject(
            CustomOutputParser(inputCol="req", outputCol="parsed", udf=_custom_out),
            _http_request_table(),
        )

    add("mmlspark_tpu.io.http.transformers.CustomOutputParser", custom_output_parser)

    def string_output_parser():
        from mmlspark_tpu.io.http import StringOutputParser

        return TestObject(
            StringOutputParser(inputCol="req", outputCol="s"),
            _http_request_table(),
            check_transform=False,  # consumes HTTPResponseData from a live call
        )

    add("mmlspark_tpu.io.http.transformers.StringOutputParser", string_output_parser)

    def json_output_parser():
        from mmlspark_tpu.io.http import JSONOutputParser

        return TestObject(
            JSONOutputParser(inputCol="req", outputCol="parsed"),
            _http_request_table(),
            check_transform=False,
        )

    add("mmlspark_tpu.io.http.transformers.JSONOutputParser", json_output_parser)

    def http_transformer():
        from mmlspark_tpu.io.http import HTTPTransformer

        return TestObject(
            HTTPTransformer(inputCol="req", outputCol="resp"),
            _http_request_table(),
            check_transform=False,
        )

    add("mmlspark_tpu.io.http.transformers.HTTPTransformer", http_transformer)

    def simple_http():
        from mmlspark_tpu.io.http import JSONInputParser, SimpleHTTPTransformer

        return TestObject(
            SimpleHTTPTransformer(
                inputCol="payload",
                outputCol="out",
                inputParser=JSONInputParser(url="http://localhost:1/api"),
            ),
            _http_request_table(),
            check_transform=False,
        )

    add("mmlspark_tpu.io.http.transformers.SimpleHTTPTransformer", simple_http)

    def powerbi():
        from mmlspark_tpu.io.powerbi import PowerBIWriter

        return TestObject(
            PowerBIWriter(url="http://localhost:1/push", batchSize=2),
            Table({"a": np.arange(3, dtype=np.float64)}),
            check_transform=False,  # pushes to a live endpoint
        )

    add("mmlspark_tpu.io.powerbi.PowerBIWriter", powerbi)

    def consolidator():
        from mmlspark_tpu.io.http import PartitionConsolidator

        return TestObject(
            PartitionConsolidator(inputCol="req", outputCol="resp", concurrency=2),
            _http_request_table(),
            check_transform=False,
        )

    add("mmlspark_tpu.io.http.transformers.PartitionConsolidator", consolidator)

    return reg


TEST_OBJECTS = _make_test_objects()


# Classes that are deliberately NOT fuzzed directly, with the reason — the
# analogue of FuzzingTest.scala's exemption lists. Abstract/base classes and
# models that only exist via their estimator's fit() (covered through
# fit_produces) do not belong here; this list is for everything else.
EXEMPT: Dict[str, str] = {
    "mmlspark_tpu.core.pipeline.PipelineStage": "abstract base",
    "mmlspark_tpu.core.pipeline.Transformer": "abstract base",
    "mmlspark_tpu.core.pipeline.Estimator": "abstract base",
    "mmlspark_tpu.core.pipeline.Model": "abstract base",
    "mmlspark_tpu.core.pipeline.Pipeline": "meta-stage; roundtrip covered in test_core_params Pipeline tests",
    "mmlspark_tpu.core.pipeline.PipelineModel": "meta-stage; covered with Pipeline",
    "mmlspark_tpu.lightgbm.base.LightGBMBase": "abstract learner base (objective hooks unimplemented)",
    "mmlspark_tpu.lightgbm.base.LightGBMModelBase": "abstract model base",
    "mmlspark_tpu.vw.base.VowpalWabbitBase": "abstract learner base",
    "mmlspark_tpu.vw.base.VowpalWabbitModelBase": "abstract model base",
    "mmlspark_tpu.automl.tune.TuneHyperparameters": "estimator-of-estimators; covered in test_automl (needs param grids)",
    "mmlspark_tpu.automl.tune.TuneHyperparametersModel": "produced by TuneHyperparameters; covered in test_automl",
    "mmlspark_tpu.automl.tune.FindBestModel": "model-selection meta-stage; covered in test_automl",
    "mmlspark_tpu.automl.tune.BestModel": "produced by FindBestModel; covered in test_automl",
    "mmlspark_tpu.sweep.estimator.TrainValidSweep": "estimator-of-estimators; covered in test_sweep (needs param spaces)",
    "mmlspark_tpu.sweep.estimator.TrainValidSweepModel": "produced by TrainValidSweep; covered in test_sweep",
}
