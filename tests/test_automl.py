"""automl/ tests — mirrors reference ``automl/`` suites
(VerifyTuneHyperparameters, VerifyFindBestModel)."""

import numpy as np
import pytest

from mmlspark_tpu.automl import (
    DiscreteHyperParam,
    DoubleRangeHyperParam,
    FindBestModel,
    GridSpace,
    HyperparamBuilder,
    IntRangeHyperParam,
    TuneHyperparameters,
)
from mmlspark_tpu.data.table import Table
from mmlspark_tpu.lightgbm import LightGBMClassifier


@pytest.fixture
def clf_table(rng):
    X = rng.normal(size=(200, 5))
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(np.float64)
    return Table({"features": X, "label": y})


class TestHyperparams:
    def test_discrete(self):
        rng = np.random.default_rng(0)
        d = DiscreteHyperParam([1, 2, 3])
        assert all(d.get_next(rng) in (1, 2, 3) for _ in range(20))

    def test_ranges(self):
        rng = np.random.default_rng(0)
        r = IntRangeHyperParam(5, 10)
        assert all(5 <= r.get_next(rng) < 10 for _ in range(50))
        f = DoubleRangeHyperParam(0.1, 0.2)
        assert all(0.1 <= f.get_next(rng) < 0.2 for _ in range(50))
        with pytest.raises(ValueError):
            IntRangeHyperParam(3, 3)

    def test_builder_and_grid(self):
        space = (
            HyperparamBuilder()
            .add_hyperparam("a", DiscreteHyperParam([1, 2]))
            .add_hyperparam("b", DoubleRangeHyperParam(0, 1))
            .build()
        )
        maps = list(space.param_maps(4))
        assert len(maps) == 4 and all({"a", "b"} == set(m) for m in maps)
        grid = GridSpace({"a": [1, 2], "b": ["x", "y"]})
        assert len(list(grid.param_maps())) == 4


class TestTuneHyperparameters:
    def test_tune_improves_or_matches(self, clf_table):
        tuned = TuneHyperparameters(
            models=LightGBMClassifier(numIterations=10),
            paramSpace={
                "numLeaves": DiscreteHyperParam([3, 15]),
                "learningRate": DoubleRangeHyperParam(0.05, 0.3),
            },
            evaluationMetric="accuracy",
            numFolds=2,
            numRuns=3,
            seed=5,
        ).fit(clf_table)
        assert 0.5 <= tuned.getBestMetric() <= 1.0
        assert len(tuned.getAllMetrics()) == 3
        out = tuned.transform(clf_table)
        assert "prediction" in out

    def test_parallel_matches_serial(self, clf_table):
        kwargs = dict(
            models=LightGBMClassifier(numIterations=5),
            paramSpace={"numLeaves": DiscreteHyperParam([3, 7])},
            evaluationMetric="accuracy",
            numFolds=2,
            numRuns=2,
            seed=1,
        )
        serial = TuneHyperparameters(parallelism=1, **kwargs).fit(clf_table)
        parallel = TuneHyperparameters(parallelism=2, **kwargs).fit(clf_table)
        np.testing.assert_allclose(serial.getAllMetrics(), parallel.getAllMetrics())


class TestFindBestModel:
    def test_picks_best(self, clf_table):
        good = LightGBMClassifier(numIterations=20, numLeaves=15).fit(clf_table)
        weak = LightGBMClassifier(numIterations=1, numLeaves=2).fit(clf_table)
        best = FindBestModel(
            models=[weak, good], evaluationMetric="accuracy"
        ).fit(clf_table)
        assert best.getBestModel() is good or (
            best.getBestModelMetrics()
            >= best.get_evaluated_models()["metric"].min()
        )
        evald = best.get_evaluated_models()
        assert evald.num_rows == 2

    def test_no_models_raises(self, clf_table):
        with pytest.raises(ValueError):
            FindBestModel(models=[]).fit(clf_table)
