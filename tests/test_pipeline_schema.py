"""Static pipeline schema validation: ``Pipeline.validate`` rejects
mis-wired stage graphs before any stage executes (the SparkML
``transformSchema`` contract)."""

import numpy as np
import pytest

from mmlspark_tpu.core.pipeline import Pipeline, PipelineModel
from mmlspark_tpu.core.schema import (
    DTYPE_MISMATCH,
    DUPLICATE_OUTPUT_COL,
    MISSING_INPUT_COL,
    ColType,
    SchemaError,
    as_schema,
    schema_of_table,
)
from mmlspark_tpu.data.table import Table
from mmlspark_tpu.featurize.clean import CleanMissingData
from mmlspark_tpu.featurize.featurize import AssembleFeatures
from mmlspark_tpu.featurize.indexers import ValueIndexer
from mmlspark_tpu.featurize.text import TextFeaturizer
from mmlspark_tpu.stages.basic import (
    DropColumns,
    RenameColumn,
    SelectColumns,
    UDFTransformer,
)
from mmlspark_tpu.stages.batching import FixedMiniBatchTransformer, FlattenBatch


@pytest.fixture
def table():
    return Table(
        {
            "a": np.arange(4.0),
            "b": np.arange(4).astype(np.int32),
            "vec": np.ones((4, 3), dtype=np.float32),
            "txt": np.array(["x y", "y z", "z w", "w"], dtype=object),
        }
    )


class ExplodingStage(DropColumns):
    """Any execution during validate() is a test failure."""

    def transform(self, table):
        raise AssertionError("validate() must not execute stages")

    def _fit(self, table):
        raise AssertionError("validate() must not fit stages")


class TestSchemaOfTable:
    def test_dtypes_and_shapes(self, table):
        s = schema_of_table(table)
        assert s["a"] == ColType(np.dtype(np.float64), ())
        assert s["vec"] == ColType(np.dtype(np.float32), (3,))
        assert s["txt"].dtype == np.dtype(object)

    def test_as_schema_accepts_dtype_mapping(self):
        s = as_schema({"a": np.float32, "b": None})
        assert s["a"].dtype == np.dtype(np.float32)
        assert s["b"] == ColType()


class TestValidChains:
    def test_valid_chain_passes_and_propagates(self, table):
        p = Pipeline(
            stages=[
                RenameColumn(inputCol="a", outputCol="a2"),
                CleanMissingData(inputCols=["a2"]),
                AssembleFeatures(inputCols=["a2", "b", "vec"], outputCol="features"),
                DropColumns(cols=["txt"]),
            ]
        )
        out = p.validate(table)
        assert set(out) == {"a2", "b", "vec", "features"}
        # widths add up statically: 1 (a2) + 1 (b) + 3 (vec)
        assert out["features"] == ColType(np.dtype(np.float32), (5,))

    def test_accepts_plain_schema_without_table(self):
        p = Pipeline(stages=[SelectColumns(cols=["a"])])
        out = p.validate({"a": np.float64, "b": np.int32})
        assert set(out) == {"a"}

    def test_batching_roundtrip_schema(self, table):
        p = Pipeline(stages=[FixedMiniBatchTransformer(batchSize=2), FlattenBatch()])
        out = p.validate(table)
        assert set(out) == {"a", "b", "vec", "txt"}

    def test_text_featurizer_width(self, table):
        p = Pipeline(
            stages=[TextFeaturizer(inputCol="txt", outputCol="tf", numFeatures=64)]
        )
        out = p.validate(table)
        assert out["tf"] == ColType(np.dtype(np.float32), (64,))

    def test_pipeline_model_transform_schema(self, table):
        pm = PipelineModel(
            stages=[RenameColumn(inputCol="a", outputCol="a2")]
        )
        out = pm.transform_schema(schema_of_table(table))
        assert "a2" in out and "a" not in out


class TestWiringErrors:
    def test_missing_input_col_names_stage(self, table):
        p = Pipeline(
            stages=[
                DropColumns(cols=["txt"]),
                SelectColumns(cols=["txt", "a"]),  # txt was just dropped
            ]
        )
        with pytest.raises(SchemaError) as ei:
            p.validate(table)
        assert ei.value.kind == MISSING_INPUT_COL
        assert ei.value.column == "txt"
        assert "SelectColumns" in str(ei.value) and "1" in ei.value.stage

    def test_dtype_mismatch_names_stage(self):
        p = Pipeline(stages=[AssembleFeatures(inputCols=["s"], outputCol="f")])
        with pytest.raises(SchemaError) as ei:
            p.validate({"s": np.dtype("U16")})
        assert ei.value.kind == DTYPE_MISMATCH
        assert "AssembleFeatures" in ei.value.stage

    def test_duplicate_output_col_names_stage(self, table):
        p = Pipeline(
            stages=[
                ValueIndexer(inputCol="txt", outputCol="idx"),
                RenameColumn(inputCol="a", outputCol="idx"),  # collides
            ]
        )
        with pytest.raises(SchemaError) as ei:
            p.validate(table)
        assert ei.value.kind == DUPLICATE_OUTPUT_COL
        assert "RenameColumn" in ei.value.stage
        assert ei.value.column == "idx"

    def test_validate_executes_nothing(self, table):
        p = Pipeline(stages=[ExplodingStage(cols=["nope"])])
        with pytest.raises(SchemaError) as ei:
            p.validate(table)
        assert ei.value.kind == MISSING_INPUT_COL

    def test_fit_validates_before_executing(self, table):
        p = Pipeline(stages=[ExplodingStage(cols=["nope"])])
        with pytest.raises(SchemaError):
            p.fit(table)  # SchemaError, not the stage's AssertionError

    def test_fit_still_works_on_valid_pipeline(self, table):
        p = Pipeline(
            stages=[
                UDFTransformer(
                    inputCol="a", outputCol="a3", udf=lambda c: c * 3
                ),
                DropColumns(cols=["txt"]),
            ]
        )
        out = p.fit(table).transform(table)
        np.testing.assert_allclose(out.column("a3"), table.column("a") * 3)
        assert "txt" not in out.columns
