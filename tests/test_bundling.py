"""Exclusive Feature Bundling (lightgbm/bundling.py + its binning/train/
model-text wiring).

EFB is the reference engine's binning-time sparse optimization
(``enable_bundle``/``max_conflict_rate``): (near-)mutually-exclusive
features greedily graph-colored into shared dense columns with bin-offset
packing, so the histogram width K = Σ_f B_f shrinks while every emitted
artifact — split ids, model text, SHAP — stays in ORIGINAL feature space.
These tests pin (a) the pack/route/expand maps, (b) the conflict budget,
(c) structural identity of a zero-conflict bundled fit on the U path and
float-level parity on the compare path, (d) the bundle→original-id round
trip through model text, and (e) SHAP parity.
"""

import os

import numpy as np
import pytest

# MMLSPARK_TPU_NO_U=1 silently degrades histogram_method="u" to the
# compare-built path, whose default-bin subtraction is float-equivalent
# but not bit-equivalent — the structure-identity contracts below only
# hold on the U path (the float-parity tests cover the NO_U pass).
_no_u = pytest.mark.skipif(
    os.environ.get("MMLSPARK_TPU_NO_U") == "1",
    reason="U path disabled: bit-level structural identity not contracted",
)

from mmlspark_tpu.lightgbm.binning import bin_dataset
from mmlspark_tpu.lightgbm.bundling import (
    expand_maps,
    pack_bundles,
    route_maps,
    unpack_bins,
)
from mmlspark_tpu.lightgbm.objectives import auc
from mmlspark_tpu.lightgbm.train import TrainOptions, train


def _one_hot_case(n=3000, blocks=6, card=5, conts=3, seed=0):
    """Blocks of value-bearing one-hot indicators (mutually exclusive
    within a block) plus dense continuous tail columns."""
    rng = np.random.default_rng(seed)
    X = np.zeros((n, blocks * card), np.float64)
    for b in range(blocks):
        hot = rng.integers(0, card, n)
        X[np.arange(n), b * card + hot] = rng.uniform(0.5, 2.0, n)
    X = np.hstack([X, rng.normal(size=(n, conts))])
    y = (X[:, 0] + 2 * X[:, card + 2] + X[:, -1] > 1.2).astype(np.float64)
    return X, y


def _auc(y, s):
    return auc(y, s, np.ones(len(y)))


class TestBundlePlan:
    def test_one_hot_blocks_pack_and_round_trip(self):
        X, _ = _one_hot_case()
        bins_u, m_u = bin_dataset(X, max_bin=255)
        bins_b, m_b = bin_dataset(X, max_bin=255, feature_bundling=True)
        spec = m_b.bundles
        assert spec is not None
        # packing is real: fewer columns, narrower histogram
        assert spec.num_features == X.shape[1]
        assert spec.num_columns < spec.num_features
        assert spec.k_packed < sum(int(b) for b in m_u.num_bins)
        assert spec.conflict_count == 0  # one-hot blocks are exactly exclusive
        assert bins_b.shape == (len(X), spec.num_columns)
        # binning itself is unchanged (same edges), only the layout differs
        np.testing.assert_array_equal(m_b.edges, m_u.edges)
        np.testing.assert_array_equal(unpack_bins(bins_b, spec), bins_u)
        np.testing.assert_array_equal(pack_bundles(bins_u, spec), bins_b)

    def test_route_maps_decode_every_cell(self):
        X, _ = _one_hot_case(seed=3)
        bins_u, _ = bin_dataset(X, max_bin=63)
        bins_b, m_b = bin_dataset(X, max_bin=63, feature_bundling=True)
        spec = m_b.bundles
        col_of, lo, span, skip, dflt = route_maps(spec)
        for f in range(spec.num_features):
            q = bins_b[:, col_of[f]].astype(np.int64) - lo[f]
            inb = (q >= 0) & (q < span[f])
            dec = np.where(inb, q + (q >= skip[f]), dflt[f])
            np.testing.assert_array_equal(dec, bins_u[:, f], err_msg=f"f={f}")

    def test_expand_maps_shapes_and_identity_columns(self):
        X, _ = _one_hot_case()
        _, m_b = bin_dataset(X, max_bin=63, feature_bundling=True)
        spec = m_b.bundles
        cidx, gmask, dmask = expand_maps(spec, 64)
        assert cidx.shape == gmask.shape == dmask.shape == (spec.num_features, 64)
        # exactly one default-bin residual slot per bundled feature, none
        # for identity (unbundled) columns
        per_feat = dmask.sum(axis=1)
        assert set(per_feat.tolist()) <= {0.0, 1.0}
        # a default slot never also gathers directly
        assert float((gmask * dmask).sum()) == 0.0

    def test_conflict_budget_gates_bundling(self):
        rng = np.random.default_rng(5)
        n = 4000
        # two near-exclusive indicators (default bin 0 for both): ~0.3%
        # of rows carry both nonzero
        u = rng.uniform(size=n)
        a = (u < 0.30).astype(np.float64)
        b = ((u >= 0.30) & (u < 0.60)).astype(np.float64)
        b[rng.uniform(size=n) < 0.01] = 1.0
        X = np.column_stack([a, b, rng.normal(size=n)])
        _, strict = bin_dataset(X, max_bin=255, feature_bundling=True)
        _, loose = bin_dataset(
            X, max_bin=255, feature_bundling=True, max_conflict_rate=0.05
        )
        assert strict.bundles is None  # 1% overlap busts a zero budget
        assert loose.bundles is not None
        assert loose.bundles.num_columns < 3
        assert loose.bundles.conflict_count > 0

    def test_feature_bundled_event_published(self):
        from mmlspark_tpu.observability import FeatureBundled, get_bus

        seen = []
        bus = get_bus()
        listener = seen.append
        bus.add_listener(listener)
        try:
            X, _ = _one_hot_case()
            bin_dataset(X, max_bin=63, feature_bundling=True)
        finally:
            bus.remove_listener(listener)
        ev = [e for e in seen if isinstance(e, FeatureBundled)]
        assert ev and ev[0].k_after < ev[0].k_before
        assert ev[0].num_columns < ev[0].num_features


class TestBundledFitParity:
    @_no_u
    def test_zero_conflict_u_fit_structurally_identical(self):
        # golden: on the U path the bundled histogram expands to the exact
        # same f32 values as the unbundled pass (default bin recovered by
        # subtraction in the same association), so a zero-conflict fit is
        # INDISTINGUISHABLE from the unbundled fit — model text and all
        X, y = _one_hot_case()
        bins_u, m_u = bin_dataset(X, max_bin=255)
        bins_b, m_b = bin_dataset(X, max_bin=255, feature_bundling=True)
        assert m_b.bundles is not None and m_b.bundles.conflict_count == 0
        for extra in ({}, {"growth": "depthwise", "max_depth": 4}):
            opts = TrainOptions(
                objective="binary", num_iterations=8, num_leaves=15,
                learning_rate=0.2, histogram_method="u", **extra,
            )
            ru = train(bins_u, y, opts, mapper=m_u)
            rb = train(bins_b, y, opts, mapper=m_b)
            assert (
                rb.booster.model_to_string() == ru.booster.model_to_string()
            ), f"bundled fit diverged structurally ({extra or 'leafwise'})"

    def test_compare_path_fit_float_parity(self):
        # the compare-built path recovers default bins by subtraction too;
        # that is float-equivalent, not bit-equivalent (same property as
        # native LightGBM's most_freq_bin histograms), so the contract here
        # is margin closeness + AUC parity, not byte identity
        X, y = _one_hot_case(seed=7)
        bins_u, m_u = bin_dataset(X, max_bin=255)
        bins_b, m_b = bin_dataset(X, max_bin=255, feature_bundling=True)
        opts = TrainOptions(
            objective="binary", num_iterations=8, num_leaves=15,
            learning_rate=0.2,
        )
        ru = train(bins_u, y, opts, mapper=m_u)
        rb = train(bins_b, y, opts, mapper=m_b)
        pu = ru.booster.raw_margin(X)[:, 0]
        pb = rb.booster.raw_margin(X)[:, 0]
        assert abs(_auc(y, pu) - _auc(y, pb)) <= 0.002
        assert np.abs(pu - pb).mean() < 5e-3

    def test_model_text_round_trips_in_original_feature_space(self):
        from mmlspark_tpu.lightgbm.booster import Booster

        X, y = _one_hot_case(seed=11)
        bins_b, m_b = bin_dataset(X, max_bin=255, feature_bundling=True)
        spec = m_b.bundles
        opts = TrainOptions(
            objective="binary", num_iterations=6, num_leaves=15,
            learning_rate=0.2, histogram_method="u",
        )
        rb = train(bins_b, y, opts, mapper=m_b)
        txt = rb.booster.model_to_string()
        assert f"max_feature_idx={X.shape[1] - 1}" in txt
        # every split id is an ORIGINAL feature id, and ids beyond the
        # packed column count appear — proof splits aren't in packed space
        feats = np.concatenate([
            sf[le == 0]
            for sf, le in zip(rb.booster.split_feature, rb.booster.is_leaf)
        ])
        assert feats.size and feats.max() < X.shape[1]
        assert feats.max() >= spec.num_columns
        rt = Booster.from_string(txt)
        np.testing.assert_allclose(  # text serialization = f32 precision
            rt.raw_margin(X), rb.booster.raw_margin(X), rtol=1e-5, atol=1e-6
        )

    def test_shap_parity(self):
        from mmlspark_tpu.lightgbm.shap import tree_shap

        X, y = _one_hot_case(seed=13)
        bins_u, m_u = bin_dataset(X, max_bin=255)
        bins_b, m_b = bin_dataset(X, max_bin=255, feature_bundling=True)
        opts = TrainOptions(
            objective="binary", num_iterations=6, num_leaves=15,
            learning_rate=0.2, histogram_method="u",
        )
        ru = train(bins_u, y, opts, mapper=m_u)
        rb = train(bins_b, y, opts, mapper=m_b)
        Xq = X[:200]
        phi_b = tree_shap(rb.booster, Xq)
        assert phi_b.shape == (200, 1, X.shape[1] + 1)
        # SHAP is additive: contributions sum to the margin
        np.testing.assert_allclose(
            phi_b.sum(-1)[:, 0], rb.booster.raw_margin(Xq)[:, 0],
            rtol=1e-6, atol=1e-6,
        )
        # and match the unbundled fit's explanation (identical U-path model;
        # on the NO_U compare path the models are only float-equivalent)
        if os.environ.get("MMLSPARK_TPU_NO_U") != "1":
            np.testing.assert_allclose(phi_b, tree_shap(ru.booster, Xq),
                                       rtol=1e-6, atol=1e-6)

    def test_unpacked_bins_with_bundled_mapper_rejected(self):
        X, y = _one_hot_case()
        bins_u, _ = bin_dataset(X, max_bin=255)
        _, m_b = bin_dataset(X, max_bin=255, feature_bundling=True)
        with pytest.raises(ValueError, match="packed bins"):
            train(
                bins_u, y,
                TrainOptions(objective="binary", num_iterations=2, num_leaves=7),
                mapper=m_b,
            )

    def test_voting_parallel_with_bundles_rejected(self):
        X, y = _one_hot_case()
        bins_b, m_b = bin_dataset(X, max_bin=255, feature_bundling=True)
        with pytest.raises(ValueError, match="voting"):
            train(
                bins_b, y,
                TrainOptions(objective="binary", num_iterations=2, num_leaves=7,
                             tree_learner="voting_parallel", top_k=3),
                mapper=m_b,
            )


class TestBundledEstimator:
    def test_classifier_param_flow_and_parity(self):
        from mmlspark_tpu.data.table import Table
        from mmlspark_tpu.lightgbm.classifier import LightGBMClassifier

        X, y = _one_hot_case(seed=17)
        tbl = Table({"features": X, "label": y})
        kw = dict(numIterations=8, numLeaves=15,
                  featuresCol="features", labelCol="label")
        m_plain = LightGBMClassifier(**kw).fit(tbl)
        m_bund = LightGBMClassifier(
            featureBundling=True, maxConflictRate=0.0, **kw
        ).fit(tbl)
        p0 = np.asarray(m_plain.transform(tbl)["probability"])[:, 1]
        p1 = np.asarray(m_bund.transform(tbl)["probability"])[:, 1]
        a0, a1 = _auc(y, p0), _auc(y, p1)
        assert a1 > 0.9
        assert abs(a0 - a1) <= 0.002, (a0, a1)
