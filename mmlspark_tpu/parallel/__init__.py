"""Mesh construction, topology discovery, sharding helpers, collectives."""

from mmlspark_tpu.parallel.mesh import MeshConfig, best_mesh, get_topology, make_mesh

__all__ = ["MeshConfig", "make_mesh", "best_mesh", "get_topology"]
