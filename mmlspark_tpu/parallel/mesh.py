"""TPU topology discovery and mesh construction.

TPU-native replacement for ``ClusterUtil`` (``core/utils/ClusterUtil.scala:13-177``)
and the driver socket rendezvous (``lightgbm/LightGBMUtils.scala:117-186``):
instead of discovering executor cores and exchanging host:port lists over a
``ServerSocket``, we discover the chip topology from the JAX runtime and build
a ``jax.sharding.Mesh``. Rendezvous/collective bring-up is the JAX runtime's
job (``jax.distributed`` + ICI); the "driver" only decides the mesh shape and
the partition→device assignment.

Axis convention (used across the framework):
- ``data``  — data parallel (batch/rows; the LightGBM ``data_parallel`` axis)
- ``model`` — tensor/feature parallel (feature-parallel histograms, TP matmuls)
- ``seq``   — sequence/context parallel (ring attention)
- ``pipe``  — pipeline parallel stages
- ``expert``— expert parallel (MoE)
Axes of size 1 cost nothing under XLA, so a single config covers 1 chip → pods.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict, Optional, Sequence, Tuple

import numpy as np

AXIS_DATA = "data"
AXIS_MODEL = "model"
AXIS_SEQ = "seq"
AXIS_PIPE = "pipe"
AXIS_EXPERT = "expert"

ALL_AXES = (AXIS_DATA, AXIS_MODEL, AXIS_SEQ, AXIS_PIPE, AXIS_EXPERT)


@dataclasses.dataclass(frozen=True)
class Topology:
    """What ``ClusterUtil`` discovered on Spark, re-expressed for TPU."""

    num_devices: int
    num_hosts: int
    devices_per_host: int
    platform: str
    device_kind: str

    @property
    def multi_host(self) -> bool:
        return self.num_hosts > 1


def get_topology() -> Topology:
    import jax

    devices = jax.devices()
    hosts = {d.process_index for d in devices}
    return Topology(
        num_devices=len(devices),
        num_hosts=len(hosts),
        devices_per_host=len(devices) // max(1, len(hosts)),
        platform=devices[0].platform,
        device_kind=devices[0].device_kind,
    )


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    """Declarative mesh shape. -1 on ``data`` means 'absorb remaining devices'."""

    data: int = -1
    model: int = 1
    seq: int = 1
    pipe: int = 1
    expert: int = 1

    def resolve(self, num_devices: int) -> Dict[str, int]:
        fixed = self.model * self.seq * self.pipe * self.expert
        if num_devices % fixed != 0:
            raise ValueError(
                f"{num_devices} devices not divisible by model*seq*pipe*expert={fixed}"
            )
        data = self.data if self.data != -1 else num_devices // fixed
        if data * fixed != num_devices:
            raise ValueError(
                f"mesh {data}x{fixed} != {num_devices} devices"
            )
        return {
            AXIS_DATA: data,
            AXIS_MODEL: self.model,
            AXIS_SEQ: self.seq,
            AXIS_PIPE: self.pipe,
            AXIS_EXPERT: self.expert,
        }


def make_mesh(
    config: Optional[MeshConfig] = None,
    devices: Optional[Sequence[Any]] = None,
    axis_names: Optional[Sequence[str]] = None,
):
    """Build a ``jax.sharding.Mesh`` over all (or given) devices.

    Device order follows ``jax.devices()``, which JAX already orders for ICI
    locality; inner-most mesh axes therefore get the tightest rings, so put
    the heavy-traffic axis (``model``/``seq``) last when customizing.
    """
    import jax
    from jax.sharding import Mesh

    config = config or MeshConfig()
    devices = list(devices if devices is not None else jax.devices())
    sizes = config.resolve(len(devices))
    names = tuple(axis_names or ALL_AXES)
    shape = tuple(sizes[n] for n in names)
    dev_array = np.asarray(devices).reshape(shape)
    return Mesh(dev_array, names)


def best_mesh(num_devices: Optional[int] = None):
    """A sensible default: everything on the data axis (the reference's only
    distribution mode is data parallel — SURVEY.md §5)."""
    import jax

    devices = jax.devices()
    if num_devices is not None:
        devices = devices[:num_devices]
    return make_mesh(MeshConfig(), devices=devices)


def data_sharding(mesh):
    """NamedSharding that shards dim 0 over the ``data`` axis only, replicating
    across model/seq/pipe/expert groups and all other dims."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    return NamedSharding(mesh, P(AXIS_DATA))


def replicated(mesh):
    from jax.sharding import NamedSharding, PartitionSpec as P

    return NamedSharding(mesh, P())


def pad_to_multiple(
    n: int, multiple: int
) -> Tuple[int, int]:
    """Rows to pad so n divides the mesh/data axis. Returns (padded_n, pad)."""
    padded = int(math.ceil(n / multiple) * multiple)
    return padded, padded - n


def distributed_init(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    executor_ids: Optional[Sequence[str]] = None,
    local_executor_id: Optional[str] = None,
    initialization_timeout: Optional[float] = None,
) -> Topology:
    """Multi-host bootstrap — the surviving driver-rendezvous role.

    The reference's driver collects executor host:port lines over a
    ``ServerSocket`` and broadcasts the worker list
    (``lightgbm/LightGBMUtils.scala:117-186``, ``ClusterUtil.scala:107-177``);
    on TPU the collective mesh is the JAX runtime's job and the driver's
    only duty is numbering the processes. Two calling conventions:

    - explicit: ``coordinator_address`` (driver host:port), ``num_processes``,
      ``process_id`` — forwarded to :func:`jax.distributed.initialize`;
    - executor-keyed: pass the full sorted-stable list of ``executor_ids``
      plus this host's ``local_executor_id``; the process id is the
      executor's rank in the list (deterministic across hosts, no extra
      coordination round).

    No-ops (returning the current topology) when running single-process,
    or when the process group is already initialized AND no explicit
    rendezvous was requested. An explicit multi-process rendezvous while a
    prior client is still up (a worker re-forming its gang after a member
    died) first tears the old client down via
    :func:`distributed_shutdown` — silently keeping the stale group would
    rendezvous iteration state against a dead membership.

    ``initialization_timeout`` (seconds) bounds how long the rendezvous
    waits for stragglers; a gang member that never shows up surfaces as an
    exception here instead of a five-minute default hang.
    """
    import jax

    if executor_ids is not None:
        if local_executor_id is None:
            raise ValueError("local_executor_id required with executor_ids")
        ordered = sorted(set(map(str, executor_ids)))
        if str(local_executor_id) not in ordered:
            raise ValueError(
                f"local executor {local_executor_id!r} not in executor_ids"
            )
        num_processes = len(ordered)
        process_id = ordered.index(str(local_executor_id))

    if num_processes is not None and num_processes > 1:
        if coordinator_address is None:
            raise ValueError(
                f"{num_processes} processes derived but no coordinator_address "
                "— pass the driver's host:port (the one piece of rendezvous "
                "the runtime cannot discover itself)"
            )
        if process_id is None:
            raise ValueError(
                f"{num_processes} processes requested but no process_id — "
                "pass it explicitly or use the executor_ids convention"
            )
        already = getattr(jax.distributed, "global_state", None)
        if already is not None and getattr(already, "client", None) is not None:
            # Re-initialization (second gang epoch in one process): the old
            # client must go down before a new rendezvous can form. The old
            # behavior — no-opping on global_state — left the process wired
            # to a dead coordinator.
            distributed_shutdown()
        kwargs = {}
        if initialization_timeout is not None:
            kwargs["initialization_timeout"] = int(max(1, initialization_timeout))
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
            **kwargs,
        )
    return get_topology()


def distributed_shutdown(timeout_s: float = 5.0, clear_backends: bool = False) -> bool:
    """Tear down this process's ``jax.distributed`` client/service so a new
    group can form (the gang-recovery teardown half of
    :func:`distributed_init`).

    The clean path is :func:`jax.distributed.shutdown`; it can block
    indefinitely when the coordinator died first, so it runs on a reaper
    thread bounded by ``timeout_s`` and on overrun the global state is
    force-cleared — the orphaned client leaks, but the process regains the
    ability to rendezvous, which is the property gang recovery needs.

    ``clear_backends=True`` additionally drops already-initialized XLA
    backends and compiled caches (the :func:`force_platform` teardown):
    required before re-initializing, because a backend created under the
    old group bakes in its process count/device topology. Returns True on
    a clean shutdown, False when state had to be force-cleared.
    """
    import threading

    import jax
    from jax._src import distributed as _dist
    from jax._src import xla_bridge

    state = getattr(_dist, "global_state", None)
    clean = True
    if state is not None and (
        getattr(state, "client", None) is not None
        or getattr(state, "service", None) is not None
    ):
        done = threading.Event()

        def _shutdown():
            try:
                jax.distributed.shutdown()
            except Exception:  # noqa: BLE001 - a dead coordinator is expected here
                pass
            finally:
                done.set()

        t = threading.Thread(
            target=_shutdown, name="mmlspark-tpu-dist-shutdown", daemon=True
        )
        t.start()
        if not done.wait(timeout_s):
            clean = False
        if getattr(state, "client", None) is not None or not clean:
            # force-clear whatever the (possibly wedged) clean path left
            for attr, value in (
                ("client", None), ("service", None),
                ("preemption_sync_manager", None),
                ("process_id", 0), ("num_processes", 0),
                ("coordinator_address", None),
            ):
                try:
                    setattr(state, attr, value)
                except AttributeError:
                    pass
    if clear_backends:
        if getattr(xla_bridge, "_backends", None) and hasattr(
            xla_bridge, "_clear_backends"
        ):
            xla_bridge._clear_backends()
            if hasattr(xla_bridge.get_backend, "cache_clear"):
                xla_bridge.get_backend.cache_clear()
            jax.clear_caches()
    return clean


def partition_assignment(num_partitions: int, mesh) -> Dict[int, Tuple[int, ...]]:
    """Map data-partition ids onto mesh coordinates — the partition→chip
    assignment that replaces ``ClusterUtil``'s executor/core bookkeeping.

    Partitions are assigned round-robin over the ``data`` axis (a partition's
    rows land on every device in that data-slice's model/seq/... subgroup,
    which replicates or shards them per the program's NamedShardings).
    Returns {partition_id: mesh coordinates of its data slice}.
    """
    sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
    data_size = sizes.get(AXIS_DATA, 1)
    if num_partitions < data_size:
        raise ValueError(
            f"{num_partitions} partitions cannot cover data axis of {data_size} "
            "(repartition up, or shrink the mesh — empty mesh slices would "
            "deadlock collectives, the 'empty partition' hazard of "
            "LightGBMUtils.scala:144-161)"
        )
    data_axis_pos = (
        mesh.axis_names.index(AXIS_DATA) if AXIS_DATA in mesh.axis_names else None
    )
    out: Dict[int, Tuple[int, ...]] = {}
    for pid in range(num_partitions):
        coord = [0] * len(mesh.axis_names)
        if data_axis_pos is not None:
            coord[data_axis_pos] = pid % data_size
        out[pid] = tuple(coord)  # no data axis: one slice takes everything
    return out


def feature_parallel_sharding(mesh):
    """NamedSharding for a (rows, features) matrix sharded rows-over-``data``
    AND features-over-``model`` — LightGBM's ``feature_parallel`` data layout
    (vertical partitioning), expressed as a sharding annotation: XLA then
    partitions histogram build + split search across the model axis and
    inserts the small best-split argmax collectives itself."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    return NamedSharding(mesh, P(AXIS_DATA, AXIS_MODEL))


def force_platform(platform: str, min_devices: int = 1) -> None:
    """Re-point JAX at a platform mid-process, tearing down already-initialized
    backends (the container sitecustomize pre-creates a TPU client at
    interpreter startup, so env vars alone are too late). For ``cpu`` with
    ``min_devices > 1`` the host-platform device-count flag is injected —
    it must be set before the first CPU client is created.

    WARNING: only reliable before the first jit execution in the process;
    after real compute has run, dispatch can silently stick to the old
    backend. Use a fresh subprocess to benchmark a second platform."""
    import os
    import re

    if platform == "cpu" and min_devices > 1:
        flags = os.environ.get("XLA_FLAGS", "")
        m = re.search(r"--xla_force_host_platform_device_count=(\d+)", flags)
        if m is None:
            flags = (flags + f" --xla_force_host_platform_device_count={min_devices}").strip()
        elif int(m.group(1)) < min_devices:
            flags = flags.replace(
                m.group(0), f"--xla_force_host_platform_device_count={min_devices}"
            )
        os.environ["XLA_FLAGS"] = flags

    import jax
    from jax._src import xla_bridge

    # Inspect only already-initialized backends — querying jax.devices() here
    # would instantiate the CURRENT platform's client (claiming the TPU relay,
    # the very thing this function exists to avoid).
    initialized = dict(getattr(xla_bridge, "_backends", {}) or {})
    current_ok = (
        platform in initialized
        and xla_bridge._default_backend is not None
        and xla_bridge._default_backend.platform == platform
        and len(initialized[platform].devices()) >= min_devices
    )
    if current_ok:
        return
    if initialized:
        if not hasattr(xla_bridge, "_clear_backends"):
            raise RuntimeError(
                "jax backends already initialized and this jax version has no "
                "_clear_backends hook; restart the process with "
                f"JAX_PLATFORMS={platform}"
            )
        xla_bridge._clear_backends()
        if hasattr(xla_bridge.get_backend, "cache_clear"):
            xla_bridge.get_backend.cache_clear()
        # Compiled-executable caches survive the backend teardown and can be
        # REUSED against the new client: a program traced on the old
        # single-device backend then silently misexecutes collectives on the
        # new multi-device one (observed as wrong ring-attention output after
        # an entry()-style warm-up preceded the platform switch).
        jax.clear_caches()
    jax.config.update("jax_platforms", platform)
    if len(jax.devices()) < min_devices:
        raise RuntimeError(
            f"could not materialize {min_devices} {platform} devices; "
            f"got {jax.devices()}"
        )
