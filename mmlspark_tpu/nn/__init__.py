"""Nearest neighbors (reference ``nn/``, SURVEY.md §2.6)."""

from mmlspark_tpu.nn.ball_tree import BallTree, BestMatch, ConditionalBallTree
from mmlspark_tpu.nn.knn import KNN, ConditionalKNN, ConditionalKNNModel, KNNModel

__all__ = [
    "BallTree",
    "BestMatch",
    "ConditionalBallTree",
    "ConditionalKNN",
    "ConditionalKNNModel",
    "KNN",
    "KNNModel",
]
