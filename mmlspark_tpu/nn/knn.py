"""KNN / ConditionalKNN — maximum-inner-product nearest neighbors.

Reference: ``nn/KNN.scala:45-115`` (fit collects the index to the driver,
builds a ball tree, broadcasts it, queries per row via UDF) and
``ConditionalKNN`` with label-filtered queries; optimized fit injection at
``org/apache/spark/sql/types/injections/OptimizedCKNNFitting.scala:74``.

TPU-first redesign: the default query path is **brute-force on the MXU** —
one ``queries @ keys.T`` matmul + ``lax.top_k`` per query batch, which for
the index sizes the reference targets (driver-collectable, i.e. ≤ a few
million rows) beats tree traversal by orders of magnitude and is exactly
the layout the systolic array wants (SURVEY.md §7 step 8: "KNN: consider
brute-force ``jnp.top_k`` on chip first"). The host ball tree
(:mod:`mmlspark_tpu.nn.ball_tree`) remains available via
``method="balltree"`` for huge indices or chip-free environments.

Conditional queries mask inadmissible index rows to ``-inf`` before the
top-k; rows are grouped by distinct conditioner so each group is a single
masked matmul.
"""

from __future__ import annotations

from typing import Any, Dict, Hashable, List, Optional, Sequence, Set

import numpy as np

from mmlspark_tpu.core.params import (
    HasFeaturesCol,
    HasOutputCol,
    Param,
    one_of,
    to_int,
    to_str,
)
from mmlspark_tpu.core.pipeline import Estimator, Model
from mmlspark_tpu.data.table import Table
from mmlspark_tpu.nn.ball_tree import BallTree, ConditionalBallTree

_QUERY_BATCH = 4096


def _run_topk(K, Q, m, k):
    import jax
    import jax.numpy as jnp

    scores = Q @ K.T  # (nq, n) — the MXU hot op
    if m is not None:
        scores = jnp.where(m[None, :], scores, -jnp.inf)
    return jax.lax.top_k(scores, k)


_run_topk_jit = None  # module-level so identical (shape, k) calls hit the jit cache


def _topk_inner_products(keys: np.ndarray, queries: np.ndarray, k: int,
                         mask: Optional[np.ndarray] = None):
    """Batched MIPS on device: scores = Q·Kᵀ (MXU), then top-k per row.

    Returns (scores, indices) as host arrays, shapes (nq, k).
    ``mask``: optional bool (n_index,) — False rows are excluded.
    """
    import jax
    import jax.numpy as jnp

    global _run_topk_jit
    if _run_topk_jit is None:
        _run_topk_jit = jax.jit(_run_topk, static_argnames=("k",))

    k = min(k, len(keys))
    K = jnp.asarray(keys, dtype=jnp.float32)
    m = None if mask is None else jnp.asarray(mask)
    out_s: List[np.ndarray] = []
    out_i: List[np.ndarray] = []
    for start in range(0, len(queries), _QUERY_BATCH):
        Q = jnp.asarray(queries[start:start + _QUERY_BATCH], dtype=jnp.float32)
        s, i = _run_topk_jit(K, Q, m, k)
        out_s.append(np.asarray(s))
        out_i.append(np.asarray(i))
    return np.concatenate(out_s), np.concatenate(out_i)


class _KNNParams(HasFeaturesCol, HasOutputCol):
    """Shared params (``nn/KNN.scala:21-44``)."""

    valuesCol = Param("Column of values returned for each match", default="values",
                      converter=to_str)
    k = Param("Number of matches to return", default=5, converter=to_int)
    leafSize = Param("Max leaf size of the ball tree", default=50, converter=to_int)
    method = Param("Query engine: 'brute' (on-chip matmul top-k) or 'balltree' (host)",
                   default="brute", validator=one_of("brute", "balltree"))


class KNN(_KNNParams, Estimator):
    """Fits a MIPS index over (featuresCol, valuesCol) rows."""

    def __init__(self, **kwargs):
        kwargs.setdefault("outputCol", None)
        super().__init__(**kwargs)

    def _fit(self, table: Table) -> "KNNModel":
        keys = np.asarray(table.column(self.getFeaturesCol()), dtype=np.float64)
        values = list(table.column(self.getValuesCol()))
        model = KNNModel(
            featuresCol=self.getFeaturesCol(),
            valuesCol=self.getValuesCol(),
            outputCol=self.getOutputCol() or f"{self.uid}_output",
            k=self.getK(),
            leafSize=self.getLeafSize(),
            method=self.getMethod(),
            indexKeys=keys,
            indexValues=values,
        )
        model.parent = self
        return model


class KNNModel(_KNNParams, Model):
    indexKeys = Param("Index key matrix (n × d)", is_complex=True, default=None)
    indexValues = Param("Per-row values returned on match", is_complex=True, default=None)

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self._tree: Optional[BallTree] = None

    def _ball_tree(self) -> BallTree:
        if self._tree is None:
            self._tree = BallTree(self.getIndexKeys(), self.getIndexValues(),
                                  leaf_size=self.getLeafSize())
        return self._tree

    def transform(self, table: Table) -> Table:
        queries = np.asarray(table.column(self.getFeaturesCol()), dtype=np.float64)
        k = self.getK()
        values = self.getIndexValues()
        out = np.empty(len(queries), dtype=object)
        if self.getMethod() == "brute":
            scores, idx = _topk_inner_products(self.getIndexKeys(), queries, k)
            for r in range(len(queries)):
                out[r] = [{"value": values[idx[r, j]], "distance": float(scores[r, j])}
                          for j in range(idx.shape[1])]
        else:
            tree = self._ball_tree()
            for r in range(len(queries)):
                out[r] = [{"value": values[m.index], "distance": m.distance}
                          for m in tree.find_maximum_inner_products(queries[r], k)]
        return table.with_column(self.getOutputCol(), out)


class _ConditionalKNNParams(_KNNParams):
    labelCol = Param("Column of index labels for conditional queries",
                     default="labels", converter=to_str)
    conditionerCol = Param("Query column holding the set of admissible labels",
                           default="conditioner", converter=to_str)


class ConditionalKNN(_ConditionalKNNParams, Estimator):
    """KNN whose matches are restricted per query to a set of labels
    (``nn/BallTree.scala:203``; fit injection ``OptimizedCKNNFitting.scala:74``)."""

    def __init__(self, **kwargs):
        kwargs.setdefault("outputCol", None)
        super().__init__(**kwargs)

    def _fit(self, table: Table) -> "ConditionalKNNModel":
        keys = np.asarray(table.column(self.getFeaturesCol()), dtype=np.float64)
        values = list(table.column(self.getValuesCol()))
        labels = list(table.column(self.getLabelCol()))
        model = ConditionalKNNModel(
            featuresCol=self.getFeaturesCol(),
            valuesCol=self.getValuesCol(),
            labelCol=self.getLabelCol(),
            conditionerCol=self.getConditionerCol(),
            outputCol=self.getOutputCol() or f"{self.uid}_output",
            k=self.getK(),
            leafSize=self.getLeafSize(),
            method=self.getMethod(),
            indexKeys=keys,
            indexValues=values,
            indexLabels=labels,
        )
        model.parent = self
        return model


class ConditionalKNNModel(_ConditionalKNNParams, Model):
    indexKeys = Param("Index key matrix (n × d)", is_complex=True, default=None)
    indexValues = Param("Per-row values returned on match", is_complex=True, default=None)
    indexLabels = Param("Per-row labels filtered by the conditioner", is_complex=True,
                        default=None)

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self._tree: Optional[ConditionalBallTree] = None

    def _ball_tree(self) -> ConditionalBallTree:
        if self._tree is None:
            self._tree = ConditionalBallTree(
                self.getIndexKeys(), self.getIndexValues(), self.getIndexLabels(),
                leaf_size=self.getLeafSize())
        return self._tree

    def transform(self, table: Table) -> Table:
        queries = np.asarray(table.column(self.getFeaturesCol()), dtype=np.float64)
        conditioners = table.column(self.getConditionerCol())
        k = self.getK()
        values = self.getIndexValues()
        labels = np.asarray(self.getIndexLabels(), dtype=object)
        out = np.empty(len(queries), dtype=object)
        if self.getMethod() == "brute":
            # group rows by distinct conditioner → one masked matmul per group
            groups: Dict[frozenset, List[int]] = {}
            for r, c in enumerate(conditioners):
                groups.setdefault(frozenset(c), []).append(r)
            for cond, rows in groups.items():
                mask = np.fromiter((l in cond for l in labels), dtype=bool,
                                   count=len(labels))
                kk = min(k, int(mask.sum()))
                if kk == 0:
                    for r in rows:
                        out[r] = []
                    continue
                scores, idx = _topk_inner_products(
                    self.getIndexKeys(), queries[rows], kk, mask=mask)
                for n, r in enumerate(rows):
                    out[r] = [{"value": values[idx[n, j]],
                               "distance": float(scores[n, j]),
                               "label": labels[idx[n, j]]}
                              for j in range(kk)]
        else:
            tree = self._ball_tree()
            for r in range(len(queries)):
                matches = tree.find_maximum_inner_products(
                    queries[r], k, conditioner=set(conditioners[r]))
                out[r] = [{"value": values[m.index], "distance": m.distance,
                           "label": labels[m.index]} for m in matches]
        return table.with_column(self.getOutputCol(), out)
