"""Host-side ball-tree for maximum-inner-product search (MIPS).

Re-design of the reference's Breeze ball tree (``nn/BallTree.scala:110`` and
``ConditionalBallTree`` ``nn/BallTree.scala:203`` with label-filtered queries
via ``ReverseIndex`` ``:182-201``). Construction and leaf scans are
numpy-vectorized; traversal prunes with the Cauchy–Schwarz upper bound
``query·mean + |query|·radius`` (``nn/BallTree.scala:53-55``).

On TPU the default query path is the brute-force MXU matmul in
:mod:`mmlspark_tpu.nn.knn` — the tree is the host/CPU structure used for
very large indices, for incremental queries, and for save/load parity with
the reference's hand-written ``ConditionalBallTree.py`` py4j wrapper.
"""

from __future__ import annotations

import heapq
import pickle
from dataclasses import dataclass, field
from typing import Any, Hashable, List, Optional, Sequence, Set, Tuple

import numpy as np


@dataclass
class _Node:
    mean: np.ndarray
    radius: float
    # leaf payload: row indices into the key matrix; None for inner nodes
    idx: Optional[np.ndarray] = None
    left: Optional["_Node"] = None
    right: Optional["_Node"] = None
    # labels present in this subtree (conditional tree only); used to skip
    # whole subtrees whose labels are disjoint from the conditioner — the
    # ReverseIndex role (``nn/BallTree.scala:182-201``).
    labels: Optional[frozenset] = None

    @property
    def is_leaf(self) -> bool:
        return self.idx is not None


@dataclass(order=True)
class BestMatch:
    """One query result: ``distance`` is the inner product (the reference
    returns inner products as 'distance', ``nn/KNN.scala:96-100``)."""

    distance: float
    index: int = field(compare=False)


def _make_split(keys: np.ndarray, idx: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """Two-pivot split: pivot1 = furthest from idx[0], pivot2 = furthest from
    pivot1; points go to the nearer pivot (``nn/BallTree.scala:57-82``)."""
    pts = keys[idx]
    d0 = np.linalg.norm(pts - pts[0], axis=1)
    p1 = int(np.argmax(d0))
    d1 = np.linalg.norm(pts - pts[p1], axis=1)
    p2 = int(np.argmax(d1))
    d2 = np.linalg.norm(pts - pts[p2], axis=1)
    to_left = d1 <= d2
    # guard degenerate splits (all points identical)
    if to_left.all() or not to_left.any():
        half = len(idx) // 2
        return idx[:half], idx[half:]
    return idx[to_left], idx[~to_left]


def _build(keys: np.ndarray, idx: np.ndarray, leaf_size: int,
           labels: Optional[np.ndarray]) -> _Node:
    pts = keys[idx]
    mean = pts.mean(axis=0)
    radius = float(np.linalg.norm(pts - mean, axis=1).max()) if len(idx) else 0.0
    node_labels = frozenset(labels[idx].tolist()) if labels is not None else None
    if len(idx) <= leaf_size:
        return _Node(mean=mean, radius=radius, idx=idx, labels=node_labels)
    li, ri = _make_split(keys, idx)
    if len(li) == 0 or len(ri) == 0:  # pragma: no cover - guarded in _make_split
        return _Node(mean=mean, radius=radius, idx=idx, labels=node_labels)
    return _Node(
        mean=mean,
        radius=radius,
        left=_build(keys, li, leaf_size, labels),
        right=_build(keys, ri, leaf_size, labels),
        labels=node_labels,
    )


class BallTree:
    """MIPS ball tree over ``keys`` (n × d) carrying per-row ``values``.

    ``find_maximum_inner_products(q, k)`` returns the top-k
    :class:`BestMatch` sorted by descending inner product
    (``nn/BallTree.scala:146-152``).
    """

    def __init__(self, keys: np.ndarray, values: Sequence[Any], leaf_size: int = 50):
        self.keys = np.ascontiguousarray(np.asarray(keys, dtype=np.float64))
        if self.keys.ndim != 2:
            raise ValueError(f"keys must be 2-D, got shape {self.keys.shape}")
        if len(values) != len(self.keys):
            raise ValueError("values length must match keys")
        self.values = list(values)
        self.leaf_size = int(leaf_size)
        self.root = _build(self.keys, np.arange(len(self.keys)), self.leaf_size, self._label_array())

    def _label_array(self) -> Optional[np.ndarray]:
        return None

    # -- querying -----------------------------------------------------------

    def _upper_bound(self, q: np.ndarray, q_norm: float, node: _Node) -> float:
        # Cauchy–Schwarz MIP bound (``nn/BallTree.scala:53-55``)
        return float(q @ node.mean) + q_norm * node.radius

    def _leaf_scan(self, q: np.ndarray, node: _Node,
                   heap: List[Tuple[float, int]], k: int,
                   mask: Optional[np.ndarray]) -> None:
        idx = node.idx
        if mask is not None:
            idx = idx[mask[idx]]
            if len(idx) == 0:
                return
        scores = self.keys[idx] @ q
        for s, i in zip(scores, idx):
            if len(heap) < k:
                heapq.heappush(heap, (float(s), int(i)))
            elif s > heap[0][0]:
                heapq.heapreplace(heap, (float(s), int(i)))

    def _query(self, q: np.ndarray, k: int,
               conditioner: Optional[Set[Hashable]] = None,
               mask: Optional[np.ndarray] = None) -> List[BestMatch]:
        q = np.asarray(q, dtype=np.float64).ravel()
        q_norm = float(np.linalg.norm(q))
        heap: List[Tuple[float, int]] = []  # min-heap of (score, idx)
        stack = [self.root]
        while stack:
            node = stack.pop()
            if conditioner is not None and node.labels is not None \
                    and node.labels.isdisjoint(conditioner):
                continue
            if len(heap) >= k and self._upper_bound(q, q_norm, node) <= heap[0][0]:
                continue
            if node.is_leaf:
                self._leaf_scan(q, node, heap, k, mask)
            else:
                # visit the more promising child last so it is popped first
                ub_l = self._upper_bound(q, q_norm, node.left)
                ub_r = self._upper_bound(q, q_norm, node.right)
                children = (node.left, node.right) if ub_l <= ub_r else (node.right, node.left)
                stack.extend(children)
        return [BestMatch(distance=s, index=i)
                for s, i in sorted(heap, key=lambda t: -t[0])]

    def find_maximum_inner_products(self, query: np.ndarray, k: int = 1) -> List[BestMatch]:
        return self._query(query, k)

    # -- persistence (``ConditionalBallTree.save/load``, BallTree.scala:261) -

    def save(self, filename: str) -> None:
        with open(filename, "wb") as f:
            pickle.dump(self, f)

    @classmethod
    def load(cls, filename: str) -> "BallTree":
        with open(filename, "rb") as f:
            tree = pickle.load(f)
        if not isinstance(tree, cls):
            raise TypeError(f"loaded {type(tree).__name__}, expected {cls.__name__}")
        return tree

    def __repr__(self) -> str:
        return f"{type(self).__name__}(n={len(self.keys)}, d={self.keys.shape[1]}, leaf_size={self.leaf_size})"


class ConditionalBallTree(BallTree):
    """Ball tree whose rows carry labels; queries pass a ``conditioner`` set
    of admissible labels (``nn/BallTree.scala:203-259``). Subtrees whose
    label sets are disjoint from the conditioner are pruned wholesale."""

    def __init__(self, keys: np.ndarray, values: Sequence[Any],
                 labels: Sequence[Hashable], leaf_size: int = 50):
        if len(labels) != len(values):
            raise ValueError("labels length must match values")
        self.labels = np.asarray(list(labels), dtype=object)
        super().__init__(keys, values, leaf_size)

    def _label_array(self) -> Optional[np.ndarray]:
        return self.labels

    def find_maximum_inner_products(self, query: np.ndarray, k: int = 1,
                                    conditioner: Optional[Set[Hashable]] = None
                                    ) -> List[BestMatch]:
        if conditioner is None:
            return self._query(query, k)
        conditioner = set(conditioner)
        mask = np.fromiter((l in conditioner for l in self.labels),
                           dtype=bool, count=len(self.labels))
        return self._query(query, k, conditioner=conditioner, mask=mask)
