"""Cognitive-service transformers (reference ``cognitive/``, SURVEY.md §2.17).

Each service is a thin :class:`CognitiveServicesBase` subclass declaring its
request shape — the heavy lifting (HTTP, retries, error columns, key
headers) lives in the base. Live-endpoint tests are impossible without
network egress; suites exercise these against in-process mock servers, the
pattern the reference's serving suites use (``io/split2/HTTPv2Suite``).
"""

from __future__ import annotations

import json
from typing import Any, Dict, Optional

from mmlspark_tpu.cognitive import schemas
from mmlspark_tpu.cognitive.base import CognitiveServicesBase, ServiceParam
from mmlspark_tpu.core.params import Param, to_str
from mmlspark_tpu.data.table import Table


class _TextAnalyticsBase(CognitiveServicesBase):
    """documents batch body (``cognitive/TextAnalytics.scala``)."""

    textCol = Param("Column of input text", default="text", converter=to_str)
    language = ServiceParam("Language hint", default=("value", "en"))

    def prepare_entity(self, table: Table, row: int) -> Dict[str, Any]:
        lang = self._resolve_service_param("language", table, row)
        return {
            "documents": [
                {"id": "0", "language": lang,
                 "text": str(table.column(self.textCol)[row])}
            ]
        }


class TextSentiment(_TextAnalyticsBase):
    """``cognitive/TextAnalytics.scala`` TextSentiment."""

    response_schema = schemas.TAResponse


class LanguageDetector(_TextAnalyticsBase):
    """``cognitive/TextAnalytics.scala`` LanguageDetector."""

    response_schema = schemas.TAResponse

    def prepare_entity(self, table: Table, row: int) -> Dict[str, Any]:
        return {
            "documents": [
                {"id": "0", "text": str(table.column(self.textCol)[row])}
            ]
        }


class EntityDetector(_TextAnalyticsBase):
    """``cognitive/TextAnalytics.scala`` EntityDetector."""

    response_schema = schemas.TAResponse


class KeyPhraseExtractor(_TextAnalyticsBase):
    """``cognitive/TextAnalytics.scala`` KeyPhraseExtractor."""

    response_schema = schemas.TAResponse


class NER(_TextAnalyticsBase):
    """``cognitive/TextAnalytics.scala`` NER."""

    response_schema = schemas.TAResponse


class _ImageServiceBase(CognitiveServicesBase):
    """Image-URL body (``cognitive/ComputerVision.scala`` HasImageUrl)."""

    imageUrlCol = Param("Column of image URLs", default="url", converter=to_str)

    def prepare_entity(self, table: Table, row: int) -> Dict[str, Any]:
        return {"url": str(table.column(self.imageUrlCol)[row])}


class OCR(_ImageServiceBase):
    """``cognitive/ComputerVision.scala`` OCR."""

    response_schema = schemas.OCRResponse
    detectOrientation = ServiceParam("Detect orientation", is_url_param=True)


class AnalyzeImage(_ImageServiceBase):
    """``cognitive/ComputerVision.scala`` AnalyzeImage."""

    response_schema = schemas.AnalyzeImageResponse
    visualFeatures = ServiceParam("Comma-joined feature list", is_url_param=True)


class DescribeImage(_ImageServiceBase):
    """``cognitive/ComputerVision.scala`` DescribeImage."""

    response_schema = schemas.DescribeImageResponse
    maxCandidates = ServiceParam("Caption candidates", is_url_param=True)


class TagImage(_ImageServiceBase):
    """``cognitive/ComputerVision.scala`` TagImage."""

    response_schema = schemas.TagImageResponse


class RecognizeText(_ImageServiceBase):
    """``cognitive/ComputerVision.scala`` RecognizeText: the REAL async
    flow — the service answers 202 with an Operation-Location header and
    the result arrives by polling that URL until a terminal status."""

    response_schema = schemas.RecognizeTextResponse
    polling = True
    mode = ServiceParam("Printed|Handwritten", is_url_param=True)


class GenerateThumbnails(_ImageServiceBase):
    """``cognitive/ComputerVision.scala`` GenerateThumbnails."""

    width = ServiceParam("Thumb width", is_url_param=True)
    height = ServiceParam("Thumb height", is_url_param=True)
    smartCropping = ServiceParam("Smart crop", is_url_param=True)


class DetectFace(_ImageServiceBase):
    """``cognitive/Face.scala`` DetectFace."""

    response_schema = schemas.FaceListResponse
    returnFaceAttributes = ServiceParam("Attribute list", is_url_param=True)
    returnFaceLandmarks = ServiceParam("Landmarks flag", is_url_param=True)


class FindSimilarFace(CognitiveServicesBase):
    """``cognitive/Face.scala`` FindSimilarFace."""

    faceIdCol = Param("Column of face ids", default="faceId", converter=to_str)
    faceIds = ServiceParam("Candidate face id list")

    def prepare_entity(self, table: Table, row: int) -> Dict[str, Any]:
        return {
            "faceId": str(table.column(self.faceIdCol)[row]),
            "faceIds": self._resolve_service_param("faceIds", table, row) or [],
        }


class IdentifyFaces(CognitiveServicesBase):
    """``cognitive/Face.scala`` IdentifyFaces: match detected faces against
    a person group."""

    response_schema = schemas.IdentifyResponse
    faceIdsCol = Param("Column of face-id lists", default="faceIds", converter=to_str)
    personGroupId = ServiceParam("Person group to search")
    maxNumOfCandidatesReturned = ServiceParam("Candidate cap")
    confidenceThreshold = ServiceParam("Match confidence threshold")

    def prepare_entity(self, table: Table, row: int) -> Dict[str, Any]:
        ids = table.column(self.faceIdsCol)[row]
        if hasattr(ids, "tolist"):
            ids = ids.tolist()
        body: Dict[str, Any] = {
            "faceIds": list(ids),
            "personGroupId": self._resolve_service_param("personGroupId", table, row),
        }
        for opt in ("maxNumOfCandidatesReturned", "confidenceThreshold"):
            v = self._resolve_service_param(opt, table, row)
            if v is not None:
                body[opt] = v
        return body


class GroupFaces(CognitiveServicesBase):
    """``cognitive/Face.scala`` GroupFaces: cluster face ids by similarity."""

    response_schema = schemas.GroupResponse
    faceIdsCol = Param("Column of face-id lists", default="faceIds", converter=to_str)

    def prepare_entity(self, table: Table, row: int) -> Dict[str, Any]:
        ids = table.column(self.faceIdsCol)[row]
        if hasattr(ids, "tolist"):
            ids = ids.tolist()
        return {"faceIds": list(ids)}


class VerifyFaces(CognitiveServicesBase):
    """``cognitive/Face.scala`` VerifyFaces: same-person check for a pair of
    face ids (or face id vs person)."""

    response_schema = schemas.VerifyResponse
    faceId1Col = Param("Column of first face ids", default="faceId1", converter=to_str)
    faceId2Col = Param("Column of second face ids", default="faceId2", converter=to_str)

    def prepare_entity(self, table: Table, row: int) -> Dict[str, Any]:
        return {
            "faceId1": str(table.column(self.faceId1Col)[row]),
            "faceId2": str(table.column(self.faceId2Col)[row]),
        }


class DetectAnomalies(CognitiveServicesBase):
    """``cognitive/AnamolyDetection.scala:23-160`` DetectAnomalies: series of
    (timestamp, value) points + granularity."""

    response_schema = schemas.AnomalyResponse
    seriesCol = Param("Column of point-dict lists", default="series", converter=to_str)
    granularity = ServiceParam("Series granularity", default=("value", "daily"))

    def prepare_entity(self, table: Table, row: int) -> Dict[str, Any]:
        series = table.column(self.seriesCol)[row]
        if hasattr(series, "tolist"):
            series = series.tolist()
        return {
            "series": list(series),
            "granularity": self._resolve_service_param("granularity", table, row),
        }


class SpeechToText(CognitiveServicesBase):
    """``cognitive/SpeechToText.scala`` REST speech recognition: binary audio
    body in ONE request. For the streaming variant (pull-stream frames over
    chunked transfer, the Speech SDK transport shape) see
    :class:`mmlspark_tpu.cognitive.SpeechToTextSDK`."""

    response_schema = schemas.SpeechResponse
    audioDataCol = Param("Column of audio bytes", default="audio", converter=to_str)
    format = ServiceParam("simple|detailed", is_url_param=True)
    language = ServiceParam("Recognition language", is_url_param=True,
                            default=("value", "en-US"))

    def prepare_entity(self, table: Table, row: int) -> Dict[str, Any]:
        import base64

        audio = table.column(self.audioDataCol)[row]
        if isinstance(audio, bytes):
            audio = base64.b64encode(audio).decode("ascii")
        return {"audio": audio}


class BingImageSearch(CognitiveServicesBase):
    """``cognitive/BingImageSearch.scala:27-66``: GET with query url param."""

    queryCol = Param("Column of search queries", default="q", converter=to_str)
    count = ServiceParam("Result count", is_url_param=True)
    offset = ServiceParam("Result offset", is_url_param=True)

    def prepare_method(self) -> str:
        return "GET"

    def prepare_entity(self, table: Table, row: int) -> Optional[Dict[str, Any]]:
        return None

    def url_params(self, table: Table, row: int) -> Dict[str, str]:
        out = super().url_params(table, row)
        out["q"] = str(table.column(self.queryCol)[row])
        return out
