"""Cognitive-service transformers (reference ``cognitive/``, SURVEY.md §2.17).

Each service is a thin :class:`CognitiveServicesBase` subclass declaring its
request shape — the heavy lifting (HTTP, retries, error columns, key
headers) lives in the base. Live-endpoint tests are impossible without
network egress; suites exercise these against in-process mock servers, the
pattern the reference's serving suites use (``io/split2/HTTPv2Suite``).
"""

from __future__ import annotations

import json
from typing import Any, Dict, Optional

from mmlspark_tpu.cognitive.base import CognitiveServicesBase, ServiceParam
from mmlspark_tpu.core.params import Param, to_str
from mmlspark_tpu.data.table import Table


class _TextAnalyticsBase(CognitiveServicesBase):
    """documents batch body (``cognitive/TextAnalytics.scala``)."""

    textCol = Param("Column of input text", default="text", converter=to_str)
    language = ServiceParam("Language hint", default=("value", "en"))

    def prepare_entity(self, table: Table, row: int) -> Dict[str, Any]:
        lang = self._resolve_service_param("language", table, row)
        return {
            "documents": [
                {"id": "0", "language": lang,
                 "text": str(table.column(self.textCol)[row])}
            ]
        }


class TextSentiment(_TextAnalyticsBase):
    """``cognitive/TextAnalytics.scala`` TextSentiment."""


class LanguageDetector(_TextAnalyticsBase):
    """``cognitive/TextAnalytics.scala`` LanguageDetector."""

    def prepare_entity(self, table: Table, row: int) -> Dict[str, Any]:
        return {
            "documents": [
                {"id": "0", "text": str(table.column(self.textCol)[row])}
            ]
        }


class EntityDetector(_TextAnalyticsBase):
    """``cognitive/TextAnalytics.scala`` EntityDetector."""


class KeyPhraseExtractor(_TextAnalyticsBase):
    """``cognitive/TextAnalytics.scala`` KeyPhraseExtractor."""


class NER(_TextAnalyticsBase):
    """``cognitive/TextAnalytics.scala`` NER."""


class _ImageServiceBase(CognitiveServicesBase):
    """Image-URL body (``cognitive/ComputerVision.scala`` HasImageUrl)."""

    imageUrlCol = Param("Column of image URLs", default="url", converter=to_str)

    def prepare_entity(self, table: Table, row: int) -> Dict[str, Any]:
        return {"url": str(table.column(self.imageUrlCol)[row])}


class OCR(_ImageServiceBase):
    """``cognitive/ComputerVision.scala`` OCR."""

    detectOrientation = ServiceParam("Detect orientation", is_url_param=True)


class AnalyzeImage(_ImageServiceBase):
    """``cognitive/ComputerVision.scala`` AnalyzeImage."""

    visualFeatures = ServiceParam("Comma-joined feature list", is_url_param=True)


class RecognizeText(_ImageServiceBase):
    """``cognitive/ComputerVision.scala`` RecognizeText (async
    polling-location flow collapses to one call against mocks)."""

    mode = ServiceParam("Printed|Handwritten", is_url_param=True)


class GenerateThumbnails(_ImageServiceBase):
    """``cognitive/ComputerVision.scala`` GenerateThumbnails."""

    width = ServiceParam("Thumb width", is_url_param=True)
    height = ServiceParam("Thumb height", is_url_param=True)
    smartCropping = ServiceParam("Smart crop", is_url_param=True)


class DetectFace(_ImageServiceBase):
    """``cognitive/Face.scala`` DetectFace."""

    returnFaceAttributes = ServiceParam("Attribute list", is_url_param=True)
    returnFaceLandmarks = ServiceParam("Landmarks flag", is_url_param=True)


class FindSimilarFace(CognitiveServicesBase):
    """``cognitive/Face.scala`` FindSimilarFace."""

    faceIdCol = Param("Column of face ids", default="faceId", converter=to_str)
    faceIds = ServiceParam("Candidate face id list")

    def prepare_entity(self, table: Table, row: int) -> Dict[str, Any]:
        return {
            "faceId": str(table.column(self.faceIdCol)[row]),
            "faceIds": self._resolve_service_param("faceIds", table, row) or [],
        }


class DetectAnomalies(CognitiveServicesBase):
    """``cognitive/AnamolyDetection.scala:23-160`` DetectAnomalies: series of
    (timestamp, value) points + granularity."""

    seriesCol = Param("Column of point-dict lists", default="series", converter=to_str)
    granularity = ServiceParam("Series granularity", default=("value", "daily"))

    def prepare_entity(self, table: Table, row: int) -> Dict[str, Any]:
        series = table.column(self.seriesCol)[row]
        if hasattr(series, "tolist"):
            series = series.tolist()
        return {
            "series": list(series),
            "granularity": self._resolve_service_param("granularity", table, row),
        }


class SpeechToText(CognitiveServicesBase):
    """``cognitive/SpeechToText.scala`` REST speech recognition: binary audio
    body (the native Speech SDK streaming variant is out of TPU scope —
    SURVEY.md §2.20 item 5 keeps it a host HTTP client)."""

    audioDataCol = Param("Column of audio bytes", default="audio", converter=to_str)
    format = ServiceParam("simple|detailed", is_url_param=True)
    language = ServiceParam("Recognition language", is_url_param=True,
                            default=("value", "en-US"))

    def prepare_entity(self, table: Table, row: int) -> Dict[str, Any]:
        import base64

        audio = table.column(self.audioDataCol)[row]
        if isinstance(audio, bytes):
            audio = base64.b64encode(audio).decode("ascii")
        return {"audio": audio}


class BingImageSearch(CognitiveServicesBase):
    """``cognitive/BingImageSearch.scala:27-66``: GET with query url param."""

    queryCol = Param("Column of search queries", default="q", converter=to_str)
    count = ServiceParam("Result count", is_url_param=True)
    offset = ServiceParam("Result offset", is_url_param=True)

    def prepare_method(self) -> str:
        return "GET"

    def prepare_entity(self, table: Table, row: int) -> Optional[Dict[str, Any]]:
        return None

    def url_params(self, table: Table, row: int) -> Dict[str, str]:
        out = super().url_params(table, row)
        out["q"] = str(table.column(self.queryCol)[row])
        return out
