"""Audio pull streams for streaming speech recognition.

Re-design of the reference's ``cognitive/AudioStreams.scala:16-84``
(``WavStream``/``CompressedStream`` — PullAudioInputStreamCallback
adapters for the Speech SDK): a WAV header parser with the same strict
contract (RIFF/WAVE, PCM format tag, mono, 16 kHz, 16-bit — the asserts
mirror the Scala line for line) and a frame iterator that feeds the
streaming transport in bounded chunks, so arbitrarily long audio never
materializes in one buffer.
"""

from __future__ import annotations

import io
import struct
from typing import Iterator, Union


class _PullStream:
    """Shared pull-stream plumbing: bytes-or-stream wrapping, frame
    iteration, close (the ``PullAudioInputStreamCallback`` read contract)."""

    def __init__(self, data: Union[bytes, io.RawIOBase], chunk_size: int):
        self._stream = io.BytesIO(data) if isinstance(data, (bytes, bytearray)) else data
        self.chunk_size = int(chunk_size)

    def read(self, n: int) -> bytes:
        """One frame of at most ``n`` bytes (empty at end of stream)."""
        return self._stream.read(n) or b""

    def frames(self) -> Iterator[bytes]:
        while True:
            frame = self.read(self.chunk_size)
            if not frame:
                return
            yield frame

    def close(self) -> None:
        self._stream.close()


class WavStream(_PullStream):
    """Pull stream over a WAV payload: validates the header, then yields the
    PCM data in ``chunk_size``-byte frames (``WavStream.read``'s contract)."""

    def __init__(self, data: Union[bytes, io.RawIOBase], chunk_size: int = 3200):
        # 3200 bytes = 100 ms of 16 kHz mono 16-bit PCM (the SDK's cadence)
        super().__init__(data, chunk_size)
        self._parse_wav_header()

    # -- header ------------------------------------------------------------

    def _read_exact(self, n: int) -> bytes:
        buf = self._stream.read(n)
        if buf is None or len(buf) != n:
            raise ValueError("truncated WAV header")
        return buf

    def _uint32(self) -> int:
        return struct.unpack("<I", self._read_exact(4))[0]

    def _uint16(self) -> int:
        return struct.unpack("<H", self._read_exact(2))[0]

    def _parse_wav_header(self) -> None:
        if self._read_exact(4) != b"RIFF":
            raise ValueError("RIFF")
        self._uint32()  # file length
        if self._read_exact(4) != b"WAVE":
            raise ValueError("WAVE")
        if self._read_exact(4) != b"fmt ":
            raise ValueError("fmt ")
        format_size = self._uint32()
        if format_size < 16:
            raise ValueError("formatSize")
        format_tag = self._uint16()
        channels = self._uint16()
        samples_per_sec = self._uint32()
        self._uint32()  # avg bytes/sec
        self._uint16()  # block align
        bits_per_sample = self._uint16()
        # the reference's exact contract (AudioStreams.scala:63-67)
        if format_tag != 1:
            raise ValueError("PCM")
        if channels != 1:
            raise ValueError("single channel")
        if samples_per_sec != 16000:
            raise ValueError("samples per second")
        if bits_per_sample != 16:
            raise ValueError("bits per sample")
        if format_size > 16:
            self._read_exact(format_size - 16)
        if self._read_exact(4) != b"data":
            raise ValueError("data")
        self.data_length = self._uint32()


class CompressedStream(_PullStream):
    """Opaque compressed audio (mp3/ogg — ``CompressedStream``,
    AudioStreams.scala:84+): no header validation, frames pass through for
    server-side decoding."""

    def __init__(self, data: Union[bytes, io.RawIOBase], chunk_size: int = 4096):
        super().__init__(data, chunk_size)


def make_audio_stream(data: bytes, file_type: str = "wav", chunk_size: int = 3200):
    """Factory matching ``SpeechToTextSDK``'s fileType dispatch."""
    if file_type == "wav":
        return WavStream(data, chunk_size=chunk_size)
    if file_type in ("mp3", "ogg"):
        return CompressedStream(data, chunk_size=chunk_size)
    raise ValueError(f"unsupported audio fileType {file_type!r} (wav|mp3|ogg)")
