"""Azure-Search-style index writer.

Reference: ``cognitive/AzureSearch.scala:84-136`` (``AddDocuments``
transformer: rows → batched index actions with exponential backoff) and
``cognitive/AzureSearchAPI.scala:16-42`` (index creation / existence
checks).
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional

import numpy as np

from mmlspark_tpu.cognitive.base import CognitiveServicesBase, ServiceParam
from mmlspark_tpu.core.params import Param, gt, to_int, to_str
from mmlspark_tpu.core.pipeline import Transformer
from mmlspark_tpu.data.table import Table
from mmlspark_tpu.io.http.clients import HTTPClient
from mmlspark_tpu.io.http.schema import EntityData, HeaderData, HTTPRequestData


class AddDocuments(CognitiveServicesBase):
    """Push table rows into a search index in batches
    (``AzureSearch.scala:84-136``). Each batch is one POST of
    ``{"value": [{"@search.action": ..., <row fields>}, ...]}``."""

    actionCol = Param("Column holding the per-row index action",
                      default=None)
    batchSize = Param("Documents per request", default=100, converter=to_int,
                      validator=gt(0))

    def transform(self, table: Table) -> Table:
        if self.getUrl() is None:
            raise ValueError("AddDocuments requires url")
        client = HTTPClient(retries=(0.2, 0.8, 3.2))  # exponential backoff
        action_col = self.getActionCol()
        statuses: List[int] = []
        n = table.num_rows
        for start in range(0, n, self.getBatchSize()):
            # Column-bound keys resolve per batch (row `start`), not row 0.
            key = self._resolve_service_param("subscriptionKey", table, start)
            headers = {"Content-Type": "application/json"}
            if key:
                headers["api-key"] = key
            docs = []
            for row in range(start, min(start + self.getBatchSize(), n)):
                doc: Dict[str, Any] = {
                    "@search.action": (
                        str(table.column(action_col)[row]) if action_col else "upload"
                    )
                }
                for name in table.columns:
                    if name == action_col:
                        continue
                    v = table.column(name)[row]
                    if isinstance(v, np.ndarray):
                        v = v.tolist()
                    elif isinstance(v, np.generic):
                        v = v.item()
                    doc[name] = v
                docs.append(doc)
            req = HTTPRequestData(
                url=self.getUrl(),
                method="POST",
                headers=[HeaderData(k, v) for k, v in headers.items()],
                entity=EntityData(content=json.dumps({"value": docs}).encode("utf-8"),
                                  contentType="application/json"),
            )
            resp = client.send(req)
            statuses.extend([resp.status_code] * len(docs))
        return table.with_column("indexStatus", np.asarray(statuses, dtype=np.int64))
