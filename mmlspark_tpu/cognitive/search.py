"""Azure-Search-style index writer.

Reference: ``cognitive/AzureSearch.scala:84-136`` (``AddDocuments``
transformer: rows → batched index actions with exponential backoff) and
``cognitive/AzureSearchAPI.scala:16-42`` (index creation / existence
checks).
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional

import numpy as np

from mmlspark_tpu.cognitive.base import CognitiveServicesBase, ServiceParam
from mmlspark_tpu.core.params import Param, gt, to_int, to_str
from mmlspark_tpu.core.pipeline import Transformer
from mmlspark_tpu.data.table import Table, row_as_json_dict
from mmlspark_tpu.io.http.clients import HTTPClient
from mmlspark_tpu.io.http.schema import EntityData, HeaderData, HTTPRequestData


class AddDocuments(CognitiveServicesBase):
    """Push table rows into a search index in batches
    (``AzureSearch.scala:84-136``). Each batch is one POST of
    ``{"value": [{"@search.action": ..., <row fields>}, ...]}``."""

    actionCol = Param("Column holding the per-row index action",
                      default=None)
    batchSize = Param("Documents per request", default=100, converter=to_int,
                      validator=gt(0))

    def transform(self, table: Table) -> Table:
        if self.getUrl() is None:
            raise ValueError("AddDocuments requires url")
        client = HTTPClient(retries=(0.2, 0.8, 3.2))  # exponential backoff
        action_col = self.getActionCol()
        statuses: List[int] = []
        n = table.num_rows
        for start in range(0, n, self.getBatchSize()):
            # Column-bound keys resolve per batch (row `start`), not row 0.
            key = self._resolve_service_param("subscriptionKey", table, start)
            headers = {"Content-Type": "application/json"}
            if key:
                headers["api-key"] = key
            docs = []
            for row in range(start, min(start + self.getBatchSize(), n)):
                doc: Dict[str, Any] = {
                    "@search.action": (
                        str(table.column(action_col)[row]) if action_col else "upload"
                    )
                }
                doc.update(
                    row_as_json_dict(
                        table, row, exclude=(action_col,) if action_col else ()
                    )
                )
                docs.append(doc)
            req = HTTPRequestData(
                url=self.getUrl(),
                method="POST",
                headers=[HeaderData(k, v) for k, v in headers.items()],
                entity=EntityData(content=json.dumps({"value": docs}).encode("utf-8"),
                                  contentType="application/json"),
            )
            resp = client.send(req)
            statuses.extend([resp.status_code] * len(docs))
        return table.with_column("indexStatus", np.asarray(statuses, dtype=np.int64))


class SearchIndexClient:
    """Index management against an Azure-Search-style REST surface —
    existence check + creation with exponential backoff
    (``cognitive/AzureSearchAPI.scala:16-42``)."""

    def __init__(self, service_url: str, api_key: Optional[str] = None,
                 retries=(0.2, 0.8, 3.2)):
        self.service_url = service_url.rstrip("/")
        self.api_key = api_key
        self.client = HTTPClient(retries=retries)

    def _headers(self) -> List[HeaderData]:
        headers = [HeaderData("Content-Type", "application/json")]
        if self.api_key:
            headers.append(HeaderData("api-key", self.api_key))
        return headers

    def index_exists(self, name: str) -> bool:
        resp = self.client.send(
            HTTPRequestData(
                url=f"{self.service_url}/indexes/{name}",
                method="GET",
                headers=self._headers(),
            )
        )
        if resp.status_code == 200:
            return True
        if resp.status_code == 404:
            return False
        raise RuntimeError(
            f"index existence check failed: HTTP {resp.status_code} {resp.text()[:200]}"
        )

    @staticmethod
    def _validate(definition: Dict[str, Any]) -> str:
        """The schema checks ``AzureSearchAPI.scala`` performs before any
        request: a name, fields, and exactly one key field."""
        name = definition.get("name")
        fields = definition.get("fields")
        if not name or not isinstance(fields, list) or not fields:
            raise ValueError("index definition requires 'name' and 'fields'")
        keys = [f for f in fields if f.get("key")]
        if len(keys) != 1:
            raise ValueError(
                f"index definition must have exactly one key field (got {len(keys)})"
            )
        return name

    def create_index(self, definition: Dict[str, Any]) -> Dict[str, Any]:
        """PUT the index definition (idempotent create-or-update)."""
        name = self._validate(definition)
        resp = self.client.send(
            HTTPRequestData(
                url=f"{self.service_url}/indexes/{name}",
                method="PUT",
                headers=self._headers(),
                entity=EntityData(
                    content=json.dumps(definition).encode("utf-8"),
                    contentType="application/json",
                ),
            )
        )
        if resp.status_code not in (200, 201, 204):
            raise RuntimeError(
                f"index creation failed: HTTP {resp.status_code} {resp.text()[:200]}"
            )
        # 204 No Content (the standard update response) has an empty body
        if resp.entity is None or not resp.entity.content:
            return {}
        return resp.json() or {}

    def ensure_index(self, definition: Dict[str, Any]) -> bool:
        """Create the index unless it already exists. Returns True when it
        was created. Validates the definition up front so a malformed one
        errors instead of silently reporting 'already exists'."""
        name = self._validate(definition)
        if self.index_exists(name):
            return False
        self.create_index(definition)
        return True
