"""Typed response schemas for cognitive services.

The reference gives every service a ``SparkBindings`` case-class response
schema (~3.8k LoC across ``cognitive/*.scala``) so downstream pipeline
stages see typed columns rather than raw JSON. Python-native equivalent:
light dataclasses with tolerant ``from_json`` constructors (unknown keys
ignored, missing keys default) — services parse payloads into these when
``typed=True``.
"""

# NOTE: no `from __future__ import annotations` — _build dispatches on the
# REAL field types (get_origin/is_dataclass); stringified annotations would
# silently disable nested parsing.
import dataclasses
from typing import (
    Any,
    Dict,
    List,
    Optional,
    Type,
    TypeVar,
    Union,
    get_args,
    get_origin,
)

T = TypeVar("T")


def _build(cls: Type[T], data: Any) -> Any:
    """Tolerantly construct a dataclass tree from parsed JSON."""
    if data is None or not dataclasses.is_dataclass(cls):
        return data
    if not isinstance(data, dict):
        return data
    kwargs = {}
    for f in dataclasses.fields(cls):
        if f.name not in data:
            continue
        v = data[f.name]
        t = f.type
        origin = get_origin(t)
        if origin is list and v is not None:
            (elem,) = get_args(t)
            kwargs[f.name] = [_build(elem, x) for x in v]
        elif origin is None and dataclasses.is_dataclass(t):
            kwargs[f.name] = _build(t, v)
        elif origin is Union:  # Optional[X] normalizes to Union[X, None]
            inner = [a for a in get_args(t) if a is not type(None)]
            if inner and dataclasses.is_dataclass(inner[0]) and isinstance(v, dict):
                kwargs[f.name] = _build(inner[0], v)
            else:
                kwargs[f.name] = v
        else:
            kwargs[f.name] = v
    return cls(**kwargs)


class ResponseSchema:
    """Mixin: ``from_json`` tolerant constructor."""

    @classmethod
    def from_json(cls, data: Optional[Dict[str, Any]]):
        return _build(cls, data)


# -- text analytics (TextAnalytics.scala bindings) ---------------------------


@dataclasses.dataclass
class TADocument(ResponseSchema):
    id: Optional[str] = None
    score: Optional[float] = None
    sentiment: Optional[str] = None
    keyPhrases: Optional[list] = None
    entities: Optional[list] = None
    detectedLanguages: Optional[list] = None


@dataclasses.dataclass
class TAError(ResponseSchema):
    id: Optional[str] = None
    message: Optional[str] = None


@dataclasses.dataclass
class TAResponse(ResponseSchema):
    documents: List[TADocument] = dataclasses.field(default_factory=list)
    errors: List[TAError] = dataclasses.field(default_factory=list)


# -- computer vision (ComputerVision.scala bindings) -------------------------


@dataclasses.dataclass
class OCRWord(ResponseSchema):
    boundingBox: Optional[str] = None
    text: Optional[str] = None


@dataclasses.dataclass
class OCRLine(ResponseSchema):
    boundingBox: Optional[str] = None
    words: List[OCRWord] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class OCRRegion(ResponseSchema):
    boundingBox: Optional[str] = None
    lines: List[OCRLine] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class OCRResponse(ResponseSchema):
    language: Optional[str] = None
    orientation: Optional[str] = None
    textAngle: Optional[float] = None
    regions: List[OCRRegion] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class RTLine(ResponseSchema):
    boundingBox: Optional[list] = None
    text: Optional[str] = None


@dataclasses.dataclass
class RTResult(ResponseSchema):
    lines: List[RTLine] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class RecognizeTextResponse(ResponseSchema):
    status: Optional[str] = None
    recognitionResult: Optional[RTResult] = None


@dataclasses.dataclass
class ImageTag(ResponseSchema):
    name: Optional[str] = None
    confidence: Optional[float] = None


@dataclasses.dataclass
class ImageCaption(ResponseSchema):
    text: Optional[str] = None
    confidence: Optional[float] = None


@dataclasses.dataclass
class ImageDescription(ResponseSchema):
    tags: Optional[list] = None
    captions: List[ImageCaption] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class AnalyzeImageResponse(ResponseSchema):
    categories: Optional[list] = None
    tags: List[ImageTag] = dataclasses.field(default_factory=list)
    description: Optional[ImageDescription] = None
    requestId: Optional[str] = None


@dataclasses.dataclass
class DescribeImageResponse(ResponseSchema):
    description: Optional[ImageDescription] = None
    requestId: Optional[str] = None


@dataclasses.dataclass
class TagImageResponse(ResponseSchema):
    tags: List[ImageTag] = dataclasses.field(default_factory=list)
    requestId: Optional[str] = None


# -- face (Face.scala bindings) ----------------------------------------------


@dataclasses.dataclass
class FaceRectangle(ResponseSchema):
    top: Optional[int] = None
    left: Optional[int] = None
    width: Optional[int] = None
    height: Optional[int] = None


@dataclasses.dataclass
class DetectedFace(ResponseSchema):
    faceId: Optional[str] = None
    faceRectangle: Optional[FaceRectangle] = None
    faceAttributes: Optional[dict] = None


@dataclasses.dataclass
class FaceListResponse(ResponseSchema):
    faces: List[DetectedFace] = dataclasses.field(default_factory=list)

    @classmethod
    def from_json(cls, data):
        # face detect returns a bare JSON array
        if isinstance(data, list):
            return cls(faces=[_build(DetectedFace, d) for d in data])
        return _build(cls, data)


@dataclasses.dataclass
class IdentifyCandidate(ResponseSchema):
    personId: Optional[str] = None
    confidence: Optional[float] = None


@dataclasses.dataclass
class IdentifyResult(ResponseSchema):
    faceId: Optional[str] = None
    candidates: List[IdentifyCandidate] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class IdentifyResponse(ResponseSchema):
    results: List[IdentifyResult] = dataclasses.field(default_factory=list)

    @classmethod
    def from_json(cls, data):
        if isinstance(data, list):
            return cls(results=[_build(IdentifyResult, d) for d in data])
        return _build(cls, data)


@dataclasses.dataclass
class GroupResponse(ResponseSchema):
    groups: List[list] = dataclasses.field(default_factory=list)
    messyGroup: Optional[list] = None


@dataclasses.dataclass
class VerifyResponse(ResponseSchema):
    isIdentical: Optional[bool] = None
    confidence: Optional[float] = None


# -- anomaly detection (AnamolyDetection.scala bindings) ---------------------


@dataclasses.dataclass
class AnomalyResponse(ResponseSchema):
    expectedValues: Optional[list] = None
    isAnomaly: Optional[list] = None
    isPositiveAnomaly: Optional[list] = None
    isNegativeAnomaly: Optional[list] = None
    upperMargins: Optional[list] = None
    lowerMargins: Optional[list] = None
    period: Optional[int] = None


# -- speech ------------------------------------------------------------------


@dataclasses.dataclass
class SpeechResponse(ResponseSchema):
    RecognitionStatus: Optional[str] = None
    DisplayText: Optional[str] = None
    Offset: Optional[int] = None
    Duration: Optional[int] = None
