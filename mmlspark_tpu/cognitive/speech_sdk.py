"""SpeechToTextSDK — streaming speech recognition transport.

Re-design of the reference's ``cognitive/SpeechToTextSDK.scala:66-249``:
where the reference wraps the native Speech SDK (a host-side C library
pumping a ``PullAudioInputStreamCallback`` over a websocket), this runtime
streams the same pull-stream frames over HTTP **chunked transfer
encoding** — audio never materializes in one request buffer, the server
sees frames as they are produced, and the response is the event list the
SDK's recognizing/recognized callbacks would deliver (one event per
utterance; ``streamIntermediateResults`` keeps the intermediate
"recognizing" events in the output, matching the reference's param of the
same name).

WAV validation (PCM mono 16 kHz 16-bit) and compressed passthrough live in
:mod:`mmlspark_tpu.cognitive.audio` (``AudioStreams.scala`` analogue).
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional
from urllib.parse import urlencode, urlsplit

import numpy as np

from mmlspark_tpu.cognitive.audio import make_audio_stream
from mmlspark_tpu.cognitive.base import ServiceParam, _HasServiceParams
from mmlspark_tpu.core.params import Param, to_bool, to_int, to_str
from mmlspark_tpu.core.pipeline import Transformer
from mmlspark_tpu.core.params import HasOutputCol
from mmlspark_tpu.data.table import Table


class SpeechToTextSDK(_HasServiceParams, HasOutputCol, Transformer):
    """Streams audio columns to a speech endpoint in pull-stream frames."""

    subscriptionKey = ServiceParam("API key (value or column)")
    url = Param("Service endpoint URL", default=None)
    errorCol = Param("Error column", default=None)
    audioDataCol = Param("Column of audio bytes", default="audio", converter=to_str)
    fileType = ServiceParam("wav|mp3|ogg", default=("value", "wav"))
    language = ServiceParam("Recognition language", is_url_param=True,
                            default=("value", "en-US"))
    format = ServiceParam("simple|detailed", is_url_param=True)
    profanity = ServiceParam("masked|raw|removed", is_url_param=True)
    endpointId = Param("Custom speech model endpoint id", default=None)
    streamIntermediateResults = Param(
        "Keep intermediate 'recognizing' events in the output (final "
        "'recognized' events only when False)",
        default=True, converter=to_bool,
    )
    chunkSize = Param(
        "Streaming frame size in bytes (default 3200 = 100ms of 16kHz PCM)",
        default=3200, converter=to_int,
    )

    def __init__(self, **kwargs):
        for key in ("subscriptionKey", "fileType", "language", "format", "profanity"):
            if key in kwargs and isinstance(kwargs[key], str):
                kwargs[key] = ("value", kwargs[key])
        super().__init__(**kwargs)

    # -- transport ---------------------------------------------------------

    def _stream_one(self, audio: bytes, table: Table, row: int) -> List[Dict[str, Any]]:
        import http.client

        url = self.getUrl()
        if not url:
            raise ValueError("SpeechToTextSDK requires url")
        # URL params come from the is_url_param flag on the declarations —
        # the same contract CognitiveServicesBase uses — so a new param
        # can't be silently dropped by a hand-kept list.
        params = {}
        for name, spec in type(self)._param_specs.items():
            if isinstance(spec, ServiceParam) and spec.is_url_param:
                v = self._resolve_service_param(name, table, row)
                if v is not None:
                    params[name] = v
        if self.getEndpointId():
            params["cid"] = self.getEndpointId()
        parts = urlsplit(url)
        path = parts.path or "/"
        query = "&".join(q for q in (parts.query, urlencode(params)) if q)
        if query:
            path = f"{path}?{query}"

        file_type = self._resolve_service_param("fileType", table, row) or "wav"
        stream = make_audio_stream(audio, file_type, chunk_size=self.getChunkSize())

        conn_cls = (
            http.client.HTTPSConnection if parts.scheme == "https"
            else http.client.HTTPConnection
        )
        conn = conn_cls(parts.netloc, timeout=60)
        try:
            headers = {
                "Content-Type": f"audio/{file_type}; codecs=audio/pcm; samplerate=16000",
                "Transfer-Encoding": "chunked",
                "Accept": "application/json",
            }
            key = self._resolve_service_param("subscriptionKey", table, row)
            if key:
                headers["Ocp-Apim-Subscription-Key"] = key
            # chunked upload straight from the pull stream: http.client
            # frames each yielded block as one transfer chunk
            conn.request("POST", path, body=stream.frames(),
                         headers=headers, encode_chunked=True)
            resp = conn.getresponse()
            raw = resp.read()
            if resp.status >= 400:
                raise RuntimeError(f"speech endpoint {resp.status}: {raw[:200]!r}")
            events = json.loads(raw)
        finally:
            stream.close()
            conn.close()
        if isinstance(events, dict):  # single-utterance (REST-shaped) reply
            events = [events]
        if not self.getStreamIntermediateResults():
            events = [
                e for e in events
                if e.get("RecognitionStatus", "Success") != "Recognizing"
            ]
        return events

    def transform(self, table: Table) -> Table:
        col = table.column(self.getAudioDataCol())
        out = np.empty(table.num_rows, dtype=object)
        errors: Optional[np.ndarray] = (
            np.empty(table.num_rows, dtype=object) if self.getErrorCol() else None
        )
        for i in range(table.num_rows):
            audio = col[i]
            if isinstance(audio, str):
                import base64

                audio = base64.b64decode(audio)
            try:
                out[i] = self._stream_one(bytes(audio), table, i)
                if errors is not None:
                    errors[i] = None
            except Exception as e:  # noqa: BLE001 — per-row error column contract
                if errors is None:
                    raise
                out[i] = None
                errors[i] = f"{type(e).__name__}: {e}"
        result = table.with_column(self.getOutputCol(), out)
        if errors is not None:
            result = result.with_column(self.getErrorCol(), errors)
        return result
