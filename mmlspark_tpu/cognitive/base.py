"""Cognitive-service base machinery.

Reference: ``cognitive/CognitiveServiceBase.scala`` — ``ServiceParam``
(value-or-column Either params, ``:29-151``) and ``CognitiveServicesBase``
whose internal pipeline is Lambda(struct of dynamic cols) →
SimpleHTTPTransformer → DropColumns (``:282-308``), with URL params and the
subscription-key header (``:321+``).
"""

from __future__ import annotations

import json
from typing import Any, Callable, Dict, List, Optional
from urllib.parse import urlencode

import numpy as np

from mmlspark_tpu.core.params import HasOutputCol, Param, to_int, to_str
from mmlspark_tpu.core.pipeline import Transformer
from mmlspark_tpu.data.table import Table
from mmlspark_tpu.io.http.schema import EntityData, HeaderData, HTTPRequestData
from mmlspark_tpu.io.http.transformers import (
    CustomInputParser,
    CustomOutputParser,
    SimpleHTTPTransformer,
)


class _ParseError(str):
    """Sentinel carrying a post-parse failure message to the error column."""


class _ConcurrentOutputParser(CustomOutputParser):
    """CustomOutputParser that maps the udf over rows with a bounded thread
    pool — async-polling services would otherwise serialize their poll
    loops row by row, defeating the concurrency param."""

    workers = Param("Thread-pool width", default=4, converter=to_int)

    def transform(self, table: Table) -> Table:
        from concurrent.futures import ThreadPoolExecutor

        col = table.column(self.getInputCol())
        udf = self.getUdf()
        with ThreadPoolExecutor(max_workers=max(1, self.getWorkers())) as pool:
            out_list = list(pool.map(udf, col))
        out = np.empty(len(col), dtype=object)
        for i, v in enumerate(out_list):
            out[i] = v
        return table.with_column(self.getOutputCol(), out)


class ServiceParam(Param):
    """A param that holds either a constant value or a column name
    (``ServiceParam`` Left/Right, ``CognitiveServiceBase.scala:29-151``).
    Stored as ``("value", v)`` or ``("col", name)`` tuples."""

    def __init__(self, doc: str = "", default: Any = None, is_url_param: bool = False):
        super().__init__(doc=doc, default=default)
        self.is_url_param = is_url_param


class _HasServiceParams:
    """Mixin providing setX/setXCol accessors for ServiceParams."""

    def set_scalar(self, name: str, value: Any):
        return self.set(name, ("value", value))

    def set_vector(self, name: str, col: str):
        return self.set(name, ("col", col))

    def _resolve_service_param(self, name: str, table: Table, row: int) -> Any:
        v = self.getOrDefault(name)
        if v is None:
            return None
        kind, payload = v
        if kind == "value":
            return payload
        cell = table.column(payload)[row]
        return cell.tolist() if isinstance(cell, np.ndarray) else cell


class CognitiveServicesBase(_HasServiceParams, HasOutputCol, Transformer):
    """Base REST transformer. Subclasses define ``urlPath``, declare
    ServiceParams, and implement ``prepare_entity`` (row dict -> JSON body)
    — the ``CognitiveServicesBase.prepareEntity`` hook.

    ``typed=True`` parses payloads into the subclass's ``response_schema``
    dataclass (the SparkBindings analogue); subclasses with
    ``polling = True`` follow the async Operation-Location flow
    (``ComputerVision.scala`` recognizeText: 202 → poll the returned
    location until the operation reports a terminal status)."""

    subscriptionKey = ServiceParam("API key (value or column)")
    url = Param("Service base URL", default=None)
    errorCol = Param("Error column", default=None)
    concurrency = Param("Max in-flight requests", default=4, converter=to_int)
    typed = Param("Parse responses into the typed schema", default=False)
    pollingIntervalMs = Param("Async poll interval", default=50, converter=to_int)
    maxPollingRetries = Param("Async poll attempts", default=40, converter=to_int)
    pollingDeadlineMs = Param(
        "Overall wall-clock budget for one async operation's poll loop; the "
        "retry count alone let Retry-After hints stretch the wait unboundedly",
        default=60_000, converter=to_int,
    )

    response_schema = None  # ResponseSchema subclass, set per service
    polling = False  # async Operation-Location flow

    _key_header = "Ocp-Apim-Subscription-Key"

    def __init__(self, **kwargs):
        # plain-string conveniences: subscriptionKey="k" means a constant
        for name in list(kwargs):
            param = getattr(type(self), name, None)
            if isinstance(param, ServiceParam) and not (
                isinstance(kwargs[name], tuple) and len(kwargs[name]) == 2
                and kwargs[name][0] in ("value", "col")
            ):
                kwargs[name] = ("value", kwargs[name])
        super().__init__(**kwargs)

    # -- subclass hooks ------------------------------------------------------

    def url_params(self, table: Table, row: int) -> Dict[str, str]:
        out = {}
        for name, p in self.params.items():
            if isinstance(p, ServiceParam) and p.is_url_param:
                v = self._resolve_service_param(name, table, row)
                if v is not None:
                    out[name] = str(v)
        return out

    def prepare_entity(self, table: Table, row: int) -> Optional[Dict[str, Any]]:
        raise NotImplementedError

    def prepare_method(self) -> str:
        return "POST"

    # -- request assembly ----------------------------------------------------

    def _make_request(self, table: Table):
        def build(row_and_table):
            table, row = row_and_table
            body = self.prepare_entity(table, row)
            params = self.url_params(table, row)
            url = self.getUrl()
            if params:
                url = f"{url}?{urlencode(params)}"
            headers = {"Content-Type": "application/json"}
            key = self._resolve_service_param("subscriptionKey", table, row)
            if key:
                headers[self._key_header] = key
            entity = None
            if body is not None:
                entity = EntityData(
                    content=json.dumps(body).encode("utf-8"),
                    contentType="application/json",
                )
            return HTTPRequestData(
                url=url,
                method=self.prepare_method(),
                headers=[HeaderData(k, v) for k, v in headers.items()],
                entity=entity,
            )

        return build

    # -- async polling (ComputerVision.scala recognize-text flow) ------------

    def _poll(self, resp, key: Optional[str], clock=None, sleep=None):
        """Follow the Operation-Location header until a terminal status —
        the reference's async flow where the initial 202 carries only the
        polling URL and the result arrives from subsequent GETs.

        Two budgets bound the loop: ``maxPollingRetries`` (attempt count)
        and ``pollingDeadlineMs`` (wall clock) — a tighter ambient
        :func:`~mmlspark_tpu.resilience.budget.current_deadline` wins over
        the param. A poll answering 429/503 with ``Retry-After`` stretches
        that one interval to the hint (clipped to the deadline) instead of
        hammering a throttling service. ``clock``/``sleep`` are injectable
        for zero-sleep tests."""
        import time as _time

        from mmlspark_tpu.io.http.clients import HTTPClient
        from mmlspark_tpu.resilience.budget import Deadline, current_deadline
        from mmlspark_tpu.resilience.policy import parse_retry_after

        clock = clock or _time.monotonic
        sleep = sleep or _time.sleep
        # header names are case-insensitive on the wire (h2 hops lowercase)
        headers_ci = {k.lower(): v for k, v in resp.header_map().items()}
        loc = headers_ci.get("operation-location")
        if not loc:
            raise ValueError("202 response without Operation-Location header")
        headers = [HeaderData(self._key_header, key)] if key else []
        client = HTTPClient()
        interval = self.getPollingIntervalMs() / 1000.0
        deadline = Deadline.after(self.getPollingDeadlineMs() / 1000.0, clock=clock)
        ambient = current_deadline()
        payload = None
        polls = 0
        for _ in range(self.getMaxPollingRetries()):
            wait = interval
            if polls:  # a Retry-After hint governs the NEXT poll's wait
                hint = parse_retry_after(
                    {k.lower(): v for k, v in poll.header_map().items()}
                    .get("retry-after")
                ) if poll.status_code in (429, 503) else None
                if hint is not None:
                    wait = max(wait, hint)
            wait = min(wait, max(0.0, deadline.remaining()))
            if ambient is not None:
                wait = min(wait, max(0.0, ambient.remaining()))
            sleep(wait)
            if deadline.expired or (ambient is not None and ambient.expired):
                raise TimeoutError(
                    f"{type(self).__name__}: async operation at {loc} exceeded "
                    f"its {self.getPollingDeadlineMs()} ms polling deadline "
                    f"after {polls} polls (last: {payload!r})"
                )
            poll = client.send(HTTPRequestData(url=loc, method="GET", headers=headers))
            polls += 1
            payload = poll.json()
            status = (payload or {}).get("status", "")
            if str(status).lower() in ("succeeded", "failed"):
                return payload
        raise TimeoutError(
            f"{type(self).__name__}: async operation at {loc} did not reach a "
            f"terminal status in {self.getMaxPollingRetries()} polls "
            f"(last: {payload!r})"
        )

    def _make_response_parser(self):
        schema = type(self).response_schema
        needs_key = type(self).polling
        key = None
        if needs_key:
            kv = self.getOrDefault("subscriptionKey")
            if kv is not None and kv[0] == "col":
                raise ValueError(
                    "async polling services require a constant subscriptionKey "
                    "(column-bound keys cannot be threaded into poll requests)"
                )
            key = kv[1] if kv is not None else None

        def parse(resp):
            if resp is None:
                return None
            try:
                if type(self).polling and resp.status_code == 202:
                    payload = self._poll(resp, key)
                else:
                    payload = resp.json()
                if self.getTyped() and schema is not None:
                    return schema.from_json(payload)
                return payload
            except Exception as e:  # noqa: BLE001 — error-row semantics:
                # polling timeout / malformed payload become a structured
                # _ParseError row carrying the message, not a lost failure
                return _ParseError(f"{type(e).__name__}: {e}")

        return parse

    def transform(self, table: Table) -> Table:
        from mmlspark_tpu.data.table import find_unused_column_name

        if self.getUrl() is None:
            raise ValueError(f"{type(self).__name__} requires url")
        idx_col = find_unused_column_name("_row", table)
        indexed = table.with_column(idx_col, np.arange(table.num_rows))
        build = self._make_request(table)
        inner = SimpleHTTPTransformer(
            inputCol=idx_col,
            outputCol=self.getOutputCol(),
            errorCol=self.getErrorCol(),
            concurrency=self.getConcurrency(),
            inputParser=CustomInputParser(udf=lambda row: build((table, int(row)))),
            outputParser=_ConcurrentOutputParser(
                udf=self._make_response_parser(),
                workers=self.getConcurrency(),
            ),
        )
        result = inner.transform(indexed).drop(idx_col)
        # Post-parse failures (polling timeouts etc.) route to the error
        # column like transport failures do; without an errorCol they raise.
        out_col = result.column(self.getOutputCol())
        if any(isinstance(v, _ParseError) for v in out_col):
            err_name = self.getErrorCol()
            if err_name is None:
                first = next(v for v in out_col if isinstance(v, _ParseError))
                raise RuntimeError(str(first))
            errors = result.column(err_name)
            new_out = np.empty(len(out_col), dtype=object)
            new_err = np.empty(len(out_col), dtype=object)
            for i, v in enumerate(out_col):
                if isinstance(v, _ParseError):
                    new_out[i] = None
                    new_err[i] = str(v)
                else:
                    new_out[i] = v
                    new_err[i] = errors[i]
            result = result.with_column(self.getOutputCol(), new_out)
            result = result.with_column(err_name, new_err)
        return result
