"""Cognitive-service base machinery.

Reference: ``cognitive/CognitiveServiceBase.scala`` — ``ServiceParam``
(value-or-column Either params, ``:29-151``) and ``CognitiveServicesBase``
whose internal pipeline is Lambda(struct of dynamic cols) →
SimpleHTTPTransformer → DropColumns (``:282-308``), with URL params and the
subscription-key header (``:321+``).
"""

from __future__ import annotations

import json
from typing import Any, Callable, Dict, List, Optional
from urllib.parse import urlencode

import numpy as np

from mmlspark_tpu.core.params import HasOutputCol, Param, to_int, to_str
from mmlspark_tpu.core.pipeline import Transformer
from mmlspark_tpu.data.table import Table
from mmlspark_tpu.io.http.schema import EntityData, HeaderData, HTTPRequestData
from mmlspark_tpu.io.http.transformers import (
    CustomInputParser,
    JSONOutputParser,
    SimpleHTTPTransformer,
)


class ServiceParam(Param):
    """A param that holds either a constant value or a column name
    (``ServiceParam`` Left/Right, ``CognitiveServiceBase.scala:29-151``).
    Stored as ``("value", v)`` or ``("col", name)`` tuples."""

    def __init__(self, doc: str = "", default: Any = None, is_url_param: bool = False):
        super().__init__(doc=doc, default=default)
        self.is_url_param = is_url_param


class _HasServiceParams:
    """Mixin providing setX/setXCol accessors for ServiceParams."""

    def set_scalar(self, name: str, value: Any):
        return self.set(name, ("value", value))

    def set_vector(self, name: str, col: str):
        return self.set(name, ("col", col))

    def _resolve_service_param(self, name: str, table: Table, row: int) -> Any:
        v = self.getOrDefault(name)
        if v is None:
            return None
        kind, payload = v
        if kind == "value":
            return payload
        cell = table.column(payload)[row]
        return cell.tolist() if isinstance(cell, np.ndarray) else cell


class CognitiveServicesBase(_HasServiceParams, HasOutputCol, Transformer):
    """Base REST transformer. Subclasses define ``urlPath``, declare
    ServiceParams, and implement ``prepare_entity`` (row dict -> JSON body)
    — the ``CognitiveServicesBase.prepareEntity`` hook."""

    subscriptionKey = ServiceParam("API key (value or column)")
    url = Param("Service base URL", default=None)
    errorCol = Param("Error column", default=None)
    concurrency = Param("Max in-flight requests", default=4, converter=to_int)

    _key_header = "Ocp-Apim-Subscription-Key"

    def __init__(self, **kwargs):
        # plain-string conveniences: subscriptionKey="k" means a constant
        for name in list(kwargs):
            param = getattr(type(self), name, None)
            if isinstance(param, ServiceParam) and not (
                isinstance(kwargs[name], tuple) and len(kwargs[name]) == 2
                and kwargs[name][0] in ("value", "col")
            ):
                kwargs[name] = ("value", kwargs[name])
        super().__init__(**kwargs)

    # -- subclass hooks ------------------------------------------------------

    def url_params(self, table: Table, row: int) -> Dict[str, str]:
        out = {}
        for name, p in self.params.items():
            if isinstance(p, ServiceParam) and p.is_url_param:
                v = self._resolve_service_param(name, table, row)
                if v is not None:
                    out[name] = str(v)
        return out

    def prepare_entity(self, table: Table, row: int) -> Optional[Dict[str, Any]]:
        raise NotImplementedError

    def prepare_method(self) -> str:
        return "POST"

    # -- request assembly ----------------------------------------------------

    def _make_request(self, table: Table):
        def build(row_and_table):
            table, row = row_and_table
            body = self.prepare_entity(table, row)
            params = self.url_params(table, row)
            url = self.getUrl()
            if params:
                url = f"{url}?{urlencode(params)}"
            headers = {"Content-Type": "application/json"}
            key = self._resolve_service_param("subscriptionKey", table, row)
            if key:
                headers[self._key_header] = key
            entity = None
            if body is not None:
                entity = EntityData(
                    content=json.dumps(body).encode("utf-8"),
                    contentType="application/json",
                )
            return HTTPRequestData(
                url=url,
                method=self.prepare_method(),
                headers=[HeaderData(k, v) for k, v in headers.items()],
                entity=entity,
            )

        return build

    def transform(self, table: Table) -> Table:
        from mmlspark_tpu.data.table import find_unused_column_name

        if self.getUrl() is None:
            raise ValueError(f"{type(self).__name__} requires url")
        idx_col = find_unused_column_name("_row", table)
        indexed = table.with_column(idx_col, np.arange(table.num_rows))
        build = self._make_request(table)
        inner = SimpleHTTPTransformer(
            inputCol=idx_col,
            outputCol=self.getOutputCol(),
            errorCol=self.getErrorCol(),
            concurrency=self.getConcurrency(),
            inputParser=CustomInputParser(udf=lambda row: build((table, int(row)))),
            outputParser=JSONOutputParser(),
        )
        return inner.transform(indexed).drop(idx_col)
