"""Cognitive services on Table (reference ``cognitive/``, SURVEY.md §2.17)."""

from mmlspark_tpu.cognitive.base import CognitiveServicesBase, ServiceParam
from mmlspark_tpu.cognitive.search import AddDocuments
from mmlspark_tpu.cognitive.services import (
    NER,
    OCR,
    AnalyzeImage,
    BingImageSearch,
    DetectAnomalies,
    DetectFace,
    EntityDetector,
    FindSimilarFace,
    GenerateThumbnails,
    KeyPhraseExtractor,
    LanguageDetector,
    RecognizeText,
    SpeechToText,
    TextSentiment,
)

__all__ = [
    "AddDocuments",
    "AnalyzeImage",
    "BingImageSearch",
    "CognitiveServicesBase",
    "DetectAnomalies",
    "DetectFace",
    "EntityDetector",
    "FindSimilarFace",
    "GenerateThumbnails",
    "KeyPhraseExtractor",
    "LanguageDetector",
    "NER",
    "OCR",
    "RecognizeText",
    "ServiceParam",
    "SpeechToText",
    "TextSentiment",
]
