"""Cognitive services on Table (reference ``cognitive/``, SURVEY.md §2.17)."""

from mmlspark_tpu.cognitive import schemas
from mmlspark_tpu.cognitive.audio import CompressedStream, WavStream
from mmlspark_tpu.cognitive.base import CognitiveServicesBase, ServiceParam
from mmlspark_tpu.cognitive.search import AddDocuments, SearchIndexClient
from mmlspark_tpu.cognitive.speech_sdk import SpeechToTextSDK
from mmlspark_tpu.cognitive.services import (
    NER,
    OCR,
    AnalyzeImage,
    BingImageSearch,
    DescribeImage,
    DetectAnomalies,
    DetectFace,
    EntityDetector,
    FindSimilarFace,
    GenerateThumbnails,
    GroupFaces,
    IdentifyFaces,
    KeyPhraseExtractor,
    LanguageDetector,
    RecognizeText,
    SpeechToText,
    TagImage,
    TextSentiment,
    VerifyFaces,
)

__all__ = [
    "AddDocuments",
    "DescribeImage",
    "GroupFaces",
    "IdentifyFaces",
    "SearchIndexClient",
    "TagImage",
    "VerifyFaces",
    "schemas",
    "AnalyzeImage",
    "BingImageSearch",
    "CognitiveServicesBase",
    "DetectAnomalies",
    "DetectFace",
    "EntityDetector",
    "FindSimilarFace",
    "GenerateThumbnails",
    "KeyPhraseExtractor",
    "LanguageDetector",
    "NER",
    "OCR",
    "RecognizeText",
    "ServiceParam",
    "SpeechToText",
    "SpeechToTextSDK",
    "CompressedStream",
    "WavStream",
    "TextSentiment",
]
