"""Training objectives and evaluation metrics.

Mirrors the objective set accepted by the reference's param surface
(``lightgbm/LightGBMParams.scala``, ``lightgbm/TrainParams.scala``:
binary, multiclass, regression/l2, l1, huber, quantile, poisson, tweedie)
with gradients/hessians as jitted closed forms. Eval-metric direction
handling (auc/ndcg/map maximize, losses minimize) matches
``TrainUtils.scala:276-308`` early-stopping semantics.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass(frozen=True)
class Objective:
    name: str
    num_outputs_fn: Callable[[int], int]  # num_classes -> margin columns
    # (margins (N,C), y (N,), w (N,)) -> grad (N,C), hess (N,C)
    grad_hess: Callable[..., Tuple[jax.Array, jax.Array]]
    # (y, num_classes, w) -> init margin (C,)
    init_score: Callable[..., np.ndarray]
    default_metric: str
    # Distinguishes data-specific objective INSTANCES sharing a name in the
    # jitted-program cache (train._PROGRAM_CACHE keys on this): the registry
    # singletons use None; per-fit objectives (lambdarank closes over the
    # query-group structure) must carry a unique token or a later fit with
    # identical TrainOptions silently reuses the first fit's closure.
    cache_token: Any = None


def _sigmoid(x):
    return jax.nn.sigmoid(x)


# -- binary ------------------------------------------------------------------

def _binary_grad_hess(margins, y, w, **kw):
    p = _sigmoid(margins[:, 0])
    g = (p - y) * w
    h = jnp.maximum(p * (1.0 - p), 1e-16) * w
    return g[:, None], h[:, None]


def _binary_init(y, num_classes, w):
    pos = float(np.sum(y * w))
    neg = float(np.sum(w)) - pos
    pos, neg = max(pos, 1e-12), max(neg, 1e-12)
    return np.array([np.log(pos / neg)], dtype=np.float32)


# -- multiclass softmax ------------------------------------------------------

def _multiclass_grad_hess(margins, y, w, num_classes=2, **kw):
    p = jax.nn.softmax(margins, axis=-1)  # (N, C)
    onehot = jax.nn.one_hot(y.astype(jnp.int32), num_classes)
    g = (p - onehot) * w[:, None]
    h = jnp.maximum(2.0 * p * (1.0 - p), 1e-16) * w[:, None]
    return g, h


def _multiclass_init(y, num_classes, w):
    counts = np.array(
        [np.sum(w[np.asarray(y) == c]) for c in range(num_classes)], dtype=np.float64
    )
    probs = np.maximum(counts / max(counts.sum(), 1e-12), 1e-12)
    return np.log(probs).astype(np.float32)


# -- regression family -------------------------------------------------------

def _l2_grad_hess(margins, y, w, **kw):
    g = (margins[:, 0] - y) * w
    return g[:, None], w[:, None] * jnp.ones_like(g)[:, None]


def _l2_init(y, num_classes, w):
    return np.array([np.average(y, weights=w)], dtype=np.float32)


def _l1_grad_hess(margins, y, w, **kw):
    g = jnp.sign(margins[:, 0] - y) * w
    return g[:, None], w[:, None] * jnp.ones_like(g)[:, None]


def _huber_grad_hess(margins, y, w, alpha=0.9, **kw):
    d = margins[:, 0] - y
    g = jnp.clip(d, -alpha, alpha) * w
    return g[:, None], w[:, None] * jnp.ones_like(g)[:, None]


def _quantile_grad_hess(margins, y, w, alpha=0.9, **kw):
    d = margins[:, 0] - y
    g = jnp.where(d >= 0, 1.0 - alpha, -alpha) * w
    return g[:, None], w[:, None] * jnp.ones_like(g)[:, None]


def _poisson_grad_hess(margins, y, w, **kw):
    mu = jnp.exp(margins[:, 0])
    g = (mu - y) * w
    h = jnp.maximum(mu, 1e-16) * w
    return g[:, None], h[:, None]


def _poisson_init(y, num_classes, w):
    return np.array([np.log(max(np.average(y, weights=w), 1e-12))], dtype=np.float32)


def _tweedie_grad_hess(margins, y, w, tweedie_variance_power=1.5, **kw):
    rho = tweedie_variance_power
    m = margins[:, 0]
    a = y * jnp.exp((1.0 - rho) * m)
    b = jnp.exp((2.0 - rho) * m)
    g = (-a + b) * w
    h = jnp.maximum(-a * (1.0 - rho) + b * (2.0 - rho), 1e-16) * w
    return g[:, None], h[:, None]


OBJECTIVES: Dict[str, Objective] = {
    "binary": Objective("binary", lambda c: 1, _binary_grad_hess, _binary_init, "auc"),
    "multiclass": Objective(
        "multiclass", lambda c: c, _multiclass_grad_hess, _multiclass_init, "multi_logloss"
    ),
    "regression": Objective("regression", lambda c: 1, _l2_grad_hess, _l2_init, "l2"),
    "regression_l1": Objective("regression_l1", lambda c: 1, _l1_grad_hess, _l2_init, "l1"),
    "huber": Objective("huber", lambda c: 1, _huber_grad_hess, _l2_init, "l2"),
    "quantile": Objective("quantile", lambda c: 1, _quantile_grad_hess, _l2_init, "quantile"),
    "poisson": Objective("poisson", lambda c: 1, _poisson_grad_hess, _poisson_init, "poisson"),
    "tweedie": Objective("tweedie", lambda c: 1, _tweedie_grad_hess, _poisson_init, "tweedie"),
}

# LightGBM objective aliases (TrainParams.scala objective strings).
_ALIASES = {"l2": "regression", "mean_squared_error": "regression", "mse": "regression",
            "l1": "regression_l1", "mae": "regression_l1", "lambdarank": "lambdarank"}


def get_objective(name: str) -> Objective:
    name = _ALIASES.get(name, name)
    if name not in OBJECTIVES:
        raise ValueError(f"unknown objective {name!r}; known: {sorted(OBJECTIVES)}")
    return OBJECTIVES[name]


# ---------------------------------------------------------------------------
# Metrics (host-side numpy; validation sets are small relative to train)
# ---------------------------------------------------------------------------

def auc(y: np.ndarray, score: np.ndarray, w: np.ndarray) -> float:
    order = np.argsort(score, kind="stable")
    y, w = np.asarray(y, dtype=np.float64)[order], np.asarray(w, dtype=np.float64)[order]
    pos_w = y * w
    neg_w = (1.0 - y) * w
    cum_neg = np.cumsum(neg_w)
    total_pos, total_neg = pos_w.sum(), neg_w.sum()
    if total_pos == 0 or total_neg == 0:
        return 0.5
    # rank-sum with tie correction via averaging over equal-score groups
    auc_sum = 0.0
    i = 0
    n = len(y)
    score = score[order]
    prev_cum_neg = 0.0
    while i < n:
        j = i
        while j < n and score[j] == score[i]:
            j += 1
        grp_pos = pos_w[i:j].sum()
        grp_neg = neg_w[i:j].sum()
        auc_sum += grp_pos * (prev_cum_neg + grp_neg / 2.0)
        prev_cum_neg += grp_neg
        i = j
    return float(auc_sum / (total_pos * total_neg))


def _sigmoid_np(x):
    return 1.0 / (1.0 + np.exp(-x))


def binary_logloss(y, margin, w):
    p = np.clip(_sigmoid_np(margin), 1e-15, 1 - 1e-15)
    return float(np.average(-(y * np.log(p) + (1 - y) * np.log(1 - p)), weights=w))


def multi_logloss(y, margins, w):
    m = margins - margins.max(axis=1, keepdims=True)
    logp = m - np.log(np.exp(m).sum(axis=1, keepdims=True))
    ll = logp[np.arange(len(y)), np.asarray(y, dtype=int)]
    return float(np.average(-ll, weights=w))


def multi_error(y, margins, w):
    pred = margins.argmax(axis=1)
    return float(np.average(pred != np.asarray(y, dtype=int), weights=w))


def l2_loss(y, pred, w):
    return float(np.average((pred - y) ** 2, weights=w))


def rmse(y, pred, w):
    return float(np.sqrt(l2_loss(y, pred, w)))


def l1_loss(y, pred, w):
    return float(np.average(np.abs(pred - y), weights=w))


def quantile_loss(y, pred, w, alpha=0.9):
    d = y - pred
    return float(np.average(np.maximum(alpha * d, (alpha - 1) * d), weights=w))


def binary_error(y, margin, w):
    return float(np.average((margin > 0) != (y > 0.5), weights=w))


#: metric name -> (fn(y, score_or_margin, w), higher_is_better)
METRICS = {
    "auc": (auc, True),
    "binary_logloss": (binary_logloss, False),
    "binary_error": (binary_error, False),
    "multi_logloss": (multi_logloss, False),
    "multi_error": (multi_error, False),
    "l2": (l2_loss, False),
    "mse": (l2_loss, False),
    "rmse": (rmse, False),
    "l1": (l1_loss, False),
    "mae": (l1_loss, False),
    "quantile": (quantile_loss, False),
    "poisson": (l2_loss, False),  # monitored via l2 on the response scale
    "tweedie": (l2_loss, False),
}


def metric_higher_is_better(name: str) -> bool:
    if name in METRICS:
        return METRICS[name][1]
    # ndcg@k / map@k style names maximize (TrainUtils.scala:283-287)
    return name.split("@")[0] in ("auc", "ndcg", "map")
