"""Exclusive Feature Bundling (EFB) — pack (near-)mutually-exclusive
features into shared columns at binning time.

The native engine behind the reference bundles by default
(``enable_bundle``, LightGBM's EFB from the original paper §4): features
that are rarely non-default simultaneously — one-hot blocks, sparse
indicators — merge into ONE column whose bin ranges are offset per member.
On this runtime the win is structural: every histogram pass streams
K = Σ_f B_f packed one-hot rows from HBM (``ops/u_histogram.py``), and
bundling shrinks both K and the column count F, so the HBM re-stream that
bounds the pass (83% of peak at the continuous 255-bin shape,
``docs/perf_histogram.md``) drops proportionally — and the fit-resident U
fits the ``MMLSPARK_TPU_U_BUDGET`` gate at row counts that previously
overflowed it.

Layout (exactly LightGBM's ``FeatureGroup`` offset packing): each member
feature f of a bundle has a DEFAULT bin d_f (its most frequent bin in the
binning sample — overwhelmingly the zero/missing bin on sparse data).
Packed column value 0 means "every member at its default"; member f's
non-default bins occupy the half-open range [lo_f, lo_f + w_f - 1) via

    packed = lo_f + b - (b > d_f)          for b != d_f

and the inverse (used by row routing against original-feature splits)

    b = q + (q >= d_f)    where q = packed - lo_f,  q in [0, w_f - 1).

The member's OWN default bin never gets a packed slot: rows where f is
default but a sibling is not land in the sibling's range, so f's default
count is not directly readable from the bundle histogram. It is recovered
by subtraction — ``hist[f, d_f] = totals - Σ_b≠d_f hist[f, b]`` — the same
most-frequent-bin trick native LightGBM uses, exact for counts and exact
in distribution for g/h (association differs only within f32 rounding).

Everything downstream of the histogram (split search, model text, SHAP,
prediction, the Booster) stays in ORIGINAL feature space: the trainer
expands the bundle-space histogram to dense (k, F, B, 3) right after the
build (``train._hist_fn``), and row routing converts the packed bin back
to the original bin before every threshold compare. Emitted models are
therefore indistinguishable from unbundled fits — the golden tests pin a
zero-conflict fit to structural byte-identity (``tests/test_bundling.py``).

Host numpy only; the spec is a frozen all-tuple dataclass so it hashes
into the jitted-program cache key and pickles with the BinMapper across
the ``procfit`` process boundary.
"""

from __future__ import annotations

import dataclasses
from functools import lru_cache
from typing import Optional, Tuple

import numpy as np

# Packed columns stay uint8 + inside the precomputed-U ``num_bins <= 256``
# gate: a bundle's bin count (1 shared default slot + member ranges) never
# exceeds this.
MAX_BUNDLE_BINS = 256

# route_maps sentinel for identity columns: packed bin == original bin, so
# the unpack step (q >= skip) must never fire and the range check must
# always pass. 256 > any uint8 bin id.
_IDENTITY = 256


@dataclasses.dataclass(frozen=True)
class BundleSpec:
    """Static description of one fitted bundling. All-tuple fields: hashable
    (program-cache key material) and pickle-stable (rides the BinMapper to
    procfit workers).

    Per ORIGINAL feature f:
      - ``column_of[f]``: packed column holding f
      - ``lo_of[f]``: first packed bin of f's non-default range (0 for
        identity columns)
      - ``span_of[f]``: width of that range (w_f - 1 for bundled members;
        the sentinel 256 for identity columns so every packed bin is "in
        range")
      - ``skip_of[f]``: the unpack step threshold (= d_f for members; 256
        for identity columns so no step is ever added)
      - ``default_of[f]``: f's default (most frequent) bin d_f — the
        original bin an out-of-range packed value decodes to
      - ``identity[f]``: True when f's column holds f alone with packed
        bin == original bin

    Per PACKED column c: ``widths[c]`` (bin count incl. the shared default
    slot 0) and ``members[c]`` (original feature ids, packing order)."""

    column_of: Tuple[int, ...]
    lo_of: Tuple[int, ...]
    span_of: Tuple[int, ...]
    skip_of: Tuple[int, ...]
    default_of: Tuple[int, ...]
    identity: Tuple[bool, ...]
    widths: Tuple[int, ...]
    members: Tuple[Tuple[int, ...], ...]
    # fit metadata (bench/report material, not behavior)
    conflict_count: int = 0
    sample_rows: int = 0
    k_original: int = 0  # Σ_f w_f before bundling

    @property
    def num_features(self) -> int:
        return len(self.column_of)

    @property
    def num_columns(self) -> int:
        return len(self.widths)

    @property
    def num_bins(self) -> int:
        """Bundle-space dense histogram width B_b (max column bin count)."""
        return max(self.widths) if self.widths else 1

    @property
    def k_packed(self) -> int:
        """Σ_c widths[c] — the K the histogram pass actually streams."""
        return int(sum(self.widths))


def fit_feature_bundles(
    bins_sample: np.ndarray,
    num_bins: np.ndarray,
    max_conflict_rate: float = 0.0,
    categorical_slots=(),
    max_bundle_bins: int = MAX_BUNDLE_BINS,
) -> Optional[BundleSpec]:
    """Greedy graph-coloring over a binned row sample — LightGBM's
    ``BundleFeatures``/greedy bundling (EFB paper Alg. 1/2 with the
    conflict budget of Alg. 1's K): features ordered by non-default count
    descending; each joins the first bundle whose accumulated conflict
    count (rows where the feature AND the bundle are both non-default)
    stays within ``max_conflict_rate * n_sample`` and whose packed bin
    count stays within ``max_bundle_bins``. Returns None when no bundle
    gets a second member (bundling would be a no-op, so callers skip the
    whole machinery and the fit is bit-identical to an unbundled one).

    Categorical features never bundle (their split search and value-set
    masks address original bins directly), nor do features already at the
    column cap. Constant features (w <= 1) bundle for free: they have no
    non-default bins, so they cost 0 packed slots and 0 conflicts."""
    n, f = bins_sample.shape
    if n == 0 or f == 0:
        return None
    budget = int(max_conflict_rate * n)
    cat_set = set(int(c) for c in categorical_slots)
    w = np.asarray(
        [int(min(max(int(x), 1), max_bundle_bins)) for x in num_bins], np.int64
    )

    # Default bin per feature = most frequent bin in the sample.
    defaults = np.zeros(f, np.int64)
    for j in range(f):
        counts = np.bincount(bins_sample[:, j].astype(np.int64), minlength=1)
        defaults[j] = int(np.argmax(counts))
    nz = bins_sample != defaults[None, :]  # non-default indicator (n, f)
    nz_count = nz.sum(axis=0)

    # Most-frequently-non-default first (EFB's degree order), original
    # index as the deterministic tie-break.
    order = sorted(
        (j for j in range(f) if j not in cat_set),
        key=lambda j: (-int(nz_count[j]), j),
    )
    bundles = []  # dicts: members, ind (n,) bool, conflicts, width
    for j in order:
        span = max(0, int(w[j]) - 1)
        placed = False
        for bd in bundles:
            if bd["width"] + span > max_bundle_bins:
                continue
            c = int(np.count_nonzero(nz[:, j] & bd["ind"]))
            if bd["conflicts"] + c > budget:
                continue
            bd["members"].append(j)
            bd["ind"] = bd["ind"] | nz[:, j]
            bd["conflicts"] += c
            bd["width"] += span
            placed = True
            break
        if not placed:
            bundles.append(
                {
                    "members": [j],
                    "ind": nz[:, j].copy(),
                    "conflicts": 0,
                    "width": 1 + span,
                }
            )
    if all(len(bd["members"]) <= 1 for bd in bundles):
        return None

    # Assemble columns: multi-member bundles pack; singletons (and every
    # categorical feature) stay identity. Column order = min member id, so
    # column layout tracks the original feature order deterministically.
    cols = [bd["members"] for bd in bundles]
    cols += [[j] for j in sorted(cat_set) if j < f]
    cols.sort(key=lambda m: min(m))

    column_of = np.zeros(f, np.int64)
    lo_of = np.zeros(f, np.int64)
    span_of = np.zeros(f, np.int64)
    skip_of = np.zeros(f, np.int64)
    widths = []
    members = []
    for c, mem in enumerate(cols):
        if len(mem) == 1:
            j = mem[0]
            column_of[j] = c
            lo_of[j] = 0
            span_of[j] = _IDENTITY
            skip_of[j] = _IDENTITY
            widths.append(int(w[j]))
            members.append((j,))
            continue
        lo = 1  # packed bin 0 = every member at its default
        for j in mem:
            column_of[j] = c
            lo_of[j] = lo
            span_of[j] = max(0, int(w[j]) - 1)
            skip_of[j] = int(defaults[j])
            lo += max(0, int(w[j]) - 1)
        widths.append(lo)
        members.append(tuple(mem))

    identity = tuple(bool(span_of[j] == _IDENTITY) for j in range(f))
    total_conflicts = int(sum(bd["conflicts"] for bd in bundles))
    return BundleSpec(
        column_of=tuple(int(x) for x in column_of),
        lo_of=tuple(int(x) for x in lo_of),
        span_of=tuple(int(x) for x in span_of),
        skip_of=tuple(int(x) for x in skip_of),
        default_of=tuple(int(x) for x in defaults),
        identity=identity,
        widths=tuple(widths),
        members=tuple(members),
        conflict_count=total_conflicts,
        sample_rows=int(n),
        k_original=int(w.sum()),
    )


def pack_bundles(bins: np.ndarray, spec: BundleSpec) -> np.ndarray:
    """(N, F) original bins -> (N, C) packed bins (uint8). Identity columns
    copy through; bundled columns start at 0 ("all default") and each
    member scatters its non-default rows into its offset range. On the
    (budgeted-rare) conflict rows where two members are simultaneously
    non-default, the later member in packing order wins — the same
    last-writer rule as the sample the spec was fitted on, so packing is
    deterministic."""
    n = bins.shape[0]
    out = np.zeros((n, spec.num_columns), dtype=np.uint8)
    for c, mem in enumerate(spec.members):
        if len(mem) == 1 and spec.identity[mem[0]]:
            out[:, c] = bins[:, mem[0]]
            continue
        for j in mem:
            d = spec.default_of[j]
            col = bins[:, j].astype(np.int64)
            nd = col != d
            if not nd.any():
                continue
            v = col[nd]
            out[nd, c] = (spec.lo_of[j] + v - (v > d)).astype(np.uint8)
    return out


@lru_cache(maxsize=32)
def route_maps(
    spec: BundleSpec,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Per-ORIGINAL-feature routing arrays (host numpy, lru-cached so jit
    traces see stable constants): (col, lo, span, skip, dflt), each (F,)
    int32. A row's original bin for feature f given its packed column
    value xb is

        q = xb - lo[f]
        orig = q + (q >= skip[f])   if 0 <= q < span[f]   else dflt[f]

    Identity columns encode lo=0, span=skip=256 => orig == xb always."""
    return (
        np.asarray(spec.column_of, np.int32),
        np.asarray(spec.lo_of, np.int32),
        np.asarray(spec.span_of, np.int32),
        np.asarray(spec.skip_of, np.int32),
        np.asarray(spec.default_of, np.int32),
    )


@lru_cache(maxsize=32)
def expand_maps(
    spec: BundleSpec, num_bins: int
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Static maps expanding the bundle-space histogram
    (k, C, B_b, 3) to the dense original-space (k, F, num_bins, 3) the
    split search consumes: ``cidx[f, b]`` indexes the flattened (C * B_b)
    bundle plane, ``gmask[f, b]`` keeps only real packed slots, and
    ``dmask[f, b]`` marks each bundled member's default bin — filled by
    subtraction from the node totals (module docstring)."""
    f = spec.num_features
    bb = spec.num_bins
    cidx = np.zeros((f, num_bins), np.int32)
    gmask = np.zeros((f, num_bins), np.float32)
    dmask = np.zeros((f, num_bins), np.float32)
    for j in range(f):
        c = spec.column_of[j]
        if spec.identity[j]:
            wj = min(spec.widths[c], num_bins)
            cidx[j, :wj] = c * bb + np.arange(wj)
            gmask[j, :wj] = 1.0
            continue
        d = spec.default_of[j]
        span = spec.span_of[j]
        lo = spec.lo_of[j]
        wj = span + 1  # original width w_f
        for b in range(min(wj, num_bins)):
            if b == d:
                dmask[j, b] = 1.0
                continue
            cidx[j, b] = c * bb + lo + b - (b > d)
            gmask[j, b] = 1.0
    return cidx, gmask, dmask


def unpack_bins(packed: np.ndarray, spec: BundleSpec) -> np.ndarray:
    """(N, C) packed -> (N, F) original bins — the host-side inverse of
    :func:`pack_bundles` (exact wherever packing was conflict-free; a
    conflict row decodes the surviving writer and the overwritten member's
    default). Test/diagnostic utility; training routes on device via
    :func:`route_maps` instead."""
    col, lo, span, skip, dflt = route_maps(spec)
    xb = packed[:, col].astype(np.int64)  # (N, F)
    q = xb - lo[None, :]
    inb = (q >= 0) & (q < span[None, :])
    orig = q + (q >= skip[None, :])
    return np.where(inb, orig, dflt[None, :]).astype(np.uint8)


def cat_row_maps_bundled(
    u_spec, spec: BundleSpec, cat_slots
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Bundle-aware :func:`mmlspark_tpu.ops.u_histogram.cat_row_maps`:
    ``u_spec`` is laid out over PACKED columns, but the membership matmul
    matches split features in ORIGINAL ids — categorical features are
    always identity columns, so their packed rows are their original bins
    and only the column lookup changes."""
    rows, feats, locals_ = [], [], []
    for f_ in sorted(int(s) for s in cat_slots):
        c = spec.column_of[f_]
        w = u_spec.widths[c]
        o = u_spec.offsets[c]
        rows.extend(range(o, o + w))
        feats.extend([f_] * w)
        locals_.extend(range(w))
    return (
        np.asarray(rows, np.int32),
        np.asarray(feats, np.int32),
        np.asarray(locals_, np.int32),
    )
