"""Quantile feature binning — the ``max_bin`` dataset-construction stage.

Replaces LightGBM's native dataset build (``LGBM_DatasetCreateFromMat``,
reference ``lightgbm/LightGBMUtils.scala:212-239``): features are
quantile-binned once on the host into a row-major uint8 matrix that ships to
TPU HBM as a single transfer. Bin 0 is reserved for NaN/missing, matching
LightGBM's ``use_missing`` default semantics.

Host numpy today; the layout (contiguous uint8, per-feature edge arrays) is
chosen so the C++ ingest library (SURVEY.md §2.20 item 1) can take over
without format changes.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import numpy as np

MISSING_BIN = 0


@dataclasses.dataclass
class BinMapper:
    """Per-feature quantile bin edges. ``edges[f]`` has shape (max_bin-1,);
    value v maps to bin ``1 + searchsorted(edges[f], v, 'left')`` (bin 0 = NaN).
    ``upper[f][b]`` is the raw-value threshold meaning "bin <= b goes left"."""

    edges: np.ndarray  # (F, max_bin-1) float64, padded with +inf
    num_bins: np.ndarray  # (F,) actual bin count per feature (incl. missing bin)
    max_bin: int

    @property
    def num_features(self) -> int:
        return self.edges.shape[0]

    def threshold_value(self, feature: int, bin_idx: int) -> float:
        """Raw-value decision threshold for 'go left if x <= t' at bin_idx."""
        return float(self.edges[feature, bin_idx])


def fit_bin_mapper(
    X: np.ndarray,
    max_bin: int = 255,
    sample_cnt: int = 200_000,
    seed: int = 0,
) -> BinMapper:
    """Compute per-feature quantile edges (LightGBM ``bin_construct_sample_cnt``
    defaults to 200k sampled rows)."""
    n, f = X.shape
    if n > sample_cnt:
        rng = np.random.default_rng(seed)
        idx = rng.choice(n, size=sample_cnt, replace=False)
        sample = X[idx]
    else:
        sample = X
    # max_bin usable value bins (bin 0 reserved for missing) -> max_bin-1 edges.
    edges = np.full((f, max_bin - 1), np.inf, dtype=np.float64)
    num_bins = np.zeros(f, dtype=np.int32)
    for j in range(f):
        col = sample[:, j]
        col = col[~np.isnan(col)]
        if col.size == 0:
            num_bins[j] = 1
            continue
        uniq = np.unique(col)
        if len(uniq) <= max_bin - 1:
            # One bin per distinct value; edge = the value itself ("<= v" left).
            e = uniq
        else:
            qs = np.quantile(col, np.linspace(0, 1, max_bin), method="linear")
            e = np.unique(qs)[:-1]  # drop max so the top quantile maps inside
        k = len(e)
        edges[j, :k] = e
        num_bins[j] = k + 2  # +1 missing bin, +1 overflow bin above last edge
    # Snap edges to the float32 grid: prediction routes raw float32 values
    # against float32 thresholds, so binning must use the identical
    # comparison grid or boundary values (x == edge) route differently in
    # train vs predict vs SHAP.
    finite = np.isfinite(edges)
    edges[finite] = edges[finite].astype(np.float32).astype(np.float64)
    return BinMapper(edges=edges, num_bins=num_bins, max_bin=max_bin)


def apply_bins(X: np.ndarray, mapper: BinMapper) -> np.ndarray:
    """Map raw features to uint8 bin indices (row-major (N, F) uint8)."""
    n, f = X.shape
    out = np.zeros((n, f), dtype=np.uint8)
    for j in range(f):
        # float32 comparison grid — identical to the predict/SHAP paths.
        col = X[:, j].astype(np.float32)
        nan_mask = np.isnan(col)
        # 'left' => v <= edge stays at that edge's bin; v > last edge -> overflow bin.
        b = 1 + np.searchsorted(mapper.edges[j].astype(np.float32), col, side="left")
        b = np.where(nan_mask, MISSING_BIN, b)
        out[:, j] = np.clip(b, 0, mapper.max_bin).astype(np.uint8)
    return out


def bin_dataset(
    X: np.ndarray, max_bin: int = 255, mapper: Optional[BinMapper] = None
) -> Tuple[np.ndarray, BinMapper]:
    X = np.asarray(X, dtype=np.float64)
    if mapper is None:
        mapper = fit_bin_mapper(X, max_bin=max_bin)
    return apply_bins(X, mapper), mapper
