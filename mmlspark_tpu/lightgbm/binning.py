"""Quantile feature binning — the ``max_bin`` dataset-construction stage.

Replaces LightGBM's native dataset build (``LGBM_DatasetCreateFromMat``,
reference ``lightgbm/LightGBMUtils.scala:212-239``): features are
quantile-binned once on the host into a row-major uint8 matrix that ships to
TPU HBM as a single transfer. Bin 0 is reserved for NaN/missing, matching
LightGBM's ``use_missing`` default semantics.

Host numpy today; the layout (contiguous uint8, per-feature edge arrays) is
chosen so the C++ ingest library (SURVEY.md §2.20 item 1) can take over
without format changes.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import numpy as np

from mmlspark_tpu.lightgbm.bundling import (
    BundleSpec,
    fit_feature_bundles,
    pack_bundles,
)

MISSING_BIN = 0


@dataclasses.dataclass
class BinMapper:
    """Per-feature quantile bin edges. ``edges[f]`` has shape (max_bin-1,);
    value v maps to bin ``1 + searchsorted(edges[f], v, 'left')`` (bin 0 = NaN).
    ``upper[f][b]`` is the raw-value threshold meaning "bin <= b goes left".

    Categorical features (``categoricalSlotIndexes``/``Names``, reference
    ``lightgbm/LightGBMParams.scala:125-133``) bin by VALUE IDENTITY instead:
    each of the up to ``max_bin - 1`` most frequent category values owns one
    bin (``cat_values[f][b-1]`` is bin b's raw value, a bijection), and any
    other/unseen/NaN value maps to the missing bin 0 — which categorical
    split search treats as "not in any left set" (routes right), matching
    LightGBM's unseen-category behavior."""

    edges: np.ndarray  # (F, max_bin-1) float64, padded with +inf
    num_bins: np.ndarray  # (F,) actual bin count per feature (incl. missing bin)
    max_bin: int
    # feature index -> sorted-by-frequency raw category values (bin i+1 <-> v[i])
    cat_values: Optional[dict] = None
    # Exclusive Feature Bundling layout (mmlspark_tpu.lightgbm.bundling):
    # when set, apply_bins emits PACKED (N, C) columns and the trainer
    # expands histograms / converts routing back to original feature
    # space. None = unbundled (every consumer behaves exactly as before).
    bundles: Optional[BundleSpec] = None

    @property
    def num_features(self) -> int:
        return self.edges.shape[0]

    @property
    def categorical_features(self):
        return sorted(self.cat_values) if self.cat_values else []

    def is_categorical(self, feature: int) -> bool:
        return bool(self.cat_values) and feature in self.cat_values

    def threshold_value(self, feature: int, bin_idx: int) -> float:
        """Raw-value decision threshold for 'go left if x <= t' at bin_idx."""
        return float(self.edges[feature, bin_idx])


def fit_bin_mapper(
    X: np.ndarray,
    max_bin: int = 255,
    sample_cnt: int = 200_000,
    seed: int = 0,
    categorical_features=None,
    max_bin_by_feature=None,
) -> BinMapper:
    """Compute per-feature quantile edges (LightGBM ``bin_construct_sample_cnt``
    defaults to 200k sampled rows; ``binSampleCount``). ``categorical_features``:
    indices binned by value identity (one bin per frequent category).
    ``max_bin_by_feature``: per-feature bin cap (LightGBM maxBinByFeature;
    empty/None = the global ``max_bin`` everywhere)."""
    n, f = X.shape
    cat_set = set(int(c) for c in (categorical_features or []))
    caps = list(max_bin_by_feature or [])
    if caps:
        if len(caps) != f:
            raise ValueError(
                f"maxBinByFeature has {len(caps)} entries for {f} features"
            )
        bad = [c for c in caps if not (2 <= int(c) <= max_bin)]
        if bad:
            # explicit diagnostic instead of a silent clamp: this runtime's
            # uint8 bin layout caps per-feature bins at the global max_bin
            # (unlike native LightGBM, whose per-feature bins may exceed it)
            raise ValueError(
                f"maxBinByFeature entries must be in [2, maxBin={max_bin}] "
                f"(got {bad[:5]})"
            )
    if n > sample_cnt:
        rng = np.random.default_rng(seed)
        idx = rng.choice(n, size=sample_cnt, replace=False)
        sample = X[idx]
    else:
        sample = X
    # max_bin usable value bins (bin 0 reserved for missing) -> max_bin-1 edges.
    edges = np.full((f, max_bin - 1), np.inf, dtype=np.float64)
    num_bins = np.zeros(f, dtype=np.int32)
    cat_values: dict = {}
    for j in range(f):
        mb = int(caps[j]) if caps else max_bin
        col = sample[:, j]
        col = col[~np.isnan(col)]
        if j in cat_set:
            u, counts = np.unique(col, return_counts=True)
            cat_values[j] = _cat_values_from_counts(u, counts, mb)
            num_bins[j] = len(cat_values[j]) + 1  # + missing bin
            continue
        if col.size == 0:
            num_bins[j] = 1
            continue
        u, counts = np.unique(col, return_counts=True)
        e = _edges_from_counts(u, counts, mb, np.linspace(0, 1, mb))
        k = len(e)
        edges[j, :k] = e
        num_bins[j] = k + 2  # +1 missing bin, +1 overflow bin above last edge
    mapper = _snap_edges(edges, num_bins, max_bin)
    mapper.cat_values = cat_values or None
    return mapper


def _cat_values_from_counts(u: np.ndarray, counts: np.ndarray, mb: int) -> np.ndarray:
    """Value-identity bin list for one categorical feature: most frequent
    first (ties by value), capacity ``mb - 1`` — the ONE rule shared by the
    dense and CSR fits (they must stay bit-identical)."""
    order = np.lexsort((u, -counts))
    return np.asarray(u[order][: mb - 1], dtype=np.float64)


def _edges_from_counts(
    u: np.ndarray, counts: np.ndarray, max_bin: int, qs: np.ndarray
) -> np.ndarray:
    """Edges for one feature from its sorted unique non-NaN values + counts —
    the single edge rule shared by the dense and CSR fits (the two must stay
    bit-identical for sparse/dense training parity)."""
    if len(u) <= max_bin - 1:
        # One bin per distinct value; edge = the value itself ("<= v" left).
        return u
    qvals = _weighted_quantile(u, counts, qs)
    return np.unique(qvals)[:-1]  # drop max so the top quantile maps inside


def _snap_edges(edges: np.ndarray, num_bins: np.ndarray, max_bin: int) -> BinMapper:
    # Snap edges to the float32 grid: prediction routes raw float32 values
    # against float32 thresholds, so binning must use the identical
    # comparison grid or boundary values (x == edge) route differently in
    # train vs predict vs SHAP.
    finite = np.isfinite(edges)
    edges[finite] = edges[finite].astype(np.float32).astype(np.float64)
    return BinMapper(edges=edges, num_bins=num_bins, max_bin=max_bin)


def cat_to_bins(col: np.ndarray, values: np.ndarray) -> np.ndarray:
    """Raw category column -> bin ids: value ``values[i]`` -> bin ``i+1``;
    NaN/unseen -> missing bin 0. The ONE definition of categorical bin
    assignment (train, predict, and SHAP must agree)."""
    order = np.argsort(values, kind="stable")
    sv = values[order]
    col = np.asarray(col, dtype=np.float64)
    pos = np.searchsorted(sv, col)
    pos = np.clip(pos, 0, len(sv) - 1) if len(sv) else np.zeros(len(col), np.int64)
    hit = len(sv) > 0
    match = (sv[pos] == col) if hit else np.zeros(len(col), bool)
    bins = np.where(match, (order[pos] + 1) if hit else 0, MISSING_BIN)
    return np.where(np.isnan(col), MISSING_BIN, bins).astype(np.int64)


def apply_bins(X: np.ndarray, mapper: BinMapper) -> np.ndarray:
    """Map raw features to uint8 bin indices — row-major (N, F) uint8, or
    the PACKED (N, C) layout when the mapper carries a fitted
    :class:`~mmlspark_tpu.lightgbm.bundling.BundleSpec` (so train, valid
    sets, batch chaining, and procfit shards all bin consistently).
    Row-pure either way (the partitioned path concatenates shards)."""
    out = _apply_bins_raw(X, mapper)
    spec = getattr(mapper, "bundles", None)  # pre-EFB pickles lack the field
    if spec is not None:
        out = pack_bundles(out, spec)
    return out


def _apply_bins_raw(X: np.ndarray, mapper: BinMapper) -> np.ndarray:
    """Original-feature-space binning (pre-bundling). Uses the host C++
    library when built (bit-identical contract,
    ``native/mmlspark_native.cpp``); numpy otherwise. Categorical columns
    are overlaid afterwards (value-identity bins, ``cat_to_bins``)."""
    from mmlspark_tpu.native import apply_bins_native

    native = apply_bins_native(np.asarray(X, dtype=np.float64), mapper.edges, mapper.max_bin)
    if native is not None:
        if mapper.cat_values:
            native = np.array(native, copy=True)
            for j, vals in mapper.cat_values.items():
                native[:, j] = cat_to_bins(X[:, j], vals).astype(np.uint8)
        return native
    n, f = X.shape
    out = np.zeros((n, f), dtype=np.uint8)
    for j in range(f):
        if mapper.is_categorical(j):
            out[:, j] = cat_to_bins(X[:, j], mapper.cat_values[j]).astype(np.uint8)
            continue
        # float32 comparison grid — identical to the predict/SHAP paths.
        col = X[:, j].astype(np.float32)
        nan_mask = np.isnan(col)
        # 'left' => v <= edge stays at that edge's bin; v > last edge -> overflow bin.
        b = 1 + np.searchsorted(mapper.edges[j].astype(np.float32), col, side="left")
        b = np.where(nan_mask, MISSING_BIN, b)
        out[:, j] = np.clip(b, 0, mapper.max_bin).astype(np.uint8)
    return out


def fit_bundles_inplace(
    mapper: BinMapper,
    raw_bins: np.ndarray,
    max_conflict_rate: float = 0.0,
    sample_cnt: int = 200_000,
    seed: int = 0,
) -> Optional[BundleSpec]:
    """Fit Exclusive Feature Bundling over a row sample of the ALREADY
    binned (original-space) matrix and attach the spec to the mapper.
    Stays None when no bundle gains a second member — then every consumer
    is bit-identical to an unbundled fit. Same sampling discipline as the
    edge fit (``sample_cnt`` rows, seeded rng)."""
    n = raw_bins.shape[0]
    if n > sample_cnt:
        rng = np.random.default_rng(seed)
        sample = raw_bins[rng.choice(n, size=sample_cnt, replace=False)]
    else:
        sample = raw_bins
    spec = fit_feature_bundles(
        sample,
        mapper.num_bins,
        max_conflict_rate=max_conflict_rate,
        categorical_slots=mapper.categorical_features,
    )
    mapper.bundles = spec
    if spec is not None:
        from mmlspark_tpu.observability.events import FeatureBundled, get_bus

        bus = get_bus()
        if bus.active:
            bus.publish(FeatureBundled(
                num_features=spec.num_features,
                num_columns=spec.num_columns,
                k_before=int(sum(int(x) for x in mapper.num_bins)),
                k_after=spec.k_packed,
                conflicts=spec.conflict_count,
                sample_rows=spec.sample_rows,
            ))
    return spec


def bin_dataset_to_device(
    X: np.ndarray,
    max_bin: int = 255,
    mapper: Optional[BinMapper] = None,
    categorical_features=None,
    feature_bundling: bool = False,
    max_conflict_rate: float = 0.0,
):
    """Bin on the host, then dispatch ONE asynchronous ``jax.device_put`` —
    the transfer flies while the caller sets up the rest of the fit
    (remote-attached chips pay ~0.3-0.45 s of fixed cost PER transfer, so
    chunked uploads measured strictly slower than one shot). Returns
    (device_bins uint8 (N, F) — or (N, C) packed under ``feature_bundling``
    — and the mapper); feed the device array straight to
    :func:`~mmlspark_tpu.lightgbm.train.train` (it skips its own upload
    for device-resident bins)."""
    import jax

    bins, mapper = bin_dataset(
        X, max_bin=max_bin, mapper=mapper,
        categorical_features=categorical_features,
        feature_bundling=feature_bundling,
        max_conflict_rate=max_conflict_rate,
    )
    return jax.device_put(np.ascontiguousarray(bins)), mapper


def bin_dataset(
    X, max_bin: int = 255, mapper: Optional[BinMapper] = None,
    categorical_features=None, sample_cnt: int = 200_000,
    max_bin_by_feature=None, feature_bundling: bool = False,
    max_conflict_rate: float = 0.0,
) -> Tuple[np.ndarray, BinMapper]:
    from mmlspark_tpu.data.sparse import CSRMatrix

    fresh = mapper is None
    if isinstance(X, CSRMatrix):
        if max_bin_by_feature:
            raise ValueError(
                "maxBinByFeature is not supported on sparse (CSR) input"
            )
        if fresh:
            mapper = fit_bin_mapper_csr(
                X, max_bin=max_bin, sample_cnt=sample_cnt,
                categorical_features=categorical_features,
            )
        raw = _apply_bins_csr_raw(X, mapper)
    else:
        X = np.asarray(X, dtype=np.float64)
        if fresh:
            mapper = fit_bin_mapper(
                X, max_bin=max_bin, sample_cnt=sample_cnt,
                categorical_features=categorical_features,
                max_bin_by_feature=max_bin_by_feature,
            )
        raw = _apply_bins_raw(X, mapper)
    if fresh and feature_bundling:
        fit_bundles_inplace(
            mapper, raw, max_conflict_rate=max_conflict_rate,
            sample_cnt=sample_cnt,
        )
    spec = getattr(mapper, "bundles", None)
    if spec is not None:
        return pack_bundles(raw, spec), mapper
    return raw, mapper


def bin_dataset_partitioned(
    X, max_bin: int = 255, mapper: Optional[BinMapper] = None,
    categorical_features=None, sample_cnt: int = 200_000,
    max_bin_by_feature=None, policy=None, metrics=None,
    journal_root: Optional[str] = None, journal_key: Optional[str] = None,
    feature_bundling: bool = False, max_conflict_rate: float = 0.0,
) -> Tuple[np.ndarray, BinMapper]:
    """:func:`bin_dataset` with the row-binning pass dispatched as
    partitioned tasks on the fault-tolerant scheduler
    (:mod:`mmlspark_tpu.runtime`). The :class:`BinMapper` fit stays inline
    (one cheap, deterministic quantile pass over a sample); the expensive
    per-row :func:`apply_bins` is row-pure, so partition results
    concatenated in index order are bit-identical to the inline call — an
    injected executor death mid-bin retries/recomputes and changes nothing
    downstream. Each partition records lineage (its row slice), so a
    :class:`~mmlspark_tpu.runtime.lineage.PartitionLostError` rebuilds the
    shard instead of failing the fit.

    CSR input falls back to the inline path (``apply_bins_csr`` scatters
    over the whole matrix in one pass).

    ``journal_root`` + ``journal_key`` make the pass durable: each
    partition's binned block checkpoints to a
    :class:`~mmlspark_tpu.runtime.journal.FitJournal` as it completes, so
    a killed process rerun with the same key restores finished partitions
    with zero re-execution (the partition count is folded into the
    journal identity — a different ``max_workers`` starts clean rather
    than mixing incompatible row slices).
    """
    from mmlspark_tpu import runtime
    from mmlspark_tpu.data.sparse import CSRMatrix

    if isinstance(X, CSRMatrix):
        return bin_dataset(
            X, max_bin=max_bin, mapper=mapper,
            categorical_features=categorical_features, sample_cnt=sample_cnt,
            max_bin_by_feature=max_bin_by_feature,
            feature_bundling=feature_bundling,
            max_conflict_rate=max_conflict_rate,
        )
    X = np.asarray(X, dtype=np.float64)
    fresh = mapper is None
    if fresh:
        mapper = fit_bin_mapper(
            X, max_bin=max_bin, sample_cnt=sample_cnt,
            categorical_features=categorical_features,
            max_bin_by_feature=max_bin_by_feature,
        )
    if fresh and feature_bundling:
        # Bundle fit stays inline (like the mapper fit): bin only the
        # sample rows in original space, attach the spec, and every
        # partition task's apply_bins packs consistently (row-pure).
        n_all = X.shape[0]
        if n_all > sample_cnt:
            rng = np.random.default_rng(0)
            rows = X[rng.choice(n_all, size=sample_cnt, replace=False)]
        else:
            rows = X
        fit_bundles_inplace(
            mapper, _apply_bins_raw(rows, mapper),
            max_conflict_rate=max_conflict_rate, sample_cnt=sample_cnt,
        )
    pol = policy or runtime.current_policy() or runtime.SchedulerPolicy()
    n = X.shape[0]
    num_parts = max(1, min(pol.max_workers, n))
    if n == 0:
        return apply_bins(X, mapper), mapper
    bounds = np.linspace(0, n, num_parts + 1).astype(np.int64)
    lineage = runtime.Lineage()
    shards = [
        lineage.record(
            i,
            (lambda lo=int(bounds[i]), hi=int(bounds[i + 1]): X[lo:hi]),
            describe=f"rows[{bounds[i]}:{bounds[i + 1]}]",
        )
        for i in range(num_parts)
    ]
    journal = None
    if journal_root is not None and journal_key is not None:
        journal = runtime.FitJournal(
            journal_root, f"{journal_key}-p{num_parts}", num_tasks=num_parts
        )
    try:
        parts = runtime.run_partitioned(
            lambda rows: apply_bins(rows, mapper), shards, pol,
            lineage=lineage, metrics=metrics, journal=journal,
        )
    finally:
        if journal is not None:
            journal.close()
    return np.concatenate(parts, axis=0), mapper


# ---------------------------------------------------------------------------
# Sparse (CSR) ingest — the LGBM_DatasetCreateFromCSRSpark analogue
# (reference lightgbm/LightGBMUtils.scala:246-266). Implicit entries are 0.0;
# the dense float matrix is never materialized: quantiles fold the implicit
# zero mass in analytically, and bin assignment scatters explicit entries over
# a zero-bin-initialized uint8 matrix (the layout training wants anyway).
# ---------------------------------------------------------------------------


def _weighted_quantile(u: np.ndarray, c: np.ndarray, qs: np.ndarray) -> np.ndarray:
    """Quantiles of the multiset {u[k] repeated c[k] times}, matching
    ``np.quantile(..., method='linear')`` bit-for-bit: position p = q*(W-1),
    linear interpolation between virtual sorted elements floor(p)/ceil(p)."""
    w = int(c.sum())
    cum = np.cumsum(c)
    p = qs * (w - 1)
    i = np.floor(p).astype(np.int64)
    frac = p - i
    i2 = np.minimum(i + 1, w - 1)
    a_lo = u[np.searchsorted(cum, i, side="right")]
    a_hi = u[np.searchsorted(cum, i2, side="right")]
    # numpy's _lerp switches formula at t >= 0.5 for monotonicity; reproduce
    # it so these edges are bitwise np.quantile's.
    diff = a_hi - a_lo
    out = a_lo + frac * diff
    return np.where(frac >= 0.5, a_hi - diff * (1 - frac), out)


def fit_bin_mapper_csr(csr, max_bin: int = 255, sample_cnt: int = 200_000,
                       seed: int = 0, categorical_features=None) -> BinMapper:
    """Per-feature quantile edges from CSR without densifying. Matches
    :func:`fit_bin_mapper` on the equivalent dense matrix exactly (same
    sampling rng, same quantile arithmetic with the implicit-zero mass;
    categorical features count the implicit zeros toward category 0.0's
    frequency)."""
    n, f = csr.shape
    if n > sample_cnt:
        rng = np.random.default_rng(seed)
        idx = rng.choice(n, size=sample_cnt, replace=False)
        sel = np.zeros(n, dtype=bool)
        sel[idx] = True
        n_sample = sample_cnt
    else:
        sel = None
        n_sample = n

    if sel is not None:
        row_ids = np.repeat(np.arange(n, dtype=np.int64), np.diff(csr.indptr))
        keep = sel[row_ids]
        cols, vals = csr.indices[keep], csr.data[keep]
    else:
        cols, vals = csr.indices, csr.data

    order = np.argsort(cols, kind="stable")
    cols_s, vals_s = cols[order], vals[order]
    col_starts = np.searchsorted(cols_s, np.arange(f + 1))

    cat_set = set(int(c) for c in (categorical_features or []))
    edges = np.full((f, max_bin - 1), np.inf, dtype=np.float64)
    num_bins = np.zeros(f, dtype=np.int32)
    cat_values: dict = {}
    qs = np.linspace(0, 1, max_bin)
    for j in range(f):
        explicit = vals_s[col_starts[j] : col_starts[j + 1]]
        n_zero = n_sample - len(explicit)  # implicit entries are 0.0
        explicit = explicit[~np.isnan(explicit)]
        if len(explicit) + n_zero == 0:
            num_bins[j] = 1
            continue
        # Fold the implicit zero mass into the (value, count) multiset, then
        # defer to the shared edge rule.
        u, counts = np.unique(explicit, return_counts=True)
        pos = np.searchsorted(u, 0.0)
        if pos < len(u) and u[pos] == 0.0:
            counts = counts.copy()
            counts[pos] += n_zero
        elif n_zero > 0:
            u = np.insert(u, pos, 0.0)
            counts = np.insert(counts, pos, n_zero)
        if j in cat_set:
            # shared rule, with the implicit-zero mass already folded in
            cat_values[j] = _cat_values_from_counts(u, counts, max_bin)
            num_bins[j] = len(cat_values[j]) + 1
            continue
        e = _edges_from_counts(u, counts, max_bin, qs)
        k = len(e)
        edges[j, :k] = e
        num_bins[j] = k + 2
    mapper = _snap_edges(edges, num_bins, max_bin)
    mapper.cat_values = cat_values or None
    return mapper


def apply_bins_csr(csr, mapper: BinMapper) -> np.ndarray:
    """CSR → dense row-major uint8 bins (packed when the mapper bundles).
    Bit-identical to ``apply_bins`` on the densified matrix."""
    out = _apply_bins_csr_raw(csr, mapper)
    spec = getattr(mapper, "bundles", None)
    if spec is not None:
        out = pack_bundles(out, spec)
    return out


def _apply_bins_csr_raw(csr, mapper: BinMapper) -> np.ndarray:
    """Original-feature-space CSR binning: initialize every cell to its
    feature's zero-bin, then scatter the explicit entries column-by-column."""
    n, f = csr.shape
    edges32 = mapper.edges.astype(np.float32)
    zero_bins = np.clip(
        1 + np.array([np.searchsorted(edges32[j], np.float32(0.0), side="left") for j in range(f)]),
        0,
        mapper.max_bin,
    ).astype(np.uint8)
    for j, vals in (mapper.cat_values or {}).items():
        # categorical zero-fill: category 0.0's value bin (or missing)
        zero_bins[j] = np.uint8(cat_to_bins(np.array([0.0]), vals)[0])
    out = np.broadcast_to(zero_bins[None, :], (n, f)).copy()

    col_indptr, row_ids, values = csr.to_csc()
    for j in range(f):
        lo, hi = col_indptr[j], col_indptr[j + 1]
        if hi == lo:
            continue
        if mapper.is_categorical(j):
            b = cat_to_bins(values[lo:hi], mapper.cat_values[j])
            out[row_ids[lo:hi], j] = b.astype(np.uint8)
            continue
        v = values[lo:hi].astype(np.float32)
        b = 1 + np.searchsorted(edges32[j], v, side="left")
        b = np.where(np.isnan(v), MISSING_BIN, b)
        out[row_ids[lo:hi], j] = np.clip(b, 0, mapper.max_bin).astype(np.uint8)
    return out
