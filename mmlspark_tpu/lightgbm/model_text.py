"""Native LightGBM model-text serde.

The reference's booster string IS LightGBM's text format — loadable by any
LightGBM runtime, ONNX converters, and SHAP tooling
(``lightgbm/LightGBMBooster.scala:277-310``; save/load API
``LightGBMClassifier.scala:172-194``). This module emits and parses that
format (model file ``version=v3``, the LightGBM 3.x layout) so boosters
trained here interoperate with the LightGBM ecosystem and models trained by
LightGBM score here.

Encoding notes (mirroring LightGBM's ``src/io/tree.cpp`` / ``gbdt_model_text.cpp``):

- A tree with L leaves has L-1 internal nodes. ``left_child``/``right_child``
  entries >= 0 index internal nodes; negative entries encode leaves as
  ``~leaf_index`` (i.e. leaf j is stored as -(j+1)).
- ``decision_type`` is a bit field: bit 0 = categorical, bit 1 =
  default_left, bits 2-3 = missing type (0 none, 1 zero, 2 NaN). Numeric
  nodes trained here always route NaN left: ``decision_type = 10``.
- Categorical splits (``num_cat > 0``): a cat node's ``threshold`` is an
  index into ``cat_boundaries`` (num_cat+1 cumulative uint32-word offsets)
  / ``cat_threshold`` (bitset words over RAW category values; value v in
  the left set iff word[v//32] has bit v%32). Export requires the
  category values be non-negative integers (LightGBM's own contract);
  NaN/unseen values route right on both engines.
- ``boost_from_average``: LightGBM has no init-score field — the init score
  lives inside the first iteration's leaf values. Export therefore folds
  ``init_score[c]`` into iteration-0 class-c leaf values; import leaves
  ``init_score = 0`` (the margins come out identical).
- Floats print with ``%.17g`` (round-trip exact for float64).

- Linear trees (``is_linear=1``, LightGBM's ``linear_tree=true``): per-leaf
  linear models import/export via ``leaf_const`` / ``num_features`` /
  ``leaf_features`` / ``leaf_coeff`` (concatenated in leaf order); predict
  evaluates them in float64 with native LightGBM's NaN fallback to the
  plain leaf output. SHAP on such models raises.

``missing_type=None`` imports with the LightGBM predictor's convention that
a NaN at such a node behaves like 0.0, which resolves to a static per-node
direction ``nan_left = (0.0 <= threshold)``; ``missing_type=Zero``
(``zero_as_missing=true``) imports as per-node ``zero_missing`` flags — a
0.0 or NaN value routes per ``default_left`` there.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

_G = "%.17g"


def _fmt(values) -> str:
    return " ".join(_G % float(v) for v in values)


def _fmt_int(values) -> str:
    return " ".join(str(int(v)) for v in values)


# Our objective names -> LightGBM model-file objective strings.
def _objective_str(objective: str, num_classes: int) -> str:
    if objective == "binary":
        return "binary sigmoid:1"
    if objective == "multiclass":
        return f"multiclass num_class:{num_classes}"
    return objective  # regression / regression_l1 / huber / quantile / poisson / tweedie


def _parse_objective(s: str) -> str:
    tok = s.split()
    return tok[0] if tok else "regression"


def to_lightgbm_text(booster, shrinkage: float = 1.0) -> str:
    """Serialize a :class:`~mmlspark_tpu.lightgbm.booster.Booster` to
    LightGBM's model text. ``shrinkage`` is recorded per tree (informational:
    leaf values in the file are final, as LightGBM itself writes them)."""
    t = booster.num_trees
    c = booster.num_classes
    f = booster.num_features
    nan_left = getattr(booster, "nan_left", None)
    init = np.asarray(booster.init_score, dtype=np.float64)
    if t == 0 and np.any(init != 0):
        raise ValueError(
            "cannot export a zero-tree booster with nonzero init_score: "
            "LightGBM's format stores the init score inside the first "
            "iteration's leaf values"
        )

    cat_nodes_all = booster.cat_nodes
    cat_masks_all = booster.cat_masks
    cat_values_all = booster.cat_values or {}
    zero_missing_all = booster.zero_missing

    tree_strs: List[str] = []
    for ti in range(t):
        is_leaf = np.asarray(booster.is_leaf[ti], dtype=bool)
        left = np.asarray(booster.left_child[ti])
        right = np.asarray(booster.right_child[ti])
        feat = np.asarray(booster.split_feature[ti])
        thr = np.asarray(booster.split_threshold[ti], dtype=np.float64)
        cat_node = (
            np.asarray(cat_nodes_all[ti], bool)
            if cat_nodes_all is not None else np.zeros(len(feat), bool)
        )
        lval = np.asarray(booster.leaf_values[ti], dtype=np.float64)
        gain = (
            np.asarray(booster.split_gain[ti], dtype=np.float64)
            if booster.split_gain is not None
            else np.zeros(len(feat))
        )
        cover = (
            np.asarray(booster.cover[ti], dtype=np.float64)
            if booster.cover is not None
            else np.zeros(len(feat))
        )
        nl = (
            np.asarray(nan_left[ti], dtype=bool)
            if nan_left is not None
            else np.ones(len(feat), dtype=bool)
        )

        # init-score folding: iteration 0, class ti % c
        bias = float(init[ti % c]) if ti < c else 0.0

        # Walk reachable slots from the root, assigning LightGBM indices:
        # internal nodes and leaves each in pre-order discovery order.
        internal_ids = {}
        leaf_ids = {}
        order: List[int] = []
        stack = [0]
        while stack:
            slot = stack.pop()
            order.append(slot)
            if is_leaf[slot]:
                leaf_ids[slot] = len(leaf_ids)
                continue
            internal_ids[slot] = len(internal_ids)
            stack.append(int(right[slot]))
            stack.append(int(left[slot]))
        num_leaves = len(leaf_ids)
        ni = len(internal_ids)

        sf = np.zeros(ni, np.int64)
        sg = np.zeros(ni, np.float64)
        th = np.zeros(ni, np.float64)
        dt = np.zeros(ni, np.int64)
        lc = np.zeros(ni, np.int64)
        rc = np.zeros(ni, np.int64)
        ivalue = np.zeros(ni, np.float64)
        iw = np.zeros(ni, np.float64)  # float cover (weighted row mass)
        lv = np.zeros(max(num_leaves, 1), np.float64)
        lw = np.zeros(max(num_leaves, 1), np.float64)

        def child_ref(slot: int) -> int:
            return internal_ids[slot] if not is_leaf[slot] else ~leaf_ids[slot]

        # categorical nodes: threshold = index into cat_boundaries /
        # cat_threshold (bitsets over RAW category values, uint32 words)
        cat_boundaries = [0]
        cat_words: List[int] = []
        slot_by_ii = {ii: slot for slot, ii in internal_ids.items()}
        for slot in order:
            if is_leaf[slot]:
                li = leaf_ids[slot]
                lv[li] = lval[slot] + bias
                lw[li] = cover[slot]
                continue
            ii = internal_ids[slot]
            sf[ii] = int(feat[slot])
            sg[ii] = max(gain[slot], 0.0)
            th[ii] = thr[slot]
            # bit1 default_left per the node's NaN routing; bits2-3 =
            # Zero(1) for zero_missing nodes, NaN(2) otherwise
            zm_bit = (
                zero_missing_all is not None and bool(zero_missing_all[ti][slot])
            )
            dt[ii] = (2 if nl[slot] else 0) | ((1 if zm_bit else 2) << 2)
            lc[ii] = child_ref(int(left[slot]))
            rc[ii] = child_ref(int(right[slot]))
            iw[ii] = cover[slot]
        num_cat = 0
        for ii in range(ni):  # cat indexes assigned in internal-node order
            slot = slot_by_ii[ii]
            if not cat_node[slot]:
                continue
            f_idx = int(feat[slot])
            vals_f = np.asarray(cat_values_all.get(f_idx, ()), np.float64)
            bins_in = np.nonzero(np.asarray(cat_masks_all[ti][slot], bool))[0]
            bins_in = bins_in[(bins_in >= 1) & (bins_in <= len(vals_f))]
            raw = vals_f[bins_in - 1]
            if raw.size == 0 or np.any(raw < 0) or np.any(np.mod(raw, 1) != 0):
                raise ValueError(
                    f"tree {ti} slot {slot}: categorical split values must "
                    "be non-negative integers for LightGBM's bitset format "
                    f"(got {raw[:5]}...)"
                )
            raw_i = raw.astype(np.int64)
            nwords = int(raw_i.max()) // 32 + 1
            words = np.zeros(nwords, np.uint32)
            np.bitwise_or.at(
                words, raw_i // 32, np.uint32(1) << (raw_i % 32).astype(np.uint32)
            )
            th[ii] = float(num_cat)
            dt[ii] = 1 | (2 << 2)  # bit0 categorical, missing NaN (-> right)
            cat_words.extend(int(w) for w in words)
            cat_boundaries.append(len(cat_words))
            num_cat += 1

        if num_leaves == 0:  # degenerate: root itself missing (cannot happen)
            num_leaves = 1

        fields = [
            f"num_leaves={num_leaves}",
            f"num_cat={num_cat}",
            f"split_feature={_fmt_int(sf)}",
            f"split_gain={_fmt(sg)}",
            f"threshold={_fmt(th)}",
            f"decision_type={_fmt_int(dt)}",
            f"left_child={_fmt_int(lc)}",
            f"right_child={_fmt_int(rc)}",
            f"leaf_value={_fmt(lv)}",
            f"leaf_weight={_fmt(lw)}",
            f"leaf_count={_fmt_int(np.round(lw))}",
            f"internal_value={_fmt(ivalue)}",
            f"internal_weight={_fmt(iw)}",
            f"internal_count={_fmt_int(np.round(iw))}",
        ]
        if num_cat:
            fields += [
                f"cat_boundaries={_fmt_int(cat_boundaries)}",
                f"cat_threshold={_fmt_int(cat_words)}",
            ]

        # Linear leaves (imported linear_tree models being re-exported):
        # concatenate per-leaf models in leaf-id order; the iteration-0 bias
        # folds into BOTH the intercepts and the fallback leaf values.
        lin_fields: List[str] = []
        if getattr(booster, "leaf_const", None) is not None:
            lconst = np.zeros(max(num_leaves, 1), np.float64)
            per: List[tuple] = [((), ())] * max(num_leaves, 1)
            for slot, li in leaf_ids.items():
                lconst[li] = float(booster.leaf_const[ti][slot]) + bias
                fi = np.asarray(booster.leaf_feat[ti][slot])
                co = np.asarray(booster.leaf_coeff[ti][slot])
                v = fi >= 0
                per[li] = (fi[v].tolist(), co[v].tolist())
            lin_fields = [
                "is_linear=1",
                f"leaf_const={_fmt(lconst)}",
                f"num_features={_fmt_int([len(p[0]) for p in per])}",
                f"leaf_features={_fmt_int([x for p in per for x in p[0]])}",
                f"leaf_coeff={_fmt([x for p in per for x in p[1]])}",
            ]
        else:
            lin_fields = ["is_linear=0"]

        fields += lin_fields + [f"shrinkage={_G % shrinkage}"]
        if ni == 0:
            # single-leaf tree: LightGBM omits the internal-node arrays
            fields = [
                f"num_leaves={num_leaves}",
                "num_cat=0",
                f"leaf_value={_fmt(lv)}",
            ] + lin_fields + [f"shrinkage={_G % shrinkage}"]
        tree_strs.append(f"Tree={ti}\n" + "\n".join(fields) + "\n\n\n")

    names = booster.feature_names or [f"Column_{j}" for j in range(f)]
    edges = booster.bin_edges
    infos = []
    for j in range(f):
        if edges is not None and np.isfinite(edges[j]).any():
            fin = edges[j][np.isfinite(edges[j])]
            infos.append(f"[{_G % fin.min()}:{_G % fin.max()}]")
        else:
            infos.append("none")

    header = "\n".join(
        [
            "tree",
            "version=v3",
            f"num_class={c}",
            f"num_tree_per_iteration={c}",
            "label_index=0",
            f"max_feature_idx={max(f - 1, 0)}",
            f"objective={_objective_str(booster.objective, c)}",
            "feature_names=" + " ".join(names),
            "feature_infos=" + " ".join(infos),
            "tree_sizes=" + " ".join(str(len(s.encode())) for s in tree_strs),
        ]
    )
    imp = booster.feature_importances("split") if t else np.zeros(f)
    imp_lines = "\n".join(
        f"{names[j]}={int(imp[j])}"
        for j in np.argsort(-imp, kind="stable")
        if imp[j] > 0
    )
    return (
        header
        + "\n\n"
        + "".join(tree_strs)
        + "end of trees\n\n"
        + "feature_importances:\n"
        + imp_lines
        + ("\n" if imp_lines else "")
        + "\nparameters:\n"
        + f"[objective: {_parse_objective(_objective_str(booster.objective, c))}]\n"
        + "end of parameters\n\n"
        + "pandas_categorical:null\n"
    )


def _parse_linear_block(blk: dict, num_leaves: int, bi: int):
    """Per-leaf linear models of an ``is_linear=1`` tree block
    (LightGBM's ``linear_tree=true`` serialization): ``leaf_const`` is the
    intercept per leaf, ``num_features`` the per-leaf model width, and
    ``leaf_features``/``leaf_coeff`` the concatenated feature ids /
    coefficients in leaf order. Returns (const, [feat_ids...], [coefs...])."""
    const = np.fromstring(_block_value(blk, "leaf_const"), sep=" ")
    if const.size != num_leaves:
        raise ValueError(
            f"tree {bi}: leaf_const has {const.size} entries for "
            f"{num_leaves} leaves"
        )
    counts = np.fromstring(blk.get("num_features", ""), sep=" ").astype(np.int64)
    if counts.size == 0:
        counts = np.zeros(num_leaves, np.int64)
    if counts.size != num_leaves:
        raise ValueError(
            f"tree {bi}: num_features has {counts.size} entries for "
            f"{num_leaves} leaves"
        )
    feats = np.fromstring(blk.get("leaf_features", ""), sep=" ").astype(np.int64)
    coefs = np.fromstring(blk.get("leaf_coeff", ""), sep=" ")
    total = int(counts.sum())
    if feats.size != total or coefs.size != total:
        raise ValueError(
            f"tree {bi}: leaf_features/leaf_coeff lengths "
            f"({feats.size}/{coefs.size}) do not match num_features sum {total}"
        )
    offs = np.concatenate([[0], np.cumsum(counts)]).astype(np.int64)
    return (
        const,
        [feats[offs[j] : offs[j + 1]] for j in range(num_leaves)],
        [coefs[offs[j] : offs[j + 1]] for j in range(num_leaves)],
    )


def _block_value(block: dict, key: str, default=None):
    if key not in block:
        if default is not None:
            return default
        raise ValueError(f"LightGBM model text: tree block missing {key!r}")
    return block[key]


def from_lightgbm_text(s: str):
    """Parse LightGBM model text into a Booster (categorical splits,
    ``zero_as_missing``, and linear trees included). Raises ``ValueError``
    on structurally invalid files."""
    from mmlspark_tpu.lightgbm.booster import Booster

    lines = s.splitlines()
    header = {}
    i = 0
    while i < len(lines):
        line = lines[i].strip()
        if line.startswith("Tree="):
            break
        if "=" in line:
            k, _, v = line.partition("=")
            header[k] = v
        i += 1

    num_classes = int(header.get("num_class", 1))
    per_iter = int(header.get("num_tree_per_iteration", num_classes))
    if per_iter != num_classes:
        raise ValueError(
            f"num_tree_per_iteration={per_iter} != num_class={num_classes} "
            "(boosted random forests of multiple trees per round are not supported)"
        )
    objective = _parse_objective(header.get("objective", "regression"))
    if objective not in (
        "binary", "multiclass", "regression", "regression_l1", "huber",
        "quantile", "poisson", "tweedie", "lambdarank",
    ):
        raise ValueError(f"unsupported objective in model text: {objective!r}")
    max_feature_idx = int(header.get("max_feature_idx", 0))
    feature_names = header.get("feature_names", "").split() or None

    # Tree blocks: key=value lines between "Tree=i" and the next blank run.
    blocks = []
    cur: Optional[dict] = None
    for line in lines[i:]:
        line = line.strip()
        if line.startswith("Tree="):
            cur = {}
            blocks.append(cur)
            continue
        if line == "end of trees":
            break
        if not line or cur is None:
            continue
        k, _, v = line.partition("=")
        cur[k] = v

    trees = []
    for bi, blk in enumerate(blocks):
        num_leaves = int(_block_value(blk, "num_leaves"))
        num_cat = int(blk.get("num_cat", "0"))
        is_lin = blk.get("is_linear", "0").strip() not in ("0", "")
        lin_fields = (
            _parse_linear_block(blk, num_leaves, bi) if is_lin else None
        )
        lv = np.fromstring(_block_value(blk, "leaf_value"), sep=" ")
        if num_leaves == 1:
            tr = dict(feat=[0], thr=[np.inf], left=[0], right=[0],
                      is_leaf=[True], lval=[lv[0]], nanl=[True], zm=[False],
                      cover=[0.0], gain=[0.0], cat={})
            if lin_fields is not None:
                tr["lin"] = lin_fields
            trees.append(tr)
            continue
        sf = np.fromstring(_block_value(blk, "split_feature"), sep=" ").astype(np.int64)
        th = np.fromstring(_block_value(blk, "threshold"), sep=" ")
        dt = np.fromstring(_block_value(blk, "decision_type"), sep=" ").astype(np.int64)
        lc = np.fromstring(_block_value(blk, "left_child"), sep=" ").astype(np.int64)
        rc = np.fromstring(_block_value(blk, "right_child"), sep=" ").astype(np.int64)
        gain = np.fromstring(blk.get("split_gain", ""), sep=" ")
        # Covers: prefer the *_weight fields (we export float row mass there;
        # real LightGBM stores hessian sums — both are the TreeSHAP node
        # measure), falling back to the integer *_count fields.
        icnt = np.fromstring(
            blk.get("internal_weight", "") or blk.get("internal_count", ""), sep=" "
        )
        lcnt = np.fromstring(
            blk.get("leaf_weight", "") or blk.get("leaf_count", ""), sep=" "
        )
        ni = num_leaves - 1
        if any(len(a) != ni for a in (sf, th, dt, lc, rc)):
            raise ValueError(f"tree {bi}: inconsistent internal-node array lengths")

        is_cat_i = (dt & 1) != 0
        missing = (dt >> 2) & 3
        default_left = (dt & 2) != 0
        # missing_type None: LightGBM's predictor treats NaN like 0.0 there;
        # missing_type Zero: 0.0 AND NaN route per default_left (zero_missing)
        nan_left_i = np.where(missing == 0, 0.0 <= th, default_left)
        nan_left_i = np.where(is_cat_i, False, nan_left_i)  # cat NaN -> right
        zero_missing_i = (missing == 1) & ~is_cat_i

        # Categorical nodes: threshold = index into cat_boundaries /
        # cat_threshold; decode each node's bitset into raw value arrays.
        cat_sets = {}
        if np.any(is_cat_i) and num_cat == 0:
            raise ValueError(
                f"tree {bi}: categorical decision_type on a node but "
                "num_cat=0 (cat_boundaries/cat_threshold missing)"
            )
        if num_cat > 0 and np.any(is_cat_i):
            cbound = np.fromstring(
                _block_value(blk, "cat_boundaries"), sep=" "
            ).astype(np.int64)
            cwords = np.fromstring(
                _block_value(blk, "cat_threshold"), sep=" "
            ).astype(np.int64)
            for ii in np.nonzero(is_cat_i)[0]:
                c = int(th[ii])
                if not (0 <= c < num_cat):
                    raise ValueError(
                        f"tree {bi}: categorical threshold index {c} out of "
                        f"range for num_cat={num_cat}"
                    )
                words = cwords[cbound[c] : cbound[c + 1]]
                vals = [
                    wi * 32 + bit
                    for wi, w in enumerate(words)
                    for bit in range(32)
                    if (int(w) >> bit) & 1
                ]
                cat_sets[int(ii)] = np.asarray(vals, np.int64)

        # LightGBM indices -> slot layout: internal i -> slot i,
        # leaf j -> slot ni + j (any consistent layout works for routing).
        m = 2 * num_leaves - 1

        def slot_of(ref: int) -> int:
            return int(ref) if ref >= 0 else ni + (~int(ref))

        feat = np.zeros(m, np.int64)
        thr_s = np.full(m, np.inf)
        left_s = np.zeros(m, np.int64)
        right_s = np.zeros(m, np.int64)
        isl = np.zeros(m, bool)
        lval_s = np.zeros(m)
        nanl_s = np.ones(m, bool)
        zm_s = np.zeros(m, bool)
        cover_s = np.zeros(m)
        gain_s = np.zeros(m)
        isl[ni:] = True
        lval_s[ni:] = lv[:num_leaves]
        if len(lcnt) == num_leaves:
            cover_s[ni:] = lcnt
        for ii in range(ni):
            feat[ii] = sf[ii]
            # cat nodes: the file's threshold is a cat index, meaningless as
            # a numeric cut — keep +inf; routing uses the decoded value set
            thr_s[ii] = np.inf if ii in cat_sets else th[ii]
            left_s[ii] = slot_of(lc[ii])
            right_s[ii] = slot_of(rc[ii])
            nanl_s[ii] = bool(nan_left_i[ii])
            zm_s[ii] = bool(zero_missing_i[ii])
            if len(gain) == ni:
                gain_s[ii] = gain[ii]
            if len(icnt) == ni:
                cover_s[ii] = icnt[ii]
        tr = dict(feat=feat, thr=thr_s, left=left_s, right=right_s,
                  is_leaf=isl, lval=lval_s, nanl=nanl_s, zm=zm_s,
                  cover=cover_s, gain=gain_s, cat=cat_sets)
        if lin_fields is not None:
            tr["lin"] = lin_fields
        trees.append(tr)

    t = len(trees)
    m = max((len(tr["feat"]) for tr in trees), default=1)

    def pad(key, fill, dtype):
        out = np.full((t, m), fill, dtype=dtype)
        for ti, tr in enumerate(trees):
            out[ti, : len(tr[key])] = tr[key]
        return out

    # Linear-tree state: per-LEAF linear models land at their leaf SLOTS
    # (leaf j of a tree with ni internal nodes sits at slot ni + j). Trees
    # without a model (mixed files — LightGBM itself writes all-or-nothing)
    # fall back to const = plain leaf value with zero features, which makes
    # the linear predict path exact for them too.
    leaf_const = leaf_coeff = leaf_feat = None
    if any("lin" in tr for tr in trees):
        lmax = max(
            (
                max((len(a) for a in tr["lin"][1]), default=0)
                for tr in trees if "lin" in tr
            ),
            default=0,
        )
        lmax = max(lmax, 1)
        leaf_const = pad("lval", 0.0, np.float64)
        leaf_coeff = np.zeros((t, m, lmax), np.float64)
        leaf_feat = np.full((t, m, lmax), -1, np.int32)
        for ti, tr in enumerate(trees):
            if "lin" not in tr:
                continue
            m_t = len(tr["feat"])
            nl_t = (m_t + 1) // 2
            ni_t = m_t - nl_t
            const, lfeats, lcoefs = tr["lin"]
            leaf_const[ti, ni_t : ni_t + nl_t] = const[:nl_t]
            for j in range(nl_t):
                w = len(lfeats[j])
                leaf_feat[ti, ni_t + j, :w] = lfeats[j]
                leaf_coeff[ti, ni_t + j, :w] = lcoefs[j]

    # Booster-level categorical state: per-feature sorted value lists (the
    # union of every node's bitset on that feature) and per-node masks over
    # the value-bin ids (bin i+1 <-> values[i]; bin 0 = unseen/NaN).
    cat_nodes = cat_masks = cat_values = None
    if any(tr.get("cat") for tr in trees):
        feat_vals: dict = {}
        for tr in trees:
            for slot, vals in tr.get("cat", {}).items():
                f_ = int(tr["feat"][slot])
                feat_vals.setdefault(f_, set()).update(int(v) for v in vals)
        cat_values = {
            f_: np.asarray(sorted(s), np.float64) for f_, s in feat_vals.items()
        }
        bc = max(len(v) for v in cat_values.values()) + 1
        cat_nodes = np.zeros((t, m), bool)
        cat_masks = np.zeros((t, m, bc), bool)
        for ti, tr in enumerate(trees):
            for slot, vals in tr.get("cat", {}).items():
                f_ = int(tr["feat"][slot])
                idx = np.searchsorted(
                    cat_values[f_], np.asarray(vals, np.float64)
                )
                cat_nodes[ti, slot] = True
                cat_masks[ti, slot, idx + 1] = True

    booster = Booster(
        split_feature=pad("feat", 0, np.int32),
        # float64: LightGBM thresholds are f64 midpoints; narrowing here would
        # misroute rows whose f32-cast value falls between the f64 threshold
        # and its f32 rounding. Predict snaps to f32 DOWNWARD (booster.py
        # _thr_f32), which preserves the f64 decision set exactly for f32
        # inputs; residual contract: f64 inputs that straddle an f32 gap can
        # still differ (the predict kernel compares in f32).
        split_threshold=pad("thr", np.inf, np.float64),
        split_bin=np.zeros((t, m), np.int32),
        left_child=pad("left", 0, np.int32),
        right_child=pad("right", 0, np.int32),
        is_leaf=pad("is_leaf", False, bool),
        leaf_values=pad("lval", 0.0, np.float32),
        cover=pad("cover", 0.0, np.float32),
        split_gain=pad("gain", 0.0, np.float32),
        init_score=np.zeros(num_classes, np.float32),
        num_classes=num_classes,
        objective=objective,
        max_depth=_pointer_depth(trees),
        feature_names=feature_names
        or [f"Column_{j}" for j in range(max_feature_idx + 1)],
        nan_left=pad("nanl", True, bool),
        zero_missing=(
            pad("zm", False, bool)
            if any(np.any(tr["zm"]) for tr in trees) else None
        ),
        cat_nodes=cat_nodes,
        cat_masks=cat_masks,
        cat_values=cat_values,
        leaf_const=leaf_const,
        leaf_coeff=leaf_coeff,
        leaf_feat=leaf_feat,
    )
    return booster


def _pointer_depth(trees) -> int:
    depth = 1
    for tr in trees:
        left, right, isl = tr["left"], tr["right"], tr["is_leaf"]
        d = {0: 0}
        best = 0
        stack = [0]
        while stack:
            s = stack.pop()
            if isl[s]:
                best = max(best, d[s])
                continue
            for ch in (int(left[s]), int(right[s])):
                d[ch] = d[s] + 1
                stack.append(ch)
        depth = max(depth, best)
    return max(1, depth)
