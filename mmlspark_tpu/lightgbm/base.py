"""Shared LightGBM-style estimator machinery: param surface + train flow.

Param names/defaults mirror ``lightgbm/LightGBMParams.scala:13-251`` so a
reference user finds the identical knobs. The train flow re-creates
``LightGBMBase.train``/``innerTrain`` (``lightgbm/LightGBMBase.scala:26-213``):
column extraction, validation-indicator split, batch-mode chaining
(``numBatches``), and worker/mesh selection — minus everything the TPU
runtime makes obsolete (socket rendezvous, barrier mode, Kryo reduce).
"""

from __future__ import annotations

from typing import Any, List, Optional, Tuple

import numpy as np

from mmlspark_tpu.core.params import (
    HasFeaturesCol,
    HasInitScoreCol,
    HasLabelCol,
    HasPredictionCol,
    HasValidationIndicatorCol,
    HasWeightCol,
    Param,
    Params,
    ge,
    gt,
    in_range,
    one_of,
    to_bool,
    to_float,
    to_int,
    to_list_int,
    to_list_str,
    to_str,
)
from mmlspark_tpu.core.pipeline import Estimator, Model
from mmlspark_tpu.data.table import Table
from mmlspark_tpu.lightgbm.binning import BinMapper, bin_dataset
from mmlspark_tpu.lightgbm.booster import Booster
from mmlspark_tpu.lightgbm.train import TrainOptions, TrainResult, train


class LightGBMParams(
    HasFeaturesCol,
    HasLabelCol,
    HasPredictionCol,
    HasWeightCol,
    HasInitScoreCol,
    HasValidationIndicatorCol,
    Params,
):
    """The shared knob surface (LightGBMParams.scala)."""

    numIterations = Param("Number of boosting iterations", default=100, converter=to_int, validator=gt(0))
    learningRate = Param("Shrinkage rate", default=0.1, converter=to_float, validator=gt(0))
    numLeaves = Param("Max leaves per tree", default=31, converter=to_int, validator=gt(1))
    maxDepth = Param("Max tree depth (-1 = derive from numLeaves)", default=-1, converter=to_int)
    maxBin = Param("Max number of feature bins", default=255, converter=to_int, validator=gt(1))
    binSampleCount = Param(
        "Rows sampled when computing histogram bin edges "
        "(bin_construct_sample_cnt)",
        default=200000, converter=to_int, validator=gt(0),
    )
    maxBinByFeature = Param(
        "Per-feature max-bin override (empty = maxBin everywhere)",
        default=[], converter=to_list_int,
    )
    slotNames = Param(
        "Feature slot names (overrides the generated f0..fN; also the "
        "namespace categoricalSlotNames resolves against)",
        default=[], converter=to_list_str,
    )
    baggingFraction = Param("Row subsample fraction", default=1.0, converter=to_float, validator=in_range(0, 1))
    posBaggingFraction = Param(
        "Positive-class bagging fraction (binary; 1.0 = off)",
        default=1.0, converter=to_float, validator=in_range(0, 1),
    )
    negBaggingFraction = Param(
        "Negative-class bagging fraction (binary; 1.0 = off)",
        default=1.0, converter=to_float, validator=in_range(0, 1),
    )
    baggingFreq = Param("Resample bagging mask every k iterations (0=off)", default=0, converter=to_int, validator=ge(0))
    baggingSeed = Param("Bagging seed", default=3, converter=to_int)
    featureFraction = Param("Feature subsample fraction per tree", default=1.0, converter=to_float, validator=in_range(0, 1))
    lambdaL1 = Param("L1 regularization", default=0.0, converter=to_float, validator=ge(0))
    lambdaL2 = Param("L2 regularization", default=0.0, converter=to_float, validator=ge(0))
    minSumHessianInLeaf = Param("Minimum hessian sum per leaf", default=1e-3, converter=to_float, validator=ge(0))
    minDataInLeaf = Param("Minimum rows per leaf", default=20, converter=to_int, validator=ge(0))
    minGainToSplit = Param("Minimum gain to split", default=0.0, converter=to_float, validator=ge(0))
    maxDeltaStep = Param("Max leaf output magnitude (0=off)", default=0.0, converter=to_float, validator=ge(0))
    boostingType = Param(
        "gbdt, rf, dart, or goss", default="gbdt",
        converter=to_str, validator=one_of("gbdt", "rf", "dart", "goss"),
    )
    earlyStoppingRound = Param("Stop after k rounds without improvement (0=off)", default=0, converter=to_int, validator=ge(0))
    improvementTolerance = Param("Minimal delta counted as improvement", default=0.0, converter=to_float, validator=ge(0))
    metric = Param("Eval metric name ('' = objective default)", default="", converter=to_str)
    parallelism = Param(
        "data_parallel, voting_parallel, or serial",
        default="data_parallel", converter=to_str,
        validator=one_of("data_parallel", "voting_parallel", "serial"),
    )
    topK = Param("Top features for voting parallel", default=20, converter=to_int, validator=gt(0))
    topRate = Param("GOSS: kept fraction of large-gradient rows", default=0.2, converter=to_float, validator=in_range(0, 1))
    otherRate = Param("GOSS: sampled fraction of remaining rows", default=0.1, converter=to_float, validator=in_range(0, 1))
    dropRate = Param("DART: per-tree dropout probability", default=0.1, converter=to_float, validator=in_range(0, 1))
    growthPolicy = Param(
        "leafwise (LightGBM best-first, numLeaves-bounded) or depthwise "
        "(balanced levels — fewer, larger MXU passes)",
        default="leafwise", converter=to_str, validator=one_of("leafwise", "depthwise"),
    )
    leafBatch = Param(
        "Frontier leaves split per histogram pass under leafwise growth. "
        "NOTE: the default (8) is a batched APPROXIMATION of LightGBM's "
        "sequential best-first growth — up to 8 frontier leaves commit "
        "together, so default fits are not best-first-exact and differ "
        "slightly from the native engine's trees (bench AUC delta ~0.001, "
        "docs/perf_histogram.md). Set leafBatch=1 for the exact sequential "
        "algorithm (~4x slower), or leafBatchRatio=1.0 to keep batching "
        "only for exact gain ties. >1 costs ~one pass via the panel kernel",
        default=8, converter=to_int, validator=gt(0),
    )
    leafBatchRatio = Param(
        "Only batch leaves whose gain >= ratio * pass-best (0 = off; 1.0 "
        "reproduces exact best-first; ~0.2 measured to IMPROVE holdout AUC "
        "past both exact best-first and the CPU engine at ~20% extra fit "
        "time — docs/perf_histogram.md)",
        default=0.0, converter=to_float, validator=in_range(0, 1),
    )
    useQuantizedGrad = Param(
        "LightGBM's gradient-quantization training (use_quantized_grad): "
        "stochastically round g/h to an 8-bit per-tree grid so the "
        "histogram pass runs on the integer MXU (~15% faster fits at the "
        "bench shape, docs/perf_histogram.md). Per-bin sums stay unbiased "
        "and counts exact; off (default) keeps bit-exact bf16 stats. "
        "Requires the precomputed-U path (single-device, maxBin <= 255, U "
        "within the HBM budget) and < 2^24 rows (f32 count exactness) — "
        "otherwise training logs a warning and proceeds with exact stats. "
        "Depthwise fits with depth >= 7 exceed the 128-slot U panel "
        "budget on deep levels (> 42 frontier nodes) and fall back "
        "per-level to exact histograms, logged once per fit",
        default=False, converter=to_bool,
    )
    featureBundling = Param(
        "Exclusive Feature Bundling (native enable_bundle): greedily pack "
        "(near-)mutually-exclusive features into shared bin columns at "
        "binning time. Shrinks K = sum_f bins_f — the HBM re-stream that "
        "bounds every histogram pass — and the column count, so sparse/"
        "one-hot matrices fit the precomputed-U budget at row counts that "
        "previously overflowed it. Splits, model text, SHAP, and "
        "prediction stay in original feature space (emitted models are "
        "indistinguishable from unbundled fits; with zero bundling "
        "conflicts the tree structure is identical). Off by default — the "
        "native engine defaults on, but bundled histogram g/h for a "
        "member's default bin are recovered by subtraction, so float "
        "leaf values can differ in the last ulp from an unbundled fit",
        default=False, converter=to_bool,
    )
    maxConflictRate = Param(
        "EFB conflict budget (native max_conflict_rate): fraction of "
        "sampled rows where two bundled features may be simultaneously "
        "non-default. 0.0 = only perfectly exclusive features bundle "
        "(lossless); small values (e.g. 0.05) bundle harder at a bounded "
        "accuracy cost on conflict rows",
        default=0.0, converter=to_float, validator=in_range(0, 1),
    )
    categoricalSlotIndexes = Param(
        "Feature indexes treated as categorical (value-identity bins + "
        "LightGBM sorted-set split search)",
        default=[], converter=to_list_int,
    )
    categoricalSlotNames = Param(
        "Feature names treated as categorical (resolved against the "
        "assembled feature names, e.g. 'f3')",
        default=[], converter=to_list_str,
    )
    maxCatThreshold = Param(
        "Max categories in a categorical split's left set",
        default=32, converter=to_int, validator=gt(0),
    )
    catSmooth = Param(
        "Smoothing for the categorical g/h bin ordering",
        default=10.0, converter=to_float, validator=ge(0),
    )
    catL2 = Param(
        "Extra L2 applied to categorical split gains",
        default=10.0, converter=to_float, validator=ge(0),
    )
    maxCatToOnehot = Param(
        "Categorical features with at most this many seen categories use "
        "the one-vs-rest split search instead of the sorted-set algorithm "
        "(native LightGBM max_cat_to_onehot)",
        default=4, converter=to_int, validator=gt(0),
    )
    minDataPerGroup = Param(
        "Minimal rows a category needs to enter the sorted-set split "
        "search (native LightGBM min_data_per_group; the one-vs-rest "
        "path is exempt)",
        default=100, converter=to_int, validator=gt(0),
    )
    boostFromAverage = Param(
        "Start boosting from the label average init score (false = from 0)",
        default=True, converter=to_bool,
    )
    isProvideTrainingMetric = Param(
        "Record the train-set metric each iteration (evals['training'])",
        default=False, converter=to_bool,
    )
    numBatches = Param("Split training into sequential batches (0=off)", default=0, converter=to_int, validator=ge(0))
    modelString = Param("Warm-start booster string", default="", converter=to_str)
    verbosity = Param("Verbosity", default=-1, converter=to_int)
    seed = Param("Master seed", default=0, converter=to_int)
    featuresShapCol = Param("Output column for SHAP values ('' = off)", default="", converter=to_str)
    leafPredictionCol = Param("Output column for leaf indices ('' = off)", default="", converter=to_str)
    useSingleDatasetMode = Param("Accepted for API parity (dataset is always host-resident)", default=True, converter=to_bool)
    numTasks = Param("Override number of mesh shards (0 = all devices)", default=0, converter=to_int, validator=ge(0))
    numExecutors = Param(
        "Run the histogram-binning prepass as partitioned tasks on this "
        "many fault-tolerant executors (mmlspark_tpu.runtime): bounded "
        "retries, heartbeat-loss re-dispatch, and lineage recompute apply, "
        "and the binned matrix is bit-identical to the inline pass. 0 "
        "(default) bins inline; an ambient runtime.policy() also activates "
        "the scheduler",
        default=0, converter=to_int, validator=ge(0),
    )
    numProcesses = Param(
        "Run the fit itself across this many real worker processes under a "
        "supervised gang (mmlspark_tpu.runtime.procgroup): each process "
        "fits a contiguous row shard, histograms allreduce over sockets, "
        "and a process killed mid-fit triggers gang recovery that resumes "
        "from the fit journal with zero re-execution of committed "
        "iterations. The distributed analog of the reference's "
        "per-executor native fit. 0/1 (default) fits in-process. Process "
        "mode restricts options (no bagging/GOSS/dart, no validation "
        "sets); see lightgbm.procfit.validate_process_options",
        default=0, converter=to_int, validator=ge(0),
    )

    def _objective_name(self) -> str:
        raise NotImplementedError

    def _extra_train_options(self) -> dict:
        return {}

    def _make_options(self, num_class: int = 1) -> TrainOptions:
        kwargs = dict(
            objective=self._objective_name(),
            num_iterations=self.getNumIterations(),
            learning_rate=self.getLearningRate(),
            num_leaves=self.getNumLeaves(),
            max_depth=self.getMaxDepth(),
            max_bin=self.getMaxBin(),
            lambda_l1=self.getLambdaL1(),
            lambda_l2=self.getLambdaL2(),
            min_data_in_leaf=self.getMinDataInLeaf(),
            min_sum_hessian_in_leaf=self.getMinSumHessianInLeaf(),
            min_gain_to_split=self.getMinGainToSplit(),
            bagging_fraction=self.getBaggingFraction(),
            pos_bagging_fraction=self.getPosBaggingFraction(),
            neg_bagging_fraction=self.getNegBaggingFraction(),
            bagging_freq=self.getBaggingFreq(),
            feature_fraction=self.getFeatureFraction(),
            max_delta_step=self.getMaxDeltaStep(),
            num_class=num_class,
            boosting_type=self.getBoostingType(),
            metric=self.getMetric() or None,
            early_stopping_round=self.getEarlyStoppingRound(),
            improvement_tolerance=self.getImprovementTolerance(),
            seed=self.getSeed(),
            growth=self.getGrowthPolicy(),
            leaf_batch=self.getLeafBatch(),
            leaf_batch_ratio=self.getLeafBatchRatio(),
            use_quantized_grad=self.getUseQuantizedGrad(),
            tree_learner=(
                "voting_parallel"
                if self.getParallelism() == "voting_parallel"
                else "data_parallel"
            ),
            top_k=self.getTopK(),
            top_rate=self.getTopRate(),
            other_rate=self.getOtherRate(),
            drop_rate=self.getDropRate(),
            max_cat_threshold=self.getMaxCatThreshold(),
            cat_smooth=self.getCatSmooth(),
            cat_l2=self.getCatL2(),
            max_cat_to_onehot=self.getMaxCatToOnehot(),
            min_data_per_group=self.getMinDataPerGroup(),
            boost_from_average=self.getBoostFromAverage(),
            provide_training_metric=self.getIsProvideTrainingMetric(),
        )
        kwargs.update(self._extra_train_options())
        return TrainOptions(**kwargs)


def extract_features(table: Table, features_col: str, num_features: int = 0):
    """Dense (N, F) float64 — or a :class:`CSRMatrix` when the column holds
    per-row (indices, values) sparse tuples (the
    ``LGBM_DatasetCreateFromCSRSpark`` ingest path,
    LightGBMUtils.scala:246-266). ``num_features`` pins the sparse feature
    count (pass the trained F at predict/valid time so a batch whose highest
    explicit index is smaller does not silently shrink the matrix)."""
    from mmlspark_tpu.data.sparse import csr_column_to_matrix, is_sparse_column

    feats = table.column(features_col)
    if feats.dtype == object:
        if is_sparse_column(feats):
            return csr_column_to_matrix(feats, num_features=num_features)
        feats = np.stack([np.asarray(row, dtype=np.float64) for row in feats])
    return np.asarray(feats, dtype=np.float64)


class LightGBMBase(LightGBMParams, Estimator):
    """Shared fit flow (LightGBMBase.scala:26-213)."""

    def _num_classes(self, y: np.ndarray) -> int:
        return 1

    def _adjust_weights(self, y: np.ndarray, w):
        """Label-dependent weight hook (isUnbalance lives in the classifier)."""
        return w

    def _select_mesh(self):
        """Mesh selection = the ClusterUtil worker-count computation
        (LightGBMBase.scala:166-176): all devices on the data axis unless
        `numTasks` caps it or parallelism is serial."""
        import jax

        if self.getParallelism() == "serial":
            return None
        n = len(jax.devices())
        if self.getNumTasks() > 0:
            n = min(n, self.getNumTasks())
        if n <= 1:
            return None
        from mmlspark_tpu.parallel.mesh import best_mesh

        return best_mesh(n)

    def _prepare(self, table: Table, num_features: int = 0):
        X = extract_features(table, self.getFeaturesCol(), num_features)
        y = np.asarray(table.column(self.getLabelCol()), dtype=np.float64)
        w = None
        if self.isSet("weightCol"):
            w = np.asarray(table.column(self.getWeightCol()), dtype=np.float64)
        init = None
        if self.isSet("initScoreCol"):
            init = np.asarray(table.column(self.getInitScoreCol()), dtype=np.float64)
        return X, y, w, init

    def set_delegate(self, *callbacks) -> "LightGBMBase":
        """Attach training delegates
        (:class:`~mmlspark_tpu.lightgbm.callbacks.TrainingCallback`) — the
        ``LightGBMDelegate.scala`` hook surface. Delegates are live objects,
        not Params: they do not serialize with the stage (matching the
        reference, whose delegate is a transient field)."""
        self._callbacks = list(callbacks)
        return self

    @property
    def callbacks(self):
        return list(getattr(self, "_callbacks", []))

    def _bin_dataset(self, X, opts, cat_slots):
        """Histogram-discretize the training matrix. With `numExecutors` > 0
        or an ambient :func:`mmlspark_tpu.runtime.policy`, the per-row pass
        runs as partitioned tasks on the fault-tolerant scheduler — the
        Spark analog of binning inside executors — and is bit-identical to
        the inline path (apply_bins is row-pure). Scheduler metrics land on
        ``self._runtime_metrics`` for inspection."""
        kwargs = dict(
            max_bin=opts.max_bin,
            categorical_features=sorted(cat_slots) or None,
            sample_cnt=self.getBinSampleCount(),
            max_bin_by_feature=self.getMaxBinByFeature() or None,
            # EFB is a histogram-layout optimization; the voting reducer
            # ships per-feature vote sets in original ids, so bundling is
            # gated to the non-voting learners.
            feature_bundling=(
                self.getFeatureBundling()
                and self.getParallelism() != "voting_parallel"
            ),
            max_conflict_rate=self.getMaxConflictRate(),
        )
        from mmlspark_tpu import runtime

        ambient = runtime.current_policy()
        if ambient is None and self.getNumExecutors() <= 0:
            return bin_dataset(X, **kwargs)
        from mmlspark_tpu.lightgbm.binning import bin_dataset_partitioned

        pol = ambient or runtime.SchedulerPolicy(
            max_workers=self.getNumExecutors(), seed=self.getSeed()
        )
        from mmlspark_tpu.observability.tracing import get_tracer

        # durable binning: under MMLSPARK_TPU_CHECKPOINT_DIR each
        # partition's binned block checkpoints as it completes, so a
        # killed fit rerun with the same params + data resumes with zero
        # re-execution of finished partitions
        journal_root = journal_key = None
        ckpt_root = runtime.default_checkpoint_dir()
        if ckpt_root is not None:
            import os

            journal_root = os.path.join(ckpt_root, "binning")
            journal_key = self._checkpoint_key(X, kwargs)
        self._runtime_metrics = runtime.RuntimeMetrics()
        with get_tracer().span(
            "lightgbm.binning", rows=int(getattr(X, "shape", (0,))[0])
        ):
            bins, mapper = bin_dataset_partitioned(
                X, policy=pol, metrics=self._runtime_metrics,
                journal_root=journal_root, journal_key=journal_key, **kwargs
            )
        self._runtime_metrics.log(prefix="binning: ")
        return bins, mapper

    def _checkpoint_key(self, X, bin_kwargs: dict) -> str:
        """Identity of one durable fit: estimator class + binning params +
        a data fingerprint (shape + content CRC). A rerun with identical
        inputs resumes; any change lands in a fresh journal directory."""
        import zlib

        arr = np.ascontiguousarray(np.asarray(X, dtype=np.float64))
        crc = zlib.crc32(arr.view(np.uint8).reshape(-1)) & 0xFFFFFFFF
        parts = [type(self).__name__, f"seed{self.getSeed()}"]
        parts += [f"{k}={bin_kwargs[k]}" for k in sorted(bin_kwargs)]
        parts.append(f"X{arr.shape[0]}x{arr.shape[1] if arr.ndim > 1 else 1}")
        parts.append(f"{crc:08x}")
        return "-".join(parts)

    def _fit(self, table: Table) -> "LightGBMModelBase":
        # Validation split by indicator column (LightGBMBase.scala:196-197).
        valid_table = None
        if self.isSet("validationIndicatorCol"):
            ind = np.asarray(table.column(self.getValidationIndicatorCol()), dtype=bool)
            valid_table, table = table.filter(ind), table.filter(~ind)

        warm = self.getModelString()
        prev = Booster.from_string(warm) if warm else None
        # Warm start: pin sparse extraction to the previous booster's feature
        # count so its trees never gather past the new batch's explicit width.
        X, y, w, init = self._prepare(
            table, num_features=prev.num_features if prev else 0
        )
        w = self._adjust_weights(y, w)
        num_class = self._num_classes(y)
        opts = self._make_options(num_class)

        # Feature slot names: slotNames overrides the generated f0..fN
        # (LightGBMParams slotNames) and is the namespace categorical names
        # resolve against.
        num_features = X.shape[1] if hasattr(X, "shape") else X.num_features
        slot_names = self.getSlotNames() or []
        if slot_names and len(slot_names) != num_features:
            raise ValueError(
                f"slotNames has {len(slot_names)} entries for "
                f"{num_features} features"
            )
        feature_names = list(slot_names) or [f"f{i}" for i in range(num_features)]

        # Categorical slot resolution (LightGBMBase.scala:148-156): indexes
        # union names resolved against the feature slot names.
        cat_slots = set(self.getCategoricalSlotIndexes() or [])
        names = self.getCategoricalSlotNames() or []
        bad = sorted(i for i in cat_slots if not (0 <= i < num_features))
        if bad:
            raise ValueError(
                f"categoricalSlotIndexes out of range for {num_features} "
                f"features: {bad}"
            )
        if names:
            name_to_idx = {nm: i for i, nm in enumerate(feature_names)}
            for nm in names:
                if nm not in name_to_idx:
                    raise ValueError(
                        f"categoricalSlotNames: unknown feature name {nm!r}"
                    )
                cat_slots.add(name_to_idx[nm])

        bins, mapper = self._bin_dataset(X, opts, cat_slots)
        valid_sets = []
        if valid_table is not None and valid_table.num_rows > 0:
            Xv, yv, wv, _ = self._prepare(valid_table, num_features=X.shape[1])
            bv, _ = bin_dataset(Xv, mapper=mapper)
            valid_sets.append(("valid_0", bv, yv, wv))

        mesh = self._select_mesh()
        init_margins = None
        if init is not None:
            init_margins = np.asarray(init, dtype=np.float32)
            if init_margins.ndim == 1:
                init_margins = init_margins[:, None]
        if prev is not None:
            init_margins = prev.raw_margin(X)

        num_batches = self.getNumBatches()
        num_processes = self.getNumProcesses()
        if num_processes > 1:
            result = self._fit_process_group(
                bins, y, w, init_margins, opts, mapper, valid_sets,
                feature_names, num_processes, num_batches, X,
            )
        elif num_batches and num_batches > 1:
            result = self._fit_batches(
                bins, y, w, init_margins, opts, mapper, mesh, valid_sets, feature_names,
                num_batches,
            )
        else:
            result = train(
                bins, y, opts, w=w, init_margins=init_margins,
                valid_sets=valid_sets, mapper=mapper, mesh=mesh,
                feature_names=feature_names, callbacks=self.callbacks,
            )
        model = self._make_model(result)
        model.parent = self
        # per-iteration metric histories (valid sets + 'training' when
        # isProvideTrainingMetric) — transient, like the reference's
        # delegate-observed metrics
        model._train_evals = result.evals
        from mmlspark_tpu.observability.events import ModelCommitted, get_bus

        # durable model commit: atomic-rename versioned write under the
        # checkpoint root, so a warm-restarting server's recovery scan
        # (ModelStore.latest) never observes a torn model file
        version = None
        from mmlspark_tpu.runtime.journal import ModelStore, default_checkpoint_dir

        ckpt_root = default_checkpoint_dir()
        if ckpt_root is not None:
            import os

            store = ModelStore(os.path.join(ckpt_root, "models"))
            version = store.commit(
                model.get_model_string(), name=type(model).__name__.lower()
            )
        bus = get_bus()
        if bus.active:
            detail = (
                f"{result.booster.num_trees} trees"
                if getattr(result, "booster", None) is not None else ""
            )
            if version is not None:
                detail = f"{detail} v{version}".strip()
            bus.publish(ModelCommitted(
                model=type(model).__name__, detail=detail,
            ))
        return model

    def _fit_process_group(
        self, bins, y, w, init_margins, opts, mapper, valid_sets,
        feature_names, num_processes, num_batches, X,
    ) -> TrainResult:
        """`numProcesses` > 1: hand the fit to a supervised worker gang
        (:func:`mmlspark_tpu.lightgbm.procfit.fit_process_group`). The
        feature combinations a shard-local process cannot reproduce are
        rejected up front rather than silently diverging."""
        from mmlspark_tpu.lightgbm.procfit import fit_process_group

        if num_batches and num_batches > 1:
            raise ValueError("numProcesses and numBatches are exclusive")
        if valid_sets:
            raise ValueError(
                "process-parallel fit does not support validation sets "
                "(validation is driver-side; score the model after fit)"
            )
        if init_margins is not None:
            raise ValueError(
                "process-parallel fit does not support initScoreCol or "
                "modelString warm start"
            )
        if self.callbacks:
            raise ValueError(
                "training delegates cannot cross the process boundary; "
                "unset delegates or numProcesses"
            )
        journal_root = journal_key = None
        from mmlspark_tpu.runtime.journal import default_checkpoint_dir

        ckpt_root = default_checkpoint_dir()
        if ckpt_root is not None:
            import os

            journal_root = os.path.join(ckpt_root, "procfit")
            journal_key = self._checkpoint_key(
                X, {"procs": num_processes, "iters": opts.num_iterations}
            )
        result = fit_process_group(
            None, y, opts, w=w, num_processes=num_processes,
            feature_names=feature_names, bins=bins, mapper=mapper,
            journal_root=journal_root,
            journal_key=journal_key or "procfit",
        )
        self._process_fit = result  # epochs/exit statuses for inspection
        return TrainResult(
            booster=result.booster, evals={}, best_iteration=-1
        )

    def _fit_batches(
        self, bins, y, w, init_margins, opts, mapper, mesh, valid_sets,
        feature_names, num_batches,
    ) -> TrainResult:
        """Batch-mode training: boosters chained across row batches with
        margin carry-over (LightGBMBase.scala:26-48)."""
        n = len(y)
        edges = np.linspace(0, n, num_batches + 1).astype(int)
        boosters: List[Booster] = []
        merged_evals: dict = {}
        result = None
        for bi in range(num_batches):
            lo, hi = edges[bi], edges[bi + 1]
            if hi <= lo:
                continue
            im = None if init_margins is None else init_margins[lo:hi]
            if boosters:
                # margins of previous ensemble on this batch's rows
                im = _ensemble_margin(boosters, bins[lo:hi], mapper)
            result = train(
                bins[lo:hi], y[lo:hi], opts,
                w=None if w is None else w[lo:hi],
                init_margins=im, valid_sets=valid_sets, mapper=mapper, mesh=mesh,
                feature_names=feature_names,
            )
            boosters.append(result.booster)
            # metric histories concatenate across the chained batches (each
            # batch's scores are its delta booster on its own rows)
            for name, metrics in result.evals.items():
                dst = merged_evals.setdefault(name, {})
                for mname, scores in metrics.items():
                    dst.setdefault(mname, []).extend(scores)
        merged = _merge_boosters(boosters)
        return TrainResult(booster=merged, evals=merged_evals, best_iteration=result.best_iteration)

    def _make_model(self, result: TrainResult) -> "LightGBMModelBase":
        raise NotImplementedError


def _ensemble_margin(boosters: List[Booster], bins: np.ndarray, mapper: BinMapper) -> np.ndarray:
    import jax
    import jax.numpy as jnp

    from mmlspark_tpu.lightgbm.train import _bundle_route_consts, _route_binned

    spec = getattr(mapper, "bundles", None)
    consts = _bundle_route_consts(spec) if spec is not None else None
    total = None
    for b in boosters:
        # Route in bin space (bins built with the shared mapper; EFB-packed
        # when the mapper carries a bundle plan — trees are in original ids).
        def margin_fn(bv):
            m = jnp.broadcast_to(
                jnp.asarray(b.init_score)[None, :], (bv.shape[0], b.num_classes)
            )
            for t in range(b.num_trees):
                leaf = _route_binned(
                    bv,
                    jnp.asarray(b.split_feature[t]),
                    jnp.asarray(b.split_bin[t]),
                    jnp.asarray(b.left_child[t]),
                    jnp.asarray(b.right_child[t]),
                    jnp.asarray(b.is_leaf[t]),
                    b.max_depth,
                    cat_node=(
                        None if b.cat_nodes is None
                        else jnp.asarray(b.cat_nodes[t])
                    ),
                    cat_mask=(
                        None if b.cat_masks is None
                        else jnp.asarray(b.cat_masks[t])
                    ),
                    bundle_consts=consts,
                )
                m = m.at[:, t % b.num_classes].add(jnp.asarray(b.leaf_values[t])[leaf])
            return m

        m = np.asarray(jax.jit(margin_fn)(jnp.asarray(bins, dtype=jnp.int32)))
        total = m if total is None else total + m
    return total


def _merge_boosters(boosters: List[Booster]) -> Booster:
    """Concatenate chained batch boosters into one additive model
    (the `LGBM_BoosterMerge` analogue, TrainUtils.scala:165-167)."""
    if len(boosters) == 1:
        return boosters[0]
    first = boosters[0]

    def cat(field, pad=0):
        arrs = [getattr(b, field) for b in boosters]
        if any(a is None for a in arrs):
            return None
        arrs = [np.asarray(a) for a in arrs]
        # Pad trailing (node/bitmask) axes to the widest booster before
        # stacking trees: a model-text round-trip shrinks node arrays to
        # each tree's true width, so chained-fit boosters legitimately
        # disagree on M. Dead slots are unreachable (child indices only
        # point inside the original tree); is_leaf pads True so even an
        # accidental visit terminates.
        ndim = arrs[0].ndim
        target = tuple(max(a.shape[d] for a in arrs) for d in range(1, ndim))
        padded = []
        for a in arrs:
            widths = [(0, 0)] + [
                (0, t - a.shape[d + 1]) for d, t in enumerate(target)
            ]
            if any(w for _, w in widths):
                a = np.pad(a, widths, constant_values=pad)
            padded.append(a)
        return np.concatenate(padded)

    return Booster(
        split_feature=cat("split_feature"),
        split_bin=cat("split_bin"),
        split_threshold=cat("split_threshold"),
        left_child=cat("left_child"),
        right_child=cat("right_child"),
        is_leaf=cat("is_leaf", pad=1),
        leaf_values=cat("leaf_values"),
        cover=cat("cover"),
        split_gain=cat("split_gain"),
        init_score=first.init_score,
        num_classes=first.num_classes,
        objective=first.objective,
        max_depth=max(b.max_depth for b in boosters),
        best_iteration=-1,
        feature_names=first.feature_names,
        bin_edges=first.bin_edges,
        nan_left=cat("nan_left"),
        zero_missing=cat("zero_missing"),
        cat_nodes=cat("cat_nodes"),
        cat_masks=cat("cat_masks"),
        cat_values=first.cat_values,
    )


class LightGBMModelBase(HasFeaturesCol, HasPredictionCol, Model):
    """Shared model surface: booster access, native-model serde, leaf output."""

    boosterData = Param("Fitted booster state", is_complex=True)
    leafPredictionCol = Param("Output column for leaf indices ('' = off)", default="", converter=to_str)
    featuresShapCol = Param("Output column for SHAP values ('' = off)", default="", converter=to_str)

    @property
    def booster(self) -> Booster:
        return Booster.from_dict(self.getBoosterData())

    def set_booster(self, booster: Booster) -> None:
        self.set("boosterData", booster.to_dict())

    def get_model_string(self) -> str:
        return self.booster.model_to_string()

    def save_native_model(self, path: str) -> None:
        """`saveNativeModel` (LightGBMClassifier.scala:172-180)."""
        with open(path, "w") as f:
            f.write(self.get_model_string())

    @classmethod
    def from_model_string(cls, text: str, **kwargs) -> "LightGBMModelBase":
        """Build a model from native model text — the loader a
        warm-restarting server hands to
        :func:`mmlspark_tpu.serving.recover_model`."""
        m = cls(**kwargs)
        m.set_booster(Booster.from_string(text))
        return m

    @classmethod
    def load_native_model(cls, path: str, **kwargs) -> "LightGBMModelBase":
        with open(path) as f:
            return cls.from_model_string(f.read(), **kwargs)

    def get_feature_importances(self, importance_type: str = "split") -> np.ndarray:
        return self.booster.feature_importances(importance_type)

    def _with_leaf_col(self, table: Table, X: np.ndarray) -> Table:
        if self.getLeafPredictionCol():
            leaves = self.booster.predict_leaf(X).astype(np.float64)
            table = table.with_column(self.getLeafPredictionCol(), leaves)
        if self.getFeaturesShapCol():
            # (N, C, F+1) → (N, C*(F+1)) — LightGBM's contrib layout: per
            # class, per-feature contributions then the bias term
            # (LightGBMBooster.scala:240-275 featuresShap).
            shap = self.booster.features_shap(X)
            n = shap.shape[0]
            table = table.with_column(
                self.getFeaturesShapCol(), shap.reshape(n, -1).astype(np.float64)
            )
        return table
