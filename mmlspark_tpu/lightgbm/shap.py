"""Path-dependent TreeSHAP for pointer-layout boosters.

The ``featuresShap`` capability of the reference booster
(``lightgbm/LightGBMBooster.scala:240-275`` — per-row
``LGBM_BoosterPredictForMatSingle`` with ``predict_contrib``). LightGBM's
native implementation is Lundberg et al.'s polynomial-time path-dependent
TreeSHAP; this is the same algorithm, vectorized over the whole query batch:

- path *z* entries (cold-path cover fractions) are products of training-cover
  ratios — identical for every row, kept as scalars;
- path *o* entries (hot-path fractions) and the permutation weights *w*
  depend on each row's decision path — kept as (N,)-vectors, so one Python
  recursion over the ≤2·num_leaves-1 tree nodes explains every row at once.

Explanation is a host/explain-path API (the reference scores it row-by-row
over JNI); the hot training loop stays on-chip.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np


def tree_shap(booster, X: np.ndarray, num_iteration: Optional[int] = None) -> np.ndarray:
    """(N, C, F+1): per-feature SHAP plus bias term (last column).
    ``out.sum(-1) == booster.raw_margin(X)`` up to float tolerance."""
    t_used = booster._used_trees(num_iteration)
    n, f = X.shape
    c = booster.num_classes
    has_cat = booster.has_categorical
    Xr = booster._cat_binned(X) if has_cat else X  # cat cols -> value-bin ids
    phi = np.zeros((n, c, f + 1), dtype=np.float64)
    phi[:, :, f] += np.asarray(booster.init_score, dtype=np.float64)[None, :]
    for t in range(t_used):
        contrib, bias = _shap_one_tree(
            booster.split_feature[t],
            booster.split_threshold[t],
            booster.left_child[t],
            booster.right_child[t],
            booster.is_leaf[t],
            booster.leaf_values[t],
            booster.cover[t],
            Xr,
            nan_left=None if booster.nan_left is None else booster.nan_left[t],
            cat_node=None if not has_cat else booster.cat_nodes[t],
            cat_mask=None if not has_cat else booster.cat_masks[t],
            zero_missing=(
                None if booster.zero_missing is None else booster.zero_missing[t]
            ),
        )
        cls = t % c
        phi[:, cls, :f] += contrib
        phi[:, cls, f] += bias
    return phi


def _shap_one_tree(feat, thr, left, right, is_leaf, leaf_val, cover, X,
                   nan_left=None, cat_node=None, cat_mask=None,
                   zero_missing=None):
    n, num_features = X.shape
    phi = np.zeros((n, num_features), dtype=np.float64)

    # Hot child per row per node (row's own decision), precomputed in
    # float32 with the SAME downward f64→f32 threshold snap as the jitted
    # predict path (booster._thr_f32), so boundary values route identically
    # and additivity (sum == raw_margin) holds exactly — round-to-nearest
    # narrowing here would diverge from predict on imported f64 thresholds.
    from mmlspark_tpu.lightgbm.booster import _thr_f32

    xv = X[:, feat].astype(np.float32)  # (N, M)
    nl = np.ones(len(feat), bool) if nan_left is None else np.asarray(nan_left, bool)
    miss = np.isnan(xv)
    if zero_missing is not None and np.any(zero_missing):
        from mmlspark_tpu.lightgbm.booster import K_ZERO_THRESHOLD

        miss = miss | (
            np.asarray(zero_missing, bool)[None, :]
            & (np.abs(xv) <= K_ZERO_THRESHOLD)
        )
    goes_left = np.where(miss, nl[None, :], xv <= _thr_f32(thr)[None, :])
    if cat_node is not None and np.any(cat_node):
        # categorical columns of X hold value-bin ids (tree_shap pre-bins);
        # left iff the node's set contains the bin — same rule as predict
        bc = cat_mask.shape[-1]
        xb = np.clip(np.nan_to_num(xv, nan=0.0), 0, bc - 1).astype(np.int64)
        gl_cat = cat_mask[np.arange(len(feat))[None, :], xb]
        goes_left = np.where(cat_node[None, :], gl_cat, goes_left)

    root_cover = max(float(cover[0]), 1e-12)

    # Expected value over the training distribution = bias column.
    bias = float(np.sum(np.where(is_leaf, leaf_val * cover, 0.0)) / root_cover)

    def extend(d: List[int], z: List[float], o, w, pz: float, po, pi: int):
        p = len(d)
        d = d + [pi]
        z = z + [pz]
        o = np.concatenate([o, po[:, None]], axis=1)
        w = np.concatenate(
            [w, np.full((n, 1), 1.0 if p == 0 else 0.0)], axis=1
        )
        for i in range(p - 1, -1, -1):
            w[:, i + 1] += po * w[:, i] * (i + 1) / (p + 1)
            w[:, i] = pz * w[:, i] * (p - i) / (p + 1)
        return d, z, o, w

    def unwind(d, z, o, w, i):
        p = len(d) - 1
        o_i = o[:, i]
        z_i = z[i]
        hot = o_i != 0.0
        o_safe = np.where(hot, o_i, 1.0)
        z_safe = z_i if z_i != 0.0 else 1.0
        nn = w[:, p].copy()
        w = w.copy()
        for j in range(p - 1, -1, -1):
            t_ = w[:, j].copy()
            w_hot = nn * (p + 1) / ((j + 1) * o_safe)
            nn_hot = t_ - w_hot * z_i * (p - j) / (p + 1)
            w_cold = t_ * (p + 1) / (z_safe * (p - j))
            w[:, j] = np.where(hot, w_hot, w_cold)
            nn = np.where(hot, nn_hot, nn)
        # Weights are recomputed in place over 0..p-1 (last column drops);
        # the feature/fraction entries shift out element i.
        d = [x for k, x in enumerate(d) if k != i]
        z = [x for k, x in enumerate(z) if k != i]
        o = np.delete(o, i, axis=1)
        w = w[:, :-1]
        return d, z, o, w

    def unwound_sum(z, o, w, i):
        p = len(z) - 1
        o_i = o[:, i]
        z_i = z[i]
        hot = o_i != 0.0
        o_safe = np.where(hot, o_i, 1.0)
        z_safe = z_i if z_i != 0.0 else 1.0
        total = np.zeros(n, dtype=np.float64)
        nn = w[:, p].copy()
        for j in range(p - 1, -1, -1):
            t_hot = nn * (p + 1) / ((j + 1) * o_safe)
            total += np.where(hot, t_hot, w[:, j] * (p + 1) / (z_safe * (p - j)))
            nn = np.where(hot, w[:, j] - t_hot * z_i * (p - j) / (p + 1), nn)
        return total

    def recurse(node: int, d, z, o, w, pz: float, po, pi: int):
        d, z, o, w = extend(d, z, o, w, pz, po, pi)
        if is_leaf[node]:
            v = float(leaf_val[node])
            for i in range(1, len(d)):
                s = unwound_sum(z, o, w, i)
                phi[:, d[i]] += s * (o[:, i] - z[i]) * v
            return
        split = int(feat[node])
        lc, rc = int(left[node]), int(right[node])
        cov = max(float(cover[node]), 1e-12)
        rl = float(cover[lc]) / cov
        rr = float(cover[rc]) / cov
        hot_left = goes_left[:, node]  # (N,) this row's hot child is left

        iz, io = 1.0, np.ones(n, dtype=np.float64)
        k = next((i for i in range(1, len(d)) if d[i] == split), -1)
        if k >= 0:
            iz, io = z[k], o[:, k].copy()
            d, z, o, w = unwind(d, z, o, w, k)
        # Left child: hot for rows going left, cold (o=0) otherwise.
        if float(cover[lc]) > 0:
            recurse(lc, list(d), list(z), o.copy(), w.copy(),
                    iz * rl, np.where(hot_left, io, 0.0), split)
        if float(cover[rc]) > 0:
            recurse(rc, list(d), list(z), o.copy(), w.copy(),
                    iz * rr, np.where(hot_left, 0.0, io), split)

    recurse(
        0,
        [],
        [],
        np.empty((n, 0), dtype=np.float64),
        np.empty((n, 0), dtype=np.float64),
        1.0,
        np.ones(n, dtype=np.float64),
        -1,
    )
    return phi, bias
