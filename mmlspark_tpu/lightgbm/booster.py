"""Booster: the trained forest, with jitted batch predict, SHAP, and serde.

Equivalent of ``LightGBMBooster`` (reference ``lightgbm/LightGBMBooster.scala``):
score / predictLeaf / featuresShap / raw-margin output, iteration slicing for
early stopping, string serde. Instead of per-row JNI calls with ThreadLocal
native buffers (``LightGBMBooster.scala:37-128``), prediction is one jitted
XLA program over the whole batch.

Tree layout — pointer-based node arrays (per tree, ``M`` node slots), the
layout LightGBM's own model text uses, supporting both level-wise and
LightGBM's defining *leaf-wise* growth (unbalanced trees would explode an
implicit heap: depth can reach ``num_leaves - 1``):

- ``split_feature``   (M,) int32   — internal nodes; 0 at leaves/dead slots
- ``split_threshold`` (M,) float32 — raw-value "go left if NaN or x <= t";
                                      +inf at dead slots (float64 on imported
                                      LightGBM models; predict snaps DOWN to
                                      f32, see ``_thr_f32``)
- ``split_bin``       (M,) int32   — binned-space threshold (training path)
- ``left_child`` / ``right_child`` (M,) int32 — slot indices
- ``is_leaf``         (M,) bool
- ``leaf_values``     (M,) float32 — learning-rate-scaled outputs at leaves
- ``cover``           (M,) float32 — training rows through the node (TreeSHAP)
- ``split_gain``      (M,) float32 — realized gain (importance_type="gain")

Routing is ``max_depth`` rounds of gathers — no data-dependent control flow;
rows that reach a leaf early simply stay there (``is_leaf`` gate).

Forest arrays stack trees as (num_trees, M) where tree ``i*C + c`` is
iteration i, class c (LightGBM's tree ordering).
"""

from __future__ import annotations

import dataclasses
import json
from functools import partial
from typing import Any, Dict, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from jax import lax

from mmlspark_tpu.lightgbm.binning import BinMapper

#: LightGBM's kZeroThreshold: |x| <= this counts as zero (zero_as_missing).
K_ZERO_THRESHOLD = 1e-35

#: Size gate for the dense (T*I, Fc*Bc) categorical mask matrix: above
#: this, predict uses the memory-bounded gather kernel instead.
_CM_BYTES_CAP = 128 << 20

def _predict_chunk_rows(
    t: int, i: int, budget_bytes: int = 256 << 20, extra_row_bytes: int = 0
) -> int:
    """Rows per predict dispatch. The budget covers the (N, T, I) decision
    tensor AND its same-shape temporaries (D, score, match ≈ 4x), plus any
    caller-declared per-row transients (``extra_row_bytes`` — the
    categorical path's stacked one-hot and decision matrices), so huge
    forests shrink the chunk rather than OOM; no floor overrides it."""
    per_row = 16 * max(t * i, 1) + max(extra_row_bytes, 0)
    return max(1, min(131072, budget_bytes // per_row))


def _cat_row_bytes(cat) -> int:
    """Per-row transient bytes of the categorical predict kernels, for the
    chunk budget: the matmul path materializes a bf16 (Fc*Bc, N) one-hot +
    an f32 (T*I, N) decision matrix; the gather path an int32 (N, T, I)
    index tensor."""
    if cat[0] == "matmul":
        _, iscat, cfeats, cm = cat
        t, i = iscat.shape
        return 2 * cm.shape[1] + 4 * t * i
    _, iscat, catm = cat
    t, i = iscat.shape
    return 4 * t * i


@dataclasses.dataclass
class Booster:
    split_feature: np.ndarray  # (T, M) int32
    split_threshold: np.ndarray  # (T, M) float32 (float64 on imported models)
    split_bin: np.ndarray  # (T, M) int32
    left_child: np.ndarray  # (T, M) int32
    right_child: np.ndarray  # (T, M) int32
    is_leaf: np.ndarray  # (T, M) bool
    leaf_values: np.ndarray  # (T, M) float32
    init_score: np.ndarray  # (C,)
    num_classes: int  # margin columns C
    objective: str
    max_depth: int  # routing steps (>= realized depth of every tree)
    cover: Optional[np.ndarray] = None  # (T, M) float32
    split_gain: Optional[np.ndarray] = None  # (T, M) float32
    best_iteration: int = -1  # -1 = use all
    feature_names: Optional[list] = None
    bin_edges: Optional[np.ndarray] = None  # (F, max_bin-1) for re-binning
    # (T, M) bool: where a NaN feature value routes at each internal node.
    # None = all True (trees trained here always send missing left); imported
    # LightGBM models carry per-node directions from their decision_type.
    nan_left: Optional[np.ndarray] = None
    # Categorical splits (reference LightGBMParams.scala:125-133): cat_nodes
    # (T, M) bool marks categorical decisions; cat_masks (T, M, Bc) bool is
    # the LEFT set over the feature's value-bin ids; cat_values maps feature
    # -> sorted-by-frequency raw category values (bin i+1 <-> values[i]).
    # A raw value not in cat_values (unseen/NaN) routes RIGHT, matching
    # native LightGBM's unseen-category behavior.
    cat_nodes: Optional[np.ndarray] = None
    cat_masks: Optional[np.ndarray] = None
    cat_values: Optional[Dict[int, np.ndarray]] = None
    # (T, M) bool: zero_as_missing nodes (imported LightGBM missing_type=
    # Zero): a 0.0 or NaN feature value routes per nan_left there.
    zero_missing: Optional[np.ndarray] = None
    # Linear trees (imported ``linear_tree=true`` models; training here
    # never produces them): at leaf slot m the output is
    # ``leaf_const[t, m] + sum_l leaf_coeff[t, m, l] * x[leaf_feat[t, m, l]]``
    # over valid entries (``leaf_feat >= 0``; -1 pads). If ANY feature used
    # by the leaf's model is NaN, the plain ``leaf_values`` output applies —
    # native LightGBM's missing fallback for linear leaves.
    leaf_const: Optional[np.ndarray] = None  # (T, M) float64
    leaf_coeff: Optional[np.ndarray] = None  # (T, M, L) float64
    leaf_feat: Optional[np.ndarray] = None  # (T, M, L) int32, -1 pad

    @property
    def has_categorical(self) -> bool:
        return self.cat_nodes is not None and bool(np.any(self.cat_nodes))

    @property
    def has_linear(self) -> bool:
        return self.leaf_const is not None

    def _cat_binned(self, X: np.ndarray) -> np.ndarray:
        """Replace categorical columns of a raw batch with their value-bin
        ids (float) — the predict-side twin of training's binning, via the
        shared ``cat_to_bins`` rule."""
        from mmlspark_tpu.lightgbm.binning import cat_to_bins

        Xp = np.array(X, dtype=np.float64, copy=True)
        for f, vals in (self.cat_values or {}).items():
            Xp[:, f] = cat_to_bins(X[:, f], np.asarray(vals, np.float64))
        return Xp

    @property
    def num_trees(self) -> int:
        return self.split_feature.shape[0]

    @property
    def num_features(self) -> int:
        """Trained feature-space width (pins CSR predict batches to the
        training F so narrower sparse batches can't silently shrink)."""
        if self.feature_names:
            return len(self.feature_names)
        if self.bin_edges is not None:
            return self.bin_edges.shape[0]
        internal = (~self.is_leaf) & np.isfinite(self.split_threshold)
        feats = self.split_feature[internal]
        return int(feats.max()) + 1 if feats.size else 0

    @property
    def num_iterations(self) -> int:
        return self.num_trees // self.num_classes

    def _used_trees(self, num_iteration: Optional[int] = None) -> int:
        it = num_iteration
        if it is None:
            it = self.best_iteration if self.best_iteration > 0 else self.num_iterations
        return min(it, self.num_iterations) * self.num_classes

    # -- predict -------------------------------------------------------------

    def raw_margin(
        self, X, num_iteration: Optional[int] = None
    ) -> np.ndarray:
        """(N, C) raw margins (init_score + sum of tree outputs). ``X`` may be
        dense (N, F) or a CSRMatrix (densified in bounded row chunks)."""
        chunks = _csr_chunks(
            X,
            dtype=np.float64
            if (self.has_categorical or self.has_linear)
            else np.float32,
        )
        if chunks is not None:
            return np.concatenate(
                [self.raw_margin(c, num_iteration) for c in chunks], axis=0
            )
        t = self._used_trees(num_iteration)
        if t == 0:
            return np.broadcast_to(
                self.init_score[None, :], (X.shape[0], self.num_classes)
            ).copy()
        if self.has_linear:
            return self._raw_margin_linear(X, num_iteration)
        pc = _paths_cache(self, t)
        has_cat = self.has_categorical
        X32 = np.asarray(
            self._cat_binned(X) if has_cat else X, dtype=np.float32
        )
        if has_cat:
            cat = _cat_paths_cache(self, t)
        extra = _cat_row_bytes(cat) if has_cat else 0
        chunk = _predict_chunk_rows(*pc.feats.shape, extra_row_bytes=extra)
        outs = []
        # device-resident constants built ONCE — a jnp.asarray per chunk
        # would re-upload every tree table each iteration (transfers are
        # the fixed cost on remote-attached chips)
        cargs = (
            jnp.asarray(pc.feats), jnp.asarray(pc.thrs),
            jnp.asarray(pc.nanl), jnp.asarray(pc.zm),
            jnp.asarray(pc.P), jnp.asarray(pc.plen),
        )
        lvals_d = jnp.asarray(pc.lvals)
        isc_d = jnp.asarray(self.init_score)
        if has_cat:
            cat_kernel = (
                _predict_margin_paths_cat_jit
                if cat[0] == "matmul"
                else _predict_margin_paths_catgather_jit
            )
            catargs = tuple(jnp.asarray(a) for a in cat[1:])
        for lo in range(0, max(len(X32), 1), chunk):
            xd = jnp.asarray(X32[lo : lo + chunk])
            if has_cat:
                m = cat_kernel(
                    xd, *cargs, *catargs, lvals_d, isc_d, self.num_classes,
                )
            else:
                m = _predict_margin_paths_jit(
                    xd, *cargs, lvals_d, isc_d, self.num_classes,
                )
            outs.append(np.asarray(m))
        return np.concatenate(outs, axis=0) if outs else np.zeros((0, self.num_classes), np.float32)

    def _raw_margin_linear(
        self, X, num_iteration: Optional[int] = None
    ) -> np.ndarray:
        """Margins for linear-tree models: leaf ROUTING stays on device (the
        jitted path-matrix leaf predict), the per-leaf linear models run in
        float64 on host — native LightGBM evaluates linear leaves in double,
        and an f32 detour would visibly drift coefficient-heavy leaves.
        A leaf whose model touches a NaN feature falls back to the plain
        constant output (native behavior for linear leaves + missing)."""
        slots = self.predict_leaf(X, num_iteration)  # (N, T) leaf slots
        t = slots.shape[1]
        Xd = np.asarray(X, np.float64)
        n = Xd.shape[0]
        tt = np.arange(t)[None, :]
        lmax = self.leaf_feat.shape[-1]
        out = np.empty((n, t), np.float64)
        chunk = max(1, (64 << 20) // max(8 * t * lmax, 1))
        for lo in range(0, max(n, 1), chunk):
            sl = slots[lo : lo + chunk]
            const = self.leaf_const[tt, sl]  # (n, T)
            coeff = self.leaf_coeff[tt, sl]  # (n, T, L)
            fidx = self.leaf_feat[tt, sl]  # (n, T, L)
            valid = fidx >= 0
            rows = np.arange(sl.shape[0])[:, None, None]
            xv = Xd[lo : lo + chunk][rows, np.maximum(fidx, 0)]
            nanf = np.any(valid & np.isnan(xv), axis=-1)
            lin = const + np.where(
                valid & ~np.isnan(xv), coeff * xv, 0.0
            ).sum(axis=-1)
            plain = self.leaf_values[tt, sl].astype(np.float64)
            out[lo : lo + chunk] = np.where(nanf, plain, lin)
        rounds = t // self.num_classes
        margins = out.reshape(n, rounds, self.num_classes).sum(axis=1)
        return margins + np.asarray(self.init_score, np.float64)[None, :]

    def predict_leaf(
        self, X, num_iteration: Optional[int] = None
    ) -> np.ndarray:
        """(N, T) leaf slot per tree (``predictLeaf``, LightGBMBooster.scala:240+)."""
        chunks = _csr_chunks(
            X, dtype=np.float64 if self.has_categorical else np.float32
        )
        if chunks is not None:
            return np.concatenate(
                [self.predict_leaf(c, num_iteration) for c in chunks], axis=0
            )
        t = self._used_trees(num_iteration)
        if t == 0:
            return np.zeros((np.shape(X)[0], 0), np.int32)
        pc = _paths_cache(self, t)
        has_cat = self.has_categorical
        X32 = np.asarray(
            self._cat_binned(X) if has_cat else X, dtype=np.float32
        )
        if has_cat:
            cat = _cat_paths_cache(self, t)
        extra = _cat_row_bytes(cat) if has_cat else 0
        chunk = _predict_chunk_rows(*pc.feats.shape, extra_row_bytes=extra)
        outs = []
        cargs = (
            jnp.asarray(pc.feats), jnp.asarray(pc.thrs),
            jnp.asarray(pc.nanl), jnp.asarray(pc.zm),
            jnp.asarray(pc.P), jnp.asarray(pc.plen),
        )
        lslots_d = jnp.asarray(pc.lslots)
        if has_cat:
            cat_kernel = (
                _predict_leaf_paths_cat_jit
                if cat[0] == "matmul"
                else _predict_leaf_paths_catgather_jit
            )
            catargs = tuple(jnp.asarray(a) for a in cat[1:])
        for lo in range(0, max(len(X32), 1), chunk):
            xd = jnp.asarray(X32[lo : lo + chunk])
            if has_cat:
                leaves = cat_kernel(xd, *cargs, *catargs, lslots_d)
            else:
                leaves = _predict_leaf_paths_jit(xd, *cargs, lslots_d)
            outs.append(np.asarray(leaves))
        return np.concatenate(outs, axis=0) if outs else np.zeros((0, t), np.int32)

    def features_shap(
        self, X, num_iteration: Optional[int] = None
    ) -> np.ndarray:
        """(N, C, F+1) per-feature SHAP values plus bias term (last column);
        ``sum(axis=-1) == raw_margin`` (``featuresShap``,
        LightGBMBooster.scala:240-275). Path-dependent TreeSHAP using the
        training covers recorded per node."""
        from mmlspark_tpu.lightgbm.shap import tree_shap

        if self.has_linear:
            raise NotImplementedError(
                "SHAP values are not implemented for linear-tree models "
                "(leaf outputs are per-leaf linear functions, outside "
                "TreeSHAP's piecewise-constant contract)"
            )
        chunks = _csr_chunks(X, dtype=np.float64)
        if chunks is not None:
            return np.concatenate(
                [self.features_shap(c, num_iteration) for c in chunks], axis=0
            )
        return tree_shap(self, np.asarray(X, dtype=np.float64), num_iteration)

    # -- serde ---------------------------------------------------------------

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "Booster":
        d = dict(d)
        for k in ("split_feature", "split_bin", "left_child", "right_child"):
            d[k] = np.asarray(d[k], dtype=np.int32)
        for k in ("leaf_values", "init_score"):
            d[k] = np.asarray(d[k], dtype=np.float32)
        # thresholds keep f64 when they arrive as f64 (imported LightGBM
        # models); trained-here boosters are exact f32 values either way
        thr = np.asarray(d["split_threshold"])
        d["split_threshold"] = thr.astype(
            np.float64 if thr.dtype == np.float64 else np.float32
        )
        d["is_leaf"] = np.asarray(d["is_leaf"], dtype=bool)
        for k in ("cover", "split_gain"):
            if d.get(k) is not None:
                d[k] = np.asarray(d[k], dtype=np.float32)
        for k in ("nan_left", "cat_nodes", "cat_masks", "zero_missing"):
            if d.get(k) is not None:
                d[k] = np.asarray(d[k], dtype=bool)
        if d.get("bin_edges") is not None:
            d["bin_edges"] = np.asarray(d["bin_edges"], dtype=np.float64)
        if d.get("cat_values") is not None:
            d["cat_values"] = {
                int(k): np.asarray(v, dtype=np.float64)
                for k, v in d["cat_values"].items()
            }
        for k, dt in (
            ("leaf_const", np.float64),
            ("leaf_coeff", np.float64),
            ("leaf_feat", np.int32),
        ):
            if d.get(k) is not None:
                d[k] = np.asarray(d[k], dtype=dt)
        return Booster(**d)

    def model_to_string(self) -> str:
        """``saveNativeModel`` string — the REAL LightGBM model-text format
        (``LightGBMBooster.scala:277-310``): loadable by any LightGBM
        runtime, ONNX converters, and SHAP tooling. See
        :mod:`mmlspark_tpu.lightgbm.model_text` for encoding notes (the init
        score is folded into iteration-0 leaf values, as LightGBM's own
        boost_from_average does, so margins survive the round-trip)."""
        from mmlspark_tpu.lightgbm.model_text import to_lightgbm_text

        return to_lightgbm_text(self)

    def to_json_string(self) -> str:
        """Lossless internal JSON dump (keeps split_bin / bin_edges /
        init_score exactly — the stage-serialization payload)."""
        d = self.to_dict()
        for k, v in d.items():
            if isinstance(v, np.ndarray):
                d[k] = {"__nd__": v.tolist(), "dtype": str(v.dtype), "shape": v.shape}
        if d.get("cat_values") is not None:
            d["cat_values"] = {
                str(k): np.asarray(v).tolist() for k, v in d["cat_values"].items()
            }
        return json.dumps(d)

    @staticmethod
    def from_string(s: str) -> "Booster":
        """Parse either format: LightGBM model text (starts with ``tree``)
        or the internal JSON dump."""
        head = s.lstrip()[:16]
        if head.startswith("tree"):
            from mmlspark_tpu.lightgbm.model_text import from_lightgbm_text

            return from_lightgbm_text(s)
        d = json.loads(s)
        for k, v in list(d.items()):
            if isinstance(v, dict) and "__nd__" in v:
                d[k] = np.asarray(v["__nd__"], dtype=v["dtype"]).reshape(v["shape"])
        return Booster.from_dict(d)

    def feature_importances(self, importance_type: str = "split") -> np.ndarray:
        """Split-count or total-gain importance
        (``getFeatureImportances``, LightGBMBooster.scala:295-310)."""
        internal = (~self.is_leaf) & np.isfinite(self.split_threshold)
        feats = self.split_feature[internal]
        num_features = self.num_features
        if importance_type == "gain":
            if self.split_gain is None:
                raise ValueError(
                    "importance_type='gain' requires split_gain (absent on "
                    "this booster — e.g. merged from a booster without it)"
                )
            gains = self.split_gain[internal]
            out = np.zeros(num_features, dtype=np.float64)
            np.add.at(out, feats.ravel(), gains.ravel())
            return out
        if importance_type != "split":
            raise ValueError(f"unknown importance_type {importance_type!r}")
        return np.bincount(feats.ravel(), minlength=num_features).astype(np.float64)


def _csr_chunks(X, target_bytes: int = 256 << 20, dtype=np.float32):
    """None for dense inputs; for CSRMatrix, an iterator of densified row
    chunks sized so each chunk stays under ``target_bytes`` regardless of
    feature count (wide sparse data shrinks the row window).

    Categorical boosters must densify in float64: training bins CSR
    categorical values in f64 (``apply_bins_csr``), and a float32 detour
    would round category ids above 2**24 before ``_cat_binned``'s
    value-identity match, silently routing them as 'unseen'."""
    from mmlspark_tpu.data.sparse import CSRMatrix

    if not isinstance(X, CSRMatrix):
        return None
    itemsize = np.dtype(dtype).itemsize
    chunk_rows = min(
        65536, max(1, target_bytes // (itemsize * max(X.num_features, 1)))
    )
    return (
        X.row_slice(lo, min(lo + chunk_rows, X.num_rows)).to_dense(dtype)
        for lo in range(0, max(X.num_rows, 1), chunk_rows)
    )


# ---------------------------------------------------------------------------
# Path-matrix predict: trees as one MXU matmul instead of serial gathers
# ---------------------------------------------------------------------------
#
# Pointer-chasing routing costs max_depth serial gather rounds per tree —
# gathers are the slowest primitive on TPU (measured ~19 ms/round at 400k
# rows). The TPU-native formulation evaluates ALL internal-node decisions at
# once and selects the leaf algebraically:
#   d[n,i]   = x_{feat_i} <= thr_i (or NaN)        # (N, I) compares
#   D        = 2 d - 1                             # ±1
#   score    = D @ P                               # (N, L) MXU matmul
#   leaf     = argmax(score == pathlen)            # exact path match
# where P[i,l] is +1/-1/0 as leaf l's root path goes left/right/misses node
# i. A row matches pathlen[l] exactly for its true leaf only. Tree structure
# is host-precomputed once per booster (cached) and baked as constants.


def _thr_f32(thr) -> np.ndarray:
    """f64 thresholds → the LARGEST f32 value <= each threshold. For f32
    inputs x, ``x <= thr_f32`` then decides identically to LightGBM's f64
    ``x <= thr`` (round-to-nearest narrowing could round UP past the
    threshold and admit rows the f64 comparison rejects)."""
    thr = np.asarray(thr)
    if thr.dtype != np.float64:
        return thr.astype(np.float32)
    t32 = thr.astype(np.float32)
    over = t32.astype(np.float64) > thr
    if over.any():
        t32 = np.where(over, np.nextafter(t32, np.float32(-np.inf)), t32)
    return t32


class PathConsts(NamedTuple):
    """Per-tree padded predict constants (one derivation for everything
    the path-matrix kernels consume — _cat_paths aligns on `internals`)."""

    feats: np.ndarray  # (T, I) int32 split features
    thrs: np.ndarray  # (T, I) f32 thresholds (f64 snapped DOWN, _thr_f32)
    P: np.ndarray  # (T, I, L) ±1/0 path signs
    plen: np.ndarray  # (T, L) path lengths
    lvals: np.ndarray  # (T, L) leaf values
    lslots: np.ndarray  # (T, L) leaf slot ids
    nanl: np.ndarray  # (T, I) bool NaN-goes-left
    zm: np.ndarray  # (T, I) bool zero_as_missing
    internals: list  # per-tree internal-slot ordering


def _leaf_paths(b: "Booster", t: int) -> "PathConsts":
    feats_l, thrs_l, P_l, plen_l, lvals_l, lslots_l, nanl_l = [], [], [], [], [], [], []
    zm_l = []
    max_i = max_l = 1
    per_tree = []
    for ti in range(t):
        is_leaf = b.is_leaf[ti]
        left, right = b.left_child[ti], b.right_child[ti]
        feat, thr = b.split_feature[ti], b.split_threshold[ti]
        # DFS from the root collecting root->leaf paths
        paths = []  # (leaf_slot, [(internal_slot, +1|-1), ...])
        stack = [(0, [])]
        while stack:
            slot, path = stack.pop()
            if is_leaf[slot]:
                paths.append((slot, path))
                continue
            stack.append((int(left[slot]), path + [(slot, 1)]))
            stack.append((int(right[slot]), path + [(slot, -1)]))
        internal = sorted({s for _, path in paths for s, _ in path})
        per_tree.append((paths, internal))
        max_i = max(max_i, len(internal))
        max_l = max(max_l, len(paths))
    for ti in range(t):
        paths, internal = per_tree[ti]
        pos = {s: k for k, s in enumerate(internal)}
        fe = np.zeros(max_i, np.int32)
        th = np.full(max_i, np.inf, np.float32)  # padding: always-left, off-path
        nl = np.ones(max_i, bool)  # padding: NaN goes left (off-path anyway)
        zm = np.zeros(max_i, bool)  # padding: plain numeric comparison
        fe[: len(internal)] = b.split_feature[ti][internal]
        th[: len(internal)] = _thr_f32(b.split_threshold[ti][internal])
        if b.nan_left is not None:
            nl[: len(internal)] = b.nan_left[ti][internal]
        if b.zero_missing is not None:
            zm[: len(internal)] = b.zero_missing[ti][internal]
        P = np.zeros((max_i, max_l), np.float32)
        plen = np.full(max_l, np.float32(max_i + 1))  # unmatched sentinel
        lv = np.zeros(max_l, np.float32)
        ls = np.zeros(max_l, np.int32)
        for li, (slot, path) in enumerate(paths):
            for s, sign in path:
                P[pos[s], li] = sign
            plen[li] = len(path)
            lv[li] = b.leaf_values[ti][slot]
            ls[li] = slot
        feats_l.append(fe)
        thrs_l.append(th)
        nanl_l.append(nl)
        zm_l.append(zm)
        P_l.append(P)
        plen_l.append(plen)
        lvals_l.append(lv)
        lslots_l.append(ls)
    return PathConsts(
        feats=np.stack(feats_l),
        thrs=np.stack(thrs_l),
        P=np.stack(P_l),
        plen=np.stack(plen_l),
        lvals=np.stack(lvals_l),
        lslots=np.stack(lslots_l),
        nanl=np.stack(nanl_l),
        zm=np.stack(zm_l),
        internals=[internal for _, internal in per_tree],
    )


def _path_match(X, feats, thrs, nanl, zm, P, plen):
    """(N, T, L) one-hot leaf membership per tree."""
    x = jnp.take(X, feats.reshape(-1), axis=1)
    n = X.shape[0]
    t, i = feats.shape
    x = x.reshape(n, t, i)
    # missing (NaN — and 0.0 at zero_as_missing nodes) routes per the
    # node's nan_left flag; pads are always-left
    miss = jnp.isnan(x) | (zm[None] & (jnp.abs(x) <= K_ZERO_THRESHOLD))
    d = jnp.where(miss, nanl[None], x <= thrs[None])
    D = 2.0 * d.astype(jnp.float32) - 1.0  # (N, T, I)
    score = jnp.einsum(
        "nti,til->ntl", D, P, preferred_element_type=jnp.float32,
        precision=lax.Precision.HIGHEST,
    )
    # true leaf: every on-path sign agrees -> score == plen; any miss costs 2
    return score >= plen[None]


@partial(jax.jit, static_argnames=("num_classes",))
def _predict_margin_paths_jit(X, feats, thrs, nanl, zm, P, plen, lvals, init_score, num_classes):
    match = _path_match(X, feats, thrs, nanl, zm, P, plen)
    # match is one-hot over leaves: the contribution IS a matmul, no gather
    contrib = jnp.einsum(
        "ntl,tl->nt", match.astype(jnp.float32), lvals,
        preferred_element_type=jnp.float32, precision=lax.Precision.HIGHEST,
    )
    n, t = contrib.shape
    rounds = t // num_classes
    margins = contrib.reshape(n, rounds, num_classes).sum(axis=1)
    return margins + init_score[None, :]


@jax.jit
def _predict_leaf_paths_jit(X, feats, thrs, nanl, zm, P, plen, lslots):
    match = _path_match(X, feats, thrs, nanl, zm, P, plen)
    # one-hot contraction again: slot id = sum_l match * slot_l
    return jnp.einsum(
        "ntl,tl->nt", match.astype(jnp.float32), lslots.astype(jnp.float32),
        precision=lax.Precision.HIGHEST,
    ).astype(jnp.int32)


def _path_match_cat_gather(X, feats, thrs, nanl, zm, P, plen, iscat, catm):
    """Memory-bounded categorical path match: flat 1-D gather over the
    (T, I, Bc) mask tables. ~Two orders of magnitude slower than the
    matmul kernel below (docs/perf_histogram.md round 5) — used only when
    the dense (T*I, Fc*Bc) mask matrix would exceed its size gate."""
    x = jnp.take(X, feats.reshape(-1), axis=1)
    n = X.shape[0]
    t, i = feats.shape
    x = x.reshape(n, t, i)
    miss = jnp.isnan(x) | (zm[None] & (jnp.abs(x) <= K_ZERO_THRESHOLD))
    d_num = jnp.where(miss, nanl[None], x <= thrs[None])
    bc = catm.shape[-1]
    xb = jnp.clip(x, 0, bc - 1).astype(jnp.int32)
    lin = (
        jnp.arange(t, dtype=jnp.int32)[None, :, None] * (i * bc)
        + jnp.arange(i, dtype=jnp.int32)[None, None, :] * bc
        + xb
    )
    d = jnp.where(iscat[None], catm.reshape(-1)[lin], d_num)
    D = 2.0 * d.astype(jnp.float32) - 1.0
    score = jnp.einsum(
        "nti,til->ntl", D, P, preferred_element_type=jnp.float32,
        precision=lax.Precision.HIGHEST,
    )
    return score >= plen[None]


@partial(jax.jit, static_argnames=("num_classes",))
def _predict_margin_paths_catgather_jit(
    X, feats, thrs, nanl, zm, P, plen, iscat, catm, lvals, init_score, num_classes
):
    match = _path_match_cat_gather(X, feats, thrs, nanl, zm, P, plen, iscat, catm)
    contrib = jnp.einsum(
        "ntl,tl->nt", match.astype(jnp.float32), lvals,
        preferred_element_type=jnp.float32, precision=lax.Precision.HIGHEST,
    )
    n, t = contrib.shape
    rounds = t // num_classes
    margins = contrib.reshape(n, rounds, num_classes).sum(axis=1)
    return margins + init_score[None, :]


@jax.jit
def _predict_leaf_paths_catgather_jit(
    X, feats, thrs, nanl, zm, P, plen, iscat, catm, lslots
):
    match = _path_match_cat_gather(X, feats, thrs, nanl, zm, P, plen, iscat, catm)
    return jnp.einsum(
        "ntl,tl->nt", match.astype(jnp.float32), lslots.astype(jnp.float32),
        precision=lax.Precision.HIGHEST,
    ).astype(jnp.int32)


def _path_match_cat(X, feats, thrs, nanl, zm, P, plen, iscat, cfeats, cm):
    """(N, T, L) leaf membership with categorical decisions: categorical
    columns of ``X`` hold value-bin ids (``Booster._cat_binned``); at cat
    nodes d = mask[bin] (bin 0 = unseen/NaN => right).

    Categorical decisions for EVERY node come from one MXU matmul: stacked
    per-feature bin one-hots (Fc*Bc, N) against the per-node mask matrix
    ``cm`` (T*I, Fc*Bc) built by ``_cat_paths``. Gather formulations of
    this lookup (3-axis batched or flattened) measured 300-450x slower
    than the numeric compare path on TPU (r5)."""
    x = jnp.take(X, feats.reshape(-1), axis=1)
    n = X.shape[0]
    t, i = feats.shape
    x = x.reshape(n, t, i)
    miss = jnp.isnan(x) | (zm[None] & (jnp.abs(x) <= K_ZERO_THRESHOLD))
    d_num = jnp.where(miss, nanl[None], x <= thrs[None])
    fc = cfeats.shape[0]
    bc = cm.shape[1] // max(fc, 1)
    xc = jnp.take(X, cfeats, axis=1)  # (N, Fc) value-bin ids
    xct = jnp.clip(xc, 0, bc - 1).astype(jnp.int32).T  # (Fc, N)
    oh = (
        jnp.arange(bc, dtype=jnp.int32)[None, :, None] == xct[:, None, :]
    ).reshape(fc * bc, n)  # stacked per-feature one-hots
    D_cat = lax.dot_general(
        cm.astype(jnp.bfloat16), oh.astype(jnp.bfloat16),
        (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32,
    )  # (T*I, N); exact: both operands 0/1
    d_cat = (D_cat > 0).T.reshape(n, t, i)
    d = jnp.where(iscat[None], d_cat, d_num)
    D = 2.0 * d.astype(jnp.float32) - 1.0
    score = jnp.einsum(
        "nti,til->ntl", D, P, preferred_element_type=jnp.float32,
        precision=lax.Precision.HIGHEST,
    )
    return score >= plen[None]


@partial(jax.jit, static_argnames=("num_classes",))
def _predict_margin_paths_cat_jit(
    X, feats, thrs, nanl, zm, P, plen, iscat, cfeats, cm, lvals, init_score, num_classes
):
    match = _path_match_cat(X, feats, thrs, nanl, zm, P, plen, iscat, cfeats, cm)
    contrib = jnp.einsum(
        "ntl,tl->nt", match.astype(jnp.float32), lvals,
        preferred_element_type=jnp.float32, precision=lax.Precision.HIGHEST,
    )
    n, t = contrib.shape
    rounds = t // num_classes
    margins = contrib.reshape(n, rounds, num_classes).sum(axis=1)
    return margins + init_score[None, :]


@jax.jit
def _predict_leaf_paths_cat_jit(X, feats, thrs, nanl, zm, P, plen, iscat, cfeats, cm, lslots):
    match = _path_match_cat(X, feats, thrs, nanl, zm, P, plen, iscat, cfeats, cm)
    return jnp.einsum(
        "ntl,tl->nt", match.astype(jnp.float32), lslots.astype(jnp.float32),
        precision=lax.Precision.HIGHEST,
    ).astype(jnp.int32)


def _paths_cache(b: "Booster", t: int):
    cache = getattr(b, "_path_cache", None)
    if cache is None or cache[0] != t:
        consts = _leaf_paths(b, t)
        object.__setattr__(b, "_path_cache", (t, consts))
        cache = (t, consts)
    return cache[1]


def _cat_paths(b: "Booster", t: int):
    """(ISCAT (T, I), CFEATS (Fc,), CM (T*I, Fc*Bc)) aligned by construction
    with _leaf_paths' padded constants (it shares the internal-slot ordering
    _leaf_paths returns — no second derivation to drift).

    CM is the matmul form of the per-node left-set masks: row ti*I+ii of a
    categorical node carries its (Bc,) mask at the column block of its
    feature, so the whole batch's categorical decisions are ONE
    (T*I, Fc*Bc) x (Fc*Bc, N) contraction against stacked per-feature
    one-hots — the 3-axis batched gather this replaces ran ~450x slower
    than the numeric compare path (39k rows/s, r5)."""
    consts = _paths_cache(b, t)
    max_i = consts.feats.shape[1]
    internals = consts.internals
    bc = b.cat_masks.shape[-1]
    iscat = np.zeros((t, max_i), bool)
    catm = np.zeros((t, max_i, bc), bool)
    for ti in range(t):
        internal = internals[ti]
        iscat[ti, : len(internal)] = b.cat_nodes[ti][internal]
        catm[ti, : len(internal)] = b.cat_masks[ti][internal]
    cfeats = np.asarray(sorted(b.cat_values or {}), np.int32)
    # cm is block-sparse stored dense ((T*I, Fc*Bc), one Bc block per cat
    # node): Fc-times the old (T, I, Bc) tables. Gate it — a huge imported
    # forest with many high-cardinality features must fall back to the
    # (slow but memory-bounded) gather kernel rather than OOM.
    if t * max_i * len(cfeats) * bc <= _CM_BYTES_CAP:
        cpos = {int(f_): j for j, f_ in enumerate(cfeats)}
        cm = np.zeros((t * max_i, len(cfeats) * bc), np.uint8)
        for ti in range(t):
            for ii in np.nonzero(iscat[ti])[0]:
                j = cpos[int(consts.feats[ti, ii])]
                cm[ti * max_i + ii, j * bc : (j + 1) * bc] = catm[ti, ii]
        return ("matmul", iscat, cfeats, cm)
    return ("gather", iscat, catm)


def _cat_paths_cache(b: "Booster", t: int):
    cache = getattr(b, "_cat_path_cache", None)
    if cache is None or cache[0] != t:
        consts = _cat_paths(b, t)
        object.__setattr__(b, "_cat_path_cache", (t, consts))
        cache = (t, consts)
    return cache[1]


# ---------------------------------------------------------------------------
# Jitted predict kernels
# ---------------------------------------------------------------------------


def _route_rows(X, feat, thr, left, right, is_leaf, depth: int):
    """One tree, all rows: ``depth`` gather steps through the pointer arrays.
    X (N,F) raw float32. Returns final leaf slot (N,). Rows at a leaf stay."""
    n = X.shape[0]
    node = jnp.zeros(n, dtype=jnp.int32)
    for _ in range(depth):
        f = feat[node]  # (N,)
        t = thr[node]
        x = jnp.take_along_axis(X, f[:, None], axis=1)[:, 0]
        go_left = jnp.isnan(x) | (x <= t)
        nxt = jnp.where(go_left, left[node], right[node])
        node = jnp.where(is_leaf[node], node, nxt)
    return node


@partial(jax.jit, static_argnames=("num_classes", "depth"))
def _predict_margin_jit(
    X, feat, thr, left, right, is_leaf, leaf_vals, init_score, num_classes, depth
):
    t = feat.shape[0]
    rounds = t // num_classes

    def r(a):
        return a.reshape(rounds, num_classes, -1)

    n = X.shape[0]

    def one_round(margins, tree):
        f, th, lc, rc, il, lv = tree

        def one_class(c):
            leaf = _route_rows(X, f[c], th[c], lc[c], rc[c], il[c], depth)
            return lv[c][leaf]

        contrib = jax.vmap(one_class, out_axes=1)(jnp.arange(num_classes))
        return margins + contrib, None

    init = jnp.broadcast_to(init_score[None, :], (n, num_classes))
    margins, _ = jax.lax.scan(
        one_round, init, (r(feat), r(thr), r(left), r(right), r(is_leaf), r(leaf_vals))
    )
    return margins


@partial(jax.jit, static_argnames=("depth",))
def _predict_leaf_jit(X, feat, thr, left, right, is_leaf, depth):
    def one_tree(tree):
        f, th, lc, rc, il = tree
        return _route_rows(X, f, th, lc, rc, il, depth)

    return jax.vmap(one_tree, out_axes=1)((feat, thr, left, right, is_leaf))
