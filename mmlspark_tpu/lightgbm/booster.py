"""Booster: the trained forest, with jitted batch predict and model serde.

Equivalent of ``LightGBMBooster`` (reference ``lightgbm/LightGBMBooster.scala``):
score / predictLeaf / raw-margin output, iteration slicing for early stopping,
string serde. Instead of per-row JNI calls with ThreadLocal native buffers
(``LightGBMBooster.scala:37-128``), prediction is one jitted XLA program over
the whole batch; trees are dense implicit-heap arrays so traversal is D
gathers per tree — no data-dependent control flow.

Tree layout (depth D, per tree):
- ``split_feature``  (2^D - 1,) int32   — heap order; dead nodes = 0
- ``split_threshold``(2^D - 1,) float32 — raw-value "go left if x <= t or NaN";
                                           dead nodes = +inf (all rows left)
- ``split_bin``      (2^D - 1,) int32   — binned-space threshold (training path)
- ``leaf_values``    (2^D,)    float32  — learning-rate-scaled outputs

Forest arrays stack trees as (num_trees, ...) where tree ``i*C + c`` is
iteration i, class c (LightGBM's tree ordering).
"""

from __future__ import annotations

import dataclasses
import json
from functools import partial
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from mmlspark_tpu.lightgbm.binning import BinMapper


@dataclasses.dataclass
class Booster:
    split_feature: np.ndarray  # (T, I)
    split_threshold: np.ndarray  # (T, I)
    split_bin: np.ndarray  # (T, I)
    leaf_values: np.ndarray  # (T, L)
    init_score: np.ndarray  # (C,)
    num_classes: int  # margin columns C
    objective: str
    max_depth: int
    best_iteration: int = -1  # -1 = use all
    feature_names: Optional[list] = None
    bin_edges: Optional[np.ndarray] = None  # (F, max_bin-1) for re-binning

    @property
    def num_trees(self) -> int:
        return self.split_feature.shape[0]

    @property
    def num_iterations(self) -> int:
        return self.num_trees // self.num_classes

    def _used_trees(self, num_iteration: Optional[int] = None) -> int:
        it = num_iteration
        if it is None:
            it = self.best_iteration if self.best_iteration > 0 else self.num_iterations
        return min(it, self.num_iterations) * self.num_classes

    # -- predict -------------------------------------------------------------

    def raw_margin(
        self, X: np.ndarray, num_iteration: Optional[int] = None
    ) -> np.ndarray:
        """(N, C) raw margins (init_score + sum of tree outputs)."""
        t = self._used_trees(num_iteration)
        if t == 0:
            return np.broadcast_to(
                self.init_score[None, :], (X.shape[0], self.num_classes)
            ).copy()
        out = _predict_margin_jit(
            jnp.asarray(X, dtype=jnp.float32),
            jnp.asarray(self.split_feature[:t]),
            jnp.asarray(self.split_threshold[:t]),
            jnp.asarray(self.leaf_values[:t]),
            jnp.asarray(self.init_score),
            self.num_classes,
        )
        return np.asarray(out)

    def predict_leaf(
        self, X: np.ndarray, num_iteration: Optional[int] = None
    ) -> np.ndarray:
        """(N, T) leaf index per tree (``predictLeaf``, LightGBMBooster.scala:240+)."""
        t = self._used_trees(num_iteration)
        out = _predict_leaf_jit(
            jnp.asarray(X, dtype=jnp.float32),
            jnp.asarray(self.split_feature[:t]),
            jnp.asarray(self.split_threshold[:t]),
        )
        return np.asarray(out)

    # -- serde ---------------------------------------------------------------

    def to_dict(self) -> Dict[str, Any]:
        d = dataclasses.asdict(self)
        return d

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "Booster":
        d = dict(d)
        for k in ("split_feature", "split_bin"):
            d[k] = np.asarray(d[k], dtype=np.int32)
        for k in ("split_threshold", "leaf_values", "init_score"):
            d[k] = np.asarray(d[k], dtype=np.float32)
        if d.get("bin_edges") is not None:
            d["bin_edges"] = np.asarray(d["bin_edges"], dtype=np.float64)
        return Booster(**d)

    def model_to_string(self) -> str:
        """Textual model dump (``saveNativeModel`` analogue; our own JSON
        format — LightGBM text-format interop is tracked as a gap)."""
        d = self.to_dict()
        for k, v in d.items():
            if isinstance(v, np.ndarray):
                d[k] = {"__nd__": v.tolist(), "dtype": str(v.dtype), "shape": v.shape}
        return json.dumps(d)

    @staticmethod
    def from_string(s: str) -> "Booster":
        d = json.loads(s)
        for k, v in list(d.items()):
            if isinstance(v, dict) and "__nd__" in v:
                d[k] = np.asarray(v["__nd__"], dtype=v["dtype"]).reshape(v["shape"])
        return Booster.from_dict(d)

    def feature_importances(self, importance_type: str = "split") -> np.ndarray:
        """Split-count or total-gain-free importance
        (``getFeatureImportances``, LightGBMBooster.scala:295-310)."""
        alive = np.isfinite(self.split_threshold)
        feats = self.split_feature[alive]
        num_features = (
            len(self.feature_names)
            if self.feature_names
            else (int(feats.max()) + 1 if feats.size else 0)
        )
        return np.bincount(feats.ravel(), minlength=num_features).astype(np.float64)


# ---------------------------------------------------------------------------
# Jitted predict kernels
# ---------------------------------------------------------------------------


def _route_rows(X, feat, thr):
    """One tree, all rows: D gather steps through the implicit heap.
    X (N,F) raw float32; feat/thr (I,). Returns final leaf index (N,)."""
    n = X.shape[0]
    num_internal = feat.shape[0]
    depth = int(np.log2(num_internal + 1))
    node = jnp.zeros(n, dtype=jnp.int32)
    for _ in range(depth):
        f = feat[node]  # (N,)
        t = thr[node]
        x = jnp.take_along_axis(X, f[:, None], axis=1)[:, 0]
        go_right = jnp.logical_not(jnp.isnan(x) | (x <= t))
        node = 2 * node + 1 + go_right.astype(jnp.int32)
    return node - num_internal  # leaf index in [0, 2^D)


@partial(jax.jit, static_argnames=("num_classes",))
def _predict_margin_jit(X, feat, thr, leaf_vals, init_score, num_classes):
    t = feat.shape[0]
    rounds = t // num_classes
    featr = feat.reshape(rounds, num_classes, -1)
    thrr = thr.reshape(rounds, num_classes, -1)
    lvr = leaf_vals.reshape(rounds, num_classes, -1)
    n = X.shape[0]

    def one_round(margins, tree):
        f, th, lv = tree

        def one_class(c):
            leaf = _route_rows(X, f[c], th[c])
            return lv[c][leaf]

        contrib = jax.vmap(one_class, out_axes=1)(jnp.arange(num_classes))
        return margins + contrib, None

    init = jnp.broadcast_to(init_score[None, :], (n, num_classes))
    margins, _ = jax.lax.scan(one_round, init, (featr, thrr, lvr))
    return margins


@jax.jit
def _predict_leaf_jit(X, feat, thr):
    def one_tree(tree):
        f, th = tree
        return _route_rows(X, f, th)

    return jax.vmap(one_tree, out_axes=1)((feat, thr))
