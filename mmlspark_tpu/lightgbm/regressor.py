"""LightGBMRegressor — regression objectives incl. quantile/tweedie/poisson.

API parity with ``lightgbm/LightGBMRegressor.scala`` (objective, alpha,
tweedieVariancePower params).
"""

from __future__ import annotations

import numpy as np

from mmlspark_tpu.core.params import Param, one_of, to_float, to_str
from mmlspark_tpu.data.table import Table
from mmlspark_tpu.lightgbm.base import (
    LightGBMBase,
    LightGBMModelBase,
    extract_features,
)
from mmlspark_tpu.lightgbm.train import TrainResult


class LightGBMRegressor(LightGBMBase):
    objective = Param(
        "regression objective",
        default="regression",
        converter=to_str,
        validator=one_of(
            "regression", "regression_l1", "l2", "l1", "huber", "quantile",
            "poisson", "tweedie", "mae", "mse",
        ),
    )
    alpha = Param("Quantile/huber alpha", default=0.9, converter=to_float)
    tweedieVariancePower = Param(
        "Tweedie variance power in (1, 2)", default=1.5, converter=to_float
    )

    def _objective_name(self) -> str:
        return self.getObjective()

    def _extra_train_options(self) -> dict:
        return {
            "alpha": self.getAlpha(),
            "tweedie_variance_power": self.getTweedieVariancePower(),
        }

    def _make_model(self, result: TrainResult) -> "LightGBMRegressionModel":
        return LightGBMRegressionModel(
            featuresCol=self.getFeaturesCol(),
            predictionCol=self.getPredictionCol(),
            leafPredictionCol=self.getLeafPredictionCol(),
            featuresShapCol=self.getFeaturesShapCol(),
            objective=self.getObjective(),
            boosterData=result.booster.to_dict(),
        )


class LightGBMRegressionModel(LightGBMModelBase):
    objective = Param("Objective the booster was trained with", default="regression", converter=to_str)

    def transform(self, table: Table) -> Table:
        booster = self.booster
        X = extract_features(table, self.getFeaturesCol(), booster.num_features)
        margins = booster.raw_margin(X)[:, 0]
        if self.getObjective() in ("poisson", "tweedie"):
            margins = np.exp(margins)
        out = table.with_column(self.getPredictionCol(), margins.astype(np.float64))
        return self._with_leaf_col(out, X)
