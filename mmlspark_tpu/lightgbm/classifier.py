"""LightGBMClassifier — binary & multiclass GBDT classification.

API parity with the reference ``lightgbm/LightGBMClassifier.scala:24-142``:
infers ``actualNumClasses`` from labels, emits rawPrediction / probability /
prediction columns, optional leaf-index output, ``saveNativeModel`` serde.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from mmlspark_tpu.core.params import Param, to_bool, to_int, to_str
from mmlspark_tpu.data.table import Table
from mmlspark_tpu.lightgbm.base import (
    LightGBMBase,
    LightGBMModelBase,
    extract_features,
)
from mmlspark_tpu.lightgbm.train import TrainResult


class LightGBMClassifier(LightGBMBase):
    objective = Param(
        "binary or multiclass ('' = infer from label arity)",
        default="", converter=to_str,
    )
    rawPredictionCol = Param("Raw margin output column", default="rawPrediction", converter=to_str)
    probabilityCol = Param("Probability output column", default="probability", converter=to_str)
    isUnbalance = Param(
        "Binary class weighting for unbalanced data: positive rows get "
        "weight n_neg/n_pos (native is_unbalance, LightGBMClassifier.scala:32)",
        default=False, converter=to_bool,
    )

    _inferred_classes: int = 2

    def _adjust_weights(self, y: np.ndarray, w):
        if not self.getIsUnbalance():
            return w
        y = np.asarray(y)
        labels = set(np.unique(y).tolist())
        if not labels <= {0.0, 1.0}:
            # native LightGBM restricts is_unbalance to the binary objective;
            # non-contiguous labels (e.g. {0, 2}) infer a multiclass fit
            raise ValueError(
                "isUnbalance requires binary 0/1 labels "
                f"(got values {sorted(labels)[:5]})"
            )
        n_pos = max(1, int((y > 0.5).sum()))
        n_neg = max(1, int((y <= 0.5).sum()))
        base = np.ones(len(y), dtype=np.float64) if w is None else np.asarray(w, np.float64)
        # native is_unbalance: scale the positive class so classes balance
        return np.where(y > 0.5, base * (n_neg / n_pos), base)

    def _num_classes(self, y: np.ndarray) -> int:
        # actualNumClasses inference (LightGBMClassifier.scala:38-52)
        n = int(np.max(y)) + 1 if len(y) else 2
        self._inferred_classes = max(2, n)
        return self._inferred_classes

    def _objective_name(self) -> str:
        obj = self.getObjective()
        if obj:
            return obj
        return "binary" if self._inferred_classes <= 2 else "multiclass"

    def _make_model(self, result: TrainResult) -> "LightGBMClassificationModel":
        return LightGBMClassificationModel(
            featuresCol=self.getFeaturesCol(),
            predictionCol=self.getPredictionCol(),
            rawPredictionCol=self.getRawPredictionCol(),
            probabilityCol=self.getProbabilityCol(),
            leafPredictionCol=self.getLeafPredictionCol(),
            featuresShapCol=self.getFeaturesShapCol(),
            numClasses=self._inferred_classes,
            boosterData=result.booster.to_dict(),
        )


class LightGBMClassificationModel(LightGBMModelBase):
    rawPredictionCol = Param("Raw margin output column", default="rawPrediction", converter=to_str)
    probabilityCol = Param("Probability output column", default="probability", converter=to_str)
    numClasses = Param("Number of classes", default=2, converter=to_int)

    def transform(self, table: Table) -> Table:
        booster = self.booster
        X = extract_features(table, self.getFeaturesCol(), booster.num_features)
        margins = booster.raw_margin(X)  # (N, C)
        if booster.num_classes == 1:
            # binary: sigmoid fixup (LightGBMBooster.scala:312-328)
            p1 = 1.0 / (1.0 + np.exp(-margins[:, 0]))
            probs = np.stack([1.0 - p1, p1], axis=1)
            raw = np.stack([-margins[:, 0], margins[:, 0]], axis=1)
        else:
            m = margins - margins.max(axis=1, keepdims=True)
            e = np.exp(m)
            probs = e / e.sum(axis=1, keepdims=True)
            raw = margins
        pred = probs.argmax(axis=1).astype(np.float64)
        out = (
            table.with_column(self.getRawPredictionCol(), raw)
            .with_column(self.getProbabilityCol(), probs)
            .with_column(self.getPredictionCol(), pred)
        )
        return self._with_leaf_col(out, X)
