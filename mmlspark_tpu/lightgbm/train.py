"""GBDT training loop: leaf-wise (LightGBM semantics) and level-wise growth,
jitted per-iteration step.

Replaces the reference's native training core (``LGBM_BoosterUpdateOneIter``
driven from ``lightgbm/TrainUtils.scala:220-315``) with a single jitted XLA
program per boosting iteration:

  gradients → histogram pass(es) → split search over the
  (node, feature, bin) lattice → routing update → leaf values → margins.

Two growth policies, both emitting pointer-based trees (see booster.py):

- ``leafwise`` (default — LightGBM's defining best-first algorithm,
  ``numLeaves`` bounds the *leaf count*, ``LightGBMParams.scala:13-251``):
  ``num_leaves - 1`` sequential splits; each step picks the frontier leaf
  with the best cached gain, routes its rows, and builds the two-child
  histogram in ONE masked one-hot pass over all rows. Static shapes
  throughout — the per-split histogram matmul is (N x 2B) so total FLOPs
  match a level-wise build of the same leaf count.
- ``depthwise``: every level is ONE dense histogram pass over all rows —
  fewer, larger MXU matmuls; the fast path when balanced trees are fine.

Early stopping, eval-metric direction, and improvement tolerance follow
``TrainUtils.scala:276-315``.

Distribution (``tree_learner=data_parallel``): rows are sharded over the
mesh ``data`` axis; the histogram is a row-sum, so XLA inserts the
cross-device all-reduce — the ``lax.psum`` equivalent of LightGBM's socket
allreduce. Split decisions are computed identically on every device from the
reduced histogram, so routing needs no further communication.
``tree_learner=voting_parallel`` (``topK``, ``LightGBMParams.scala:20-24``)
reduces only the top-K-voted features' histograms — see ``ops/voting.py``.
"""

from __future__ import annotations

import collections
import dataclasses
import math
import time
from functools import lru_cache, partial
from typing import Any, Dict, List, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from mmlspark_tpu.lightgbm.binning import BinMapper
from mmlspark_tpu.observability.profiler import get_profiler
from mmlspark_tpu.lightgbm.booster import Booster
from mmlspark_tpu.lightgbm.objectives import (
    METRICS,
    Objective,
    get_objective,
    metric_higher_is_better,
)
from mmlspark_tpu.ops.histogram import build_histograms


@dataclasses.dataclass
class TrainOptions:
    """Native ``TrainParams`` equivalent (``lightgbm/TrainParams.scala:8-128``),
    defaults matching ``LightGBMParams.scala:13-251``."""

    objective: str = "binary"
    num_iterations: int = 100
    learning_rate: float = 0.1
    num_leaves: int = 31
    max_depth: int = -1  # -1: unbounded (leafwise) / derived (depthwise)
    max_bin: int = 255
    lambda_l1: float = 0.0
    lambda_l2: float = 0.0
    min_data_in_leaf: int = 20
    min_sum_hessian_in_leaf: float = 1e-3
    min_gain_to_split: float = 0.0
    bagging_fraction: float = 1.0
    # class-stratified bagging (LightGBM pos/neg_bagging_fraction; 1.0 = off,
    # both must be set together with bagging_freq to take effect)
    pos_bagging_fraction: float = 1.0
    neg_bagging_fraction: float = 1.0
    bagging_freq: int = 0
    feature_fraction: float = 1.0
    max_delta_step: float = 0.0
    num_class: int = 1
    alpha: float = 0.9  # quantile/huber
    tweedie_variance_power: float = 1.5
    boosting_type: str = "gbdt"
    metric: Optional[str] = None
    early_stopping_round: int = 0
    improvement_tolerance: float = 0.0
    seed: int = 0
    histogram_method: Optional[str] = None
    growth: str = "leafwise"  # leafwise | depthwise
    tree_learner: str = "data_parallel"  # data_parallel | voting_parallel
    top_k: int = 20  # voting_parallel vote width
    top_rate: float = 0.2  # goss: kept fraction of large-gradient rows
    other_rate: float = 0.1  # goss: sampled fraction of the rest
    drop_rate: float = 0.1  # dart: per-tree drop probability
    leaf_batch: int = 8  # frontier leaves split per histogram pass (1 = exact best-first)
    # LightGBM's gradient-quantization training (use_quantized_grad): g/h
    # stochastically rounded to a 127-level per-tree grid so the U-pass
    # histogram contraction runs s8 x s8 on the int MXU (2x the ops/cycle
    # of bf16) — per-bin sums stay unbiased, counts exact below 2^24 rows
    # (the f32 integer-exactness limit; the row gate enforces it). Only
    # affects fits on the precomputed-U path; off = bit-exact bf16 stats.
    use_quantized_grad: bool = False
    # Sibling histogram subtraction (native LightGBM's always-on trick,
    # exposed as a knob for A/B measurement): build only the SMALLER child
    # of each split and derive the sibling as parent - smaller, in packed
    # (pre-EFB-expansion) space — integer-exact on the quantized path, so
    # subtraction on/off grows byte-identical trees there. Off = build
    # both children directly (the measurement baseline).
    histogram_subtraction: bool = True
    # only batch leaves with gain >= ratio * pass-best (0 = off): tightens
    # multi-leaf passes toward best-first; 1.0 reproduces leaf_batch=1
    leaf_batch_ratio: float = 0.0
    # categorical split search (LightGBMParams.scala:125-133 forwards these
    # to native LightGBM; same names/defaults as the native engine):
    categorical_slots: tuple = ()  # feature indices treated as categorical
    max_cat_threshold: int = 32  # max categories in a split's left set
    cat_smooth: float = 10.0  # smoothing for the g/h category sort
    cat_l2: float = 10.0  # extra L2 applied to categorical split gains
    # one-vs-rest split search for categorical features with at most this
    # many seen categories (native LightGBM's max_cat_to_onehot; the engine
    # the reference forwards to switches algorithms on this boundary)
    max_cat_to_onehot: int = 4
    # sorted-path candidate gate: categories with fewer rows than this never
    # enter the g/h-ratio sort (native min_data_per_group; the one-vs-rest
    # path is exempt, as in the native engine)
    min_data_per_group: int = 100
    # derived from the mapper at fit time: the categorical_slots subset that
    # uses the one-vs-rest search (static => part of the program cache key)
    onehot_slots: tuple = ()
    # boost_from_average=False: margins start at 0 instead of the
    # objective's average-based init score (LightGBMParams boostFromAverage)
    boost_from_average: bool = True
    # compute the train-set metric each iteration into evals["training"]
    # (isProvideTrainingMetric; forces the per-iteration loop path)
    provide_training_metric: bool = False
    verbosity: int = -1

    @property
    def depth(self) -> int:
        """Static depth of a depthwise tree."""
        if self.max_depth and self.max_depth > 0:
            return self.max_depth
        return max(1, math.ceil(math.log2(max(2, self.num_leaves))))

    @property
    def num_nodes(self) -> int:
        """Node-slot count M of one tree in pointer layout."""
        if self.growth == "depthwise":
            return 2 ** (self.depth + 1) - 1
        return 2 * self.num_leaves - 1

    @property
    def routing_steps(self) -> int:
        """Static bound on tree depth for routing loops."""
        if self.growth == "depthwise":
            return self.depth
        if self.max_depth and self.max_depth > 0:
            return min(self.max_depth, self.num_leaves - 1)
        return self.num_leaves - 1


@dataclasses.dataclass
class TrainResult:
    booster: Booster
    evals: Dict[str, Dict[str, List[float]]]  # set name -> metric -> history
    best_iteration: int


class TreeArrays(NamedTuple):
    """One tree in pointer layout (each (M,) — or (C, M) after vmap)."""

    feat: jax.Array
    bin: jax.Array
    thr: jax.Array
    left: jax.Array
    right: jax.Array
    is_leaf: jax.Array
    leaf_val: jax.Array
    cover: jax.Array
    gain: jax.Array
    row_leaf: jax.Array  # (N,) final leaf slot of every training row
    cat_node: jax.Array  # (M,) bool: categorical split at this node
    cat_mask: jax.Array  # (M, B) bool left-set bins ((M, 1) placeholder when no cat)


class SplitSearch(NamedTuple):
    """Per-node best-split candidates from one histogram batch (each (k,))."""

    value: jax.Array  # own leaf value (lr-scaled)
    cover: jax.Array  # row count
    hess: jax.Array  # hessian sum
    gain: jax.Array  # best gain, -inf if unsplittable
    feat: jax.Array
    bin: jax.Array
    thr: jax.Array  # raw-value threshold
    lval: jax.Array  # left child value if split (lr-scaled)
    rval: jax.Array
    lcov: jax.Array
    rcov: jax.Array
    is_cat: jax.Array  # (k,) bool: categorical split (bin = the prefix-
    # defining BIN id; the left set itself lives in cat_mask)
    cat_mask: jax.Array  # (k, B) bool: bins in the LEFT set (all-False if numeric)
    value_cat: jax.Array  # (k,) own leaf value under l2+cat_l2 (cat-parent case)


def _soft_threshold(g: jax.Array, l1: float) -> jax.Array:
    if l1 == 0.0:
        return g
    return jnp.sign(g) * jnp.maximum(jnp.abs(g) - l1, 0.0)


@lru_cache(maxsize=None)
def _cat_static_maps(
    cat_slots: tuple, onehot_slots: tuple, num_features: int
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Host-side index maps for the categorical split search, memoized on
    the (static) slot tuples so none of the numpy setup runs under trace:
    sorted categorical feature indices, the is-categorical mask, the
    feature -> categorical-slice position map, and the one-vs-rest mask."""
    cat_idx = np.asarray(sorted(cat_slots), np.int32)
    is_cat = np.zeros(num_features, bool)
    is_cat[cat_idx] = True
    inv = np.zeros(num_features, np.int32)
    inv[cat_idx] = np.arange(len(cat_idx))
    onehot = np.isin(cat_idx, np.asarray(onehot_slots, np.int32))
    return cat_idx, is_cat, inv, onehot


def _split_search(
    hist: jax.Array,  # (k, F, B, 3)
    totals: jax.Array,  # (k, 3) exact per-node [sum_g, sum_h, count]
    edges: jax.Array,  # (F, E)
    feature_mask: jax.Array,  # (F,)
    opts: TrainOptions,
    lr=None,  # traced per-iteration learning rate (dynamic-LR callbacks)
) -> SplitSearch:
    """Best split per node from its histogram — the split-finding core the
    native library runs per leaf (``TrainUtils.scala:220-315`` inner loop)."""
    k, f, b, _ = hist.shape
    l1, l2 = opts.lambda_l1, opts.lambda_l2
    if lr is None:
        lr = opts.learning_rate

    g_tot, h_tot, c_tot = totals[:, 0], totals[:, 1], totals[:, 2]

    # Left stats at "<= bin": a lower-triangular ones-matmul over the bin
    # axis instead of jnp.cumsum — XLA lowers cumsum to reduce-window on
    # TPU (measured 0.27 ms per search at B=256, ~1.4 ms/tree), while the
    # (B, B) triangle rides the MXU for free. Counts stay exact below
    # 2^24 rows (0/1 triangle x integer sums; f32 holds integers exactly
    # only up to 2^24 — the quantized-path row gate enforces the bound,
    # and the exact path's counts carry the same f32 caveat past it);
    # g/h association differs from
    # reduce-window's only within f32 rounding, which the cumsum lowering
    # never specified either.
    tri = jnp.tril(jnp.ones((b, b), jnp.float32))
    cum = jnp.einsum(
        "ij,kfjs->kfis", tri, hist, precision=lax.Precision.HIGHEST
    )  # (k, F, B, 3)
    gl, hl, cl = cum[..., 0], cum[..., 1], cum[..., 2]
    gr = g_tot[:, None, None] - gl
    hr = h_tot[:, None, None] - hl
    cr = c_tot[:, None, None] - cl

    tl, tr = _soft_threshold(gl, l1), _soft_threshold(gr, l1)
    tg = _soft_threshold(g_tot, l1)
    parent_score = (tg * tg) / (h_tot + l2)  # (k,)
    gain = tl * tl / (hl + l2) + tr * tr / (hr + l2) - parent_score[:, None, None]

    valid = (
        (cl >= opts.min_data_in_leaf)
        & (cr >= opts.min_data_in_leaf)
        & (hl >= opts.min_sum_hessian_in_leaf)
        & (hr >= opts.min_sum_hessian_in_leaf)
        & (jnp.arange(b)[None, None, :] < b - 1)
        & (feature_mask[None, :, None] > 0)
    )
    gain = jnp.where(valid, gain, -jnp.inf)

    # Categorical split search (LightGBM's sorted-prefix algorithm, native
    # FindBestThresholdCategoricalInner): bins of a categorical feature sort
    # by sum_g / (sum_h + cat_smooth) and the candidate left sets are the
    # prefixes of that order — scanned in BOTH directions (a small
    # high-ratio set is a short descending prefix), capped at
    # max_cat_threshold categories, with lambda_l2 + cat_l2 regularization.
    # The missing bin 0 never enters a left set (unseen/NaN routes right).
    has_cat = bool(opts.categorical_slots)
    if has_cat:
        # All sorted-prefix machinery runs on the (k, F_cat, B) SLICE only —
        # sorts are the expensive primitive here, and categorical features
        # are typically a small subset of the matrix.
        cat_idx_np, cf_np, inv_np, oh_np = _cat_static_maps(
            opts.categorical_slots, opts.onehot_slots, f
        )
        cat_idx = jnp.asarray(cat_idx_np)
        hist_c = hist[:, cat_idx]  # (k, Fc, B, 3)
        gsum, hsum, cnt = hist_c[..., 0], hist_c[..., 1], hist_c[..., 2]
        jpos = jnp.arange(b)[None, None, :]
        # min_data_per_group gates the SORTED candidates (native builds its
        # sorted_idx list only from categories with enough rows; the
        # one-vs-rest path below is exempt, also as in native)
        nonempty = (cnt >= max(1, opts.min_data_per_group)) & (jpos > 0)
        ratio = gsum / (hsum + opts.cat_smooth)
        l2c = l2 + opts.cat_l2
        parent_c = (tg * tg) / (h_tot + l2c)  # tg shared with the numeric branch
        fm_c = feature_mask[cat_idx]
        # Sorted-prefix search WITHOUT sorting: the prefix of the g/h-ratio
        # order ending at category i is exactly {j : key_j <= key_i} (ties
        # broken by bin index, = a stable sort's order), so each candidate's
        # prefix sums are one masked einsum against the (B, B) order-
        # indicator M — dense MXU/VPU work replacing the per-pass argsort +
        # gather + cumsum chain (which also made the CPU test battery ~2x
        # slower). Candidate index = BIN id (the prefix-defining category),
        # and the winner's left-set mask is just M's row — no order
        # permutation to invert. Both scan directions ride a leading axis d
        # (0 = ascending ratio, 1 = descending).
        keys = jnp.stack([ratio, -ratio], axis=0)  # (2, k, Fc, B)
        ki = keys[..., :, None]  # key_i, candidate axis
        kj = keys[..., None, :]  # key_j, member axis
        tie = jnp.arange(b)[None, :] <= jnp.arange(b)[:, None]  # j <= i
        M = ((kj < ki) | ((kj == ki) & tie)) & nonempty[None, ..., None, :]
        Mf = M.astype(jnp.float32)
        hp = lax.Precision.HIGHEST

        def prefix(stat):  # (k, Fc, B) member sums -> (2, k, Fc, B) per candidate
            return jnp.einsum("dkfij,kfj->dkfi", Mf, stat, precision=hp)

        sg, sh, sc = prefix(gsum), prefix(hsum), prefix(cnt)
        sizes = prefix(nonempty.astype(jnp.float32))
        grc = g_tot[None, :, None, None] - sg
        hrc = h_tot[None, :, None, None] - sh
        crc = c_tot[None, :, None, None] - sc
        tlc, trc = _soft_threshold(sg, l1), _soft_threshold(grc, l1)
        gain_c = (
            tlc * tlc / (sh + l2c)
            + trc * trc / (hrc + l2c)
            - parent_c[None, :, None, None]
        )
        valid_c = (
            nonempty[None]  # the prefix-defining category itself qualifies
            & (sizes <= opts.max_cat_threshold)
            & (sc >= opts.min_data_in_leaf)
            & (crc >= opts.min_data_in_leaf)
            & (sh >= opts.min_sum_hessian_in_leaf)
            & (hrc >= opts.min_sum_hessian_in_leaf)
            & (fm_c[None, None, :, None] > 0)
        )
        gain_dirs = jnp.where(valid_c, gain_c, -jnp.inf)  # (2, k, Fc, B)
        gain_cat = jnp.maximum(gain_dirs[0], gain_dirs[1])
        use_desc = gain_dirs[1] > gain_dirs[0]  # (k, Fc, B)

        # One-vs-rest search (native use_onehot, max_cat_to_onehot): for
        # small-cardinality features the candidates are the SINGLE-category
        # left sets {bin j} — position j in the gain plane IS bin j (no sort
        # order involved). Same lambda_l2 + cat_l2 regularization; no
        # cat_smooth, no min_data_per_group (native's one-hot loop applies
        # neither). Bin 0 (unseen/NaN) never splits left.
        if oh_np.any():
            gr_oh = g_tot[:, None, None] - gsum
            hr_oh = h_tot[:, None, None] - hsum
            cr_oh = c_tot[:, None, None] - cnt
            tl_oh = _soft_threshold(gsum, l1)
            tr_oh = _soft_threshold(gr_oh, l1)
            gain_oh = (
                tl_oh * tl_oh / (hsum + l2c)
                + tr_oh * tr_oh / (hr_oh + l2c)
                - parent_c[:, None, None]
            )
            valid_oh = (
                (jpos > 0)
                & (cnt >= opts.min_data_in_leaf)
                & (cr_oh >= opts.min_data_in_leaf)
                & (hsum >= opts.min_sum_hessian_in_leaf)
                & (hr_oh >= opts.min_sum_hessian_in_leaf)
                & (fm_c[None, :, None] > 0)
            )
            gain_oh = jnp.where(valid_oh, gain_oh, -jnp.inf)
            oh_mask = jnp.asarray(oh_np)  # (Fc,) static
            gain_cat = jnp.where(oh_mask[None, :, None], gain_oh, gain_cat)
        gain = gain.at[:, cat_idx, :].set(gain_cat)

    flat = gain.reshape(k, f * b)
    best_idx = jnp.argmax(flat, axis=1)  # (k,)
    best_gain = jnp.take_along_axis(flat, best_idx[:, None], axis=1)[:, 0]
    best_f = (best_idx // b).astype(jnp.int32)
    best_b = (best_idx % b).astype(jnp.int32)

    def leaf_value(g, h):
        v = -_soft_threshold(g, l1) / (h + l2)
        if opts.max_delta_step > 0:
            v = jnp.clip(v, -opts.max_delta_step, opts.max_delta_step)
        return v * lr

    iota = jnp.arange(k)
    glb = gl[iota, best_f, best_b]
    hlb = hl[iota, best_f, best_b]
    clb = cl[iota, best_f, best_b]

    # Raw threshold: split bin t means "x <= edges[f, t-1]"; t=0 ⇒ NaN-only left.
    thr_raw = edges[best_f, jnp.maximum(best_b - 1, 0)]
    thr_raw = jnp.where(best_b == 0, -jnp.inf, thr_raw).astype(jnp.float32)

    is_cat_best = jnp.zeros(k, bool)
    cat_mask = jnp.zeros((k, b), bool)
    if has_cat:
        # Native parity: leaves created BY a categorical split get outputs
        # regularized with lambda_l2 + cat_l2 (LightGBM's
        # CalculateSplittedLeafOutput for the categorical path).
        def leaf_value_cat(g, h):
            v = -_soft_threshold(g, l1) / (h + l2 + opts.cat_l2)
            if opts.max_delta_step > 0:
                v = jnp.clip(v, -opts.max_delta_step, opts.max_delta_step)
            return v * lr

        is_cat_best = jnp.asarray(cf_np)[best_f]  # (k,)
        cpos = jnp.asarray(inv_np)[best_f]  # (k,) index into the cat slice
        dsel = use_desc[iota, cpos, best_b].astype(jnp.int32)  # (k,) direction

        glb_c = sg[dsel, iota, cpos, best_b]
        hlb_c = sh[dsel, iota, cpos, best_b]
        clb_c = sc[dsel, iota, cpos, best_b]
        # One-vs-rest winners read their left stats STRAIGHT from the
        # histogram at bin best_b (no prefix involved).
        is_oh_best = (
            jnp.asarray(oh_np)[cpos] & is_cat_best
            if oh_np.any() else jnp.zeros(k, bool)
        )
        if oh_np.any():
            glb_c = jnp.where(is_oh_best, gsum[iota, cpos, best_b], glb_c)
            hlb_c = jnp.where(is_oh_best, hsum[iota, cpos, best_b], hlb_c)
            clb_c = jnp.where(is_oh_best, cnt[iota, cpos, best_b], clb_c)
        glb = jnp.where(is_cat_best, glb_c, glb)
        hlb = jnp.where(is_cat_best, hlb_c, hlb)
        clb = jnp.where(is_cat_best, clb_c, clb)
        thr_raw = jnp.where(is_cat_best, jnp.inf, thr_raw)
        # Left-set membership: the winning candidate's row of M IS the set.
        cat_mask = M[dsel, iota, cpos, best_b, :] & is_cat_best[:, None]
        if oh_np.any():
            # one-vs-rest left set = exactly {best_b}
            cat_mask = jnp.where(
                is_oh_best[:, None],
                jnp.arange(b)[None, :] == best_b[:, None],
                cat_mask,
            )
        lval = jnp.where(
            is_cat_best, leaf_value_cat(glb, hlb), leaf_value(glb, hlb)
        )
        rval = jnp.where(
            is_cat_best,
            leaf_value_cat(g_tot - glb, h_tot - hlb),
            leaf_value(g_tot - glb, h_tot - hlb),
        )
        value_cat = leaf_value_cat(g_tot, h_tot)
    else:
        lval = leaf_value(glb, hlb)
        rval = leaf_value(g_tot - glb, h_tot - hlb)
        value_cat = leaf_value(g_tot, h_tot)

    return SplitSearch(
        value=leaf_value(g_tot, h_tot),
        cover=c_tot,
        hess=h_tot,
        gain=best_gain,
        feat=best_f,
        bin=best_b,
        thr=thr_raw,
        lval=lval,
        rval=rval,
        lcov=clb,
        rcov=c_tot - clb,
        is_cat=is_cat_best,
        cat_mask=cat_mask,
        value_cat=value_cat,
    )


def _bundle_route_consts(bundle):
    """Device views of the per-original-feature routing arrays (col, lo,
    span, skip, dflt) — host lru-cached numpy underneath, so traces close
    over stable constants."""
    from mmlspark_tpu.lightgbm.bundling import route_maps

    return tuple(jnp.asarray(a) for a in route_maps(bundle))


def _orig_bins(packed_cols, feats, consts):
    """Packed column values → ORIGINAL-feature bin ids at a routing site.

    ``packed_cols`` holds bin values already gathered from each feature's
    packed column (any shape broadcastable with ``feats``); ``feats`` are
    original feature ids. q = xb - lo recovers the member-local offset,
    the +1 skip jump crosses the member's elided default bin, and any
    out-of-span value means some OTHER member of the bundle was
    non-default — i.e. this feature sat at its default bin."""
    _, lo, span, skip, dflt = consts
    xb = packed_cols.astype(jnp.int32)
    q = xb - lo[feats]
    inb = (q >= 0) & (q < span[feats])
    return jnp.where(inb, q + (q >= skip[feats]).astype(jnp.int32), dflt[feats])


def _expand_bundled(h, totals, bundle, num_bins):
    """Bundle-space histogram (k, C, B_b, 3) → original space (k, F, B, 3).

    Runs ONCE per pass, after the optional cross-process reduce (so the
    allreduce payload stays in the smaller packed space). Each original
    feature's non-default bins gather straight out of its packed column;
    the default bin is recovered by subtraction from the per-node totals
    (LightGBM's most_freq_bin trick) — counts stay exact, grad/hess exact
    up to f32 association order."""
    from mmlspark_tpu.lightgbm.bundling import expand_maps

    cidx, gmask, dmask = expand_maps(bundle, num_bins)
    k = h.shape[0]
    flat = h.reshape(k, -1, 3)  # (k, C*B_b, 3)
    dense = jnp.take(flat, jnp.asarray(cidx.reshape(-1)), axis=1)
    dense = dense.reshape(k, bundle.num_features, num_bins, 3)
    dense = dense * jnp.asarray(gmask)[None, :, :, None]
    resid = totals[:, None, :] - dense.sum(axis=2)
    return dense + jnp.asarray(dmask)[None, :, :, None] * resid[:, :, None, :]


def _hist_fn(opts: TrainOptions, mesh=None, u_spec=None, hist_reduce=None,
             bundle=None):
    """Histogram builder honoring the tree_learner choice. Returns a
    callable producing (hist (k,F,B,3), totals (k,3)); ``feature_mask``
    (featureFraction) steers voting so reduced histograms are spent only
    on splittable features.

    ``hist_reduce`` is the cross-PROCESS reduction hook (data-parallel
    fit over OS processes, ``lightgbm/procfit.py``): a host callable
    summing the local histogram across the worker gang — LightGBM's
    socket ``Network::Allreduce`` at the same point in the algorithm. It
    is injected via ``jax.pure_callback`` right after the local build, so
    everything downstream (totals, split search, leaf values) sees GLOBAL
    statistics and every member grows byte-identical trees. The histogram
    is the only tensor that crosses processes; its shape is row-count
    independent, so members with different shard sizes stay aligned.

    When ``u_spec`` is set and the caller passes the fit-resident ``u``
    one-hot (``ops/u_histogram.py``), passes whose panel fits one lane
    group run as a single MXU contraction against U — measured 2.1x the
    compare-built kernel at the bench hot shape; wider passes (deep
    depthwise levels) fall back to the compare-built path."""
    if opts.tree_learner == "voting_parallel":
        from mmlspark_tpu.ops.voting import build_histograms_voting

        vfull = partial(
            build_histograms_voting,
            top_k=opts.top_k,
            mesh=mesh,
            # 'u' has no meaning inside the voting reducer — auto-pick there
            method=None if opts.histogram_method == "u" else opts.histogram_method,
        )

        def voting(bins, grad, hess, count, node, num_nodes, num_bins,
                   feature_mask=None, u=None, stats=None):
            return vfull(bins, grad, hess, count, node, num_nodes, num_bins,
                         feature_mask=feature_mask)

        return voting

    method = opts.histogram_method
    if method == "u":
        method = None  # 'u' forces the U path; fallback shape-gated passes auto-pick
    if mesh is not None and method in (None, "pallas"):
        # pallas_call has no GSPMD partitioning rule: under jit with
        # row-sharded inputs it cannot shard over the data axis the way the
        # plain-XLA formulations do, so the mesh path sticks to those.
        method = "onehot" if jax.default_backend() in ("tpu", "axon") else "segment"

    def packed(bins, grad, hess, count, node, num_nodes, num_bins,
               feature_mask=None, u=None, stats=None):
        """SPEC-space histogram (k, C, B_b, 3) + per-node totals — the
        pass BEFORE dequantization and bundle expansion. This is the
        representation the sibling-subtraction cache lives in: packed
        columns (C <= F under EFB) and, on the quantized U path, the
        narrow integer accumulator dtype — so parent - child is an exact
        integer subtraction and the allreduce payload stays minimal."""
        if u is not None and u_spec is not None and 3 * num_nodes <= 128:
            if u_spec.chunk_rows:
                from mmlspark_tpu.ops.u_histogram import (
                    build_histograms_u_chunked,
                )

                h = build_histograms_u_chunked(
                    u, grad, hess, count, node, num_nodes, u_spec,
                    stats=stats, dequant=False,
                )
            else:
                from mmlspark_tpu.ops.u_histogram import build_histograms_u

                h = build_histograms_u(
                    u, grad, hess, count, node, num_nodes, u_spec,
                    stats=stats, dequant=False,
                )
        else:
            h = build_histograms(
                bins, grad, hess, count, node, num_nodes,
                bundle.num_bins if bundle is not None else num_bins,
                method=method, chunk_rows=(mesh is None),
            )
        if hist_reduce is not None:
            # host round-trip per histogram pass; "expand_dims" keeps one
            # callback call under the per-class vmap so gang members make
            # identical, aligned allreduce sequences. Runs in the packed
            # space, so under sibling subtraction the gang allreduces only
            # the smaller child's histograms (the quant path never reaches
            # here — procfit rejects it — so the payload is always f32).
            h = jax.pure_callback(
                hist_reduce, jax.ShapeDtypeStruct(h.shape, h.dtype), h,
                vmap_method="expand_dims",
            )
        totals = h[:, 0, :, :].sum(axis=1)  # feature/column 0 covers all rows
        return h, totals

    def expand(h, totals, num_bins, stats=None):
        """Finish a ``packed`` result for the split search: apply the
        deferred quant scales (exactly once, AFTER any subtraction), then
        expand EFB's packed columns back to original feature space
        (``num_bins`` = the ORIGINAL bin width the search expects)."""
        if jnp.issubdtype(h.dtype, jnp.integer):
            from mmlspark_tpu.ops.u_histogram import dequant_hist

            scales = stats[1]
            h = dequant_hist(h, scales)
            totals = dequant_hist(totals, scales)
        if bundle is not None:
            h = _expand_bundled(h, totals, bundle, num_bins)
        return h, totals

    def full(bins, grad, hess, count, node, num_nodes, num_bins,
             feature_mask=None, u=None, stats=None):
        h, totals = packed(
            bins, grad, hess, count, node, num_nodes, num_bins,
            feature_mask=feature_mask, u=u, stats=stats,
        )
        return expand(h, totals, num_bins, stats=stats)

    full.packed = packed
    full.expand = expand
    return full


# ---------------------------------------------------------------------------
# Depthwise (level-wise) growth — one histogram pass per level.
# ---------------------------------------------------------------------------


def _build_tree_depthwise(
    bins: jax.Array,  # (N, F) int32
    grad: jax.Array,  # (N,)
    hess: jax.Array,  # (N,)
    count: jax.Array,  # (N,) 1/0 bagging presence
    edges: jax.Array,  # (F, E) float32 raw-value bin edges
    feature_mask: jax.Array,  # (F,) float32 0/1
    *,
    num_bins: int,
    opts: TrainOptions,
    histf,
    lr=None,
    u=None,
    qkey=None,
    bundle=None,
) -> TreeArrays:
    n = bins.shape[0]
    b = num_bins
    depth = opts.depth
    stats = _tree_stats(grad, hess, count, qkey) if u is not None else None
    rconsts = _bundle_route_consts(bundle) if bundle is not None else None

    node = jnp.zeros(n, dtype=jnp.int32)  # heap position
    alive = jnp.ones(1, dtype=bool)
    inherited = jnp.zeros(1, dtype=jnp.float32)
    cover_cur = jnp.zeros(1, dtype=jnp.float32)

    has_cat = bool(opts.categorical_slots)
    feat_lv, bin_lv, thr_lv, cover_lv, gain_lv = [], [], [], [], []
    iscat_lv, catmask_lv = [], []

    for d in range(depth):
        k = 1 << d
        offset = k - 1
        local = node - offset
        hist, totals = histf(
            bins, grad, hess, count, local, k, b, feature_mask=feature_mask,
            u=u, stats=stats,
        )
        # (k, F, B, 3) — row-sum: XLA all-reduces across data shards here.
        s = _split_search(hist, totals, edges, feature_mask, opts, lr=lr)

        can_split = alive & jnp.isfinite(s.gain) & (s.gain > opts.min_gain_to_split)
        # A node's value-if-it-ends-here is what its PARENT's split assigned
        # (``inherited`` — which carries the l2+cat_l2 output for children of
        # categorical splits); recomputing from own totals would silently
        # drop that regularization. The root has no parent: use its own.
        value_cur = s.value if d == 0 else inherited
        cover_here = jnp.where(alive, s.cover, cover_cur)

        # Record this level (dead/non-split nodes: bin=b ⇒ every row left, thr=+inf).
        feat_lv.append(jnp.where(can_split, s.feat, 0))
        bin_lv.append(jnp.where(can_split, s.bin, b))
        thr_lv.append(jnp.where(can_split, s.thr, jnp.inf).astype(jnp.float32))
        cover_lv.append(cover_here)
        gain_lv.append(jnp.where(can_split, s.gain, 0.0))
        if has_cat:
            iscat_lv.append(can_split & s.is_cat)
            catmask_lv.append(s.cat_mask & can_split[:, None])

        # Route rows down one level. Split features/bins live in ORIGINAL
        # space (histograms are expanded before the search); under bundling
        # the row's value gathers from the feature's packed column and
        # decodes back to an original bin before the compare.
        row_f = feat_lv[-1][local]
        row_b = bin_lv[-1][local]
        row_c = rconsts[0][row_f] if rconsts is not None else row_f
        x_bin = jnp.take_along_axis(bins, row_c[:, None], axis=1)[:, 0]
        if rconsts is not None:
            x_bin = _orig_bins(x_bin, row_f, rconsts)
        go_right = x_bin > row_b
        if has_cat:
            ic = iscat_lv[-1][local]
            cm = catmask_lv[-1].reshape(-1)[local * b + x_bin.astype(jnp.int32)]
            go_right = jnp.where(ic, ~cm, go_right)
        go_right = go_right.astype(jnp.int32)
        node = 2 * node + 1 + go_right

        inherited = jnp.stack(
            [
                jnp.where(can_split, s.lval, value_cur),
                jnp.where(can_split, s.rval, value_cur),
            ],
            axis=1,
        ).reshape(2 * k)
        cover_cur = jnp.stack(
            [
                jnp.where(can_split, s.lcov, cover_here),
                jnp.where(can_split, s.rcov, 0.0),
            ],
            axis=1,
        ).reshape(2 * k)
        alive = jnp.repeat(can_split, 2)

    # Heap → pointer layout: internal slots 0..2^D-2, leaves 2^D-1..2^(D+1)-2.
    internal = 2**depth - 1
    leaves = 2**depth
    iota = jnp.arange(internal, dtype=jnp.int32)
    zeros_l = jnp.zeros(leaves, dtype=jnp.int32)
    return TreeArrays(
        feat=jnp.concatenate([jnp.concatenate(feat_lv), zeros_l]),
        bin=jnp.concatenate([jnp.concatenate(bin_lv), jnp.full(leaves, b, jnp.int32)]),
        thr=jnp.concatenate(
            [jnp.concatenate(thr_lv), jnp.full(leaves, jnp.inf, jnp.float32)]
        ),
        left=jnp.concatenate([2 * iota + 1, zeros_l]),
        right=jnp.concatenate([2 * iota + 2, zeros_l]),
        is_leaf=jnp.concatenate(
            [jnp.zeros(internal, bool), jnp.ones(leaves, bool)]
        ),
        leaf_val=jnp.concatenate([jnp.zeros(internal, jnp.float32), inherited]),
        cover=jnp.concatenate([jnp.concatenate(cover_lv), cover_cur]),
        gain=jnp.concatenate([jnp.concatenate(gain_lv), jnp.zeros(leaves, jnp.float32)]),
        row_leaf=node,  # already absolute pointer slots
        cat_node=(
            jnp.concatenate([jnp.concatenate(iscat_lv), jnp.zeros(leaves, bool)])
            if has_cat else jnp.zeros(internal + leaves, bool)
        ),
        cat_mask=(
            jnp.concatenate(
                [jnp.concatenate(catmask_lv), jnp.zeros((leaves, b), bool)]
            )
            if has_cat else jnp.zeros((internal + leaves, 1), bool)
        ),
    )


# ---------------------------------------------------------------------------
# Leaf-wise (best-first) growth — LightGBM's algorithm.
# ---------------------------------------------------------------------------


def _build_tree_leafwise(
    bins: jax.Array,
    grad: jax.Array,
    hess: jax.Array,
    count: jax.Array,
    edges: jax.Array,
    feature_mask: jax.Array,
    *,
    num_bins: int,
    opts: TrainOptions,
    histf,
    lr=None,
    u=None,
    u_spec=None,
    qkey=None,
    bundle=None,
) -> TreeArrays:
    """Best-first growth, ``leaf_batch`` frontier leaves per histogram pass.

    Each pass splits the top-``k`` frontier leaves by cached candidate gain
    in ONE node-keyed histogram pass — the panel formulation
    (``ops/pallas_histogram.py``) makes a k-node pass cost the same as a
    1-node pass, so a 31-leaf tree costs ~6 passes instead of 30. ``k = 1``
    is LightGBM's exact sequential best-first; ``k > 1`` approximates it
    (the k-th split is committed before the first split's children can
    compete — ties and near-ties resolve in frontier-gain order, then by
    lower slot index, matching ``lax.top_k``'s ordering). Slots are
    allocated densely in split order: the j-th split overall creates slots
    2j+1 and 2j+2, so the layout is deterministic and static-shaped
    (M = 2*num_leaves - 1) and ``k = 1`` reproduces the sequential layout
    bit-for-bit."""
    # Under bundling ``bins`` is (N, C) packed columns while the histogram
    # cache / subtraction / search all live in ORIGINAL feature space —
    # f here sizes those, NOT the packed width.
    n = bins.shape[0]
    f = bundle.num_features if bundle is not None else bins.shape[1]
    rconsts = _bundle_route_consts(bundle) if bundle is not None else None
    b = num_bins
    num_leaves = opts.num_leaves
    m = 2 * num_leaves - 1
    max_depth = opts.max_depth if (opts.max_depth and opts.max_depth > 0) else m

    # Histogram subtraction (LightGBM's core trick): cache every frontier
    # leaf's histogram, build only the SMALLER child of each split per
    # pass, and derive the sibling as parent - smaller — halving the node
    # count of the hot pass from 2k to k AND keying the pass on the child
    # with fewer rows. The cache lives in PACKED space — (M, C, B_b, 3)
    # where C is the EFB-packed column count and, on the quantized U path,
    # the narrow integer accumulator dtype — so subtraction is an exact
    # integer op before dequantization/expansion and the cache shrinks
    # with the K-reduction. Gated by a memory budget on that cache — which
    # the boosting step vmaps over num_class, so the budget multiplies by
    # the class count — and off under voting-parallel (its histograms only
    # carry the top-K winner features, so parent - smaller is garbage
    # elsewhere).
    c_cols = bins.shape[1]  # packed column count (== f without bundling)
    b_pack = bundle.num_bins if bundle is not None else b
    quant = u is not None and qkey is not None
    from mmlspark_tpu.ops.u_histogram import histogram_acc_dtype

    acc_dtype = histogram_acc_dtype(n, quant)
    acc_bytes = jnp.dtype(acc_dtype).itemsize
    use_sub = (
        opts.histogram_subtraction
        and max(1, opts.num_class) * m * c_cols * b_pack * 3 * acc_bytes
        <= (256 << 20)
        and opts.tree_learner != "voting_parallel"
    )
    # Panel-pass node budget: 3 stats x nodes must fit one 128-lane group
    # (subtraction keys k left children; without it 2k child nodes).
    cap = 42 if use_sub else 21
    k = max(1, min(opts.leaf_batch, num_leaves - 1, cap))

    def searchk(histk, totalsk, depthk):
        """Candidate searches for freshly created children; depth-capped.
        NaN gains (0/0 under zero-regularization params) are sanitized to
        -inf at write time so one poisoned candidate can neither halt the
        whole build through cond's max nor win an argmax."""
        s = _split_search(histk, totalsk, edges, feature_mask, opts, lr=lr)
        capped = jnp.where(depthk >= max_depth, -jnp.inf, s.gain)
        capped = jnp.where(jnp.isnan(capped), -jnp.inf, capped)
        return s._replace(gain=capped)

    # Per-tree hoist for the U path: the (3, N) stat rows are node-
    # independent, so they upload to the panel layout once per tree.
    stats = _tree_stats(grad, hess, count, qkey) if u is not None else None

    # Root: one-node histogram over all rows. Under subtraction the packed
    # (pre-expansion) result seeds the cache and is expanded separately
    # for the search.
    if use_sub:
        root_p, root_tp = histf.packed(
            bins, grad, hess, count, jnp.zeros(n, jnp.int32), 1, b,
            feature_mask=feature_mask, u=u, stats=stats,
        )
        root_hist, root_tot = histf.expand(root_p, root_tp, b, stats=stats)
    else:
        root_hist, root_tot = histf(
            bins, grad, hess, count, jnp.zeros(n, jnp.int32), 1, b,
            feature_mask=feature_mask, u=u, stats=stats,
        )
    root = _split_search(root_hist, root_tot, edges, feature_mask, opts, lr=lr)

    def at0(template, s_):
        return template.at[0].set(s_[0])

    has_cat = bool(opts.categorical_slots)
    # Categorical-row view of U, sliced ONCE here (outside the while_loop —
    # XLA does not hoist the gather out of the loop body; left inside it
    # re-sliced ~90 MB per pass and cost ~1 s per mixed fit, measured r5).
    u_cat = fr_dev = lrow_dev = None
    if (
        has_cat and u is not None and u_spec is not None
        and not u_spec.chunk_rows  # chunked u is a bins stack, not a one-hot
    ):
        if bundle is not None:
            # categoricals are identity columns under bundling: only the
            # column lookup changes; the matmul still matches ORIGINAL ids
            from mmlspark_tpu.lightgbm.bundling import cat_row_maps_bundled

            rows_np, fr_np, lr_np = cat_row_maps_bundled(
                u_spec, bundle, opts.categorical_slots
            )
        else:
            from mmlspark_tpu.ops.u_histogram import cat_row_maps

            rows_np, fr_np, lr_np = cat_row_maps(u_spec, opts.categorical_slots)
        u_cat = u[jnp.asarray(rows_np)]
        fr_dev = jnp.asarray(fr_np)
        lrow_dev = jnp.asarray(lr_np)
    zi = jnp.zeros(m, jnp.int32)
    zf = jnp.zeros(m, jnp.float32)
    state = dict(
        node=jnp.zeros(n, dtype=jnp.int32),
        feat=zi,
        bin=jnp.full(m, b, jnp.int32),
        thr=jnp.full(m, jnp.inf, jnp.float32),
        left=zi,
        right=zi,
        is_leaf=jnp.zeros(m, bool).at[0].set(True),
        leaf_val=at0(zf, root.value),
        cover=at0(zf, root.cover),
        gain=zf,
        depth=zi,
        n_splits=jnp.int32(0),
        # frontier candidates (-inf gain = not frontier / not splittable;
        # NaN sanitized at write so cond's max stays NaN-free)
        c_gain=jnp.full(m, -jnp.inf).at[0].set(
            jnp.where(jnp.isnan(root.gain[0]), -jnp.inf, root.gain[0])
        ),
        c_feat=at0(zi, root.feat),
        c_bin=at0(zi, root.bin),
        c_thr=at0(zf, root.thr),
    )
    if has_cat:
        zb = jnp.zeros(m, bool)
        zmb = jnp.zeros((m, b), bool)
        state.update(
            cat_node=zb,
            cat_mask=zmb,
            c_iscat=at0(zb, root.is_cat),
            c_catmask=zmb.at[0].set(root.cat_mask[0]),
        )
    if use_sub:
        # Packed-space cache: C columns x bundle-bin width in the pass's
        # accumulator dtype (narrow int on the quantized U path) — the
        # subtraction happens here, BEFORE dequant/EFB expansion.
        state["leaf_hist"] = (
            jnp.zeros((m, c_cols, b_pack, 3), root_p.dtype).at[0].set(root_p[0])
        )
        state["leaf_tot"] = (
            jnp.zeros((m, 3), root_tp.dtype).at[0].set(root_tp[0])
        )
        # Which child of each cached candidate split is SMALLER (by row
        # count): the pass builds that child and derives the other. False
        # (left) for non-candidates — harmless, their gain is -inf.
        state["c_subR"] = jnp.zeros(m, bool).at[0].set(
            root.rcov[0] < root.lcov[0]
        )

    def cond(st):
        # c_gain is NaN-free by construction; -inf marks non-frontier and
        # +inf (f32 gain overflow) is a legitimate best split.
        best = jnp.max(st["c_gain"])
        return (st["n_splits"] < num_leaves - 1) & (best > opts.min_gain_to_split)

    def body(st):
        # Top-k frontier leaves by cached candidate gain (sorted descending,
        # ties by lower slot index).
        top_g, top_l = lax.top_k(st["c_gain"], k)
        j = jnp.arange(k, dtype=jnp.int32)
        can = (top_g > opts.min_gain_to_split) & (
            st["n_splits"] + j < num_leaves - 1
        )  # monotone in j: gains sorted descending, budget consumed in order
        if opts.leaf_batch_ratio > 0.0:
            # quality gate: only leaves whose gain is within ratio of the
            # pass best split together — tightens batched growth toward
            # sequential best-first (monotone in j: gains sorted). Lane 0 IS
            # the pass best, so it always qualifies — without that exemption
            # a negative best gain (legal when min_gain_to_split < 0) fails
            # its own ratio test and the while_loop never makes progress.
            can = can & ((j == 0) | (top_g >= opts.leaf_batch_ratio * top_g[0]))
        lslot = 2 * (st["n_splits"] + j) + 1
        rslot = lslot + 1
        # Guarded scatter indices: disabled lanes write out of range (m) and
        # are dropped, never clipped onto a live slot.
        gparent = jnp.where(can, top_l, m)
        glslot = jnp.where(can, lslot, m)
        grslot = jnp.where(can, rslot, m)

        sf = st["c_feat"][top_l]  # (k,) split feature / bin / threshold
        sb = st["c_bin"][top_l]
        sthr = st["c_thr"][top_l]
        if use_sub:
            small_r = st["c_subR"][top_l]  # (k,) smaller child is RIGHT
        if has_cat:
            sic = st["c_iscat"][top_l]  # (k,)
            scm = st["c_catmask"][top_l]  # (k, B)

        # Route rows and build the pass's node keys in one unrolled sweep:
        # key = j for rows entering split j's LEFT child (subtraction mode;
        # 2j + went_right without), k·(invalid) elsewhere — the panel
        # histogram drops out-of-range keys, so the key IS the in-leaf mask
        # and grad/hess need no masking pass.
        node = st["node"]
        new_node = node
        key = jnp.full(n, 2 * k, jnp.int32)
        in_set = None
        if u_cat is not None:
            # Categorical membership for ALL k leaves as one MXU matmul
            # against the CATEGORICAL rows of the fit-resident one-hot U
            # (streams ~Σ cat widths per pass, not K_pad); the per-leaf
            # gather fallback below serves the no-U paths (mesh, CPU).
            from mmlspark_tpu.ops.u_histogram import membership_matmul

            in_set = membership_matmul(u_cat, fr_dev, lrow_dev, sf, scm, n)
        # One (N, k) gather for all k split columns — k separate lane-axis
        # dynamic slices each paid their own relayout (measured ~2 ms/tree
        # at k=16); jnp.take batches them into a single op. Under bundling
        # the gather targets the packed columns and decodes to original
        # bins for the whole (N, k) block at once.
        if rconsts is not None:
            cols = jnp.take(bins, rconsts[0][sf], axis=1)  # (N, k) packed
            cols = _orig_bins(cols, sf, rconsts)
        else:
            cols = jnp.take(bins, sf, axis=1)  # (N, k)
        for jj in range(k):
            colj = cols[:, jj]
            in_j = (node == top_l[jj]) & can[jj]
            right_j = colj > sb[jj]
            if has_cat:
                # categorical: LEFT iff the row's bin is in the split set
                right_j = jnp.where(
                    sic[jj],
                    ~in_set[jj]
                    if in_set is not None
                    else ~scm[jj][colj.astype(jnp.int32)],
                    right_j,
                )
            new_node = jnp.where(
                in_j, jnp.where(right_j, rslot[jj], lslot[jj]), new_node
            )
            if use_sub:
                # key rows landing in the SMALLER child (right when
                # small_r, else left) — the built child of split jj
                key = jnp.where(
                    in_j & (right_j == small_r[jj]), jj, key
                )
            else:
                key = jnp.where(in_j, 2 * jj + right_j.astype(jnp.int32), key)

        if use_sub:
            # Build the smaller child in PACKED space, derive the sibling
            # as parent - smaller (exact integer subtraction on the quant
            # path — the derived sibling is bit-identical to a direct
            # build), then assign built/derived back to left/right.
            histS, totS = histf.packed(
                bins, grad, hess, count, key, k, b, feature_mask=feature_mask,
                u=u, stats=stats,
            )  # (k, C, B_b, 3)
            histO = st["leaf_hist"][top_l] - histS
            totO = st["leaf_tot"][top_l] - totS
            sel = small_r[:, None, None, None]
            histL_p = jnp.where(sel, histO, histS)
            histR_p = jnp.where(sel, histS, histO)
            totL_p = jnp.where(small_r[:, None], totO, totS)
            totR_p = jnp.where(small_r[:, None], totS, totO)
            hlr, tlr = histf.expand(
                jnp.concatenate([histL_p, histR_p]),
                jnp.concatenate([totL_p, totR_p]),
                b, stats=stats,
            )
            histL, histR = hlr[:k], hlr[k:]
            totL, totR = tlr[:k], tlr[k:]
        else:
            h2, t2 = histf(
                bins, grad, hess, count, key, 2 * k, b, feature_mask=feature_mask,
                u=u, stats=stats,
            )
            h2 = h2.reshape(k, 2, f, b, 3)
            t2 = t2.reshape(k, 2, 3)
            histL, histR = h2[:, 0], h2[:, 1]
            totL, totR = t2[:, 0], t2[:, 1]

        child_depth = st["depth"][top_l] + 1  # (k,)
        cs = searchk(
            jnp.concatenate([histL, histR]),
            jnp.concatenate([totL, totR]),
            jnp.concatenate([child_depth, child_depth]),
        )  # (2k,) fields: [left children | right children]

        st = dict(st)
        if use_sub:
            st["leaf_hist"] = (
                st["leaf_hist"].at[glslot].set(histL_p, mode="drop")
                .at[grslot].set(histR_p, mode="drop")
            )
            st["leaf_tot"] = (
                st["leaf_tot"].at[glslot].set(totL_p, mode="drop")
                .at[grslot].set(totR_p, mode="drop")
            )
            sub_r = cs.rcov < cs.lcov  # (2k,) per fresh candidate
            st["c_subR"] = (
                st["c_subR"].at[glslot].set(sub_r[:k], mode="drop")
                .at[grslot].set(sub_r[k:], mode="drop")
            )
        st["node"] = new_node
        st["feat"] = st["feat"].at[gparent].set(sf, mode="drop")
        st["bin"] = st["bin"].at[gparent].set(sb, mode="drop")
        st["thr"] = st["thr"].at[gparent].set(sthr, mode="drop")
        st["left"] = st["left"].at[gparent].set(lslot, mode="drop")
        st["right"] = st["right"].at[gparent].set(rslot, mode="drop")
        st["is_leaf"] = (
            st["is_leaf"].at[gparent].set(False, mode="drop")
            .at[glslot].set(True, mode="drop")
            .at[grslot].set(True, mode="drop")
        )
        # A final leaf's value comes from the split that CREATED it: children
        # of categorical splits carry the l2+cat_l2 output (native parity).
        lv_l, lv_r = cs.value[:k], cs.value[k:]
        if has_cat:
            lv_l = jnp.where(sic, cs.value_cat[:k], lv_l)
            lv_r = jnp.where(sic, cs.value_cat[k:], lv_r)
        st["leaf_val"] = (
            st["leaf_val"].at[glslot].set(lv_l, mode="drop")
            .at[grslot].set(lv_r, mode="drop")
        )
        st["cover"] = (
            st["cover"].at[glslot].set(cs.cover[:k], mode="drop")
            .at[grslot].set(cs.cover[k:], mode="drop")
        )
        st["gain"] = st["gain"].at[gparent].set(top_g, mode="drop")
        st["depth"] = (
            st["depth"].at[glslot].set(child_depth, mode="drop")
            .at[grslot].set(child_depth, mode="drop")
        )
        st["c_gain"] = (
            st["c_gain"].at[gparent].set(-jnp.inf, mode="drop")
            .at[glslot].set(cs.gain[:k], mode="drop")
            .at[grslot].set(cs.gain[k:], mode="drop")
        )
        st["c_feat"] = (
            st["c_feat"].at[glslot].set(cs.feat[:k], mode="drop")
            .at[grslot].set(cs.feat[k:], mode="drop")
        )
        st["c_bin"] = (
            st["c_bin"].at[glslot].set(cs.bin[:k], mode="drop")
            .at[grslot].set(cs.bin[k:], mode="drop")
        )
        st["c_thr"] = (
            st["c_thr"].at[glslot].set(cs.thr[:k], mode="drop")
            .at[grslot].set(cs.thr[k:], mode="drop")
        )
        if has_cat:
            st["cat_node"] = st["cat_node"].at[gparent].set(sic, mode="drop")
            st["cat_mask"] = st["cat_mask"].at[gparent].set(scm, mode="drop")
            st["c_iscat"] = (
                st["c_iscat"].at[glslot].set(cs.is_cat[:k], mode="drop")
                .at[grslot].set(cs.is_cat[k:], mode="drop")
            )
            st["c_catmask"] = (
                st["c_catmask"].at[glslot].set(cs.cat_mask[:k], mode="drop")
                .at[grslot].set(cs.cat_mask[k:], mode="drop")
            )
        st["n_splits"] = st["n_splits"] + can.sum().astype(jnp.int32)
        return st

    state = jax.lax.while_loop(cond, body, state)

    return TreeArrays(
        feat=state["feat"],
        bin=state["bin"],
        thr=state["thr"],
        left=state["left"],
        right=state["right"],
        is_leaf=state["is_leaf"],
        leaf_val=state["leaf_val"],
        cover=state["cover"],
        gain=state["gain"],
        row_leaf=state["node"],
        cat_node=state["cat_node"] if has_cat else jnp.zeros(m, bool),
        cat_mask=state["cat_mask"] if has_cat else jnp.zeros((m, 1), bool),
    )


# ---------------------------------------------------------------------------
# Boosting step
# ---------------------------------------------------------------------------


def _route_binned(
    bins: jax.Array, feat, binthr, left, right, is_leaf, steps: int,
    cat_node=None, cat_mask=None, bundle_consts=None,
) -> jax.Array:
    """Route binned rows through one pointer tree; returns final leaf slot.
    ``cat_mask`` (M, B) bool: at categorical nodes (``cat_node``) a row goes
    LEFT iff its bin is in the node's set ((M, 1) placeholder = no cats).
    ``bundle_consts`` (from :func:`_bundle_route_consts`): ``bins`` is EFB-
    packed — gather each node's packed column and decode to the original
    bin before the compare; tree arrays are always in original space."""
    n = bins.shape[0]
    node = jnp.zeros(n, dtype=jnp.int32)
    for _ in range(steps):
        fcur = feat[node]
        bcur = binthr[node]
        fcol = bundle_consts[0][fcur] if bundle_consts is not None else fcur
        x_bin = jnp.take_along_axis(bins, fcol[:, None], axis=1)[:, 0]
        if bundle_consts is not None:
            x_bin = _orig_bins(x_bin, fcur, bundle_consts)
        go_left = x_bin <= bcur
        if cat_mask is not None and cat_mask.shape[-1] > 1:
            bwidth = cat_mask.shape[-1]
            cm = cat_mask.reshape(-1)[node * bwidth + x_bin.astype(jnp.int32)]
            go_left = jnp.where(cat_node[node], cm, go_left)
        nxt = jnp.where(go_left, left[node], right[node])
        node = jnp.where(is_leaf[node], node, nxt)
    return node


def _tree_stats(grad, hess, count, qkey=None):
    from mmlspark_tpu.ops.u_histogram import stat_rows, stat_rows_quant

    if qkey is not None:
        return stat_rows_quant(grad, hess, count, qkey)
    return stat_rows(grad, hess, count)


def _make_step(
    opts: TrainOptions, objective: Objective, num_bins: int, mesh=None,
    n_real: Optional[int] = None, u_spec=None, hist_reduce=None, bundle=None,
):
    build = (
        _build_tree_leafwise if opts.growth == "leafwise" else _build_tree_depthwise
    )
    histf = _hist_fn(opts, mesh, u_spec, hist_reduce=hist_reduce, bundle=bundle)
    obj_kwargs = {
        "num_classes": opts.num_class,
        "alpha": opts.alpha,
        "tweedie_variance_power": opts.tweedie_variance_power,
    }

    def step(bins, y, w, margins, edges, bag_mask, feature_mask, it, lr=None, u=None):
        grad, hess = objective.grad_hess(margins, y, w, **obj_kwargs)  # (N, C)

        if opts.boosting_type == "goss":
            # Gradient-based One-Side Sampling: keep the top_rate fraction of
            # rows by |gradient|, sample other_rate of the rest, and amplify
            # the sampled small-gradient rows by (1-a)/b so histogram sums
            # stay unbiased (the GOSS estimator from the LightGBM paper).
            # Exactly n_top rows are kept (top_k index selection, ties broken
            # by lower row index — LightGBM's own sort-based top-N), and
            # n_top is computed from the UNPADDED row count so mesh padding
            # never inflates the kept fraction.
            n_rows = grad.shape[0]
            gabs = jnp.abs(grad).sum(axis=1) * bag_mask
            n_top = max(1, int(round((n_real or n_rows) * opts.top_rate)))
            _, top_idx = lax.top_k(gabs, n_top)
            top = jnp.zeros(n_rows, bool).at[top_idx].set(True)
            key = jax.random.fold_in(jax.random.PRNGKey(opts.seed), it)
            p = opts.other_rate / max(1e-12, 1.0 - opts.top_rate)
            sampled = (~top) & (jax.random.uniform(key, (n_rows,)) < p)
            amp = (1.0 - opts.top_rate) / max(1e-12, opts.other_rate)
            goss_w = top.astype(grad.dtype) + sampled.astype(grad.dtype) * amp
            bag_mask = bag_mask * goss_w

        grad = grad * bag_mask[:, None]
        hess = hess * bag_mask[:, None]
        count = (bag_mask > 0).astype(grad.dtype)

        def per_class(g, h, qk=None):
            kw = {"u_spec": u_spec} if opts.growth == "leafwise" else {}
            return build(
                bins, g, h, count, edges, feature_mask,
                num_bins=num_bins, opts=opts, histf=histf, lr=lr, u=u,
                qkey=qk, bundle=bundle, **kw,
            )

        if opts.use_quantized_grad and u is not None:
            # One stochastic-rounding key per (iteration, margin column);
            # folded from the fit seed so quantized fits are run-to-run
            # deterministic like everything else. grad.shape[1], NOT
            # opts.num_class: binary classifiers carry num_class=2 with a
            # single margin column.
            qkeys = jax.random.split(
                jax.random.fold_in(
                    jax.random.PRNGKey(opts.seed ^ 0x51AB51AB), it
                ),
                grad.shape[1],
            )
            tree = jax.vmap(per_class, in_axes=(1, 1, 0))(grad, hess, qkeys)
        else:
            tree = jax.vmap(per_class, in_axes=(1, 1))(grad, hess)  # (C, ...)

        # Percentile leaf renewal (native RenewTreeOutput,
        # regression_objective.hpp): quantile and L1 objectives have
        # CONSTANT-magnitude gradients, so gradient-derived leaf values move
        # margins by at most ~lr per iteration in RAW label units — on
        # unscaled targets the fit never reaches the requested percentile.
        # Native replaces each leaf's output with the weighted alpha-
        # percentile (L1: median) of the leaf's residuals, then shrinks by
        # the learning rate; so do we, before margins update.
        if objective.name in ("quantile", "regression_l1"):
            pct = opts.alpha if objective.name == "quantile" else 0.5
            lr_t = lr if lr is not None else opts.learning_rate
            resid = y - margins[:, 0]
            w_eff = w * bag_mask
            leaf = tree.row_leaf[0]  # (N,) — both objectives are C=1
            m_slots = tree.leaf_val.shape[1]
            n_rows = resid.shape[0]
            # O(N) weighted per-leaf percentile: order rows by (leaf,
            # residual) with two STABLE sorts (a composite integer sort key
            # would silently overflow int32 at large num_leaves x rows —
            # TPU truncates int64), then ONE global weight cumsum with
            # per-leaf boundaries from segment reductions — no
            # (num_leaves, N) matrix materializes inside the scanned step.
            perm1 = jnp.argsort(resid)
            order = perm1[jnp.argsort(leaf[perm1], stable=True)]
            r_s = resid[order]
            l_s = leaf[order]
            w_s = w_eff[order]
            cum_all = jnp.cumsum(w_s)
            tw = jax.ops.segment_sum(w_s, l_s, num_segments=m_slots)
            before = cum_all - w_s  # exclusive global prefix
            start = jax.ops.segment_min(before, l_s, num_segments=m_slots)
            in_leaf_cum = cum_all - start[l_s]  # inclusive prefix WITHIN leaf
            hit = in_leaf_cum >= jnp.maximum(pct * tw[l_s], 1e-12)
            # f32 rounding of million-row global cumsums can leave the
            # threshold unreached in a leaf at alpha near 1; the percentile
            # is always <= the leaf's max residual, so the last row of each
            # leaf hits by definition.
            last_in_leaf = jnp.concatenate(
                [l_s[1:] != l_s[:-1], jnp.ones(1, bool)]
            )
            hit = hit | last_in_leaf
            pos = jnp.where(hit, jnp.arange(n_rows), n_rows)
            first = jax.ops.segment_min(pos, l_s, num_segments=m_slots)
            vals = r_s[jnp.clip(first, 0, n_rows - 1)] * lr_t
            renewed = jnp.where(
                (tw > 0) & (first < n_rows), vals, tree.leaf_val[0]
            )
            tree = tree._replace(leaf_val=renewed[None, :])

        if opts.boosting_type == "rf":
            # Random-forest mode: trees fit the init-score residual
            # independently; margins never accumulate during training and
            # the final booster's leaf values are averaged post-hoc.
            return tree, margins
        # margins update: row_leaf (C, N) slots into leaf_val (C, M)
        contrib = jnp.take_along_axis(tree.leaf_val, tree.row_leaf, axis=1).T  # (N, C)
        return tree, margins + contrib

    return step


# Jitted-program cache shared across train() calls. A fit's programs are
# fully determined by (options, bin count, mesh, scan-vs-loop shape); without
# this cache every fit would rebuild its closures and re-trace/lower the
# whole boosting program — several seconds of host work that dwarfs the
# actual device time on warm fits (jit re-specializes per input shape
# underneath each cached callable, so shapes need not be part of the key).
# LRU-bounded so hyperparameter sweeps (every combo is a distinct key) don't
# grow compiled executables without limit; 256 entries ≈ 64 configs in
# flight, far beyond a CV fold x param-grid working set.
_PROGRAM_CACHE: "collections.OrderedDict[Any, Any]" = collections.OrderedDict()
_PROGRAM_CACHE_SIZE = 256


def _cached_program(key, make):
    fn = _PROGRAM_CACHE.get(key)
    hit = fn is not None
    if fn is None:
        fn = _PROGRAM_CACHE[key] = make()
        if len(_PROGRAM_CACHE) > _PROGRAM_CACHE_SIZE:
            _PROGRAM_CACHE.popitem(last=False)
    else:
        _PROGRAM_CACHE.move_to_end(key)
    prof = get_profiler()
    if prof.active:
        prof.note_program_cache(hit=hit, size=len(_PROGRAM_CACHE))
    return fn


def _opts_key(opts: "TrainOptions"):
    return dataclasses.astuple(opts)


#: TrainOptions fields the many-models plane threads through the compiled
#: program as TRACED per-candidate data instead of baked constants:
#: learning_rate rides the scanned (K, iterations) lr stack, and the
#: bagging/feature-fraction knobs only shape the host-side _mask_schedule
#: draws (the program consumes the resulting mask stacks, never the
#: fractions themselves). Everything else — num_leaves, num_iterations,
#: regularization, objective, seed (GOSS/quantized bake PRNGKey(seed)
#: statically) — changes the traced program and therefore the bucket.
MANY_VMAPPED_FIELDS = (
    "learning_rate",
    "feature_fraction",
    "bagging_fraction",
    "bagging_freq",
    "pos_bagging_fraction",
    "neg_bagging_fraction",
)


def normalize_many_opts(opts: "TrainOptions") -> "TrainOptions":
    """Canonical representative of ``opts``' shape-bucket: the vmapped
    fields pinned to fixed values. Two candidates batch into one compiled
    program iff their normalized options (plus mapper/objective context)
    agree — the shape-bucketing rule documented in docs/automl_sweep.md."""
    return dataclasses.replace(
        opts,
        learning_rate=0.0,
        feature_fraction=1.0,
        bagging_fraction=1.0,
        bagging_freq=0,
        pos_bagging_fraction=1.0,
        neg_bagging_fraction=1.0,
    )


def many_bucket_key(opts: "TrainOptions"):
    """Hashable shape-bucket key for the many-models plane."""
    return _opts_key(normalize_many_opts(opts))


def _scan_steps_run(step, per_iter_bag: bool, per_iter_lr: bool = False,
                    with_u: bool = False):
    """The UNJITTED scan-over-iterations program body shared by the
    single-fit fast path (:func:`_make_scan_steps` jits it directly) and
    the many-models plane (:func:`_make_scan_steps_many` vmaps it over a
    stacked candidate axis before jitting). Factored so both paths trace
    the identical per-iteration semantics."""

    def run(bins, y, w, margins, edges, bag, fm_all, lr_all, it0, u_arg):
        iters = fm_all.shape[0]
        u = u_arg if with_u else None

        def body(m, per_iter):
            it, fmv = per_iter[0], per_iter[-1 if not per_iter_lr else -2]
            bag_i = per_iter[1] if per_iter_bag else bag
            lr_i = per_iter[-1] if per_iter_lr else None
            tree, m2 = step(
                bins, y, w, m, edges, bag_i.astype(jnp.float32), fmv, it, lr_i,
                u=u,
            )
            return m2, tree._replace(row_leaf=jnp.zeros((), jnp.int32))

        # global iteration ids (it0 > 0 on segmented fits): GOSS's per-
        # iteration rng folds on these, so segments never repeat a stream
        idx = jnp.arange(iters, dtype=jnp.int32) + it0
        xs = [idx]
        if per_iter_bag:
            xs.append(bag)
        xs.append(fm_all)
        if per_iter_lr:
            xs.append(lr_all)
        margins_out, trees = lax.scan(body, margins, tuple(xs))
        return margins_out, trees

    return run


def _make_scan_steps(step, per_iter_bag: bool, per_iter_lr: bool = False,
                     with_u: bool = False):
    """All boosting iterations in ONE device program: ``lax.scan`` over the
    per-tree step, per-iteration bagging/feature masks as scanned inputs,
    stacked tree arrays as the scan output. One dispatch and one bulk fetch
    replace per-iteration round-trips — on remote-attached chips (axon
    tunnel) dispatch latency otherwise dominates the entire fit.

    When bagging never resamples (``per_iter_bag=False``) the single (N,)
    mask is closed over inside the program rather than scanned, so no
    (iterations, N) buffer is ever materialized. A dynamic learning-rate
    schedule (``per_iter_lr``) rides as one more scanned (iterations,)
    input — schedule callbacks keep the one-dispatch fast path.

    ``with_u`` (U histogram path): the caller builds the fit-resident
    one-hot ONCE per fit and passes it in — building it inside this program
    would redo the multi-GB materialization once per SEGMENT when the fit
    is split for the dispatch watchdog."""
    run = _scan_steps_run(
        step, per_iter_bag, per_iter_lr=per_iter_lr, with_u=with_u
    )
    return jax.jit(run, donate_argnums=(3,))


def _make_scan_steps_many(step, per_iter_bag: bool):
    """The many-models program: vmap the scan body over a leading candidate
    axis so K same-shaped fits train in ONE compiled dispatch. Data (bins,
    y, w, edges) is SHARED across candidates (in_axes=None — XLA keeps one
    copy); margins, per-iteration bagging/feature masks, and the
    per-iteration learning-rate stack carry the candidate axis. lr is
    always scanned here: it is the vmapped hyperparameter, and a traced f32
    scalar is bit-identical to the baked Python float the sequential path
    closes over (weak f32 typing), so batched and sequential fits agree.

    When no candidate in the bucket bags (``per_iter_bag=False``) the
    shared (N,) presence mask broadcasts (in_axes=None) and no
    (K, iterations, N) mask stack ever materializes."""
    run = _scan_steps_run(
        step, per_iter_bag=per_iter_bag, per_iter_lr=True, with_u=False
    )
    in_axes = (
        None, None, None, 0, None, 0 if per_iter_bag else None, 0, 0,
        None, None,
    )
    return jax.jit(jax.vmap(run, in_axes=in_axes), donate_argnums=(3,))


def _bagging_active(opts: "TrainOptions") -> bool:
    return opts.bagging_freq > 0 and (
        opts.bagging_fraction < 1.0
        or opts.pos_bagging_fraction < 1.0
        or opts.neg_bagging_fraction < 1.0
    )


def _mask_schedule(opts: "TrainOptions", rng, n, pad, num_bag, num_feat, f,
                   presence, y=None):
    """Per-iteration (bag_mask, bag_changed, feature_mask_or_None) — the ONE
    definition of the bagging/feature-sampling schedule and its rng stream,
    shared by the scan and loop paths so they cannot diverge. Class-
    stratified bagging (pos/neg_bagging_fraction) samples each binary class
    at its own rate, matching native LightGBM's goal-oriented sampling."""
    bag = presence
    stratified = (
        opts.pos_bagging_fraction < 1.0 or opts.neg_bagging_fraction < 1.0
    ) and y is not None
    if stratified:
        pos_idx = np.nonzero(np.asarray(y[:n]) > 0.5)[0]
        neg_idx = np.nonzero(np.asarray(y[:n]) <= 0.5)[0]
        n_pos = max(1, int(round(len(pos_idx) * opts.pos_bagging_fraction)))
        n_neg = max(1, int(round(len(neg_idx) * opts.neg_bagging_fraction)))
    for it in range(opts.num_iterations):
        changed = False
        if _bagging_active(opts):
            if it % opts.bagging_freq == 0:
                bag = np.zeros(n + pad, dtype=np.float32)
                if stratified:
                    if len(pos_idx):
                        bag[rng.choice(pos_idx, size=n_pos, replace=False)] = 1.0
                    if len(neg_idx):
                        bag[rng.choice(neg_idx, size=n_neg, replace=False)] = 1.0
                else:
                    bag[rng.choice(n, size=num_bag, replace=False)] = 1.0
                changed = True
        if opts.feature_fraction < 1.0:
            fm = np.zeros(f, dtype=np.float32)
            fm[rng.choice(f, size=num_feat, replace=False)] = 1.0
        else:
            fm = None
        yield bag, changed, fm


def _make_tree_contrib(steps: int, bundle=None):
    """(N, C) margin contribution of ONE tree-round on a binned matrix —
    used by dart mode to subtract dropped trees. ``bundle``: the matrix is
    EFB-packed; routing decodes per-node original bins on the fly."""
    consts = _bundle_route_consts(bundle) if bundle is not None else None

    @jax.jit
    def contrib(bins_v, feat, bthr, lc, rc, il, vals, catn, catm):
        def per_class(f_, b_, l_, r_, i_, v_, cn_, cm_):
            leaf = _route_binned(
                bins_v, f_, b_, l_, r_, i_, steps, cat_node=cn_, cat_mask=cm_,
                bundle_consts=consts,
            )
            return v_[leaf]

        return jax.vmap(per_class, out_axes=1)(feat, bthr, lc, rc, il, vals, catn, catm)

    return contrib


def _make_valid_update(steps: int, bundle=None):
    contrib = _make_tree_contrib(steps, bundle)

    def update(bins_v, margins_v, tree):
        return margins_v + contrib(
            bins_v, tree.feat, tree.bin, tree.left, tree.right, tree.is_leaf,
            tree.leaf_val, tree.cat_node, tree.cat_mask,
        )

    return jax.jit(update, donate_argnums=(1,))


def _margin_to_score(margins: np.ndarray, metric: str, objective: str) -> np.ndarray:
    """What the metric consumes: margins for loss metrics, margin column 0
    for auc (rank-invariant), response scale for poisson/tweedie l2."""
    if metric in ("multi_logloss", "multi_error"):
        return margins
    if objective in ("poisson", "tweedie") and metric in ("l2", "rmse", "l1"):
        return np.exp(margins[:, 0])
    return margins[:, 0]


def _evaluate(
    metric: str, objective: str, y: np.ndarray, margins: np.ndarray, w: np.ndarray,
    alpha: float,
) -> float:
    fn, _ = METRICS[metric]
    score = _margin_to_score(margins, metric, objective)
    if metric == "quantile":
        return fn(y, score, w, alpha=alpha)
    return fn(y, score, w)


def train(
    bins: np.ndarray,  # (N, F) uint8
    y: np.ndarray,
    opts: TrainOptions,
    w: Optional[np.ndarray] = None,
    init_margins: Optional[np.ndarray] = None,  # (N, C) warm-start margins
    valid_sets: Optional[Sequence[Tuple[str, np.ndarray, np.ndarray, Optional[np.ndarray]]]] = None,
    mapper: Optional[BinMapper] = None,
    mesh: Optional[Any] = None,
    feature_names: Optional[List[str]] = None,
    callbacks: Optional[Sequence[Any]] = None,
    hist_reduce: Optional[Any] = None,
    iteration_hook: Optional[Any] = None,
    start_iteration: int = 0,
) -> TrainResult:
    """Run boosting. ``valid_sets`` entries are (name, bins_v, y_v, w_v).

    ``callbacks`` are :class:`~mmlspark_tpu.lightgbm.callbacks.TrainingCallback`
    delegates (``LightGBMDelegate.scala`` analogue): LR schedules ride the
    scan fast path; per-iteration hooks run on the loop path.

    ``hist_reduce`` is the process-parallel histogram allreduce hook (see
    :func:`_hist_fn`); ``iteration_hook(it, tree)`` fires after each
    committed iteration on the loop path with the retained
    :class:`TreeArrays` — the journal-commit point for
    ``lightgbm/procfit.py``. Either forces the loop path (per-iteration
    host control is the point) and bypasses the shared program cache
    (the hook closures are fit-specific).

    ``start_iteration`` resumes a journaled fit at iteration k: the first
    k bagging/feature-mask draws are consumed WITHOUT running (the rng
    stream stays aligned with an uninterrupted fit — the property model
    parity after gang recovery rests on) and boosting begins at absolute
    iteration k against the caller-rebuilt ``init_margins``. The returned
    booster then contains only the new trees; a resuming caller packs
    restored + new trees itself via :func:`_pack_booster`."""
    # Boosting-type contracts (matching native LightGBM's own errors):
    if opts.boosting_type == "rf":
        if not (opts.bagging_fraction < 1.0 and opts.bagging_freq > 0):
            raise ValueError(
                "boosting_type='rf' requires bagging "
                "(bagging_fraction < 1 and bagging_freq > 0)"
            )
        if valid_sets:
            raise ValueError(
                "boosting_type='rf' does not support validation sets "
                "(averaged-ensemble eval is not incremental)"
            )
        # rf trees are full-strength; averaging happens at the end
        opts = dataclasses.replace(opts, learning_rate=1.0)
    elif opts.boosting_type == "goss":
        if opts.bagging_fraction < 1.0:
            raise ValueError("boosting_type='goss' cannot be combined with bagging")
        if opts.top_rate + opts.other_rate > 1.0:
            raise ValueError(
                "goss requires top_rate + other_rate <= 1 "
                f"(got {opts.top_rate} + {opts.other_rate})"
            )
    elif opts.boosting_type == "dart":
        if opts.early_stopping_round > 0:
            raise ValueError("early stopping is not available in dart mode")
    if (
        opts.pos_bagging_fraction < 1.0 or opts.neg_bagging_fraction < 1.0
    ) and opts.objective != "binary":
        # native LightGBM likewise restricts pos/neg bagging to binary
        raise ValueError(
            "posBaggingFraction/negBaggingFraction require the binary "
            f"objective (got {opts.objective!r})"
        )
    objective = get_objective(opts.objective)
    num_classes = objective.num_outputs_fn(opts.num_class)
    n, f = bins.shape
    num_bins = opts.max_bin + 1  # + missing bin
    # EFB: when the mapper carries a bundle plan, ``bins`` is the PACKED
    # (N, C) matrix. Histograms build in packed space and expand to the
    # original (k, F, B, 3) before the split search, so everything from the
    # search down (tree arrays, model text, SHAP) stays in original ids;
    # f_feat sizes the original-feature surfaces (feature_fraction masks).
    bundle = getattr(mapper, "bundles", None) if mapper is not None else None
    if bundle is not None:
        if f != bundle.num_columns:
            raise ValueError(
                f"bundled mapper expects packed bins with {bundle.num_columns} "
                f"columns, got {f} — bin through apply_bins/bin_dataset with "
                "this mapper"
            )
        if opts.tree_learner == "voting_parallel":
            raise ValueError(
                "featureBundling is not supported with tree_learner="
                "'voting_parallel' (voting's top-K feature exchange needs "
                "per-feature histograms on the wire)"
            )
    f_feat = bundle.num_features if bundle is not None else f
    # The mapper is the single source of truth for categorical features
    # (LightGBMBase.scala:148-156 likewise resolves slots before training).
    if mapper is not None and mapper.cat_values:
        opts = dataclasses.replace(
            opts,
            categorical_slots=tuple(sorted(mapper.cat_values)),
            # native max_cat_to_onehot boundary: features whose SEEN category
            # count is small use the one-vs-rest search instead of the sort
            onehot_slots=tuple(
                f_
                for f_ in sorted(mapper.cat_values)
                if len(mapper.cat_values[f_]) <= opts.max_cat_to_onehot
            ),
        )

    w_is_default = w is None
    w = np.ones(n, dtype=np.float32) if w is None else np.asarray(w, dtype=np.float32)
    y_np = np.asarray(y, dtype=np.float32)

    if init_margins is None:
        if opts.boost_from_average:
            init_score = objective.init_score(y_np, num_classes, w)
        else:
            init_score = np.zeros(num_classes, dtype=np.float32)
        margins0 = np.broadcast_to(init_score[None, :], (n, num_classes)).copy()
    else:
        # Warm start from provided margins: the booster is a delta model
        # (LightGBM disables boost_from_average when init_score is given).
        init_score = np.zeros(num_classes, dtype=np.float32)
        margins0 = np.asarray(init_margins, dtype=np.float32).reshape(n, num_classes)

    # Device placement; shard rows over the mesh data axis when given.
    # Rows are padded to a multiple of the data-axis size; padding rides along
    # with zero weight/count so it never influences histograms or stats — the
    # "empty partition sends ignore" analogue (LightGBMUtils.scala:144-161).
    pad = 0
    sh_bins = None
    if mesh is not None:
        from mmlspark_tpu.parallel.mesh import (
            AXIS_MODEL,
            data_sharding,
            feature_parallel_sharding,
            pad_to_multiple,
            replicated,
        )

        shard_n = int(mesh.shape["data"])
        padded_n, pad = pad_to_multiple(n, shard_n)
        if pad:
            bins = np.concatenate([bins, np.zeros((pad, f), dtype=bins.dtype)])
            y_np = np.concatenate([y_np, np.zeros(pad, dtype=np.float32)])
            w = np.concatenate([w, np.zeros(pad, dtype=np.float32)])
            margins0 = np.concatenate(
                [margins0, np.zeros((pad, num_classes), dtype=margins0.dtype)]
            )
        sh_rows = data_sharding(mesh)
        sh_rep = replicated(mesh)
        model_size = int(mesh.shape.get(AXIS_MODEL, 1))
        if model_size > 1 and f % model_size == 0 and bundle is None:
            # feature parallel: bins vertically partitioned over the model
            # axis (LightGBM's feature_parallel layout); XLA partitions the
            # histogram build/split search and inserts the best-split
            # argmax collectives across model shards itself. (Indivisible
            # feature counts stay row-sharded/replicated over model.)
            sh_bins = feature_parallel_sharding(mesh)
        put_rows = lambda a: jax.device_put(a, sh_rows)
        put_rep = lambda a: jax.device_put(a, sh_rep)
    else:
        put_rows = put_rep = jnp.asarray
    presence = np.ones(n + pad, dtype=np.float32)
    if pad:
        presence[n:] = 0.0

    if mapper is not None:
        edges = np.where(np.isfinite(mapper.edges), mapper.edges, np.float32(np.finfo(np.float32).max))
    else:
        edges = np.zeros((f, 1))
    edges_dev = put_rep(edges.astype(np.float32))

    def dev_rows(a):
        """Re-shard a device-created array onto the row sharding (device-to-
        device; no host wire traffic)."""
        return jax.device_put(a, sh_rows) if mesh is not None else a

    # Ship bins as uint8 when they fit (4x less wire traffic — host->device
    # transfers are the fixed cost of a fit on remote-attached chips);
    # consumers compare/gather fine on uint8 and the histogram kernels
    # upcast per-tile. Device-RESIDENT bins (bin_dataset_to_device's
    # overlapped streaming upload) skip the put entirely.
    put_bins = (lambda a: jax.device_put(a, sh_bins)) if sh_bins is not None else put_rows
    if isinstance(bins, jax.Array) and mesh is None:
        bins_dev = bins
    elif num_bins <= 256:
        # uint8 inputs (incl. out-of-core memmaps) upload as-is — no host
        # copy; device_put streams straight from the mapping
        b8 = np.asarray(bins) if not isinstance(bins, np.ndarray) else bins
        b8 = b8 if b8.dtype == np.uint8 else b8.astype(np.uint8)
        bins_dev = put_bins(np.ascontiguousarray(b8))
    else:
        bins_dev = put_bins(np.asarray(bins, dtype=np.int32))
    # Integer-valued labels (binary/multiclass/count targets) ride the wire
    # as uint8 and upcast on device — 4x less of the per-fit transfer cost.
    if y_np.size and np.all(np.mod(y_np, 1) == 0) and np.all((y_np >= 0) & (y_np <= 255)):
        y_dev = put_rows(y_np.astype(np.uint8)).astype(jnp.float32)
    else:
        y_dev = put_rows(y_np)
    # Constant-valued operands are created ON device instead of uploaded.
    if w_is_default:
        w_dev = dev_rows(jnp.ones(n + pad, jnp.float32))
    else:
        w_dev = put_rows(w)
    if init_margins is None:
        margins = dev_rows(
            jnp.asarray(init_score, dtype=jnp.float32)[None, :]
            * jnp.ones((n + pad, 1), jnp.float32)
        )
    else:
        margins = put_rows(margins0.astype(np.float32))

    # U histogram path (ops/u_histogram.py): single-device fits whose packed
    # one-hot fits the HBM budget contract each pass against a fit-resident
    # U instead of rebuilding the one-hot (measured 2.1x/pass on v5e).
    # histogram_method='u' forces it (tests exercise it on CPU); the env
    # knobs kill it or resize the budget without code changes.
    import os as _os

    u_spec = None
    u_budget = 0  # the in-force U HBM budget; the OOM ladder halves it
    if (
        mesh is None
        and opts.tree_learner != "voting_parallel"
        and num_bins <= 256
        and _os.environ.get("MMLSPARK_TPU_NO_U") != "1"
        and (
            opts.histogram_method == "u"
            or (
                opts.histogram_method in (None, "pallas")
                and jax.default_backend() in ("tpu", "axon")
            )
        )
    ):
        from mmlspark_tpu.ops.u_histogram import (
            chunked_u_spec,
            make_u_spec,
            num_u_chunks,
            u_bytes,
        )

        if bundle is not None:
            # U laid out over the PACKED columns — K = Σ bundle widths is
            # the whole point: fewer one-hot rows to re-stream per pass.
            cand = make_u_spec(
                bundle.num_bins, f, [int(wd) for wd in bundle.widths]
            )
        else:
            per_feature = None if mapper is None else [int(x) for x in mapper.num_bins]
            cand = make_u_spec(num_bins, f, per_feature)
        try:
            budget = int(_os.environ.get("MMLSPARK_TPU_U_BUDGET", str(8 << 30)))
        except ValueError:
            from mmlspark_tpu.core.profiling import get_logger

            get_logger("mmlspark_tpu.lightgbm").warning(
                "MMLSPARK_TPU_U_BUDGET=%r is not an integer byte count; "
                "using the default 8 GB budget",
                _os.environ["MMLSPARK_TPU_U_BUDGET"],
            )
            budget = 8 << 30
        if u_bytes(n + pad, cand) > budget:
            # Over budget: stream the pass in row chunks instead of
            # abandoning the MXU path wholesale (the pre-chunking behavior
            # was an all-or-nothing cliff: one row past the budget and the
            # whole fit fell back to the compare-built kernels).
            cand = chunked_u_spec(n + pad, cand, budget)
        u_spec = cand
        u_budget = budget
        if u_spec.chunk_rows:
            chunks = num_u_chunks(n + pad, u_spec)
            from mmlspark_tpu.core.profiling import get_logger

            get_logger("mmlspark_tpu.lightgbm").info(
                "U one-hot (%.1f GB) exceeds MMLSPARK_TPU_U_BUDGET (%.1f GB);"
                " streaming each histogram pass in %d row chunks of %d",
                u_bytes(n + pad, dataclasses.replace(u_spec, chunk_rows=0))
                / 1e9,
                budget / 1e9, chunks, u_spec.chunk_rows,
            )
            from mmlspark_tpu.observability.events import (
                HistogramChunked,
                get_bus,
            )

            bus = get_bus()
            if bus.active:
                from mmlspark_tpu.ops.u_histogram import histogram_acc_dtype

                # quant may still fall back below (row cap); mirror that
                # predicate so the event records the dtype actually used
                _ck_quant = opts.use_quantized_grad and (
                    n + pad <= min((1 << 31) // 127, 1 << 24)
                )
                _ck_dt = jnp.dtype(histogram_acc_dtype(n + pad, _ck_quant))
                _ck_3k = 3 * max(1, min(opts.leaf_batch, opts.num_leaves - 1))
                bus.publish(HistogramChunked(
                    rows=n + pad, k_packed=u_spec.k_pad,
                    chunk_rows=u_spec.chunk_rows, num_chunks=chunks,
                    budget_bytes=budget,
                    acc_dtype=_ck_dt.name,
                    bytes_saved=u_spec.k_pad * _ck_3k
                    * (4 - _ck_dt.itemsize),
                ))

    if opts.use_quantized_grad:
        reason = None
        if u_spec is None:
            reason = (
                "the precomputed-U histogram path is inactive (non-TPU "
                "backend without histogram_method='u', mesh/voting "
                "parallelism, num_bins > 256, or U over the HBM budget)"
            )
        elif n + pad > min((1 << 31) // 127, 1 << 24):
            # Two ceilings, enforce the tighter (2^24): s8 x s8 sums
            # accumulate in int32 (|sum| <= 127 * rows wraps past
            # 2^31/127 ~= 16.9M rows), and the f32 count channel loses
            # integer exactness above 2^24 — the "counts stay exact"
            # contract in _split_search holds only below it.
            reason = (
                f"{n + pad} rows exceeds the quantized-path cap "
                "min(2^31/127, 2^24) = 2^24 (f32 count exactness / int32 "
                "histogram accumulator)"
            )
        if reason is not None:
            from mmlspark_tpu.core.profiling import get_logger

            get_logger("mmlspark_tpu.lightgbm").warning(
                "use_quantized_grad requested but %s; training with exact "
                "bf16 stats instead", reason,
            )
            opts = dataclasses.replace(opts, use_quantized_grad=False)

    if (
        opts.use_quantized_grad
        and u_spec is not None
        and opts.growth == "depthwise"
        and opts.depth >= 7
    ):
        # The U panel packs 3 stat planes per frontier node into 128
        # slots, so levels with > 42 nodes (2^6 = 64 at level 6, reached
        # once depth >= 7) can't ride the quantized U kernel; _hist_fn
        # drops those levels to the exact histogram path. Surface the
        # per-level degrade once per fit instead of silently.
        from mmlspark_tpu.core.profiling import get_logger

        get_logger("mmlspark_tpu.lightgbm").warning(
            "use_quantized_grad with depthwise growth and depth %d: levels "
            "deeper than 5 have > 42 frontier nodes and exceed the 128-slot "
            "U panel budget (3 stats x nodes), so those levels fall back to "
            "exact (non-quantized) histograms per level",
            opts.depth,
        )

    if opts.growth == "leafwise" and opts.histogram_subtraction:
        # Mirror _build_tree_leafwise's use_sub gate so the event reports
        # the path the trace will actually take (static predicate).
        from mmlspark_tpu.observability.events import (
            HistogramSubtracted,
            get_bus,
        )
        from mmlspark_tpu.ops.u_histogram import histogram_acc_dtype

        _sb_cols = len(bundle.widths) if bundle is not None else f
        _sb_bins = bundle.num_bins if bundle is not None else num_bins
        _sb_quant = opts.use_quantized_grad and u_spec is not None
        _sb_dt = jnp.dtype(histogram_acc_dtype(n + pad, _sb_quant))
        _sb_m = 2 * opts.num_leaves - 1
        _sb_cache = (
            max(1, opts.num_class) * _sb_m * _sb_cols * _sb_bins * 3
            * _sb_dt.itemsize
        )
        bus = get_bus()
        if (
            bus.active
            and _sb_cache <= (256 << 20)
            and opts.tree_learner != "voting_parallel"
        ):
            bus.publish(HistogramSubtracted(
                rows=n + pad, num_leaves=opts.num_leaves,
                packed_columns=_sb_cols, packed_bins=_sb_bins,
                acc_dtype=_sb_dt.name, cache_bytes=_sb_cache,
                bytes_saved_per_tree=(opts.num_leaves - 1) * _sb_cols
                * _sb_bins * 3 * _sb_dt.itemsize,
            ))

    okey = (_opts_key(opts), num_bins, mesh, u_spec, bundle, objective.cache_token)
    if opts.boosting_type == "goss":
        okey = okey + (n,)  # GOSS bakes the unpadded row count into the program
    _prof = get_profiler()
    _prof_on = _prof.active
    if hist_reduce is not None:
        # the reduce hook closes over a live socket group — never share a
        # compiled program holding it across fits. The profiler wrap times
        # the host-side collective per call, splitting each iteration into
        # histogram-build (device) vs allreduce (wire) time.
        if _prof_on:
            hist_reduce = _prof.wrap_host(hist_reduce, "gbdt.hist_allreduce")
        step_raw = _make_step(
            opts, objective, num_bins, mesh, n_real=n, u_spec=u_spec,
            hist_reduce=hist_reduce, bundle=bundle,
        )
        step = jax.jit(step_raw, donate_argnums=(3,))
    else:
        step_raw = _cached_program(
            ("step_raw", okey),
            lambda: _make_step(
                opts, objective, num_bins, mesh, n_real=n, u_spec=u_spec,
                bundle=bundle,
            ),
        )
        step = _cached_program(
            ("step_jit", okey), lambda: jax.jit(step_raw, donate_argnums=(3,))
        )
    u_builder = None
    if u_spec is not None:
        if u_spec.chunk_rows:
            # chunked pass consumes a (num_chunks, F, chunk) bins stack
            # laid out once per fit, not the resident one-hot
            from mmlspark_tpu.ops.u_histogram import prepare_chunked_bins

            u_builder = partial(prepare_chunked_bins, spec=u_spec)
        else:
            from mmlspark_tpu.ops.u_histogram import build_u

            u_builder = partial(build_u, spec=u_spec)
    valid_update = _cached_program(
        ("valid_update", opts.routing_steps, bundle),
        lambda: _make_valid_update(opts.routing_steps, bundle),
    )

    # -- RESOURCE_EXHAUSTED degradation ladder (docs/resilience.md) ----------
    # An HBM OOM during a histogram dispatch is retryable at a reduced
    # footprint: halve the in-memory U budget (floor 1 MiB), re-derive the
    # chunked-U spec, rebuild the step program, and re-run the SAME
    # iteration. Chunked and resident passes are bit-exact, so the final
    # model text matches an undisturbed run byte for byte. The last rung —
    # a smaller ``leaf_batch`` — changes split-scheduling and is left to
    # the caller (it trades reproducibility for survival).
    from mmlspark_tpu.runtime.faults import (
        current_faults as _current_faults,
        is_oom_error as _is_oom,
    )

    _fault_plan = _current_faults()
    _oom_retry_cap = 8

    def _degrade_for_oom(err, stage, iteration, retries) -> bool:
        """Walk one rung down the ladder; True when the caller may retry."""
        nonlocal u_spec, u_budget, okey, step_raw, step, u_builder
        if u_spec is None:
            return False  # no U path active: nothing to shrink in-loop
        new_budget = max(u_budget // 2, 1 << 20)
        if new_budget == u_budget and u_spec.chunk_rows:
            return False  # floor reached; the OOM is genuine scarcity
        u_budget = new_budget
        from mmlspark_tpu.ops.u_histogram import (
            build_u,
            chunked_u_spec,
            prepare_chunked_bins,
        )

        u_spec = chunked_u_spec(
            n + pad, dataclasses.replace(u_spec, chunk_rows=0), u_budget
        )
        okey = (
            _opts_key(opts), num_bins, mesh, u_spec, bundle,
            objective.cache_token,
        )
        if opts.boosting_type == "goss":
            okey = okey + (n,)
        if hist_reduce is not None:
            step_raw = _make_step(
                opts, objective, num_bins, mesh, n_real=n, u_spec=u_spec,
                hist_reduce=hist_reduce, bundle=bundle,
            )
            step = jax.jit(step_raw, donate_argnums=(3,))
        else:
            step_raw = _cached_program(
                ("step_raw", okey),
                lambda: _make_step(
                    opts, objective, num_bins, mesh, n_real=n, u_spec=u_spec,
                    bundle=bundle,
                ),
            )
            step = _cached_program(
                ("step_jit", okey),
                lambda: jax.jit(step_raw, donate_argnums=(3,)),
            )
        u_builder = (
            partial(prepare_chunked_bins, spec=u_spec) if u_spec.chunk_rows
            else partial(build_u, spec=u_spec)
        )
        from mmlspark_tpu.core.profiling import get_logger

        get_logger("mmlspark_tpu.lightgbm").warning(
            "histogram %s dispatch hit RESOURCE_EXHAUSTED at iteration %d "
            "(%s); degrading: U budget -> %d bytes, chunk_rows -> %d, "
            "retry %d",
            stage, iteration, str(err)[:120], u_budget, u_spec.chunk_rows,
            retries,
        )
        from mmlspark_tpu.observability.events import (
            HistogramDegraded,
            MemoryPressure,
            get_bus,
        )

        bus = get_bus()
        if bus.active:
            bus.publish(MemoryPressure(
                source="device", level="critical", used_bytes=0.0,
                limit_bytes=0.0, detail=str(err)[:200],
            ))
            bus.publish(HistogramDegraded(
                rows=n + pad, budget_bytes=u_budget,
                chunk_rows=u_spec.chunk_rows, stage=stage,
                iteration=int(iteration), retries=int(retries),
            ))
        return True

    valid_sets = list(valid_sets or [])
    valid_state = []
    for name, bv, yv, wv in valid_sets:
        wv = np.ones(len(yv), dtype=np.float32) if wv is None else np.asarray(wv, np.float32)
        mv = np.broadcast_to(init_score[None, :], (len(yv), num_classes)).copy()
        valid_state.append(
            {
                "name": name,
                "bins": jnp.asarray(np.asarray(bv, dtype=np.int32)),
                "y": np.asarray(yv, dtype=np.float32),
                "w": wv,
                "margins": jnp.asarray(mv.astype(np.float32)),
            }
        )

    metric = opts.metric or objective.default_metric
    higher_better = metric_higher_is_better(metric)
    evals: Dict[str, Dict[str, List[float]]] = {
        vs["name"]: {metric: []} for vs in valid_state
    }
    if opts.provide_training_metric:
        evals["training"] = {metric: []}

    rng = np.random.default_rng(opts.seed)
    num_bag = max(1, int(round(n * opts.bagging_fraction)))
    num_feat = max(1, int(round(f_feat * opts.feature_fraction)))

    from mmlspark_tpu.lightgbm.callbacks import (
        CallbackEnv,
        _has_iteration_hooks,
        _lr_schedule,
    )

    callbacks = list(callbacks or [])
    lr_all = _lr_schedule(callbacks, opts.learning_rate, opts.num_iterations)
    iteration_hooks = _has_iteration_hooks(callbacks)

    def _cb_env(it: int) -> "CallbackEnv":
        lr_it = float(lr_all[it]) if (lr_all is not None and it < len(lr_all)) \
            else opts.learning_rate
        return CallbackEnv(
            iteration=it, num_iterations=opts.num_iterations,
            learning_rate=lr_it, evals=evals,
        )

    for cb in callbacks:
        cb.before_training(_cb_env(0))

    trees: List[TreeArrays] = []
    best_score = -np.inf if higher_better else np.inf
    best_iter = 0
    stale = 0

    # Device-resident inputs are uploaded once and only re-uploaded when
    # bagging/feature-fraction actually resamples, and per-tree outputs stay
    # on device until one bulk fetch after the loop — host<->device
    # round-trips per iteration are what dominate wall time on remote-attached
    # chips (each transfer is a full tunnel round-trip).
    # presence mask built on device (zeroed pad tail) — no upload
    bag_dev = dev_rows(
        jnp.ones(n + pad, jnp.float32)
        if pad == 0
        else jnp.ones(n + pad, jnp.float32).at[n:].set(0.0)
    )
    fm_ones_dev = put_rep(np.ones(f_feat, dtype=np.float32))

    # Fast path: no per-iteration host decisions (no valid-set metrics, no
    # mesh special-casing) — run every boosting iteration in ONE device
    # program via lax.scan. Per-iteration masks come from the same
    # _mask_schedule as the loop path, so semantics (bagging schedule,
    # feature sampling, rng stream order) are identical.
    stacked_trees = None
    schedule = _mask_schedule(
        opts, rng, n, pad, num_bag, num_feat, f_feat, presence, y=y_np
    )
    bag_resampling = _bagging_active(opts)
    # The scan path materializes an (iterations, N) uint8 bagging-mask array
    # on device when bagging resamples; gate it so a huge fit (e.g. 10M rows
    # x 1000 iters = 10 GB) falls back to the loop path, which re-uploads
    # only on resample.
    bag_stack_ok = (
        not bag_resampling or opts.num_iterations * (n + pad) <= (512 << 20)
    )
    if (
        mesh is None
        and not valid_state
        and not iteration_hooks  # per-iteration delegates need the loop path
        and bag_stack_ok
        and opts.num_iterations > 0
        and opts.boosting_type != "dart"  # dart drops trees per host decision
        and not opts.provide_training_metric  # needs per-iteration margins
        and hist_reduce is None  # process fits need per-iteration control
        and iteration_hook is None
        and start_iteration == 0
    ):
        bag_list, fm_list = [], []
        for bag_np, _, fm_np in schedule:
            bag_list.append(bag_np)
            fm_list.append(fm_np if fm_np is not None else np.ones(f_feat, np.float32))
        if bag_resampling:
            # uint8 on the wire (masks are 0/1; 4x less than f32 — transfers
            # are the fixed cost on remote-attached chips); cast per scan step
            bag_arg = jnp.asarray(np.stack(bag_list).astype(np.uint8))
        else:
            bag_arg = bag_dev  # (N,) closed over inside the program
        fm_all = jnp.asarray(np.stack(fm_list))
        per_iter_lr = lr_all is not None
        lr_arg = jnp.asarray(lr_all) if per_iter_lr else fm_all  # unused placeholder
        runner = _cached_program(
            ("scan", okey, bag_resampling, per_iter_lr),
            lambda: _make_scan_steps(
                step_raw, per_iter_bag=bag_resampling, per_iter_lr=per_iter_lr,
                with_u=u_builder is not None,
            ),
        )
        # fit-resident U: built ONCE here, shared by every segment below
        u_dev_scan = jnp.int32(0)  # unused placeholder when no U path
        if u_builder is not None:
            u_jit = _cached_program(
                ("u_build_jit", u_spec), lambda: jax.jit(u_builder)
            )
            u_dev_scan = u_jit(bins_dev)
        # Segment the one-dispatch fit when a single device program would
        # run long enough to trip the remote-attach relay's worker watchdog:
        # a 4M-row x 100-iteration scan (~90 s on-device) reproducibly kills
        # the TPU worker, while 4M x 50 and 2M x 100 (~50 s) run fine.
        # Equal-length segments share one compiled program; margins thread
        # between dispatches, so results are identical to the single scan.
        row_iters = n * max(1, opts.num_iterations) * max(1, num_classes)
        budget = int(_os.environ.get("MMLSPARK_TPU_SCAN_ROW_ITERS", 200_000_000))
        nseg = max(1, -(-row_iters // budget))
        # prefer a divisor of the iteration count close to nseg: equal
        # segment lengths mean ONE compiled shape instead of two
        for cand in range(nseg, min(nseg + 3, max(1, opts.num_iterations)) + 1):
            if opts.num_iterations % cand == 0:
                nseg = cand
                break
        seg = -(-opts.num_iterations // nseg)
        parts = []
        for s0 in range(0, opts.num_iterations, seg):
            s1 = min(s0 + seg, opts.num_iterations)
            # margins is donated into the runner; a degraded retry of
            # this segment needs the pre-dispatch value back, so keep a
            # host snapshot (segments are rare — usually one per fit)
            margins_before = np.asarray(margins)
            oom_retries = 0
            while True:
                try:
                    # injected OOM fires pre-dispatch (margins not donated
                    # yet), so the degraded retry re-dispatches cleanly
                    if _fault_plan is not None:
                        _fault_plan.apply_on_histogram(s0, oom_retries)
                    # profiling forces a per-segment sync (an honest device
                    # window needs block_until_ready); the unprofiled fit
                    # keeps the async dispatch pipeline.
                    t_seg = time.perf_counter() if _prof_on else 0.0
                    cache_before = (
                        runner._cache_size() if _prof_on
                        and hasattr(runner, "_cache_size") else None
                    )
                    margins, part = runner(
                        bins_dev, y_dev, w_dev, margins, edges_dev,
                        bag_arg[s0:s1] if bag_resampling else bag_arg,
                        fm_all[s0:s1],
                        lr_arg[s0:s1] if per_iter_lr else lr_arg,
                        jnp.int32(s0),
                        u_dev_scan,
                    )
                    if _prof_on:
                        jax.block_until_ready((margins, part))
                        dt = time.perf_counter() - t_seg
                        compiled = (
                            cache_before is not None
                            and hasattr(runner, "_cache_size")
                            and runner._cache_size() > cache_before
                        )
                        if compiled:
                            _prof.note_compile("gbdt.scan", dt)
                        else:
                            _prof.note_cache_hit("gbdt.scan")
                        _prof.note_execute("gbdt.scan", dt)
                    break
                except Exception as e:  # noqa: BLE001 - OOM-classified below
                    if (
                        not _is_oom(e)
                        or oom_retries >= _oom_retry_cap
                        or not _degrade_for_oom(e, "scan", s0, oom_retries + 1)
                    ):
                        raise
                    oom_retries += 1
                    # recreate the donated margins buffer and rebuild the
                    # scan program + fit-resident U under the new spec
                    margins = jnp.asarray(margins_before)
                    runner = _cached_program(
                        ("scan", okey, bag_resampling, per_iter_lr),
                        lambda: _make_scan_steps(
                            step_raw, per_iter_bag=bag_resampling,
                            per_iter_lr=per_iter_lr,
                            with_u=u_builder is not None,
                        ),
                    )
                    if u_builder is not None:
                        u_jit = _cached_program(
                            ("u_build_jit", u_spec), lambda: jax.jit(u_builder)
                        )
                        u_dev_scan = u_jit(bins_dev)
            parts.append(part)
        stacked_trees = (
            parts[0]
            if len(parts) == 1
            else jax.tree.map(lambda *xs: jnp.concatenate(xs, axis=0), *parts)
        )
    else:
        dart_rng = np.random.default_rng(opts.seed + 7919)
        # loop path: the fit-resident U builds once, outside the loop
        # (cached jitted builder — a fresh jax.jit per fit would retrace)
        u_dev = None
        if u_builder is not None:
            u_jit = _cached_program(
                ("u_build_jit", u_spec), lambda: jax.jit(u_builder)
            )
            u_dev = u_jit(bins_dev)
        tree_contrib = _cached_program(
            ("tree_contrib", opts.routing_steps, bundle),
            lambda: _make_tree_contrib(opts.routing_steps, bundle),
        )

        def contrib_of(tr, bins_v):
            return tree_contrib(
                bins_v, tr.feat, tr.bin, tr.left, tr.right, tr.is_leaf,
                tr.leaf_val, tr.cat_node, tr.cat_mask,
            )

        pending_bag = None
        for it, (bag_np, bag_changed, fm_np) in enumerate(schedule):
            if it < start_iteration:
                # journal resume: consume the draw (rng stream stays
                # aligned with an uninterrupted fit) without boosting
                if bag_changed:
                    pending_bag = bag_np
                continue
            if pending_bag is not None:
                # the last skipped resample is the mask in force at k
                if not bag_changed:
                    bag_np, bag_changed = pending_bag, True
                pending_bag = None
            if bag_changed:
                bag_dev = put_rows(bag_np)
            fm_dev = put_rep(fm_np) if fm_np is not None else fm_ones_dev
            for cb in callbacks:
                cb.before_iteration(_cb_env(it))
            # traced scalar (not a baked constant) so per-iteration LR values
            # don't each recompile the step program
            lr_it = jnp.float32(
                lr_all[it] if lr_all is not None else opts.learning_rate
            )

            # dart: drop a random subset of existing trees from the margins
            # the new tree fits against (each with prob drop_rate), then
            # renormalize — new tree x 1/(k+1), dropped trees x k/(k+1)
            # (the DART weight-shrinkage rule).
            dropped = []
            if opts.boosting_type == "dart" and trees:
                dropped = list(np.nonzero(
                    dart_rng.random(len(trees)) < opts.drop_rate
                )[0])
            if dropped:
                c_d = contrib_of(trees[dropped[0]], bins_dev)
                for di in dropped[1:]:
                    c_d = c_d + contrib_of(trees[di], bins_dev)
                margins_in = margins - c_d
            else:
                margins_in = margins

            # Injected OOM faults fire here, BEFORE dispatch, so margins_in
            # has not been donated when the degraded retry re-dispatches.
            # A real device OOM surfaces after donation; the retry is then
            # best-effort (the allocator usually fails before consuming the
            # donated buffer, but that is not contractual).
            oom_retries = 0
            while True:
                try:
                    if _fault_plan is not None:
                        _fault_plan.apply_on_histogram(it, oom_retries)
                    t_step = time.perf_counter() if _prof_on else 0.0
                    step_cache_before = (
                        step._cache_size() if _prof_on
                        and hasattr(step, "_cache_size") else None
                    )
                    tree, new_margins = step(
                        bins_dev, y_dev, w_dev, margins_in, edges_dev,
                        bag_dev, fm_dev, jnp.int32(it), lr_it, u=u_dev,
                    )
                    break
                except Exception as e:  # noqa: BLE001 - OOM-classified below
                    if (
                        not _is_oom(e)
                        or oom_retries >= _oom_retry_cap
                        or not _degrade_for_oom(e, "loop", it, oom_retries + 1)
                    ):
                        raise
                    oom_retries += 1
                    if u_builder is not None:
                        u_jit = _cached_program(
                            ("u_build_jit", u_spec), lambda: jax.jit(u_builder)
                        )
                        u_dev = u_jit(bins_dev)

            if dropped:
                k = len(dropped)
                scale_new = 1.0 / (k + 1)
                scale_drop = k / (k + 1)
                # margins_in was donated into step — recover the unscaled
                # new-tree contribution from the row->leaf map it computed
                c_new = jnp.take_along_axis(tree.leaf_val, tree.row_leaf, axis=1).T
                # valid-set deltas need the PRE-scaled dropped trees
                for vs in valid_state:
                    c_dv = contrib_of(trees[dropped[0]], vs["bins"])
                    for di in dropped[1:]:
                        c_dv = c_dv + contrib_of(trees[di], vs["bins"])
                    c_newv = contrib_of(tree, vs["bins"])
                    vs["margins"] = (
                        vs["margins"] - c_dv * scale_new + c_newv * scale_new
                    )
                    vs["_updated"] = True
                tree = tree._replace(leaf_val=tree.leaf_val * scale_new)
                for di in dropped:
                    trees[di] = trees[di]._replace(
                        leaf_val=trees[di].leaf_val * scale_drop
                    )
                margins = margins - c_d * scale_new + c_new * scale_new
            else:
                margins = new_margins
            # Synchronize each iteration on the mesh path: an unbounded async
            # queue of collective programs can starve a device thread past the
            # XLA rendezvous timeout (hard abort on the host-platform mesh),
            # and per-iteration sync is the barrier-execution-mode semantics
            # of the reference anyway (TrainUtils.scala:477-483).
            jax.block_until_ready(margins)
            if _prof_on:
                # the per-iteration device window: step dispatch through
                # the mesh sync above (dart host work rides along on the
                # rare dropped-tree iterations)
                dt = time.perf_counter() - t_step
                compiled = (
                    step_cache_before is not None
                    and hasattr(step, "_cache_size")
                    and step._cache_size() > step_cache_before
                )
                if compiled:
                    _prof.note_compile("gbdt.step", dt)
                else:
                    _prof.note_cache_hit("gbdt.step")
                _prof.note_execute("gbdt.step", dt)
            # drop row_leaf, a (C, N) buffer per tree, before retaining
            trees.append(tree._replace(row_leaf=None))
            if iteration_hook is not None:
                # the commit point: the iteration's tree is final and its
                # margins applied — procfit journals it here
                iteration_hook(it, trees[-1])

            if opts.provide_training_metric:
                # isProvideTrainingMetric: train-set metric per iteration
                # (a device fetch per round — opt-in, loop path only)
                evals["training"][metric].append(_evaluate(
                    metric, opts.objective, y_np[:n], np.asarray(margins)[:n],
                    w[:n], opts.alpha,
                ))

            improved_any = False
            for vs in valid_state:
                if vs.pop("_updated", False):
                    pass  # dart already applied this round's delta
                else:
                    vs["margins"] = valid_update(vs["bins"], vs["margins"], tree)
                score = _evaluate(
                    metric, opts.objective, vs["y"], np.asarray(vs["margins"]),
                    vs["w"], opts.alpha,
                )
                evals[vs["name"]][metric].append(score)
                # best-so-far from the true score (TrainUtils.scala:276-315);
                # the first finite eval improves on the ±inf sentinel
                # naturally, and a NaN score never registers as an improvement.
                delta = (score - best_score) if higher_better else (best_score - score)
                if delta > opts.improvement_tolerance:
                    best_score, best_iter, improved_any = score, it + 1, True
            stop_requested = False
            for cb in callbacks:
                if cb.after_iteration(_cb_env(it)):
                    stop_requested = True
            if stop_requested:
                break
            if valid_state and opts.early_stopping_round > 0:
                stale = 0 if improved_any else stale + 1
                if stale >= opts.early_stopping_round:
                    break

    # scan path: all iterations ran inside one program (trees list unused)
    iters_done = opts.num_iterations if stacked_trees is not None else len(trees)
    for cb in callbacks:
        cb.after_training(_cb_env(max(0, iters_done - 1)))

    if opts.verbosity >= 1:
        import logging as _logging

        from mmlspark_tpu.core.profiling import get_logger

        logger = get_logger("mmlspark_tpu.lightgbm")
        # verbosity is an explicit request for output — lift the level floor
        # for THIS summary only, restoring the configured level after
        root_logger = _logging.getLogger("mmlspark_tpu")
        prev_level = root_logger.level
        if root_logger.getEffectiveLevel() > _logging.INFO:
            root_logger.setLevel(_logging.INFO)
        try:
            for name, metrics in evals.items():
                for mname, scores in metrics.items():
                    if not scores:
                        continue
                    arr = np.asarray(scores, dtype=np.float64)
                    if np.isnan(arr).all():
                        logger.info("valid %s %s: all evals NaN", name, mname)
                        continue
                    best_i = int(
                        np.nanargmax(arr) if higher_better else np.nanargmin(arr)
                    )
                    logger.info(
                        "valid %s %s: last=%.6f best=%.6f@%d",
                        name, mname, scores[-1], arr[best_i], best_i + 1,
                    )
        finally:
            root_logger.setLevel(prev_level)

    booster = _pack_booster(
        trees, stacked_trees, opts, num_classes, init_score, mapper,
        feature_names,
        best_iteration=best_iter
        if (valid_state and opts.early_stopping_round > 0) else -1,
    )
    return TrainResult(booster=booster, evals=evals, best_iteration=best_iter)


def train_many(
    bins: np.ndarray,  # (N, F) uint8 — SHARED by every candidate
    y: np.ndarray,
    opts_list: Sequence[TrainOptions],
    w: Optional[np.ndarray] = None,
    mapper: Optional[BinMapper] = None,
    feature_names: Optional[List[str]] = None,
) -> List[TrainResult]:
    """Train K candidates of ONE shape-bucket in a single compiled program.

    The many-models plane: every candidate must share
    :func:`many_bucket_key` (callers bucket heterogeneous grids first and
    call once per bucket). The per-iteration step is vmapped over a leading
    candidate axis (:func:`_make_scan_steps_many`), so the whole sweep
    bucket is one dispatch and one compile — the per-candidate
    hyperparameters ride as traced data: learning_rate as a scanned
    (K, iterations) stack, bagging/feature-fraction as host-drawn mask
    stacks from the same :func:`_mask_schedule` the sequential path uses
    (identical rng stream per candidate seed, so a batched fit matches the
    equivalent :func:`train` call).

    Scope (ValueError outside it): single-device (no mesh), gbdt/goss
    boosting, no validation sets / callbacks / warm start. The U histogram
    path is bypassed — candidates share the compare-built kernels, which
    vmap over the candidate axis safely.
    """
    opts_list = list(opts_list)
    if not opts_list:
        raise ValueError("train_many requires at least one candidate")
    base_key = many_bucket_key(opts_list[0])
    for o in opts_list[1:]:
        if many_bucket_key(o) != base_key:
            raise ValueError(
                "train_many candidates must share one shape-bucket "
                "(many_bucket_key agreement) — partition heterogeneous "
                "grids into buckets first"
            )
    if opts_list[0].boosting_type not in ("gbdt", "goss"):
        raise ValueError(
            "train_many supports boosting_type 'gbdt' or 'goss' (dart "
            "drops trees per host decision; rf averages at the end) — got "
            f"{opts_list[0].boosting_type!r}"
        )
    if opts_list[0].num_iterations <= 0:
        raise ValueError("train_many requires num_iterations > 0")
    for o in opts_list:
        if o.boosting_type == "goss" and o.bagging_fraction < 1.0:
            raise ValueError(
                "boosting_type='goss' cannot be combined with bagging"
            )
        if o.boosting_type == "goss" and o.top_rate + o.other_rate > 1.0:
            raise ValueError(
                "goss requires top_rate + other_rate <= 1 "
                f"(got {o.top_rate} + {o.other_rate})"
            )
        if (
            o.pos_bagging_fraction < 1.0 or o.neg_bagging_fraction < 1.0
        ) and o.objective != "binary":
            raise ValueError(
                "posBaggingFraction/negBaggingFraction require the binary "
                f"objective (got {o.objective!r})"
            )

    objective = get_objective(opts_list[0].objective)
    num_classes = objective.num_outputs_fn(opts_list[0].num_class)
    n, f = bins.shape
    num_bins = opts_list[0].max_bin + 1
    bundle = getattr(mapper, "bundles", None) if mapper is not None else None
    if bundle is not None and f != bundle.num_columns:
        raise ValueError(
            f"bundled mapper expects packed bins with {bundle.num_columns} "
            f"columns, got {f}"
        )
    f_feat = bundle.num_features if bundle is not None else f
    if mapper is not None and mapper.cat_values:
        # same mapper → same slot resolution for every candidate (the
        # bucket key already agrees on categorical/onehot slots)
        cat_kw = dict(
            categorical_slots=tuple(sorted(mapper.cat_values)),
            onehot_slots=tuple(
                f_
                for f_ in sorted(mapper.cat_values)
                if len(mapper.cat_values[f_])
                <= opts_list[0].max_cat_to_onehot
            ),
        )
        opts_list = [dataclasses.replace(o, **cat_kw) for o in opts_list]
    base = normalize_many_opts(opts_list[0])
    K = len(opts_list)
    iters = base.num_iterations

    w_is_default = w is None
    w = (
        np.ones(n, dtype=np.float32)
        if w is None
        else np.asarray(w, dtype=np.float32)
    )
    y_np = np.asarray(y, dtype=np.float32)
    # boost_from_average is static (outside MANY_VMAPPED_FIELDS), so one
    # init_score serves the whole bucket
    if base.boost_from_average:
        init_score = objective.init_score(y_np, num_classes, w)
    else:
        init_score = np.zeros(num_classes, dtype=np.float32)
    margins0 = np.broadcast_to(init_score[None, :], (n, num_classes)).copy()
    presence = np.ones(n, dtype=np.float32)

    if mapper is not None:
        edges = np.where(
            np.isfinite(mapper.edges), mapper.edges,
            np.float32(np.finfo(np.float32).max),
        )
    else:
        edges = np.zeros((f, 1))
    edges_dev = jnp.asarray(edges.astype(np.float32))
    if num_bins <= 256:
        b8 = np.asarray(bins)
        b8 = b8 if b8.dtype == np.uint8 else b8.astype(np.uint8)
        bins_dev = jnp.asarray(np.ascontiguousarray(b8))
    else:
        bins_dev = jnp.asarray(np.asarray(bins, dtype=np.int32))
    if (
        y_np.size
        and np.all(np.mod(y_np, 1) == 0)
        and np.all((y_np >= 0) & (y_np <= 255))
    ):
        y_dev = jnp.asarray(y_np.astype(np.uint8)).astype(jnp.float32)
    else:
        y_dev = jnp.asarray(y_np)
    w_dev = jnp.ones(n, jnp.float32) if w_is_default else jnp.asarray(w)

    # Per-candidate host-side schedules: each candidate draws its own
    # bagging/feature masks from ITS seed and fractions — the exact
    # sequential-path stream — and its constant learning rate becomes an
    # (iterations,) lane of the scanned lr stack.
    any_bag = any(_bagging_active(o) for o in opts_list)
    bag_stacks: List[np.ndarray] = []
    fm_stacks: List[np.ndarray] = []
    lr_stacks: List[np.ndarray] = []
    for o in opts_list:
        rng = np.random.default_rng(o.seed)
        num_bag = max(1, int(round(n * o.bagging_fraction)))
        num_feat = max(1, int(round(f_feat * o.feature_fraction)))
        bag_l, fm_l = [], []
        for bag_np, _, fm_np in _mask_schedule(
            o, rng, n, 0, num_bag, num_feat, f_feat, presence, y=y_np
        ):
            bag_l.append(bag_np)
            fm_l.append(
                fm_np if fm_np is not None else np.ones(f_feat, np.float32)
            )
        if any_bag:
            bag_stacks.append(np.stack(bag_l).astype(np.uint8))
        fm_stacks.append(np.stack(fm_l))
        lr_stacks.append(np.full(iters, o.learning_rate, dtype=np.float32))
    margins_many = jnp.asarray(
        np.broadcast_to(margins0[None], (K, n, num_classes)).copy()
    )
    fm_all = jnp.asarray(np.stack(fm_stacks))  # (K, iters, F)
    lr_all = jnp.asarray(np.stack(lr_stacks))  # (K, iters)
    bag_arg = (
        jnp.asarray(np.stack(bag_stacks))  # (K, iters, N) uint8
        if any_bag
        else jnp.ones(n, jnp.float32)  # shared presence, broadcast
    )

    okey = (many_bucket_key(opts_list[0]), num_bins, None, None, bundle,
            objective.cache_token)
    if base.boosting_type == "goss":
        okey = okey + (n,)  # GOSS bakes the unpadded row count
    step_raw = _cached_program(
        ("step_raw_many", okey),
        lambda: _make_step(
            base, objective, num_bins, None, n_real=n, u_spec=None,
            bundle=bundle,
        ),
    )
    runner = _cached_program(
        ("scan_many", okey, any_bag),
        lambda: _make_scan_steps_many(step_raw, per_iter_bag=any_bag),
    )

    _prof = get_profiler()
    _prof_on = _prof.active
    t0 = time.perf_counter() if _prof_on else 0.0
    cache_before = (
        runner._cache_size()
        if _prof_on and hasattr(runner, "_cache_size") else None
    )
    margins_out, stacked = runner(
        bins_dev, y_dev, w_dev, margins_many, edges_dev, bag_arg, fm_all,
        lr_all, jnp.int32(0), jnp.int32(0),
    )
    if _prof_on:
        jax.block_until_ready((margins_out, stacked))
        dt = time.perf_counter() - t0
        compiled = (
            cache_before is not None
            and hasattr(runner, "_cache_size")
            and runner._cache_size() > cache_before
        )
        if compiled:
            _prof.note_compile("gbdt.scan_many", dt)
        else:
            _prof.note_cache_hit("gbdt.scan_many")
        _prof.note_execute("gbdt.scan_many", dt)

    results: List[TrainResult] = []
    for ki, o in enumerate(opts_list):
        cand = jax.tree.map(lambda x, _ki=ki: x[_ki], stacked)
        booster = _pack_booster(
            None, cand, o, num_classes, init_score, mapper, feature_names,
            best_iteration=-1,
        )
        results.append(
            TrainResult(booster=booster, evals={}, best_iteration=0)
        )
    return results


def _pack_booster(
    trees: Optional[List[TreeArrays]],
    stacked_trees: Optional[TreeArrays],
    opts: TrainOptions,
    num_classes: int,
    init_score: np.ndarray,
    mapper: Optional[BinMapper],
    feature_names: Optional[List[str]] = None,
    best_iteration: int = -1,
) -> Booster:
    """Pack per-tree arrays into one :class:`Booster` — train()'s tail,
    factored so the process-parallel fit (``procfit.py``) can rebuild the
    identical booster from journal-restored trees. Accepts either a list
    of per-iteration :class:`TreeArrays` (loop path / journal restore) or
    a scan-stacked TreeArrays pytree."""
    t = opts.num_iterations if stacked_trees is not None else len(trees)
    m = opts.num_nodes

    # ONE device-side pack + ONE fetch for all tree fields: every int/bool
    # field's values fit float32 exactly (slot ids < 2^24), so the 9 fields
    # ride a single (9, T*C, M) f32 wire transfer instead of 9 round-trips
    # (each transfer pays full tunnel latency on remote-attached chips).
    _FIELDS = (
        "feat", "bin", "thr", "left", "right", "is_leaf", "leaf_val", "cover", "gain",
    )

    def _field_dev(field):
        if stacked_trees is not None:
            dev = getattr(stacked_trees, field)  # (T, C, M)
        else:
            dev = jnp.concatenate([getattr(tr, field) for tr in trees], axis=0)
        return dev.reshape(t * num_classes, m).astype(jnp.float32)

    packed = np.asarray(jnp.stack([_field_dev(fld) for fld in _FIELDS]))

    def stack(field, dtype):
        return packed[_FIELDS.index(field)].astype(dtype)

    # Categorical split arrays ride separate (small) transfers: the bool
    # mask matrix does not fit the homogeneous f32 pack.
    cat_nodes_np = cat_masks_np = None
    if opts.categorical_slots:
        if stacked_trees is not None:
            cn_dev = stacked_trees.cat_node.reshape(t * num_classes, m)
            cm_dev = stacked_trees.cat_mask.reshape(t * num_classes, m, -1)
        else:
            cn_dev = jnp.concatenate([tr.cat_node for tr in trees]).reshape(
                t * num_classes, m
            )
            cm_dev = jnp.concatenate([tr.cat_mask for tr in trees], axis=0).reshape(
                t * num_classes, m, -1
            )
        cat_nodes_np = np.asarray(cn_dev).astype(bool)
        cat_masks_np = np.asarray(cm_dev.astype(jnp.uint8)).astype(bool)

    left = stack("left", np.int32)
    right = stack("right", np.int32)
    is_leaf = stack("is_leaf", bool)
    leaf_values = stack("leaf_val", np.float32)
    if opts.boosting_type == "rf":
        # random-forest mode predicts the AVERAGE of the trees
        leaf_values = leaf_values / max(1, t)
    return Booster(
        split_feature=stack("feat", np.int32),
        split_bin=stack("bin", np.int32),
        split_threshold=stack("thr", np.float32),
        left_child=left,
        right_child=right,
        is_leaf=is_leaf,
        leaf_values=leaf_values,
        cover=stack("cover", np.float32),
        split_gain=stack("gain", np.float32),
        init_score=np.asarray(init_score, dtype=np.float32),
        num_classes=num_classes,
        objective=opts.objective,
        max_depth=_realized_depth(left, right, is_leaf, opts.routing_steps),
        best_iteration=best_iteration,
        feature_names=feature_names,
        bin_edges=None if mapper is None else mapper.edges,
        cat_nodes=cat_nodes_np,
        cat_masks=cat_masks_np,
        cat_values=(
            None if (mapper is None or not mapper.cat_values)
            else {int(j): np.asarray(v) for j, v in mapper.cat_values.items()}
        ),
    )


def _realized_depth(left, right, is_leaf, bound: int) -> int:
    """Max root→leaf depth over all trees (host-side; the static routing
    step count for predict). One forward pass over slots suffices: children
    always occupy a higher slot index than their parent in both layouts."""
    t, m = left.shape
    depth = np.zeros((t, m), dtype=np.int64)
    rows = np.arange(t)
    for j in range(m):
        internal = ~is_leaf[:, j] & (left[:, j] > j)  # real internal nodes only
        if not internal.any():
            continue
        for child in (left[:, j], right[:, j]):
            depth[rows[internal], child[internal]] = depth[internal, j] + 1
    reachable = depth[is_leaf]
    realized = int(reachable.max()) if reachable.size else 1
    return max(1, min(realized, bound))
