"""GBDT training loop: level-wise tree growth, jitted per-iteration step.

Replaces the reference's native training core (``LGBM_BoosterUpdateOneIter``
driven from ``lightgbm/TrainUtils.scala:220-315``) with a single jitted XLA
program per boosting iteration:

  gradients → per-depth histogram pass → split search over the
  (node, feature, bin) lattice → routing update → leaf values → margins.

Trees grow level-wise to a static depth (derived from ``numLeaves`` when
``maxDepth`` is unset): every level is ONE dense histogram pass over all
rows — static shapes, no per-leaf work queues, exactly what XLA/MXU want.
Early stopping, eval-metric direction, and improvement tolerance follow
``TrainUtils.scala:276-315``.

Distribution (``tree_learner=data_parallel``): rows are sharded over the
mesh ``data`` axis; the histogram is a row-sum, so XLA inserts the
cross-device all-reduce — the ``lax.psum`` equivalent of LightGBM's socket
allreduce. Split decisions are computed identically on every device from the
reduced histogram, so routing needs no further communication.
"""

from __future__ import annotations

import dataclasses
import math
from functools import partial
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from mmlspark_tpu.lightgbm.binning import BinMapper
from mmlspark_tpu.lightgbm.booster import Booster
from mmlspark_tpu.lightgbm.objectives import (
    METRICS,
    Objective,
    get_objective,
    metric_higher_is_better,
)
from mmlspark_tpu.ops.histogram import build_histograms


@dataclasses.dataclass
class TrainOptions:
    """Native ``TrainParams`` equivalent (``lightgbm/TrainParams.scala:8-128``),
    defaults matching ``LightGBMParams.scala:13-251``."""

    objective: str = "binary"
    num_iterations: int = 100
    learning_rate: float = 0.1
    num_leaves: int = 31
    max_depth: int = -1  # -1: derived from num_leaves
    max_bin: int = 255
    lambda_l1: float = 0.0
    lambda_l2: float = 0.0
    min_data_in_leaf: int = 20
    min_sum_hessian_in_leaf: float = 1e-3
    min_gain_to_split: float = 0.0
    bagging_fraction: float = 1.0
    bagging_freq: int = 0
    feature_fraction: float = 1.0
    max_delta_step: float = 0.0
    num_class: int = 1
    alpha: float = 0.9  # quantile/huber
    tweedie_variance_power: float = 1.5
    boosting_type: str = "gbdt"
    metric: Optional[str] = None
    early_stopping_round: int = 0
    improvement_tolerance: float = 0.0
    seed: int = 0
    histogram_method: Optional[str] = None
    verbosity: int = -1

    @property
    def depth(self) -> int:
        if self.max_depth and self.max_depth > 0:
            return self.max_depth
        return max(1, math.ceil(math.log2(max(2, self.num_leaves))))


@dataclasses.dataclass
class TrainResult:
    booster: Booster
    evals: Dict[str, Dict[str, List[float]]]  # set name -> metric -> history
    best_iteration: int


def _soft_threshold(g: jax.Array, l1: float) -> jax.Array:
    if l1 == 0.0:
        return g
    return jnp.sign(g) * jnp.maximum(jnp.abs(g) - l1, 0.0)


def _build_tree_single(
    bins: jax.Array,  # (N, F) int32
    grad: jax.Array,  # (N,)
    hess: jax.Array,  # (N,)
    count: jax.Array,  # (N,) 1/0 bagging presence
    edges: jax.Array,  # (F, E) float32 raw-value bin edges
    feature_mask: jax.Array,  # (F,) float32 0/1
    *,
    depth: int,
    num_bins: int,
    opts: TrainOptions,
) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array, jax.Array]:
    """Grow one tree. Returns (split_feature (I,), split_bin (I,),
    split_threshold (I,), leaf_values (L,), final_node_leaf (N,))."""
    n, f = bins.shape
    b = num_bins
    lr = opts.learning_rate
    l1, l2 = opts.lambda_l1, opts.lambda_l2

    node = jnp.zeros(n, dtype=jnp.int32)  # heap position
    alive = jnp.ones(1, dtype=bool)
    inherited = jnp.zeros(1, dtype=jnp.float32)

    feat_levels, bin_levels, thr_levels = [], [], []

    for d in range(depth):
        k = 1 << d
        offset = k - 1
        local = node - offset
        hist = build_histograms(
            bins, grad, hess, count, local, k, b, method=opts.histogram_method
        )  # (k, F, B, 3) — row-sum: XLA all-reduces across data shards here.

        totals = hist[:, 0, :, :].sum(axis=1)  # (k, 3) — feature 0 covers all rows
        g_tot, h_tot, c_tot = totals[:, 0], totals[:, 1], totals[:, 2]

        cum = jnp.cumsum(hist, axis=2)  # (k, F, B, 3) left stats at "<= bin"
        gl, hl, cl = cum[..., 0], cum[..., 1], cum[..., 2]
        gr = g_tot[:, None, None] - gl
        hr = h_tot[:, None, None] - hl
        cr = c_tot[:, None, None] - cl

        tl, tr = _soft_threshold(gl, l1), _soft_threshold(gr, l1)
        tg = _soft_threshold(g_tot, l1)
        parent_score = (tg * tg) / (h_tot + l2)  # (k,)
        gain = tl * tl / (hl + l2) + tr * tr / (hr + l2) - parent_score[:, None, None]

        valid = (
            (cl >= opts.min_data_in_leaf)
            & (cr >= opts.min_data_in_leaf)
            & (hl >= opts.min_sum_hessian_in_leaf)
            & (hr >= opts.min_sum_hessian_in_leaf)
            & (jnp.arange(b)[None, None, :] < b - 1)
            & (feature_mask[None, :, None] > 0)
        )
        gain = jnp.where(valid, gain, -jnp.inf)

        flat = gain.reshape(k, f * b)
        best_idx = jnp.argmax(flat, axis=1)  # (k,)
        best_gain = jnp.take_along_axis(flat, best_idx[:, None], axis=1)[:, 0]
        best_f = (best_idx // b).astype(jnp.int32)
        best_b = (best_idx % b).astype(jnp.int32)

        can_split = alive & jnp.isfinite(best_gain) & (best_gain > opts.min_gain_to_split)

        # Leaf value if growth stops here (LightGBM leaf output, lr-scaled).
        own_value = -tg / (h_tot + l2)
        if opts.max_delta_step > 0:
            own_value = jnp.clip(own_value, -opts.max_delta_step, opts.max_delta_step)
        own_value = own_value * lr
        value_cur = jnp.where(alive, own_value, inherited)

        # Child values from the winning split's left/right stats.
        iota = jnp.arange(k)
        glb = gl[iota, best_f, best_b]
        hlb = hl[iota, best_f, best_b]
        grb = g_tot - glb
        hrb = h_tot - hlb
        left_value = -_soft_threshold(glb, l1) / (hlb + l2) * lr
        right_value = -_soft_threshold(grb, l1) / (hrb + l2) * lr
        if opts.max_delta_step > 0:
            lim = opts.max_delta_step * lr
            left_value = jnp.clip(left_value, -lim, lim)
            right_value = jnp.clip(right_value, -lim, lim)

        # Record this level (dead/non-split nodes: bin=b ⇒ every row left, thr=+inf).
        feat_rec = jnp.where(can_split, best_f, 0)
        bin_rec = jnp.where(can_split, best_b, b)
        # Raw threshold: split bin t means "x <= edges[f, t-1]"; t=0 ⇒ NaN-only left.
        thr_raw = edges[best_f, jnp.maximum(best_b - 1, 0)]
        thr_raw = jnp.where(best_b == 0, -jnp.inf, thr_raw)
        thr_rec = jnp.where(can_split, thr_raw, jnp.inf).astype(jnp.float32)
        feat_levels.append(feat_rec)
        bin_levels.append(bin_rec)
        thr_levels.append(thr_rec)

        # Route rows down one level.
        row_f = feat_rec[local]
        row_b = bin_rec[local]
        x_bin = jnp.take_along_axis(bins, row_f[:, None], axis=1)[:, 0]
        go_right = (x_bin > row_b).astype(jnp.int32)
        node = 2 * node + 1 + go_right

        inherited = jnp.stack(
            [
                jnp.where(can_split, left_value, value_cur),
                jnp.where(can_split, right_value, value_cur),
            ],
            axis=1,
        ).reshape(2 * k)
        alive = jnp.repeat(can_split, 2)

    leaf_values = inherited  # (2^depth,)
    split_feature = jnp.concatenate(feat_levels)
    split_bin = jnp.concatenate(bin_levels)
    split_threshold = jnp.concatenate(thr_levels)
    final_leaf = node - ((1 << depth) - 1)
    return split_feature, split_bin, split_threshold, leaf_values, final_leaf


def _route_binned(bins: jax.Array, feat: jax.Array, binthr: jax.Array, depth: int):
    """Route binned rows through one tree using bin-space thresholds."""
    n = bins.shape[0]
    node = jnp.zeros(n, dtype=jnp.int32)
    for _ in range(depth):
        fcur = feat[node]
        bcur = binthr[node]
        x_bin = jnp.take_along_axis(bins, fcur[:, None], axis=1)[:, 0]
        node = 2 * node + 1 + (x_bin > bcur).astype(jnp.int32)
    return node - (feat.shape[0])


def _make_step(opts: TrainOptions, objective: Objective, num_bins: int):
    depth = opts.depth
    obj_kwargs = {
        "num_classes": opts.num_class,
        "alpha": opts.alpha,
        "tweedie_variance_power": opts.tweedie_variance_power,
    }

    def step(bins, y, w, margins, edges, bag_mask, feature_mask):
        grad, hess = objective.grad_hess(margins, y, w, **obj_kwargs)  # (N, C)
        grad = grad * bag_mask[:, None]
        hess = hess * bag_mask[:, None]
        count = bag_mask

        def per_class(g, h):
            return _build_tree_single(
                bins, g, h, count, edges, feature_mask,
                depth=depth, num_bins=num_bins, opts=opts,
            )

        sf, sb, st, lv, leaf = jax.vmap(per_class, in_axes=(1, 1))(grad, hess)
        # margins update: leaf (C, N) indices into lv (C, L)
        contrib = jnp.take_along_axis(lv, leaf, axis=1).T  # (N, C)
        return sf, sb, st, lv, margins + contrib

    return jax.jit(step, donate_argnums=(3,))


def _make_valid_update(depth: int):
    def update(bins_v, margins_v, sf, sb, lv):
        def per_class(f, bthr, vals):
            leaf = _route_binned(bins_v, f, bthr, depth)
            return vals[leaf]

        contrib = jax.vmap(per_class, out_axes=1)(sf, sb, lv)
        return margins_v + contrib

    return jax.jit(update, donate_argnums=(1,))


def _margin_to_score(margins: np.ndarray, metric: str, objective: str) -> np.ndarray:
    """What the metric consumes: margins for loss metrics, margin column 0
    for auc (rank-invariant), response scale for poisson/tweedie l2."""
    if metric in ("multi_logloss", "multi_error"):
        return margins
    if objective in ("poisson", "tweedie") and metric in ("l2", "rmse", "l1"):
        return np.exp(margins[:, 0])
    return margins[:, 0]


def _evaluate(
    metric: str, objective: str, y: np.ndarray, margins: np.ndarray, w: np.ndarray,
    alpha: float,
) -> float:
    fn, _ = METRICS[metric]
    score = _margin_to_score(margins, metric, objective)
    if metric == "quantile":
        return fn(y, score, w, alpha=alpha)
    return fn(y, score, w)


def train(
    bins: np.ndarray,  # (N, F) uint8
    y: np.ndarray,
    opts: TrainOptions,
    w: Optional[np.ndarray] = None,
    init_margins: Optional[np.ndarray] = None,  # (N, C) warm-start margins
    valid_sets: Optional[Sequence[Tuple[str, np.ndarray, np.ndarray, Optional[np.ndarray]]]] = None,
    mapper: Optional[BinMapper] = None,
    mesh: Optional[Any] = None,
    feature_names: Optional[List[str]] = None,
) -> TrainResult:
    """Run boosting. ``valid_sets`` entries are (name, bins_v, y_v, w_v)."""
    objective = get_objective(opts.objective)
    num_classes = objective.num_outputs_fn(opts.num_class)
    n, f = bins.shape
    num_bins = opts.max_bin + 1  # + missing bin

    w = np.ones(n, dtype=np.float32) if w is None else np.asarray(w, dtype=np.float32)
    y_np = np.asarray(y, dtype=np.float32)

    if init_margins is None:
        init_score = objective.init_score(y_np, num_classes, w)
        margins0 = np.broadcast_to(init_score[None, :], (n, num_classes)).copy()
    else:
        # Warm start from provided margins: the booster is a delta model
        # (LightGBM disables boost_from_average when init_score is given).
        init_score = np.zeros(num_classes, dtype=np.float32)
        margins0 = np.asarray(init_margins, dtype=np.float32).reshape(n, num_classes)

    # Device placement; shard rows over the mesh data axis when given.
    # Rows are padded to a multiple of the data-axis size; padding rides along
    # with zero weight/count so it never influences histograms or stats — the
    # "empty partition sends ignore" analogue (LightGBMUtils.scala:144-161).
    pad = 0
    if mesh is not None:
        from mmlspark_tpu.parallel.mesh import data_sharding, pad_to_multiple, replicated

        shard_n = int(mesh.shape["data"])
        padded_n, pad = pad_to_multiple(n, shard_n)
        if pad:
            bins = np.concatenate([bins, np.zeros((pad, f), dtype=bins.dtype)])
            y_np = np.concatenate([y_np, np.zeros(pad, dtype=np.float32)])
            w = np.concatenate([w, np.zeros(pad, dtype=np.float32)])
            margins0 = np.concatenate(
                [margins0, np.zeros((pad, num_classes), dtype=margins0.dtype)]
            )
        sh_rows = data_sharding(mesh)
        sh_rep = replicated(mesh)
        put_rows = lambda a: jax.device_put(a, sh_rows)
        put_rep = lambda a: jax.device_put(a, sh_rep)
    else:
        put_rows = put_rep = jnp.asarray
    presence = np.ones(n + pad, dtype=np.float32)
    if pad:
        presence[n:] = 0.0

    if mapper is not None:
        edges = np.where(np.isfinite(mapper.edges), mapper.edges, np.float32(np.finfo(np.float32).max))
    else:
        edges = np.zeros((f, 1))
    edges_dev = put_rep(edges.astype(np.float32))
    bins_dev = put_rows(np.asarray(bins, dtype=np.int32))
    y_dev = put_rows(y_np)
    w_dev = put_rows(w)
    margins = put_rows(margins0.astype(np.float32))

    step = _make_step(opts, objective, num_bins)
    valid_update = _make_valid_update(opts.depth)

    valid_sets = list(valid_sets or [])
    valid_state = []
    for name, bv, yv, wv in valid_sets:
        wv = np.ones(len(yv), dtype=np.float32) if wv is None else np.asarray(wv, np.float32)
        mv = np.broadcast_to(init_score[None, :], (len(yv), num_classes)).copy()
        valid_state.append(
            {
                "name": name,
                "bins": jnp.asarray(np.asarray(bv, dtype=np.int32)),
                "y": np.asarray(yv, dtype=np.float32),
                "w": wv,
                "margins": jnp.asarray(mv.astype(np.float32)),
            }
        )

    metric = opts.metric or objective.default_metric
    higher_better = metric_higher_is_better(metric)
    evals: Dict[str, Dict[str, List[float]]] = {
        vs["name"]: {metric: []} for vs in valid_state
    }

    rng = np.random.default_rng(opts.seed)
    num_bag = max(1, int(round(n * opts.bagging_fraction)))
    num_feat = max(1, int(round(f * opts.feature_fraction)))

    trees_sf, trees_sb, trees_st, trees_lv = [], [], [], []
    best_score = -np.inf if higher_better else np.inf
    best_iter = 0
    stale = 0

    bag_mask_np = presence.copy()
    for it in range(opts.num_iterations):
        if opts.bagging_fraction < 1.0 and opts.bagging_freq > 0:
            if it % opts.bagging_freq == 0:
                bag_mask_np = np.zeros(n + pad, dtype=np.float32)
                bag_mask_np[rng.choice(n, size=num_bag, replace=False)] = 1.0
        if opts.feature_fraction < 1.0:
            fm = np.zeros(f, dtype=np.float32)
            fm[rng.choice(f, size=num_feat, replace=False)] = 1.0
        else:
            fm = np.ones(f, dtype=np.float32)

        sf, sb, st, lv, margins = step(
            bins_dev, y_dev, w_dev, margins, edges_dev,
            put_rows(bag_mask_np), put_rep(fm),
        )
        trees_sf.append(np.asarray(sf))
        trees_sb.append(np.asarray(sb))
        trees_st.append(np.asarray(st))
        trees_lv.append(np.asarray(lv))

        improved_any = False
        for vs in valid_state:
            vs["margins"] = valid_update(vs["bins"], vs["margins"], sf, sb, lv)
            score = _evaluate(
                metric, opts.objective, vs["y"], np.asarray(vs["margins"]), vs["w"],
                opts.alpha,
            )
            evals[vs["name"]][metric].append(score)
            delta = (score - best_score) if higher_better else (best_score - score)
            if delta > opts.improvement_tolerance or it == 0:
                best_score, best_iter, improved_any = score, it + 1, True
        if valid_state and opts.early_stopping_round > 0:
            stale = 0 if improved_any else stale + 1
            if stale >= opts.early_stopping_round:
                break

    t = len(trees_sf)
    booster = Booster(
        split_feature=np.concatenate([a for a in trees_sf], axis=0).reshape(t * num_classes, -1),
        split_bin=np.concatenate(trees_sb, axis=0).reshape(t * num_classes, -1),
        split_threshold=np.concatenate(trees_st, axis=0).reshape(t * num_classes, -1),
        leaf_values=np.concatenate(trees_lv, axis=0).reshape(t * num_classes, -1),
        init_score=np.asarray(init_score, dtype=np.float32),
        num_classes=num_classes,
        objective=opts.objective,
        max_depth=opts.depth,
        best_iteration=best_iter if (valid_state and opts.early_stopping_round > 0) else -1,
        feature_names=feature_names,
        bin_edges=None if mapper is None else mapper.edges,
    )
    return TrainResult(booster=booster, evals=evals, best_iteration=best_iter)
