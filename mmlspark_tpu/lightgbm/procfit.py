"""Process-spanning GBDT fit over a supervised worker gang.

The reference's distributed fit is real OS processes: each Spark executor
runs native LightGBM against its partition, histograms cross executors
over LightGBM's socket ``Network::Allreduce``, and a died executor means
a re-run task on a surviving one. This module is that shape on the TPU
framework:

- the driver (:func:`fit_process_group`) bins the dataset once, parks it
  in the group workdir, and hands the fit to a
  :class:`~mmlspark_tpu.runtime.procgroup.ProcessGroup` — N worker
  processes, ``jax.distributed`` rendezvous, heartbeats, gang recovery;
- each worker (:func:`worker_fit`) slices its contiguous row shard,
  rebuilds margins from the shared
  :class:`~mmlspark_tpu.runtime.journal.FitJournal`, and runs
  :func:`~mmlspark_tpu.lightgbm.train.train` with the histogram
  allreduce injected (``hist_reduce``) — so every member grows identical
  trees from GLOBAL statistics;
- rank 0 journals each committed iteration (``iteration_hook``), and on
  gang recovery the re-formed group resumes at the first un-journaled
  iteration with ZERO re-execution of committed ones
  (``TaskRecovered`` per restored iteration, exactly like the
  thread-scheduler's checkpoint recovery).

Because the bagging mask would otherwise be drawn per-shard (breaking
parity with a single-process fit), process mode restricts the option
surface: no bagging, no GOSS/dart, no quantile/L1 percentile renewal, no
voting-parallel, no quantized gradients, no validation sets. Everything
else — growth policies, categoricals, feature fraction, weights,
multiclass — carries over unchanged, and a 2-process fit reproduces the
single-process model text byte for byte.
"""

from __future__ import annotations

import dataclasses
import json
import os
import pickle
import time
from pathlib import Path
from typing import Any, Dict, List, Optional

import numpy as np

from mmlspark_tpu.core.profiling import get_logger

logger = get_logger("mmlspark_tpu.lightgbm.procfit")

#: dataclass fields that must come back as tuples after a JSON round-trip
_TUPLE_FIELDS = ("categorical_slots", "onehot_slots")


@dataclasses.dataclass
class ProcessFitResult:
    """What a process-group fit hands back to the driver."""

    booster: Any
    model_text: str
    iterations: int
    recovered_iterations: int
    epochs: int
    worker_results: Dict[int, Any]
    exit_statuses: List[Any]


def options_to_payload(opts) -> Dict[str, Any]:
    """JSON-safe TrainOptions (tuples become lists in flight)."""
    return dataclasses.asdict(opts)


def options_from_payload(d: Dict[str, Any]):
    """Rebuild TrainOptions from the epoch-spec payload, restoring the
    tuple-typed fields (``_opts_key`` hashes them)."""
    from mmlspark_tpu.lightgbm.train import TrainOptions

    fixed = dict(d)
    for key in _TUPLE_FIELDS:
        if key in fixed and isinstance(fixed[key], list):
            fixed[key] = tuple(fixed[key])
    return TrainOptions(**fixed)


def validate_process_options(opts) -> None:
    """Reject option combinations whose semantics depend on the row shard
    (they would break single-process parity) or that need cross-row state
    the histogram allreduce does not carry."""
    problems = []
    if opts.bagging_fraction < 1.0 or opts.bagging_freq > 0:
        problems.append(
            "bagging (masks would be drawn per-shard, not globally)"
        )
    if opts.pos_bagging_fraction < 1.0 or opts.neg_bagging_fraction < 1.0:
        problems.append("pos/neg bagging")
    if opts.boosting_type in ("goss", "dart"):
        problems.append(
            f"boosting_type={opts.boosting_type!r} (GOSS top-k and dart "
            "drops are global-row decisions)"
        )
    if opts.objective in ("quantile", "regression_l1"):
        problems.append(
            f"objective={opts.objective!r} (percentile leaf renewal sorts "
            "all rows globally)"
        )
    if opts.tree_learner == "voting_parallel":
        problems.append("tree_learner='voting_parallel'")
    if opts.use_quantized_grad:
        problems.append("use_quantized_grad (U path is single-device)")
    if opts.histogram_method == "u":
        problems.append("histogram_method='u' (U path is single-device)")
    if opts.provide_training_metric:
        problems.append("provide_training_metric (needs global margins)")
    if opts.early_stopping_round > 0:
        problems.append("early stopping (validation is driver-side)")
    if problems:
        raise ValueError(
            "process-parallel fit does not support: " + "; ".join(problems)
        )


def model_texts_close(a: str, b: str, rtol: float = 1e-3,
                      atol: float = 1e-6) -> bool:
    """Model-text parity for distributed fits.

    A process-parallel fit sums shard histograms over the wire, so float
    cells round differently than the single-process full-row scatter-add
    (1-2 ulps — native LightGBM's parallel learners diverge the same
    way). Tree STRUCTURE must be byte-identical: every line compares
    exactly except that float-valued fields (``split_gain``,
    ``leaf_value``, ...) compare within tolerance. Integer-valued fields
    compare exactly even on the float path."""
    la, lb = a.splitlines(), b.splitlines()
    if len(la) != len(lb):
        return False
    for x, z in zip(la, lb):
        if x == z:
            continue
        ka, _, va = x.partition("=")
        kb, _, vb = z.partition("=")
        if ka != kb:
            return False
        if ka == "tree_sizes":
            # byte length of each serialized tree — tracks float repr
            # width, not structure; only the tree count must agree
            if len(va.split()) != len(vb.split()):
                return False
            continue
        try:
            fa = np.asarray([float(t) for t in va.split()])
            fb = np.asarray([float(t) for t in vb.split()])
        except ValueError:
            return False
        if fa.shape != fb.shape or not np.allclose(fa, fb, rtol=rtol,
                                                   atol=atol):
            return False
    return True


def _journal_key(payload: Dict[str, Any]) -> str:
    return str(payload.get("journal_key", "procfit"))


def _shard(rank: int, world: int, n: int):
    lo = rank * n // world
    hi = (rank + 1) * n // world
    return lo, hi


# -- worker side --------------------------------------------------------------


def worker_fit(ctx) -> Dict[str, Any]:
    """Per-member fit entry, invoked by ``procgroup.worker_main`` inside a
    formed epoch (rendezvous done, socket group live, distributed client
    already released). Returns a small JSON-safe summary; the model rides
    the filesystem (rank 0 writes ``model.txt``), trees ride the shared
    journal."""
    import jax

    from mmlspark_tpu.lightgbm.train import (
        _make_tree_contrib,
        _pack_booster,
        train,
    )
    from mmlspark_tpu.observability import TaskRecovered, get_bus
    from mmlspark_tpu.runtime.journal import FitJournal
    from mmlspark_tpu.runtime.procgroup import GroupRevokedError

    payload = ctx.payload
    opts = options_from_payload(payload["options"])
    data = np.load(payload["dataset"])
    with open(payload["mapper"], "rb") as fh:
        mapper = pickle.load(fh)
    bins, y = data["bins"], data["y"]
    w = data["w"] if "w" in data.files else None
    n = int(y.shape[0])
    lo, hi = _shard(ctx.rank, ctx.world, n)
    bins_l = np.ascontiguousarray(bins[lo:hi])
    y_l = np.ascontiguousarray(y[lo:hi])
    w_l = None if w is None else np.ascontiguousarray(w[lo:hi])

    init_score = np.asarray(payload["init_score"], np.float32)
    num_classes = int(init_score.shape[0])
    total_iters = int(opts.num_iterations)

    journal = FitJournal(
        payload["journal_root"], key=_journal_key(payload),
        num_tasks=total_iters,
    )
    restored = journal.restore()
    trees: List[Any] = []
    while len(trees) in restored:  # contiguous committed prefix only
        trees.append(restored[len(trees)])
    k = len(trees)
    bus = get_bus()
    if k and ctx.rank == 0 and bus.active:
        # the scheduler's checkpoint-recovery event, one per iteration
        # that will NOT re-execute
        for it in range(k):
            bus.publish(TaskRecovered(job_id=0, task_id=it))
    if k:
        logger.info("member %d resuming at iteration %d/%d (epoch %d)",
                    ctx.member, k, total_iters, ctx.epoch)

    # margins = global init score + the committed trees applied to the
    # LOCAL shard (trees are membership-independent, so this works for any
    # re-formed world size)
    margins = np.broadcast_to(
        init_score[None, :], (y_l.shape[0], num_classes)
    ).astype(np.float32).copy()
    if k:
        # bins are EFB-packed when the shared mapper carries a bundle plan;
        # journaled trees are in original feature ids like any fit's
        contrib = _make_tree_contrib(
            opts.routing_steps, getattr(mapper, "bundles", None)
        )
        bins_dev = np.asarray(bins_l, dtype=np.int32)
        for tr in trees:
            margins = margins + np.asarray(contrib(
                bins_dev, tr.feat, tr.bin, tr.left, tr.right, tr.is_leaf,
                tr.leaf_val, tr.cat_node, tr.cat_mask,
            ))

    state = {"it": k}
    # per-member allreduce timing, summarized into the worker result so
    # the driver can fold the wire-vs-device split per member (the
    # worker's own registry/profiler dies with the process)
    wire = {"calls": 0, "seconds": 0.0}

    def hist_reduce(h):
        # first collective of iteration `it`: the designated death point
        # for kill_process chaos — peers are already blocked in this same
        # allreduce when the victim goes down. Under sibling subtraction
        # (the default) `h` holds only the SMALLER child of each frontier
        # split — members derive the sibling from the cached parent AFTER
        # this reduce, so the wire payload per pass is halved. Alignment
        # holds because every member picks the smaller child from the
        # same GLOBAL (already-reduced) parent stats.
        ctx.maybe_die(state["it"])
        t0 = time.perf_counter()
        out = ctx.allreduce(h)
        wire["calls"] += 1
        wire["seconds"] += time.perf_counter() - t0
        return out

    def hook(it, tree):
        tree_np = jax.tree.map(
            lambda a: None if a is None else np.asarray(a), tree,
            is_leaf=lambda a: a is None,
        )
        if ctx.rank == 0:
            journal.record(it, tree_np)
        trees.append(tree_np)
        state["it"] = it + 1

    # child of the ambient gang.worker span, so the fit shows up in the
    # driver's trace (the epoch spec carried the TraceContext over)
    from mmlspark_tpu.observability.tracing import get_tracer

    try:
        with get_tracer().span(
            "procfit.train", member=ctx.member, rank=ctx.rank,
            world=ctx.world, start_iteration=k,
        ):
            train(
                bins_l, y_l, opts, w=w_l, init_margins=margins,
                mapper=mapper,
                feature_names=payload.get("feature_names"),
                hist_reduce=hist_reduce if ctx.world > 1 else None,
                iteration_hook=hook, start_iteration=k,
            )
    except GroupRevokedError:
        raise
    except Exception as e:
        if ctx.group is not None and ctx.group.revoked:
            # the allreduce died inside jit; jax re-raises it as
            # XlaRuntimeError — translate back to the gang-protocol signal
            raise GroupRevokedError(
                f"collective failed at iteration {state['it']}: {e}"
            ) from e
        raise

    result: Dict[str, Any] = {
        "iterations": len(trees), "recovered": k, "rank": ctx.rank,
        "world": ctx.world, "rows": int(y_l.shape[0]),
        "journal_appended": journal.appended,
        "profile": {
            "allreduce_calls": wire["calls"],
            "allreduce_seconds": wire["seconds"],
        },
    }
    from mmlspark_tpu.observability.profiler import get_profiler

    worker_prof = get_profiler()
    if worker_prof.active:
        # full per-function table: the worker's registry/profiler dies
        # with the process, so ship it home in the result for the
        # driver-side fold (history roofline then covers gang workers)
        result["profile"]["functions"] = {
            name: {
                "executions": int(p.get("executions", 0)),
                "device_seconds": float(p.get("device_seconds", 0.0)),
                "compiles": int(p.get("compiles", 0)),
                "compile_seconds": float(p.get("compile_seconds", 0.0)),
            }
            for name, p in worker_prof.snapshot()["functions"].items()
        }
    if ctx.rank == 0:
        booster = _pack_booster(
            trees, None, opts, num_classes, init_score, mapper,
            payload.get("feature_names"),
        )
        model_path = Path(ctx.workdir) / "model.txt"
        model_path.write_text(booster.model_to_string())
        result["model_path"] = str(model_path)
    journal.close()
    return result


# -- driver side --------------------------------------------------------------


def fit_process_group(
    X: Optional[np.ndarray],
    y: np.ndarray,
    opts,
    w: Optional[np.ndarray] = None,
    num_processes: int = 2,
    workdir: Optional[str] = None,
    feature_names: Optional[List[str]] = None,
    bins: Optional[np.ndarray] = None,
    mapper=None,
    journal_root: Optional[str] = None,
    journal_key: str = "procfit",
    group_options: Optional[Dict[str, Any]] = None,
) -> ProcessFitResult:
    """Fit a booster across ``num_processes`` real worker processes.

    Pass raw ``X`` (binned here, once, on the driver) or pre-binned
    ``bins`` + ``mapper`` (the ``LightGBMBase._bin_dataset`` output — its
    binning journal still applies). The fit itself is delegated to a
    :class:`~mmlspark_tpu.runtime.procgroup.ProcessGroup`; a member
    SIGKILL'd mid-fit surfaces here only as ``ProcessLost``/
    ``GroupReformed`` events and a higher ``epochs`` count — the returned
    model is the same either way, resumed from the shared journal with no
    committed iteration re-executed.
    """
    from mmlspark_tpu.lightgbm.booster import Booster
    from mmlspark_tpu.lightgbm.objectives import get_objective
    from mmlspark_tpu.runtime.procgroup import ProcessGroup

    validate_process_options(opts)
    if bins is None:
        if X is None:
            raise ValueError("pass either X or pre-binned bins + mapper")
        from mmlspark_tpu.lightgbm.binning import bin_dataset

        bins, mapper = bin_dataset(
            X, max_bin=opts.max_bin, mapper=mapper,
            categorical_features=list(opts.categorical_slots) or None,
        )
    elif mapper is None:
        raise ValueError("pre-binned input requires its BinMapper")
    n = int(np.asarray(y).shape[0])
    if n < num_processes:
        raise ValueError(f"{n} rows cannot shard over {num_processes} processes")

    objective = get_objective(opts.objective)
    num_classes = objective.num_outputs_fn(opts.num_class)
    y_np = np.asarray(y, dtype=np.float32)
    w_np = None if w is None else np.asarray(w, dtype=np.float32)
    if opts.boost_from_average:
        init_score = objective.init_score(
            y_np, num_classes,
            np.ones(n, np.float32) if w_np is None else w_np,
        )
    else:
        init_score = np.zeros(num_classes, dtype=np.float32)

    if workdir is None:
        import tempfile

        workdir = tempfile.mkdtemp(prefix="mmlspark-tpu-procfit-")
    wd = Path(workdir)
    wd.mkdir(parents=True, exist_ok=True)
    dataset_path = wd / "dataset.npz"
    arrays = {"bins": np.asarray(bins), "y": y_np}
    if w_np is not None:
        arrays["w"] = w_np
    np.savez(dataset_path, **arrays)
    mapper_path = wd / "mapper.pkl"
    with open(mapper_path, "wb") as fh:
        pickle.dump(mapper, fh, protocol=4)
    if journal_root is None:
        journal_root = str(wd / "journal")
    # Pre-create the journal (meta.json) on the driver: worker ranks open
    # the same journal concurrently, and only an already-settled meta file
    # keeps their constructors read-only (no atomic-write race).
    from mmlspark_tpu.runtime.journal import FitJournal

    FitJournal(journal_root, key=journal_key,
               num_tasks=int(opts.num_iterations)).close()

    payload = {
        "dataset": str(dataset_path),
        "mapper": str(mapper_path),
        "options": options_to_payload(opts),
        "init_score": [float(v) for v in np.asarray(init_score).ravel()],
        "feature_names": list(feature_names) if feature_names else None,
        "journal_root": journal_root,
        "journal_key": journal_key,
    }
    gkw = dict(group_options or {})
    gkw.setdefault("seed", opts.seed)
    pg = ProcessGroup(
        num_processes, "mmlspark_tpu.lightgbm.procfit:worker_fit",
        payload=payload, workdir=str(wd / "group"), rendezvous="jax", **gkw,
    )
    try:
        worker_results = pg.run()
    finally:
        # losses booked during recovery + final statuses from shutdown
        exit_statuses = pg.exit_statuses + pg.shutdown()

    model_path = None
    recovered = 0
    iterations = 0
    for res in worker_results.values():
        if res and res.get("model_path"):
            model_path = res["model_path"]
        if res:
            recovered = max(recovered, int(res.get("recovered", 0)))
            iterations = max(iterations, int(res.get("iterations", 0)))
    if model_path is None:
        raise RuntimeError(
            f"no member produced a model; results: {worker_results}"
        )
    # fold the per-member allreduce timing into the driver's profiler —
    # the process-spanning analogue of the in-process hist_allreduce wrap
    from mmlspark_tpu.observability.profiler import get_profiler

    prof = get_profiler()
    if prof.active:
        for member in sorted(worker_results):
            p = (worker_results[member] or {}).get("profile") or {}
            if p.get("allreduce_calls"):
                prof.merge(
                    f"procfit.allreduce[m{member}]",
                    executions=int(p["allreduce_calls"]),
                    device_seconds=float(p.get("allreduce_seconds", 0.0)),
                )
            # the worker's own profile table, qualified per member — the
            # federation hop that puts gang-worker kernels on the
            # driver's roofline (history report + incident bundles)
            for name, fp in sorted((p.get("functions") or {}).items()):
                prof.merge(
                    f"{name}[m{member}]",
                    executions=int(fp.get("executions", 0)),
                    device_seconds=float(fp.get("device_seconds", 0.0)),
                    compiles=int(fp.get("compiles", 0)),
                    compile_seconds=float(fp.get("compile_seconds", 0.0)),
                )

    model_text = Path(model_path).read_text()
    booster = Booster.from_string(model_text)
    # the text round-trip keeps only [min:max] per feature; restore the
    # full bin edges so this booster re-serializes like an in-process fit
    booster.bin_edges = None if mapper is None else mapper.edges
    return ProcessFitResult(
        booster=booster,
        model_text=model_text,
        iterations=iterations,
        recovered_iterations=recovered,
        epochs=pg.epoch + 1,
        worker_results=worker_results,
        exit_statuses=exit_statuses,
    )
