"""Training delegate/callback hooks for GBDT boosting.

Mirrors the reference's ``LightGBMDelegate``
(``lightgbm/LightGBMDelegate.scala``: beforeTrainIteration /
afterTrainIteration / getLearningRate) and the dynamic-learning-rate path
(``lightgbm/TrainUtils.scala:211-218``, exercised by
``VerifyLightGBMClassifier.scala:394``).

TPU-first split of responsibilities:

- ``get_learning_rate(iteration)`` is **schedule-only** (a pure function of
  the iteration index). It is precomputed on the host into a
  ``(num_iterations,)`` array that rides the single-dispatch ``lax.scan``
  training program as a scanned input — dynamic LR costs nothing.
- ``before_iteration`` / ``after_iteration`` need per-iteration host
  control, so their presence switches training to the per-iteration loop
  path (one device program per tree, the reference's own cadence).
  ``after_iteration`` returning ``True`` stops training (the delegate's
  early-stop channel, composing with metric-based early stopping).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence


@dataclasses.dataclass
class CallbackEnv:
    """What a hook sees. ``evals`` holds the metric history so far
    (set name -> metric -> scores per iteration)."""

    iteration: int  # 0-based
    num_iterations: int
    learning_rate: float
    evals: Dict[str, Dict[str, List[float]]]


class TrainingCallback:
    """Base delegate. Override any subset; the default is a no-op."""

    def before_training(self, env: CallbackEnv) -> None:  # noqa: B027
        pass

    def after_training(self, env: CallbackEnv) -> None:  # noqa: B027
        pass

    def before_iteration(self, env: CallbackEnv) -> None:  # noqa: B027
        pass

    def after_iteration(self, env: CallbackEnv) -> Optional[bool]:
        """Return True to stop training after this iteration."""
        return None

    def get_learning_rate(self, iteration: int) -> Optional[float]:
        """Schedule-only dynamic LR; None = keep the configured rate."""
        return None


class LearningRateSchedule(TrainingCallback):
    """``reset_parameter``-style LR schedule from a function or list."""

    def __init__(self, schedule):
        self._schedule = schedule

    def get_learning_rate(self, iteration: int) -> float:
        if callable(self._schedule):
            return float(self._schedule(iteration))
        return float(self._schedule[iteration])


def _has_iteration_hooks(callbacks: Sequence[TrainingCallback]) -> bool:
    """True when any callback overrides a per-iteration host hook (their
    presence forfeits the one-dispatch scan fast path)."""
    for cb in callbacks:
        if type(cb).before_iteration is not TrainingCallback.before_iteration:
            return True
        if type(cb).after_iteration is not TrainingCallback.after_iteration:
            return True
    return False


def _lr_schedule(
    callbacks: Sequence[TrainingCallback], base_lr: float, num_iterations: int
):
    """(num_iterations,) float32 LR array, or None when constant. The LAST
    callback that returns a rate for an iteration wins (delegate chaining)."""
    import numpy as np

    out = np.full(num_iterations, base_lr, dtype=np.float32)
    dynamic = False
    for cb in callbacks:
        if type(cb).get_learning_rate is TrainingCallback.get_learning_rate:
            continue
        for it in range(num_iterations):
            lr = cb.get_learning_rate(it)
            if lr is not None:
                out[it] = lr
                dynamic = True
    return out if dynamic else None
