"""Histogram gradient-boosted decision trees, TPU-native.

Same capability surface as LightGBM-on-Spark (reference ``lightgbm/``,
SURVEY.md §2.2) — ``LightGBMClassifier`` / ``LightGBMRegressor`` /
``LightGBMRanker`` estimators with boosters, early stopping, bagging,
warm start — but the native C++ core (``lightgbmlib`` SWIG jar) and its
socket-mesh allreduce (``LGBM_NetworkInit``) are replaced by jitted XLA:

- feature values quantile-binned to uint8 on the host (C++-ready layout),
- per-depth histogram building as one dense pass (segment-sum / one-hot
  matmul onto the MXU) instead of per-leaf scatter loops,
- split search as pure array ops over the (node, feature, bin) lattice,
- data-parallel training by shard-by-rows + ``lax.psum`` of histograms
  over the ICI mesh — the ``tree_learner=data_parallel`` equivalent.
"""

from mmlspark_tpu.lightgbm.classifier import LightGBMClassificationModel, LightGBMClassifier
from mmlspark_tpu.lightgbm.regressor import LightGBMRegressionModel, LightGBMRegressor
from mmlspark_tpu.lightgbm.ranker import LightGBMRanker, LightGBMRankerModel
from mmlspark_tpu.lightgbm.booster import Booster

__all__ = [
    "LightGBMClassifier",
    "LightGBMClassificationModel",
    "LightGBMRegressor",
    "LightGBMRegressionModel",
    "LightGBMRanker",
    "LightGBMRankerModel",
    "Booster",
]
