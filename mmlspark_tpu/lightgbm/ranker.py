"""LightGBMRanker — LambdaRank (NDCG-weighted pairwise) learning-to-rank.

API parity with ``lightgbm/LightGBMRanker.scala:73-102``: ``groupCol``
defines query groups (rows are sorted by group before training, the
``sortWithinPartitions(group)`` analogue); run-length group encoding mirrors
``countCardinality`` (``lightgbm/TrainUtils.scala:105-155``).

TPU formulation: groups are padded to a static max size G, and the LambdaRank
gradients are computed as dense (Q, G, G) pairwise tensors in one jitted
program — no per-query loops.
"""

from __future__ import annotations

import hashlib
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from mmlspark_tpu.core.params import HasGroupCol, Param, gt, to_float, to_int, to_str
from mmlspark_tpu.data.table import Table
from mmlspark_tpu.lightgbm.base import (
    LightGBMBase,
    LightGBMModelBase,
    extract_features,
)
from mmlspark_tpu.lightgbm.objectives import OBJECTIVES, Objective
from mmlspark_tpu.lightgbm.train import TrainResult


def group_structure(group: np.ndarray) -> Tuple[np.ndarray, int]:
    """Row indices per group, padded with N. Requires rows sorted by group
    (we sort in fit). Returns (index (Q, G) int32, max_group_size)."""
    n = len(group)
    change = np.nonzero(np.concatenate([[True], group[1:] != group[:-1]]))[0]
    starts = change
    ends = np.concatenate([change[1:], [n]])
    sizes = ends - starts
    g_max = int(sizes.max())
    q = len(starts)
    idx = np.full((q, g_max), n, dtype=np.int32)
    for qi, (s, e) in enumerate(zip(starts, ends)):
        idx[qi, : e - s] = np.arange(s, e)
    return idx, g_max


def _gain_fn(label_gain):
    """Relevance -> gain. None = LightGBM's default 2^i - 1 table; a custom
    ``label_gain`` array is indexed by the integer relevance label
    (LightGBMRanker labelGain / native lambdarank label_gain)."""
    if label_gain is None:
        return lambda yy: jnp.exp2(yy) - 1.0
    lg = jnp.asarray(np.asarray(label_gain, np.float32))

    def fn(yy):
        return lg[jnp.clip(yy.astype(jnp.int32), 0, lg.shape[0] - 1)]

    return fn


def make_lambdarank_objective(
    group_index: np.ndarray, sigma: float = 1.0, label_gain=None
) -> Objective:
    """Objective whose grad/hess are LambdaRank lambdas over padded groups."""
    idx = jnp.asarray(group_index)  # (Q, G), pad = N
    q, g = group_index.shape
    gain_of = _gain_fn(label_gain)

    def grad_hess(margins, y, w, **kw):
        n = margins.shape[0]
        pad = lambda a: jnp.concatenate([a, jnp.zeros((1,), a.dtype)])
        m = pad(margins[:, 0])[idx]  # (Q, G)
        yy = pad(y)[idx]
        ww = pad(w)[idx]
        mask = (idx < n).astype(jnp.float32)

        # ranks of each item within its group by current margin (descending)
        neg = jnp.where(mask > 0, m, -jnp.inf)
        order = jnp.argsort(-neg, axis=1)
        pos = jnp.argsort(order, axis=1)  # 0-based rank
        discount = 1.0 / jnp.log2(2.0 + pos)
        gain = gain_of(yy) * mask

        # ideal DCG per group (labels sorted descending)
        sorted_gain = -jnp.sort(-gain, axis=1)
        ideal_discount = 1.0 / jnp.log2(2.0 + jnp.arange(g, dtype=jnp.float32))
        idcg = jnp.maximum((sorted_gain * ideal_discount[None, :]).sum(axis=1), 1e-12)

        diff_m = m[:, :, None] - m[:, None, :]  # (Q, G, G) si - sj
        better = ((yy[:, :, None] > yy[:, None, :])
                  & (mask[:, :, None] > 0) & (mask[:, None, :] > 0))
        delta_ndcg = jnp.abs(
            (gain[:, :, None] - gain[:, None, :])
            * (discount[:, :, None] - discount[:, None, :])
        ) / idcg[:, None, None]

        rho = jax.nn.sigmoid(-sigma * diff_m)  # P(si should beat sj but doesn't)
        lam = jnp.where(better, -sigma * rho * delta_ndcg, 0.0)
        hees = jnp.where(better, sigma * sigma * rho * (1 - rho) * delta_ndcg, 0.0)

        grad_g = lam.sum(axis=2) - lam.sum(axis=1)  # i as winner minus i as loser
        hess_g = hees.sum(axis=2) + hees.sum(axis=1)
        grad_g = grad_g * ww
        hess_g = jnp.maximum(hess_g, 1e-16) * ww

        # scatter back to rows (pad targets drop)
        flat_idx = idx.reshape(-1)
        grad = jnp.zeros(n + 1).at[flat_idx].add(grad_g.reshape(-1))[:n]
        hess = jnp.zeros(n + 1).at[flat_idx].add(hess_g.reshape(-1))[:n]
        hess = jnp.maximum(hess, 1e-16)
        return grad[:, None], hess[:, None]

    def init_score(y, num_classes, w):
        return np.zeros(1, dtype=np.float32)

    # Content-derived token: the jitted-program cache must not conflate two
    # fits whose group structures / gain tables differ, but refits on the
    # SAME grouping (CV folds resampled elsewhere, param sweeps) must still
    # hit the cache — re-tracing is seconds per fit.
    gi = np.ascontiguousarray(group_index)
    token = hashlib.sha1(
        repr((gi.shape, str(gi.dtype))).encode() + gi.tobytes()
    ).hexdigest()
    lg_key = None if label_gain is None else tuple(float(v) for v in label_gain)
    return Objective(
        "lambdarank", lambda c: 1, grad_hess, init_score, "ndcg@5",
        cache_token=("lambdarank", token, float(sigma), lg_key),
    )


def ndcg_at_k(y: np.ndarray, score: np.ndarray, group: np.ndarray, k: int,
              label_gain=None) -> float:
    """Host-side NDCG@k over contiguous groups. ``label_gain``: optional
    relevance->gain table (default: LightGBM's 2^i - 1)."""
    if label_gain is None:
        gains_of = lambda yy: (2.0 ** yy) - 1
    else:
        lg = np.asarray(label_gain, np.float64)
        gains_of = lambda yy: lg[np.clip(yy.astype(np.int64), 0, len(lg) - 1)]
    total, q = 0.0, 0
    i, n = 0, len(y)
    while i < n:
        j = i
        while j < n and group[j] == group[i]:
            j += 1
        yy, ss = y[i:j], score[i:j]
        order = np.argsort(-ss, kind="stable")[:k]
        gains = gains_of(yy[order])
        disc = 1.0 / np.log2(2 + np.arange(len(order)))
        dcg = float((gains * disc).sum())
        ideal_y = np.sort(yy)[::-1][:k]  # already descending
        idcg = float((gains_of(ideal_y) * (1.0 / np.log2(2 + np.arange(len(ideal_y))))).sum())
        if idcg > 0:
            total += dcg / idcg
            q += 1
        i = j
    return total / max(q, 1)


class LightGBMRanker(HasGroupCol, LightGBMBase):
    objective = Param("Ranking objective", default="lambdarank", converter=to_str)
    sigma = Param("LambdaRank sigmoid steepness", default=1.0, converter=to_float, validator=gt(0))
    evalAt = Param("NDCG truncation for eval", default=5, converter=to_int, validator=gt(0))
    maxPosition = Param("Accepted for parity (NDCG optimization position)", default=20, converter=to_int)
    labelGain = Param(
        "Relevance->gain table for the lambdarank objective and ndcg eval "
        "(empty = LightGBM's default 2^i - 1); indexed by the integer "
        "relevance label",
        default=[],
    )

    def _objective_name(self) -> str:
        return "lambdarank"

    def _fit(self, table: Table):
        table = table.sort_by(self.getGroupCol())
        group = np.asarray(table.column(self.getGroupCol()))
        idx, _ = group_structure(group)
        lg = self.getLabelGain() or None
        if lg is not None:
            max_label = int(np.max(table.column(self.getLabelCol())))
            if max_label >= len(lg):
                raise ValueError(
                    f"labelGain has {len(lg)} entries but labels reach "
                    f"{max_label}"
                )
        # register a table-specific lambdarank objective for the train loop
        OBJECTIVES["lambdarank"] = make_lambdarank_objective(
            idx, self.getSigma(), label_gain=lg
        )
        try:
            return super()._fit(table)
        finally:
            OBJECTIVES.pop("lambdarank", None)

    def _extra_train_options(self) -> dict:
        # ndcg during training needs group context the generic eval loop does
        # not carry yet; monitor margin l2 unless the user set a metric.
        if not self.getMetric():
            return {"metric": "l2"}
        return {}

    def _make_model(self, result: TrainResult) -> "LightGBMRankerModel":
        return LightGBMRankerModel(
            featuresCol=self.getFeaturesCol(),
            predictionCol=self.getPredictionCol(),
            leafPredictionCol=self.getLeafPredictionCol(),
            featuresShapCol=self.getFeaturesShapCol(),
            boosterData=result.booster.to_dict(),
        )


class LightGBMRankerModel(LightGBMModelBase):
    def transform(self, table: Table) -> Table:
        booster = self.booster
        X = extract_features(table, self.getFeaturesCol(), booster.num_features)
        margins = booster.raw_margin(X)[:, 0]
        out = table.with_column(self.getPredictionCol(), margins.astype(np.float64))
        return self._with_leaf_col(out, X)
