"""Ranking evaluation + adapters (reference ``recommendation/``):

- :class:`AdvancedRankingMetrics` / :class:`RankingEvaluator` —
  ``RankingEvaluator.scala:15-152`` (map, ndcgAt, precisionAtk, recallAtK,
  diversityAtK, maxDiversity, mrr, fcp).
- :class:`RankingAdapter` / :class:`RankingAdapterModel` —
  ``RankingAdapter.scala:67-151`` (wrap any recommender to emit per-user
  (prediction, label) ranked lists for evaluation).
- :class:`RecommendationIndexer` — ``RecommendationIndexer.scala:17-101``
  (user/item value → dense index).
- :class:`RankingTrainValidationSplit` —
  ``RankingTrainValidationSplit.scala:24-328`` (user-stratified split with
  min-ratings filters, then fit/evaluate over a param grid).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from mmlspark_tpu.core.params import Param, gt, one_of, to_float, to_int, to_str
from mmlspark_tpu.core.pipeline import Estimator, Evaluator, Model, Transformer
from mmlspark_tpu.data.table import Table
from mmlspark_tpu.featurize.indexers import ValueIndexer


class AdvancedRankingMetrics:
    """All ranking metrics over per-row (predicted items, actual items)
    pairs — formulas match mllib ``RankingMetrics`` plus the reference's
    extras (``RankingEvaluator.scala:15-93``)."""

    def __init__(self, pred_and_labels: Sequence[Tuple[Sequence, Sequence]],
                 k: int, n_items: int):
        self.pairs = [(list(p), list(l)) for p, l in pred_and_labels]
        self.k = k
        self.n_items = n_items

    def mean_average_precision(self) -> float:
        out = []
        for pred, lab in self.pairs:
            lab_set = set(lab)
            if not lab_set:
                out.append(0.0)
                continue
            hits, score = 0, 0.0
            for i, p in enumerate(pred):
                if p in lab_set:
                    hits += 1
                    score += hits / (i + 1.0)
            out.append(score / len(lab_set))
        return float(np.mean(out)) if out else 0.0

    def ndcg_at(self) -> float:
        k = self.k
        out = []
        for pred, lab in self.pairs:
            lab_set = set(lab)
            if not lab_set:
                out.append(0.0)
                continue
            n = min(max(len(pred), len(lab)), k)
            dcg = sum(
                1.0 / np.log2(i + 2)
                for i in range(min(len(pred), n))
                if pred[i] in lab_set
            )
            idcg = sum(1.0 / np.log2(i + 2) for i in range(min(len(lab_set), n)))
            out.append(dcg / idcg if idcg > 0 else 0.0)
        return float(np.mean(out)) if out else 0.0

    def precision_at_k(self) -> float:
        k = self.k
        out = [
            len(set(pred[:k]) & set(lab)) / float(k)
            for pred, lab in self.pairs
        ]
        return float(np.mean(out)) if out else 0.0

    def recall_at_k(self) -> float:
        # Reference quirk preserved: denominator is |pred|, not |label|
        # (``RankingEvaluator.scala:27-30``).
        out = [
            len(set(pred) & set(lab)) / float(len(pred)) if pred else 0.0
            for pred, lab in self.pairs
        ]
        return float(np.mean(out)) if out else 0.0

    def diversity_at_k(self) -> float:
        recommended = set()
        for pred, _ in self.pairs:
            recommended.update(pred)
        return len(recommended) / float(self.n_items)

    def max_diversity(self) -> float:
        seen = set()
        for pred, lab in self.pairs:
            seen.update(lab)
            seen.update(pred)
        return len(seen) / float(self.n_items)

    def mean_reciprocal_rank(self) -> float:
        out = []
        for pred, lab in self.pairs:
            lab_set = set(lab)
            rr = 0.0
            if lab_set:
                for i, p in enumerate(pred):
                    if p in lab_set:
                        rr = 1.0 / (i + 1)
                        break
            out.append(rr)
        return float(np.mean(out)) if out else 0.0

    def fraction_concordant_pairs(self) -> float:
        out = []
        for pred, lab in self.pairs:
            nc = nd = 0.0
            for i, p in enumerate(pred):
                if i < len(lab):
                    if p == lab[i]:
                        nc += 1
                    else:
                        nd += 1
            out.append(nc / (nc + nd) if (nc + nd) > 0 else 0.0)
        return float(np.mean(out)) if out else 0.0

    _DISPATCH = {
        "map": mean_average_precision,
        "ndcgAt": ndcg_at,
        "precisionAtk": precision_at_k,
        "recallAtK": recall_at_k,
        "diversityAtK": diversity_at_k,
        "maxDiversity": max_diversity,
        "mrr": mean_reciprocal_rank,
        "fcp": fraction_concordant_pairs,
    }

    def match_metric(self, name: str) -> float:
        return self._DISPATCH[name](self)

    def get_all_metrics(self) -> Dict[str, float]:
        return {name: fn(self) for name, fn in self._DISPATCH.items()}


class RankingEvaluator(Evaluator):
    """Evaluates a table of per-user ``predictionCol``/``labelCol`` item
    lists (``RankingEvaluator.scala:98-152``)."""

    k = Param("Cutoff for ndcg/precision", default=10, converter=to_int,
              validator=gt(0))
    nItems = Param("Catalog size for diversity metrics", default=-1,
                   converter=to_int)
    metricName = Param("Which metric evaluate() returns", default="ndcgAt",
                       converter=to_str,
                       validator=one_of(*AdvancedRankingMetrics._DISPATCH))
    predictionCol = Param("Predicted item-list column", default="prediction",
                          converter=to_str)
    labelCol = Param("Actual item-list column", default="label", converter=to_str)

    def _metrics(self, table: Table) -> AdvancedRankingMetrics:
        preds = table.column(self.getPredictionCol())
        labels = table.column(self.getLabelCol())
        pairs = list(zip([list(p) for p in preds], [list(l) for l in labels]))
        n_items = self.getNItems()
        if n_items <= 0:
            n_items = len({i for p, l in pairs for i in list(p) + list(l)})
        return AdvancedRankingMetrics(pairs, self.getK(), max(n_items, 1))

    def get_metrics_map(self, table: Table) -> Dict[str, float]:
        return self._metrics(table).get_all_metrics()

    def evaluate(self, table: Table) -> float:
        return self._metrics(table).match_metric(self.getMetricName())

    def is_larger_better(self) -> bool:
        return True


class RecommendationIndexer(Estimator):
    """User/item value → dense index, with inverse transform
    (``RecommendationIndexer.scala:17-101``); composed from two
    :class:`ValueIndexer` fits."""

    userInputCol = Param("Raw user column", converter=to_str)
    userOutputCol = Param("Indexed user column", converter=to_str)
    itemInputCol = Param("Raw item column", converter=to_str)
    itemOutputCol = Param("Indexed item column", converter=to_str)
    ratingCol = Param("Rating column (passed through)", default="rating",
                      converter=to_str)

    def _fit(self, table: Table) -> "RecommendationIndexerModel":
        user_model = ValueIndexer(
            inputCol=self.getUserInputCol(), outputCol=self.getUserOutputCol()
        ).fit(table)
        item_model = ValueIndexer(
            inputCol=self.getItemInputCol(), outputCol=self.getItemOutputCol()
        ).fit(table)
        model = RecommendationIndexerModel(
            userInputCol=self.getUserInputCol(),
            userOutputCol=self.getUserOutputCol(),
            itemInputCol=self.getItemInputCol(),
            itemOutputCol=self.getItemOutputCol(),
            userIndexModel=user_model,
            itemIndexModel=item_model,
        )
        model.parent = self
        return model


class RecommendationIndexerModel(Model):
    userInputCol = Param("Raw user column", converter=to_str)
    userOutputCol = Param("Indexed user column", converter=to_str)
    itemInputCol = Param("Raw item column", converter=to_str)
    itemOutputCol = Param("Indexed item column", converter=to_str)
    userIndexModel = Param("Fitted user ValueIndexerModel", is_complex=True,
                           default=None)
    itemIndexModel = Param("Fitted item ValueIndexerModel", is_complex=True,
                           default=None)

    def transform(self, table: Table) -> Table:
        out = self.getUserIndexModel().transform(table)
        return self.getItemIndexModel().transform(out)

    def recover_user(self, indices: np.ndarray) -> np.ndarray:
        from mmlspark_tpu.featurize.indexers import decode_levels

        return decode_levels(indices, self.getUserIndexModel().getLevels())

    def recover_item(self, indices: np.ndarray) -> np.ndarray:
        from mmlspark_tpu.featurize.indexers import decode_levels

        return decode_levels(indices, self.getItemIndexModel().getLevels())


class RankingAdapter(Estimator):
    """Wraps a recommender Estimator so its output can feed
    :class:`RankingEvaluator` (``RankingAdapter.scala:67-97``)."""

    recommender = Param("The wrapped recommender estimator", is_complex=True)
    k = Param("Recommendations per user", default=10, converter=to_int,
              validator=gt(0))
    mode = Param("allUsers (recommendForAllUsers)", default="allUsers",
                 converter=to_str, validator=one_of("allUsers"))
    labelCol = Param("Output column of per-user actual items", default="label",
                     converter=to_str)

    def _fit(self, table: Table) -> "RankingAdapterModel":
        rec_model = self.getRecommender().fit(table)
        model = RankingAdapterModel(
            recommenderModel=rec_model,
            k=self.getK(),
            mode=self.getMode(),
            labelCol=self.getLabelCol(),
        )
        model.parent = self
        return model


class RankingAdapterModel(Model):
    """transform(): per-user top-k ground truth (by rating desc) joined with
    the recommender's top-k predictions (``RankingAdapter.scala:116-141``)."""

    recommenderModel = Param("Fitted recommender", is_complex=True, default=None)
    k = Param("Recommendations per user", default=10, converter=to_int)
    mode = Param("allUsers", default="allUsers", converter=to_str)
    labelCol = Param("Per-user actual item lists", default="label", converter=to_str)

    def transform(self, table: Table) -> Table:
        rec = self.getRecommenderModel()
        user_col, item_col = rec.getUserCol(), rec.getItemCol()
        rating_col = rec.getRatingCol()
        k = self.getK()

        users = table.column(user_col).astype(np.int64)
        items = table.column(item_col).astype(np.int64)
        ratings = (
            table.column(rating_col).astype(np.float64)
            if rating_col in table
            else np.ones(len(users))
        )
        # per-user actual top-k items ordered by rating desc, item asc
        order = np.lexsort((items, -ratings, users))
        actual: Dict[int, List[int]] = {}
        for i in order:
            u = int(users[i])
            lst = actual.setdefault(u, [])
            if len(lst) < k:
                lst.append(int(items[i]))

        recs = rec.recommend_for_user_subset(table, k)
        rec_users = recs.column(user_col).astype(np.int64)
        rec_items = recs.column("recommendations")

        preds = np.empty(len(rec_users), dtype=object)
        labels = np.empty(len(rec_users), dtype=object)
        for n, u in enumerate(rec_users):
            preds[n] = [int(v) for v in rec_items[n]]
            labels[n] = actual.get(int(u), [])
        return Table({"prediction": preds, self.getLabelCol(): labels})


class RankingTrainValidationSplit(Estimator):
    """User-stratified train/validation split + grid evaluation
    (``RankingTrainValidationSplit.scala:24-328``). Rows of users/items with
    fewer than ``minRatingsU``/``minRatingsI`` events are dropped, each
    user's events are split by ``trainRatio``, and each param map is
    fitted on train / scored on validation with :class:`RankingEvaluator`."""

    estimator = Param("Recommender estimator (fit via RankingAdapter)",
                      is_complex=True)
    evaluator = Param("RankingEvaluator", is_complex=True, default=None)
    estimatorParamMaps = Param("Param maps to sweep (list of dicts)",
                               default=None, is_complex=True)
    trainRatio = Param("Fraction of each user's events in train", default=0.75,
                       converter=to_float, validator=lambda v: 0.0 < v < 1.0)
    minRatingsU = Param("Min events per user", default=1, converter=to_int,
                        validator=gt(0))
    minRatingsI = Param("Min events per item", default=1, converter=to_int,
                        validator=gt(0))
    userCol = Param("User column", default="user", converter=to_str)
    itemCol = Param("Item column", default="item", converter=to_str)
    ratingCol = Param("Rating column", default="rating", converter=to_str)
    seed = Param("Split RNG seed", default=42, converter=to_int)

    def _filter_min_ratings(self, table: Table) -> Table:
        users = table.column(self.getUserCol()).astype(np.int64)
        items = table.column(self.getItemCol()).astype(np.int64)
        keep = np.ones(len(users), dtype=bool)
        u_counts = np.bincount(users)
        i_counts = np.bincount(items)
        keep &= u_counts[users] >= self.getMinRatingsU()
        keep &= i_counts[items] >= self.getMinRatingsI()
        return table.filter(keep)

    def split(self, table: Table) -> Tuple[Table, Table]:
        table = self._filter_min_ratings(table)
        users = table.column(self.getUserCol()).astype(np.int64)
        rng = np.random.default_rng(self.getSeed())
        ratio = self.getTrainRatio()
        in_train = np.zeros(len(users), dtype=bool)
        # one O(n log n) pass: group rows by user, shuffle within each group
        order = np.argsort(users, kind="stable")
        _, starts = np.unique(users[order], return_index=True)
        bounds = np.append(starts, len(users))
        for s, e in zip(bounds[:-1], bounds[1:]):
            rows = order[s:e].copy()
            rng.shuffle(rows)
            n_train = max(1, int(round(len(rows) * ratio)))
            in_train[rows[:n_train]] = True
        return table.filter(in_train), table.filter(~in_train)

    def _fit(self, table: Table) -> "RankingTrainValidationSplitModel":
        train, valid = self.split(table)
        evaluator = self.getEvaluator() or RankingEvaluator()
        grids = self.getEstimatorParamMaps() or [{}]
        best_metric, best_model, all_metrics = None, None, []
        for grid in grids:
            est = self.getEstimator().copy(grid) if grid else self.getEstimator()
            adapter = RankingAdapter(recommender=est, k=evaluator.getK())
            model = adapter.fit(train)
            metric = evaluator.evaluate(model.transform(valid))
            all_metrics.append(metric)
            better = (
                best_metric is None
                or (metric > best_metric) == evaluator.is_larger_better()
            )
            if better:
                best_metric, best_model = metric, model
        out = RankingTrainValidationSplitModel(
            bestModel=best_model,
            validationMetrics=all_metrics,
        )
        out.parent = self
        return out


class RankingTrainValidationSplitModel(Model):
    bestModel = Param("Best RankingAdapterModel", is_complex=True, default=None)
    validationMetrics = Param("Metric per param map", default=None)

    def transform(self, table: Table) -> Table:
        return self.getBestModel().transform(table)
