"""Recommendation (reference ``recommendation/``, SURVEY.md §2.9)."""

from mmlspark_tpu.recommendation.ranking import (
    AdvancedRankingMetrics,
    RankingAdapter,
    RankingAdapterModel,
    RankingEvaluator,
    RankingTrainValidationSplit,
    RankingTrainValidationSplitModel,
    RecommendationIndexer,
    RecommendationIndexerModel,
)
from mmlspark_tpu.recommendation.sar import SAR, SARModel

__all__ = [
    "AdvancedRankingMetrics",
    "RankingAdapter",
    "RankingAdapterModel",
    "RankingEvaluator",
    "RankingTrainValidationSplit",
    "RankingTrainValidationSplitModel",
    "RecommendationIndexer",
    "RecommendationIndexerModel",
    "SAR",
    "SARModel",
]
