"""SAR — Smart Adaptive Recommendations, TPU-native.

Reference: ``recommendation/SAR.scala:38-208`` (user-item affinity with
exponential time decay, item-item similarity with jaccard/lift/co-occurrence
measures) and ``recommendation/SARModel.scala:23-169`` (recommendForAllUsers
via block-matrix product of user affinity × item similarity).

TPU-first redesign: the reference builds both matrices with Spark
groupBy/UDF passes and multiplies distributed block matrices. Here both hot
ops are single MXU matmuls under ``jit``:

- co-occurrence ``C = Uᵀ·U`` with U the binary user×item interaction matrix,
- scoring ``S = A·sim`` (user affinity × item similarity) + ``lax.top_k``.

Sharding: both matmuls shard row-wise over the mesh "data" axis via the
standard data-parallel layout; for catalog sizes beyond one chip's HBM,
shard the item axis of ``sim`` (model axis) — the scoring contraction then
rides a ``psum`` over ICI.
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from mmlspark_tpu.core.params import Param, gt, one_of, to_float, to_int, to_str
from mmlspark_tpu.core.pipeline import Estimator, Model
from mmlspark_tpu.data.table import Table


class _SARParams:
    userCol = Param("User id column (integer ids; see RecommendationIndexer)",
                    default="user", converter=to_str)
    itemCol = Param("Item id column (integer ids)", default="item", converter=to_str)
    ratingCol = Param("Rating column (optional)", default="rating", converter=to_str)
    timeCol = Param("Event-time column (optional)", default="timestamp", converter=to_str)
    timeDecayCoeff = Param("Half-life of the affinity decay, in days",
                           default=30, converter=to_int, validator=gt(0))
    startTime = Param("Reference time (ISO string); default = max event time",
                      default=None)
    supportThreshold = Param("Min co-occurrence count for a nonzero similarity",
                             default=4, converter=to_int, validator=gt(0))
    similarityFunction = Param(
        "jaccard | lift | cooccurrence (``SAR.scala:150-207``)",
        default="jaccard",
        converter=to_str,
        validator=one_of("jaccard", "lift", "cooccurrence"),
    )


def _to_minutes(col: np.ndarray) -> np.ndarray:
    """Event times -> float minutes. Accepts numeric epoch-seconds,
    numpy datetime64, or ISO-8601 strings."""
    if col.dtype == object or col.dtype.kind == "U":
        col = np.array([np.datetime64(str(v)) for v in col])
    if np.issubdtype(col.dtype, np.datetime64):
        return col.astype("datetime64[s]").astype(np.float64) / 60.0
    return col.astype(np.float64) / 60.0


@jax.jit
def _cooccurrence(U):
    """C[i,j] = #users who interacted with both i and j — one MXU matmul."""
    return U.T @ U


class SAR(_SARParams, Estimator):
    """Fits user-affinity + item-similarity matrices from an event table."""

    def _affinities(self, table: Table, n_users: int, n_items: int) -> np.ndarray:
        """User×item affinity: sum over events of rating × 2^(-Δt/half-life)
        (``SAR.scala:84-120``). Missing rating → 1; missing time → no decay."""
        users = table.column(self.getUserCol()).astype(np.int64)
        items = table.column(self.getItemCol()).astype(np.int64)
        n = len(users)
        weights = np.ones(n, dtype=np.float64)
        if self.getRatingCol() in table:
            weights = table.column(self.getRatingCol()).astype(np.float64)
        if self.getTimeCol() in table:
            t_min = _to_minutes(table.column(self.getTimeCol()))
            start = self.getStartTime()
            ref = (
                _to_minutes(np.array([start], dtype=object))[0]
                if start is not None
                else t_min.max()
            )
            half_life_min = self.getTimeDecayCoeff() * 24.0 * 60.0
            decay = np.power(2.0, -(ref - t_min) / half_life_min)
            weights = weights * decay
        aff = np.zeros((n_users, n_items), dtype=np.float64)
        np.add.at(aff, (users, items), weights)
        return aff

    def _similarity(self, table: Table, n_users: int, n_items: int) -> np.ndarray:
        """Item×item similarity from binary distinct-user co-occurrence
        (``SAR.scala:150-207``)."""
        users = table.column(self.getUserCol()).astype(np.int64)
        items = table.column(self.getItemCol()).astype(np.int64)
        U = np.zeros((n_users, n_items), dtype=np.float32)
        U[users, items] = 1.0  # distinct users per item pair
        cooc = np.asarray(_cooccurrence(jnp.asarray(U)), dtype=np.float64)
        occ = np.diag(cooc).copy()
        fn = self.getSimilarityFunction()
        with np.errstate(invalid="ignore", divide="ignore"):
            if fn == "jaccard":
                denom = occ[:, None] + occ[None, :] - cooc
                sim = np.where(denom > 0, cooc / denom, 0.0)
            elif fn == "lift":
                denom = occ[:, None] * occ[None, :]
                sim = np.where(denom > 0, cooc / denom, 0.0)
            else:
                sim = cooc
        sim = np.where(cooc >= self.getSupportThreshold(), sim, 0.0)
        return sim

    def _fit(self, table: Table) -> "SARModel":
        users = table.column(self.getUserCol()).astype(np.int64)
        items = table.column(self.getItemCol()).astype(np.int64)
        if users.min(initial=0) < 0 or items.min(initial=0) < 0:
            raise ValueError("user/item ids must be non-negative integers")
        n_users = int(users.max()) + 1
        n_items = int(items.max()) + 1
        model = SARModel(
            userCol=self.getUserCol(),
            itemCol=self.getItemCol(),
            ratingCol=self.getRatingCol(),
            userAffinity=self._affinities(table, n_users, n_items),
            itemSimilarity=self._similarity(table, n_users, n_items),
        )
        model.parent = self
        return model


@partial(jax.jit, static_argnames=("k",))
def _score_topk(A, S, k):
    """scores = A·S (MXU), then per-user top-k."""
    return jax.lax.top_k(A @ S, k)


class SARModel(_SARParams, Model):
    """Holds the dense affinity/similarity factors
    (``SARModel.userDataFrame``/``itemDataFrame`` analogues)."""

    userAffinity = Param("User×item affinity matrix", is_complex=True, default=None)
    itemSimilarity = Param("Item×item similarity matrix", is_complex=True, default=None)

    def _recommend(self, affinity: np.ndarray, k: int) -> Tuple[np.ndarray, np.ndarray]:
        S = self.getItemSimilarity()
        k = min(k, S.shape[0])
        scores, idx = _score_topk(
            jnp.asarray(affinity, dtype=jnp.float32),
            jnp.asarray(S, dtype=jnp.float32),
            k,
        )
        return np.asarray(idx), np.asarray(scores, dtype=np.float64)

    def recommend_for_all_users(self, num_items: int) -> Table:
        """(user, recommendations=[item...], ratings=[score...])
        (``SARModel.recommendForAllUsers``, ``SARModel.scala:51``)."""
        A = self.getUserAffinity()
        idx, scores = self._recommend(A, num_items)
        return Table({
            self.getUserCol(): np.arange(A.shape[0], dtype=np.int64),
            "recommendations": idx.astype(np.int64),
            "ratings": scores,
        })

    def recommend_for_user_subset(self, table: Table, num_items: int) -> Table:
        """Top-k for the unique user ids in ``table``; ids unseen at fit time
        are dropped — the reference's left-semi join against the factor frame
        (``SARModel.recommendForUserSubset``/``getSourceFactorSubset``,
        ``SARModel.scala:65-88``)."""
        users = np.unique(table.column(self.getUserCol()).astype(np.int64))
        users = users[(users >= 0) & (users < self.getUserAffinity().shape[0])]
        A = self.getUserAffinity()[users]
        idx, scores = self._recommend(A, num_items)
        return Table({
            self.getUserCol(): users,
            "recommendations": idx.astype(np.int64),
            "ratings": scores,
        })

    def transform(self, table: Table) -> Table:
        """Scores each (user, item) row: affinity·similarity[:, item].
        Cold-start users/items unseen at fit time score 0.0."""
        users = table.column(self.getUserCol()).astype(np.int64)
        items = table.column(self.getItemCol()).astype(np.int64)
        A = self.getUserAffinity()
        S = self.getItemSimilarity()
        known = (
            (users >= 0) & (users < A.shape[0])
            & (items >= 0) & (items < S.shape[1])
        )
        u = np.where(known, users, 0)
        i = np.where(known, items, 0)
        scores = np.einsum("ij,ij->i", A[u], S[:, i].T)
        return table.with_column("prediction", np.where(known, scores, 0.0))
