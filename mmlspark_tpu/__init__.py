"""mmlspark_tpu — a TPU-native machine-learning pipeline framework.

A brand-new framework with the capabilities of MMLSpark (Microsoft Machine
Learning for Apache Spark), re-designed TPU-first on JAX/XLA/Pallas/pjit:

- Columnar :class:`~mmlspark_tpu.data.Table` replaces Spark DataFrames; columns
  live in host numpy and move to TPU HBM in large batched transfers.
- ``Estimator.fit`` / ``Transformer.transform`` / ``Pipeline`` compose exactly
  like SparkML stages (reference: ``core/contracts/Params.scala``), but all
  heavy compute is jitted XLA running on a ``jax.sharding.Mesh`` of TPU chips.
- Distributed training replaces socket/spanning-tree allreduce with
  ``lax.psum`` over the ICI mesh (reference: ``lightgbm/LightGBMUtils.scala``,
  ``vw/VowpalWabbitBase.scala``).

Subpackages mirror the reference's component inventory (SURVEY.md §2):

- ``core``      — params/pipeline contracts, serialization, schema, topology
- ``runtime``   — fault-tolerant partition scheduler (the driver/executor
  layer Spark provided: retries, heartbeats, lineage recompute)
- ``data``      — columnar Table, readers, partitioning
- ``parallel``  — mesh construction, sharding helpers, collectives, ring attention
- ``ops``       — hashing, histograms, image kernels (XLA + Pallas)
- ``lightgbm``  — histogram GBDT learners (LightGBM-on-Spark equivalent)
- ``vw``        — online linear learners (VowpalWabbit-on-Spark equivalent)
- ``nn_models`` — deep-model inference, ImageFeaturizer (CNTKModel equivalent)
- ``stages``    — generic pipeline stages
- ``featurize`` — auto-featurization, text featurization
- ``train``     — simplified train/eval API + model statistics
- ``automl``    — hyperparameter search, best-model selection
- ``knn``       — (conditional) nearest neighbors
- ``recommendation`` — SAR, ranking evaluation
- ``lime``      — model-agnostic interpretability
- ``isolationforest`` — anomaly detection
- ``io``        — HTTP-on-TPU client stack + low-latency serving
- ``streaming`` — Structured-Streaming-analogue micro-batch engine:
  offset-tracked sources, checkpointed exactly-once queries, incremental
  warm-start fit sinks feeding zero-downtime model hot swap in serving
- ``resilience`` — request-plane fault tolerance: circuit breakers,
  deadline propagation (``X-Deadline-Ms``), retry budgets, admission
  control shared by serving and every outbound HTTP caller
- ``cognitive`` — REST cognitive-service transformers
- ``downloader`` — pretrained model repository
"""

__version__ = "0.1.0"

# The runtime lock witness (MMLSPARK_TPU_LOCKCHECK=1) must wrap
# threading.Lock/RLock before any package module allocates one, so this
# hook runs ahead of every other package import. No-op unless the env
# var is set.
from mmlspark_tpu.analysis.witness import install_from_env as _install_lock_witness

_install_lock_witness()

from mmlspark_tpu.core.params import Param, Params
from mmlspark_tpu.core.pipeline import (
    Estimator,
    Evaluator,
    Model,
    Pipeline,
    PipelineModel,
    PipelineStage,
    Transformer,
)
from mmlspark_tpu.data.table import Table


def clear_compiled_caches() -> None:
    """Release every compiled-program cache the package (and JAX) holds.

    Long-lived processes that fit many differently-shaped models — test
    harnesses, notebook sessions, serving workers cycling models —
    accumulate compiled XLA executables: the boosting-step cache
    (``lightgbm.train._PROGRAM_CACHE``), module-level jitted predict
    kernels, and JAX's own pjit caches. XLA:CPU tolerates only so much of
    this in one process (an upstream compiler crash reproduces after
    several hundred accumulated compilations — see
    ``tests/conftest.py``); calling this between workloads bounds the
    footprint. Safe at any point: every cache refills on demand.
    """
    import gc

    import jax

    from mmlspark_tpu.lightgbm import train as _train

    _train._PROGRAM_CACHE.clear()
    jax.clear_caches()
    gc.collect()


__all__ = [
    "Param",
    "Params",
    "PipelineStage",
    "Transformer",
    "Estimator",
    "Model",
    "Pipeline",
    "PipelineModel",
    "Evaluator",
    "Table",
    "clear_compiled_caches",
    "__version__",
]
