"""VowpalWabbitClassifier — logistic-loss online linear classification.

Parity with ``vw/VowpalWabbitClassifier.scala`` (labels mapped to {-1, +1},
probability via sigmoid of the raw margin).
"""

from __future__ import annotations

import numpy as np

from mmlspark_tpu.core.params import Param, to_str
from mmlspark_tpu.data.table import Table
from mmlspark_tpu.vw.base import (
    VowpalWabbitBase,
    VowpalWabbitModelBase,
    VWTrainResult,
)


class VowpalWabbitClassifier(VowpalWabbitBase):
    _default_loss = "logistic"

    rawPredictionCol = Param("Raw margin output column", default="rawPrediction", converter=to_str)
    probabilityCol = Param("Probability output column", default="probability", converter=to_str)

    def _label_transform(self, y: np.ndarray) -> np.ndarray:
        # 0/1 -> -1/+1 (VW binary label convention)
        return np.where(y > 0.5, 1.0, -1.0).astype(np.float32)

    def _make_model(self, result: VWTrainResult, dim: int, const_idx: int):
        return VowpalWabbitClassificationModel(
            featuresCol=self.getFeaturesCol(),
            predictionCol=self.getPredictionCol(),
            rawPredictionCol=self.getRawPredictionCol(),
            probabilityCol=self.getProbabilityCol(),
            modelWeights=result.weights,
            sparseDim=dim,
            constantIndex=const_idx,
            trainingStats=result.stats,
        )


class VowpalWabbitClassificationModel(VowpalWabbitModelBase):
    rawPredictionCol = Param("Raw margin output column", default="rawPrediction", converter=to_str)
    probabilityCol = Param("Probability output column", default="probability", converter=to_str)

    def transform(self, table: Table) -> Table:
        m = self._margins(table)
        p1 = 1.0 / (1.0 + np.exp(-m))
        probs = np.stack([1 - p1, p1], axis=1)
        raw = np.stack([-m, m], axis=1)
        return (
            table.with_column(self.getRawPredictionCol(), raw)
            .with_column(self.getProbabilityCol(), probs)
            .with_column(self.getPredictionCol(), (m > 0).astype(np.float64))
        )
