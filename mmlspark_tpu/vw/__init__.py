"""Online linear learning, TPU-native (VowpalWabbit-on-Spark equivalent).

Same capability surface as the reference's ``vw/`` package (SURVEY.md §2.3):
hashing featurizer with namespaces and interactions, online linear learners
with adaptive updates, distributed training — but the native VW core and its
spanning-tree allreduce (``ClusterSpanningTree``) are replaced by jitted
adagrad-SGD scans per mesh shard with ``lax.pmean`` weight averaging at each
pass boundary (the ``endPass`` allreduce equivalent).
"""

from mmlspark_tpu.vw.featurizer import VowpalWabbitFeaturizer
from mmlspark_tpu.vw.interactions import VowpalWabbitInteractions
from mmlspark_tpu.vw.classifier import VowpalWabbitClassifier, VowpalWabbitClassificationModel
from mmlspark_tpu.vw.regressor import VowpalWabbitRegressor, VowpalWabbitRegressionModel

__all__ = [
    "VowpalWabbitFeaturizer",
    "VowpalWabbitInteractions",
    "VowpalWabbitClassifier",
    "VowpalWabbitClassificationModel",
    "VowpalWabbitRegressor",
    "VowpalWabbitRegressionModel",
]
