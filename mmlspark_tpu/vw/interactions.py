"""VowpalWabbitInteractions — quadratic feature crossing between namespaces.

Parity with ``vw/VowpalWabbitInteractions.scala``: given sparse feature
columns (namespaces), emit the crossed features — index = VW-style
hash-combine of the member indices, value = product of member values.

Crossing is column-vectorized like the featurizer: each input column is
duplicate-combined and zero-trimmed once (``combine_csr``), then the
a-major pair expansion for every row happens in one flat gather — pair t of
row r reads member ``t // |b_r|`` of the left namespace and ``t % |b_r|``
of the right — with no per-row Python. Output is a :class:`SparseRows` CSR
column, feature-space identical to the original per-row implementation.
"""

from __future__ import annotations

from typing import Tuple

import numpy as np

from mmlspark_tpu.core.params import (
    HasInputCols,
    HasOutputCol,
    Param,
    in_range,
    to_bool,
    to_int,
)
from mmlspark_tpu.core.pipeline import Transformer
from mmlspark_tpu.data.sparse import SparseRows, combine_csr
from mmlspark_tpu.data.table import Table

# VW's FNV-style hash-combine multiplier used when crossing namespaces.
_INTERACTION_MULT = np.uint32(0x5BD1E995)


def combine_hashes(a: np.ndarray, b: np.ndarray, num_bits: int) -> np.ndarray:
    with np.errstate(over="ignore"):
        h = (a.astype(np.uint32) * _INTERACTION_MULT) ^ b.astype(np.uint32)
        return (h & np.uint32((1 << num_bits) - 1)).astype(np.int32)


def _as_csr(col) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Any sparse column (SparseRows or legacy tuple objects) as flat CSR."""
    if isinstance(col, SparseRows):
        return col.indices.astype(np.int64), col.values, col.indptr
    idx = [np.asarray(x[0], dtype=np.int64) for x in col]
    val = [np.asarray(x[1], dtype=np.float32) for x in col]
    counts = np.fromiter(map(len, idx), dtype=np.int64, count=len(idx))
    indptr = np.zeros(len(idx) + 1, dtype=np.int64)
    np.cumsum(counts, out=indptr[1:])
    return (
        np.concatenate(idx) if idx else np.zeros(0, dtype=np.int64),
        np.concatenate(val) if val else np.zeros(0, dtype=np.float32),
        indptr,
    )


def _cross_csr(
    ai: np.ndarray, av: np.ndarray, ap: np.ndarray,
    bi: np.ndarray, bv: np.ndarray, bp: np.ndarray,
    num_bits: int,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Row-wise a-major cross product of two CSR namespaces: one gather per
    side, |a_r| * |b_r| pairs per row, index = hash-combine, value = product."""
    n = len(ap) - 1
    ca, cb = np.diff(ap), np.diff(bp)
    m = ca * cb
    optr = np.zeros(n + 1, dtype=np.int64)
    np.cumsum(m, out=optr[1:])
    M = int(optr[-1])
    rows = np.repeat(np.arange(n, dtype=np.int64), m)
    t = np.arange(M, dtype=np.int64) - optr[rows]
    cbr = cb[rows]
    a_pos = ap[rows] + t // cbr
    b_pos = bp[rows] + t % cbr
    ci = combine_hashes(ai[a_pos], bi[b_pos], num_bits).astype(np.int64)
    return ci, av[a_pos] * bv[b_pos], optr


class VowpalWabbitInteractions(HasInputCols, HasOutputCol, Transformer):
    numBits = Param("log2 feature-space size", default=18, converter=to_int, validator=in_range(1, 30))
    sumCollisions = Param("Sum values on hash collisions", default=True, converter=to_bool)

    def transform(self, table: Table) -> Table:
        cols = self.getInputCols()
        if len(cols) < 2:
            raise ValueError("interactions need at least two input columns")
        num_bits = self.getNumBits()
        dim = 1 << num_bits
        # Each input namespace is duplicate-combined (summed, as the padded
        # batches always were) and zero-trimmed BEFORE crossing; intermediate
        # cross products are never re-filtered, matching the original.
        csrs = [combine_csr(*_as_csr(table.column(c))) for c in cols]
        ci, cv, cp = csrs[0]
        ci = ci.astype(np.int64)
        for bi, bv, bp in csrs[1:]:
            ci, cv, cp = _cross_csr(ci, cv, cp, bi.astype(np.int64), bv, bp, num_bits)
        fi, fv, fp = combine_csr(ci, cv, cp, self.getSumCollisions())
        return table.with_column(
            self.getOutputCol(),
            SparseRows(fi, fv, fp, dim),
            metadata={"sparse_dim": dim},
        )
