"""VowpalWabbitInteractions — quadratic feature crossing between namespaces.

Parity with ``vw/VowpalWabbitInteractions.scala``: given sparse feature
columns (namespaces), emit the crossed features — index = VW-style
hash-combine of the member indices, value = product of member values.
"""

from __future__ import annotations

import numpy as np

from mmlspark_tpu.core.params import (
    HasInputCols,
    HasOutputCol,
    Param,
    in_range,
    to_bool,
    to_int,
)
from mmlspark_tpu.core.pipeline import Transformer
from mmlspark_tpu.data.sparse import batch_to_column, column_to_batch, from_lists
from mmlspark_tpu.data.table import Table

# VW's FNV-style hash-combine multiplier used when crossing namespaces.
_INTERACTION_MULT = np.uint32(0x5BD1E995)


def combine_hashes(a: np.ndarray, b: np.ndarray, num_bits: int) -> np.ndarray:
    with np.errstate(over="ignore"):
        h = (a.astype(np.uint32) * _INTERACTION_MULT) ^ b.astype(np.uint32)
        return (h & np.uint32((1 << num_bits) - 1)).astype(np.int32)


class VowpalWabbitInteractions(HasInputCols, HasOutputCol, Transformer):
    numBits = Param("log2 feature-space size", default=18, converter=to_int, validator=in_range(1, 30))
    sumCollisions = Param("Sum values on hash collisions", default=True, converter=to_bool)

    def transform(self, table: Table) -> Table:
        cols = self.getInputCols()
        if len(cols) < 2:
            raise ValueError("interactions need at least two input columns")
        num_bits = self.getNumBits()
        dim = 1 << num_bits
        batches = [
            column_to_batch(table.column(c), dim) for c in cols
        ]
        n = table.num_rows
        idx_lists, val_lists = [], []
        for i in range(n):
            cross_idx = batches[0].indices[i]
            cross_val = batches[0].values[i]
            keep = batches[0].values[i] != 0
            cross_idx, cross_val = cross_idx[keep], cross_val[keep]
            for b in batches[1:]:
                keep = b.values[i] != 0
                bi, bv = b.indices[i][keep], b.values[i][keep]
                ci = combine_hashes(
                    np.repeat(cross_idx, len(bi)), np.tile(bi, len(cross_idx)), num_bits
                )
                cv = (cross_val[:, None] * bv[None, :]).reshape(-1)
                cross_idx, cross_val = ci, cv
            idx_lists.append(cross_idx)
            val_lists.append(cross_val.astype(np.float32))
        batch = from_lists(idx_lists, val_lists, dim, self.getSumCollisions())
        return table.with_column(
            self.getOutputCol(), batch_to_column(batch), metadata={"sparse_dim": dim}
        )
