"""VowpalWabbitFeaturizer — typed columns → hashed sparse features.

Re-design of ``vw/VowpalWabbitFeaturizer.scala`` (+ the per-type featurizers
under ``vw/featurizer/*.scala``): numeric, boolean, string, string-array,
map, and dense-vector columns are hashed into one sparse feature space of
``2^numBits`` dims with murmur3, namespace prefix seeding, and
``sumCollisions`` semantics. Hashing runs vectorized on the host; the output
column stores (indices, values) pairs ready for padded TPU batches.
"""

from __future__ import annotations

from typing import List

import numpy as np

from mmlspark_tpu.core.params import (
    HasInputCols,
    HasOutputCol,
    Param,
    ge,
    in_range,
    to_bool,
    to_int,
)
from mmlspark_tpu.core.pipeline import Transformer
from mmlspark_tpu.data.sparse import batch_to_column, from_lists
from mmlspark_tpu.data.table import Table
from mmlspark_tpu.ops.hashing import (
    mask_bits,
    murmur32_ints,
    murmur32_strings,
    namespace_seed,
)


class VowpalWabbitFeaturizer(HasInputCols, HasOutputCol, Transformer):
    numBits = Param("log2 of feature-space size", default=18, converter=to_int, validator=in_range(1, 30))
    hashSeed = Param("Murmur hash seed", default=0, converter=to_int)
    sumCollisions = Param("Sum values on hash collisions (vs keep first)", default=True, converter=to_bool)
    stringSplit = Param("Split string columns on whitespace into tokens", default=False, converter=to_bool)
    prefixStringsWithColumnName = Param("Prefix hashed tokens with the column name", default=True, converter=to_bool)

    def transform(self, table: Table) -> Table:
        num_bits = self.getNumBits()
        seed = self.getHashSeed()
        dim = 1 << num_bits
        n = table.num_rows
        per_row_idx: List[List[np.ndarray]] = [[] for _ in range(n)]
        per_row_val: List[List[np.ndarray]] = [[] for _ in range(n)]

        for col_name in self.getInputCols():
            col = table.column(col_name)
            ns_seed = namespace_seed(col_name, seed)
            if col.dtype != object and col.ndim == 2:
                # dense vector column: feature j hashed from its index
                f = col.shape[1]
                idx = mask_bits(murmur32_ints(np.arange(f), ns_seed), num_bits)
                for i in range(n):
                    per_row_idx[i].append(idx)
                    per_row_val[i].append(col[i].astype(np.float32))
            elif col.dtype != object and col.dtype != bool:
                # numeric column: one feature named after the column
                h = mask_bits(murmur32_ints(np.zeros(1), ns_seed), num_bits)
                for i in range(n):
                    per_row_idx[i].append(h)
                    per_row_val[i].append(np.asarray([col[i]], dtype=np.float32))
            elif col.dtype == bool:
                h = mask_bits(murmur32_ints(np.zeros(1), ns_seed), num_bits)
                for i in range(n):
                    if col[i]:
                        per_row_idx[i].append(h)
                        per_row_val[i].append(np.ones(1, dtype=np.float32))
            else:
                first = next((v for v in col if v is not None), None)
                hash_cache: dict = {}  # one per column: recurring tokens hash once
                if isinstance(first, dict):
                    for i in range(n):
                        d = col[i] or {}
                        keys = list(d.keys())
                        if not keys:
                            continue
                        hs = mask_bits(
                            murmur32_strings(keys, ns_seed, hash_cache), num_bits
                        )
                        per_row_idx[i].append(hs)
                        per_row_val[i].append(
                            np.asarray([float(d[k]) for k in keys], dtype=np.float32)
                        )
                else:
                    prefix = col_name if self.getPrefixStringsWithColumnName() else ""
                    split = self.getStringSplit()
                    for i in range(n):
                        v = col[i]
                        if v is None:
                            continue
                        if isinstance(v, str):
                            tokens = v.split() if split else [v]
                        else:
                            tokens = [str(t) for t in v]
                        if not tokens:
                            continue
                        named = [prefix + t for t in tokens] if prefix else tokens
                        hs = mask_bits(
                            murmur32_strings(named, ns_seed, hash_cache), num_bits
                        )
                        per_row_idx[i].append(hs)
                        per_row_val[i].append(np.ones(len(tokens), dtype=np.float32))

        idx_lists = [
            np.concatenate(r) if r else np.zeros(0, dtype=np.int64) for r in per_row_idx
        ]
        val_lists = [
            np.concatenate(r) if r else np.zeros(0, dtype=np.float32) for r in per_row_val
        ]
        batch = from_lists(idx_lists, val_lists, dim, self.getSumCollisions())
        return table.with_column(
            self.getOutputCol(), batch_to_column(batch), metadata={"sparse_dim": dim}
        )
