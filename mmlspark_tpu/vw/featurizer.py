"""VowpalWabbitFeaturizer — typed columns → hashed sparse features.

Re-design of ``vw/VowpalWabbitFeaturizer.scala`` (+ the per-type featurizers
under ``vw/featurizer/*.scala``): numeric, boolean, string, string-array,
map, and dense-vector columns are hashed into one sparse feature space of
``2^numBits`` dims with murmur3, namespace prefix seeding, and
``sumCollisions`` semantics.

The pipeline is column-vectorized end to end (docs/vw_featurization.md):
each column is tokenized in one byte-level pass (flat token spans over a
packed utf-8 buffer), recurring tokens dedup through
``np.unique(return_inverse=True)`` so each distinct token hashes once, the
whole column hashes in ONE ``murmur32_bytes_batch`` call (native library or
vectorized numpy), and rows assemble as flat CSR — no per-token Python and
no per-row list building. The output column is a :class:`SparseRows` CSR
column ready for padded TPU batches via one scatter. Feature spaces are
bit-identical to the original per-row implementation (pinned by
``tests/fixtures/golden_matrix_vw.csv``).
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

from mmlspark_tpu.core.params import (
    HasInputCols,
    HasOutputCol,
    Param,
    ge,
    in_range,
    to_bool,
    to_int,
)
from mmlspark_tpu.core.pipeline import Transformer
from mmlspark_tpu.data.sparse import SparseRows, combine_csr
from mmlspark_tpu.data.table import Table
from mmlspark_tpu.native import murmur3_split_hash_native
from mmlspark_tpu.ops.hashing import (
    batch_hash_is_native,
    mask_bits,
    murmur32_bytes_batch,
    murmur32_ints,
    namespace_seed,
)

#: ASCII code points ``str.split()`` treats as whitespace (chr(c).isspace()),
#: as a 256-entry lookup table (one gather per buffer byte beats np.isin).
_WS_LUT = np.zeros(256, dtype=bool)
_WS_LUT[[9, 10, 11, 12, 13, 28, 29, 30, 31, 32]] = True

#: utf-8 lead bytes that can start a NON-ASCII whitespace code point
#: (U+0085/U+00A0 -> C2, U+1680 -> E1, U+2000..U+205F -> E2, U+3000 -> E3).
#: Rows containing any of these fall back to Python ``str.split`` so the
#: byte-level splitter never has to decode utf-8; everything else splits on
#: ASCII whitespace bytes, which is exact because utf-8 continuation bytes
#: are all >= 0x80.
_SUSPECT_LUT = np.zeros(256, dtype=bool)
_SUSPECT_LUT[[0xC2, 0xE1, 0xE2, 0xE3]] = True

#: dedup via the fixed-width token matrix only up to this token length —
#: beyond it the (T, L) gather outweighs re-hashing duplicates.
_DEDUP_MAX_TOKEN_BYTES = 64


def _pack_bytes(parts: List[bytes]) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Concatenate byte strings into (buf uint8, starts int64, lens int64)."""
    lens = np.fromiter(map(len, parts), dtype=np.int64, count=len(parts))
    starts = np.zeros(len(parts), dtype=np.int64)
    np.cumsum(lens[:-1], out=starts[1:])
    buf = np.frombuffer(b"".join(parts), dtype=np.uint8)
    return buf, starts, lens


def _hash_token_list(
    tokens: List[str], seed: int, prefix: bytes
) -> np.ndarray:
    """Hash a Python token list: dedup distinct tokens with
    ``np.unique(return_inverse=True)`` over a fixed-width unicode view, hash
    each distinct token once through one batch murmur call, broadcast back.
    The 'U' dtype cannot represent trailing NULs, so token lists containing
    them skip dedup and batch-hash directly (still one murmur call)."""
    if not tokens:
        return np.zeros(0, dtype=np.uint32)
    ua = np.asarray(tokens, dtype=str)
    actual = np.fromiter(map(len, tokens), dtype=np.int64, count=len(tokens))
    if bool((np.char.str_len(ua) == actual).all()):
        uniq, inv = np.unique(ua, return_inverse=True)
        buf, starts, lens = _pack_bytes([s.encode("utf-8") for s in uniq.tolist()])
        return murmur32_bytes_batch(buf, starts, lens, seed, prefix)[inv]
    buf, starts, lens = _pack_bytes([t.encode("utf-8") for t in tokens])
    return murmur32_bytes_batch(buf, starts, lens, seed, prefix)


def _split_spans(
    buf: np.ndarray, row_starts: np.ndarray, row_lens: np.ndarray
) -> Tuple[np.ndarray, np.ndarray]:
    """Whitespace-split every row of a packed byte buffer in one pass.
    Returns (token starts, token lens), tokens ordered row-major. Rows are
    independent: boundaries act as whitespace. Per-token row ids are NOT
    produced here — callers that need them derive them lazily (per-row
    counts only need an n-sized searchsorted over the row boundaries)."""
    if buf.size == 0:
        z = np.zeros(0, dtype=np.int64)
        return z, z
    ws = _WS_LUT[buf]
    prev_ws = np.empty_like(ws)
    prev_ws[0] = True
    prev_ws[1:] = ws[:-1]
    next_ws = np.empty_like(ws)
    next_ws[-1] = True
    next_ws[:-1] = ws[1:]
    nonempty = row_lens > 0
    prev_ws[row_starts[nonempty]] = True
    next_ws[(row_starts + row_lens - 1)[nonempty]] = True
    tok_starts = np.flatnonzero(~ws & prev_ws)
    tok_ends = np.flatnonzero(~ws & next_ws)
    return tok_starts, tok_ends - tok_starts + 1


def _hash_spans(
    buf: np.ndarray,
    starts: np.ndarray,
    lens: np.ndarray,
    seed: int,
    prefix: bytes,
) -> np.ndarray:
    """Hash token spans over a shared buffer. With the native library loaded,
    the whole span list goes to C directly — one call hashes millions of
    tokens faster than any host-side dedup could sort them. On the numpy
    fallback, where per-token block mixing is the dominant cost, distinct
    (bytes, length) keys are found first via
    ``np.unique(return_inverse=True)`` — over a packed uint64 key for short
    tokens, a fixed-width void view otherwise — so recurring tokens hash
    once."""
    T = len(starts)
    if T == 0:
        return np.zeros(0, dtype=np.uint32)
    if batch_hash_is_native():
        return murmur32_bytes_batch(buf, starts, lens, seed, prefix)
    L = int(lens.max())
    if 0 < L <= 6 and T > 256:
        # token bytes + length packed into one uint64 (length rides in the
        # top byte so "a" and "a\x00" stay distinct) — integer unique sorts
        # radix-fast, unlike void comparisons
        pos = starts[:, None] + np.arange(L, dtype=np.int64)
        mat = buf[np.minimum(pos, buf.size - 1)].astype(np.uint64)
        mat[np.arange(L)[None, :] >= lens[:, None]] = 0
        key = (lens.astype(np.uint64) << np.uint64(56))
        for j in range(L):
            key |= mat[:, j] << np.uint64(8 * j)
        _, uidx, inv = np.unique(key, return_index=True, return_inverse=True)
        return murmur32_bytes_batch(buf, starts[uidx], lens[uidx], seed, prefix)[inv]
    if 0 < L <= _DEDUP_MAX_TOKEN_BYTES and T > 256:
        pos = starts[:, None] + np.arange(L, dtype=np.int64)
        mat = buf[np.minimum(pos, buf.size - 1)]
        mat[np.arange(L)[None, :] >= lens[:, None]] = 0
        key = np.zeros((T, L + 2), dtype=np.uint8)
        key[:, :L] = mat
        key[:, L] = lens & 0xFF
        key[:, L + 1] = (lens >> 8) & 0xFF
        void = np.ascontiguousarray(key).view(np.dtype((np.void, L + 2))).ravel()
        _, uidx, inv = np.unique(void, return_index=True, return_inverse=True)
        return murmur32_bytes_batch(buf, starts[uidx], lens[uidx], seed, prefix)[inv]
    return murmur32_bytes_batch(buf, starts, lens, seed, prefix)


class VowpalWabbitFeaturizer(HasInputCols, HasOutputCol, Transformer):
    numBits = Param("log2 of feature-space size", default=18, converter=to_int, validator=in_range(1, 30))
    hashSeed = Param("Murmur hash seed", default=0, converter=to_int)
    sumCollisions = Param("Sum values on hash collisions (vs keep first)", default=True, converter=to_bool)
    stringSplit = Param("Split string columns on whitespace into tokens", default=False, converter=to_bool)
    prefixStringsWithColumnName = Param("Prefix hashed tokens with the column name", default=True, converter=to_bool)

    def _string_column(
        self, col: np.ndarray, ns_seed: int, num_bits: int, prefix: bytes
    ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """String / string-array column -> (indices, ones, per-row counts).
        Plain-string rows split byte-level in one pass; rows that might
        contain non-ASCII whitespace, unsplit strings, and sequence rows go
        through a per-row token stream (still hashed in one batch call)."""
        n = len(col)
        split = self.getStringSplit()
        counts_p = np.zeros(n, dtype=np.int64)
        enc: List[bytes] = []
        enc_rows: Optional[List[int]] = []
        py_specs: List[Tuple[int, object]] = []  # (row, value) for Python path
        if split:
            try:
                # all-plain-str fast path: one comprehension, no per-row
                # type dispatch (None/sequence rows raise AttributeError)
                enc = [v.encode("utf-8") for v in col]
                enc_rows = None  # identity: enc index == table row
            except AttributeError:
                enc = []
        if enc_rows is not None and not enc:
            for i in range(n):
                v = col[i]
                if v is None:
                    continue
                if isinstance(v, str):
                    if split:
                        enc.append(v.encode("utf-8"))
                        enc_rows.append(i)
                    else:
                        py_specs.append((i, (v,)))  # whole string, even ""
                else:
                    toks = tuple(str(t) for t in v)
                    if toks:
                        py_specs.append((i, toks))

        counts_b = np.zeros(n, dtype=np.int64)
        hb = np.zeros(0, dtype=np.int32)
        trow_b = np.zeros(0, dtype=np.int64)
        tok_enc: Optional[np.ndarray] = None
        counts_enc = np.zeros(0, dtype=np.int64)
        if enc:
            buf, row_starts, row_lens = _pack_bytes(enc)
            fused = murmur3_split_hash_native(
                buf, row_starts, row_lens, ns_seed, prefix
            )
            if fused is not None:
                # one C pass: split + suspect detection + prefix-seeded hash
                hashes, counts_enc, sus_flags = fused
                sus_rows = np.flatnonzero(sus_flags)
            else:
                # numpy path: rows whose bytes could start a non-ASCII
                # whitespace char fall back to Python str.split for exactness
                suspect = _SUSPECT_LUT[buf]
                sus = np.zeros(len(enc), dtype=np.int64)
                if suspect.any():
                    byte_row = np.repeat(np.arange(len(enc), dtype=np.int64), row_lens)
                    sus = np.bincount(byte_row[suspect], minlength=len(enc))
                sus_rows = np.flatnonzero(sus)
                tok_starts, tok_lens = _split_spans(buf, row_starts, row_lens)
                if len(sus_rows):
                    tok_enc = np.searchsorted(row_starts, tok_starts, side="right") - 1
                    keep = sus[tok_enc] == 0
                    tok_starts, tok_lens, tok_enc = (
                        tok_starts[keep], tok_lens[keep], tok_enc[keep]
                    )
                    counts_enc = np.bincount(tok_enc, minlength=len(enc)).astype(np.int64)
                else:
                    # per-enc-row token counts without a per-token
                    # searchsorted: token starts are sorted, so each row's
                    # first token index is an n-sized binary search over the
                    # row boundaries
                    first = np.searchsorted(tok_starts, row_starts)
                    counts_enc = np.diff(np.append(first, len(tok_starts)))
                hashes = _hash_spans(buf, tok_starts, tok_lens, ns_seed, prefix)
            for j in sus_rows:
                row = int(enc_rows[j]) if enc_rows is not None else int(j)
                toks = tuple(col[row].split())
                if toks:
                    py_specs.append((row, toks))
            if enc_rows is None:
                counts_b = counts_enc
            else:
                counts_b = np.zeros(n, dtype=np.int64)
                counts_b[np.asarray(enc_rows, dtype=np.int64)] = counts_enc
            hb = mask_bits(hashes, num_bits)

        py_specs.sort(key=lambda s: s[0])
        py_tokens: List[str] = []
        for i, toks in py_specs:
            py_tokens.extend(toks)
            counts_p[i] = len(toks)
        if not py_tokens:
            # byte stream only — already row-major, nothing to interleave
            return hb, np.ones(len(hb), dtype=np.float32), counts_b
        if len(hb):
            if tok_enc is None:
                tok_enc = np.repeat(
                    np.arange(len(enc), dtype=np.int64), counts_enc
                )
            trow_b = (
                tok_enc
                if enc_rows is None
                else np.asarray(enc_rows, dtype=np.int64)[tok_enc]
            )
        hp = mask_bits(_hash_token_list(py_tokens, ns_seed, prefix), num_bits)

        # merge the two streams row-major (each row belongs to exactly one)
        counts = counts_b + counts_p
        indptr = np.zeros(n + 1, dtype=np.int64)
        np.cumsum(counts, out=indptr[1:])
        out = np.empty(int(indptr[-1]), dtype=np.int64)
        if len(hb):
            bptr = np.zeros(n + 1, dtype=np.int64)
            np.cumsum(counts_b, out=bptr[1:])
            rank = np.arange(len(hb), dtype=np.int64) - bptr[trow_b]
            out[indptr[trow_b] + rank] = hb
        if len(hp):
            prow = np.repeat(np.arange(n, dtype=np.int64), counts_p)
            pptr = np.zeros(n + 1, dtype=np.int64)
            np.cumsum(counts_p, out=pptr[1:])
            rank = np.arange(len(hp), dtype=np.int64) - pptr[prow]
            out[indptr[prow] + rank] = hp
        return out, np.ones(len(out), dtype=np.float32), counts

    def _map_column(
        self, col: np.ndarray, ns_seed: int, num_bits: int
    ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Map column: keys hash (dict order, no prefix), values pass through."""
        n = len(col)
        counts = np.zeros(n, dtype=np.int64)
        keys: List[str] = []
        vals: List[float] = []
        for i in range(n):
            d = col[i] or {}
            if not d:
                continue
            counts[i] = len(d)
            keys.extend(str(k) for k in d.keys())
            vals.extend(float(x) for x in d.values())
        idx = mask_bits(_hash_token_list(keys, ns_seed, b""), num_bits).astype(np.int64)
        return idx, np.asarray(vals, dtype=np.float32), counts

    def transform(self, table: Table) -> Table:
        num_bits = self.getNumBits()
        seed = self.getHashSeed()
        dim = 1 << num_bits
        n = table.num_rows

        # (indices int64, values f32, per-row counts int64) per input column
        parts: List[Tuple[np.ndarray, np.ndarray, np.ndarray]] = []
        for col_name in self.getInputCols():
            col = table.column(col_name)
            ns_seed = namespace_seed(col_name, seed)
            if col.dtype != object and col.ndim == 2:
                # dense vector column: feature j hashed from its index
                f = col.shape[1]
                idx = mask_bits(murmur32_ints(np.arange(f), ns_seed), num_bits)
                parts.append(
                    (
                        np.tile(idx.astype(np.int64), n),
                        np.ascontiguousarray(col, dtype=np.float32).reshape(-1),
                        np.full(n, f, dtype=np.int64),
                    )
                )
            elif col.dtype != object and col.dtype != bool:
                # numeric column: one feature named after the column
                h = int(mask_bits(murmur32_ints(np.zeros(1, dtype=np.uint32), ns_seed), num_bits)[0])
                parts.append(
                    (
                        np.full(n, h, dtype=np.int64),
                        col.astype(np.float32),
                        np.ones(n, dtype=np.int64),
                    )
                )
            elif col.dtype == bool:
                h = int(mask_bits(murmur32_ints(np.zeros(1, dtype=np.uint32), ns_seed), num_bits)[0])
                truthy = col.astype(np.int64)
                parts.append(
                    (
                        np.full(int(truthy.sum()), h, dtype=np.int64),
                        np.ones(int(truthy.sum()), dtype=np.float32),
                        truthy,
                    )
                )
            else:
                first = next((v for v in col if v is not None), None)
                if isinstance(first, dict):
                    parts.append(self._map_column(col, ns_seed, num_bits))
                else:
                    prefix = (
                        col_name.encode("utf-8")
                        if self.getPrefixStringsWithColumnName()
                        else b""
                    )
                    parts.append(
                        self._string_column(col, ns_seed, num_bits, prefix)
                    )

        # row-major merge of per-column CSR streams, columns in input order
        if len(parts) == 1:
            # single column: its stream IS already row-major
            cidx, cval, ccounts = parts[0]
            indptr = np.zeros(n + 1, dtype=np.int64)
            np.cumsum(ccounts, out=indptr[1:])
            flat_idx, flat_val = np.asarray(cidx), cval
        else:
            total = np.zeros(n, dtype=np.int64)
            for _, _, c in parts:
                total += c
            indptr = np.zeros(n + 1, dtype=np.int64)
            np.cumsum(total, out=indptr[1:])
            flat_idx = np.empty(int(indptr[-1]), dtype=np.int64)
            flat_val = np.empty(int(indptr[-1]), dtype=np.float32)
            prev = np.zeros(n, dtype=np.int64)
            for cidx, cval, ccounts in parts:
                if len(cidx):
                    rows_c = np.repeat(np.arange(n, dtype=np.int64), ccounts)
                    cptr = np.zeros(n + 1, dtype=np.int64)
                    np.cumsum(ccounts, out=cptr[1:])
                    dest = indptr[rows_c] + prev[rows_c] + (
                        np.arange(len(cidx), dtype=np.int64) - cptr[rows_c]
                    )
                    flat_idx[dest] = cidx
                    flat_val[dest] = cval
                prev += ccounts

        ci, cv, cp = combine_csr(flat_idx, flat_val, indptr, self.getSumCollisions())
        return table.with_column(
            self.getOutputCol(),
            SparseRows(ci, cv, cp, dim),
            metadata={"sparse_dim": dim},
        )
