"""Shared VW learner machinery: params + the jitted adagrad-SGD train loop.

Re-design of ``vw/VowpalWabbitBase.scala:238-442``: the native
``VowpalWabbitNative.learn()`` per-example hot loop becomes a ``lax.scan``
over padded minibatches (gather weights → margin → loss gradient →
scatter-add adagrad update), and the spanning-tree allreduce
(``trainInternalDistributed`` ``:337-365``) becomes ``lax.pmean`` weight
averaging at each pass boundary inside one ``shard_map`` over the mesh
``data`` axis — VW's ``endPass`` synchronization, ICI-native.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Optional, Tuple

import numpy as np

from mmlspark_tpu.core.params import (
    HasFeaturesCol,
    HasLabelCol,
    HasPredictionCol,
    HasWeightCol,
    Param,
    Params,
    ge,
    gt,
    in_range,
    to_bool,
    to_float,
    to_int,
    to_str,
)
from mmlspark_tpu.core.pipeline import Estimator, Model
from mmlspark_tpu.core.utils import StopWatch
from mmlspark_tpu.data.sparse import SparseBatch, column_to_batch, dense_to_batch
from mmlspark_tpu.data.table import Table
from mmlspark_tpu.ops.hashing import mask_bits, murmur32_bytes
from mmlspark_tpu.ops.shmap import shard_map

#: VW's implicit constant (bias) feature, hashed from the literal "Constant".
CONSTANT_FEATURE = b"Constant"


def _loss_grad(loss: str, margin, y, quantile_tau: float):
    """d loss / d margin. Labels: classifier y in {-1, +1}; regressor real."""
    import jax
    import jax.numpy as jnp

    if loss == "logistic":
        return -y * jax.nn.sigmoid(-y * margin)
    if loss == "squared":
        return margin - y
    if loss == "hinge":
        return jnp.where(y * margin < 1.0, -y, 0.0)
    if loss == "quantile":
        return jnp.where(margin > y, 1.0 - quantile_tau, -quantile_tau)
    raise ValueError(f"unknown loss {loss!r}")


@dataclasses.dataclass
class VWTrainResult:
    weights: np.ndarray
    stats: dict


class VowpalWabbitBaseParams(
    HasFeaturesCol, HasLabelCol, HasPredictionCol, HasWeightCol, Params
):
    numPasses = Param("Training passes over the data", default=1, converter=to_int, validator=gt(0))
    learningRate = Param("Base learning rate", default=0.5, converter=to_float, validator=gt(0))
    powerT = Param("Learning-rate decay exponent", default=0.5, converter=to_float, validator=ge(0))
    l1 = Param("L1 regularization (lazy, applied at pass end)", default=0.0, converter=to_float, validator=ge(0))
    l2 = Param("L2 regularization", default=0.0, converter=to_float, validator=ge(0))
    numBits = Param("log2 feature-space size (when features are dense)", default=18, converter=to_int, validator=in_range(1, 30))
    batchSize = Param("Rows per SGD minibatch", default=64, converter=to_int, validator=gt(0))
    hashSeed = Param("Hash seed for the constant feature", default=0, converter=to_int)
    passThroughArgs = Param("VW-style CLI arg string (parsed for known flags)", default="", converter=to_str)
    useBarrierExecutionMode = Param("Accepted for API parity (SPMD is always synchronous)", default=True, converter=to_bool)
    initialModel = Param("Warm-start weights", is_complex=True)
    interactions = Param("Namespace interaction pairs (handled by VowpalWabbitInteractions)", default=[], is_complex=False)

    # flag -> (out key, converter); None converter = boolean switch
    _ARG_SPEC = {
        "--loss_function": ("loss", str),
        "--learning_rate": ("learning_rate", float),
        "-l": ("learning_rate", float),
        "--passes": ("passes", int),
        "--l1": ("l1", float),
        "--l2": ("l2", float),
        "--power_t": ("power_t", float),
        "-b": ("num_bits", int),
        "--bit_precision": ("num_bits", int),
        "--quantile_tau": ("quantile_tau", float),
        "--ftrl": ("ftrl", None),
        "--ftrl_alpha": ("ftrl_alpha", float),
        "--ftrl_beta": ("ftrl_beta", float),
        "--link": ("link", str),
        "--noconstant": ("noconstant", None),
        # NOTE: hashing happens in the (separate) VowpalWabbitFeaturizer
        # stage in this runtime, so --hash_seed here governs LEARNER-side
        # hashing only (the constant feature / un-featurized spaces). To
        # move the whole feature space, set hashSeed on the featurizer —
        # unlike native VW, where the learner owns all hashing.
        "--hash_seed": ("hash_seed", int),
    }

    #: Diagnostic / IO flags that do not change the trained model: accepted
    #: for pipeline compatibility (the reference forwards them to native VW
    #: where they are no-ops for training math) and skipped with a warning.
    #: Maps flag -> True if it consumes a value token.
    _NOOP_ARGS = {
        "--quiet": False,
        "--no_stdin": False,
        "--holdout_off": False,
        "-p": True,
        "--predictions": True,
        "--progress": True,
        "-P": True,
        "--cache": False,
        "-c": False,
        "--cache_file": True,
        "-k": False,
        "--kill_cache": False,
        "--save_resume": False,
        "--preserve_performance_counters": False,
        "--readable_model": True,
        "--invert_hash": True,
        "--audit": False,
        "-a": False,
    }

    def _parse_args(self) -> dict:
        """Parse the VW CLI flags this runtime implements
        (``appendParamIfNotThere`` analogue, VowpalWabbitBase.scala:140-159).
        Unknown MODEL-CHANGING flags RAISE: the reference hands the full
        string to native VW where every reduction works — silently dropping
        one here would train a different model than the user asked for.
        Known diagnostic/IO flags (``_NOOP_ARGS``) are skipped with a
        warning so existing pipelines that pass e.g. ``--quiet`` keep
        working."""
        from mmlspark_tpu.core.profiling import get_logger

        out = {}
        toks = self.getPassThroughArgs().split()
        i = 0
        while i < len(toks):
            t = toks[i]
            inline = None
            if t.startswith("--") and "=" in t:
                t, _, inline = t.partition("=")
            if t in self._NOOP_ARGS:
                get_logger("mmlspark_tpu.vw").warning(
                    "passThroughArgs: ignoring diagnostic VW flag %r "
                    "(no effect on the trained model in this runtime)", t
                )
                i += 1 + (1 if self._NOOP_ARGS[t] and inline is None else 0)
                continue
            if t not in self._ARG_SPEC:
                raise ValueError(
                    f"passThroughArgs: unsupported VW flag {t!r}. This "
                    "runtime implements: "
                    + " ".join(sorted(self._ARG_SPEC))
                    + ". Other VW reductions/flags are not silently ignored "
                    "— they would change the trained model."
                )
            key, conv = self._ARG_SPEC[t]
            if conv is None:  # boolean switch
                if inline is not None:
                    raise ValueError(f"passThroughArgs flag {t!r} takes no value")
                out[key] = True
                i += 1
                continue
            if inline is None:
                if i + 1 >= len(toks):
                    raise ValueError(f"passThroughArgs flag {t!r} expects a value")
                inline = toks[i + 1]
                i += 2
            else:
                i += 1
            out[key] = conv(inline)
        if out.get("link") not in (None, "identity", "logistic"):
            raise ValueError(
                f"--link {out['link']!r} not supported (identity | logistic)"
            )
        return out


class VowpalWabbitBase(VowpalWabbitBaseParams, Estimator):
    _default_loss = "squared"

    def _label_transform(self, y: np.ndarray) -> np.ndarray:
        return y.astype(np.float32)

    def _get_batch(self, table: Table, num_bits=None) -> Tuple[SparseBatch, bool]:
        """Returns (batch, is_hashed_space). ``num_bits`` overrides the
        param (the ``-b``/``--bit_precision`` pass-through flag); a
        pre-featurized column's ``sparse_dim`` metadata wins over both
        (the space was fixed upstream by VowpalWabbitFeaturizer)."""
        col = table.column(self.getFeaturesCol())
        if col.dtype == object:
            dim = table.metadata(self.getFeaturesCol()).get("sparse_dim")
            if dim is None:
                dim = 1 << (num_bits or self.getNumBits())
            return column_to_batch(col, dim), True
        # dense vector column: positions are the features; slot f is the bias
        dense = np.asarray(col, dtype=np.float32)
        return dense_to_batch(dense, dense.shape[1] + 1), False

    def _train_setup(self, table: Table):
        """Everything ``_fit`` resolves BEFORE the numeric train loop:
        (args, batch, y, w, const_idx, init). Factored so the many-models
        plane (``sweep/batched.py``) can prepare rows once per bucket and
        route K candidates through :func:`train_linear_many` while this
        estimator's single-fit path stays the reference semantics."""
        args = self._parse_args()
        batch, is_hashed = self._get_batch(table, num_bits=args.get("num_bits"))
        y = self._label_transform(
            np.asarray(table.column(self.getLabelCol()), dtype=np.float64)
        )
        w = (
            np.asarray(table.column(self.getWeightCol()), dtype=np.float32)
            if self.isSet("weightCol")
            else np.ones(batch.num_rows, dtype=np.float32)
        )
        hash_seed = args.get("hash_seed", self.getHashSeed())
        if args.get("noconstant"):
            const_idx = -1  # --noconstant: no bias feature anywhere
        elif is_hashed:
            # hashed feature space: the constant feature hashes like any other
            const_idx = int(
                mask_bits(
                    np.asarray([murmur32_bytes(CONSTANT_FEATURE, hash_seed)]),
                    int(np.log2(batch.dim)),
                )[0]
            )
        else:
            # dense feature space: the reserved last slot is the bias
            const_idx = batch.dim - 1

        init = None
        if self.isSet("initialModel"):
            init = np.asarray(self.getInitialModel(), dtype=np.float32)
        return args, batch, y, w, const_idx, init

    def _fit(self, table: Table) -> "VowpalWabbitModelBase":
        args, batch, y, w, const_idx, init = self._train_setup(table)

        result = train_linear(
            batch,
            y,
            w,
            loss=args.get("loss", self._default_loss),
            num_passes=args.get("passes", self.getNumPasses()),
            learning_rate=args.get("learning_rate", self.getLearningRate()),
            power_t=args.get("power_t", self.getPowerT()),
            l1=args.get("l1", self.getL1()),
            l2=args.get("l2", self.getL2()),
            batch_size=self.getBatchSize(),
            constant_index=const_idx,
            initial_weights=init,
            quantile_tau=args.get("quantile_tau", 0.5),
            optimizer="ftrl" if args.get("ftrl") else "adagrad",
            ftrl_alpha=args.get("ftrl_alpha", 0.005),
            ftrl_beta=args.get("ftrl_beta", 0.1),
            mesh=self._select_mesh(),
        )
        self._link = args.get("link", "identity")
        model = self._make_model(result, batch.dim, const_idx)
        model.set("linkFunction", self._link)
        model.parent = self
        return model

    def _select_mesh(self):
        import jax

        if len(jax.devices()) <= 1:
            return None
        from mmlspark_tpu.parallel.mesh import best_mesh

        return best_mesh()

    def _make_model(self, result: VWTrainResult, dim: int, const_idx: int):
        raise NotImplementedError


def _prep_rows(
    batch: SparseBatch,
    y: np.ndarray,
    sample_weight: np.ndarray,
    constant_index: int,
    batch_size: int,
    n_shards: int,
):
    """Row layout shared by the single-fit and many-models paths: append
    the constant feature, pad rows to ``n_shards * num_batches *
    batch_size``. Padding rides with zero value/weight so it never moves
    the weights. Returns (idx, val, y, sample_weight, k, num_batches)."""
    n, k = batch.indices.shape

    if constant_index >= 0:
        # append the constant feature to every row
        idx = np.concatenate(
            [batch.indices, np.full((n, 1), constant_index, dtype=np.int32)], axis=1
        )
        val = np.concatenate([batch.values, np.ones((n, 1), dtype=np.float32)], axis=1)
        k += 1
    else:
        idx, val = batch.indices, batch.values

    rows_per_shard = -(-n // n_shards)  # ceil
    num_batches = -(-rows_per_shard // batch_size)
    padded = n_shards * num_batches * batch_size
    pad = padded - n
    if pad:
        idx = np.concatenate([idx, np.zeros((pad, k), dtype=np.int32)])
        val = np.concatenate([val, np.zeros((pad, k), dtype=np.float32)])
        y = np.concatenate([y.astype(np.float32), np.zeros(pad, dtype=np.float32)])
        sample_weight = np.concatenate(
            [sample_weight, np.zeros(pad, dtype=np.float32)]
        )
    else:
        y = y.astype(np.float32)
    return idx, val, y, sample_weight, k, num_batches


def train_linear(
    batch: SparseBatch,
    y: np.ndarray,
    sample_weight: np.ndarray,
    *,
    loss: str,
    num_passes: int,
    learning_rate: float,
    power_t: float,
    l1: float,
    l2: float,
    batch_size: int,
    constant_index: int,
    initial_weights: Optional[np.ndarray] = None,
    quantile_tau: float = 0.5,
    optimizer: str = "adagrad",
    ftrl_alpha: float = 0.005,
    ftrl_beta: float = 0.1,
    mesh: Optional[Any] = None,
) -> VWTrainResult:
    """Adagrad SGD (or FTRL-Proximal, VW ``--ftrl``) over padded
    minibatches; per-pass pmean state averaging across mesh shards (VW
    endPass allreduce). ``constant_index < 0`` = ``--noconstant``."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    sw = StopWatch()
    dim = batch.dim
    n = batch.num_rows

    n_shards = int(mesh.shape["data"]) if mesh is not None else 1
    idx, val, y, sample_weight, k, num_batches = _prep_rows(
        batch, y, sample_weight, constant_index, batch_size, n_shards
    )

    w0 = (
        initial_weights.copy()
        if initial_weights is not None
        else np.zeros(dim, dtype=np.float32)
    )

    lr = float(learning_rate)

    def run_pass(weights, acc, bidx, bval, by, bw, t0):
        """One pass over this shard's minibatches. Shapes:
        bidx/bval (num_batches, B, K); by/bw (num_batches, B)."""

        def step(carry, xs):
            weights, acc, t = carry
            bi, bv, yy, ww = xs
            wi = weights[bi]  # (B, K) gather
            margin = jnp.sum(wi * bv, axis=1)
            g_row = _loss_grad(loss, margin, yy, quantile_tau) * ww
            g = g_row[:, None] * bv  # (B, K)
            if l2:
                g = g + l2 * wi * (bv != 0)
            flat_i = bi.reshape(-1)
            flat_g = g.reshape(-1)
            acc = acc.at[flat_i].add(flat_g * flat_g)
            denom = jnp.sqrt(acc[flat_i]) + 1e-6
            step_t = lr if power_t == 0.0 else lr / ((1.0 + t) ** power_t)
            weights = weights.at[flat_i].add(-step_t * flat_g / denom)
            return (weights, acc, t + 1.0), None

        (weights, acc, t0), _ = jax.lax.scan(
            step, (weights, acc, t0), (bidx, bval, by, bw)
        )
        return weights, acc, t0

    def ftrl_w(z, nacc):
        """FTRL-Proximal closed-form weights from the (z, n) accumulators."""
        w = -(z - jnp.sign(z) * l1) / (
            (ftrl_beta + jnp.sqrt(nacc)) / ftrl_alpha + l2
        )
        return jnp.where(jnp.abs(z) > l1, w, 0.0)

    def run_pass_ftrl(z, nacc, bidx, bval, by, bw, t0):
        """FTRL-Proximal (VW --ftrl; McMahan et al.): per-coordinate (z, n)
        state, weights materialized lazily on the touched coordinates."""

        def step(carry, xs):
            z, nacc, t = carry
            bi, bv, yy, ww = xs
            zi, ni = z[bi], nacc[bi]  # (B, K) gathers
            wi = ftrl_w(zi, ni)
            margin = jnp.sum(wi * bv, axis=1)
            g = (_loss_grad(loss, margin, yy, quantile_tau) * ww)[:, None] * bv
            sigma = (jnp.sqrt(ni + g * g) - jnp.sqrt(ni)) / ftrl_alpha
            flat_i = bi.reshape(-1)
            z = z.at[flat_i].add((g - sigma * wi).reshape(-1))
            nacc = nacc.at[flat_i].add((g * g).reshape(-1))
            return (z, nacc, t + 1.0), None

        (z, nacc, t0), _ = jax.lax.scan(step, (z, nacc, t0), (bidx, bval, by, bw))
        return z, nacc, t0

    def fit_fn(idx_s, val_s, y_s, w_s, weights, acc):
        # idx_s etc are this shard's rows: (num_batches*B, K)
        bidx = idx_s.reshape(num_batches, batch_size, k)
        bval = val_s.reshape(num_batches, batch_size, k)
        by = y_s.reshape(num_batches, batch_size)
        bw = w_s.reshape(num_batches, batch_size)
        t = jnp.zeros(())
        if optimizer == "ftrl":
            # warm start: invert the closed form at n=0 (ignoring l1)
            z = -weights * (ftrl_beta / ftrl_alpha + l2)
            nacc = acc
            for _ in range(num_passes):
                z, nacc, t = run_pass_ftrl(z, nacc, bidx, bval, by, bw, t)
                if mesh is not None:
                    z = jax.lax.pmean(z, "data")
                    nacc = jax.lax.pmean(nacc, "data")
            # l1 lives inside the closed form — no extra lazy shrink
            return ftrl_w(z, nacc), nacc
        for _ in range(num_passes):
            weights, acc, t = run_pass(weights, acc, bidx, bval, by, bw, t)
            if mesh is not None:
                weights = jax.lax.pmean(weights, "data")
                acc = jax.lax.pmean(acc, "data")
        if l1:
            weights = jnp.sign(weights) * jnp.maximum(jnp.abs(weights) - l1, 0.0)
        return weights, acc

    with sw.measure():
        if mesh is None:
            fitted, _ = jax.jit(fit_fn)(
                jnp.asarray(idx), jnp.asarray(val), jnp.asarray(y),
                jnp.asarray(sample_weight), jnp.asarray(w0),
                jnp.zeros(dim, dtype=jnp.float32),
            )
        else:
            shard = shard_map(
                fit_fn,
                mesh=mesh,
                in_specs=(P("data"), P("data"), P("data"), P("data"), P(), P()),
                out_specs=(P(), P()),
                check_vma=False,
            )
            fitted, _ = jax.jit(shard)(
                jnp.asarray(idx), jnp.asarray(val), jnp.asarray(y),
                jnp.asarray(sample_weight), jnp.asarray(w0),
                jnp.zeros(dim, dtype=jnp.float32),
            )
        fitted = np.asarray(jax.block_until_ready(fitted))

    stats = {
        "rows": int(n),
        "passes": int(num_passes),
        "learn_time_s": sw.elapsed_s,
        "shards": n_shards,
        "ipass_loss": None,
    }
    return VWTrainResult(weights=fitted, stats=stats)


#: compiled many-models fit programs, keyed on the trace-shaping statics
#: (everything else — shapes, lr/power_t/l1/l2 — is traced data)
_MANY_FIT_CACHE: dict = {}


def _make_fit_many(loss, num_passes, optimizer, quantile_tau, ftrl_alpha,
                   ftrl_beta):
    """The vmapped VW fit: one candidate's whole SGD run as a function of
    TRACED (lr, power_t, l1, l2) scalars, vmapped over a leading candidate
    axis. The minibatch stream (bidx/bval/by/bw) is shared across
    candidates (in_axes=None — one device copy). The regularization terms
    are applied UNCONDITIONALLY (the sequential path branches on Python
    truthiness): at 0.0 each form is the exact identity — ``g + 0*...``,
    ``lr/(1+t)**0 == lr``, ``sign(w)*max(|w|-0, 0) == w`` — so a batched
    candidate matches its :func:`train_linear` fit."""
    import jax
    import jax.numpy as jnp

    def fit_one(bidx, bval, by, bw, weights, acc, lr, power_t, l1, l2):
        def step(carry, xs):
            weights, acc, t = carry
            bi, bv, yy, ww = xs
            wi = weights[bi]  # (B, K) gather
            margin = jnp.sum(wi * bv, axis=1)
            g_row = _loss_grad(loss, margin, yy, quantile_tau) * ww
            g = g_row[:, None] * bv  # (B, K)
            g = g + l2 * wi * (bv != 0)
            flat_i = bi.reshape(-1)
            flat_g = g.reshape(-1)
            acc = acc.at[flat_i].add(flat_g * flat_g)
            denom = jnp.sqrt(acc[flat_i]) + 1e-6
            step_t = lr / ((1.0 + t) ** power_t)
            weights = weights.at[flat_i].add(-step_t * flat_g / denom)
            return (weights, acc, t + 1.0), None

        def ftrl_w(z, nacc):
            w = -(z - jnp.sign(z) * l1) / (
                (ftrl_beta + jnp.sqrt(nacc)) / ftrl_alpha + l2
            )
            return jnp.where(jnp.abs(z) > l1, w, 0.0)

        def step_ftrl(carry, xs):
            z, nacc, t = carry
            bi, bv, yy, ww = xs
            zi, ni = z[bi], nacc[bi]
            wi = ftrl_w(zi, ni)
            margin = jnp.sum(wi * bv, axis=1)
            g = (_loss_grad(loss, margin, yy, quantile_tau) * ww)[:, None] * bv
            sigma = (jnp.sqrt(ni + g * g) - jnp.sqrt(ni)) / ftrl_alpha
            flat_i = bi.reshape(-1)
            z = z.at[flat_i].add((g - sigma * wi).reshape(-1))
            nacc = nacc.at[flat_i].add((g * g).reshape(-1))
            return (z, nacc, t + 1.0), None

        t = jnp.zeros(())
        if optimizer == "ftrl":
            z = -weights * (ftrl_beta / ftrl_alpha + l2)
            nacc = acc
            for _ in range(num_passes):
                (z, nacc, t), _ = jax.lax.scan(
                    step_ftrl, (z, nacc, t), (bidx, bval, by, bw)
                )
            return ftrl_w(z, nacc)
        for _ in range(num_passes):
            (weights, acc, t), _ = jax.lax.scan(
                step, (weights, acc, t), (bidx, bval, by, bw)
            )
        return jnp.sign(weights) * jnp.maximum(jnp.abs(weights) - l1, 0.0)

    return jax.jit(jax.vmap(
        fit_one, in_axes=(None, None, None, None, 0, 0, 0, 0, 0, 0)
    ))


def train_linear_many(
    batch: SparseBatch,
    y: np.ndarray,
    sample_weight: np.ndarray,
    *,
    loss: str,
    num_passes: int,
    learning_rates,
    power_ts,
    l1s,
    l2s,
    batch_size: int,
    constant_index: int,
    initial_weights: Optional[np.ndarray] = None,
    quantile_tau: float = 0.5,
    optimizer: str = "adagrad",
    ftrl_alpha: float = 0.005,
    ftrl_beta: float = 0.1,
) -> "list[VWTrainResult]":
    """Train K VW candidates in ONE compiled program (the many-models
    plane). Candidates share the data, loss, pass count, batch size, and
    optimizer — the shape-bucket statics — and differ only in the traced
    (learning_rate, power_t, l1, l2) lanes. Single device only (the
    sweep's gang mode shards BUCKETS across processes instead)."""
    import jax
    import jax.numpy as jnp

    from mmlspark_tpu.observability.profiler import get_profiler

    K = len(learning_rates)
    if not (K == len(power_ts) == len(l1s) == len(l2s)):
        raise ValueError("per-candidate hyperparameter stacks disagree on K")
    sw = StopWatch()
    dim = batch.dim
    n = batch.num_rows
    idx, val, y, sample_weight, k, num_batches = _prep_rows(
        batch, y, sample_weight, constant_index, batch_size, 1
    )
    w0 = (
        initial_weights.copy()
        if initial_weights is not None
        else np.zeros(dim, dtype=np.float32)
    )

    ckey = (loss, int(num_passes), optimizer, float(quantile_tau),
            float(ftrl_alpha), float(ftrl_beta))
    fit = _MANY_FIT_CACHE.get(ckey)
    if fit is None:
        fit = _MANY_FIT_CACHE[ckey] = _make_fit_many(*ckey)

    bidx = jnp.asarray(idx.reshape(num_batches, batch_size, k))
    bval = jnp.asarray(val.reshape(num_batches, batch_size, k))
    by = jnp.asarray(y.reshape(num_batches, batch_size))
    bw = jnp.asarray(sample_weight.reshape(num_batches, batch_size))
    weights0 = jnp.asarray(np.broadcast_to(w0[None], (K, dim)).copy())
    acc0 = jnp.zeros((K, dim), jnp.float32)

    _prof = get_profiler()
    _prof_on = _prof.active
    with sw.measure():
        t0 = time.perf_counter() if _prof_on else 0.0
        cache_before = (
            fit._cache_size()
            if _prof_on and hasattr(fit, "_cache_size") else None
        )
        fitted = fit(
            bidx, bval, by, bw, weights0, acc0,
            jnp.asarray(np.asarray(learning_rates, np.float32)),
            jnp.asarray(np.asarray(power_ts, np.float32)),
            jnp.asarray(np.asarray(l1s, np.float32)),
            jnp.asarray(np.asarray(l2s, np.float32)),
        )
        fitted = np.asarray(jax.block_until_ready(fitted))
        if _prof_on:
            dt = time.perf_counter() - t0
            compiled = (
                cache_before is not None
                and hasattr(fit, "_cache_size")
                and fit._cache_size() > cache_before
            )
            if compiled:
                _prof.note_compile("vw.fit_many", dt)
            else:
                _prof.note_cache_hit("vw.fit_many")
            _prof.note_execute("vw.fit_many", dt)

    results = []
    for ki in range(K):
        stats = {
            "rows": int(n),
            "passes": int(num_passes),
            "learn_time_s": sw.elapsed_s,
            "shards": 1,
            "ipass_loss": None,
        }
        results.append(VWTrainResult(weights=fitted[ki], stats=stats))
    return results


class VowpalWabbitModelBase(HasFeaturesCol, HasPredictionCol, Model):
    """Shared model: weights + raw margin computation
    (``VowpalWabbitBaseModel.scala``)."""

    modelWeights = Param("Fitted weight vector", is_complex=True)
    sparseDim = Param("Feature-space size", default=0, converter=to_int)
    constantIndex = Param("Bias feature index (-1 = trained --noconstant)", default=0, converter=to_int)
    numBits = Param("log2 feature-space size for dense inputs", default=18, converter=to_int)
    linkFunction = Param("Prediction link (--link): identity or logistic", default="identity", converter=to_str)

    def _margins(self, table: Table) -> np.ndarray:
        col = table.column(self.getFeaturesCol())
        w = np.asarray(self.getModelWeights())
        if col.dtype == object:
            batch = column_to_batch(col, len(w))
        else:
            batch = dense_to_batch(np.asarray(col, dtype=np.float32), len(w))
        m = (w[batch.indices] * batch.values).sum(axis=1)
        ci = self.getConstantIndex()
        return m if ci < 0 else m + w[ci]

    def _apply_link(self, m: np.ndarray) -> np.ndarray:
        if self.getLinkFunction() == "logistic":
            return 1.0 / (1.0 + np.exp(-m))
        return m

    def get_performance_statistics(self) -> Table:
        """Diagnostics DataFrame analogue (VowpalWabbitBase.scala:367-391)."""
        stats = self.getTrainingStats() if self.isSet("trainingStats") else {}
        return Table({k: [v] for k, v in stats.items() if v is not None})

    trainingStats = Param("Training diagnostics", is_complex=True)
