"""VowpalWabbitRegressor — squared/quantile-loss online linear regression.

Parity with ``vw/VowpalWabbitRegressor.scala``.
"""

from __future__ import annotations

import numpy as np

from mmlspark_tpu.data.table import Table
from mmlspark_tpu.vw.base import (
    VowpalWabbitBase,
    VowpalWabbitModelBase,
    VWTrainResult,
)


class VowpalWabbitRegressor(VowpalWabbitBase):
    _default_loss = "squared"

    def _make_model(self, result: VWTrainResult, dim: int, const_idx: int):
        return VowpalWabbitRegressionModel(
            featuresCol=self.getFeaturesCol(),
            predictionCol=self.getPredictionCol(),
            modelWeights=result.weights,
            sparseDim=dim,
            constantIndex=const_idx,
            trainingStats=result.stats,
        )


class VowpalWabbitRegressionModel(VowpalWabbitModelBase):
    def transform(self, table: Table) -> Table:
        return table.with_column(
            self.getPredictionCol(),
            self._apply_link(self._margins(table)).astype(np.float64),
        )
