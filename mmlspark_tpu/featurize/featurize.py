"""Featurize — heterogeneous columns to one dense feature matrix.

Re-design of ``featurize/Featurize.scala:25`` + ``AssembleFeatures.scala:96-467``:
per-type casting, missing-value imputation, categorical one-hot, text
hashing, and assembly. The reference assembles into a Spark vector via
``FastVectorAssembler``; here assembly is a single ``np.hstack`` into a 2-D
float column — already the layout the GBDT binner and linear learners ingest,
so no row-wise metadata walk is needed (the FastVectorAssembler speed trick
is moot columnar-side).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

from mmlspark_tpu.core.params import (
    HasInputCols,
    HasOutputCol,
    Param,
    gt,
    to_bool,
    to_int,
    to_str,
)
from mmlspark_tpu.core.pipeline import Estimator, Model, Transformer
from mmlspark_tpu.core.schema import ColType, add_column, require_column
from mmlspark_tpu.data.table import Table
from mmlspark_tpu.featurize.text import hashing_tf


def _is_numeric(col: np.ndarray) -> bool:
    return col.ndim == 1 and np.issubdtype(col.dtype, np.number) or col.dtype == bool


class AssembleFeatures(HasInputCols, HasOutputCol, Transformer):
    """Concatenate numeric/vector columns into one 2-D features column
    (``FastVectorAssembler`` role; categorical metadata is honored by
    Featurize before assembly)."""

    outputCol = Param("Assembled features column", default="features", converter=to_str)

    def transform(self, table: Table) -> Table:
        blocks: List[np.ndarray] = []
        for name in self.getInputCols():
            col = table.column(name)
            if col.ndim == 2:
                blocks.append(col.astype(np.float32))
            elif col.dtype == object:
                blocks.append(
                    np.stack([np.asarray(v, dtype=np.float32) for v in col])
                )
            elif col.dtype == bool:
                blocks.append(col.astype(np.float32)[:, None])
            elif np.issubdtype(col.dtype, np.number):
                blocks.append(col.astype(np.float32)[:, None])
            else:
                raise ValueError(
                    f"column {name!r} (dtype {col.dtype}) is not assemblable; "
                    "index or hash it first"
                )
        return table.with_column(self.getOutputCol(), np.hstack(blocks))

    def transform_schema(self, schema: Dict[str, Any]) -> Dict[str, Any]:
        name = type(self).__name__
        width: Optional[int] = 0
        for c in self.getInputCols():
            col = require_column(schema, c, name, numeric=False)
            if col.dtype is not None and col.dtype.kind in "US":
                # mirrors the runtime "not assemblable" error, statically
                from mmlspark_tpu.core.schema import DTYPE_MISMATCH, SchemaError

                raise SchemaError(
                    DTYPE_MISMATCH,
                    f"column {c!r} (dtype {col.dtype}) is not assemblable; "
                    "index or hash it first",
                    stage=name,
                    column=c,
                )
            if width is not None and col.shape is not None:
                width += col.shape[0] if col.shape else 1
            else:
                width = None  # any unknown-width input -> unknown total
        out = self.getOutputCol()
        shape = (width,) if width is not None else None
        return add_column(
            schema,
            out,
            ColType(np.dtype(np.float32), shape),
            name,
            replace=out in set(self.getInputCols()),
        )


class Featurize(HasInputCols, HasOutputCol, Estimator):
    """Auto-featurizer: imputes numerics, one-hot (or index) encodes low-
    cardinality strings, hashes free text, passes vectors through, and
    assembles everything into ``outputCol``."""

    outputCol = Param("Features column", default="features", converter=to_str)
    oneHotEncodeCategoricals = Param(
        "One-hot (true) vs single index column (false)",
        default=True,
        converter=to_bool,
    )
    numberOfFeatures = Param(
        "Hash dimensions for text columns (power of two)",
        default=1 << 8,
        converter=to_int,
        validator=gt(0),
    )
    allowImages = Param("Kept for parity", default=False, converter=to_bool)

    _MAX_CATEGORICAL_CARDINALITY = 100

    def transform_schema(self, schema: Dict[str, Any]) -> Dict[str, Any]:
        name = type(self).__name__
        for c in self.getInputCols():
            require_column(schema, c, name)
        out = self.getOutputCol()
        # width depends on fitted plans (one-hot cardinalities) -> unknown
        return add_column(
            schema,
            out,
            ColType(np.dtype(np.float32)),
            name,
            replace=out in set(self.getInputCols()),
        )

    def _fit(self, table: Table) -> "FeaturizeModel":
        plans: List[Dict[str, Any]] = []
        for name in self.getInputCols():
            col = table.column(name)
            if col.ndim == 2 or (col.dtype == object and len(col) and
                                 isinstance(col[0], (list, np.ndarray))):
                plans.append({"kind": "vector", "col": name})
            elif _is_numeric(col):
                values = col.astype(np.float64)
                valid = values[~np.isnan(values)]
                fill = float(valid.mean()) if len(valid) else 0.0
                plans.append({"kind": "numeric", "col": name, "fill": fill})
            else:
                values = [str(v) for v in col if v is not None]
                distinct = sorted(set(values))
                # Low-cardinality strings that actually repeat are categories;
                # near-unique strings are free text and get hashed.
                if len(distinct) <= self._MAX_CATEGORICAL_CARDINALITY and (
                    len(values) < 2 or len(distinct) <= max(2, len(values) // 2)
                ):
                    plans.append(
                        {"kind": "categorical", "col": name, "levels": distinct}
                    )
                else:
                    plans.append({"kind": "text", "col": name})
        model = FeaturizeModel(
            outputCol=self.getOutputCol(),
            plans=plans,
            oneHotEncodeCategoricals=self.getOneHotEncodeCategoricals(),
            numberOfFeatures=self.getNumberOfFeatures(),
        )
        model.parent = self
        return model


class FeaturizeModel(HasOutputCol, Model):
    plans = Param("Per-column featurization plans", default=[])
    oneHotEncodeCategoricals = Param("One-hot categoricals", default=True, converter=to_bool)
    numberOfFeatures = Param("Text hash dimensions", default=1 << 8, converter=to_int)

    def transform_schema(self, schema: Dict[str, Any]) -> Dict[str, Any]:
        name = type(self).__name__
        width: Optional[int] = 0
        for plan in self.getPlans():
            col = require_column(schema, plan["col"], name)
            kind = plan["kind"]
            if kind == "numeric":
                w: Optional[int] = 1
            elif kind == "categorical":
                w = (
                    len(plan["levels"]) + 1
                    if self.getOneHotEncodeCategoricals()
                    else 1
                )
            elif kind == "text":
                w = self.getNumberOfFeatures()
            else:  # vector: width comes from the input column, if known
                w = col.shape[0] if col.shape else None
            width = width + w if (width is not None and w is not None) else None
        out = self.getOutputCol()
        shape = (width,) if width is not None else None
        return add_column(
            schema,
            out,
            ColType(np.dtype(np.float32), shape),
            name,
            replace=out in {p["col"] for p in self.getPlans()},
        )

    def transform(self, table: Table) -> Table:
        blocks: List[np.ndarray] = []
        for plan in self.getPlans():
            col = table.column(plan["col"])
            kind = plan["kind"]
            if kind == "vector":
                if col.ndim == 2:
                    blocks.append(col.astype(np.float32))
                else:
                    blocks.append(
                        np.stack([np.asarray(v, dtype=np.float32) for v in col])
                    )
            elif kind == "numeric":
                values = col.astype(np.float64)
                values = np.where(np.isnan(values), plan["fill"], values)
                blocks.append(values.astype(np.float32)[:, None])
            elif kind == "categorical":
                levels: List[str] = plan["levels"]
                lookup = {v: i for i, v in enumerate(levels)}
                idx = np.array(
                    [
                        lookup.get(str(v), len(levels)) if v is not None else len(levels)
                        for v in col
                    ],
                    dtype=np.int64,
                )
                if self.getOneHotEncodeCategoricals():
                    onehot = np.zeros((len(col), len(levels) + 1), dtype=np.float32)
                    onehot[np.arange(len(col)), idx] = 1.0
                    blocks.append(onehot)
                else:
                    blocks.append(idx.astype(np.float32)[:, None])
            elif kind == "text":
                docs = [
                    ("" if v is None else str(v)).lower().split() for v in col
                ]
                blocks.append(hashing_tf(docs, self.getNumberOfFeatures()))
            else:  # pragma: no cover
                raise ValueError(f"unknown plan kind {kind!r}")
        return table.with_column(self.getOutputCol(), np.hstack(blocks))
