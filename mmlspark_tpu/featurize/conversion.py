"""Column type conversion (reference ``featurize/DataConversion.scala:21``)."""

from __future__ import annotations

from typing import Dict

import numpy as np

from mmlspark_tpu.core.params import HasInputCols, Param, one_of, to_str
from mmlspark_tpu.core.pipeline import Transformer
from mmlspark_tpu.data.table import Table

_DTYPES: Dict[str, np.dtype] = {
    "boolean": np.dtype(bool),
    "byte": np.dtype(np.int8),
    "short": np.dtype(np.int16),
    "integer": np.dtype(np.int32),
    "long": np.dtype(np.int64),
    "float": np.dtype(np.float32),
    "double": np.dtype(np.float64),
    "string": np.dtype(object),
    "toCategorical": np.dtype(object),  # handled specially
    "clearCategorical": np.dtype(object),  # handled specially
    "date": np.dtype("datetime64[ms]"),
}


class DataConversion(HasInputCols, Transformer):
    """Cast the listed columns to ``convertTo``; ``toCategorical`` indexes a
    column in place (ValueIndexer), ``clearCategorical`` decodes it back."""

    convertTo = Param(
        "Target type",
        default="double",
        converter=to_str,
        validator=one_of(*_DTYPES),
    )
    dateTimeFormat = Param(
        "strptime format for string->date", default=None,
    )

    def transform(self, table: Table) -> Table:
        target = self.getConvertTo()
        out = table
        for name in self.getInputCols():
            col = table.column(name)
            if target == "toCategorical":
                from mmlspark_tpu.featurize.indexers import ValueIndexer

                model = ValueIndexer(inputCol=name, outputCol=name).fit(out)
                out = model.transform(out)
            elif target == "clearCategorical":
                from mmlspark_tpu.featurize.indexers import IndexToValue

                out = IndexToValue(inputCol=name, outputCol=name).transform(out)
                out = out.with_metadata(name, {})
            elif target == "string":
                converted = np.array([str(v) for v in col], dtype=object)
                out = out.with_column(name, converted)
            elif target == "date":
                fmt = self.getDateTimeFormat()
                if fmt:
                    import datetime

                    converted = np.array(
                        [
                            np.datetime64(datetime.datetime.strptime(str(v), fmt), "ms")
                            for v in col
                        ]
                    )
                else:
                    converted = col.astype("datetime64[ms]")
                out = out.with_column(name, converted)
            elif target == "boolean":
                out = out.with_column(name, col.astype(np.float64) != 0)
            else:
                out = out.with_column(name, col.astype(_DTYPES[target]))
        return out
