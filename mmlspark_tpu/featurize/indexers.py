"""Categorical value indexing (reference ``featurize/ValueIndexer.scala:55``,
``ValueIndexerModel:102``, ``IndexToValue.scala:27``; categorical metadata
idiom from ``core/schema/Categoricals.scala``).

Levels are recorded in column metadata (``{"categorical": True, "levels":
[...]}``) — the Table analogue of MML-style categorical metadata — so
downstream one-hot assembly and ``IndexToValue`` need no side channel.
"""

from __future__ import annotations

from typing import Any, Dict, List

import numpy as np

from mmlspark_tpu.core.params import HasInputCol, HasOutputCol, Param, to_bool
from mmlspark_tpu.core.pipeline import Estimator, Model, Transformer
from mmlspark_tpu.core.schema import ColType, add_column, require_column
from mmlspark_tpu.data.table import Table


def _index_out_schema(stage: Any, schema: Dict[str, Any]) -> Dict[str, Any]:
    name = type(stage).__name__
    require_column(schema, stage.getInputCol(), name)
    out = stage.getOutputCol()
    return add_column(
        schema,
        out,
        ColType(np.dtype(np.int64), ()),
        name,
        replace=out == stage.getInputCol(),
    )


class ValueIndexer(HasInputCol, HasOutputCol, Estimator):
    """Distinct values -> dense indices [0, n); unseen values map to n
    (an explicit 'unknown' bucket) at transform time."""

    def transform_schema(self, schema: Dict[str, Any]) -> Dict[str, Any]:
        return _index_out_schema(self, schema)

    def _fit(self, table: Table) -> "ValueIndexerModel":
        col = table.column(self.getInputCol())
        if col.dtype == object:
            levels = sorted({str(v) for v in col if v is not None})
        else:
            valid = col[~_isnan(col)]
            levels = [v.item() for v in np.unique(valid)]
        model = ValueIndexerModel(
            inputCol=self.getInputCol(),
            outputCol=self.getOutputCol(),
            levels=levels,
            dataType="string" if col.dtype == object else str(col.dtype),
        )
        model.parent = self
        return model


def _isnan(col: np.ndarray) -> np.ndarray:
    if np.issubdtype(col.dtype, np.floating):
        return np.isnan(col)
    return np.zeros(len(col), dtype=bool)


class ValueIndexerModel(HasInputCol, HasOutputCol, Model):
    levels = Param("Ordered distinct values", default=[])
    dataType = Param("Original value dtype", default="string")

    def transform(self, table: Table) -> Table:
        col = table.column(self.getInputCol())
        levels = self.getLevels()
        lookup: Dict[Any, int] = {v: i for i, v in enumerate(levels)}
        unknown = len(levels)
        if col.dtype == object:
            out = np.array(
                [lookup.get(str(v), unknown) if v is not None else unknown for v in col],
                dtype=np.int64,
            )
        else:
            out = np.array([lookup.get(v.item(), unknown) for v in col], dtype=np.int64)
        return table.with_column(
            self.getOutputCol(),
            out,
            metadata={"categorical": True, "levels": list(levels)},
        )

    def transform_schema(self, schema: Dict[str, Any]) -> Dict[str, Any]:
        return _index_out_schema(self, schema)


def decode_levels(indices: np.ndarray, levels: List[Any]) -> np.ndarray:
    """Indices -> original level values; the unknown bucket decodes to None
    (string levels) or NaN (numeric levels). Shared by IndexToValue and
    TrainedClassifierModel."""
    idx = np.asarray(indices).astype(np.int64)
    in_range = (idx >= 0) & (idx < len(levels))
    if levels and not isinstance(levels[0], str):
        values = np.asarray(levels, dtype=np.float64)
        out = np.where(in_range, values[np.clip(idx, 0, len(levels) - 1)], np.nan)
        return out
    out = np.empty(len(idx), dtype=object)
    for i, (ok, j) in enumerate(zip(in_range, idx)):
        out[i] = levels[j] if ok else None
    return out


class IndexToValue(HasInputCol, HasOutputCol, Transformer):
    """Inverse of ValueIndexer: index column + categorical metadata -> values
    (``featurize/IndexToValue.scala:27``)."""

    def transform(self, table: Table) -> Table:
        meta = table.metadata(self.getInputCol())
        if not meta.get("categorical") or "levels" not in meta:
            raise ValueError(
                f"column {self.getInputCol()!r} has no categorical levels metadata"
            )
        out = decode_levels(table.column(self.getInputCol()), meta["levels"])
        return table.with_column(self.getOutputCol(), out)

    def transform_schema(self, schema: Dict[str, Any]) -> Dict[str, Any]:
        name = type(self).__name__
        require_column(schema, self.getInputCol(), name)
        out = self.getOutputCol()
        # decoded dtype depends on the level values (str -> object,
        # numeric -> float64) recorded in column metadata, not the schema
        return add_column(
            schema, out, ColType(), name,
            replace=out == self.getInputCol(),
        )
