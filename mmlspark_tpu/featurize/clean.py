"""Missing-value imputation (reference ``featurize/CleanMissingData.scala:49``)."""

from __future__ import annotations

from typing import Any, Dict, List

import numpy as np

from mmlspark_tpu.core.params import (
    HasInputCols,
    HasOutputCols,
    Param,
    one_of,
    to_str,
)
from mmlspark_tpu.core.pipeline import Estimator, Model
from mmlspark_tpu.core.schema import ColType, add_column, require_column
from mmlspark_tpu.data.table import Table


def _clean_out_schema(stage: Any, schema: Dict[str, Any]) -> Dict[str, Any]:
    """Each input col must exist; each output col carries the imputed values
    (float64 for numeric inputs, the input's own dtype otherwise)."""
    name = type(stage).__name__
    ins = list(stage.getInputCols())
    outs = (
        list(stage.getOutputCols()) if stage.isSet("outputCols") else ins
    )
    if len(ins) != len(outs):
        raise ValueError(
            f"inputCols ({len(ins)}) and outputCols ({len(outs)}) must align"
        )
    for in_col, out_col in zip(ins, outs):
        col = require_column(schema, in_col, name)
        if col.dtype is not None and col.dtype != np.dtype(object):
            col = ColType(np.dtype(np.float64), col.shape)
        schema = add_column(
            schema, out_col, col, name, replace=out_col == in_col
        )
    return schema


class CleanMissingData(HasInputCols, HasOutputCols, Estimator):
    """Replace NaN/None with mean, median, or a custom value per column."""

    cleaningMode = Param(
        "Mean, Median, or Custom",
        default="Mean",
        converter=to_str,
        validator=one_of("Mean", "Median", "Custom"),
    )
    customValue = Param("Replacement when cleaningMode=Custom", default=None)

    def transform_schema(self, schema: Dict[str, Any]) -> Dict[str, Any]:
        return _clean_out_schema(self, schema)

    def _fit(self, table: Table) -> "CleanMissingDataModel":
        mode = self.getCleaningMode()
        fills: Dict[str, float] = {}
        for col_name in self.getInputCols():
            col = table.column(col_name)
            if col.dtype == object:
                if mode != "Custom":
                    raise ValueError(
                        f"column {col_name!r} is non-numeric; use cleaningMode='Custom'"
                    )
                fills[col_name] = self.getCustomValue()
                continue
            values = col.astype(np.float64)
            valid = values[~np.isnan(values)]
            if mode == "Mean":
                fills[col_name] = float(valid.mean()) if len(valid) else 0.0
            elif mode == "Median":
                fills[col_name] = float(np.median(valid)) if len(valid) else 0.0
            else:
                fills[col_name] = float(self.getCustomValue())
        model = CleanMissingDataModel(
            inputCols=self.getInputCols(),
            outputCols=self.getOutputCols()
            if self.isSet("outputCols")
            else self.getInputCols(),
            fillValues=fills,
        )
        model.parent = self
        return model


class CleanMissingDataModel(HasInputCols, HasOutputCols, Model):
    fillValues = Param("column -> replacement value", default={})

    def transform_schema(self, schema: Dict[str, Any]) -> Dict[str, Any]:
        return _clean_out_schema(self, schema)

    def transform(self, table: Table) -> Table:
        fills = self.getFillValues()
        out = table
        for in_col, out_col in zip(self.getInputCols(), self.getOutputCols()):
            col = table.column(in_col)
            fill = fills[in_col]
            if col.dtype == object:
                new = np.array(
                    [fill if v is None else v for v in col], dtype=object
                )
            else:
                values = col.astype(np.float64)
                new = np.where(np.isnan(values), fill, values)
            out = out.with_column(out_col, new)
        return out
