"""Text featurization (reference ``featurize/text/`` — SURVEY.md §2.10).

``TextFeaturizer`` composes tokenize → n-grams → hashingTF → IDF exactly like
``featurize/text/TextFeaturizer.scala:181``'s internal pipeline; hashing is
the framework's vectorized murmur3 (:mod:`mmlspark_tpu.ops.hashing`) and the
TF/IDF aggregation is columnar scatter-adds — per-document Python loops only
materialize token lists, everything numeric is whole-column numpy.
"""

from __future__ import annotations

import re
from typing import Any, Dict, List, Optional

import numpy as np

from mmlspark_tpu.core.params import (
    HasInputCol,
    HasOutputCol,
    Param,
    ge,
    gt,
    to_bool,
    to_int,
    to_str,
)
from mmlspark_tpu.core.pipeline import Estimator, Model, Transformer
from mmlspark_tpu.core.schema import ColType, add_column, require_column
from mmlspark_tpu.data.table import Table
from mmlspark_tpu.ops.hashing import mask_bits, murmur32_strings


def _ragged_out_schema(stage: Any, schema: Dict[str, Any]) -> Dict[str, Any]:
    """input col exists; output is a ragged (object) list column."""
    name = type(stage).__name__
    src = stage.getInputCol()
    require_column(schema, src, name)
    out = stage.getOutputCol()
    return add_column(
        schema, out, ColType(np.dtype(object)), name, replace=out == src
    )


def _tf_out_schema(stage: Any, schema: Dict[str, Any]) -> Dict[str, Any]:
    """input col exists; output is a dense (numFeatures,) float32 vector."""
    name = type(stage).__name__
    src = stage.getInputCol()
    require_column(schema, src, name)
    out = stage.getOutputCol()
    return add_column(
        schema,
        out,
        ColType(np.dtype(np.float32), (stage.getNumFeatures(),)),
        name,
        replace=out == src,
    )


def _tokenize(text: str, pattern: str, to_lower: bool, min_len: int) -> List[str]:
    if to_lower:
        text = text.lower()
    tokens = re.split(pattern, text)
    return [t for t in tokens if len(t) >= min_len]


def _ngrams(tokens: List[str], n: int) -> List[str]:
    if n <= 1:
        return list(tokens)
    return [" ".join(tokens[i : i + n]) for i in range(len(tokens) - n + 1)]


def hashing_tf(
    docs: List[List[str]], num_features: int, binary: bool = False
) -> np.ndarray:
    """Token lists -> [n_docs, num_features] term-frequency matrix via
    murmur3 bucket hashing (HashingTF role in the reference pipeline)."""
    num_bits = int(np.log2(num_features))
    if 2**num_bits != num_features:
        raise ValueError(f"numFeatures must be a power of two, got {num_features}")
    out = np.zeros((len(docs), num_features), dtype=np.float32)
    cache: dict = {}  # one cache per table so recurring tokens hash once
    for i, tokens in enumerate(docs):
        if not tokens:
            continue
        idx = mask_bits(murmur32_strings(tokens, cache=cache), num_bits)
        np.add.at(out[i], idx, 1.0)
    if binary:
        out = (out > 0).astype(np.float32)
    return out


class PageSplitter(HasInputCol, HasOutputCol, Transformer):
    """Split documents into pages within [minimum, maximum] character budget,
    preferring boundaries (``featurize/text/PageSplitter.scala:20``).
    Output is a ragged column of page-string lists."""

    maximumPageLength = Param(
        "Max characters per page", default=5000, converter=to_int, validator=gt(0)
    )
    minimumPageLength = Param(
        "Min characters before a soft boundary split",
        default=4500,
        converter=to_int,
        validator=gt(0),
    )
    boundaryRegex = Param("Soft boundary", default=r"\s", converter=to_str)

    def transform(self, table: Table) -> Table:
        col = table.column(self.getInputCol())
        max_len = self.getMaximumPageLength()
        min_len = self.getMinimumPageLength()
        boundary = re.compile(self.getBoundaryRegex())
        out = np.empty(len(col), dtype=object)
        for i, doc in enumerate(col):
            text = "" if doc is None else str(doc)
            pages: List[str] = []
            pos = 0
            while pos < len(text):
                window = text[pos : pos + max_len]
                if len(window) < max_len:
                    pages.append(window)
                    break
                # Prefer the last soft boundary in [min_len, max_len).
                cut = max_len
                for m in boundary.finditer(window, min_len):
                    cut = m.start() + 1
                pages.append(window[:cut])
                pos += cut
            out[i] = pages
        return table.with_column(self.getOutputCol(), out)

    def transform_schema(self, schema: Dict[str, Any]) -> Dict[str, Any]:
        return _ragged_out_schema(self, schema)


class MultiNGram(HasInputCol, HasOutputCol, Transformer):
    """All n-grams for several lengths at once
    (``featurize/text/MultiNGram.scala:24``). Input: token-list column."""

    lengths = Param("N-gram lengths", default=[1, 2, 3])

    def transform(self, table: Table) -> Table:
        col = table.column(self.getInputCol())
        lengths = [int(n) for n in self.getLengths()]
        out = np.empty(len(col), dtype=object)
        for i, tokens in enumerate(col):
            tokens = list(tokens)
            grams: List[str] = []
            for n in lengths:
                grams.extend(_ngrams(tokens, n))
            out[i] = grams
        return table.with_column(self.getOutputCol(), out)

    def transform_schema(self, schema: Dict[str, Any]) -> Dict[str, Any]:
        return _ragged_out_schema(self, schema)


class TextFeaturizer(HasInputCol, HasOutputCol, Estimator):
    """tokenize -> n-grams -> hashingTF -> IDF, one estimator
    (``featurize/text/TextFeaturizer.scala:181``)."""

    useTokenizer = Param("Tokenize the input", default=True, converter=to_bool)
    tokenizerPattern = Param("Token split regex", default=r"\s+", converter=to_str)
    toLowercase = Param("Lowercase before tokenizing", default=True, converter=to_bool)
    minTokenLength = Param("Drop shorter tokens", default=0, converter=to_int)
    useNGram = Param("Add n-grams", default=False, converter=to_bool)
    nGramLength = Param("N-gram length", default=2, converter=to_int, validator=gt(0))
    numFeatures = Param(
        "Hash space size (power of two). TF blocks are dense 2-D columns, so "
        "memory is n_docs x numFeatures x 4 bytes — size accordingly",
        default=1 << 12,
        converter=to_int,
        validator=gt(0),
    )
    binary = Param("Binary term frequencies", default=False, converter=to_bool)
    useIDF = Param("Rescale by inverse document frequency", default=True, converter=to_bool)
    minDocFreq = Param("Min documents for IDF terms", default=0, converter=to_int)

    def _docs(self, col: np.ndarray) -> List[List[str]]:
        docs: List[List[str]] = []
        for v in col:
            if isinstance(v, (list, np.ndarray)):
                tokens = [str(t) for t in v]
            elif self.getUseTokenizer():
                tokens = _tokenize(
                    "" if v is None else str(v),
                    self.getTokenizerPattern(),
                    self.getToLowercase(),
                    self.getMinTokenLength(),
                )
            else:
                tokens = [] if v is None else [str(v)]
            if self.getUseNGram():
                tokens = tokens + _ngrams(tokens, self.getNGramLength())
            docs.append(tokens)
        return docs

    def transform_schema(self, schema: Dict[str, Any]) -> Dict[str, Any]:
        return _tf_out_schema(self, schema)

    def _fit(self, table: Table) -> "TextFeaturizerModel":
        docs = self._docs(table.column(self.getInputCol()))
        tf = hashing_tf(docs, self.getNumFeatures(), self.getBinary())
        idf = None
        if self.getUseIDF():
            n_docs = len(docs)
            df = (tf > 0).sum(axis=0).astype(np.float64)
            if self.getMinDocFreq() > 0:
                df = np.where(df >= self.getMinDocFreq(), df, 0.0)
            # Spark's IDF formula: log((m + 1) / (df + 1)).
            idf = np.log((n_docs + 1.0) / (df + 1.0)) * (df > 0)
        model = TextFeaturizerModel(
            inputCol=self.getInputCol(),
            outputCol=self.getOutputCol(),
            useTokenizer=self.getUseTokenizer(),
            tokenizerPattern=self.getTokenizerPattern(),
            toLowercase=self.getToLowercase(),
            minTokenLength=self.getMinTokenLength(),
            useNGram=self.getUseNGram(),
            nGramLength=self.getNGramLength(),
            numFeatures=self.getNumFeatures(),
            binary=self.getBinary(),
            idfVector=idf,
        )
        model.parent = self
        return model


class TextFeaturizerModel(HasInputCol, HasOutputCol, Model):
    useTokenizer = Param("Tokenize the input", default=True, converter=to_bool)
    tokenizerPattern = Param("Token split regex", default=r"\s+", converter=to_str)
    toLowercase = Param("Lowercase before tokenizing", default=True, converter=to_bool)
    minTokenLength = Param("Drop shorter tokens", default=0, converter=to_int)
    useNGram = Param("Add n-grams", default=False, converter=to_bool)
    nGramLength = Param("N-gram length", default=2, converter=to_int)
    numFeatures = Param("Hash space size", default=1 << 12, converter=to_int)
    binary = Param("Binary term frequencies", default=False, converter=to_bool)
    idfVector = Param("IDF weights (None = raw TF)", default=None, is_complex=True)

    _docs = TextFeaturizer._docs

    def transform(self, table: Table) -> Table:
        docs = self._docs(table.column(self.getInputCol()))
        tf = hashing_tf(docs, self.getNumFeatures(), self.getBinary())
        idf = self.getIdfVector()
        if idf is not None:
            tf = tf * np.asarray(idf, dtype=np.float32)
        return table.with_column(self.getOutputCol(), tf)

    def transform_schema(self, schema: Dict[str, Any]) -> Dict[str, Any]:
        return _tf_out_schema(self, schema)
