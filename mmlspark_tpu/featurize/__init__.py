"""Automatic featurization (reference ``featurize/`` — SURVEY.md §2.10)."""

from mmlspark_tpu.featurize.clean import CleanMissingData, CleanMissingDataModel
from mmlspark_tpu.featurize.conversion import DataConversion
from mmlspark_tpu.featurize.featurize import AssembleFeatures, Featurize
from mmlspark_tpu.featurize.indexers import (
    IndexToValue,
    ValueIndexer,
    ValueIndexerModel,
)
from mmlspark_tpu.featurize.text import (
    MultiNGram,
    PageSplitter,
    TextFeaturizer,
    TextFeaturizerModel,
)

__all__ = [
    "AssembleFeatures",
    "CleanMissingData",
    "CleanMissingDataModel",
    "DataConversion",
    "Featurize",
    "IndexToValue",
    "MultiNGram",
    "PageSplitter",
    "TextFeaturizer",
    "TextFeaturizerModel",
    "ValueIndexer",
    "ValueIndexerModel",
]
