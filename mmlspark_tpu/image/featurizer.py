"""ImageFeaturizer — transfer-learning featurization on TPU.

Re-design of ``image/ImageFeaturizer.scala:40-86``: the reference wraps a
downloaded CNTK model, cuts ``cutOutputLayers`` layers off the top, and
prepends resize/unroll. Here the backbone is a native JAX network (default:
the :mod:`mmlspark_tpu.models.resnet` zoo) and the whole chain — resize →
normalize → NCHW layout → backbone forward with ``cut`` — jits into one XLA
program executed in fixed-shape device batches by :class:`DNNModel`.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import numpy as np

from mmlspark_tpu.core.params import Param, gt, to_bool, to_int, to_str
from mmlspark_tpu.core.pipeline import Model
from mmlspark_tpu.data.table import Table
from mmlspark_tpu.dnn.model import DNNModel
from mmlspark_tpu.image.transforms import ImageTransformer


class ImageFeaturizer(Model):
    """Featurize an image column with a (cut) deep network."""

    inputCol = Param("Image column", default="image", converter=to_str)
    outputCol = Param("Feature vector column", default="features", converter=to_str)
    modelParams = Param(
        "Backbone parameter pytree (mmlspark_tpu.models zoo format)",
        default=None,
        is_complex=True,
    )
    applyFn = Param(
        "Backbone (params, x, cut) -> array; default resnet_apply",
        default=None,
        is_complex=True,
    )
    cutOutputLayers = Param(
        "Layers cut from the top: 0 = logits (headful), 1 = pooled features "
        "(reference default), 2 = feature map",
        default=1,
        converter=to_int,
    )
    inputHeight = Param("Model input height", default=32, converter=to_int, validator=gt(0))
    inputWidth = Param("Model input width", default=32, converter=to_int, validator=gt(0))
    autoResize = Param(
        "Resize images to the model input (ResizeImageTransformer analogue)",
        default=True,
        converter=to_bool,
    )
    scale = Param("Pixel scale applied before the backbone", default=1.0 / 255.0)
    batchSize = Param("Device batch size", default=64, converter=to_int, validator=gt(0))

    def _backbone(self):
        fn = self.getApplyFn()
        if fn is None:
            from mmlspark_tpu.models.resnet import resnet_apply

            fn = resnet_apply
        return fn

    def transform(self, table: Table) -> Table:
        params = self.getModelParams()
        if params is None:
            raise ValueError("modelParams must be set (see mmlspark_tpu.models)")
        work = table
        image_col = self.getInputCol()
        if self.getAutoResize():
            resized_col = "__resized__"
            work = ImageTransformer(
                inputCol=image_col,
                outputCol=resized_col,
                toFloat=True,
                stages=[
                    {
                        "op": "ResizeImage",
                        "height": self.getInputHeight(),
                        "width": self.getInputWidth(),
                    }
                ],
            ).transform(work)
            image_col = resized_col

        backbone = self._backbone()
        cut = self.getCutOutputLayers()
        scale = float(self.getScale())

        def apply_fn(p, inputs):
            x = inputs["input"].astype("float32") * scale
            x = x.transpose(0, 3, 1, 2)  # NHWC -> NCHW
            return {"output": backbone(p, x, cut)}

        dnn = DNNModel(
            applyFn=apply_fn,
            modelParams=params,
            feedDict={"input": image_col},
            fetchDict={self.getOutputCol(): "output"},
            batchSize=self.getBatchSize(),
        )
        out = dnn.transform(work)
        if image_col != self.getInputCol():
            out = out.drop(image_col)
        return out
