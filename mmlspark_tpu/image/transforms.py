"""ImageTransformer — a pipeline of image ops executed as batched XLA programs.

Re-design of ``opencv/ImageTransformer.scala:40-219``: the reference encodes
each OpenCV stage as a ``Map[String, Any]`` and runs a per-row UDF over JNI
mats. Here the same stage list drives a jitted NHWC float pipeline: images
are grouped by shape, stacked into batches, and every stage is a pure JAX
op — so a transformer chain compiles to ONE fused XLA program per input
shape instead of |rows| × |stages| native calls.

Stage dict vocabulary mirrors the reference (``ResizeImage``, ``CropImage``,
``ColorFormat``, ``Flip``, ``Blur``, ``Threshold``, ``GaussianKernel``).
Flip codes follow OpenCV: 0 = vertical (x-axis), 1 = horizontal, -1 = both.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Tuple

import numpy as np

from mmlspark_tpu.core.params import HasInputCol, HasOutputCol, Param, to_bool, to_str
from mmlspark_tpu.core.pipeline import Transformer
from mmlspark_tpu.data.table import Table


def _ensure_nhwc(batch: Any) -> Any:
    return batch if batch.ndim == 4 else batch[..., None]


def _op_resize(stage: Dict[str, Any]) -> Callable:
    import jax.image

    h, w = int(stage["height"]), int(stage["width"])

    def run(x):
        return jax.image.resize(
            x, (x.shape[0], h, w, x.shape[3]), method=stage.get("method", "linear")
        )

    return run


def _op_crop(stage: Dict[str, Any]) -> Callable:
    x0, y0 = int(stage.get("x", 0)), int(stage.get("y", 0))
    h, w = int(stage["height"]), int(stage["width"])

    def run(x):
        return x[:, y0 : y0 + h, x0 : x0 + w, :]

    return run


def _op_color_format(stage: Dict[str, Any]) -> Callable:
    import jax.numpy as jnp

    fmt = stage["format"]

    def run(x):
        if fmt == "gray":
            # OpenCV BGR2GRAY luma weights, channel order B,G,R.
            weights = jnp.asarray([0.114, 0.587, 0.299], dtype=x.dtype)
            return (x * weights).sum(axis=-1, keepdims=True)
        if fmt in ("bgr2rgb", "rgb2bgr"):
            return x[..., ::-1]
        raise ValueError(f"unknown color format {fmt!r}")

    return run


def _op_flip(stage: Dict[str, Any]) -> Callable:
    code = int(stage.get("flipCode", 1))

    def run(x):
        if code == 0:
            return x[:, ::-1, :, :]
        if code > 0:
            return x[:, :, ::-1, :]
        return x[:, ::-1, ::-1, :]

    return run


def _depthwise_filter(x, kernel2d):
    """Same-padding depthwise conv of an NHWC batch with one 2-D kernel."""
    import jax.numpy as jnp
    from jax import lax

    c = x.shape[-1]
    k = jnp.asarray(kernel2d, dtype=x.dtype)
    w = jnp.tile(k[None, None, :, :], (c, 1, 1, 1))  # OIHW, O=C, I=1
    xt = jnp.transpose(x, (0, 3, 1, 2))
    out = lax.conv_general_dilated(
        xt, w, window_strides=(1, 1), padding="SAME",
        dimension_numbers=("NCHW", "OIHW", "NCHW"), feature_group_count=c,
    )
    return jnp.transpose(out, (0, 2, 3, 1))


def _op_blur(stage: Dict[str, Any]) -> Callable:
    kh, kw = int(stage["height"]), int(stage["width"])
    kernel = np.full((kh, kw), 1.0 / (kh * kw))

    def run(x):
        return _depthwise_filter(x, kernel)

    return run


def _op_threshold(stage: Dict[str, Any]) -> Callable:
    import jax.numpy as jnp

    thresh = float(stage["threshold"])
    max_val = float(stage.get("maxVal", 255.0))

    def run(x):
        return jnp.where(x > thresh, max_val, 0.0).astype(x.dtype)

    return run


def _op_gaussian(stage: Dict[str, Any]) -> Callable:
    size = int(stage["apertureSize"])
    sigma = float(stage.get("sigma", 0.0))
    if sigma <= 0:  # OpenCV's default sigma rule
        sigma = 0.3 * ((size - 1) * 0.5 - 1) + 0.8
    ax = np.arange(size) - (size - 1) / 2.0
    g = np.exp(-(ax**2) / (2 * sigma**2))
    kernel = np.outer(g, g)
    kernel /= kernel.sum()

    def run(x):
        return _depthwise_filter(x, kernel)

    return run


def _op_normalize(stage: Dict[str, Any]) -> Callable:
    mean = np.asarray(stage.get("mean", 0.0), dtype=np.float32)
    std = np.asarray(stage.get("std", 1.0), dtype=np.float32)
    scale = float(stage.get("scale", 1.0))

    def run(x):
        return (x * scale - mean) / std

    return run


_OPS: Dict[str, Callable[[Dict[str, Any]], Callable]] = {
    "ResizeImage": _op_resize,
    "CropImage": _op_crop,
    "ColorFormat": _op_color_format,
    "Flip": _op_flip,
    "Blur": _op_blur,
    "Threshold": _op_threshold,
    "GaussianKernel": _op_gaussian,
    "Normalize": _op_normalize,
}


class ImageTransformer(HasInputCol, HasOutputCol, Transformer):
    """Applies a list of image stages to an image column."""

    stages = Param("List of {'op': name, ...} stage dicts", default=[])
    toFloat = Param(
        "Emit float32 images (skip uint8 round-trip)", default=False, converter=to_bool
    )

    inputCol = Param("Image column", default="image", converter=to_str)
    outputCol = Param("Output image column", default="image_out", converter=to_str)

    # -- fluent stage builders (ImageTransformer.scala:70-219) ---------------

    def _add(self, stage: Dict[str, Any]) -> "ImageTransformer":
        self.set("stages", list(self.getStages()) + [stage])
        return self

    def resize(self, height: int, width: int) -> "ImageTransformer":
        return self._add({"op": "ResizeImage", "height": height, "width": width})

    def crop(self, x: int, y: int, height: int, width: int) -> "ImageTransformer":
        return self._add(
            {"op": "CropImage", "x": x, "y": y, "height": height, "width": width}
        )

    def color_format(self, fmt: str) -> "ImageTransformer":
        return self._add({"op": "ColorFormat", "format": fmt})

    def flip(self, flip_code: int = 1) -> "ImageTransformer":
        return self._add({"op": "Flip", "flipCode": flip_code})

    def blur(self, height: int, width: int) -> "ImageTransformer":
        return self._add({"op": "Blur", "height": height, "width": width})

    def threshold(self, threshold: float, max_val: float = 255.0) -> "ImageTransformer":
        return self._add(
            {"op": "Threshold", "threshold": threshold, "maxVal": max_val}
        )

    def gaussian_kernel(self, aperture_size: int, sigma: float = 0.0) -> "ImageTransformer":
        return self._add(
            {"op": "GaussianKernel", "apertureSize": aperture_size, "sigma": sigma}
        )

    def normalize(self, mean: Any, std: Any, scale: float = 1.0) -> "ImageTransformer":
        return self._add({"op": "Normalize", "mean": mean, "std": std, "scale": scale})

    # -- execution -----------------------------------------------------------

    def _pipeline(self) -> Callable:
        import jax

        ops = []
        for stage in self.getStages():
            op_name = stage["op"]
            if op_name not in _OPS:
                raise ValueError(f"unknown image op {op_name!r}; have {sorted(_OPS)}")
            ops.append(_OPS[op_name](stage))

        @jax.jit
        def run(batch):
            x = batch.astype("float32")
            for op in ops:
                x = op(x)
            return x

        return run

    def transform(self, table: Table) -> Table:
        import jax

        col = table.column(self.getInputCol())
        run = self._pipeline()
        images = [np.asarray(im) for im in col]
        # Group equal-shape images into device batches: one compile per
        # distinct input shape, one program execution per group.
        by_shape: Dict[Tuple[int, ...], List[int]] = {}
        for i, im in enumerate(images):
            by_shape.setdefault(im.shape, []).append(i)
        out: List[Any] = [None] * len(images)
        for shape, idxs in by_shape.items():
            batch = _ensure_nhwc(np.stack([images[i] for i in idxs]))
            result = np.asarray(jax.device_get(run(batch)))
            if not self.getToFloat():
                result = np.clip(np.rint(result), 0, 255).astype(np.uint8)
            if result.shape[-1] == 1 and len(shape) == 2:
                result = result[..., 0]
            for j, i in enumerate(idxs):
                out[i] = result[j]
        return table.with_column(self.getOutputCol(), out)


class ImageSetAugmenter(HasInputCol, HasOutputCol, Transformer):
    """Flip-based dataset augmentation (``image/ImageSetAugmenter.scala``):
    emits the original rows plus a flipped copy per enabled axis."""

    inputCol = Param("Image column", default="image", converter=to_str)
    outputCol = Param("Output image column", default="image", converter=to_str)
    flipLeftRight = Param("Mirror horizontally", default=True, converter=to_bool)
    flipUpDown = Param("Mirror vertically", default=False, converter=to_bool)

    def transform(self, table: Table) -> Table:
        in_col, out_col = self.getInputCol(), self.getOutputCol()
        base = table if in_col == out_col else table.with_column(
            out_col, table.column(in_col)
        )
        results = [base]
        if self.getFlipLeftRight():
            flipped = ImageTransformer(
                inputCol=in_col, outputCol=out_col, stages=[
                    {"op": "Flip", "flipCode": 1}
                ]
            ).transform(table)
            results.append(flipped)
        if self.getFlipUpDown():
            flipped = ImageTransformer(
                inputCol=in_col, outputCol=out_col, stages=[
                    {"op": "Flip", "flipCode": 0}
                ]
            ).transform(table)
            results.append(flipped)
        return Table.concat(results)
