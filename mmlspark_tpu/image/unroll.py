"""UnrollImage / roll — image ↔ flat CHW vector (``image/UnrollImage.scala:28-87``).

The reference unrolls ImageSchema rows (BGR byte buffers) into CHW-ordered
DenseVectors for CNTK input, with an unsigned-byte fixup. Here images are
already numpy HWC arrays; unrolling is a transpose + ravel, vectorized over
the column.
"""

from __future__ import annotations

from typing import List

import numpy as np

from mmlspark_tpu.core.params import HasInputCol, HasOutputCol, Param, to_str
from mmlspark_tpu.core.pipeline import Transformer
from mmlspark_tpu.data.table import Table


def unroll_image(image: np.ndarray) -> np.ndarray:
    """HWC (or HW) uint8/float image -> flat float64 CHW vector."""
    arr = np.asarray(image)
    if arr.ndim == 2:
        arr = arr[..., None]
    chw = np.transpose(arr, (2, 0, 1)).astype(np.float64)
    return chw.ravel()


def roll_image(vector: np.ndarray, height: int, width: int, channels: int = 3) -> np.ndarray:
    """Inverse of :func:`unroll_image` (the reference's ``roll``)."""
    chw = np.asarray(vector, dtype=np.float64).reshape(channels, height, width)
    return np.transpose(chw, (1, 2, 0))


class UnrollImage(HasInputCol, HasOutputCol, Transformer):
    inputCol = Param("Image column", default="image", converter=to_str)
    outputCol = Param("Unrolled vector column", default="unrolled", converter=to_str)

    def transform(self, table: Table) -> Table:
        col = table.column(self.getInputCol())
        shapes = {np.asarray(im).shape for im in col}
        if len(shapes) == 1:
            stacked = np.stack([np.asarray(im) for im in col])
            if stacked.ndim == 3:
                stacked = stacked[..., None]
            flat = np.transpose(stacked, (0, 3, 1, 2)).reshape(len(col), -1)
            return table.with_column(self.getOutputCol(), flat.astype(np.float64))
        out: List[np.ndarray] = [unroll_image(im) for im in col]
        return table.with_column(self.getOutputCol(), out)
