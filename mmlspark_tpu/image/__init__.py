"""Image pipeline (reference ``opencv/`` + ``image/`` — SURVEY.md §2.5).

The reference crosses every image row into OpenCV JNI mats
(``opencv/ImageTransformer.scala``); here images are numpy HWC arrays
batched by shape and transformed by jitted JAX programs (resize/crop/
flip/blur/threshold run as XLA ops on whole batches).
"""

from mmlspark_tpu.image.featurizer import ImageFeaturizer
from mmlspark_tpu.image.transforms import ImageSetAugmenter, ImageTransformer
from mmlspark_tpu.image.unroll import UnrollImage, roll_image, unroll_image

__all__ = [
    "ImageFeaturizer",
    "ImageSetAugmenter",
    "ImageTransformer",
    "UnrollImage",
    "roll_image",
    "unroll_image",
]
