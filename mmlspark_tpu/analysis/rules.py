"""The builtin graftlint rule set.

Five framework contracts, one rule each — catalog and rationale in
``docs/static_analysis.md``:

- ``jit-purity``: no host side effects inside traced code.
- ``numpy-in-traced-code``: ``np.*`` reachable from a trace must be
  ``jnp.*`` or hoisted to host-side setup.
- ``pallas-tile-alignment``: literal Pallas block shapes must respect the
  (8, 128) VPU register tile.
- ``lock-discipline``: no blocking call while holding a lock in the
  threaded ``runtime/`` / ``serving/`` layers.
- ``bare-except-policy``: ``except Exception`` must re-raise, log, or
  carry an explicit justification.
- ``socket-deadline-policy``: every socket wait in ``runtime/`` /
  ``serving/`` must carry an explicit timeout — an unbounded socket is
  how a network partition becomes a hung gang.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set

from mmlspark_tpu.analysis.base import (
    FileContext,
    Rule,
    Violation,
    dotted_name,
    local_int_constants,
    module_int_constants,
    register_rule,
    resolve_int,
)

_SUBLANE, _LANE = 8, 128


def _traced_defs(ctx: FileContext) -> List[ast.FunctionDef]:
    """Traced defs from the project-wide index when the driver attached
    one, else a single-file index (lint_source / unit tests)."""
    index = getattr(ctx, "traced_index", None)
    if index is None:
        from mmlspark_tpu.analysis.traced import TracedIndex

        index = TracedIndex([ctx])
        ctx.traced_index = index
    return index.traced_defs(ctx)


@register_rule
class JitPurityRule(Rule):
    name = "jit-purity"
    description = (
        "No wall-clock reads, host RNG, printing, I/O, or global mutation "
        "inside jit/pallas-traced functions: side effects run once at trace "
        "time, then silently never again."
    )

    _BANNED_PREFIXES = {
        "time.": "wall-clock read executes at trace time only",
        "random.": "host RNG is frozen at trace time; use jax.random",
        "np.random.": "host RNG is frozen at trace time; use jax.random",
        "numpy.random.": "host RNG is frozen at trace time; use jax.random",
    }
    _BANNED_CALLS = {
        "print": "print() runs at trace time only; use jax.debug.print",
        "input": "blocking host I/O inside traced code",
        "open": "host file I/O inside traced code",
    }

    def check(self, ctx: FileContext) -> Iterator[Violation]:
        for func in _traced_defs(ctx):
            for node in ast.walk(func):
                if isinstance(node, ast.Global):
                    yield self.violation(
                        ctx, node,
                        f"global mutation of {', '.join(node.names)!s} inside "
                        f"traced function '{func.name}' happens at trace time "
                        "only",
                    )
                if not isinstance(node, ast.Call):
                    continue
                name = dotted_name(node.func)
                if name is None:
                    continue
                if name in self._BANNED_CALLS:
                    yield self.violation(
                        ctx, node,
                        f"{name}() inside traced function '{func.name}': "
                        f"{self._BANNED_CALLS[name]}",
                    )
                    continue
                for prefix, why in self._BANNED_PREFIXES.items():
                    if name.startswith(prefix):
                        yield self.violation(
                            ctx, node,
                            f"{name}() inside traced function "
                            f"'{func.name}': {why}",
                        )
                        break


@register_rule
class NumpyInTracedCodeRule(Rule):
    name = "numpy-in-traced-code"
    description = (
        "np.* calls reachable from jit/pallas-traced code: they break on "
        "tracers or silently constant-fold; use jnp.* or hoist to host-side "
        "setup (an lru_cache'd builder is the blessed hoist and is not "
        "flagged)."
    )

    # Host-side constant constructors that are fine under trace: dtypes,
    # scalar casts of static values, and dtype introspection.
    _ALLOWED_ATTRS = {
        "dtype", "errstate", "iinfo", "finfo", "can_cast", "result_type",
        "promote_types",
        "int8", "int16", "int32", "int64", "uint8", "uint16", "uint32",
        "uint64", "float16", "float32", "float64", "bool_", "complex64",
        "complex128", "intp", "uintp", "generic",
    }

    def check(self, ctx: FileContext) -> Iterator[Violation]:
        for func in _traced_defs(ctx):
            for node in ast.walk(func):
                if not isinstance(node, ast.Call):
                    continue
                name = dotted_name(node.func)
                if name is None:
                    continue
                for mod in ("np.", "numpy."):
                    if not name.startswith(mod):
                        continue
                    rest = name[len(mod):]
                    if rest.split(".")[0] in self._ALLOWED_ATTRS:
                        continue
                    if rest.startswith("random."):
                        continue  # jit-purity owns host RNG
                    yield self.violation(
                        ctx, node,
                        f"{name}() reachable from traced function "
                        f"'{func.name}': numpy breaks on tracers or "
                        "constant-folds at trace time; use jnp."
                        f"{rest} or hoist to host-side setup",
                    )
                    break


@register_rule
class PallasTileAlignmentRule(Rule):
    name = "pallas-tile-alignment"
    description = (
        "Literal block shapes passed to pl.pallas_call/pl.BlockSpec must "
        "tile the (8, 128) VPU register: last dim % 128 == 0, second-to-"
        "last % 8 == 0. Misaligned blocks relayout on every grid step."
    )

    def check(self, ctx: FileContext) -> Iterator[Violation]:
        consts = module_int_constants(ctx.tree)
        owners = self._owner_map(ctx.tree)
        env_cache: Dict[int, Dict[str, int]] = {}
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            name = dotted_name(node.func)
            if name is None or name.split(".")[-1] != "BlockSpec":
                continue
            shape = self._shape_arg(node)
            if shape is None:
                continue
            env = consts
            owner = owners.get(id(node))
            if owner is not None:
                env = env_cache.setdefault(
                    id(owner), local_int_constants(owner, consts)
                )
            yield from self._check_shape(ctx, node, shape, env)

    @staticmethod
    def _owner_map(tree: ast.Module) -> Dict[int, ast.AST]:
        """Map each node id to its innermost enclosing function def."""
        owners: Dict[int, ast.AST] = {}

        def visit(node: ast.AST, owner: Optional[ast.AST]) -> None:
            if owner is not None:
                owners[id(node)] = owner
            next_owner = (
                node
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
                else owner
            )
            for child in ast.iter_child_nodes(node):
                visit(child, next_owner)

        visit(tree, None)
        return owners

    @staticmethod
    def _shape_arg(node: ast.Call) -> Optional[ast.Tuple]:
        for kw in node.keywords:
            if kw.arg == "block_shape" and isinstance(kw.value, ast.Tuple):
                return kw.value
        if node.args and isinstance(node.args[0], ast.Tuple):
            return node.args[0]
        return None

    def _check_shape(
        self,
        ctx: FileContext,
        node: ast.Call,
        shape: ast.Tuple,
        env: Dict[str, int],
    ) -> Iterator[Violation]:
        dims = [resolve_int(el, env) for el in shape.elts]
        if not dims:
            return
        rendered = (
            "(" + ", ".join(
                str(d) if d is not None else "?" for d in dims
            ) + ")"
        )
        last = dims[-1]
        if last is not None and last != 1 and last % _LANE != 0:
            yield self.violation(
                ctx, node,
                f"block shape {rendered}: lane dim {last} is not a "
                f"multiple of {_LANE} — each grid step pays a lane "
                "relayout",
            )
        if len(dims) >= 2:
            sub = dims[-2]
            if sub is not None and sub != 1 and sub % _SUBLANE != 0:
                yield self.violation(
                    ctx, node,
                    f"block shape {rendered}: sublane dim {sub} is not a "
                    f"multiple of {_SUBLANE} — each grid step pays a "
                    "sublane relayout",
                )


@register_rule
class LockDisciplineRule(Rule):
    name = "lock-discipline"
    description = (
        "No blocking call (thread join, sleep, queue get/put, network I/O) "
        "while holding a threading.Lock/RLock in runtime/, serving/, "
        "streaming/, observability/, resilience/, sweep/ or dataguard/: "
        "the lock serializes every heartbeat, reply, epoch-commit, "
        "breaker-decision and metrics-scrape path behind the wait."
    )

    _PATH_PARTS = (
        "runtime", "serving", "streaming", "observability", "resilience",
        "sweep", "dataguard",
    )
    _NETWORK_PREFIXES = (
        "urllib.request.urlopen", "urlopen", "requests.", "socket.",
        "http.client.",
    )
    _NETWORK_METHODS = {"recv", "recv_into", "accept", "connect", "urlopen"}

    def _applies(self, ctx: FileContext) -> bool:
        parts = ctx.path.replace("\\", "/").split("/")
        return any(p in parts for p in self._PATH_PARTS)

    def check(self, ctx: FileContext) -> Iterator[Violation]:
        if not self._applies(ctx):
            return
        for node in ast.walk(ctx.tree):
            if not isinstance(node, (ast.With, ast.AsyncWith)):
                continue
            lock_expr = self._held_lock(node)
            if lock_expr is None:
                continue
            for stmt in node.body:
                for sub in ast.walk(stmt):
                    if isinstance(sub, ast.Call):
                        why = self._blocking_reason(sub)
                        if why is not None:
                            yield self.violation(
                                ctx, sub,
                                f"{why} while holding {lock_expr}: every "
                                "thread contending for the lock stalls "
                                "behind this wait",
                            )

    @staticmethod
    def _held_lock(node: ast.With) -> Optional[str]:
        for item in node.items:
            expr = item.context_expr
            if isinstance(expr, ast.Call):
                expr = expr.func
            name = dotted_name(expr)
            if name is not None and "lock" in name.lower():
                return name
        return None

    def _blocking_reason(self, call: ast.Call) -> Optional[str]:
        name = dotted_name(call.func)
        if name is not None:
            if name.startswith("time.") and name.endswith("sleep"):
                return f"{name}()"
            for prefix in self._NETWORK_PREFIXES:
                if name == prefix or name.startswith(prefix):
                    return f"network call {name}()"
        if not isinstance(call.func, ast.Attribute):
            return None
        attr = call.func.attr
        has_positional = bool(call.args)
        kwargs = {kw.arg for kw in call.keywords}
        if attr == "sleep":
            return "sleep()"
        if attr == "join" and not has_positional:
            # str.join always takes one positional iterable; thread/process
            # join takes none (timeouts arrive as keywords)
            return ".join()"
        if attr in ("get", "put") and (
            (not has_positional and not kwargs)
            or kwargs & {"timeout", "block"}
        ):
            # dict.get(key[, default]) always passes positionals without
            # timeout/block keywords; queue get/put is what remains
            return f"queue .{attr}()"
        if attr in self._NETWORK_METHODS:
            return f"network call .{attr}()"
        return None


@register_rule
class SocketDeadlinePolicyRule(Rule):
    name = "socket-deadline-policy"
    description = (
        "Every socket wait in runtime/ and serving/ must carry an explicit "
        "deadline: urlopen()/create_connection() without a timeout and "
        ".settimeout(None) wait forever, so a partitioned peer or a dead "
        "registry hangs the calling thread instead of failing over."
    )

    _PATH_PARTS = ("runtime", "serving")
    #: (callable suffix, index of the positional timeout argument)
    _TIMEOUT_CALLS = {
        "urlopen": 2,            # urlopen(url, data=None, timeout=...)
        "create_connection": 1,  # create_connection(address, timeout=...)
    }

    def _applies(self, ctx: FileContext) -> bool:
        parts = ctx.path.replace("\\", "/").split("/")
        return any(p in parts for p in self._PATH_PARTS)

    def check(self, ctx: FileContext) -> Iterator[Violation]:
        if not self._applies(ctx):
            return
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            name = dotted_name(node.func)
            short = (name or "").split(".")[-1]
            if short in self._TIMEOUT_CALLS:
                pos = self._TIMEOUT_CALLS[short]
                kwargs = {kw.arg for kw in node.keywords}
                if "timeout" in kwargs or len(node.args) > pos:
                    continue
                yield self.violation(
                    ctx, node,
                    f"{name or short}() without timeout=: the call blocks "
                    "forever when the peer is partitioned or dead — pass "
                    "an explicit deadline",
                )
            elif (
                isinstance(node.func, ast.Attribute)
                and node.func.attr == "settimeout"
                and node.args
                and isinstance(node.args[0], ast.Constant)
                and node.args[0].value is None
            ):
                yield self.violation(
                    ctx, node,
                    ".settimeout(None) removes the socket deadline: a "
                    "silent peer then hangs this thread forever",
                )


@register_rule
class BareExceptPolicyRule(Rule):
    name = "bare-except-policy"
    description = (
        "`except:` / `except Exception:` must re-raise, log the exception, "
        "or carry an explicit justification (# noqa: BLE001 or a graftlint "
        "suppression) — silent swallowing hides scheduler and kernel bugs."
    )

    _BROAD = {"Exception", "BaseException"}
    _LOG_METHODS = {
        "debug", "info", "warning", "error", "exception", "critical", "log",
    }

    def check(self, ctx: FileContext) -> Iterator[Violation]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if not self._is_broad(node):
                continue
            if self._justified(ctx, node):
                continue
            caught = "bare except" if node.type is None else (
                f"except {dotted_name(node.type)}"
            )
            yield self.violation(
                ctx, node,
                f"{caught} swallows the error: re-raise, log it, narrow "
                "the type, or justify with `# noqa: BLE001`",
            )

    def _is_broad(self, node: ast.ExceptHandler) -> bool:
        if node.type is None:
            return True
        name = dotted_name(node.type)
        return name in self._BROAD

    def _justified(self, ctx: FileContext, node: ast.ExceptHandler) -> bool:
        line = ctx.line_text(node.lineno)
        if "noqa" in line and "BLE001" in line:
            return True
        for sub in ast.walk(node):
            if isinstance(sub, ast.Raise):
                return True
            if isinstance(sub, ast.Call) and self._is_log_call(sub):
                return True
        return False

    def _is_log_call(self, call: ast.Call) -> bool:
        if not isinstance(call.func, ast.Attribute):
            return False
        if call.func.attr not in self._LOG_METHODS:
            return False
        base = dotted_name(call.func.value)
        return base is not None and "log" in base.lower()
