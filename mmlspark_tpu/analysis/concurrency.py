"""graftlint v2: concurrency & distributed-protocol rules.

Four families on top of the whole-program model in ``lockgraph.py``
(catalog and semantics in docs/static_analysis.md):

- lock order: ``lock-order`` (ABBA cycles in the acquisition graph),
  ``lock-blocking`` (a call made while a lock is held transitively
  reaches sleep/join/socket/HTTP/queue waits — the interprocedural
  extension of ``lock-discipline``);
- collective consistency: ``collective-deadline`` (gang waits must be
  deadline-bounded), ``collective-rank-branch`` (a collective under a
  rank/member-dependent conditional is a static gang deadlock);
- protocol ordering: ``wal-before-commit``, ``journal-before-store``,
  ``tmp-rename-atomicity``, ``onset-recovery-pairing``.

All findings honor the per-line ``# graftlint: disable=<rule>``
suppressions; whole-program findings (cycles) anchor at their smallest
edge site so a suppression has one well-defined home.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set, Tuple

from mmlspark_tpu.analysis.base import (
    FileContext,
    Rule,
    Violation,
    dotted_name,
    register_rule,
)
from mmlspark_tpu.analysis.lockgraph import concurrency_index


def _path_parts(ctx: FileContext) -> List[str]:
    return ctx.path.replace("\\", "/").split("/")


def _in_parts(ctx: FileContext, parts: Tuple[str, ...]) -> bool:
    have = _path_parts(ctx)
    return any(p in have for p in parts)


_CONCURRENT_PARTS = (
    "runtime", "serving", "streaming", "observability", "resilience",
    "sweep", "lightgbm", "dataguard",
)


# ---------------------------------------------------------------------------
# Family 1: lock order
# ---------------------------------------------------------------------------


@register_rule
class LockOrderRule(Rule):
    name = "lock-order"
    description = (
        "The whole-program lock acquisition graph (locks identified by "
        "class-qualified self._lock attribute paths) must be acyclic: a "
        "cycle means two threads can acquire the same locks in opposite "
        "orders and deadlock (ABBA). Each cycle is reported once, at its "
        "smallest edge site."
    )

    def check(self, ctx: FileContext) -> Iterator[Violation]:
        index = concurrency_index(ctx)
        for cycle in index.cycles():
            if cycle.path != ctx.path:
                continue
            yield Violation(
                rule=self.name, path=ctx.path, line=cycle.line,
                col=cycle.col,
                message=(
                    "lock-order cycle (potential ABBA deadlock): "
                    + cycle.describe()
                ),
            )


@register_rule
class LockBlockingRule(Rule):
    name = "lock-blocking"
    description = (
        "A call made while holding a lock must not transitively reach a "
        "blocking wait (sleep, unbounded join/wait, queue get/put, socket "
        "or HTTP I/O) in any callee, across modules. Direct blocking in "
        "the with-body is lock-discipline's finding; this rule follows "
        "the call graph."
    )

    _PATH_PARTS = _CONCURRENT_PARTS

    def check(self, ctx: FileContext) -> Iterator[Violation]:
        if not _in_parts(ctx, self._PATH_PARTS):
            return
        index = concurrency_index(ctx)
        for f in index.blocking_findings():
            if f.path != ctx.path:
                continue
            yield Violation(
                rule=self.name, path=ctx.path, line=f.line, col=f.col,
                message=(
                    f"call while holding {f.lock_id} reaches {f.reason} "
                    f"via {' -> '.join(f.chain)}: every thread contending "
                    "for the lock stalls behind that wait"
                ),
            )


# ---------------------------------------------------------------------------
# Family 2: collective consistency
# ---------------------------------------------------------------------------


@register_rule
class CollectiveDeadlineRule(Rule):
    name = "collective-deadline"
    description = (
        "Gang and process waits must be deadline-bounded: "
        "AllreduceGroup(...) requires an explicit timeout=, and bare "
        ".wait()/.join() without a timeout block forever when a member "
        "dies or the network partitions."
    )

    _PATH_PARTS = _CONCURRENT_PARTS

    def check(self, ctx: FileContext) -> Iterator[Violation]:
        if not _in_parts(ctx, self._PATH_PARTS):
            return
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            name = dotted_name(node.func) or ""
            short = name.split(".")[-1]
            if short == "AllreduceGroup":
                kwargs = {kw.arg for kw in node.keywords}
                if "timeout" not in kwargs and len(node.args) < 4:
                    yield self.violation(
                        ctx, node,
                        "AllreduceGroup(...) without an explicit timeout=: "
                        "formation blocks forever when a member never "
                        "arrives — pass the gang deadline",
                    )
                continue
            if not isinstance(node.func, ast.Attribute):
                continue
            attr = node.func.attr
            kwargs = {kw.arg for kw in node.keywords}
            if (
                attr in ("wait", "join")
                and not node.args
                and "timeout" not in kwargs
            ):
                yield self.violation(
                    ctx, node,
                    f"unbounded .{attr}(): a dead peer or partitioned "
                    "network hangs this thread forever — pass timeout= "
                    "and handle the expiry",
                )


_RANK_MARKERS = {
    "rank", "member_id", "process_id", "process_index", "local_rank",
    "worker_id",
}
_COLLECTIVE_SUFFIXES = {
    "allreduce", "barrier", "psum", "pmean", "pmax", "pmin", "all_gather",
    "all_to_all", "ppermute", "hist_reduce",
}


def _rank_dependent(test: ast.AST) -> Optional[str]:
    """The rank-ish reference a condition reads, else None. ``world``/
    ``process_count`` comparisons are uniform across members and allowed."""
    for node in ast.walk(test):
        if isinstance(node, ast.Name) and node.id in _RANK_MARKERS:
            return node.id
        if isinstance(node, ast.Attribute) and node.attr in _RANK_MARKERS:
            return dotted_name(node) or node.attr
        if isinstance(node, ast.Call):
            name = dotted_name(node.func) or ""
            if name.split(".")[-1] in ("process_index", "process_id"):
                return name
    return None


@register_rule
class CollectiveRankBranchRule(Rule):
    name = "collective-rank-branch"
    description = (
        "A collective (allreduce/barrier/psum/...) reachable only under a "
        "rank- or member-dependent conditional is a static gang deadlock: "
        "the members that skip the branch never enter the collective and "
        "the rest block until the gang deadline. World-size conditions "
        "(uniform across members) are allowed."
    )

    _PATH_PARTS = ("runtime", "lightgbm", "sweep", "ops", "parallel")

    def check(self, ctx: FileContext) -> Iterator[Violation]:
        if not _in_parts(ctx, self._PATH_PARTS):
            return
        for stmt in ctx.tree.body:
            yield from self._visit(ctx, stmt, None)

    def _visit(
        self, ctx: FileContext, node: ast.AST,
        guard: Optional[Tuple[str, int]],
    ) -> Iterator[Violation]:
        """Recursive visit tracking the innermost rank-dependent guard.
        Function boundaries reset the guard (the callee runs wherever it
        is called from); the condition expression itself is visited with
        the OUTER guard, only the branch bodies get the new one."""
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for stmt in node.body:
                yield from self._visit(ctx, stmt, None)
            return
        if isinstance(node, ast.Lambda):
            return
        if isinstance(node, ast.Call) and self._is_collective(node):
            if guard is not None:
                yield self._make(ctx, node, guard)
        if isinstance(node, (ast.If, ast.While, ast.IfExp)):
            marker = _rank_dependent(node.test)
            inner = (marker, node.lineno) if marker is not None else guard
            yield from self._visit(ctx, node.test, guard)
            if isinstance(node, ast.IfExp):
                yield from self._visit(ctx, node.body, inner)
                yield from self._visit(ctx, node.orelse, inner)
            else:
                for stmt in node.body:
                    yield from self._visit(ctx, stmt, inner)
                for stmt in node.orelse:
                    yield from self._visit(ctx, stmt, inner)
            return
        for child in ast.iter_child_nodes(node):
            yield from self._visit(ctx, child, guard)

    @staticmethod
    def _is_collective(node: ast.Call) -> bool:
        name = dotted_name(node.func) or ""
        return name.split(".")[-1] in _COLLECTIVE_SUFFIXES

    def _make(
        self, ctx: FileContext, node: ast.Call, guard: Tuple[str, int]
    ) -> Violation:
        name = dotted_name(node.func) or "<collective>"
        return self.violation(
            ctx, node,
            f"collective {name}() guarded by member-dependent condition "
            f"on {guard[0]!r} (line {guard[1]}): members that skip the "
            "branch never join and the rest deadlock until the gang "
            "deadline",
        )


# ---------------------------------------------------------------------------
# Family 3: protocol ordering
# ---------------------------------------------------------------------------


def _own_nodes(func: ast.AST) -> Iterator[ast.AST]:
    """Nodes of ``func``'s own body, not descending into nested defs."""
    stack = list(ast.iter_child_nodes(func))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef, ast.Lambda)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def _calls_with_suffix(func: ast.AST, suffix: str) -> List[ast.Call]:
    out = []
    for node in _own_nodes(func):
        if not isinstance(node, ast.Call):
            continue
        name = dotted_name(node.func) or ""
        if name.split(".")[-1] == suffix:
            out.append(node)
    return out


@register_rule
class WalBeforeCommitRule(Rule):
    name = "wal-before-commit"
    description = (
        "Exactly-once streaming writes the offset WAL before the commit "
        "log: a function in streaming/ that writes the commit record must "
        "write the WAL first — commit-before-WAL (or commit with no WAL) "
        "re-executes or skips a batch after a crash."
    )

    _PATH_PARTS = ("streaming",)

    def check(self, ctx: FileContext) -> Iterator[Violation]:
        if not _in_parts(ctx, self._PATH_PARTS):
            return
        for func in ast.walk(ctx.tree):
            if not isinstance(func, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if func.name == "_write_commit":
                continue
            commits = _calls_with_suffix(func, "_write_commit")
            if not commits:
                continue
            wals = _calls_with_suffix(func, "_write_wal")
            first_commit = min(commits, key=lambda c: c.lineno)
            if not wals:
                yield self.violation(
                    ctx, first_commit,
                    f"'{func.name}' writes the commit log without writing "
                    "the offset WAL: a crash between planning and commit "
                    "loses the batch boundary",
                )
            elif first_commit.lineno < min(w.lineno for w in wals):
                yield self.violation(
                    ctx, first_commit,
                    f"'{func.name}' writes the commit log before the "
                    "offset WAL: a crash in between re-executes the batch "
                    "with a different plan — write the WAL first",
                )


def _attr_call_on(node: ast.Call, attr: str, base_hint: str) -> bool:
    if not isinstance(node.func, ast.Attribute) or node.func.attr != attr:
        return False
    base = dotted_name(node.func.value) or ""
    return base_hint in base.lower()


@register_rule
class JournalBeforeStoreRule(Rule):
    name = "journal-before-store"
    description = (
        "A streaming sink that commits model text to the ModelStore must "
        "record the epoch in the fit journal first (the journal is the "
        "durability point replay dedupes on) — either in the same "
        "function, or in a same-class caller of it."
    )

    _PATH_PARTS = ("streaming",)

    def check(self, ctx: FileContext) -> Iterator[Violation]:
        if not _in_parts(ctx, self._PATH_PARTS):
            return
        index = concurrency_index(ctx)
        fm = index.file_model(ctx.path)
        if fm is None:
            return
        for fn in fm.functions.values():
            commits = [
                node for node in _own_nodes(fn.node)
                if isinstance(node, ast.Call)
                and _attr_call_on(node, "commit", "store")
            ]
            if not commits:
                continue
            records = [
                node for node in _own_nodes(fn.node)
                if isinstance(node, ast.Call)
                and _attr_call_on(node, "record", "journal")
            ]
            if records:
                if min(r.lineno for r in records) < max(
                    c.lineno for c in commits
                ):
                    continue
            elif self._caller_records(fm, fn):
                continue
            yield self.violation(
                ctx, min(commits, key=lambda c: c.lineno),
                f"'{fn.key[1]}' commits to the ModelStore without a "
                "journal record: a crash after the store write but before "
                "journaling replays the epoch and double-commits — record "
                "the epoch first",
            )

    @staticmethod
    def _caller_records(fm, fn) -> bool:
        if fn.class_name is None:
            return False
        bare = fn.key[1].split(".")[-1]
        for other in fm.functions.values():
            if other.class_name != fn.class_name or other is fn:
                continue
            calls_fn = any(
                site.name in (f"self.{bare}", f"cls.{bare}")
                for site in other.calls
            )
            if not calls_fn:
                continue
            if any(
                isinstance(node, ast.Call)
                and _attr_call_on(node, "record", "journal")
                for node in _own_nodes(other.node)
            ):
                return True
        return False


_WRITE_MODES = {"w", "wb", "wt", "w+", "w+b", "wb+"}
_RENAME_ATTRS = {"replace", "rename", "renames"}


@register_rule
class TmpRenameAtomicityRule(Rule):
    name = "tmp-rename-atomicity"
    description = (
        "Checkpoint/WAL state in streaming/, dataguard/ and "
        "runtime/journal.py must be written tmp+rename (_atomic_write): a "
        "bare open(path, 'w') or write_text leaves a torn file when the "
        "process dies mid-write, and recovery then reads garbage. "
        "Functions that os.replace/rename are exempt (they ARE the atomic "
        "writer)."
    )

    def _applies(self, ctx: FileContext) -> bool:
        parts = _path_parts(ctx)
        return (
            "streaming" in parts
            or "dataguard" in parts
            or parts[-1] == "journal.py"
        )

    def check(self, ctx: FileContext) -> Iterator[Violation]:
        if not self._applies(ctx):
            return
        for func in ast.walk(ctx.tree):
            if not isinstance(func, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if "atomic" in func.name or self._renames(func):
                continue
            for node in _own_nodes(func):
                if not isinstance(node, ast.Call):
                    continue
                name = dotted_name(node.func) or ""
                short = name.split(".")[-1]
                if short == "open" and len(node.args) >= 2:
                    mode = node.args[1]
                    if (
                        isinstance(mode, ast.Constant)
                        and isinstance(mode.value, str)
                        and mode.value in _WRITE_MODES
                    ):
                        yield self.violation(
                            ctx, node,
                            f"bare open(..., {mode.value!r}) on a "
                            "checkpoint/WAL path: a crash mid-write tears "
                            "the file — write tmp then os.replace "
                            "(_atomic_write)",
                        )
                elif (
                    isinstance(node.func, ast.Attribute)
                    and node.func.attr in ("write_text", "write_bytes")
                ):
                    yield self.violation(
                        ctx, node,
                        f".{node.func.attr}() on a checkpoint/WAL path is "
                        "not atomic: a crash mid-write tears the file — "
                        "write tmp then os.replace (_atomic_write)",
                    )

    @staticmethod
    def _renames(func: ast.AST) -> bool:
        for node in _own_nodes(func):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _RENAME_ATTRS
            ):
                return True
        return False


#: onset event class -> recovery classes, any one of which must be
#: constructed in the same file (the publisher owns both edges of its
#: outage latch, so tools/check_eventlog.py can pair them at runtime)
_EVENT_PAIRS: Dict[str, Set[str]] = {
    "WorkerQuarantined": {"WorkerParoled", "GroupReformed"},
    "ProcessLost": {"GroupReformed", "ProcessStarted"},
    "NetworkPartitioned": {"GroupReformed"},
    "RegistryUnavailable": {"RegistryRecovered"},
    "DriftDetected": {"DriftCleared"},
    "AlertFired": {"AlertResolved"},
}
#: level-carrying events: a literal warn/critical onset needs a literal
#: "ok" publish, a variable level (covers both), or a degradation event
_LEVEL_EVENTS = {"MemoryPressure", "DiskPressure"}
_DEGRADATION_EVENTS = {"HistogramDegraded", "RequestShed"}


@register_rule
class OnsetRecoveryPairingRule(Rule):
    name = "onset-recovery-pairing"
    description = (
        "A module that publishes an outage-onset event (ProcessLost, "
        "NetworkPartitioned, RegistryUnavailable, WorkerQuarantined, a "
        "warn/critical pressure level) must also publish the paired "
        "recovery event: an event log with onsets and no recoveries "
        "cannot be audited for outage duration and check_eventlog's "
        "pairing contract fails."
    )

    def check(self, ctx: FileContext) -> Iterator[Violation]:
        ctors: Dict[str, List[ast.Call]] = {}
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            name = (dotted_name(node.func) or "").split(".")[-1]
            if name in _EVENT_PAIRS or name in _LEVEL_EVENTS or (
                name in _DEGRADATION_EVENTS
                or any(name in v for v in _EVENT_PAIRS.values())
            ):
                ctors.setdefault(name, []).append(node)
        present = set(ctors)
        for onset, recoveries in _EVENT_PAIRS.items():
            if onset in present and not (recoveries & present):
                for call in ctors[onset]:
                    yield self.violation(
                        ctx, call,
                        f"{onset} published with no paired recovery event "
                        f"({' or '.join(sorted(recoveries))}) in this "
                        "module: the outage has an onset record but no "
                        "end, so duration auditing and event-log pairing "
                        "checks fail",
                    )
        for name in _LEVEL_EVENTS & present:
            yield from self._check_levels(ctx, name, ctors, present)

    def _check_levels(
        self, ctx: FileContext, name: str,
        ctors: Dict[str, List[ast.Call]], present: Set[str],
    ) -> Iterator[Violation]:
        onsets, has_ok, has_dynamic = [], False, False
        for call in ctors[name]:
            level = None
            for kw in call.keywords:
                if kw.arg == "level":
                    level = kw.value
            if level is None or not isinstance(level, ast.Constant):
                has_dynamic = True
            elif level.value == "ok":
                has_ok = True
            elif level.value in ("warn", "critical"):
                onsets.append(call)
        if onsets and not (
            has_ok or has_dynamic or (_DEGRADATION_EVENTS & present)
        ):
            for call in onsets:
                yield self.violation(
                    ctx, call,
                    f"{name} published at a literal warn/critical level "
                    "with no 'ok' recovery publish (or degradation event) "
                    "in this module: the pressure onset never pairs, so "
                    "check_eventlog --pressure fails",
                )
