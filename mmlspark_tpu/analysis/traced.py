"""Traced-function reachability index for the jit-aware rules.

``jit-purity`` and ``numpy-in-traced-code`` only make sense inside code
that runs under a JAX trace. That set is wider than "functions decorated
with ``@jax.jit``": kernels passed to ``pl.pallas_call``, bodies handed to
``lax.scan``/``while_loop``/``cond``, functions wrapped by
``jax.jit(f)`` / ``shard_map(f)`` at a call site, and — the part plain
linters miss — every function those reach by call, **across modules**
(``lightgbm/train.py`` jits step functions that call into
``ops/u_histogram.py``; a stray ``np.*`` there fails or silently
constant-folds under trace even though ``u_histogram.py`` itself never
mentions ``jax.jit``).

The index is built in two passes over every linted file:

1. per-file: function defs, local traced roots, name aliases
   (``g = partial(f, ...)``), an import map (``from m import f [as g]``,
   ``from pkg import mod``), and the call edges out of every def;
2. global BFS from the roots over call edges, following edges into other
   linted files through the import map.

The walk stops at ``functools.lru_cache``/``functools.cache``-decorated
functions: their arguments must be hashable, so they can never receive
tracers — anything behind them is host-side memoized setup by
construction (the blessed "hoist it out of the hot loop" pattern, e.g.
``ops/u_histogram._col_maps_cached``).
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from mmlspark_tpu.analysis.base import FileContext, dotted_name

# Wrappers whose *first* argument becomes traced code.
_JIT_WRAPPERS = {
    "jax.jit", "jit", "jax.pmap", "pmap", "pjit", "jax.pjit",
    "jax.vmap", "vmap", "jax.shard_map", "shard_map", "jax.grad",
    "jax.value_and_grad", "jax.checkpoint", "jax.remat",
}
# Control-flow combinators: every function-valued argument is traced.
_COMBINATORS = {
    "lax.scan", "jax.lax.scan",
    "lax.while_loop", "jax.lax.while_loop",
    "lax.fori_loop", "jax.lax.fori_loop",
    "lax.cond", "jax.lax.cond",
    "lax.switch", "jax.lax.switch",
    "lax.map", "jax.lax.map",
}
_PALLAS_CALLS = {"pl.pallas_call", "pallas_call", "pltpu.pallas_call"}
_PARTIALS = {"partial", "functools.partial"}
_HOST_BOUNDARY_DECOS = {
    "functools.lru_cache", "lru_cache", "functools.cache", "cache",
}


def _first_func_ref(node: ast.AST) -> Optional[str]:
    """The function name a wrapper argument refers to: ``f``,
    ``partial(f, ...)``, or ``module.f`` (returned dotted)."""
    if isinstance(node, (ast.Name, ast.Attribute)):
        return dotted_name(node)
    if isinstance(node, ast.Call):
        fn = dotted_name(node.func)
        if fn in _PARTIALS and node.args:
            return _first_func_ref(node.args[0])
    return None


class _FileIndex:
    """One linted file's defs, roots, aliases, imports, and call edges."""

    def __init__(self, ctx: FileContext, module: Optional[str]):
        self.ctx = ctx
        self.module = module
        # bare name -> defs with that name (nested defs share the namespace;
        # a linter can afford the over-approximation)
        self.defs: Dict[str, List[ast.FunctionDef]] = {}
        self.host_boundary: Set[str] = set()
        self.roots: Set[str] = set()
        self.aliases: Dict[str, str] = {}  # g = partial(f, ...) -> {g: f}
        self.imports: Dict[str, Tuple[str, str]] = {}  # local -> (module, name)
        self.module_imports: Dict[str, str] = {}  # local alias -> module
        self._collect()

    def _collect(self) -> None:
        for node in ast.walk(self.ctx.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.defs.setdefault(node.name, []).append(node)
                if self._is_traced_def(node):
                    self.roots.add(node.name)
                if any(
                    dotted_name(d) in _HOST_BOUNDARY_DECOS
                    or (
                        isinstance(d, ast.Call)
                        and dotted_name(d.func) in _HOST_BOUNDARY_DECOS
                    )
                    for d in node.decorator_list
                ):
                    self.host_boundary.add(node.name)
            elif isinstance(node, ast.Assign) and len(node.targets) == 1:
                target = node.targets[0]
                if isinstance(target, ast.Name):
                    ref = _first_func_ref(node.value)
                    if ref is not None and isinstance(node.value, ast.Call):
                        self.aliases[target.id] = ref
            elif isinstance(node, ast.Call):
                self._collect_call_roots(node)
            elif isinstance(node, ast.ImportFrom) and node.module:
                for alias in node.names:
                    self.imports[alias.asname or alias.name] = (
                        node.module, alias.name
                    )
            elif isinstance(node, ast.Import):
                for alias in node.names:
                    self.module_imports[alias.asname or alias.name.split(".")[0]] = (
                        alias.name
                    )

    @staticmethod
    def _is_traced_def(node: ast.AST) -> bool:
        for deco in node.decorator_list:
            name = dotted_name(deco)
            if name in _JIT_WRAPPERS:
                return True
            if isinstance(deco, ast.Call):
                fn = dotted_name(deco.func)
                if fn in _JIT_WRAPPERS:
                    return True
                if fn in _PARTIALS and deco.args:
                    if dotted_name(deco.args[0]) in _JIT_WRAPPERS:
                        return True
        return False

    def _collect_call_roots(self, node: ast.Call) -> None:
        fn = dotted_name(node.func)
        if fn in _JIT_WRAPPERS and node.args:
            ref = _first_func_ref(node.args[0])
            if ref is not None:
                self.roots.add(ref)
        elif fn in _COMBINATORS:
            for arg in node.args:
                ref = _first_func_ref(arg)
                if ref is not None:
                    self.roots.add(ref)
        elif fn is not None and fn.split(".")[-1] == "pallas_call" and node.args:
            ref = _first_func_ref(node.args[0])
            if ref is not None:
                self.roots.add(ref)

    def resolve_local(self, name: str) -> str:
        """Follow ``g = partial(f, ...)`` aliases to the underlying name."""
        seen = set()
        while name in self.aliases and name not in seen:
            seen.add(name)
            name = self.aliases[name]
        return name


class TracedIndex:
    """Project-wide set of traced function defs, queryable per file."""

    def __init__(self, contexts: Iterable[FileContext]):
        self._files: Dict[str, _FileIndex] = {}
        self._by_module: Dict[str, _FileIndex] = {}
        for ctx in contexts:
            module = _module_name(ctx.path)
            idx = _FileIndex(ctx, module)
            self._files[ctx.path] = idx
            if module is not None:
                self._by_module[module] = idx
        self._traced: Set[Tuple[str, str]] = set()  # (path, func name)
        self._bfs()

    # -- queries -------------------------------------------------------------

    def traced_defs(self, ctx: FileContext) -> List[ast.FunctionDef]:
        """The traced FunctionDef nodes of one file (deduplicated: a nested
        def inside a traced def is covered by walking its parent)."""
        idx = self._files.get(ctx.path)
        if idx is None:
            idx = _FileIndex(ctx, _module_name(ctx.path))
            self._files[ctx.path] = idx
            self._seed_and_close_single(idx)
        out = []
        for name, defs in idx.defs.items():
            if (ctx.path, name) in self._traced:
                out.extend(defs)
        return out

    # -- closure -------------------------------------------------------------

    def _bfs(self) -> None:
        frontier: List[Tuple[str, str]] = []
        for idx in self._files.values():
            frontier.extend(self._seeds(idx))
        self._close(frontier)

    def _seeds(self, idx: _FileIndex) -> List[Tuple[str, str]]:
        out: List[Tuple[str, str]] = []
        for root in idx.roots:
            if "." not in root:
                root = idx.resolve_local(root)
            out.extend(self._resolve_callee(idx, root))
        return out

    def _close(self, frontier: List[Tuple[str, str]]) -> None:
        while frontier:
            path, name = frontier.pop()
            if (path, name) in self._traced:
                continue
            self._traced.add((path, name))
            idx = self._files[path]
            for node in idx.defs.get(name, []):
                frontier.extend(self._callees(idx, node))

    def _seed_and_close_single(self, idx: _FileIndex) -> None:
        self._close(self._seeds(idx))

    def _callees(
        self, idx: _FileIndex, func: ast.FunctionDef
    ) -> List[Tuple[str, str]]:
        out: List[Tuple[str, str]] = []
        for node in ast.walk(func):
            if not isinstance(node, ast.Call):
                continue
            name = dotted_name(node.func)
            if name is None:
                continue
            out.extend(self._resolve_callee(idx, name))
        return out

    def _resolve_callee(
        self, idx: _FileIndex, name: str
    ) -> List[Tuple[str, str]]:
        head, _, rest = name.partition(".")
        # module-qualified call through `from pkg import mod` / `import m as x`
        if rest and "." not in rest:
            target_module = None
            if head in idx.module_imports:
                target_module = idx.module_imports[head]
            elif head in idx.imports:
                mod, item = idx.imports[head]
                target_module = f"{mod}.{item}"
            if target_module is not None:
                other = self._by_module.get(target_module)
                if (
                    other is not None
                    and rest in other.defs
                    and rest not in other.host_boundary
                ):
                    return [(other.ctx.path, rest)]
            return []
        if rest:
            return []
        local = idx.resolve_local(head)
        if local in idx.defs:
            if local in idx.host_boundary:
                return []
            return [(idx.ctx.path, local)]
        if local in idx.imports:
            mod, item = idx.imports[local]
            other = self._by_module.get(mod)
            if (
                other is not None
                and item in other.defs
                and item not in other.host_boundary
            ):
                return [(other.ctx.path, item)]
        return []


def _module_name(path: str) -> Optional[str]:
    """Dotted module name for files under a ``mmlspark_tpu`` tree."""
    parts = path.replace("\\", "/").split("/")
    if "mmlspark_tpu" not in parts:
        return None
    i = parts.index("mmlspark_tpu")
    rel = parts[i:]
    if not rel[-1].endswith(".py"):
        return None
    rel[-1] = rel[-1][:-3]
    if rel[-1] == "__init__":
        rel = rel[:-1]
    return ".".join(rel)
