"""Whole-program lock & call model for the concurrency rule family.

graftlint's original ``lock-discipline`` rule sees one ``with`` body at a
time, so it catches ``time.sleep`` under a lock but not ``self._flush()``
under a lock where ``_flush`` sleeps three calls deeper — and it cannot
see lock *ordering* at all. This module builds the project-wide model the
interprocedural rules (``analysis/concurrency.py``) query:

- **lock identities**: ``self._lock``-style attributes qualified by the
  defining class (``mmlspark_tpu.serving.server._BatchLoop._lock``) and
  module-level locks. One id covers every *instance* of the class — the
  same granularity the runtime witness (``analysis/witness.py``) records,
  so the two sides cross-check.
- **per-function facts**: lock acquisitions with the locks lexically held
  at that point, every call site with its held-lock set, and direct
  blocking calls (sleep, unbounded join/wait, queue get/put, socket and
  HTTP waits).
- **a resolved call graph**: ``self.m()`` to same-class methods,
  ``self._attr.m()`` through ``self._attr = ClassName(...)`` attribute
  types, bare and module-qualified calls through the same import maps
  ``analysis/traced.py`` uses, and constructor calls into ``__init__``.
- **transitive summaries** (fixpoint over the call graph): the locks a
  function may acquire and the blocking calls it may reach, each with a
  witness chain for the diagnostic message.
- **the lock-order graph**: an edge ``A -> B`` whenever ``B`` is acquired
  (directly or through calls) while ``A`` is held; cycles are potential
  ABBA deadlocks.

Everything is an over/under-approximation in the usual linter sense:
unresolvable calls (``obj.method()`` on unknown types) are dropped, and
attribute locks are merged per class. Both choices keep findings cheap to
verify by hand; docs/static_analysis.md spells out the semantics.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from mmlspark_tpu.analysis.base import FileContext, dotted_name
from mmlspark_tpu.analysis.traced import _module_name

FnKey = Tuple[str, str]  # (path, qualified function name)

_LOCK_CTORS = {
    "threading.Lock": "lock",
    "threading.RLock": "rlock",
    "Lock": "lock",
    "RLock": "rlock",
}
_LOCKISH = ("lock", "mutex")


def _is_lockish(name: str) -> bool:
    low = name.lower()
    return any(part in low for part in _LOCKISH)


# ---------------------------------------------------------------------------
# Blocking-call catalog (superset of lock-discipline's: adds unbounded
# ``.wait()`` — Event.wait()/Popen.wait() without a timeout)
# ---------------------------------------------------------------------------

_NETWORK_PREFIXES = (
    "urllib.request.urlopen", "urlopen", "requests.", "socket.",
    "http.client.",
)
_NETWORK_METHODS = {"recv", "recv_into", "accept", "connect", "urlopen"}


def blocking_reason(call: ast.Call) -> Optional[str]:
    """Why this call can block indefinitely (or long), else None."""
    name = dotted_name(call.func)
    if name is not None:
        if name.startswith("time.") and name.endswith("sleep"):
            return f"{name}()"
        for prefix in _NETWORK_PREFIXES:
            if name == prefix or name.startswith(prefix):
                return f"network call {name}()"
    if not isinstance(call.func, ast.Attribute):
        return None
    attr = call.func.attr
    has_positional = bool(call.args)
    kwargs = {kw.arg for kw in call.keywords}
    if attr == "sleep":
        return "sleep()"
    if attr == "join" and not has_positional and "timeout" not in kwargs:
        # str.join always takes one positional iterable; thread/process
        # join takes none (a deadline arrives as timeout=)
        return "unbounded .join()"
    if attr == "wait" and not has_positional and "timeout" not in kwargs:
        # Event.wait()/Popen.wait()/Condition.wait() with no deadline
        return "unbounded .wait()"
    if attr in ("get", "put") and (
        (not has_positional and not kwargs) or kwargs & {"timeout", "block"}
    ):
        return f"queue .{attr}()"
    if attr in _NETWORK_METHODS:
        return f"network call .{attr}()"
    return None


# ---------------------------------------------------------------------------
# Per-file model
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class LockDef:
    """One lock identity: where it is allocated and what primitive."""

    lock_id: str
    path: str
    line: int
    kind: str  # "lock" | "rlock" | "heuristic"


@dataclasses.dataclass(frozen=True)
class Acq:
    lock_id: str
    line: int
    col: int
    held: Tuple[str, ...]


@dataclasses.dataclass(frozen=True)
class CallSite:
    name: str  # dotted callee text as written
    line: int
    col: int
    held: Tuple[str, ...]


@dataclasses.dataclass(frozen=True)
class Blocking:
    reason: str
    line: int
    col: int
    held: Tuple[str, ...]


class _ClassModel:
    def __init__(self, name: str):
        self.name = name
        self.methods: Dict[str, ast.AST] = {}
        self.attr_locks: Dict[str, str] = {}  # attr -> kind
        self.attr_lock_lines: Dict[str, int] = {}
        self.attr_types: Dict[str, str] = {}  # attr -> callee dotted text


class _FnModel:
    def __init__(self, key: FnKey, node: ast.AST, class_name: Optional[str]):
        self.key = key
        self.node = node
        self.class_name = class_name
        self.acquisitions: List[Acq] = []
        self.calls: List[CallSite] = []
        self.blocking: List[Blocking] = []


class _FileModel:
    """One file's classes, functions, locks, and import maps."""

    def __init__(self, ctx: FileContext):
        self.ctx = ctx
        self.path = ctx.path
        self.module = _module_name(ctx.path)
        self.qual = self.module if self.module is not None else ctx.path
        self.classes: Dict[str, _ClassModel] = {}
        self.functions: Dict[str, _FnModel] = {}  # qualname -> model
        self.module_functions: Dict[str, str] = {}  # bare -> qualname
        self.module_locks: Dict[str, Tuple[str, int]] = {}  # name->(kind,ln)
        self.imports: Dict[str, Tuple[str, str]] = {}
        self.module_imports: Dict[str, str] = {}
        self._collect()

    def _collect(self) -> None:
        for node in ast.walk(self.ctx.tree):
            if isinstance(node, ast.ImportFrom) and node.module:
                for alias in node.names:
                    self.imports[alias.asname or alias.name] = (
                        node.module, alias.name
                    )
            elif isinstance(node, ast.Import):
                for alias in node.names:
                    self.module_imports[
                        alias.asname or alias.name.split(".")[0]
                    ] = alias.name
        for stmt in self.ctx.tree.body:
            if isinstance(stmt, ast.ClassDef):
                self._collect_class(stmt)
            elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.functions[stmt.name] = _FnModel(
                    (self.path, stmt.name), stmt, None
                )
                self.module_functions[stmt.name] = stmt.name
            elif isinstance(stmt, ast.Assign) and len(stmt.targets) == 1:
                target = stmt.targets[0]
                if isinstance(target, ast.Name) and isinstance(
                    stmt.value, ast.Call
                ):
                    ctor = dotted_name(stmt.value.func)
                    if ctor in _LOCK_CTORS:
                        self.module_locks[target.id] = (
                            _LOCK_CTORS[ctor], stmt.lineno
                        )

    def _collect_class(self, cls: ast.ClassDef) -> None:
        model = _ClassModel(cls.name)
        self.classes[cls.name] = model
        for stmt in cls.body:
            if not isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            model.methods[stmt.name] = stmt
            qualname = f"{cls.name}.{stmt.name}"
            self.functions[qualname] = _FnModel(
                (self.path, qualname), stmt, cls.name
            )
            # self.<attr> = threading.Lock() / C(...) anywhere in the class
            for node in ast.walk(stmt):
                if not (
                    isinstance(node, ast.Assign)
                    and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Attribute)
                    and isinstance(node.targets[0].value, ast.Name)
                    and node.targets[0].value.id == "self"
                    and isinstance(node.value, ast.Call)
                ):
                    continue
                attr = node.targets[0].attr
                ctor = dotted_name(node.value.func)
                if ctor in _LOCK_CTORS:
                    model.attr_locks[attr] = _LOCK_CTORS[ctor]
                    model.attr_lock_lines.setdefault(attr, node.lineno)
                elif ctor is not None:
                    model.attr_types.setdefault(attr, ctor)


# ---------------------------------------------------------------------------
# Project model
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class Edge:
    """Lock-order edge: ``dst`` acquired while ``src`` is held."""

    src: str
    dst: str
    path: str
    line: int
    col: int
    via: Tuple[str, ...]  # human-readable call chain, () for direct


@dataclasses.dataclass(frozen=True)
class BlockingFinding:
    """A call site that reaches a blocking call while a lock is held."""

    lock_id: str
    reason: str
    path: str
    line: int
    col: int
    chain: Tuple[str, ...]


@dataclasses.dataclass(frozen=True)
class Cycle:
    """A lock-order cycle, anchored at its smallest edge site."""

    locks: Tuple[str, ...]
    edges: Tuple[Edge, ...]
    path: str
    line: int
    col: int

    def describe(self) -> str:
        ring = " -> ".join(self.locks + (self.locks[0],))
        sites = "; ".join(
            f"{e.src} -> {e.dst} at {e.path}:{e.line}"
            + (f" (via {' -> '.join(e.via)})" if e.via else "")
            for e in self.edges
        )
        return f"{ring} [{sites}]"


class ConcurrencyIndex:
    """Project-wide lock graph + blocking reachability, built once per
    lint run and cached on the driver's TracedIndex."""

    def __init__(self, contexts: Iterable[FileContext]):
        self._files: Dict[str, _FileModel] = {}
        self._by_module: Dict[str, _FileModel] = {}
        for ctx in contexts:
            fm = _FileModel(ctx)
            self._files[ctx.path] = fm
            if fm.module is not None:
                self._by_module[fm.module] = fm
        self.lock_defs: Dict[str, LockDef] = {}
        self._register_lock_defs()
        self._scan_functions()
        self._resolved: Dict[FnKey, List[Tuple[CallSite, FnKey]]] = {}
        self._resolve_calls()
        self._locks_of: Dict[FnKey, Dict[str, Tuple[str, ...]]] = {}
        self._block_of: Dict[FnKey, Dict[str, Tuple[str, ...]]] = {}
        self._fixpoint()
        self.edges: Dict[Tuple[str, str], Edge] = {}
        self._build_edges()
        self._cycles: Optional[List[Cycle]] = None
        self._blocking: Optional[List[BlockingFinding]] = None

    # -- lock identities -------------------------------------------------

    def _register_lock_defs(self) -> None:
        # attr name -> unique (qual, class) owner, for unifying opaque
        # `other._reorder_lock`-style references with their definition;
        # an attr defined as a lock in several classes stays ambiguous
        self._attr_owner: Dict[str, Optional[Tuple[str, str]]] = {}
        for fm in self._files.values():
            for cls in fm.classes.values():
                for attr, kind in cls.attr_locks.items():
                    lid = f"{fm.qual}.{cls.name}.{attr}"
                    self.lock_defs.setdefault(lid, LockDef(
                        lid, fm.path, cls.attr_lock_lines[attr], kind
                    ))
                    if attr in self._attr_owner:
                        self._attr_owner[attr] = None
                    else:
                        self._attr_owner[attr] = (fm.qual, cls.name)
            for name, (kind, line) in fm.module_locks.items():
                lid = f"{fm.qual}.{name}"
                self.lock_defs.setdefault(
                    lid, LockDef(lid, fm.path, line, kind)
                )

    def _lock_id_of(
        self, expr: ast.AST, fm: _FileModel, fn: _FnModel
    ) -> Optional[str]:
        if isinstance(expr, ast.Call):
            expr = expr.func
        name = dotted_name(expr)
        if name is None:
            return None
        parts = name.split(".")
        if parts[0] in ("self", "cls") and len(parts) == 2 and fn.class_name:
            attr = parts[1]
            cls = fm.classes.get(fn.class_name)
            if cls is not None and (
                attr in cls.attr_locks or _is_lockish(attr)
            ):
                return f"{fm.qual}.{fn.class_name}.{attr}"
            return None
        if len(parts) == 1:
            if parts[0] in fm.module_locks or _is_lockish(parts[0]):
                return f"{fm.qual}.{parts[0]}"
            return None
        if parts[0] == "self" and len(parts) == 3 and fn.class_name:
            # self.<attr>.<lock> through self.<attr> = ClassName(...)
            cls = fm.classes.get(fn.class_name)
            type_name = cls.attr_types.get(parts[1]) if cls else None
            if type_name is not None:
                resolved = self._resolve_class(fm, type_name)
                if resolved is not None:
                    other, cname = resolved
                    cm = other.classes[cname]
                    if parts[2] in cm.attr_locks or _is_lockish(parts[2]):
                        return f"{other.qual}.{cname}.{parts[2]}"
        if _is_lockish(parts[-1]):
            owner = self._attr_owner.get(parts[-1])
            if owner is not None:
                # the attr is defined as a lock in exactly one class:
                # unify the reference with that definition
                return f"{owner[0]}.{owner[1]}.{parts[-1]}"
            # opaque attribute path (other object's lock): identity by text
            return f"{fm.qual}:{name}"
        return None

    def lock_kind(self, lock_id: str) -> str:
        d = self.lock_defs.get(lock_id)
        return d.kind if d is not None else "heuristic"

    def lock_sites(self) -> Dict[Tuple[str, int], str]:
        """(package-relative path, line) of each lock allocation ->
        lock id; the runtime witness keys its records the same way."""
        out: Dict[Tuple[str, int], str] = {}
        for d in self.lock_defs.values():
            out[(package_relative(d.path), d.line)] = d.lock_id
        return out

    # -- per-function scan -------------------------------------------------

    def _scan_functions(self) -> None:
        for fm in self._files.values():
            for fn in fm.functions.values():
                body = getattr(fn.node, "body", [])
                for stmt in body:
                    self._visit(stmt, (), fm, fn)

    def _visit(
        self,
        node: ast.AST,
        held: Tuple[str, ...],
        fm: _FileModel,
        fn: _FnModel,
    ) -> None:
        if isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef,
                   ast.Lambda)
        ):
            return  # separate scope: does not run under the current locks
        if isinstance(node, (ast.With, ast.AsyncWith)):
            inner = held
            for item in node.items:
                self._visit(item.context_expr, inner, fm, fn)
                lid = self._lock_id_of(item.context_expr, fm, fn)
                if lid is not None:
                    fn.acquisitions.append(Acq(
                        lid, node.lineno, node.col_offset, inner
                    ))
                    inner = inner + (lid,)
            for stmt in node.body:
                self._visit(stmt, inner, fm, fn)
            return
        if isinstance(node, ast.Call):
            name = dotted_name(node.func)
            if name is not None:
                # `lock.acquire()` outside a with: record the acquisition
                # event (edges from held) without extending the region
                if (
                    isinstance(node.func, ast.Attribute)
                    and node.func.attr == "acquire"
                ):
                    lid = self._lock_id_of(node.func.value, fm, fn)
                    if lid is not None:
                        fn.acquisitions.append(Acq(
                            lid, node.lineno, node.col_offset, held
                        ))
                fn.calls.append(CallSite(
                    name, node.lineno, node.col_offset, held
                ))
            reason = blocking_reason(node)
            if reason is not None:
                fn.blocking.append(Blocking(
                    reason, node.lineno, node.col_offset, held
                ))
        for child in ast.iter_child_nodes(node):
            self._visit(child, held, fm, fn)

    # -- call resolution -----------------------------------------------------

    def _resolve_class(
        self, fm: _FileModel, name: str
    ) -> Optional[Tuple[_FileModel, str]]:
        parts = name.split(".")
        if len(parts) == 1:
            if parts[0] in fm.classes:
                return fm, parts[0]
            if parts[0] in fm.imports:
                mod, item = fm.imports[parts[0]]
                other = self._by_module.get(mod)
                if other is not None and item in other.classes:
                    return other, item
            return None
        if len(parts) == 2 and parts[0] in fm.module_imports:
            other = self._by_module.get(fm.module_imports[parts[0]])
            if other is not None and parts[1] in other.classes:
                return other, parts[1]
        return None

    def _resolve_call(
        self, fm: _FileModel, fn: _FnModel, name: str
    ) -> List[FnKey]:
        parts = name.split(".")
        if parts[0] in ("self", "cls") and fn.class_name:
            cls = fm.classes.get(fn.class_name)
            if cls is None:
                return []
            if len(parts) == 2:
                if parts[1] in cls.methods:
                    return [(fm.path, f"{fn.class_name}.{parts[1]}")]
                return []
            if len(parts) == 3:
                # self._attr.m() through self._attr = ClassName(...)
                type_name = cls.attr_types.get(parts[1])
                if type_name is None:
                    return []
                resolved = self._resolve_class(fm, type_name)
                if resolved is None:
                    return []
                other, cname = resolved
                if parts[2] in other.classes[cname].methods:
                    return [(other.path, f"{cname}.{parts[2]}")]
                return []
            return []
        if len(parts) == 1:
            target = parts[0]
            if target in fm.module_functions:
                return [(fm.path, fm.module_functions[target])]
            if target in fm.imports:
                mod, item = fm.imports[target]
                other = self._by_module.get(mod)
                if other is not None:
                    if item in other.module_functions:
                        return [(other.path, other.module_functions[item])]
            resolved = self._resolve_class(fm, target)
            if resolved is not None:
                other, cname = resolved
                if "__init__" in other.classes[cname].methods:
                    return [(other.path, f"{cname}.__init__")]
            return []
        if len(parts) == 2:
            head, meth = parts
            target_module = None
            if head in fm.module_imports:
                target_module = fm.module_imports[head]
            elif head in fm.imports:
                mod, item = fm.imports[head]
                target_module = f"{mod}.{item}"
                other = self._by_module.get(mod)
                if (
                    other is not None
                    and item in other.classes
                    and meth in other.classes[item].methods
                ):
                    return [(other.path, f"{item}.{meth}")]
            if target_module is not None:
                other = self._by_module.get(target_module)
                if other is not None and meth in other.module_functions:
                    return [(other.path, other.module_functions[meth])]
                return []
            if head in fm.classes and meth in fm.classes[head].methods:
                return [(fm.path, f"{head}.{meth}")]
        return []

    def _resolve_calls(self) -> None:
        for fm in self._files.values():
            for fn in fm.functions.values():
                out: List[Tuple[CallSite, FnKey]] = []
                for site in fn.calls:
                    for key in self._resolve_call(fm, fn, site.name):
                        if key in self._fn_index():
                            out.append((site, key))
                self._resolved[fn.key] = out

    def _fn_index(self) -> Dict[FnKey, _FnModel]:
        cached = getattr(self, "_fn_index_cache", None)
        if cached is None:
            cached = {
                fn.key: fn
                for fm in self._files.values()
                for fn in fm.functions.values()
            }
            self._fn_index_cache = cached
        return cached

    # -- transitive summaries --------------------------------------------

    @staticmethod
    def _chain_entry(key: FnKey, line: int) -> str:
        return f"{key[1]} ({package_relative(key[0])}:{line})"

    def _fixpoint(self) -> None:
        fns = self._fn_index()
        callers: Dict[FnKey, Set[FnKey]] = {}
        for key, edges in self._resolved.items():
            for _site, callee in edges:
                callers.setdefault(callee, set()).add(key)
        for key, fn in fns.items():
            self._locks_of[key] = {
                a.lock_id: (self._chain_entry(key, a.line),)
                for a in fn.acquisitions
            }
            self._block_of[key] = {
                b.reason: (self._chain_entry(key, b.line),)
                for b in fn.blocking
            }
        worklist = list(fns)
        while worklist:
            key = worklist.pop()
            changed = False
            for site, callee in self._resolved.get(key, ()):
                prefix = (self._chain_entry(key, site.line),)
                for lid, chain in self._locks_of.get(callee, {}).items():
                    if lid not in self._locks_of[key]:
                        self._locks_of[key][lid] = prefix + chain
                        changed = True
                for reason, chain in self._block_of.get(callee, {}).items():
                    if reason not in self._block_of[key]:
                        self._block_of[key][reason] = prefix + chain
                        changed = True
            if changed:
                worklist.extend(callers.get(key, ()))

    # -- lock-order graph --------------------------------------------------

    def _add_edge(
        self, src: str, dst: str, path: str, line: int, col: int,
        via: Tuple[str, ...],
    ) -> None:
        if src == dst:
            # re-acquiring an RLock is fine; a non-reentrant self-cycle
            # is reported as a one-lock cycle
            if self.lock_kind(src) != "lock":
                return
        key = (src, dst)
        existing = self.edges.get(key)
        if existing is None or (path, line) < (existing.path, existing.line):
            self.edges[key] = Edge(src, dst, path, line, col, via)

    def _build_edges(self) -> None:
        for fm in self._files.values():
            for fn in fm.functions.values():
                for acq in fn.acquisitions:
                    for held in acq.held:
                        self._add_edge(
                            held, acq.lock_id, fm.path, acq.line,
                            acq.col, (),
                        )
                for site, callee in self._resolved.get(fn.key, ()):
                    if not site.held:
                        continue
                    for lid, chain in self._locks_of.get(callee, {}).items():
                        for held in site.held:
                            self._add_edge(
                                held, lid, fm.path, site.line, site.col,
                                chain,
                            )

    def cycles(self) -> List[Cycle]:
        if self._cycles is None:
            self._cycles = self._find_cycles()
        return self._cycles

    def _find_cycles(self) -> List[Cycle]:
        graph: Dict[str, Set[str]] = {}
        for (src, dst) in self.edges:
            graph.setdefault(src, set()).add(dst)
            graph.setdefault(dst, set())
        out: List[Cycle] = []
        for scc in _tarjan(graph):
            if len(scc) == 1:
                node = next(iter(scc))
                if (node, node) not in self.edges:
                    continue
                ring = [node, node]
            else:
                ring = self._cycle_in_scc(graph, scc)
                if ring is None:
                    continue
            edges = tuple(
                self.edges[(ring[i], ring[i + 1])]
                for i in range(len(ring) - 1)
            )
            anchor = min(edges, key=lambda e: (e.path, e.line, e.col))
            out.append(Cycle(
                tuple(ring[:-1]), edges, anchor.path, anchor.line,
                anchor.col,
            ))
        out.sort(key=lambda c: (c.path, c.line, c.col))
        return out

    @staticmethod
    def _cycle_in_scc(
        graph: Dict[str, Set[str]], scc: Set[str]
    ) -> Optional[List[str]]:
        start = min(scc)
        stack: List[Tuple[str, List[str]]] = [(start, [start])]
        seen: Set[str] = set()
        while stack:
            node, trail = stack.pop()
            for nxt in sorted(graph.get(node, ())):
                if nxt not in scc:
                    continue
                if nxt == start:
                    return trail + [start]
                if nxt not in seen:
                    seen.add(nxt)
                    stack.append((nxt, trail + [nxt]))
        return None

    # -- blocking reachability ---------------------------------------------

    def blocking_findings(self) -> List[BlockingFinding]:
        """Call sites under a held lock that *transitively* reach a
        blocking call (direct blocking inside the with-body is
        lock-discipline's finding, not repeated here)."""
        if self._blocking is not None:
            return self._blocking
        out: List[BlockingFinding] = []
        seen: Set[Tuple[str, int, str, str]] = set()
        for fm in self._files.values():
            for fn in fm.functions.values():
                for site, callee in self._resolved.get(fn.key, ()):
                    if not site.held:
                        continue
                    for reason, chain in self._block_of.get(
                        callee, {}
                    ).items():
                        for held in site.held:
                            key = (fm.path, site.line, held, reason)
                            if key in seen:
                                continue
                            seen.add(key)
                            out.append(BlockingFinding(
                                held, reason, fm.path, site.line,
                                site.col, chain,
                            ))
        out.sort(key=lambda f: (f.path, f.line, f.col, f.lock_id))
        self._blocking = out
        return out

    # -- misc ------------------------------------------------------------

    def file_model(self, path: str) -> Optional[_FileModel]:
        return self._files.get(path)


def _tarjan(graph: Dict[str, Set[str]]) -> List[Set[str]]:
    """Strongly connected components (iterative Tarjan)."""
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on_stack: Set[str] = set()
    stack: List[str] = []
    sccs: List[Set[str]] = []
    counter = [0]

    for root in graph:
        if root in index:
            continue
        work: List[Tuple[str, int]] = [(root, 0)]
        while work:
            node, pi = work[-1]
            if pi == 0:
                index[node] = low[node] = counter[0]
                counter[0] += 1
                stack.append(node)
                on_stack.add(node)
            advanced = False
            succ = sorted(graph.get(node, ()))
            for i in range(pi, len(succ)):
                nxt = succ[i]
                if nxt not in index:
                    work[-1] = (node, i + 1)
                    work.append((nxt, 0))
                    advanced = True
                    break
                if nxt in on_stack:
                    low[node] = min(low[node], index[nxt])
            if advanced:
                continue
            work.pop()
            if low[node] == index[node]:
                scc: Set[str] = set()
                while True:
                    top = stack.pop()
                    on_stack.discard(top)
                    scc.add(top)
                    if top == node:
                        break
                sccs.append(scc)
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
    return sccs


def package_relative(path: str) -> str:
    """Path from the last ``mmlspark_tpu`` segment on (stable across
    checkouts; the witness normalizes its allocation sites the same way)."""
    parts = path.replace("\\", "/").split("/")
    if "mmlspark_tpu" in parts:
        i = len(parts) - 1 - parts[::-1].index("mmlspark_tpu")
        return "/".join(parts[i:])
    return path.replace("\\", "/")


def concurrency_index(ctx: FileContext) -> ConcurrencyIndex:
    """The project-wide index for this lint run, cached on the driver's
    TracedIndex (single-file fallback for lint_source / unit tests)."""
    tindex = getattr(ctx, "traced_index", None)
    if tindex is None:
        from mmlspark_tpu.analysis.traced import TracedIndex

        tindex = TracedIndex([ctx])
        ctx.traced_index = tindex
    cached = getattr(tindex, "_concurrency_index", None)
    if cached is None:
        contexts = [fi.ctx for fi in tindex._files.values()]
        if ctx.path not in tindex._files:
            contexts.append(ctx)
        cached = ConcurrencyIndex(contexts)
        tindex._concurrency_index = cached
    return cached
