"""graftlint driver + CLI: ``python -m mmlspark_tpu.analysis.lint <paths>``.

Two-phase run: parse every file first (so the traced-function index and
the concurrency index see the whole project and cross-module
reachability works — see ``analysis/traced.py`` and
``analysis/lockgraph.py``), then run every rule over every file,
dropping findings the source suppresses per line
(``# graftlint: disable=<rule>``).

Beyond the plain run:

- ``--format sarif`` prints a SARIF 2.1.0 document instead of text, and
  ``--output FILE`` additionally writes SARIF to a file (CI artifact)
  whatever the stdout format;
- ``--check-suppressions`` audits every ``# graftlint: disable=``
  comment and fails on the stale ones (a suppression that no longer
  suppresses anything is a lie waiting to hide a real finding);
- ``--witness-check PATH`` loads runtime lock-witness reports
  (``analysis/witness.py``) and cross-checks observed acquisition
  orders against the static lock graph.

Exit status: 0 when clean, 1 on violations (``--fail-on-violation`` is
accepted for explicitness in CI, it is the default behavior), 2 on usage
or parse errors.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from mmlspark_tpu.analysis.base import FileContext, Violation, all_rules
from mmlspark_tpu.analysis.traced import TracedIndex

_SKIP_DIRS = {".git", "__pycache__", ".venv", "node_modules", "build"}


def discover_files(paths: Sequence[str]) -> List[str]:
    out: List[str] = []
    for path in paths:
        if os.path.isfile(path):
            out.append(path)
        elif os.path.isdir(path):
            for root, dirs, files in os.walk(path):
                dirs[:] = sorted(d for d in dirs if d not in _SKIP_DIRS)
                out.extend(
                    os.path.join(root, f)
                    for f in sorted(files)
                    if f.endswith(".py")
                )
        else:
            raise FileNotFoundError(path)
    return out


def _load_contexts(
    files: Iterable[str],
) -> Tuple[List[FileContext], List[str]]:
    contexts, errors = [], []
    for path in files:
        try:
            with open(path, "r", encoding="utf-8") as f:
                source = f.read()
            contexts.append(FileContext(path, source))
        except (OSError, SyntaxError, ValueError) as e:
            errors.append(f"{path}: {e}")
    return contexts, errors


def _run_rules(
    contexts: List[FileContext],
    select: Optional[Sequence[str]] = None,
    ignore: Sequence[str] = (),
) -> Tuple[List[Violation], List[Violation]]:
    """Run the rule set; returns (violations, suppressed_violations)."""
    rules = all_rules()
    unknown = [
        r for r in list(select or []) + list(ignore) if r not in rules
    ]
    if unknown:
        raise KeyError(f"unknown rule(s): {', '.join(unknown)}")
    active = [
        cls()
        for name, cls in sorted(rules.items())
        if (select is None or name in select) and name not in ignore
    ]
    index = TracedIndex(contexts)
    for ctx in contexts:
        ctx.traced_index = index
    violations: List[Violation] = []
    suppressed: List[Violation] = []
    for ctx in contexts:
        for rule in active:
            for v in rule.check(ctx):
                if ctx.suppressed(v.rule, v.line):
                    suppressed.append(v)
                else:
                    violations.append(v)
    violations.sort(key=lambda v: (v.path, v.line, v.col, v.rule))
    return violations, suppressed


def lint_contexts(
    contexts: List[FileContext],
    select: Optional[Sequence[str]] = None,
    ignore: Sequence[str] = (),
) -> Tuple[List[Violation], int]:
    """Run the rule set; returns (violations, suppressed_count)."""
    violations, suppressed = _run_rules(contexts, select, ignore)
    return violations, len(suppressed)


def lint_paths(
    paths: Sequence[str],
    select: Optional[Sequence[str]] = None,
    ignore: Sequence[str] = (),
) -> Tuple[List[Violation], int, List[str]]:
    """Lint files/directories; returns (violations, suppressed, errors)."""
    contexts, errors = _load_contexts(discover_files(paths))
    violations, suppressed = lint_contexts(contexts, select, ignore)
    return violations, suppressed, errors


def lint_source(
    source: str,
    path: str = "<string>",
    select: Optional[Sequence[str]] = None,
) -> List[Violation]:
    """Lint one in-memory source string (tests / tooling)."""
    violations, _ = lint_contexts([FileContext(path, source)], select)
    return violations


def stale_suppressions(
    contexts: List[FileContext], suppressed: List[Violation]
) -> List[str]:
    """``path:line: ...`` report lines for every ``# graftlint:
    disable=`` entry that suppressed nothing in this run (run the full
    rule set: a suppression is only provably stale against every rule).
    """
    consumed: Dict[Tuple[str, int], Set[str]] = {}
    for v in suppressed:
        consumed.setdefault((v.path, v.line), set()).add(v.rule)
    known = set(all_rules())
    out: List[str] = []
    for ctx in contexts:
        for line, names in sorted(ctx.suppressions.items()):
            used = consumed.get((ctx.path, line), set())
            for name in sorted(names):
                if name == "*":
                    if not used:
                        out.append(
                            f"{ctx.path}:{line}: stale blanket suppression "
                            "(# graftlint: disable) — no rule fires here"
                        )
                elif name not in known:
                    out.append(
                        f"{ctx.path}:{line}: suppression names unknown "
                        f"rule '{name}'"
                    )
                elif name not in used:
                    out.append(
                        f"{ctx.path}:{line}: stale suppression '{name}' — "
                        "the rule no longer fires here"
                    )
    return out


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m mmlspark_tpu.analysis.lint",
        description="graftlint: framework-aware static analysis "
        "(docs/static_analysis.md)",
    )
    parser.add_argument("paths", nargs="*", help="files or directories")
    parser.add_argument(
        "--fail-on-violation",
        action="store_true",
        help="exit 1 on violations (the default; accepted for explicit CI "
        "wiring)",
    )
    parser.add_argument(
        "--select", action="append", default=None, metavar="RULE",
        help="run only the named rule (repeatable)",
    )
    parser.add_argument(
        "--ignore", action="append", default=[], metavar="RULE",
        help="skip the named rule (repeatable)",
    )
    parser.add_argument(
        "--list-rules", action="store_true", help="print the rule catalog"
    )
    parser.add_argument(
        "--format", choices=("text", "sarif"), default="text",
        help="stdout format: human text (default) or a SARIF 2.1.0 "
        "document",
    )
    parser.add_argument(
        "--output", metavar="FILE", default=None,
        help="additionally write SARIF to FILE (CI artifact), whatever "
        "the stdout format",
    )
    parser.add_argument(
        "--check-suppressions", action="store_true",
        help="audit # graftlint: disable= comments; exit 1 when any no "
        "longer suppresses a finding (requires the full rule set)",
    )
    parser.add_argument(
        "--witness-check", action="append", default=[], metavar="PATH",
        help="lock-witness report file/directory (MMLSPARK_TPU_LOCKCHECK "
        "dumps); cross-checks observed lock orders against the static "
        "lock graph (repeatable)",
    )
    parser.add_argument(
        "-q", "--quiet", action="store_true",
        help="print only the summary line",
    )
    args = parser.parse_args(argv)

    if args.list_rules:
        for name, cls in sorted(all_rules().items()):
            print(f"{name}: {cls.description}")
        return 0
    if not args.paths:
        parser.print_usage(sys.stderr)
        return 2
    if args.check_suppressions and (args.select or args.ignore):
        print(
            "graftlint: --check-suppressions needs the full rule set; "
            "drop --select/--ignore",
            file=sys.stderr,
        )
        return 2

    try:
        contexts, errors = _load_contexts(discover_files(args.paths))
        violations, suppressed = _run_rules(
            contexts, select=args.select, ignore=args.ignore
        )
    except (FileNotFoundError, KeyError) as e:
        print(f"graftlint: {e}", file=sys.stderr)
        return 2

    extra_rules = None
    if args.witness_check:
        from mmlspark_tpu.analysis.witness import (
            WITNESS_RULE,
            WITNESS_RULE_DESCRIPTION,
            check_witness,
            load_reports,
        )

        try:
            reports = load_reports(args.witness_check)
        except (OSError, ValueError) as e:
            print(f"graftlint: witness report: {e}", file=sys.stderr)
            return 2
        witness_violations = check_witness(reports, contexts)
        violations.extend(witness_violations)
        violations.sort(key=lambda v: (v.path, v.line, v.col, v.rule))
        extra_rules = {WITNESS_RULE: WITNESS_RULE_DESCRIPTION}
        print(
            f"graftlint: witness: {len(reports)} report(s), "
            f"{len(witness_violations)} inconsistenc"
            + ("y" if len(witness_violations) == 1 else "ies"),
            file=sys.stderr,
        )

    stale: List[str] = []
    if args.check_suppressions:
        stale = stale_suppressions(contexts, suppressed)

    for err in errors:
        print(f"graftlint: parse error: {err}", file=sys.stderr)

    if args.output or args.format == "sarif":
        from mmlspark_tpu.analysis.sarif import to_sarif

        doc = to_sarif(violations, extra_rules=extra_rules)
        if args.output:
            with open(args.output, "w", encoding="utf-8") as fh:
                json.dump(doc, fh, indent=2, sort_keys=True)
                fh.write("\n")
        if args.format == "sarif":
            json.dump(doc, sys.stdout, indent=2, sort_keys=True)
            sys.stdout.write("\n")

    if args.format == "text" and not args.quiet:
        for v in violations:
            print(v.render())
    for line in stale:
        print(line)
    note = f", {len(suppressed)} suppressed" if suppressed else ""
    stale_note = f", {len(stale)} stale suppression(s)" if stale else ""
    summary = (
        f"graftlint: {len(violations)} violation(s){note}{stale_note}"
        + (f", {len(errors)} parse error(s)" if errors else "")
    )
    print(summary, file=sys.stderr if args.format == "sarif" else sys.stdout)
    if errors:
        return 2
    return 1 if (violations or stale) else 0


if __name__ == "__main__":
    sys.exit(main())
