"""graftlint driver + CLI: ``python -m mmlspark_tpu.analysis.lint <paths>``.

Two-phase run: parse every file first (so the traced-function index sees
the whole project and cross-module jit reachability works — see
``analysis/traced.py``), then run every rule over every file, dropping
findings the source suppresses per line
(``# graftlint: disable=<rule>``).

Exit status: 0 when clean, 1 on violations (``--fail-on-violation`` is
accepted for explicitness in CI, it is the default behavior), 2 on usage
or parse errors.
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import Iterable, List, Optional, Sequence, Tuple

from mmlspark_tpu.analysis.base import FileContext, Violation, all_rules
from mmlspark_tpu.analysis.traced import TracedIndex

_SKIP_DIRS = {".git", "__pycache__", ".venv", "node_modules", "build"}


def discover_files(paths: Sequence[str]) -> List[str]:
    out: List[str] = []
    for path in paths:
        if os.path.isfile(path):
            out.append(path)
        elif os.path.isdir(path):
            for root, dirs, files in os.walk(path):
                dirs[:] = sorted(d for d in dirs if d not in _SKIP_DIRS)
                out.extend(
                    os.path.join(root, f)
                    for f in sorted(files)
                    if f.endswith(".py")
                )
        else:
            raise FileNotFoundError(path)
    return out


def _load_contexts(
    files: Iterable[str],
) -> Tuple[List[FileContext], List[str]]:
    contexts, errors = [], []
    for path in files:
        try:
            with open(path, "r", encoding="utf-8") as f:
                source = f.read()
            contexts.append(FileContext(path, source))
        except (OSError, SyntaxError, ValueError) as e:
            errors.append(f"{path}: {e}")
    return contexts, errors


def lint_contexts(
    contexts: List[FileContext],
    select: Optional[Sequence[str]] = None,
    ignore: Sequence[str] = (),
) -> Tuple[List[Violation], int]:
    """Run the rule set; returns (violations, suppressed_count)."""
    rules = all_rules()
    unknown = [
        r for r in list(select or []) + list(ignore) if r not in rules
    ]
    if unknown:
        raise KeyError(f"unknown rule(s): {', '.join(unknown)}")
    active = [
        cls()
        for name, cls in sorted(rules.items())
        if (select is None or name in select) and name not in ignore
    ]
    index = TracedIndex(contexts)
    for ctx in contexts:
        ctx.traced_index = index
    violations: List[Violation] = []
    suppressed = 0
    for ctx in contexts:
        for rule in active:
            for v in rule.check(ctx):
                if ctx.suppressed(v.rule, v.line):
                    suppressed += 1
                else:
                    violations.append(v)
    violations.sort(key=lambda v: (v.path, v.line, v.col, v.rule))
    return violations, suppressed


def lint_paths(
    paths: Sequence[str],
    select: Optional[Sequence[str]] = None,
    ignore: Sequence[str] = (),
) -> Tuple[List[Violation], int, List[str]]:
    """Lint files/directories; returns (violations, suppressed, errors)."""
    contexts, errors = _load_contexts(discover_files(paths))
    violations, suppressed = lint_contexts(contexts, select, ignore)
    return violations, suppressed, errors


def lint_source(
    source: str,
    path: str = "<string>",
    select: Optional[Sequence[str]] = None,
) -> List[Violation]:
    """Lint one in-memory source string (tests / tooling)."""
    violations, _ = lint_contexts([FileContext(path, source)], select)
    return violations


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m mmlspark_tpu.analysis.lint",
        description="graftlint: framework-aware static analysis "
        "(docs/static_analysis.md)",
    )
    parser.add_argument("paths", nargs="*", help="files or directories")
    parser.add_argument(
        "--fail-on-violation",
        action="store_true",
        help="exit 1 on violations (the default; accepted for explicit CI "
        "wiring)",
    )
    parser.add_argument(
        "--select", action="append", default=None, metavar="RULE",
        help="run only the named rule (repeatable)",
    )
    parser.add_argument(
        "--ignore", action="append", default=[], metavar="RULE",
        help="skip the named rule (repeatable)",
    )
    parser.add_argument(
        "--list-rules", action="store_true", help="print the rule catalog"
    )
    parser.add_argument(
        "-q", "--quiet", action="store_true",
        help="print only the summary line",
    )
    args = parser.parse_args(argv)

    if args.list_rules:
        for name, cls in sorted(all_rules().items()):
            print(f"{name}: {cls.description}")
        return 0
    if not args.paths:
        parser.print_usage(sys.stderr)
        return 2

    try:
        violations, suppressed, errors = lint_paths(
            args.paths, select=args.select, ignore=args.ignore
        )
    except (FileNotFoundError, KeyError) as e:
        print(f"graftlint: {e}", file=sys.stderr)
        return 2

    for err in errors:
        print(f"graftlint: parse error: {err}", file=sys.stderr)
    if not args.quiet:
        for v in violations:
            print(v.render())
    note = f", {suppressed} suppressed" if suppressed else ""
    print(
        f"graftlint: {len(violations)} violation(s){note}"
        + (f", {len(errors)} parse error(s)" if errors else "")
    )
    if errors:
        return 2
    return 1 if violations else 0


if __name__ == "__main__":
    sys.exit(main())
