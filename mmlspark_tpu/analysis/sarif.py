"""SARIF 2.1.0 serialization of graftlint findings.

The shape CI annotators consume (GitHub code scanning, sarif-tools):
one run, one driver (``graftlint``), a rule catalog restricted to the
rules that actually fired plus anything the caller passes, and one
result per violation with a physical location. Deliberately minimal —
no fixes, no code flows — so the document stays diffable in CI
artifacts.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional

from mmlspark_tpu.analysis.base import Violation, all_rules

_SCHEMA = (
    "https://raw.githubusercontent.com/oasis-tcs/sarif-spec/master/"
    "Schemata/sarif-schema-2.1.0.json"
)


def to_sarif(
    violations: Iterable[Violation],
    tool_version: str = "2.0",
    extra_rules: Optional[Dict[str, str]] = None,
) -> dict:
    """SARIF document (as a plain dict) for one lint run."""
    violations = list(violations)
    catalog = {name: cls.description for name, cls in all_rules().items()}
    if extra_rules:
        catalog.update(extra_rules)
    fired = sorted({v.rule for v in violations})
    rules: List[dict] = [
        {
            "id": rule,
            "shortDescription": {
                "text": catalog.get(rule, rule).split(". ")[0]
            },
            "fullDescription": {"text": catalog.get(rule, rule)},
        }
        for rule in fired
    ]
    rule_index = {rule: i for i, rule in enumerate(fired)}
    results = [
        {
            "ruleId": v.rule,
            "ruleIndex": rule_index[v.rule],
            "level": "error",
            "message": {"text": v.message},
            "locations": [
                {
                    "physicalLocation": {
                        "artifactLocation": {
                            "uri": v.path.replace("\\", "/"),
                        },
                        "region": {
                            "startLine": max(v.line, 1),
                            "startColumn": v.col + 1,
                        },
                    }
                }
            ],
        }
        for v in violations
    ]
    return {
        "$schema": _SCHEMA,
        "version": "2.1.0",
        "runs": [
            {
                "tool": {
                    "driver": {
                        "name": "graftlint",
                        "informationUri": "docs/static_analysis.md",
                        "version": tool_version,
                        "rules": rules,
                    }
                },
                "results": results,
            }
        ],
    }
