"""graftlint rule plumbing: violations, per-file context, rule registry.

A rule is a class with a ``name``, a ``description``, and a
``check(ctx) -> Iterator[Violation]``. Rules self-register via
:func:`register_rule` (the same registry-by-declaration idiom as the stage
registry in ``core/params.py``), so adding a rule is: subclass
:class:`Rule` in ``rules.py``, decorate, done — the CLI and the tests pick
it up automatically.

Suppression is per line: ``# graftlint: disable=<rule>[,<rule>]`` on the
offending line (or the line a multi-line statement starts on) silences the
named rules; a bare ``# graftlint: disable`` silences all of them. Each
rule may additionally honor domain noqa codes (the bare-except rule
accepts ``# noqa: BLE001``).
"""

from __future__ import annotations

import ast
import dataclasses
import io
import re
import tokenize
from typing import Dict, Iterator, List, Optional, Set, Type

_SUPPRESS_RE = re.compile(r"#\s*graftlint:\s*disable(?:=([\w\-, ]+))?")


@dataclasses.dataclass(frozen=True)
class Violation:
    """One finding: rule id, location, and a human-actionable message."""

    rule: str
    path: str
    line: int
    col: int
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: [{self.rule}] {self.message}"


class FileContext:
    """Parsed view of one source file shared by every rule."""

    def __init__(self, path: str, source: str):
        self.path = path
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        self.suppressions = self._parse_suppressions(source)

    @staticmethod
    def _parse_suppressions(source: str) -> Dict[int, Set[str]]:
        # Tokenize so only genuine comments count: docstrings or string
        # literals that merely *mention* the disable syntax must neither
        # silence findings on their line nor show up as stale suppressions.
        out: Dict[int, Set[str]] = {}
        try:
            tokens = list(tokenize.generate_tokens(io.StringIO(source).readline))
        except (tokenize.TokenError, IndentationError):
            return out
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            m = _SUPPRESS_RE.search(tok.string)
            if not m:
                continue
            names = m.group(1)
            i = tok.start[0]
            if names is None:
                out[i] = {"*"}
            else:
                out[i] = {n.strip() for n in names.split(",") if n.strip()}
        return out

    def suppressed(self, rule: str, line: int) -> bool:
        names = self.suppressions.get(line)
        return bool(names) and ("*" in names or rule in names)

    def line_text(self, line: int) -> str:
        if 1 <= line <= len(self.lines):
            return self.lines[line - 1]
        return ""


class Rule:
    """Base of all graftlint rules."""

    name: str = ""
    description: str = ""

    def check(self, ctx: FileContext) -> Iterator[Violation]:
        raise NotImplementedError

    def violation(
        self, ctx: FileContext, node: ast.AST, message: str
    ) -> Violation:
        return Violation(
            rule=self.name,
            path=ctx.path,
            line=getattr(node, "lineno", 0),
            col=getattr(node, "col_offset", 0),
            message=message,
        )


_RULE_REGISTRY: Dict[str, Type[Rule]] = {}


def register_rule(cls: Type[Rule]) -> Type[Rule]:
    if not cls.name:
        raise ValueError(f"rule {cls.__name__} must declare a name")
    _RULE_REGISTRY[cls.name] = cls
    return cls


def all_rules() -> Dict[str, Type[Rule]]:
    # Imports trigger registration of the builtin rule set.
    from mmlspark_tpu.analysis import concurrency as _concurrency  # noqa: F401
    from mmlspark_tpu.analysis import rules as _rules  # noqa: F401

    return dict(_RULE_REGISTRY)


# ---------------------------------------------------------------------------
# Shared AST helpers
# ---------------------------------------------------------------------------


def dotted_name(node: ast.AST) -> Optional[str]:
    """``jax.numpy.sum`` for nested Attribute/Name chains, else None."""
    parts: List[str] = []
    cur = node
    while isinstance(cur, ast.Attribute):
        parts.append(cur.attr)
        cur = cur.value
    if isinstance(cur, ast.Name):
        parts.append(cur.id)
        return ".".join(reversed(parts))
    return None


def module_int_constants(tree: ast.Module) -> Dict[str, int]:
    """Module-level ``NAME = <int literal>`` bindings (tile-size constants
    like ``_LANE = 128``), including simple aliases of earlier constants."""
    consts: Dict[str, int] = {}
    for node in tree.body:
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        target = node.targets[0]
        if not isinstance(target, ast.Name):
            continue
        value = _resolve_int(node.value, consts)
        if value is not None:
            consts[target.id] = value
    return consts


def _resolve_int(node: ast.AST, env: Dict[str, int]) -> Optional[int]:
    if isinstance(node, ast.Constant) and type(node.value) is int:
        return node.value
    if isinstance(node, ast.Name):
        return env.get(node.id)
    if isinstance(node, ast.BinOp) and isinstance(
        node.op, (ast.Mult, ast.Add, ast.Sub, ast.FloorDiv)
    ):
        lhs = _resolve_int(node.left, env)
        rhs = _resolve_int(node.right, env)
        if lhs is None or rhs is None:
            return None
        if isinstance(node.op, ast.Mult):
            return lhs * rhs
        if isinstance(node.op, ast.Add):
            return lhs + rhs
        if isinstance(node.op, ast.Sub):
            return lhs - rhs
        return lhs // rhs if rhs else None
    return None


def local_int_constants(
    func: ast.AST, module_consts: Dict[str, int]
) -> Dict[str, int]:
    """Function-local single-assignment int bindings layered over the
    module constants (resolves ``tn = _N_ALIGN`` inside a kernel builder)."""
    env = dict(module_consts)
    assigned_twice: Set[str] = set()
    for node in ast.walk(func):
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        target = node.targets[0]
        if not isinstance(target, ast.Name):
            continue
        if target.id in assigned_twice:
            env.pop(target.id, None)
            continue
        value = _resolve_int(node.value, env)
        if target.id in env and env.get(target.id) != value:
            assigned_twice.add(target.id)
            env.pop(target.id, None)
            continue
        if value is not None:
            env[target.id] = value
        assigned_twice.add(target.id)
    return env


def resolve_int(node: ast.AST, env: Dict[str, int]) -> Optional[int]:
    return _resolve_int(node, env)
