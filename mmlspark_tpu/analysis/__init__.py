"""Framework-aware static analysis (``graftlint``) + pipeline schema checks.

The SparkML side of the reference gets its composability guarantees from
``transformSchema`` — a mis-wired ``Pipeline`` fails before any executor
runs. This package is the reproduction's equivalent static layer, with two
halves:

- :mod:`mmlspark_tpu.analysis.lint` (``graftlint``): an AST-driven linter
  enforcing the framework's implicit contracts — jit purity, jnp-vs-np in
  traced code, (8, 128) Pallas tile alignment, lock discipline in the
  threaded runtime/serving layers, and the bare-except policy. Run as
  ``python -m mmlspark_tpu.analysis.lint <paths>``.
- the pipeline schema validator: stages declare ``transform_schema`` and
  ``Pipeline.validate()`` propagates column schemas through the stage
  graph at construction time (:mod:`mmlspark_tpu.core.schema`).

Docs: ``docs/static_analysis.md`` (rule catalog, suppression syntax,
adding a rule).
"""

from mmlspark_tpu.analysis.base import (
    FileContext,
    Rule,
    Violation,
    all_rules,
    register_rule,
)

__all__ = [
    "FileContext",
    "Rule",
    "Violation",
    "all_rules",
    "register_rule",
    "lint_paths",
    "lint_source",
]


def __getattr__(name):
    # Lazy so `python -m mmlspark_tpu.analysis.lint` doesn't trip runpy's
    # already-in-sys.modules warning by importing the CLI module here.
    if name in ("lint_paths", "lint_source"):
        from mmlspark_tpu.analysis import lint

        return getattr(lint, name)
    raise AttributeError(name)
