"""Framework-aware static analysis (``graftlint``) + pipeline schema checks.

The SparkML side of the reference gets its composability guarantees from
``transformSchema`` — a mis-wired ``Pipeline`` fails before any executor
runs. This package is the reproduction's equivalent static layer, with two
halves:

- :mod:`mmlspark_tpu.analysis.lint` (``graftlint``): an AST-driven linter
  enforcing the framework's implicit contracts — jit purity, jnp-vs-np in
  traced code, (8, 128) Pallas tile alignment, lock discipline in the
  threaded runtime/serving layers, and the bare-except policy. Run as
  ``python -m mmlspark_tpu.analysis.lint <paths>``.
- the whole-program concurrency & protocol analyzer
  (:mod:`mmlspark_tpu.analysis.lockgraph`,
  :mod:`mmlspark_tpu.analysis.concurrency`): interprocedural lock-order
  cycles (ABBA deadlocks), blocking calls under locks, collective
  deadline/rank-uniformity checks, and WAL/journal/tmp+rename protocol
  ordering — backed by the cross-module reachability index in
  :mod:`mmlspark_tpu.analysis.traced` and cross-checked at runtime by the
  lock witness (:mod:`mmlspark_tpu.analysis.witness`,
  ``MMLSPARK_TPU_LOCKCHECK=1``).
- the pipeline schema validator: stages declare ``transform_schema`` and
  ``Pipeline.validate()`` propagates column schemas through the stage
  graph at construction time (:mod:`mmlspark_tpu.core.schema`).

Docs: ``docs/static_analysis.md`` (rule catalog, suppression syntax,
adding a rule).
"""

from mmlspark_tpu.analysis.base import (
    FileContext,
    Rule,
    Violation,
    all_rules,
    register_rule,
)

__all__ = [
    "FileContext",
    "Rule",
    "Violation",
    "all_rules",
    "register_rule",
    "lint_paths",
    "lint_source",
    "ConcurrencyIndex",
    "LockWitness",
    "install_from_env",
    "check_witness",
    "load_reports",
    "to_sarif",
]


def __getattr__(name):
    # Lazy so `python -m mmlspark_tpu.analysis.lint` doesn't trip runpy's
    # already-in-sys.modules warning by importing the CLI module here.
    if name in ("lint_paths", "lint_source"):
        from mmlspark_tpu.analysis import lint

        return getattr(lint, name)
    if name == "ConcurrencyIndex":
        from mmlspark_tpu.analysis.lockgraph import ConcurrencyIndex

        return ConcurrencyIndex
    if name in ("LockWitness", "install_from_env", "check_witness",
                "load_reports"):
        from mmlspark_tpu.analysis import witness

        return getattr(witness, name)
    if name == "to_sarif":
        from mmlspark_tpu.analysis.sarif import to_sarif

        return to_sarif
    raise AttributeError(name)
