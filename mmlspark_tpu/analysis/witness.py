"""Runtime lock witness: record real acquisition orders, cross-check
the static lock graph (``MMLSPARK_TPU_LOCKCHECK=1``).

The static analyzer (``analysis/lockgraph.py``) proves the *absence* of
lock-order cycles it can see; the witness catches what static analysis
cannot — orders taken through callbacks, reflection, or code paths the
resolver gives up on. The shim patches ``threading.Lock``/``RLock`` with
factories that wrap locks **allocated inside the mmlspark_tpu package**
(identified by walking the allocation stack; everything else gets the
raw primitive, so stdlib/jax behavior is untouched). Each wrapped lock's
identity is its allocation site ``<package-relative path>:<line>`` —
exactly the site of the static model's ``LockDef``, so witnessed edges
and static edges land in one graph.

Per-thread held stacks live in a ``threading.local``; every successful
acquire records an edge ``held-site -> new-site``. At process exit the
report dumps as JSON (tmp+rename — we practice what we lint) to
``$MMLSPARK_TPU_LOCKCHECK_OUT/lockwitness-<pid>.json``, one file per
process so gang members never clobber each other.

Cross-check (``python -m mmlspark_tpu.analysis.lint --witness-check
<dir-or-file> <paths>``):

1. witnessed inversion — both ``A -> B`` and ``B -> A`` observed at
   runtime (two instances of the same classes locked in opposite orders
   count: the static graph merges instances per class, and so does the
   witness);
2. a witnessed edge closes a cycle when merged with the static graph
   (the static side saw ``A -> B``, the run took ``B -> A``).

Both emit rule id ``lock-witness``.
"""

from __future__ import annotations

import atexit
import json
import os
import sys
import threading
from typing import Dict, Iterable, List, Optional, Tuple

_ORIG_LOCK = threading.Lock
_ORIG_RLOCK = threading.RLock

WITNESS_RULE = "lock-witness"
WITNESS_RULE_DESCRIPTION = (
    "A lock acquisition order observed at runtime (MMLSPARK_TPU_"
    "LOCKCHECK=1) contradicts itself or the static lock graph: two "
    "locks were taken in both orders, which is an ABBA deadlock waiting "
    "for the right interleaving."
)


def _normalize(path: str) -> str:
    """Package-relative path: from the last ``mmlspark_tpu`` segment on
    (mirrors lockgraph.package_relative, duplicated so importing the
    witness never drags in the analyzer)."""
    parts = path.replace("\\", "/").split("/")
    if "mmlspark_tpu" in parts:
        i = len(parts) - 1 - parts[::-1].index("mmlspark_tpu")
        return "/".join(parts[i:])
    return path.replace("\\", "/")


class _WitnessedLock:
    """Thin wrapper delegating to the real primitive; records every
    successful acquire/release against the shared witness."""

    __slots__ = ("_lk", "_site", "_witness", "_kind")

    def __init__(self, lk, site: str, witness: "LockWitness", kind: str):
        self._lk = lk
        self._site = site
        self._witness = witness
        self._kind = kind

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        ok = self._lk.acquire(blocking, timeout)
        if ok:
            self._witness._record_acquire(self._site, self._kind)
        return ok

    def release(self) -> None:
        self._witness._record_release(self._site)
        self._lk.release()

    def locked(self) -> bool:
        return self._lk.locked()

    def __enter__(self) -> "_WitnessedLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> bool:
        self.release()
        return False

    def __repr__(self) -> str:
        return f"<witnessed {self._kind} @ {self._site}>"


class LockWitness:
    """Process-wide acquisition-order recorder."""

    def __init__(self, package_markers: Tuple[str, ...] = ("mmlspark_tpu",)):
        self.package_markers = package_markers
        self._mu = _ORIG_LOCK()
        self._edges: Dict[Tuple[str, str], int] = {}
        self._sites: Dict[str, str] = {}  # site -> kind
        self._tls = threading.local()
        self._installed = False

    # -- factory side ------------------------------------------------------

    def _alloc_site(self) -> Optional[str]:
        """Allocation site of the Lock() call when it is inside one of
        the marked packages, else None (leave the lock raw)."""
        f = sys._getframe(1)
        while f is not None:
            filename = f.f_code.co_filename
            if filename != __file__:
                norm = filename.replace("\\", "/")
                for marker in self.package_markers:
                    if f"/{marker}/" in norm or norm.startswith(
                        f"{marker}/"
                    ):
                        return f"{_normalize(filename)}:{f.f_lineno}"
                return None
            f = f.f_back
        return None

    def install(self) -> None:
        if self._installed:
            return
        self._installed = True
        witness = self

        def _factory(kind: str, orig):
            def make():
                site = witness._alloc_site()
                if site is None:
                    return orig()
                with witness._mu:
                    witness._sites.setdefault(site, kind)
                return _WitnessedLock(orig(), site, witness, kind)

            return make

        threading.Lock = _factory("lock", _ORIG_LOCK)
        threading.RLock = _factory("rlock", _ORIG_RLOCK)

    def uninstall(self) -> None:
        if not self._installed:
            return
        self._installed = False
        threading.Lock = _ORIG_LOCK
        threading.RLock = _ORIG_RLOCK

    # -- recording ---------------------------------------------------------

    def _stack(self) -> List[str]:
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = []
            self._tls.stack = stack
        return stack

    def _record_acquire(self, site: str, kind: str) -> None:
        stack = self._stack()
        if stack:
            with self._mu:
                for held in stack:
                    if held == site and kind == "rlock":
                        continue  # reentrant re-acquire is not an edge
                    key = (held, site)
                    self._edges[key] = self._edges.get(key, 0) + 1
        stack.append(site)

    def _record_release(self, site: str) -> None:
        stack = self._stack()
        # out-of-order release: drop the matching *last* occurrence
        for i in range(len(stack) - 1, -1, -1):
            if stack[i] == site:
                del stack[i]
                return

    # -- reporting ---------------------------------------------------------

    def report(self) -> dict:
        with self._mu:
            return {
                "version": 1,
                "pid": os.getpid(),
                "sites": dict(self._sites),
                "edges": [
                    {"from": a, "to": b, "count": n}
                    for (a, b), n in sorted(self._edges.items())
                ],
            }

    def dump(self, path: str) -> None:
        data = json.dumps(self.report(), indent=2, sort_keys=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w", encoding="utf-8") as fh:
            fh.write(data)
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, path)


_ACTIVE: Optional[LockWitness] = None


def active_witness() -> Optional[LockWitness]:
    return _ACTIVE


def install_from_env() -> Optional[LockWitness]:
    """Install the witness when ``MMLSPARK_TPU_LOCKCHECK=1`` (idempotent;
    called from the package ``__init__`` so every lock allocated by any
    mmlspark_tpu module in this process — gang workers included, the env
    var is inherited — is wrapped). ``MMLSPARK_TPU_LOCKCHECK_OUT`` names
    a directory for the per-process exit dump."""
    global _ACTIVE
    if os.environ.get("MMLSPARK_TPU_LOCKCHECK") != "1":
        return None
    if _ACTIVE is not None:
        return _ACTIVE
    _ACTIVE = LockWitness()
    _ACTIVE.install()
    out_dir = os.environ.get("MMLSPARK_TPU_LOCKCHECK_OUT", "")
    if out_dir:
        atexit.register(_dump_active, out_dir)
    return _ACTIVE


def _dump_active(out_dir: str) -> None:
    if _ACTIVE is None:
        return
    try:
        os.makedirs(out_dir, exist_ok=True)
        _ACTIVE.dump(
            os.path.join(out_dir, f"lockwitness-{os.getpid()}.json")
        )
    except OSError:
        pass  # exit-path best effort: losing the report must not fail the run


# ---------------------------------------------------------------------------
# Static cross-check
# ---------------------------------------------------------------------------


def load_reports(paths: Iterable[str]) -> List[dict]:
    """Witness reports from files and/or directories of
    ``lockwitness-*.json`` dumps."""
    out: List[dict] = []
    for path in paths:
        if os.path.isdir(path):
            for name in sorted(os.listdir(path)):
                if name.startswith("lockwitness") and name.endswith(".json"):
                    with open(
                        os.path.join(path, name), "r", encoding="utf-8"
                    ) as fh:
                        out.append(json.load(fh))
        elif os.path.isfile(path):
            with open(path, "r", encoding="utf-8") as fh:
                out.append(json.load(fh))
        else:
            raise FileNotFoundError(path)
    return out


def check_witness(reports: Iterable[dict], contexts) -> List:
    """Violations (rule ``lock-witness``) from witnessed orders vs the
    static lock graph built over ``contexts``."""
    from mmlspark_tpu.analysis.base import Violation
    from mmlspark_tpu.analysis.lockgraph import ConcurrencyIndex

    index = ConcurrencyIndex(contexts)
    site_to_lock = {
        f"{path}:{line}": lock_id
        for (path, line), lock_id in index.lock_sites().items()
    }

    def ident(site: str) -> str:
        return site_to_lock.get(site, f"witness:{site}")

    witnessed: Dict[Tuple[str, str], str] = {}  # (a, b) -> example site pair
    for report in reports:
        for edge in report.get("edges", ()):
            a, b = ident(edge["from"]), ident(edge["to"])
            if a != b:
                witnessed.setdefault(
                    (a, b), f"{edge['from']} -> {edge['to']}"
                )

    def site_of(lock_id: str) -> Tuple[str, int]:
        d = index.lock_defs.get(lock_id)
        return (d.path, d.line) if d is not None else ("<witness>", 0)

    violations: List[Violation] = []
    seen_pairs = set()
    # 1. direct runtime inversion
    for (a, b), example in sorted(witnessed.items()):
        if (b, a) not in witnessed:
            continue
        pair = tuple(sorted((a, b)))
        if pair in seen_pairs:
            continue
        seen_pairs.add(pair)
        path, line = site_of(pair[0])
        violations.append(Violation(
            rule=WITNESS_RULE, path=path, line=line, col=0,
            message=(
                f"runtime lock-order inversion: {a} -> {b} AND {b} -> "
                f"{a} both observed under MMLSPARK_TPU_LOCKCHECK "
                f"(e.g. {example}) — an ABBA deadlock waiting for the "
                "right interleaving"
            ),
        ))
    # 2. a witnessed edge closes a cycle against the static graph
    static_edges = set(index.edges)
    for (a, b) in sorted(witnessed):
        if (b, a) in static_edges and (b, a) not in witnessed:
            pair = tuple(sorted((a, b)))
            if pair in seen_pairs:
                continue
            seen_pairs.add(pair)
            se = index.edges[(b, a)]
            path, line = site_of(a)
            violations.append(Violation(
                rule=WITNESS_RULE, path=path, line=line, col=0,
                message=(
                    f"witnessed order {a} -> {b} inverts the static "
                    f"lock-graph edge {b} -> {a} ({se.path}:{se.line}): "
                    "the two orders together are an ABBA deadlock"
                ),
            ))
    return violations
