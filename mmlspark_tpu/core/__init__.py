"""Core contracts: params, pipeline stages, serialization, schema, topology."""
