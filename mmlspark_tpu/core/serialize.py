"""Stage persistence with a per-type complex-value serializer registry.

Re-design of the reference's ComplexParam machinery
(``core/serialize/ComplexParam.scala:13-34``,
``org/apache/spark/ml/Serializer.scala:21-130``): JSON-simple params go into
``metadata.json``; complex values (arrays, pytrees, nested stages, Tables,
callables) are written next to the metadata by type-dispatched writers, each
directory self-describing via a ``_type`` tag so loading needs no schema.
"""

from __future__ import annotations

import json
import os
import pickle
import shutil
from typing import Any, Callable, Dict, List, Tuple

import numpy as np

from mmlspark_tpu.core.params import lookup_class
from mmlspark_tpu.data.table import Table

FORMAT_VERSION = 1

_JSON_SIMPLE = (type(None), bool, int, float, str)


def _is_json_simple(v: Any) -> bool:
    if isinstance(v, _JSON_SIMPLE):
        return True
    if isinstance(v, (list, tuple)):
        return all(_is_json_simple(x) for x in v)
    if isinstance(v, dict):
        return all(isinstance(k, str) and _is_json_simple(x) for k, x in v.items())
    return False


# ---------------------------------------------------------------------------
# Value writers/readers
# ---------------------------------------------------------------------------

def _write_ndarray(value: np.ndarray, path: str) -> None:
    np.save(
        os.path.join(path, "value.npy"), value, allow_pickle=value.dtype == object
    )


def _read_ndarray(path: str) -> np.ndarray:
    return np.load(os.path.join(path, "value.npy"), allow_pickle=True)


def _write_pytree(value: Any, path: str) -> None:
    """Arbitrary pytree of arrays/leaves — flattened to npz + structure pickle."""
    import jax

    leaves, treedef = jax.tree_util.tree_flatten(value)
    np.savez(
        os.path.join(path, "leaves.npz"),
        **{f"leaf_{i}": np.asarray(l) for i, l in enumerate(leaves)},
    )
    with open(os.path.join(path, "treedef.pkl"), "wb") as f:
        pickle.dump(treedef, f)


def _read_pytree(path: str) -> Any:
    import jax

    with np.load(os.path.join(path, "leaves.npz"), allow_pickle=True) as z:
        leaves = [z[f"leaf_{i}"] for i in range(len(z.files))]
    with open(os.path.join(path, "treedef.pkl"), "rb") as f:
        treedef = pickle.load(f)
    return jax.tree_util.tree_unflatten(treedef, leaves)


def _write_table(value: Table, path: str) -> None:
    cols = value.to_dict()
    np.savez(
        os.path.join(path, "columns.npz"),
        **{k: v for k, v in cols.items() if v.dtype != object},
    )
    obj_cols = {k: v for k, v in cols.items() if v.dtype == object}
    with open(os.path.join(path, "object_columns.pkl"), "wb") as f:
        pickle.dump(obj_cols, f)
    with open(os.path.join(path, "table_meta.json"), "w") as f:
        json.dump(
            {
                "num_partitions": value.num_partitions,
                "order": value.columns,
                "metadata": {k: value.metadata(k) for k in value.columns if value.metadata(k)},
            },
            f,
        )


def _read_table(path: str) -> Table:
    with open(os.path.join(path, "table_meta.json")) as f:
        meta = json.load(f)
    cols: Dict[str, np.ndarray] = {}
    with np.load(os.path.join(path, "columns.npz")) as z:
        for k in z.files:
            cols[k] = z[k]
    with open(os.path.join(path, "object_columns.pkl"), "rb") as f:
        cols.update(pickle.load(f))
    ordered = {k: cols[k] for k in meta["order"]}
    return Table(
        ordered, metadata=meta.get("metadata") or {}, num_partitions=meta["num_partitions"]
    )


def _write_stage(value: Any, path: str) -> None:
    save_stage(value, os.path.join(path, "stage"), overwrite=True)


def _read_stage(path: str) -> Any:
    return load_stage(os.path.join(path, "stage"))


def _write_stage_list(value: List[Any], path: str) -> None:
    with open(os.path.join(path, "count.json"), "w") as f:
        json.dump(len(value), f)
    for i, stage in enumerate(value):
        save_stage(stage, os.path.join(path, f"stage_{i}"), overwrite=True)


def _read_stage_list(path: str) -> List[Any]:
    with open(os.path.join(path, "count.json")) as f:
        n = json.load(f)
    return [load_stage(os.path.join(path, f"stage_{i}")) for i in range(n)]


def _write_pickle(value: Any, path: str) -> None:
    # cloudpickle handles closures/lambdas — the UDFParam case
    # (org/apache/spark/ml/param/UDFParam.scala uses Java closure serde).
    import cloudpickle

    with open(os.path.join(path, "value.pkl"), "wb") as f:
        cloudpickle.dump(value, f)


def _read_pickle(path: str) -> Any:
    with open(os.path.join(path, "value.pkl"), "rb") as f:
        return pickle.load(f)


def _is_stage(v: Any) -> bool:
    from mmlspark_tpu.core.pipeline import PipelineStage

    return isinstance(v, PipelineStage)


def _is_jax_array(v: Any) -> bool:
    try:
        import jax

        return isinstance(v, jax.Array)
    except ImportError:  # pragma: no cover
        return False


# type tag -> (predicate, writer, reader); checked in order.
_SERIALIZERS: List[Tuple[str, Callable[[Any], bool], Callable, Callable]] = [
    ("stage", _is_stage, _write_stage, _read_stage),
    (
        "stage_list",
        lambda v: isinstance(v, (list, tuple)) and len(v) > 0 and all(_is_stage(x) for x in v),
        _write_stage_list,
        _read_stage_list,
    ),
    ("table", lambda v: isinstance(v, Table), _write_table, _read_table),
    ("ndarray", lambda v: isinstance(v, np.ndarray), _write_ndarray, _read_ndarray),
    ("ndarray", _is_jax_array, lambda v, p: _write_ndarray(np.asarray(v), p), _read_ndarray),
    ("json", _is_json_simple, lambda v, p: _write_json_value(v, p), lambda p: _read_json_value(p)),
    (
        "pytree",
        lambda v: isinstance(v, (dict, list, tuple)) and _pytree_of_arrays(v),
        _write_pytree,
        _read_pytree,
    ),
    ("pickle", lambda v: True, _write_pickle, _read_pickle),
]

_READERS = {
    "stage": _read_stage,
    "stage_list": _read_stage_list,
    "table": _read_table,
    "ndarray": _read_ndarray,
    "json": lambda p: _read_json_value(p),
    "pytree": _read_pytree,
    "pickle": _read_pickle,
}


def _pytree_of_arrays(v: Any) -> bool:
    try:
        import jax
    except ImportError:  # pragma: no cover - fall through to pickle
        return False

    leaves = jax.tree_util.tree_leaves(v)
    return len(leaves) > 0 and all(
        isinstance(l, (np.ndarray, np.generic, int, float, bool)) or _is_jax_array(l)
        for l in leaves
    )


def _write_json_value(v: Any, path: str) -> None:
    with open(os.path.join(path, "value.json"), "w") as f:
        json.dump(v, f)


def _read_json_value(path: str) -> Any:
    with open(os.path.join(path, "value.json")) as f:
        return json.load(f)


def save_value(value: Any, path: str) -> None:
    os.makedirs(path, exist_ok=True)
    for tag, pred, writer, _ in _SERIALIZERS:
        if pred(value):
            with open(os.path.join(path, "_type"), "w") as f:
                f.write(tag)
            writer(value, path)
            return
    raise TypeError(f"no serializer for {type(value)}")  # pragma: no cover


def load_value(path: str) -> Any:
    with open(os.path.join(path, "_type")) as f:
        tag = f.read().strip()
    return _READERS[tag](path)


# ---------------------------------------------------------------------------
# Stage save/load
# ---------------------------------------------------------------------------

def save_stage(stage: Any, path: str, overwrite: bool = True) -> None:
    if os.path.exists(path):
        if not overwrite:
            raise FileExistsError(path)
        shutil.rmtree(path)
    os.makedirs(path)

    simple: Dict[str, Any] = {}
    complex_names: List[str] = []
    for name, spec in stage.params.items():
        if not stage.isSet(name):
            continue
        value = stage.get(name)
        if not spec.is_complex and _is_json_simple(value):
            simple[name] = list(value) if isinstance(value, tuple) else value
        else:
            complex_names.append(name)
            save_value(value, os.path.join(path, "params", name))

    meta = {
        "format_version": FORMAT_VERSION,
        "class": f"{type(stage).__module__}.{type(stage).__qualname__}",
        "uid": stage.uid,
        "params": simple,
        "complex_params": complex_names,
    }
    with open(os.path.join(path, "metadata.json"), "w") as f:
        json.dump(meta, f, indent=2)
    stage._save_extra(path)


def load_stage(path: str) -> Any:
    with open(os.path.join(path, "metadata.json")) as f:
        meta = json.load(f)
    cls = lookup_class(meta["class"])
    stage = cls.__new__(cls)
    stage.uid = meta["uid"]
    stage._paramMap = {}
    for k, v in meta["params"].items():
        stage.set(k, v)
    for name in meta["complex_params"]:
        stage._paramMap[name] = load_value(os.path.join(path, "params", name))
    stage._load_extra(path)
    return stage
