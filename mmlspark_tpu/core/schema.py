"""Static pipeline schemas — the ``transformSchema`` half of the analysis layer.

SparkML pipelines are validated before execution: every stage implements
``transformSchema(schema: StructType)`` and ``Pipeline.fit`` threads the
DataFrame schema through the whole stage graph up front, so a mis-wired
pipeline fails in milliseconds on the driver instead of minutes into a
cluster job. This module is that contract for :class:`Table` pipelines —
the stakes are higher here, because the first ``transform`` typically
triggers a TPU compile measured in tens of seconds.

A schema is a plain ``Dict[str, ColType]``: column name to dtype plus the
optional per-row element shape (vector columns are 2-D in a Table; their
``shape`` is ``(width,)`` when known, ``None`` when data-dependent).
``ColType(None, None)`` means "column exists, nothing else known" — every
check treats unknown as compatible, so partial knowledge propagates
without false alarms.

Stage authors use the helpers (:func:`require_column`, :func:`add_column`)
inside ``transform_schema`` overrides; errors are :class:`SchemaError`
with a structured ``kind`` (``missing-input-col`` / ``dtype-mismatch`` /
``duplicate-output-col``) and the offending stage + column, so tests and
tools can assert on semantics rather than message strings.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

import numpy as np

# Structured error kinds (stable API — tests match on these).
MISSING_INPUT_COL = "missing-input-col"
DTYPE_MISMATCH = "dtype-mismatch"
DUPLICATE_OUTPUT_COL = "duplicate-output-col"


@dataclasses.dataclass(frozen=True)
class ColType:
    """Static type of one column: numpy dtype (None = unknown) and the
    per-row element shape (() = scalar column, ``(w,)`` = width-w vector,
    None = unknown/ragged)."""

    dtype: Optional[np.dtype] = None
    shape: Optional[Tuple[int, ...]] = None

    def __repr__(self) -> str:  # compact in error messages
        d = self.dtype if self.dtype is not None else "?"
        if self.shape is None:
            return f"ColType({d})"
        return f"ColType({d}, shape={self.shape})"


class SchemaError(ValueError):
    """A statically-detected pipeline wiring error."""

    def __init__(
        self,
        kind: str,
        message: str,
        stage: Optional[str] = None,
        column: Optional[str] = None,
    ):
        self.kind = kind
        self.stage = stage
        self.column = column
        self.bare_message = message
        prefix = f"[{kind}]"
        if stage:
            prefix += f" stage {stage}:"
        super().__init__(f"{prefix} {message}")

    def with_stage(self, stage: str) -> "SchemaError":
        """Re-tag with the pipeline-level stage label (index + class)."""
        return SchemaError(self.kind, self.bare_message, stage, self.column)


def as_schema(source: Any) -> Dict[str, ColType]:
    """Normalize a Table / ``{name: dtype}`` / ``{name: ColType}`` mapping
    into a ``{name: ColType}`` schema."""
    from mmlspark_tpu.data.table import Table

    if isinstance(source, Table):
        return schema_of_table(source)
    out: Dict[str, ColType] = {}
    for name, value in dict(source).items():
        if isinstance(value, ColType):
            out[name] = value
        elif value is None:
            out[name] = ColType()
        else:
            out[name] = ColType(dtype=np.dtype(value))
    return out


def schema_of_table(table: Any) -> Dict[str, ColType]:
    """Schema of a concrete Table: dtypes from the columns, element shapes
    from ndim (2-D columns are width-``shape[1]`` vectors; object columns
    have unknown element shape)."""
    out: Dict[str, ColType] = {}
    for name in table.columns:
        col = table.column(name)
        dtype = col.dtype
        if dtype == np.dtype(object):
            out[name] = ColType(dtype=dtype, shape=None)
        elif col.ndim >= 2:
            out[name] = ColType(dtype=dtype, shape=tuple(col.shape[1:]))
        else:
            out[name] = ColType(dtype=dtype, shape=())
    return out


def _is_numeric(dtype: np.dtype) -> bool:
    return np.issubdtype(dtype, np.number) or np.issubdtype(dtype, np.bool_)


def require_column(
    schema: Dict[str, ColType],
    column: str,
    stage: str,
    dtype: Any = None,
    numeric: bool = False,
) -> ColType:
    """Assert ``column`` exists (and optionally has a compatible dtype).
    Unknown dtypes always pass — the validator reports what it can prove
    wrong, not what it cannot prove right."""
    if column not in schema:
        have = ", ".join(sorted(schema)) or "<empty>"
        raise SchemaError(
            MISSING_INPUT_COL,
            f"input column {column!r} not found (have: {have})",
            stage=stage,
            column=column,
        )
    col = schema[column]
    if col.dtype is None:
        return col
    if numeric and not _is_numeric(col.dtype) and col.dtype != np.dtype(object):
        raise SchemaError(
            DTYPE_MISMATCH,
            f"column {column!r} must be numeric, found {col.dtype}",
            stage=stage,
            column=column,
        )
    if dtype is not None and col.dtype != np.dtype(object):
        want = np.dtype(dtype)
        if col.dtype != want and not np.can_cast(col.dtype, want):
            raise SchemaError(
                DTYPE_MISMATCH,
                f"column {column!r} has dtype {col.dtype}, expected {want}",
                stage=stage,
                column=column,
            )
    return col


def add_column(
    schema: Dict[str, ColType],
    column: str,
    coltype: ColType,
    stage: str,
    replace: bool = False,
) -> Dict[str, ColType]:
    """Return ``schema`` + the stage's output column. ``replace=True`` is
    for stages whose contract overwrites in place (e.g. in-col == out-col
    transforms); otherwise an existing name is a wiring error."""
    if column in schema and not replace:
        raise SchemaError(
            DUPLICATE_OUTPUT_COL,
            f"output column {column!r} already exists",
            stage=stage,
            column=column,
        )
    out = dict(schema)
    out[column] = coltype
    return out
