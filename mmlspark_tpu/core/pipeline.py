"""Pipeline stage contracts: Transformer / Estimator / Pipeline / Evaluator.

The composability layer of the framework — same shape as SparkML's
(every reference feature is packaged as a ``Transformer``/``Estimator``;
SURVEY.md §1), but operating on :class:`~mmlspark_tpu.data.table.Table` and
dispatching heavy compute to jitted JAX programs on the TPU mesh.
"""

from __future__ import annotations

import itertools
import os
import threading
import time
from typing import Any, Dict, List, Optional

from mmlspark_tpu.core.params import Param, Params
from mmlspark_tpu.data.table import Table

# pipeline-fit ids for the event log (the SparkListenerJobStart analogue
# at pipeline granularity); process-global so concurrent fits don't collide
_FIT_IDS = itertools.count()
_FIT_ID_LOCK = threading.Lock()


def _next_fit_id() -> int:
    with _FIT_ID_LOCK:
        return next(_FIT_IDS)


_TRACER = None


def _tracer():
    # cached process-global tracer: PipelineModel.transform is the serving
    # hot path and must not pay import-machinery cost per call
    global _TRACER
    if _TRACER is None:
        from mmlspark_tpu.observability.tracing import get_tracer

        _TRACER = get_tracer()
    return _TRACER


_GET_QMONITOR = None


def _quality_monitor():
    # same ambient-gate pattern as _tracer: the accessor is cached so an
    # unconfigured transform pays one env lookup, and the quality plane
    # only materializes when MMLSPARK_TPU_QUALITY_STORE is set
    global _GET_QMONITOR
    if _GET_QMONITOR is None:
        from mmlspark_tpu.observability.quality import get_monitor

        _GET_QMONITOR = get_monitor
    return _GET_QMONITOR()


class PipelineStage(Params):
    """Base of all stages. Adds persistence (save/load)."""

    def transform_schema(self, schema: Dict[str, Any]) -> Dict[str, Any]:
        """Best-effort schema propagation; stages may override."""
        return dict(schema)

    # -- persistence (ComplexParamsWritable/Readable analogue) ---------------

    def save(self, path: str, overwrite: bool = True) -> None:
        from mmlspark_tpu.core import serialize

        serialize.save_stage(self, path, overwrite=overwrite)

    @classmethod
    def load(cls, path: str) -> "PipelineStage":
        from mmlspark_tpu.core import serialize

        stage = serialize.load_stage(path)
        if cls is not PipelineStage and not isinstance(stage, cls):
            raise TypeError(f"loaded {type(stage).__name__}, expected {cls.__name__}")
        return stage

    def _save_extra(self, path: str) -> None:
        """Hook for non-param state (e.g. fitted model arrays)."""

    def _load_extra(self, path: str) -> None:
        pass


class Transformer(PipelineStage):
    def transform(self, table: Table) -> Table:
        raise NotImplementedError

    def __call__(self, table: Table) -> Table:
        return self.transform(table)


class Estimator(PipelineStage):
    def fit(self, table: Table, params: Optional[Dict[str, Any]] = None) -> "Model":
        if params:
            return self.copy(params)._fit(table)
        return self._fit(table)

    def _fit(self, table: Table) -> "Model":
        raise NotImplementedError


class Model(Transformer):
    """A fitted Transformer produced by an Estimator."""

    parent: Optional[Estimator] = None


class Evaluator(Params):
    """Computes a scalar metric from a transformed table
    (SparkML ``Evaluator`` shape; cf. ``automl/FindBestModel.scala:55``)."""

    def evaluate(self, table: Table) -> float:
        raise NotImplementedError

    def is_larger_better(self) -> bool:
        return True


class Pipeline(Estimator):
    """Chain of stages; Estimators are fitted in sequence, Transformers pass
    through — identical semantics to SparkML ``Pipeline.fit``, including the
    up-front ``transformSchema`` pass: :meth:`validate` threads the column
    schema through every stage before anything executes, so a mis-wired
    graph fails in milliseconds instead of after the first TPU compile.

    ``invalidDataPolicy`` arms the dataguard fit guard: with ``"fail"``,
    ``"drop"`` or ``"impute"``, every float column is scanned for
    NaN/Inf (and the label column of a classifier stage for domain
    violations) before any stage runs — see
    :mod:`mmlspark_tpu.dataguard.guards`. The default ``""`` skips the
    scan entirely (the pre-dataguard behavior)."""

    stages = Param("The chain of pipeline stages", default=[], is_complex=True)
    invalidDataPolicy = Param(
        "NaN/Inf/label-domain handling at fit: '' (no scan), 'fail', "
        "'drop', or 'impute'",
        default="",
    )

    def validate(self, table_or_schema: Any) -> Dict[str, Any]:
        """Statically propagate a schema (or a Table's schema) through the
        stage graph WITHOUT executing any stage. Returns the output schema;
        raises :class:`~mmlspark_tpu.core.schema.SchemaError` naming the
        offending stage on the first wiring error."""
        return _chain_schema(self.getStages(), table_or_schema)

    def transform_schema(self, schema: Dict[str, Any]) -> Dict[str, Any]:
        return _chain_schema(self.getStages(), schema)

    def _fit(self, table: Table) -> "PipelineModel":
        from mmlspark_tpu.observability.events import (
            ModelCommitted, StageCompleted, StageStarted, get_bus,
        )
        from mmlspark_tpu.observability.tracing import get_tracer

        self.validate(table)
        bus, tracer = get_bus(), get_tracer()
        fit_id = _next_fit_id()
        stages = self.getStages()
        policy = self.getInvalidDataPolicy()
        if policy:
            from mmlspark_tpu.dataguard.guards import guard_table
            from mmlspark_tpu.observability.events import RecordsDeadLettered

            label_col, label_domain = _label_contract(stages)
            table, report = guard_table(
                table, policy=policy, label_col=label_col,
                label_domain=label_domain, name=f"pipeline.fit:{fit_id}",
            )
            if report.rows_dropped and bus.active:
                bus.publish(RecordsDeadLettered(
                    source="pipeline.fit", epoch=fit_id,
                    count=report.rows_dropped, reasons=report.summary(),
                ))
        fitted: List[Transformer] = []
        cur = table
        for i, stage in enumerate(stages):
            name = type(stage).__name__
            if bus.active:
                bus.publish(StageStarted(
                    job_id=fit_id, stage_id=i, name=name, phase="fit"
                ))
            t0 = time.monotonic()
            status = "ok"
            try:
                with tracer.span(f"fit:{name}", stage=i):
                    if isinstance(stage, Estimator):
                        model = stage.fit(cur)
                        fitted.append(model)
                        if i < len(stages) - 1:
                            cur = model.transform(cur)
                    elif isinstance(stage, Transformer):
                        fitted.append(stage)
                        if i < len(stages) - 1:
                            cur = stage.transform(cur)
                    else:
                        raise TypeError(
                            f"stage {stage!r} is neither Estimator nor Transformer"
                        )
            except BaseException as e:
                status = type(e).__name__
                raise
            finally:
                if bus.active:
                    bus.publish(StageCompleted(
                        job_id=fit_id, stage_id=i, name=name,
                        duration=time.monotonic() - t0, phase="fit",
                        status=status,
                    ))
        model = PipelineModel(stages=fitted)
        model.parent = self
        if bus.active:
            bus.publish(ModelCommitted(
                model=type(model).__name__, version=fit_id,
                detail=f"{len(fitted)} stages",
            ))
        # quality plane (env-gated): profile the training columns + the
        # fitted scores and commit the reference artifact next to the
        # model version, so live serving has something to drift against
        if os.environ.get("MMLSPARK_TPU_QUALITY_STORE"):
            from mmlspark_tpu.observability.quality import (
                capture_pipeline_reference,
            )

            capture_pipeline_reference(model, table, version_hint=fit_id)
        return model


class PipelineModel(Model):
    stages = Param("The fitted pipeline stages", default=[], is_complex=True)

    def transform(self, table: Table) -> Table:
        # stage spans open only when an ambient span exists to join (a
        # serving request's apply span, a fit span, an explicit
        # tracer.span(...) around the call) — a bare untraced transform
        # pays one contextvar read, nothing more. The quality gate is the
        # same posture: one env lookup when unconfigured; the serving
        # batch loop suppresses this hook because it sketches the batch
        # itself (a request must not count twice).
        monitor = _quality_monitor()
        observe = monitor is not None and not monitor.transform_suppressed
        if observe:
            in_cols = set(table.columns)
            monitor.observe_columns({c: table.column(c) for c in in_cols})
        tracer = _tracer()
        if tracer.current() is None:
            for stage in self.getStages():
                table = stage.transform(table)
        else:
            for i, stage in enumerate(self.getStages()):
                with tracer.span(f"transform:{type(stage).__name__}", stage=i):
                    table = stage.transform(table)
        if observe:
            monitor.observe_columns({
                c: table.column(c) for c in table.columns if c not in in_cols
            })
        return table

    def transform_schema(self, schema: Dict[str, Any]) -> Dict[str, Any]:
        return _chain_schema(self.getStages(), schema)


def _label_contract(stages: List[PipelineStage]) -> tuple:
    """Best-effort (label column, label domain) for the fit guard: the
    last estimator stage exposing ``getLabelCol`` names the label, and a
    class name carrying ``Classifier`` pins the non-negative-integer
    domain. Unknown graphs guard features only."""
    label_col, domain = None, None
    for stage in stages:
        if not isinstance(stage, Estimator):
            continue
        getter = getattr(stage, "getLabelCol", None)
        if getter is None:
            continue
        try:
            label_col = getter()
        except (AttributeError, KeyError, ValueError):
            continue
        domain = "classifier" if "Classifier" in type(stage).__name__ else None
    return label_col, domain


def _chain_schema(stages: List[PipelineStage], source: Any) -> Dict[str, Any]:
    """Thread a schema through a stage list, re-tagging errors with the
    failing stage's position + class so pipeline users see *which* stage
    is mis-wired, not just which column."""
    from mmlspark_tpu.core.schema import SchemaError, as_schema

    schema = as_schema(source)
    for i, stage in enumerate(stages):
        label = f"{i} ({type(stage).__name__})"
        try:
            schema = stage.transform_schema(schema)
        except SchemaError as e:
            raise e.with_stage(label) from None
        schema = as_schema(schema)
    return schema


def make_pipeline_model(*stages: Transformer) -> PipelineModel:
    """Assemble transformers into an anonymous PipelineModel
    (``NamespaceInjections.pipelineModel``, ``org/apache/spark/ml/NamespaceInjections.scala:23``)."""
    return PipelineModel(stages=list(stages))


def ml_transform(table: Table, *stages: Transformer) -> Table:
    """``df.mlTransform(t1, t2)`` fluent sugar (``core/spark/FluentAPI.scala:13-30``)."""
    for s in stages:
        table = s.transform(table)
    return table
