"""Typed parameter system for pipeline stages.

TPU-native re-design of the SparkML ``Params`` contract used throughout the
reference (``core/contracts/Params.scala:8-216``): every stage declares typed
:class:`Param` descriptors; values are stored per-instance in a param map with
class-level defaults. Accessors (``setFoo``/``getFoo``) are generated
automatically at class-definition time — this replaces the reference's
reflection-driven wrapper codegen (``codegen/PySparkWrapper.scala``) with
plain Python metaprogramming: the Python API *is* the native API, so no
binding generation step is needed.

Complex (non-JSON) param values — arrays, pytrees, nested stages, tables,
functions — are handled by :mod:`mmlspark_tpu.core.serialize`'s type registry,
mirroring ``ComplexParam`` (``core/serialize/ComplexParam.scala:13-34``) and
``Serializer.typeToSerializer`` (``org/apache/spark/ml/Serializer.scala:21-130``).
"""

from __future__ import annotations

import copy as _copy
import uuid
from typing import Any, Callable, Dict, Optional


class _NoDefault:
    """Sentinel for 'no default value'."""

    _instance: Optional["_NoDefault"] = None

    def __new__(cls) -> "_NoDefault":
        if cls._instance is None:
            cls._instance = super().__new__(cls)
        return cls._instance

    def __repr__(self) -> str:  # pragma: no cover
        return "<no default>"


NO_DEFAULT = _NoDefault()


def gen_uid(cls_name: str) -> str:
    """Generate a unique, human-readable stage uid like ``LightGBMClassifier_a1b2c3``."""
    return f"{cls_name}_{uuid.uuid4().hex[:8]}"


class Param:
    """A typed parameter declared on a :class:`Params` subclass.

    Parameters
    ----------
    doc: human-readable description (surfaced by ``explainParams``).
    default: class-level default; omit for a required param.
    validator: callable ``value -> bool``; a falsy return raises ``ValueError``.
    converter: callable applied to the value on ``set`` (type coercion).
    is_complex: value is not JSON-serializable; routed through the complex
        serializer registry on save/load (ComplexParam equivalent).
    """

    __slots__ = ("name", "doc", "default", "validator", "converter", "is_complex", "owner")

    def __init__(
        self,
        doc: str = "",
        default: Any = NO_DEFAULT,
        validator: Optional[Callable[[Any], bool]] = None,
        converter: Optional[Callable[[Any], Any]] = None,
        is_complex: bool = False,
    ):
        self.name: str = ""  # filled by __set_name__
        self.doc = doc
        self.default = default
        self.validator = validator
        self.converter = converter
        self.is_complex = is_complex
        self.owner: Optional[type] = None

    def __set_name__(self, owner: type, name: str) -> None:
        self.name = name
        self.owner = owner

    # Descriptor access: ``stage.inputCol`` reads the current value.
    def __get__(self, instance: Any, owner: Optional[type] = None) -> Any:
        if instance is None:
            return self
        return instance.getOrDefault(self.name)

    def __set__(self, instance: Any, value: Any) -> None:
        instance.set(self.name, value)

    def __repr__(self) -> str:
        return f"Param({self.name!r})"


# ---------------------------------------------------------------------------
# Common converters / validators (TypeConverters analogue)
# ---------------------------------------------------------------------------

def to_int(v: Any) -> int:
    if isinstance(v, bool):
        raise TypeError(f"expected int, got bool {v!r}")
    return int(v)


def to_float(v: Any) -> float:
    return float(v)


def to_str(v: Any) -> str:
    if not isinstance(v, str):
        raise TypeError(f"expected str, got {type(v).__name__}")
    return v


def to_bool(v: Any) -> bool:
    if not isinstance(v, bool):
        raise TypeError(f"expected bool, got {type(v).__name__}")
    return v


def to_list_str(v: Any) -> list:
    return [to_str(x) for x in v]


def to_list_int(v: Any) -> list:
    return [to_int(x) for x in v]


def in_range(lo: float, hi: float) -> Callable[[Any], bool]:
    return lambda v: lo <= v <= hi


def gt(lo: float) -> Callable[[Any], bool]:
    return lambda v: v > lo


def ge(lo: float) -> Callable[[Any], bool]:
    return lambda v: v >= lo


def one_of(*allowed: Any) -> Callable[[Any], bool]:
    allowed_set = set(allowed)
    return lambda v: v in allowed_set


# ---------------------------------------------------------------------------
# Params base
# ---------------------------------------------------------------------------

def _accessor_suffix(name: str) -> str:
    return name[0].upper() + name[1:]


class Params:
    """Base class for anything carrying :class:`Param` declarations.

    Subclasses get ``setX``/``getX`` accessors generated for every Param
    ``x`` unless hand-written, a collected ``params`` mapping, and
    keyword-argument construction: ``LightGBMClassifier(numIterations=10)``.
    """

    # name -> Param, collected across the MRO (populated per-subclass).
    _param_specs: Dict[str, Param] = {}

    def __init_subclass__(cls, **kwargs: Any) -> None:
        super().__init_subclass__(**kwargs)
        specs: Dict[str, Param] = {}
        for klass in reversed(cls.__mro__):
            for k, v in vars(klass).items():
                if isinstance(v, Param):
                    specs[k] = v
        cls._param_specs = specs
        # Generate accessors for params that don't already have them.
        for name in specs:
            suffix = _accessor_suffix(name)
            getter, setter = f"get{suffix}", f"set{suffix}"
            if not hasattr(cls, getter):
                setattr(cls, getter, _make_getter(name))
            if not hasattr(cls, setter):
                setattr(cls, setter, _make_setter(name))
        _STAGE_REGISTRY[f"{cls.__module__}.{cls.__qualname__}"] = cls

    def __init__(self, **kwargs: Any):
        self.uid = kwargs.pop("uid", None) or gen_uid(type(self).__name__)
        self._paramMap: Dict[str, Any] = {}
        self.setParams(**kwargs)

    # -- core access --------------------------------------------------------

    @property
    def params(self) -> Dict[str, Param]:
        return dict(self._param_specs)

    def _resolve(self, param: Any) -> str:
        name = param.name if isinstance(param, Param) else param
        if name not in self._param_specs:
            raise KeyError(f"{type(self).__name__} has no param {name!r}")
        return name

    def set(self, param: Any, value: Any) -> "Params":
        name = self._resolve(param)
        spec = self._param_specs[name]
        if value is not None:
            if spec.converter is not None:
                value = spec.converter(value)
            if spec.validator is not None and not spec.validator(value):
                raise ValueError(
                    f"{type(self).__name__}.{name}: invalid value {value!r}"
                )
        self._paramMap[name] = value
        return self

    def setParams(self, **kwargs: Any) -> "Params":
        for k, v in kwargs.items():
            self.set(k, v)
        return self

    def get(self, param: Any) -> Any:
        return self._paramMap[self._resolve(param)]

    def getOrDefault(self, param: Any) -> Any:
        name = self._resolve(param)
        if name in self._paramMap:
            return self._paramMap[name]
        default = self._param_specs[name].default
        if default is NO_DEFAULT:
            raise KeyError(
                f"{type(self).__name__}.{name} is not set and has no default"
            )
        # Copy mutable defaults so instances don't share state.
        if isinstance(default, (list, dict, set)):
            default = _copy.copy(default)
        return default

    def isSet(self, param: Any) -> bool:
        return self._resolve(param) in self._paramMap

    def isDefined(self, param: Any) -> bool:
        name = self._resolve(param)
        return name in self._paramMap or self._param_specs[name].default is not NO_DEFAULT

    def hasParam(self, name: str) -> bool:
        return name in self._param_specs

    def clear(self, param: Any) -> "Params":
        self._paramMap.pop(self._resolve(param), None)
        return self

    # -- convenience --------------------------------------------------------

    def copy(self, extra: Optional[Dict[str, Any]] = None) -> "Params":
        that = _copy.copy(self)
        that._paramMap = dict(self._paramMap)
        if extra:
            for k, v in extra.items():
                that.set(k, v)
        return that

    def explainParams(self) -> str:
        lines = []
        for name, spec in sorted(self._param_specs.items()):
            cur = self._paramMap.get(name, "undefined")
            dflt = spec.default if spec.default is not NO_DEFAULT else "undefined"
            lines.append(f"{name}: {spec.doc} (default: {dflt!r}, current: {cur!r})")
        return "\n".join(lines)

    def extractParamMap(self) -> Dict[str, Any]:
        out = {}
        for name, spec in self._param_specs.items():
            if name in self._paramMap or spec.default is not NO_DEFAULT:
                out[name] = self.getOrDefault(name)
        return out

    def __repr__(self) -> str:
        set_params = ", ".join(f"{k}={v!r}" for k, v in sorted(self._paramMap.items()))
        return f"{type(self).__name__}({set_params})"


def _make_getter(name: str) -> Callable[[Params], Any]:
    def getter(self: Params) -> Any:
        return self.getOrDefault(name)

    getter.__name__ = f"get{_accessor_suffix(name)}"
    getter.__doc__ = f"Get the value of param ``{name}``."
    return getter


def _make_setter(name: str) -> Callable[..., Params]:
    def setter(self: Params, value: Any) -> Params:
        return self.set(name, value)

    setter.__name__ = f"set{_accessor_suffix(name)}"
    setter.__doc__ = f"Set param ``{name}``. Returns self for chaining."
    return setter


# ---------------------------------------------------------------------------
# Stage registry — replaces reflection over the jar (JarLoadingUtils.scala:106):
# every Params subclass self-registers, powering the fuzzing meta-test and
# load-by-classname deserialization.
# ---------------------------------------------------------------------------

_STAGE_REGISTRY: Dict[str, type] = {}


def registered_classes() -> Dict[str, type]:
    return dict(_STAGE_REGISTRY)


def lookup_class(qualified_name: str) -> type:
    if qualified_name in _STAGE_REGISTRY:
        return _STAGE_REGISTRY[qualified_name]
    # Import the module to trigger registration, then retry.
    module_name = qualified_name.rsplit(".", 1)[0]
    import importlib

    importlib.import_module(module_name)
    return _STAGE_REGISTRY[qualified_name]


# ---------------------------------------------------------------------------
# Shared column-param mixins (core/contracts/Params.scala:17-216)
# ---------------------------------------------------------------------------


class HasInputCol(Params):
    inputCol = Param("The name of the input column", converter=to_str)


class HasOutputCol(Params):
    outputCol = Param("The name of the output column", converter=to_str)


class HasInputCols(Params):
    inputCols = Param("The names of the input columns", converter=to_list_str)


class HasOutputCols(Params):
    outputCols = Param("The names of the output columns", converter=to_list_str)


class HasLabelCol(Params):
    labelCol = Param("The name of the label column", default="label", converter=to_str)


class HasFeaturesCol(Params):
    featuresCol = Param(
        "The name of the features column", default="features", converter=to_str
    )


class HasPredictionCol(Params):
    predictionCol = Param(
        "The name of the prediction column", default="prediction", converter=to_str
    )


class HasWeightCol(Params):
    weightCol = Param("The name of the instance-weight column", converter=to_str)


class HasInitScoreCol(Params):
    initScoreCol = Param(
        "The name of the initial-score (margin) column for warm start",
        converter=to_str,
    )


class HasGroupCol(Params):
    groupCol = Param("The name of the query-group column (ranking)", converter=to_str)


class HasValidationIndicatorCol(Params):
    validationIndicatorCol = Param(
        "Boolean column marking rows used for validation / early stopping",
        converter=to_str,
    )


class HasBatchSize(Params):
    batchSize = Param(
        "Rows per device mini-batch", default=1024, converter=to_int, validator=gt(0)
    )


class HasSeed(Params):
    seed = Param("Random seed", default=0, converter=to_int)
