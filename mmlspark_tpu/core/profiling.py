"""Tracing/profiling utilities (SURVEY.md §5 "tracing/profiling").

The reference's point solutions (``Timer`` stage wall-times, VW per-phase
StopWatch stats) exist in their packages; this module adds the
device-level layer the TPU build owes: ``jax.profiler`` wiring so any
pipeline region can be captured as an xprof/TensorBoard trace, plus the
named-region annotation that shows stage boundaries inside the trace.

    from mmlspark_tpu.core.profiling import profile_trace, annotate, StopWatch

    with profile_trace("/tmp/xprof"):          # full device trace
        with annotate("gbdt-fit"):             # named region in the trace
            model = clf.fit(table)

    sw = StopWatch()
    with sw.measure("binning"):
        ...
    sw.summary()  # {"binning": seconds}
"""

from __future__ import annotations

import contextlib
import logging
import time
from typing import Dict, Iterator, Optional


def get_logger(name: str = "mmlspark_tpu") -> logging.Logger:
    """Framework logger (the slf4j analogue): a namespaced logger with one
    stderr handler installed on first use; level via MMLSPARK_TPU_LOGLEVEL."""
    import os

    logger = logging.getLogger(name)
    root = logging.getLogger("mmlspark_tpu")
    if not root.handlers:
        handler = logging.StreamHandler()
        handler.setFormatter(
            logging.Formatter("%(asctime)s %(name)s %(levelname)s: %(message)s")
        )
        root.addHandler(handler)
        root.setLevel(os.environ.get("MMLSPARK_TPU_LOGLEVEL", "WARNING").upper())
        # propagate stays True: log-capture tooling (pytest caplog) hooks the
        # python root; an app that also configures root logging may see the
        # line twice, which is the lesser evil
    return logger


@contextlib.contextmanager
def profile_trace(log_dir: str) -> Iterator[None]:
    """Capture a jax.profiler (xprof) device trace into ``log_dir`` for
    TensorBoard's profile plugin."""
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


@contextlib.contextmanager
def annotate(name: str) -> Iterator[None]:
    """Named region inside an active device trace (StepTraceAnnotation's
    host-side sibling); no-op overhead when no trace is running."""
    import jax

    with jax.profiler.TraceAnnotation(name):
        yield


class StopWatch:
    """Accumulating named phase timer — the reference's ``StopWatch``
    (``core/utils/StopWatch.scala``) / VW per-phase diagnostics pattern."""

    def __init__(self) -> None:
        self._totals: Dict[str, float] = {}

    @contextlib.contextmanager
    def measure(self, phase: str) -> Iterator[None]:
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.add(phase, time.perf_counter() - t0)

    def add(self, phase: str, seconds: float) -> None:
        """Fold an externally-timed duration into ``phase`` — the public
        form of what :meth:`measure` records, for callers that already
        hold a measured interval (e.g. the scheduler's queue-wait/run
        times, which are timestamp differences across threads)."""
        self._totals[phase] = self._totals.get(phase, 0.0) + seconds

    def summary(self) -> Dict[str, float]:
        return dict(self._totals)

    def log(self, logger: Optional[logging.Logger] = None, prefix: str = "") -> None:
        logger = logger or get_logger()
        total = sum(self._totals.values()) or 1.0
        for phase, secs in sorted(self._totals.items(), key=lambda kv: -kv[1]):
            logger.info("%s%s: %.3fs (%.0f%%)", prefix, phase, secs, 100 * secs / total)
