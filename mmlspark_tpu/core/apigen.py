"""Generated API reference — the bindings-codegen analogue.

The reference generates its public API surface from stage metadata
(``codegen/CodeGen.scala:15-48`` driving ``PySparkWrapper.scala`` /
``SparklyRWrapper.scala``) and smoke-tests the result in CI. In a
Python-native framework the wrapper half is moot, but the deliverable —
a GENERATED, validated, per-stage API reference with every param, default,
and doc string — is reproduced here directly from the Params registry:

- :func:`discover_stages` reflects every concrete public ``PipelineStage``
  in the package (the same discovery the fuzzing meta-suite uses, so a
  stage cannot be public without being both fuzzed and documented);
- :func:`generate` writes one markdown file per subpackage into
  ``docs/api/`` plus an index;
- ``python -m mmlspark_tpu.core.apigen`` regenerates; ``--check`` exits
  nonzero when the committed docs drift from the code (the CI validation,
  mirroring the reference's codegen-then-test pipeline stage).
"""

from __future__ import annotations

import importlib
import inspect
import pkgutil
from typing import Dict, List, Tuple

from mmlspark_tpu.core.params import NO_DEFAULT
from mmlspark_tpu.core.pipeline import Estimator, Model, PipelineStage, Transformer


def discover_stages() -> Dict[str, type]:
    """fully.qualified.Name -> class, for every concrete public stage."""
    import mmlspark_tpu

    found: Dict[str, type] = {}
    for m in pkgutil.walk_packages(mmlspark_tpu.__path__, "mmlspark_tpu."):
        mod = importlib.import_module(m.name)
        for name, obj in vars(mod).items():
            if (
                inspect.isclass(obj)
                and issubclass(obj, PipelineStage)
                and obj.__module__ == m.name
                and not name.startswith("_")
                and not inspect.isabstract(obj)
            ):
                found[f"{obj.__module__}.{name}"] = obj
    return found


def _kind(cls: type) -> str:
    if issubclass(cls, Model):
        return "Model"
    if issubclass(cls, Estimator):
        return "Estimator"
    if issubclass(cls, Transformer):
        return "Transformer"
    return "PipelineStage"


def _fmt_default(param) -> str:
    if param.default is NO_DEFAULT:
        return "*(required)*"
    v = param.default
    if callable(v) and not isinstance(v, (bool, int, float, str)):
        return f"`{getattr(v, '__name__', type(v).__name__)}`"
    return f"`{v!r}`"


def _stage_section(qual: str, cls: type) -> str:
    doc = inspect.getdoc(cls) or ""
    summary = doc.split("\n\n")[0].replace("\n", " ") if doc else ""
    lines = [f"### {cls.__name__}", ""]
    lines.append(f"*{_kind(cls)}* — `{qual}`")
    if summary:
        lines += ["", summary]
    params = dict(getattr(cls, "_param_specs", {}))
    if params:
        lines += [
            "",
            "| param | default | doc |",
            "|---|---|---|",
        ]
        for name in sorted(params):
            p = params[name]
            doc_cell = (p.doc or "").replace("\n", " ").replace("|", "\\|")
            lines.append(f"| `{name}` | {_fmt_default(p)} | {doc_cell} |")
    lines.append("")
    return "\n".join(lines)


def _group(stages: Dict[str, type]) -> Dict[str, List[Tuple[str, type]]]:
    groups: Dict[str, List[Tuple[str, type]]] = {}
    for qual, cls in sorted(stages.items()):
        pkg = qual.split(".")[1]  # mmlspark_tpu.<pkg>...
        groups.setdefault(pkg, []).append((qual, cls))
    return groups


def render() -> Dict[str, str]:
    """filename -> content for docs/api/ (deterministic)."""
    groups = _group(discover_stages())
    files: Dict[str, str] = {}
    index = [
        "# API reference",
        "",
        "Generated from the Params registry by `mmlspark_tpu/core/apigen.py`",
        "(`python -m mmlspark_tpu.core.apigen`; CI fails on drift via",
        "`--check`). One page per subpackage; every concrete public stage",
        "with its full param table.",
        "",
        "| package | stages |",
        "|---|---|",
    ]
    for pkg, members in sorted(groups.items()):
        fname = f"{pkg}.md"
        body = [f"# `mmlspark_tpu.{pkg}`", ""]
        for qual, cls in members:
            body.append(_stage_section(qual, cls))
        files[fname] = "\n".join(body).rstrip() + "\n"
        names = ", ".join(cls.__name__ for _, cls in members)
        index.append(f"| [{pkg}]({fname}) | {names} |")
    files["README.md"] = "\n".join(index) + "\n"
    return files


def generate(out_dir: str) -> List[str]:
    import os

    os.makedirs(out_dir, exist_ok=True)
    files = render()
    # remove stale generated pages so deleted packages don't linger
    for existing in os.listdir(out_dir):
        if existing.endswith(".md") and existing not in files:
            os.remove(os.path.join(out_dir, existing))
    for fname, content in files.items():
        with open(os.path.join(out_dir, fname), "w") as fh:
            fh.write(content)
    return sorted(files)


def check(out_dir: str) -> List[str]:
    """Paths whose committed content drifts from the code (empty = clean)."""
    import os

    files = render()
    stale = []
    for fname, content in files.items():
        path = os.path.join(out_dir, fname)
        try:
            with open(path) as fh:
                on_disk = fh.read()
        except FileNotFoundError:
            stale.append(f"{path} (missing)")
            continue
        if on_disk != content:
            stale.append(path)
    for existing in sorted(os.listdir(out_dir)) if os.path.isdir(out_dir) else []:
        if existing.endswith(".md") and existing not in files:
            stale.append(os.path.join(out_dir, existing) + " (orphaned)")
    return stale


# -- R bindings (SparklyRWrapper.scala codegen analogue) ---------------------


def _snake(name: str) -> str:
    import re

    s = re.sub(r"(?<=[a-z0-9])([A-Z])", r"_\1", name)
    s = re.sub(r"([A-Z]+)([A-Z][a-z])", r"\1_\2", s)
    return s.lower()


def render_r() -> Dict[str, str]:
    """filename -> content for the generated R package (tools/R/mmlsparktpu).

    One R constructor per concrete public stage, dispatching through
    reticulate — the honest Python-native counterpart of the reference's
    generated sparklyr wrappers (``SparklyRWrapper.scala``, 205 LoC of
    codegen): same coverage guarantee (generated from the live registry,
    CI fails on drift), R-idiomatic snake_case names, roxygen docs carrying
    every param and default."""
    stages = discover_stages()
    lines = [
        "# GENERATED by `python -m mmlspark_tpu.core.apigen` — do not edit.",
        "# One constructor per mmlspark-tpu pipeline stage, via reticulate.",
        "",
        "#' @keywords internal",
        "mt_stage <- function(module, cls, ...) {",
        '  mod <- reticulate::import(module, delay_load = TRUE)',
        "  mod[[cls]](...)",
        "}",
        "",
    ]
    for qual, cls in sorted(stages.items()):
        module, _, cname = qual.rpartition(".")
        fn = "mt_" + _snake(cname)
        doc = (inspect.getdoc(cls) or "").split("\n\n")[0].replace("\n", " ")
        lines.append(f"#' {cls.__name__} ({_kind(cls)})")
        if doc:
            lines.append("#'")
            lines.append(f"#' {doc}")
        params = dict(getattr(cls, "_param_specs", {}))
        if params:
            # the function signature is `...` (kwargs pass through to the
            # Python constructor), so roxygen documents the ONE real
            # argument — per-param detail rides @section to keep
            # `R CMD check`'s usage/doc consistency happy
            lines.append("#'")
            lines.append("#' @section Parameters:")
            lines.append("#' \\itemize{")
            for name in sorted(params):
                p = params[name]
                d = "" if p.default is NO_DEFAULT else f" (default {p.default!r})"
                doc_line = (p.doc or "").replace("\n", " ").replace("%", "\\%")
                lines.append(f"#'   \\item \\code{{{name}}}: {doc_line}{d}")
            lines.append("#' }")
        lines.append(
            "#' @param ... named params forwarded to the Python constructor"
        )
        lines.append("#' @export")
        lines.append(f"{fn} <- function(...) {{")
        lines.append(f'  mt_stage("{module}", "{cls.__name__}", ...)')
        lines.append("}")
        lines.append("")
    files = {
        "R/stages.R": "\n".join(lines).rstrip() + "\n",
        "DESCRIPTION": (
            "Package: mmlsparktpu\n"
            "Title: R bindings for mmlspark-tpu (generated)\n"
            "Version: 0.2.0\n"
            "Description: Generated constructors for every mmlspark-tpu\n"
            "    pipeline stage, dispatching through reticulate. Regenerate\n"
            "    with `python -m mmlspark_tpu.core.apigen`.\n"
            "Imports: reticulate\n"
            "Encoding: UTF-8\n"
            "License: MIT + file LICENSE\n"
        ),
        "LICENSE": "YEAR: 2026\nCOPYRIGHT HOLDER: mmlspark-tpu contributors\n",
        "NAMESPACE": (
            "# GENERATED — every mt_* constructor is exported\n"
            'exportPattern("^mt_")\n'
            "import(reticulate)\n"
        ),
    }
    return files


def generate_r(out_dir: str) -> List[str]:
    import os

    files = render_r()
    for fname, content in files.items():
        path = os.path.join(out_dir, fname)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "w") as fh:
            fh.write(content)
    return sorted(files)


def check_r(out_dir: str) -> List[str]:
    import os

    stale = []
    for fname, content in render_r().items():
        path = os.path.join(out_dir, fname)
        try:
            with open(path) as fh:
                if fh.read() != content:
                    stale.append(path)
        except FileNotFoundError:
            stale.append(f"{path} (missing)")
    return stale


def _default_out_dir() -> str:
    import os

    root = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    return os.path.join(root, "docs", "api")


def _default_r_dir() -> str:
    import os

    root = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    return os.path.join(root, "tools", "R", "mmlsparktpu")


if __name__ == "__main__":
    import sys

    out = _default_out_dir()
    r_out = _default_r_dir()
    if "--check" in sys.argv:
        stale = check(out) + check_r(r_out)
        if stale:
            print("Generated-API drift (run `python -m mmlspark_tpu.core.apigen`):")
            for s in stale:
                print(f"  {s}")
            sys.exit(1)
        print(f"API reference + R bindings up to date ({out}, {r_out})")
    else:
        written = generate(out)
        written_r = generate_r(r_out)
        print(f"wrote {len(written)} pages to {out} and {len(written_r)} files to {r_out}")
