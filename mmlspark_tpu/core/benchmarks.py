"""Golden-file benchmark regression harness.

The reference keeps itself honest with ``Benchmark(name, value, precision,
higherIsBetter)`` rows compared against golden CSVs checked into the test
tree (``core/test/benchmarks/Benchmarks.scala:16-110``;
``src/test/resources/benchmarks/*.csv``). Same contract here: a suite
accumulates benchmarks, writes the "new" CSV next to the golden one for
easy promotion, and ``verify`` fails with a per-row report when a value
regresses beyond its precision.
"""

from __future__ import annotations

import csv
import dataclasses
import os
from typing import Dict, List, Optional


@dataclasses.dataclass
class Benchmark:
    name: str
    value: float
    precision: float
    higher_is_better: bool = True

    def compare(self, golden: "Benchmark") -> Optional[str]:
        """None when within tolerance, else a human-readable failure. The
        golden row's direction governs (a measuring-side direction mistake
        must not flip the check) and disagreement is itself a failure."""
        if self.higher_is_better != golden.higher_is_better:
            return (
                f"{self.name}: higher_is_better mismatch (measured "
                f"{self.higher_is_better}, golden {golden.higher_is_better})"
            )
        if golden.higher_is_better:
            # regressions fail; improvements beyond precision pass
            if self.value < golden.value - golden.precision:
                return (
                    f"{self.name}: {self.value:.5f} regressed below golden "
                    f"{golden.value:.5f} - {golden.precision}"
                )
        else:
            if self.value > golden.value + golden.precision:
                return (
                    f"{self.name}: {self.value:.5f} regressed above golden "
                    f"{golden.value:.5f} + {golden.precision}"
                )
        return None


class BenchmarkSuite:
    """Accumulate benchmarks, then verify against a golden CSV
    (columns: name,value,precision,higher_is_better)."""

    def __init__(self, name: str):
        self.name = name
        self.benchmarks: List[Benchmark] = []

    def add(
        self, name: str, value: float, precision: float, higher_is_better: bool = True
    ) -> None:
        self.benchmarks.append(
            Benchmark(name, float(value), float(precision), higher_is_better)
        )

    @staticmethod
    def read_csv(path: str) -> Dict[str, Benchmark]:
        out: Dict[str, Benchmark] = {}
        with open(path, newline="") as fh:
            for row in csv.DictReader(fh):
                out[row["name"]] = Benchmark(
                    name=row["name"],
                    value=float(row["value"]),
                    precision=float(row["precision"]),
                    higher_is_better=row.get("higher_is_better", "true").lower()
                    in ("1", "true", "yes"),
                )
        return out

    def write_csv(self, path: str) -> None:
        with open(path, "w", newline="") as fh:
            w = csv.writer(fh)
            w.writerow(["name", "value", "precision", "higher_is_better"])
            for b in self.benchmarks:
                w.writerow([b.name, f"{b.value:.6f}", b.precision, str(b.higher_is_better).lower()])

    def verify(self, golden_path: str, new_dir: Optional[str] = None) -> None:
        """Compare against the golden CSV; raises AssertionError listing every
        regressed or unknown row. Writes the measured values to
        ``<golden>.new.csv`` (or into ``new_dir``) so promoting a new golden
        is one file copy — the reference workflow."""
        new_path = (
            os.path.join(new_dir, os.path.basename(golden_path) + ".new.csv")
            if new_dir
            else golden_path + ".new.csv"
        )
        self.write_csv(new_path)
        golden = self.read_csv(golden_path)
        failures: List[str] = []
        for b in self.benchmarks:
            g = golden.get(b.name)
            if g is None:
                failures.append(
                    f"{b.name}: no golden row (promote {new_path} to add it)"
                )
            else:
                msg = b.compare(g)
                if msg:
                    failures.append(msg)
        missing = set(golden) - {b.name for b in self.benchmarks}
        for name in sorted(missing):
            failures.append(f"{name}: golden row never measured this run")
        if failures:
            raise AssertionError(
                f"benchmark regressions in suite {self.name!r}:\n  "
                + "\n  ".join(failures)
            )
