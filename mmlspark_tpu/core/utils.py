"""Small host-side utilities.

Equivalents of the reference's ``core/utils`` + ``core/env`` helpers:
``StopWatch`` (``core/utils/StopWatch.scala``), ``AsyncUtils.bufferedAwait``
(``core/utils/AsyncUtils.scala``), ``FaultToleranceUtils.retryWithTimeout``
(``downloader/ModelDownloader.scala:37-52``), ``StreamUtilities.using``
(``core/env/StreamUtilities.scala``).
"""

from __future__ import annotations

import contextlib
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable, Iterator, List, Optional, Sequence, TypeVar

T = TypeVar("T")
R = TypeVar("R")


class StopWatch:
    """Accumulating nanosecond stopwatch with a measure() context manager."""

    def __init__(self) -> None:
        self.elapsed_ns = 0
        self._start: Optional[int] = None

    def start(self) -> None:
        self._start = time.perf_counter_ns()

    def stop(self) -> None:
        if self._start is not None:
            self.elapsed_ns += time.perf_counter_ns() - self._start
            self._start = None

    @contextlib.contextmanager
    def measure(self) -> Iterator[None]:
        self.start()
        try:
            yield
        finally:
            self.stop()

    @property
    def elapsed_s(self) -> float:
        return self.elapsed_ns / 1e9


def retry(
    fn: Callable[[], T],
    attempts: int = 5,
    initial_delay_s: float = 0.1,
    backoff: float = 2.0,
    retryable: Callable[[Exception], bool] = lambda e: True,
) -> T:
    """Exponential-backoff retry (cf. ``TrainUtils.scala:496-512`` network-init
    retries and ``ModelDownloader.scala:37-52``)."""
    delay = initial_delay_s
    last: Optional[Exception] = None
    for i in range(attempts):
        try:
            return fn()
        except Exception as e:  # noqa: BLE001
            if not retryable(e):
                raise
            last = e
            if i < attempts - 1:
                time.sleep(delay)
                delay *= backoff
    assert last is not None
    raise last


def buffered_parallel_map(
    fn: Callable[[T], R], items: Sequence[T], max_concurrency: int = 8
) -> List[R]:
    """Bounded-concurrency map on a thread pool — ``AsyncUtils.bufferedAwait``.
    Order-preserving. Used for HTTP fan-out and AutoML sweeps, never for
    device compute (which batches instead)."""
    if not items:
        return []
    with ThreadPoolExecutor(max_workers=min(max_concurrency, len(items))) as pool:
        return list(pool.map(fn, items))


@contextlib.contextmanager
def using(*resources: Any) -> Iterator[Sequence[Any]]:
    """RAII for close()-able resources (``StreamUtilities.using``)."""
    try:
        yield resources
    finally:
        for r in reversed(resources):
            with contextlib.suppress(Exception):
                r.close()
