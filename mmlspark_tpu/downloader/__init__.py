"""Pre-trained model downloader (reference ``downloader/``, SURVEY.md §2.14)."""

from mmlspark_tpu.downloader.repository import (
    FaultToleranceUtils,
    LocalRepo,
    ModelDownloader,
    ModelSchema,
    RemoteRepo,
    Repository,
)

__all__ = [
    "FaultToleranceUtils",
    "LocalRepo",
    "ModelDownloader",
    "ModelSchema",
    "RemoteRepo",
    "Repository",
]
