"""Model repository + downloader.

Reference: ``downloader/ModelDownloader.scala:210`` (``Repository``
abstraction with ``HDFSRepo:55`` and ``DefaultModelRepo:125`` over the CDN),
``downloader/Schema.scala`` (``ModelSchema`` JSON: name, uri, hash,
inputNode, layerNames), and ``FaultToleranceUtils.retryWithTimeout``
(``ModelDownloader.scala:37-52``).

TPU adaptation: models are JAX checkpoints / torch state dicts consumed by
:mod:`mmlspark_tpu.dnn`; the local filesystem repo is primary (zero-egress
training images), the remote repo keeps the reference's retry semantics for
deployments with network access.
"""

from __future__ import annotations

import hashlib
import json
import os
import shutil
import time
from dataclasses import asdict, dataclass, field
from typing import Any, Callable, Dict, Iterator, List, Optional, TypeVar

T = TypeVar("T")


@dataclass
class ModelSchema:
    """Model metadata (``downloader/Schema.scala``)."""

    name: str
    uri: str
    hash: Optional[str] = None
    size: Optional[int] = None
    inputNode: Optional[str] = None
    numLayers: Optional[int] = None
    layerNames: List[str] = field(default_factory=list)

    def to_json(self) -> str:
        return json.dumps(asdict(self), indent=2)

    @classmethod
    def from_json(cls, text: str) -> "ModelSchema":
        return cls(**json.loads(text))


class FaultToleranceUtils:
    @staticmethod
    def retry_with_timeout(fn: Callable[[], T], times: int = 3,
                           backoff: float = 0.5,
                           sleep: Optional[Callable[[float], None]] = None) -> T:
        """``FaultToleranceUtils.retryWithTimeout``
        (``ModelDownloader.scala:37-52``), now a thin shim over the shared
        :class:`~mmlspark_tpu.resilience.policy.RetryPolicy` — seeded
        full-jitter backoff replaces the bare ``backoff * 2**attempt``
        (synchronized download retries from a fleet otherwise re-collide),
        and a tighter ambient deadline/retry budget is honored for free."""
        from mmlspark_tpu.resilience.policy import RetryPolicy

        policy = RetryPolicy(
            max_attempts=times, base=backoff, seed=0,
            sleep=sleep if sleep is not None else time.sleep,
        )
        return policy.run(fn, describe="model download")


class Repository:
    """Abstract model store (``Repository`` trait)."""

    def list_schemas(self) -> Iterator[ModelSchema]:
        raise NotImplementedError

    def get_bytes(self, schema: ModelSchema) -> bytes:
        raise NotImplementedError


class LocalRepo(Repository):
    """Directory of ``<name>.json`` schemas next to model payloads — the
    ``HDFSRepo`` role for local/mounted filesystems."""

    def __init__(self, path: str):
        self.path = path

    def list_schemas(self) -> Iterator[ModelSchema]:
        if not os.path.isdir(self.path):
            return
        for fname in sorted(os.listdir(self.path)):
            if fname.endswith(".json"):
                with open(os.path.join(self.path, fname)) as f:
                    yield ModelSchema.from_json(f.read())

    def get_bytes(self, schema: ModelSchema) -> bytes:
        uri = schema.uri
        path = uri[7:] if uri.startswith("file://") else uri
        if not os.path.isabs(path):
            path = os.path.join(self.path, path)
        with open(path, "rb") as f:
            return f.read()

    def add(self, schema: ModelSchema, payload: bytes) -> None:
        os.makedirs(self.path, exist_ok=True)
        with open(os.path.join(self.path, f"{schema.name}.bin"), "wb") as f:
            f.write(payload)
        schema.uri = f"{schema.name}.bin"
        schema.hash = hashlib.sha256(payload).hexdigest()
        schema.size = len(payload)
        with open(os.path.join(self.path, f"{schema.name}.json"), "w") as f:
            f.write(schema.to_json())


class RemoteRepo(Repository):
    """HTTP repo (``DefaultModelRepo`` over the CDN): an index JSON listing
    schemas; payloads fetched by uri with retries."""

    def __init__(self, base_url: str):
        self.base_url = base_url.rstrip("/")

    def list_schemas(self) -> Iterator[ModelSchema]:
        import urllib.request

        def fetch():
            with urllib.request.urlopen(f"{self.base_url}/index.json", timeout=30) as r:
                return json.loads(r.read())

        for entry in FaultToleranceUtils.retry_with_timeout(fetch):
            yield ModelSchema(**entry)

    def get_bytes(self, schema: ModelSchema) -> bytes:
        import urllib.request

        url = schema.uri
        if not url.startswith(("http://", "https://")):
            url = f"{self.base_url}/{url}"

        def fetch():
            with urllib.request.urlopen(url, timeout=120) as r:
                return r.read()

        return FaultToleranceUtils.retry_with_timeout(fetch)


class ModelDownloader:
    """Downloads models from a repo into a local cache dir, verifying hashes
    (``ModelDownloader.scala:210+``)."""

    def __init__(self, local_path: str, repo: Optional[Repository] = None):
        self.local_path = local_path
        self.repo = repo if repo is not None else LocalRepo(local_path)

    def list_models(self) -> List[ModelSchema]:
        return list(self.repo.list_schemas())

    def download_by_name(self, name: str) -> str:
        for schema in self.repo.list_schemas():
            if schema.name == name:
                return self.download_model(schema)
        raise KeyError(f"no model named {name!r} in repository")

    def download_model(self, schema: ModelSchema) -> str:
        """Returns the local path of the (cached) payload."""
        os.makedirs(self.local_path, exist_ok=True)
        dest = os.path.join(self.local_path, f"{schema.name}.bin")
        if os.path.exists(dest) and schema.hash:
            with open(dest, "rb") as f:
                if hashlib.sha256(f.read()).hexdigest() == schema.hash:
                    return dest
        payload = self.repo.get_bytes(schema)
        if schema.hash:
            got = hashlib.sha256(payload).hexdigest()
            if got != schema.hash:
                raise IOError(
                    f"hash mismatch for {schema.name}: want {schema.hash}, got {got}"
                )
        with open(dest, "wb") as f:
            f.write(payload)
        return dest
