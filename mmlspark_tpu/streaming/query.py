"""The micro-batch engine: triggers, offset WAL, commit log, restart.

This is the Structured Streaming ``StreamExecution`` analogue (PAPER.md
layer 4): a :class:`StreamingQuery` repeatedly plans an epoch (a slice of
new source offsets), durably logs the plan, runs the sink, then durably
logs the commit. The two logs live under the checkpoint location:

    <checkpoint>/offsets/<epoch>.json   — written BEFORE processing (WAL):
                                          {"epoch", "start", "end", "manifest"}
    <checkpoint>/commits/<epoch>.json   — written AFTER the sink returns:
                                          {"epoch", "start", "end", "rows"}
    <checkpoint>/deadletter/            — epoch-keyed dead-letter store for
                                          records quarantined by a
                                          permissive/dropmalformed source
                                          (see mmlspark_tpu.dataguard.dlq)

Restart contract (the ``checkpointLocation`` semantics):

- the last *committed* epoch fixes the resume offset — committed epochs
  are never re-planned and never re-processed;
- an epoch whose WAL exists but whose commit is missing (the process died
  mid-epoch) is *replayed from its recorded manifest* — the identical
  unit list, even if the source directory has since grown;
- the sink absorbs the replay idempotently (epoch-keyed dedup — see
  :mod:`mmlspark_tpu.streaming.sink`), so delivery is exactly-once end to
  end under a SIGKILL at any point.

Triggers mirror Spark's: :class:`ProcessingTime` (tick every interval),
:class:`Once` (one epoch then terminate), :class:`AvailableNow` (drain
the backlog in rate-limited epochs, then terminate).

Chaos integration: at two designated points per epoch (``post_wal`` —
plan logged, nothing processed; ``pre_commit`` — sink done, commit log
missing: the nastiest window) the query consults the ambient
:class:`~mmlspark_tpu.runtime.faults.FaultPlan` and honors a registered
``kill_stream`` directive with a real ``SIGKILL`` of its own process —
the restart-from-checkpoint contract is CI-enforced the same way
``FitJournal`` resume is (tools/streaming_chaos_smoke.py).
"""

from __future__ import annotations

import json
import os
import signal
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from mmlspark_tpu.core.profiling import get_logger
from mmlspark_tpu.observability.events import (
    StreamEpochCommitted,
    StreamEpochStarted,
    StreamSourceAdvanced,
    get_bus,
)
from mmlspark_tpu.observability.registry import get_registry
from mmlspark_tpu.runtime.journal import _atomic_write, default_checkpoint_dir
from mmlspark_tpu.streaming.sink import Sink
from mmlspark_tpu.streaming.source import StreamSource

logger = get_logger("mmlspark_tpu.streaming")

#: epoch-batch sizes are small; latency buckets would bunch in one bucket
_EPOCH_SECONDS_BUCKETS = (
    0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 15.0, 60.0,
)


class Trigger:
    """When the query plans its next epoch."""


class ProcessingTime(Trigger):
    """Tick every ``interval_s`` seconds (Spark's default trigger shape)."""

    def __init__(self, interval_s: float = 1.0):
        self.interval_s = float(interval_s)


class Once(Trigger):
    """Process exactly one epoch (if data is available), then terminate."""


class AvailableNow(Trigger):
    """Drain everything currently available as rate-limited epochs
    (``max_per_trigger`` applies per epoch), then terminate."""


class StreamingQuery:
    """One continuous source → sink pipeline with durable epoch commits.

    With no checkpoint location (``checkpoint_dir=None`` and no ambient
    ``MMLSPARK_TPU_CHECKPOINT_DIR``) the query still runs — offsets live
    in memory and a restart starts over, exactly like an un-checkpointed
    Spark query.
    """

    def __init__(
        self,
        source: StreamSource,
        sink: Sink,
        trigger: Optional[Trigger] = None,
        name: str = "query",
        checkpoint_dir: Optional[str] = None,
        registry=None,
    ):
        self.source = source
        self.sink = sink
        self.trigger = trigger or Once()
        self.name = name
        if checkpoint_dir is None:
            root = default_checkpoint_dir()
            if root is not None:
                checkpoint_dir = os.path.join(root, "streaming", name)
        self.checkpoint_dir = checkpoint_dir
        self._offset = 0
        self._next_epoch = 0
        #: (epoch, start, end, manifest) of a WAL'd-but-uncommitted epoch
        self._replay: Optional[Tuple[int, int, int, List[Any]]] = None
        self._stop = threading.Event()
        self._terminated = threading.Event()
        self._terminated.set()
        self._thread: Optional[threading.Thread] = None
        #: the exception that terminated the query, if any
        self.exception: Optional[BaseException] = None
        self.last_progress: Dict[str, Any] = {}
        reg = registry if registry is not None else get_registry()
        labels = {"query": name}
        self._reg_epochs = reg.counter(
            "streaming_epochs_total", "Micro-batch epochs committed"
        ).labels(**labels)
        self._reg_rows = reg.counter(
            "streaming_rows_total", "Rows processed by committed epochs"
        ).labels(**labels)
        self._reg_epoch_s = reg.histogram(
            "streaming_epoch_seconds", "Plan-to-commit time per epoch",
            buckets=_EPOCH_SECONDS_BUCKETS,
        ).labels(**labels)
        self._reg_offset = reg.gauge(
            "streaming_offset", "Committed source offset"
        ).labels(**labels)
        #: dead-letter store for source quarantines (checkpointed only):
        #: epoch-keyed under the WAL epoch, so a replayed epoch that
        #: re-quarantines the same corrupt records letters them once
        self.dead_letters = None
        if self.checkpoint_dir is not None:
            os.makedirs(os.path.join(self.checkpoint_dir, "offsets"), exist_ok=True)
            os.makedirs(os.path.join(self.checkpoint_dir, "commits"), exist_ok=True)
            from mmlspark_tpu.dataguard.dlq import DeadLetterStore

            self.dead_letters = DeadLetterStore(
                os.path.join(self.checkpoint_dir, "deadletter"),
                name=name, registry=reg,
            )
            self._restore()

    # -- checkpoint ----------------------------------------------------------

    def _log_path(self, kind: str, epoch: int) -> str:
        assert self.checkpoint_dir is not None
        return os.path.join(self.checkpoint_dir, kind, f"{epoch:06d}.json")

    @staticmethod
    def _read_log(path: str) -> Optional[Dict[str, Any]]:
        try:
            with open(path, "r", encoding="utf-8") as fh:
                return json.load(fh)
        except (OSError, ValueError):
            return None

    def _scan_epochs(self, kind: str) -> List[int]:
        try:
            names = os.listdir(os.path.join(self.checkpoint_dir, kind))
        except OSError:
            return []
        return sorted(
            int(n[:-5]) for n in names if n.endswith(".json") and n[:-5].isdigit()
        )

    def _restore(self) -> None:
        """Resume offsets from the commit log; arm replay for a planned
        epoch the last run never committed."""
        commits = self._scan_epochs("commits")
        if commits:
            last = commits[-1]
            rec = self._read_log(self._log_path("commits", last))
            if rec is not None:
                self._offset = int(rec.get("end", 0))
                self._next_epoch = last + 1
        wal = self._read_log(self._log_path("offsets", self._next_epoch))
        if wal is not None:
            self._replay = (
                self._next_epoch,
                int(wal.get("start", self._offset)),
                int(wal.get("end", self._offset)),
                list(wal.get("manifest", [])),
            )
            logger.info(
                "query %r: replaying uncommitted epoch %d (offsets [%d, %d))",
                self.name, self._next_epoch, self._replay[1], self._replay[2],
            )
        if commits or self._replay is not None:
            logger.info(
                "query %r restored: next epoch %d, offset %d",
                self.name, self._next_epoch, self._offset,
            )

    def _write_wal(
        self, epoch: int, start: int, end: int, manifest: List[Any]
    ) -> None:
        if self.checkpoint_dir is None:
            return
        _atomic_write(
            self._log_path("offsets", epoch),
            json.dumps({
                "epoch": epoch, "start": start, "end": end,
                "manifest": manifest,
            }).encode("utf-8"),
        )

    def _write_commit(self, epoch: int, start: int, end: int, rows: int) -> None:
        if self.checkpoint_dir is None:
            return
        _atomic_write(
            self._log_path("commits", epoch),
            json.dumps({
                "epoch": epoch, "start": start, "end": end, "rows": rows,
            }).encode("utf-8"),
        )

    @property
    def committed_epochs(self) -> List[int]:
        if self.checkpoint_dir is None:
            return list(range(self._next_epoch))
        return self._scan_epochs("commits")

    # -- chaos ---------------------------------------------------------------

    def _maybe_die(self, epoch: int, point: str) -> None:
        """Honor an ambient ``kill_stream`` directive with a REAL SIGKILL
        of this process — no Python cleanup, no atexit: the death the
        checkpoint contract exists for."""
        from mmlspark_tpu.runtime.faults import current_faults

        plan = current_faults()
        if plan is not None and plan.should_kill_stream(epoch, point):
            logger.warning(
                "query %r: injected SIGKILL at epoch %d (%s)",
                self.name, epoch, point,
            )
            os.kill(os.getpid(), signal.SIGKILL)

    # -- the epoch loop ------------------------------------------------------

    def process_next(self) -> Optional[int]:
        """Plan + process + commit one epoch. Returns rows processed, or
        None when the source has nothing new."""
        t0 = time.perf_counter()
        if self._replay is not None:
            epoch, start, end, manifest = self._replay
        else:
            end = self.source.latest_offset()
            cap = self.source.max_per_trigger
            if cap is not None and cap > 0:
                end = min(end, self._offset + cap)
            if end <= self._offset:
                return None
            epoch, start = self._next_epoch, self._offset
            manifest = self.source.plan_batch(start, end)
            self._write_wal(epoch, start, end, manifest)
        bus = get_bus()
        if bus.active:
            bus.publish(StreamEpochStarted(
                query=self.name, epoch=epoch, start=start, end=end,
            ))
            bus.publish(StreamSourceAdvanced(
                query=self.name, start=start, end=end, units=len(manifest),
            ))
        self._maybe_die(epoch, "post_wal")
        table = self.source.load_batch(manifest)
        quarantined = list(getattr(self.source, "last_quarantined", ()))
        if quarantined and self.dead_letters is not None:
            # Before the sink, after the WAL: a pre_commit SIGKILL replays
            # the epoch, re-quarantines the same records, and commit_epoch
            # finds the manifest already present — exactly-once either way.
            self.dead_letters.commit_epoch(epoch, quarantined)
        self.sink.process_batch(epoch, table)
        self._maybe_die(epoch, "pre_commit")
        rows = table.num_rows
        self._write_commit(epoch, start, end, rows)
        self._replay = None
        self._offset = end
        self._next_epoch = epoch + 1
        duration = time.perf_counter() - t0
        self._reg_epochs.inc()
        self._reg_rows.inc(rows)
        self._reg_epoch_s.observe(duration)
        self._reg_offset.set(end)
        self.last_progress = {
            "epoch": epoch, "start": start, "end": end, "rows": rows,
            "duration_s": duration,
        }
        if bus.active:
            bus.publish(StreamEpochCommitted(
                query=self.name, epoch=epoch, rows=rows, duration=duration,
            ))
        return rows

    def process_all_available(self) -> int:
        """Drain the backlog synchronously; returns total rows processed."""
        total = 0
        while not self._stop.is_set():
            rows = self.process_next()
            if rows is None:
                break
            total += rows
        return total

    # -- lifecycle -----------------------------------------------------------

    def _run(self) -> None:
        try:
            if isinstance(self.trigger, Once):
                self.process_next()
            elif isinstance(self.trigger, AvailableNow):
                self.process_all_available()
            else:
                interval = self.trigger.interval_s  # type: ignore[attr-defined]
                while not self._stop.is_set():
                    t0 = time.monotonic()
                    self.process_all_available()
                    elapsed = time.monotonic() - t0
                    self._stop.wait(max(0.0, interval - elapsed))
        except Exception as e:  # noqa: BLE001 - terminates + surfaces the query
            self.exception = e
            logger.warning(
                "query %r terminated by %s: %s", self.name, type(e).__name__, e
            )
        finally:
            self._terminated.set()

    def start(self) -> "StreamingQuery":
        """Run the trigger loop on a background thread (``Once`` and
        ``AvailableNow`` terminate on their own; ``ProcessingTime`` runs
        until :meth:`stop`)."""
        if self._thread is not None and self._thread.is_alive():
            raise RuntimeError(f"query {self.name!r} is already running")
        self._stop.clear()
        self._terminated.clear()
        self.exception = None
        self._thread = threading.Thread(
            target=self._run, daemon=True, name=f"stream-{self.name}"
        )
        self._thread.start()
        return self

    @property
    def active(self) -> bool:
        return not self._terminated.is_set()

    def await_termination(self, timeout: Optional[float] = None) -> bool:
        """Block until the trigger loop terminates; True when it did."""
        return self._terminated.wait(timeout)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=30.0)
            self._thread = None

    def __enter__(self) -> "StreamingQuery":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()
