"""Streaming sinks — epoch-keyed, idempotent batch consumers.

The exactly-once story of the micro-batch engine is split the way
Structured Streaming splits it: the query guarantees *at-least-once*
delivery of each planned epoch (offset WAL before processing, commit log
after), and the sink guarantees *idempotence per epoch id* — re-delivery
of an epoch the sink already processed must change nothing. Together
that is exactly-once end to end, surviving a SIGKILL at any point.

- :class:`MemorySink` — collects batches for tests (Spark's memory
  sink); duplicate epochs are dropped;
- :class:`ForeachBatchSink` — ``foreachBatch(fn)``: the user callable
  receives ``(table, epoch)``; duplicate epochs are dropped before the
  callable runs;
- :class:`ModelCommitSink` — the tentpole consumer: each micro-batch
  runs an incremental warm-start LightGBM fit (``modelString`` chaining
  + :func:`~mmlspark_tpu.lightgbm.base._merge_boosters`, the same
  machinery ``numBatches`` uses) and commits the merged booster through
  :class:`~mmlspark_tpu.runtime.journal.FitJournal` (epoch-keyed,
  CRC-checksummed) and the :class:`~mmlspark_tpu.runtime.journal.ModelStore`
  atomic ``CURRENT`` swap a hot-swapping server watches. The journal
  record is the epoch's durability point; the store commit is
  text-deduplicated, so a crash in any window between the two re-runs at
  most one *uncommitted* fit and never double-applies an epoch's trees.
"""

from __future__ import annotations

import os
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from mmlspark_tpu.core.profiling import get_logger
from mmlspark_tpu.data.table import Table

logger = get_logger("mmlspark_tpu.streaming")


class Sink:
    """Epoch-keyed batch consumer. ``process_batch`` MUST be idempotent in
    ``epoch``: the query re-delivers the last planned epoch after a crash
    (offset WAL replay), and the sink absorbs the duplicate."""

    def process_batch(self, epoch: int, table: Table) -> Any:
        raise NotImplementedError


class MemorySink(Sink):
    """Collects processed batches in memory (the ``memory`` sink)."""

    def __init__(self) -> None:
        self.batches: List[Tuple[int, Table]] = []
        self._seen: set = set()

    def process_batch(self, epoch: int, table: Table) -> None:
        if epoch in self._seen:
            logger.warning("memory sink dropped duplicate epoch %d", epoch)
            return
        self._seen.add(epoch)
        self.batches.append((epoch, table))

    @property
    def rows(self) -> int:
        return sum(t.num_rows for _, t in self.batches)

    def table(self) -> Table:
        """All processed rows as one table, in epoch order."""
        ordered = [t for _, t in sorted(self.batches) if t.num_rows]
        if not ordered:
            return Table({})
        return Table.concat(ordered)


class ForeachBatchSink(Sink):
    """``foreachBatch``: hand each micro-batch to ``fn(table, epoch)``.
    Duplicate epochs (WAL replay after a crash) are dropped before the
    callable runs, so ``fn`` sees each epoch at most once per process;
    cross-restart idempotence is the callable's contract, as in Spark."""

    def __init__(self, fn: Callable[[Table, int], Any]):
        self.fn = fn
        self._seen: set = set()

    def process_batch(self, epoch: int, table: Table) -> Any:
        if epoch in self._seen:
            logger.warning("foreachBatch dropped duplicate epoch %d", epoch)
            return None
        self._seen.add(epoch)
        return self.fn(table, epoch)


class ModelCommitSink(Sink):
    """Incremental warm-start fit per micro-batch + durable model commit.

    ``estimator_factory`` builds a fresh estimator per epoch (e.g.
    ``lambda: LightGBMClassifier(numIterations=10, seed=7)``); the sink
    chains epochs by setting ``modelString`` to the previous committed
    ensemble, fits the new chunk only, merges the delta booster onto the
    ensemble (:func:`~mmlspark_tpu.lightgbm.base._merge_boosters` — the
    ``LGBM_BoosterMerge`` analogue ``numBatches`` already uses), and
    commits:

    1. ``FitJournal.record(epoch, merged_text)`` — the durability point:
       a journaled epoch is never refitted (zero re-execution);
    2. ``ModelStore.commit`` under ``name`` — skipped when the store's
       latest text already equals the merged text, so a crash between
       (1) and (2) repairs the store on replay instead of re-committing,
       and the version sequence matches an undisturbed run exactly.

    The serving plane watches the store's ``CURRENT`` pointer
    (:meth:`~mmlspark_tpu.serving.ServingServer.enable_hot_swap`), which
    closes the loop: ingest → incremental fit → live commit → hot serve.
    """

    def __init__(
        self,
        estimator_factory: Callable[[], Any],
        name: str = "model",
        root: Optional[str] = None,
        registry=None,
    ):
        from mmlspark_tpu.observability.registry import get_registry
        from mmlspark_tpu.runtime.journal import (
            FitJournal,
            ModelStore,
            default_checkpoint_dir,
        )

        root = root or default_checkpoint_dir()
        if root is None:
            raise ValueError(
                "ModelCommitSink needs a durable root: pass root= or set "
                "MMLSPARK_TPU_CHECKPOINT_DIR"
            )
        self.name = name
        self.root = root
        self._factory = estimator_factory
        self.store = ModelStore(os.path.join(root, "models"))
        self._journal = FitJournal(
            os.path.join(root, "streaming-models"), key=name
        )
        #: epoch -> committed ensemble text, restored at startup so a
        #: journaled epoch is never refitted
        self._committed: Dict[int, str] = {
            int(k): str(v) for k, v in self._journal.restore().items()
        }
        #: store versions committed (or found already current) per epoch
        self.versions: Dict[int, int] = {}
        reg = registry if registry is not None else get_registry()
        self._reg_version = reg.gauge(
            "streaming_model_version",
            "Latest model version committed by the streaming fit sink",
        )
        self._reg_fit = reg.histogram(
            "streaming_fit_seconds", "Incremental fit time per micro-batch",
            buckets=(0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0),
        )

    # -- state ---------------------------------------------------------------

    @property
    def committed_epochs(self) -> List[int]:
        return sorted(self._committed)

    def latest_text(self) -> Optional[str]:
        """The committed ensemble text of the highest journaled epoch."""
        if not self._committed:
            return None
        return self._committed[max(self._committed)]

    # -- the epoch commit ----------------------------------------------------

    def process_batch(self, epoch: int, table: Table) -> int:
        epoch = int(epoch)
        if epoch in self._committed:
            # WAL replay of an already-journaled epoch: no refit; just make
            # sure the store commit (step 2) also happened before the crash
            logger.info(
                "streaming sink: epoch %d already journaled; skipping refit",
                epoch,
            )
            return self._ensure_store(epoch, self._committed[epoch])
        if table.num_rows == 0:
            # every record of the epoch quarantined (permissive source over
            # a fully-corrupt batch): fitting zero rows would either fail
            # or commit a spurious ensemble delta — skip, so the model
            # stays byte-identical to a fit over the clean complement
            logger.info(
                "streaming sink: epoch %d has no surviving rows; skipping fit",
                epoch,
            )
            latest = self.store.latest(self.name)
            return latest[0] if latest is not None else -1
        merged_text = self._fit_epoch(epoch, table)
        self._journal.record(epoch, merged_text)
        self._committed[epoch] = merged_text
        return self._ensure_store(epoch, merged_text)

    def _fit_epoch(self, epoch: int, table: Table) -> str:
        from mmlspark_tpu.lightgbm.base import _merge_boosters
        from mmlspark_tpu.lightgbm.booster import Booster

        base_text = self.latest_text()
        est = self._factory()
        if base_text:
            est.set("modelString", base_text)
        t0 = time.perf_counter()
        model = est.fit(table)
        self._reg_fit.observe(time.perf_counter() - t0)
        delta = model.booster
        if base_text:
            merged = _merge_boosters([Booster.from_string(base_text), delta])
        else:
            merged = delta
        return merged.model_to_string()

    def _ensure_store(self, epoch: int, text: str) -> int:
        """Idempotent store commit: a replay whose text is already CURRENT
        commits nothing, so version numbers track distinct ensembles."""
        latest = self.store.latest(self.name)
        if latest is not None and latest[1] == text:
            version = latest[0]
        else:
            version = self.store.commit(text, name=self.name)
            from mmlspark_tpu.observability.events import ModelCommitted, get_bus

            bus = get_bus()
            if bus.active:
                bus.publish(ModelCommitted(
                    model=self.name, version=version,
                    detail=f"stream epoch {epoch}",
                ))
        self.versions[epoch] = version
        self._reg_version.set(version)
        return version

    def close(self) -> None:
        self._journal.close()
