"""Micro-batch streaming engine (reference Spark Structured Streaming +
Spark Serving ingestion, PAPER.md layer 4).

Continuous ingest → incremental fit → durable model commit → hot serving:

    source = FileStreamSource("/data/incoming", pattern="part-*.npz")
    sink = ModelCommitSink(lambda: LightGBMClassifier(numIterations=10))
    with StreamingQuery(source, sink, trigger=AvailableNow()) as query:
        query.await_termination()
"""

from mmlspark_tpu.streaming.query import (
    AvailableNow,
    Once,
    ProcessingTime,
    StreamingQuery,
    Trigger,
)
from mmlspark_tpu.streaming.sink import (
    ForeachBatchSink,
    MemorySink,
    ModelCommitSink,
    Sink,
)
from mmlspark_tpu.streaming.source import (
    FileStreamSource,
    MemoryStream,
    StreamSource,
)

__all__ = [
    "AvailableNow",
    "FileStreamSource",
    "ForeachBatchSink",
    "MemorySink",
    "MemoryStream",
    "ModelCommitSink",
    "Once",
    "ProcessingTime",
    "Sink",
    "StreamSource",
    "StreamingQuery",
    "Trigger",
]
