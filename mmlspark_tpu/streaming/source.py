"""Streaming sources — the Structured Streaming ``Source`` analogue.

Spark's micro-batch engine (PAPER.md layer 4, ``sql/execution/streaming/``)
talks to a source through three ideas: a monotonically-growing *offset*
(how much data exists), a *planned batch* (the exact slice an epoch will
process, durably logged before processing so a restarted query replays the
identical slice), and the *batch load* itself. This module is that
contract for the TPU framework:

- :class:`StreamSource` — the three-method contract. Offsets are plain
  ints (units consumed so far); a plan is a JSON-serializable *manifest*
  naming the exact units, so the offset WAL pins a replayed epoch to the
  same bytes even if the directory grew in between;
- :class:`FileStreamSource` — the ``FileStreamSource`` analogue: a
  directory watcher consuming files in lexicographic name order
  (producers write ``part-00000.npz``, ``part-00001.npz``, ... — atomic
  rename into place; ``*.tmp`` and dotfiles are invisible). ``.npz``
  files load as named columns, ``.json``/``.jsonl`` as row objects;
- :class:`MemoryStream` — the in-memory test source (Spark's
  ``MemoryStream``): each :meth:`MemoryStream.add` call appends one
  block; not durable across processes, by design.

``max_per_trigger`` is the ``maxFilesPerTrigger`` rate limit: the query
caps each epoch at that many new units so a backlog drains as several
bounded micro-batches instead of one giant one.

Corrupt-record read modes (dataguard):
``FileStreamSource(..., mode="permissive")`` turns a torn npz, a stale
CRC sidecar, or an undecodable jsonl line into a quarantine instead of
an epoch-killing exception — whole-file failures quarantine the file
(``index`` -1), jsonl decode failures quarantine the single line and
keep the rest. The quarantines of the most recent ``load_batch`` are
exposed as ``last_quarantined``, which
:class:`~mmlspark_tpu.streaming.query.StreamingQuery` commits to the
epoch-keyed dead-letter store under its WAL. ``dropmalformed`` drops
and counts; ``failfast`` (default) re-raises like before.
"""

from __future__ import annotations

import fnmatch
import json
import os
import zipfile
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from mmlspark_tpu.core.profiling import get_logger
from mmlspark_tpu.data.table import Table
from mmlspark_tpu.dataguard.modes import (
    FAILFAST,
    CorruptRecord,
    normalize_mode,
)
from mmlspark_tpu.runtime.faults import (
    CorruptShardError,
    check_record,
    corrupt_record_bytes,
)
from mmlspark_tpu.runtime.lineage import PartitionLostError

logger = get_logger("mmlspark_tpu.streaming")

#: error classes a corrupt stream file can surface as at decode time
_RECORD_ERRORS = (
    CorruptShardError,
    PartitionLostError,
    zipfile.BadZipFile,
    ValueError,  # includes json.JSONDecodeError and UnicodeDecodeError
    KeyError,
    OSError,
)


class StreamSource:
    """Offset-tracked input contract for the micro-batch engine.

    Offsets are integers counting units (files, blocks) available so
    far; they only grow. ``plan_batch`` turns an offset range into a
    JSON-serializable manifest; ``load_batch`` materializes a manifest
    into a :class:`~mmlspark_tpu.data.table.Table`. The split exists so
    the query's offset WAL can pin a replayed epoch to the exact units
    the crashed run planned, not whatever the source sees now.
    """

    #: per-epoch unit cap (the ``maxFilesPerTrigger`` rate limit); None = all
    max_per_trigger: Optional[int] = None

    def latest_offset(self) -> int:
        raise NotImplementedError

    def plan_batch(self, start: int, end: int) -> List[Any]:
        raise NotImplementedError

    def load_batch(self, manifest: Sequence[Any]) -> Table:
        raise NotImplementedError


def _load_npz(path: str) -> Table:
    check_record(path)
    _verify_sidecar(path)
    with np.load(path, allow_pickle=False) as npz:
        return Table({name: npz[name] for name in npz.files})


def _verify_sidecar(path: str) -> None:
    """CRC-check ``path`` against a ``<path>.crc32`` sidecar when one
    exists (producers that write sidecars get end-to-end integrity on
    the streaming path too; a mismatch raises PartitionLostError)."""
    if os.path.exists(path + ".crc32"):
        from mmlspark_tpu.data.sharded import _verify_shard

        _verify_shard(path)


def _load_json_rows(
    path: str,
    mode: str = FAILFAST,
    quarantined: Optional[List[CorruptRecord]] = None,
) -> Table:
    """Load a json/jsonl file as row objects. Under a non-failfast
    ``mode`` an undecodable jsonl *line* quarantines (appended to
    ``quarantined`` with its line index) and the rest of the file
    survives — the per-record path; array-form ``.json`` files decode
    all-or-nothing, so their failures quarantine the whole file."""
    check_record(path)
    rows: List[Dict[str, Any]] = []
    with open(path, "rb") as fh:
        raw = fh.read()
    text = raw.decode("utf-8").strip()
    if text.startswith("["):
        rows = json.loads(text)
        return Table.from_rows(rows)
    for i, line in enumerate(text.splitlines()):
        if not line.strip():
            continue
        data = corrupt_record_bytes(path, i, line.encode("utf-8"))
        try:
            rows.append(json.loads(data.decode("utf-8")))
        except ValueError as e:  # JSONDecodeError and UnicodeDecodeError
            if mode == FAILFAST or quarantined is None:
                raise
            quarantined.append(CorruptRecord.from_error(path, e, index=i))
    return Table.from_rows(rows)


_LOADERS: Dict[str, Callable[[str], Table]] = {
    ".npz": _load_npz,
    ".json": _load_json_rows,
    ".jsonl": _load_json_rows,
}


class FileStreamSource(StreamSource):
    """Directory watcher consuming files in lexicographic name order.

    The offset is "how many files (sorted by name) have been made
    available"; producers therefore name files monotonically
    (``part-00000.npz``, ``part-00001.npz``, ...) and publish them
    atomically (write ``name.tmp``, then rename) — ``*.tmp`` and
    dotfiles never enter the listing, so a half-written file is
    invisible exactly the way an uncommitted Spark output file is.
    """

    def __init__(
        self,
        path: str,
        pattern: str = "*",
        loader: Optional[Callable[[str], Table]] = None,
        max_per_trigger: Optional[int] = None,
        mode: str = FAILFAST,
    ):
        self.path = path
        self.pattern = pattern
        self._loader = loader
        self.max_per_trigger = max_per_trigger
        self.mode = normalize_mode(mode)
        #: quarantines from the most recent ``load_batch`` — the query
        #: dead-letters these under its WAL epoch
        self.last_quarantined: List[CorruptRecord] = []
        #: ordered names already exposed through ``latest_offset`` — a name
        #: never moves once listed, so offsets stay stable across rescans
        self._files: List[str] = []

    def _scan(self) -> List[str]:
        try:
            names = os.listdir(self.path)
        except OSError:
            return []
        return sorted(
            n for n in names
            if fnmatch.fnmatch(n, self.pattern)
            and not n.startswith(".")
            and not n.endswith(".tmp")
        )

    def latest_offset(self) -> int:
        seen = set(self._files)
        fresh = [n for n in self._scan() if n not in seen]
        if fresh:
            # append-only: files already listed keep their index even if a
            # late-arriving name would sort before them
            self._files.extend(sorted(fresh))
        return len(self._files)

    def plan_batch(self, start: int, end: int) -> List[str]:
        if end > len(self._files):
            self.latest_offset()
        if not 0 <= start <= end <= len(self._files):
            raise ValueError(
                f"offset range [{start}, {end}) outside the {len(self._files)} "
                f"files listed under {self.path}"
            )
        return list(self._files[start:end])

    def load_batch(self, manifest: Sequence[str]) -> Table:
        self.last_quarantined = []
        tables = []
        for name in manifest:
            try:
                tables.append(self._load_one(name))
            except _RECORD_ERRORS as e:
                if self.mode == FAILFAST:
                    raise
                full = os.path.join(self.path, name)
                self.last_quarantined.append(CorruptRecord.from_error(full, e))
                logger.warning(
                    "stream source %s: quarantined %s (%s: %s)",
                    self.path, name, type(e).__name__, e,
                )
        if not tables:
            return Table({})
        return Table.concat(tables)

    def _load_one(self, name: str) -> Table:
        full = os.path.join(self.path, name)
        if self._loader is not None:
            check_record(full)
            return self._loader(full)
        ext = os.path.splitext(name)[1].lower()
        if ext in (".json", ".jsonl"):
            # per-record tolerance: line failures land in last_quarantined,
            # whole-file failures propagate to load_batch's handler
            return _load_json_rows(
                full, mode=self.mode, quarantined=self.last_quarantined
            )
        loader = _LOADERS.get(ext)
        if loader is None:
            raise ValueError(
                f"no loader for {name!r} (supported: {sorted(_LOADERS)}; "
                "pass loader= for custom formats)"
            )
        return loader(full)


class MemoryStream(StreamSource):
    """In-memory block source for tests (Spark's ``MemoryStream``): each
    :meth:`add` appends one block of rows; offsets count blocks. State
    lives in this process only — checkpointed queries over a
    ``MemoryStream`` replay nothing after a restart, exactly like the
    Spark original."""

    def __init__(self, max_per_trigger: Optional[int] = None):
        self._blocks: List[Table] = []
        self.max_per_trigger = max_per_trigger

    def add(self, table: Table) -> int:
        """Append one block; returns the new latest offset."""
        self._blocks.append(table)
        return len(self._blocks)

    def latest_offset(self) -> int:
        return len(self._blocks)

    def plan_batch(self, start: int, end: int) -> List[int]:
        if not 0 <= start <= end <= len(self._blocks):
            raise ValueError(
                f"offset range [{start}, {end}) outside {len(self._blocks)} "
                "blocks"
            )
        return list(range(start, end))

    def load_batch(self, manifest: Sequence[int]) -> Table:
        missing = [i for i in manifest if not 0 <= i < len(self._blocks)]
        if missing:
            raise ValueError(
                f"blocks {missing} not present (MemoryStream state does not "
                "survive a restart; use FileStreamSource for durable replay)"
            )
        tables = [self._blocks[i] for i in manifest]
        if not tables:
            return Table({})
        return Table.concat(tables)
