"""Streaming sources — the Structured Streaming ``Source`` analogue.

Spark's micro-batch engine (PAPER.md layer 4, ``sql/execution/streaming/``)
talks to a source through three ideas: a monotonically-growing *offset*
(how much data exists), a *planned batch* (the exact slice an epoch will
process, durably logged before processing so a restarted query replays the
identical slice), and the *batch load* itself. This module is that
contract for the TPU framework:

- :class:`StreamSource` — the three-method contract. Offsets are plain
  ints (units consumed so far); a plan is a JSON-serializable *manifest*
  naming the exact units, so the offset WAL pins a replayed epoch to the
  same bytes even if the directory grew in between;
- :class:`FileStreamSource` — the ``FileStreamSource`` analogue: a
  directory watcher consuming files in lexicographic name order
  (producers write ``part-00000.npz``, ``part-00001.npz``, ... — atomic
  rename into place; ``*.tmp`` and dotfiles are invisible). ``.npz``
  files load as named columns, ``.json``/``.jsonl`` as row objects;
- :class:`MemoryStream` — the in-memory test source (Spark's
  ``MemoryStream``): each :meth:`MemoryStream.add` call appends one
  block; not durable across processes, by design.

``max_per_trigger`` is the ``maxFilesPerTrigger`` rate limit: the query
caps each epoch at that many new units so a backlog drains as several
bounded micro-batches instead of one giant one.
"""

from __future__ import annotations

import fnmatch
import json
import os
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from mmlspark_tpu.core.profiling import get_logger
from mmlspark_tpu.data.table import Table

logger = get_logger("mmlspark_tpu.streaming")


class StreamSource:
    """Offset-tracked input contract for the micro-batch engine.

    Offsets are integers counting units (files, blocks) available so
    far; they only grow. ``plan_batch`` turns an offset range into a
    JSON-serializable manifest; ``load_batch`` materializes a manifest
    into a :class:`~mmlspark_tpu.data.table.Table`. The split exists so
    the query's offset WAL can pin a replayed epoch to the exact units
    the crashed run planned, not whatever the source sees now.
    """

    #: per-epoch unit cap (the ``maxFilesPerTrigger`` rate limit); None = all
    max_per_trigger: Optional[int] = None

    def latest_offset(self) -> int:
        raise NotImplementedError

    def plan_batch(self, start: int, end: int) -> List[Any]:
        raise NotImplementedError

    def load_batch(self, manifest: Sequence[Any]) -> Table:
        raise NotImplementedError


def _load_npz(path: str) -> Table:
    with np.load(path, allow_pickle=False) as npz:
        return Table({name: npz[name] for name in npz.files})


def _load_json_rows(path: str) -> Table:
    rows: List[Dict[str, Any]] = []
    with open(path, "r", encoding="utf-8") as fh:
        text = fh.read().strip()
    if text.startswith("["):
        rows = json.loads(text)
    else:
        rows = [json.loads(line) for line in text.splitlines() if line.strip()]
    return Table.from_rows(rows)


_LOADERS: Dict[str, Callable[[str], Table]] = {
    ".npz": _load_npz,
    ".json": _load_json_rows,
    ".jsonl": _load_json_rows,
}


class FileStreamSource(StreamSource):
    """Directory watcher consuming files in lexicographic name order.

    The offset is "how many files (sorted by name) have been made
    available"; producers therefore name files monotonically
    (``part-00000.npz``, ``part-00001.npz``, ...) and publish them
    atomically (write ``name.tmp``, then rename) — ``*.tmp`` and
    dotfiles never enter the listing, so a half-written file is
    invisible exactly the way an uncommitted Spark output file is.
    """

    def __init__(
        self,
        path: str,
        pattern: str = "*",
        loader: Optional[Callable[[str], Table]] = None,
        max_per_trigger: Optional[int] = None,
    ):
        self.path = path
        self.pattern = pattern
        self._loader = loader
        self.max_per_trigger = max_per_trigger
        #: ordered names already exposed through ``latest_offset`` — a name
        #: never moves once listed, so offsets stay stable across rescans
        self._files: List[str] = []

    def _scan(self) -> List[str]:
        try:
            names = os.listdir(self.path)
        except OSError:
            return []
        return sorted(
            n for n in names
            if fnmatch.fnmatch(n, self.pattern)
            and not n.startswith(".")
            and not n.endswith(".tmp")
        )

    def latest_offset(self) -> int:
        seen = set(self._files)
        fresh = [n for n in self._scan() if n not in seen]
        if fresh:
            # append-only: files already listed keep their index even if a
            # late-arriving name would sort before them
            self._files.extend(sorted(fresh))
        return len(self._files)

    def plan_batch(self, start: int, end: int) -> List[str]:
        if end > len(self._files):
            self.latest_offset()
        if not 0 <= start <= end <= len(self._files):
            raise ValueError(
                f"offset range [{start}, {end}) outside the {len(self._files)} "
                f"files listed under {self.path}"
            )
        return list(self._files[start:end])

    def load_batch(self, manifest: Sequence[str]) -> Table:
        tables = [self._load_one(name) for name in manifest]
        if not tables:
            return Table({})
        return Table.concat(tables)

    def _load_one(self, name: str) -> Table:
        full = os.path.join(self.path, name)
        if self._loader is not None:
            return self._loader(full)
        ext = os.path.splitext(name)[1].lower()
        loader = _LOADERS.get(ext)
        if loader is None:
            raise ValueError(
                f"no loader for {name!r} (supported: {sorted(_LOADERS)}; "
                "pass loader= for custom formats)"
            )
        return loader(full)


class MemoryStream(StreamSource):
    """In-memory block source for tests (Spark's ``MemoryStream``): each
    :meth:`add` appends one block of rows; offsets count blocks. State
    lives in this process only — checkpointed queries over a
    ``MemoryStream`` replay nothing after a restart, exactly like the
    Spark original."""

    def __init__(self, max_per_trigger: Optional[int] = None):
        self._blocks: List[Table] = []
        self.max_per_trigger = max_per_trigger

    def add(self, table: Table) -> int:
        """Append one block; returns the new latest offset."""
        self._blocks.append(table)
        return len(self._blocks)

    def latest_offset(self) -> int:
        return len(self._blocks)

    def plan_batch(self, start: int, end: int) -> List[int]:
        if not 0 <= start <= end <= len(self._blocks):
            raise ValueError(
                f"offset range [{start}, {end}) outside {len(self._blocks)} "
                "blocks"
            )
        return list(range(start, end))

    def load_batch(self, manifest: Sequence[int]) -> Table:
        missing = [i for i in manifest if not 0 <= i < len(self._blocks)]
        if missing:
            raise ValueError(
                f"blocks {missing} not present (MemoryStream state does not "
                "survive a restart; use FileStreamSource for durable replay)"
            )
        tables = [self._blocks[i] for i in manifest]
        if not tables:
            return Table({})
        return Table.concat(tables)
