"""Batched lasso via coordinate descent — LIME's per-row local fit.

Reference: ``lime/BreezeUtils.scala`` (``LassoCalculator2``: cyclic
coordinate descent, per-column least-squares on the residual followed by
soft-thresholding with ``lambda``; ``lambda=0`` degrades to plain least
squares) invoked per row through ``fitLassoUDF`` (``lime/LIME.scala:157``).

TPU-first: the reference fits one Breeze lasso per DataFrame row inside a
UDF. Here the whole batch of per-instance problems is a single
``vmap``-over-rows jitted program — n_rows independent (n_samples × d)
solves run as one XLA computation, with the cyclic sweep expressed as
``lax.fori_loop`` (compiler-friendly control flow, no Python loop in jit).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

MAX_ITER = 100


def _lasso_single(X, y, lam, max_iter):
    """One coordinate-descent lasso solve (matches LassoCalculator2: the
    unpenalized one-column LS coefficient is soft-thresholded by lam)."""
    d = X.shape[1]
    col_sq = jnp.maximum((X * X).sum(axis=0), 1e-12)

    def sweep(_, w):
        def col(j, w):
            # residual excluding column j
            r = y - X @ w + X[:, j] * w[j]
            c = (X[:, j] @ r) / col_sq[j]
            wj = jnp.sign(c) * jnp.maximum(jnp.abs(c) - lam, 0.0)
            return w.at[j].set(wj)

        return jax.lax.fori_loop(0, d, col, w)

    w0 = jnp.zeros(d, dtype=X.dtype)
    return jax.lax.fori_loop(0, max_iter, sweep, w0)


@partial(jax.jit, static_argnames=("max_iter",))
def _lasso_batch(X, y, lam, max_iter):
    return jax.vmap(_lasso_single, in_axes=(0, 0, None, None))(X, y, lam, max_iter)


def fit_lasso_batch(X: np.ndarray, y: np.ndarray, lam: float,
                    max_iter: int = MAX_ITER) -> np.ndarray:
    """Solve ``n_rows`` independent lasso problems on device.

    X: (n_rows, n_samples, d), y: (n_rows, n_samples) -> (n_rows, d).
    """
    out = _lasso_batch(
        jnp.asarray(X, dtype=jnp.float32),
        jnp.asarray(y, dtype=jnp.float32),
        jnp.float32(lam),
        max_iter,
    )
    return np.asarray(out, dtype=np.float64)
