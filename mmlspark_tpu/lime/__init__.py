"""Model interpretability (reference ``lime/``, SURVEY.md §2.8)."""

from mmlspark_tpu.lime.lasso import fit_lasso_batch
from mmlspark_tpu.lime.lime import ImageLIME, TabularLIME, TabularLIMEModel
from mmlspark_tpu.lime.superpixel import (
    SuperpixelData,
    SuperpixelTransformer,
    mask_image,
    slic,
)

__all__ = [
    "ImageLIME",
    "SuperpixelData",
    "SuperpixelTransformer",
    "TabularLIME",
    "TabularLIMEModel",
    "fit_lasso_batch",
    "mask_image",
    "slic",
]
