"""SLIC superpixel clustering + SuperpixelTransformer.

Reference: ``lime/Superpixel.scala:143+`` (grid-seeded cluster growth with
``cellSize`` / ``modifier`` params; ``SuperpixelData:26`` holds the cluster
pixel lists) and ``SuperpixelTransformer``. The reference's JVM algorithm
is a SLIC variant; here the standard SLIC iteration is fully vectorized in
numpy — host-side work, matching SURVEY.md §7 step 8 ("LIME: superpixels
host-side, perturbation batches are a natural vmap").
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List

import numpy as np

from mmlspark_tpu.core.params import HasInputCol, HasOutputCol, Param, gt, to_float, to_int
from mmlspark_tpu.core.pipeline import Transformer
from mmlspark_tpu.data.table import Table


@dataclass
class SuperpixelData:
    """Cluster decomposition of one image: ``clusters[i]`` is an (n_i, 2)
    array of (row, col) pixel coordinates (``SuperpixelData`` schema)."""

    labels: np.ndarray  # (H, W) int cluster id per pixel
    clusters: List[np.ndarray]

    @property
    def num_clusters(self) -> int:
        return len(self.clusters)


def slic(image: np.ndarray, cell_size: int = 16, modifier: float = 130.0,
         n_iter: int = 10) -> SuperpixelData:
    """SLIC clustering: k-means over (color, position) with compactness
    ``modifier``, seeds on a ``cell_size`` grid."""
    img = np.asarray(image, dtype=np.float64)
    if img.ndim == 2:
        img = img[:, :, None]
    H, W, C = img.shape
    S = max(int(cell_size), 2)

    # grid seeds at cell centers
    ys = np.arange(S // 2, H, S)
    xs = np.arange(S // 2, W, S)
    cy, cx = np.meshgrid(ys, xs, indexing="ij")
    centers_pos = np.stack([cy.ravel(), cx.ravel()], axis=1).astype(np.float64)
    centers_col = img[centers_pos[:, 0].astype(int), centers_pos[:, 1].astype(int)]
    k = len(centers_pos)

    yy, xx = np.meshgrid(np.arange(H), np.arange(W), indexing="ij")
    pos = np.stack([yy.ravel(), xx.ravel()], axis=1).astype(np.float64)  # (N, 2)
    colors = img.reshape(-1, C)
    # scale spatial distance so `modifier` plays SLIC compactness
    spatial_w = (modifier / 100.0) / S

    labels = np.zeros(len(pos), dtype=np.int64)
    for _ in range(n_iter):
        # full distance matrix in chunks to bound memory
        best = np.full(len(pos), np.inf)
        for start in range(0, k, 256):
            cp = centers_pos[start:start + 256]
            cc = centers_col[start:start + 256]
            d_col = ((colors[:, None, :] - cc[None, :, :]) ** 2).sum(-1)
            d_pos = ((pos[:, None, :] - cp[None, :, :]) ** 2).sum(-1)
            d = d_col + (spatial_w**2) * d_pos
            idx = d.argmin(axis=1)
            val = d[np.arange(len(pos)), idx]
            upd = val < best
            labels[upd] = idx[upd] + start
            best[upd] = val[upd]
        # recompute centers
        for j in range(k):
            m = labels == j
            if m.any():
                centers_pos[j] = pos[m].mean(axis=0)
                centers_col[j] = colors[m].mean(axis=0)

    # compact label ids (drop empty clusters)
    uniq, labels = np.unique(labels, return_inverse=True)
    label_img = labels.reshape(H, W)
    clusters = [np.argwhere(label_img == j) for j in range(len(uniq))]
    return SuperpixelData(labels=label_img, clusters=clusters)


def mask_image(image: np.ndarray, sp: SuperpixelData, states: np.ndarray) -> np.ndarray:
    """Keep clusters whose state is True; everything else black
    (``Superpixel.MaskImageUDF`` semantics)."""
    keep = np.zeros(sp.labels.shape, dtype=bool)
    for j, on in enumerate(states):
        if on:
            keep |= sp.labels == j
    out = np.asarray(image).copy()
    out[~keep] = 0
    return out


class SuperpixelTransformer(HasInputCol, HasOutputCol, Transformer):
    """Image column -> superpixel decomposition column
    (``lime/Superpixel.scala`` SuperpixelTransformer)."""

    cellSize = Param("Approximate superpixel grid size in pixels", default=16,
                     converter=to_int, validator=gt(1))
    modifier = Param("SLIC compactness", default=130.0, converter=to_float,
                     validator=gt(0))

    def __init__(self, **kwargs):
        kwargs.setdefault("outputCol", "superpixels")
        super().__init__(**kwargs)

    def transform(self, table: Table) -> Table:
        images = table.column(self.getInputCol())
        out = np.empty(len(images), dtype=object)
        for i, img in enumerate(images):
            out[i] = slic(img, self.getCellSize(), self.getModifier())
        return table.with_column(self.getOutputCol(), out)
