"""LIME — model-agnostic local interpretability.

Reference: ``lime/LIME.scala:164-249`` (``TabularLIME``/``TabularLIMEModel``)
and ``:251+`` (``ImageLIME``): perturb each instance, run the inner model on
the perturbed copies, then fit a per-row (weighted-free) lasso of the
predictions against the perturbations; image version perturbs by switching
SLIC superpixels off (``lime/Superpixel.scala``).

TPU-first: all rows' perturbations are flattened into ONE inner-model
transform (a single batched device program instead of the reference's
explode + per-partition UDFs), and the per-row lasso fits run as one
vmapped jit (:mod:`mmlspark_tpu.lime.lasso`).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from mmlspark_tpu.core.params import (
    HasInputCol,
    HasOutputCol,
    HasPredictionCol,
    Param,
    gt,
    to_float,
    to_int,
)
from mmlspark_tpu.core.pipeline import Estimator, Model, Transformer
from mmlspark_tpu.data.table import Table
from mmlspark_tpu.lime.lasso import fit_lasso_batch
from mmlspark_tpu.lime.superpixel import SuperpixelTransformer, mask_image


class _LIMEParams(HasInputCol, HasOutputCol, HasPredictionCol):
    """Shared params (``lime/LIME.scala:110-135``)."""

    model = Param("Model to locally approximate", is_complex=True, default=None)
    nSamples = Param("Number of perturbed samples per row", default=1000,
                     converter=to_int, validator=gt(0))
    samplingFraction = Param("Fraction of superpixels kept on", default=0.3,
                             converter=to_float)
    regularization = Param("Lasso lambda (0 = least squares)", default=0.0,
                           converter=to_float)
    seed = Param("Perturbation RNG seed", default=0, converter=to_int)


class TabularLIME(_LIMEParams, Estimator):
    """fit() records per-column mean/std used for gaussian perturbations
    (``TabularLIME.fit`` runs a StandardScaler, ``lime/LIME.scala:173-185``)."""

    def _fit(self, table: Table) -> "TabularLIMEModel":
        X = np.asarray(table.column(self.getInputCol()), dtype=np.float64)
        model = TabularLIMEModel(
            inputCol=self.getInputCol(),
            outputCol=self.getOutputCol(),
            predictionCol=self.getPredictionCol(),
            model=self.getModel(),
            nSamples=self.getNSamples(),
            samplingFraction=self.getSamplingFraction(),
            regularization=self.getRegularization(),
            seed=self.getSeed(),
            columnMeans=X.mean(axis=0),
            columnSTDs=X.std(axis=0),
        )
        model.parent = self
        return model


class TabularLIMEModel(_LIMEParams, Model):
    """Per row: ``nSamples`` gaussian draws ``N(columnMeans, columnSTDs)``
    (``TabularLIMEModel.perturbedDenseVectors``, ``lime/LIME.scala:215-221``),
    inner-model predictions, then lasso weights as the explanation."""

    columnMeans = Param("Feature means for perturbation", is_complex=True,
                        default=None)
    columnSTDs = Param("Feature stds for perturbation", is_complex=True,
                       default=None)

    def transform(self, table: Table) -> Table:
        n_rows = table.num_rows
        n_samp = self.getNSamples()
        means = np.asarray(self.getColumnMeans(), dtype=np.float64)
        stds = np.asarray(self.getColumnSTDs(), dtype=np.float64)
        d = len(means)
        rng = np.random.default_rng(self.getSeed())
        # (n_rows, n_samples, d) gaussian perturbations around column stats
        perturbed = rng.normal(size=(n_rows, n_samp, d)) * stds + means
        # ONE batched inner-model run over every perturbation of every row
        inner_in = Table({self.getInputCol(): perturbed.reshape(-1, d)})
        preds = (
            self.getModel()
            .transform(inner_in)
            .column(self.getPredictionCol())
            .astype(np.float64)
            .reshape(n_rows, n_samp)
        )
        weights = fit_lasso_batch(perturbed, preds, self.getRegularization())
        return table.with_column(self.getOutputCol(), weights)


class ImageLIME(_LIMEParams, Transformer):
    """Superpixel-mask perturbation explanation for images
    (``lime/LIME.scala:251+``): output weight i = importance of superpixel i."""

    superpixelCol = Param("Superpixel decomposition column",
                          default="superpixels", converter=str)
    cellSize = Param("Superpixel grid size", default=16, converter=to_int,
                     validator=gt(1))
    modifier = Param("SLIC compactness", default=130.0, converter=to_float)

    def __init__(self, **kwargs):
        kwargs.setdefault("nSamples", 900)
        kwargs.setdefault("samplingFraction", 0.3)
        super().__init__(**kwargs)

    def transform(self, table: Table) -> Table:
        spt = SuperpixelTransformer(
            inputCol=self.getInputCol(),
            outputCol=self.getSuperpixelCol(),
            cellSize=self.getCellSize(),
            modifier=self.getModifier(),
        )
        with_sp = spt.transform(table)
        images = with_sp.column(self.getInputCol())
        sps = with_sp.column(self.getSuperpixelCol())
        n_samp = self.getNSamples()
        rng = np.random.default_rng(self.getSeed())
        frac = self.getSamplingFraction()

        all_masked = []
        all_states = []
        for img, sp in zip(images, sps):
            # reference randomMasks: keep superpixel iff U > decInclude
            # (``lime/LIME.scala:30-41`` with decInclude = samplingFraction)
            states = rng.random(size=(n_samp, sp.num_clusters)) > frac
            all_states.append(states)
            for s in states:
                all_masked.append(mask_image(img, sp, s))
        inner_in = Table({self.getInputCol(): np.stack(all_masked)})
        preds = (
            self.getModel()
            .transform(inner_in)
            .column(self.getPredictionCol())
            .astype(np.float64)
            .reshape(len(images), n_samp)
        )
        # per-row lasso: states (n_samp, n_clusters_i) may vary in width;
        # fit row-by-row batches grouped by cluster count
        weights = np.empty(len(images), dtype=object)
        by_width = {}
        for i, st in enumerate(all_states):
            by_width.setdefault(st.shape[1], []).append(i)
        for width, rows in by_width.items():
            X = np.stack([all_states[i].astype(np.float64) for i in rows])
            y = np.stack([preds[i] for i in rows])
            W = fit_lasso_batch(X, y, self.getRegularization())
            for n, i in enumerate(rows):
                weights[i] = W[n]
        return with_sp.with_column(self.getOutputCol(), weights)
