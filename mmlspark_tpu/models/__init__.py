"""Built-in model zoo (reference ``downloader/`` model zoo role, SURVEY.md §2.14).

The reference downloads pre-trained CNTK graphs from a CDN; in the TPU build
the zoo is constructive — model families are defined here in JAX and their
weights are produced by training or loaded from checkpoints via
:mod:`mmlspark_tpu.downloader`.
"""

from mmlspark_tpu.models.resnet import init_resnet, resnet_apply
from mmlspark_tpu.models.zoo import (
    load_zoo_params,
    params_from_bytes,
    params_to_bytes,
    publish_model,
    train_resnet_classifier,
)

__all__ = [
    "init_resnet", "resnet_apply", "publish_model", "load_zoo_params",
    "params_to_bytes", "params_from_bytes", "train_resnet_classifier",
]
