"""Model-zoo plumbing: trained-weight artifacts through the downloader.

The reference ships a repository of TRAINED CNTK models that
``ImageFeaturizer`` consumes via ``ModelDownloader``
(``downloader/ModelDownloader.scala:125``, ``image/ImageFeaturizer.scala:
40-86``). The TPU equivalent: a parameter pytree serialized to one npz
payload + a ``ModelSchema`` JSON, published into any
:class:`~mmlspark_tpu.downloader.Repository` and loaded back with hash
verification — plus a small supervised trainer so artifacts carry REAL
learned weights even on zero-egress rigs (train on local data, publish,
transfer).

Payload format: numpy ``.npz`` with ``/``-joined pytree paths as keys;
LIST components are marked ``#i`` (so digit-keyed dicts round-trip
unchanged); lossless f32 round trip.
"""

from __future__ import annotations

import io
from typing import Any, Dict, Optional, Tuple

import numpy as np


def _flatten(tree: Any, prefix: str = "") -> Dict[str, np.ndarray]:
    out: Dict[str, np.ndarray] = {}
    if isinstance(tree, dict):
        for k, v in tree.items():
            # '#' marks LIST components below, '/' is the path separator —
            # dict keys using either would make the round trip ambiguous
            if "/" in str(k) or str(k).startswith("#"):
                raise ValueError(
                    f"zoo payload keys may not contain '/' or start with "
                    f"'#': {k!r}"
                )
            out.update(_flatten(v, f"{prefix}{k}/"))
    elif isinstance(tree, (list, tuple)):
        for i, v in enumerate(tree):
            out.update(_flatten(v, f"{prefix}#{i}/"))
    else:
        out[prefix[:-1]] = np.asarray(tree)
    return out


def _unflatten(flat: Dict[str, np.ndarray]) -> Any:
    root: Dict[str, Any] = {}
    for key, value in flat.items():
        parts = key.split("/")
        node = root
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = value

    def listify(node):
        if not isinstance(node, dict):
            return node
        # only explicitly-marked '#i' components become lists, so dicts
        # whose keys happen to be digit strings round-trip unchanged
        if node and all(k.startswith("#") for k in node):
            return [listify(node[f"#{i}"]) for i in range(len(node))]
        return {k: listify(v) for k, v in node.items()}

    return listify(root)


def params_to_bytes(params: Any) -> bytes:
    buf = io.BytesIO()
    np.savez(buf, **_flatten(params))
    return buf.getvalue()


def params_from_bytes(payload: bytes) -> Any:
    with np.load(io.BytesIO(payload)) as z:
        return _unflatten({k: z[k] for k in z.files})


def publish_model(
    repo_dir: str,
    name: str,
    params: Any,
    input_hw: Tuple[int, int],
    input_node: str = "image",
    extra: Optional[dict] = None,
) -> "ModelSchema":
    """Serialize ``params`` into ``repo_dir`` as ``<name>.bin`` +
    ``<name>.json`` (LocalRepo layout) and return the schema."""
    from mmlspark_tpu.downloader.repository import LocalRepo, ModelSchema

    flat = _flatten(params)
    schema = ModelSchema(
        name=name,
        uri=f"{name}.bin",
        inputNode=f"{input_node}:{input_hw[0]}x{input_hw[1]}",
        numLayers=len(flat),
        layerNames=sorted(flat)[:64],
    )
    LocalRepo(repo_dir).add(schema, params_to_bytes(params))
    return schema


def load_zoo_params(downloader, name: str) -> Any:
    """Fetch a published artifact through the downloader (hash-verified,
    cached) and deserialize the parameter pytree."""
    path = downloader.download_by_name(name)
    with open(path, "rb") as f:
        return params_from_bytes(f.read())


# ---------------------------------------------------------------------------
# Supervised trainer — REAL weights for zoo artifacts on zero-egress rigs
# ---------------------------------------------------------------------------


def train_resnet_classifier(
    params: Any,
    X: np.ndarray,  # (N, C, H, W) float32 in [0, 1]
    y: np.ndarray,  # (N,) int class ids
    *,
    num_steps: int = 300,
    batch_size: int = 64,
    learning_rate: float = 1e-3,
    seed: int = 0,
) -> Tuple[Any, float]:
    """Train the zoo ResNet's weights with Adam on softmax cross-entropy
    (BatchNorm treated as frozen affine — gamma/beta learn, running stats
    stay; fine at these scales and keeps the apply fn identical between
    train and eval). Returns (trained params, final train accuracy)."""
    import jax
    import jax.numpy as jnp
    import optax

    from mmlspark_tpu.models.resnet import resnet_apply

    X = np.asarray(X, np.float32)
    y = np.asarray(y, np.int32)
    n = len(y)
    opt = optax.adam(learning_rate)
    pdev = jax.tree_util.tree_map(jnp.asarray, params)
    state = opt.init(pdev)

    def loss_fn(p, xb, yb):
        logits = resnet_apply(p, xb, cut=0)
        return optax.softmax_cross_entropy_with_integer_labels(logits, yb).mean()

    @jax.jit
    def step(p, s, xb, yb):
        loss, grads = jax.value_and_grad(loss_fn)(p, xb, yb)
        updates, s = opt.update(grads, s, p)
        return optax.apply_updates(p, updates), s, loss

    rng = np.random.default_rng(seed)
    for i in range(num_steps):
        idx = rng.integers(0, n, size=batch_size)
        pdev, state, _ = step(pdev, state, jnp.asarray(X[idx]), jnp.asarray(y[idx]))

    @jax.jit
    def predict(p, xb):
        return resnet_apply(p, xb, cut=0).argmax(axis=1)

    correct = 0
    for lo in range(0, n, 256):
        correct += int(
            (np.asarray(predict(pdev, jnp.asarray(X[lo : lo + 256]))) == y[lo : lo + 256]).sum()
        )
    trained = jax.tree_util.tree_map(np.asarray, pdev)
    return trained, correct / n
