"""Functional ResNet in plain JAX — the ImageFeaturizer backbone.

The reference's ``ImageFeaturizer`` wraps a downloaded CNTK ResNet and cuts
``cutOutputLayers`` layers off the top (``image/ImageFeaturizer.scala:40-86``).
Here the backbone is defined natively: a ``(params, x, cut) -> array``
function whose ``cut`` argument selects the same "featurize vs classify"
behavior, and whose body is pure lax ops so the whole forward pass jits into
one XLA program (convs on the MXU, bf16-friendly).

Layout NCHW to match :mod:`mmlspark_tpu.image` unrolled tensors; weights are
float32 at rest and can be cast to bfloat16 at apply time (``dtype`` arg).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import numpy as np

VARIANTS: Dict[str, Tuple[Tuple[int, ...], bool]] = {
    # name -> (blocks per stage, bottleneck?)
    "resnet18": ((2, 2, 2, 2), False),
    "resnet34": ((3, 4, 6, 3), False),
    "resnet50": ((3, 4, 6, 3), True),
}

_STAGE_WIDTHS = (64, 128, 256, 512)


def _he(rng: np.random.Generator, shape: Tuple[int, ...]) -> np.ndarray:
    fan_in = int(np.prod(shape[1:])) if len(shape) > 1 else shape[0]
    return (rng.standard_normal(shape) * np.sqrt(2.0 / fan_in)).astype(np.float32)


def _conv_params(rng, c_out, c_in, k) -> Dict[str, np.ndarray]:
    return {"w": _he(rng, (c_out, c_in, k, k))}


def _bn_params(c) -> Dict[str, np.ndarray]:
    return {
        "gamma": np.ones(c, np.float32),
        "beta": np.zeros(c, np.float32),
        "mean": np.zeros(c, np.float32),
        "var": np.ones(c, np.float32),
    }


def init_resnet(
    seed: int = 0,
    variant: str = "resnet18",
    num_classes: int = 1000,
    in_channels: int = 3,
    small_inputs: bool = False,
) -> Dict[str, Any]:
    """Random-init parameter pytree. ``small_inputs`` uses the CIFAR stem
    (3x3 stride-1 conv, no maxpool) instead of the ImageNet 7x7 stride-2."""
    if variant not in VARIANTS:
        raise ValueError(f"unknown variant {variant!r}; choose from {sorted(VARIANTS)}")
    blocks, bottleneck = VARIANTS[variant]
    rng = np.random.default_rng(seed)
    expansion = 4 if bottleneck else 1
    # Architecture is encoded in the pytree structure itself (stem kernel
    # size ⇒ small_inputs, conv3 presence ⇒ bottleneck) so the params dict
    # stays a pure array pytree — jit-able with no static side channel.
    params: Dict[str, Any] = {
        "stem": {
            "conv": _conv_params(rng, 64, in_channels, 3 if small_inputs else 7),
            "bn": _bn_params(64),
        },
    }
    c_in = 64
    stages: List[List[Dict[str, Any]]] = []
    for stage_i, (n_blocks, width) in enumerate(zip(blocks, _STAGE_WIDTHS)):
        stage: List[Dict[str, Any]] = []
        for block_i in range(n_blocks):
            stride = 2 if (stage_i > 0 and block_i == 0) else 1
            c_out = width * expansion
            block: Dict[str, Any] = {}
            if bottleneck:
                block["conv1"] = _conv_params(rng, width, c_in, 1)
                block["bn1"] = _bn_params(width)
                block["conv2"] = _conv_params(rng, width, width, 3)
                block["bn2"] = _bn_params(width)
                block["conv3"] = _conv_params(rng, c_out, width, 1)
                block["bn3"] = _bn_params(c_out)
            else:
                block["conv1"] = _conv_params(rng, width, c_in, 3)
                block["bn1"] = _bn_params(width)
                block["conv2"] = _conv_params(rng, width, width, 3)
                block["bn2"] = _bn_params(width)
            if stride != 1 or c_in != c_out:
                block["down_conv"] = _conv_params(rng, c_out, c_in, 1)
                block["down_bn"] = _bn_params(c_out)
            stage.append(block)
            c_in = c_out
        stages.append(stage)
    params["stages"] = stages
    params["fc"] = {
        "w": _he(rng, (num_classes, c_in)),
        "b": np.zeros(num_classes, np.float32),
    }
    return params


def _conv(x, p, stride=1, padding="SAME"):
    from jax import lax

    return lax.conv_general_dilated(
        x,
        p["w"].astype(x.dtype),
        window_strides=(stride, stride),
        padding=padding,
        dimension_numbers=("NCHW", "OIHW", "NCHW"),
    )


def _bn(x, p):
    shape = (1, -1, 1, 1)
    inv = (p["var"] + 1e-5) ** -0.5
    return (
        x * (p["gamma"] * inv).astype(x.dtype).reshape(shape)
        + (p["beta"] - p["mean"] * p["gamma"] * inv).astype(x.dtype).reshape(shape)
    )


def _block(x, p, stride, bottleneck):
    import jax

    identity = x
    if bottleneck:
        out = jax.nn.relu(_bn(_conv(x, p["conv1"], 1), p["bn1"]))
        out = jax.nn.relu(_bn(_conv(out, p["conv2"], stride), p["bn2"]))
        out = _bn(_conv(out, p["conv3"], 1), p["bn3"])
    else:
        out = jax.nn.relu(_bn(_conv(x, p["conv1"], stride), p["bn1"]))
        out = _bn(_conv(out, p["conv2"], 1), p["bn2"])
    if "down_conv" in p:
        identity = _bn(_conv(x, p["down_conv"], stride), p["down_bn"])
    return jax.nn.relu(out + identity)


def resnet_apply(params: Dict[str, Any], x, cut: int = 0, dtype: Any = None):
    """Forward pass. ``cut=0`` → logits; ``cut=1`` → pooled features (the
    reference's ``cutOutputLayers=1`` transfer-learning default);
    ``cut=2`` → pre-pool feature map."""
    import jax
    from jax import lax

    small_inputs = params["stem"]["conv"]["w"].shape[-1] == 3
    bottleneck = "conv3" in params["stages"][0][0]
    if dtype is not None:
        x = x.astype(dtype)
    stride = 1 if small_inputs else 2
    x = jax.nn.relu(_bn(_conv(x, params["stem"]["conv"], stride), params["stem"]["bn"]))
    if not small_inputs:
        x = lax.reduce_window(
            x, -np.inf, lax.max, (1, 1, 3, 3), (1, 1, 2, 2),
            ((0, 0), (0, 0), (1, 1), (1, 1)),
        )
    for stage_i, stage in enumerate(params["stages"]):
        for block_i, block in enumerate(stage):
            s = 2 if (stage_i > 0 and block_i == 0) else 1
            x = _block(x, block, s, bottleneck)
    if cut >= 2:
        return x
    feats = x.mean(axis=(2, 3))
    if cut >= 1:
        return feats
    fc = params["fc"]
    return feats @ fc["w"].astype(feats.dtype).T + fc["b"].astype(feats.dtype)
