"""Admission control — shed load instead of queueing forever.

The serving queue was unbounded: under overload every listener thread
blocked on its reply event while the queue grew without limit, so one slow
dependency wedged the whole HTTP edge (exactly the failure mode *The Tail
at Scale* calls out). The fix is a counter, not a queue: at most
``max_pending`` requests may be admitted-and-unanswered at once (that one
bound covers both the micro-batch queue and listener-thread concurrency,
since every admitted request holds exactly one listener thread until its
reply). Beyond it, requests are shed immediately with ``429`` +
``Retry-After`` — a fast no is cheaper for the client than a slow maybe,
and the shed clients' retries arrive after the hinted backoff instead of
piling onto the queue.

Sheds are counted (``serving_shed_total``), the in-flight depth is a live
gauge, and each shed publishes
:class:`~mmlspark_tpu.observability.events.RequestShed` when the bus has
listeners.

Under ambient memory pressure (the resource watchdog's process-wide
:class:`~mmlspark_tpu.runtime.pressure.PressureLevel`) the effective
bound tightens — half of ``max_pending`` at WARN, a quarter at
CRITICAL — so the serving edge sheds *before* the allocator OOMs, and
restores the full bound the moment the level clears (docs/resilience.md
"Resource pressure").
"""

from __future__ import annotations

import threading


class AdmissionController:
    """Bounded-in-flight admission with 429 shedding semantics."""

    def __init__(
        self,
        max_pending: int = 1024,
        retry_after_s: float = 1.0,
        registry=None,
        name: str = "serving",
    ):
        if max_pending < 1:
            raise ValueError("max_pending must be >= 1")
        self.max_pending = int(max_pending)
        self.retry_after_s = float(retry_after_s)
        self.name = name
        self._lock = threading.Lock()
        self._inflight = 0
        if registry is None:
            from mmlspark_tpu.observability.registry import get_registry

            registry = get_registry()
        self._shed = registry.counter(
            "serving_shed_total",
            "Requests rejected with 429 by admission control",
        )
        self._gauge = registry.gauge(
            "serving_inflight", "Admitted requests awaiting a reply"
        )

    @property
    def inflight(self) -> int:
        with self._lock:
            return self._inflight

    def effective_max_pending(self) -> int:
        """The in-flight bound after the ambient memory-pressure level is
        applied: ``max_pending`` at OK, half at WARN, a quarter (floor 1)
        at CRITICAL. Restoration is automatic — the next request after
        the level clears sees the full bound again."""
        from mmlspark_tpu.runtime.pressure import (
            PressureLevel, current_pressure_level,
        )

        level = current_pressure_level("memory")
        if level >= PressureLevel.CRITICAL:
            return max(1, self.max_pending // 4)
        if level >= PressureLevel.WARN:
            return max(1, self.max_pending // 2)
        return self.max_pending

    def try_acquire(self) -> bool:
        """Admit one request, or shed it (False) when the effective bound
        is reached. A shed is counted and published; the caller answers
        429 with ``Retry-After: retry_after_s``. The shed reason is
        ``"memory_pressure"`` when the request would have been admitted
        under the unpressured bound."""
        bound = self.effective_max_pending()
        with self._lock:
            if self._inflight >= bound:
                depth = self._inflight
                admitted = False
            else:
                self._inflight += 1
                depth = self._inflight
                admitted = True
            self._gauge.set(depth)
        if admitted:
            return True
        self._shed.inc()
        from mmlspark_tpu.observability.events import RequestShed, get_bus

        bus = get_bus()
        if bus.active:
            bus.publish(RequestShed(
                reason=(
                    "memory_pressure" if depth < self.max_pending
                    else "max_pending"
                ),
                queue_depth=depth,
                retry_after=self.retry_after_s,
            ))
        return False

    def release(self) -> None:
        """One admitted request finished (replied, timed out, or the
        client hung up) — must be called exactly once per successful
        :meth:`try_acquire`."""
        with self._lock:
            self._inflight = max(0, self._inflight - 1)
            self._gauge.set(self._inflight)
