"""Admission control — shed load instead of queueing forever.

The serving queue was unbounded: under overload every listener thread
blocked on its reply event while the queue grew without limit, so one slow
dependency wedged the whole HTTP edge (exactly the failure mode *The Tail
at Scale* calls out). The fix is a counter, not a queue: at most
``max_pending`` requests may be admitted-and-unanswered at once (that one
bound covers both the micro-batch queue and listener-thread concurrency,
since every admitted request holds exactly one listener thread until its
reply). Beyond it, requests are shed immediately with ``429`` +
``Retry-After`` — a fast no is cheaper for the client than a slow maybe,
and the shed clients' retries arrive after the hinted backoff instead of
piling onto the queue.

Sheds are counted (``serving_shed_total``), the in-flight depth is a live
gauge, and each shed publishes
:class:`~mmlspark_tpu.observability.events.RequestShed` when the bus has
listeners.
"""

from __future__ import annotations

import threading


class AdmissionController:
    """Bounded-in-flight admission with 429 shedding semantics."""

    def __init__(
        self,
        max_pending: int = 1024,
        retry_after_s: float = 1.0,
        registry=None,
        name: str = "serving",
    ):
        if max_pending < 1:
            raise ValueError("max_pending must be >= 1")
        self.max_pending = int(max_pending)
        self.retry_after_s = float(retry_after_s)
        self.name = name
        self._lock = threading.Lock()
        self._inflight = 0
        if registry is None:
            from mmlspark_tpu.observability.registry import get_registry

            registry = get_registry()
        self._shed = registry.counter(
            "serving_shed_total",
            "Requests rejected with 429 by admission control",
        )
        self._gauge = registry.gauge(
            "serving_inflight", "Admitted requests awaiting a reply"
        )

    @property
    def inflight(self) -> int:
        with self._lock:
            return self._inflight

    def try_acquire(self) -> bool:
        """Admit one request, or shed it (False) when ``max_pending``
        requests are already in flight. A shed is counted and published;
        the caller answers 429 with ``Retry-After: retry_after_s``."""
        with self._lock:
            if self._inflight >= self.max_pending:
                depth = self._inflight
                admitted = False
            else:
                self._inflight += 1
                depth = self._inflight
                admitted = True
            self._gauge.set(depth)
        if admitted:
            return True
        self._shed.inc()
        from mmlspark_tpu.observability.events import RequestShed, get_bus

        bus = get_bus()
        if bus.active:
            bus.publish(RequestShed(
                reason="max_pending",
                queue_depth=depth,
                retry_after=self.retry_after_s,
            ))
        return False

    def release(self) -> None:
        """One admitted request finished (replied, timed out, or the
        client hung up) — must be called exactly once per successful
        :meth:`try_acquire`."""
        with self._lock:
            self._inflight = max(0, self._inflight - 1)
            self._gauge.set(self._inflight)
