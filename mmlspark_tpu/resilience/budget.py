"""Deadline propagation + retry budget.

Two tail-latency controls from *The Tail at Scale* (Dean & Barroso) the
reference stack never had:

- :class:`Deadline` — an ambient (contextvar) wall-clock budget for the
  whole request tree. The serving edge mints one from the
  ``X-Deadline-Ms`` header (or a server default); every outbound hop
  forwards the *remaining* budget in the same header and caps its socket
  timeout to it, so a request that has already missed its SLA stops
  consuming work at every layer at once.
- :class:`RetryBudget` — a token bucket that bounds retries to a fraction
  of live traffic. Each first attempt deposits ``ratio`` tokens, each
  retry spends one: in steady state retries are at most ``ratio`` of
  requests, so a down dependency sees load shed toward 1x instead of the
  (attempts)x multiplication a per-call retry loop produces.

Both take injectable clocks so chaos tests run with zero real sleeps.
"""

from __future__ import annotations

import contextlib
import contextvars
import threading
import time
from typing import Callable, Iterator, Optional

#: the deadline propagation header: milliseconds of budget remaining,
#: re-computed (shrunk) at every hop
DEADLINE_HEADER = "X-Deadline-Ms"


class DeadlineExceededError(TimeoutError):
    """The ambient deadline expired before (or during) the call."""


class Deadline:
    """An absolute point on a monotonic clock; ``remaining()`` is the
    budget left. Immutable once minted — hops shrink the budget simply by
    time passing."""

    __slots__ = ("at", "clock")

    def __init__(self, at: float, clock: Callable[[], float] = time.monotonic):
        self.at = float(at)
        self.clock = clock

    @classmethod
    def after(
        cls, seconds: float, clock: Callable[[], float] = time.monotonic
    ) -> "Deadline":
        return cls(clock() + float(seconds), clock)

    def remaining(self) -> float:
        """Seconds left (negative once expired)."""
        return self.at - self.clock()

    @property
    def expired(self) -> bool:
        return self.remaining() <= 0.0

    # -- header contract (docs/resilience.md) --------------------------------

    def to_header(self) -> str:
        """Remaining budget as integer milliseconds (floored at 0).
        Nearest-ms rounding: ceil/floor would drift the budget by up to
        1 ms per hop in one direction."""
        return str(max(0, round(self.remaining() * 1000.0)))

    @classmethod
    def from_header(
        cls, value: str, clock: Callable[[], float] = time.monotonic
    ) -> Optional["Deadline"]:
        """Parse an ``X-Deadline-Ms`` value; None on garbage (a malformed
        deadline must degrade to "no deadline", never to a 400)."""
        try:
            ms = float(value)
        except (TypeError, ValueError):
            return None
        return cls.after(ms / 1000.0, clock)

    def __repr__(self) -> str:
        return f"Deadline(remaining={self.remaining():.3f}s)"


_DEADLINE: contextvars.ContextVar[Optional[Deadline]] = contextvars.ContextVar(
    "mmlspark_tpu_deadline", default=None
)


def current_deadline() -> Optional[Deadline]:
    """The ambient deadline, if any caller up-stack set one."""
    return _DEADLINE.get()


@contextlib.contextmanager
def deadline_scope(
    seconds_or_deadline, clock: Callable[[], float] = time.monotonic
) -> Iterator[Deadline]:
    """Run a block under an ambient deadline::

        with deadline_scope(1.5):
            client.send(req)   # outbound hop forwards X-Deadline-Ms

    An existing tighter ambient deadline wins — a callee can only shrink
    the budget, never extend its caller's.
    """
    dl = (
        seconds_or_deadline
        if isinstance(seconds_or_deadline, Deadline)
        else Deadline.after(float(seconds_or_deadline), clock)
    )
    outer = _DEADLINE.get()
    # == not `is`: bound methods (fake_clock.now) are fresh objects per
    # attribute access but compare equal for the same instance+function
    if outer is not None and outer.at <= dl.at and outer.clock == dl.clock:
        dl = outer
    token = _DEADLINE.set(dl)
    try:
        yield dl
    finally:
        _DEADLINE.reset(token)


class RetryBudget:
    """Token-bucket retry budget (finagle's ``RetryBudget`` shape).

    ``record_request()`` on every first attempt deposits ``ratio`` tokens;
    ``try_spend()`` before every retry takes one token or answers False.
    ``min_tokens`` seeds the bucket so low-traffic callers can still retry
    a cold failure; ``max_tokens`` caps the stockpile so a long quiet
    period can't bankroll a retry storm later.
    """

    def __init__(
        self,
        ratio: float = 0.2,
        min_tokens: float = 5.0,
        max_tokens: float = 100.0,
        registry=None,
    ):
        if ratio < 0:
            raise ValueError("ratio must be >= 0")
        self.ratio = float(ratio)
        self.max_tokens = float(max_tokens)
        self._tokens = min(float(min_tokens), self.max_tokens)
        self._lock = threading.Lock()
        if registry is None:
            from mmlspark_tpu.observability.registry import get_registry

            registry = get_registry()
        self._exhausted = registry.counter(
            "resilience_retry_budget_exhausted_total",
            "Retries suppressed because the retry budget was empty",
        )

    @property
    def tokens(self) -> float:
        with self._lock:
            return self._tokens

    def record_request(self) -> None:
        with self._lock:
            self._tokens = min(self.max_tokens, self._tokens + self.ratio)

    def try_spend(self, cost: float = 1.0) -> bool:
        with self._lock:
            if self._tokens >= cost:
                self._tokens -= cost
                return True
        self._exhausted.inc()
        return False
