"""Circuit breaker — fail fast when a dependency is down.

Reference posture: ``HandlingUtils.advanced`` (``io/http/HTTPClients.scala
:64-151``) retried every failure with backoff, which under a hard outage
turns every caller into part of the retry storm. The breaker is the missing
half (Dean & Barroso, *The Tail at Scale*: stop sending work you already
know will fail): a per-dependency state machine

- **closed**    — calls flow; failures are recorded in a rolling window;
- **open**      — ``failure_threshold`` failures inside ``window_s`` trip
  the breaker: calls are rejected locally (:class:`BreakerOpenError`)
  without touching the network, for ``reset_timeout_s``;
- **half-open** — after the cooldown, up to ``half_open_max`` probe calls
  are let through; one success closes the breaker, one failure re-opens it.

The clock is injectable (``clock=``) so state transitions are testable
with no real sleeps, and every transition updates the
``resilience_breaker_state`` gauge (0=closed, 1=half-open, 2=open) and
publishes :class:`~mmlspark_tpu.observability.events.BreakerTripped` on
trip — the serving dashboards see an outage the moment the first host
stops calling, not when the error rate graph catches up.
"""

from __future__ import annotations

import collections
import logging
import threading
import time
from typing import Callable, Deque, Dict, Optional
from urllib.parse import urlsplit

logger = logging.getLogger("mmlspark_tpu.resilience")

#: gauge values per state (Prometheus convention: higher = worse)
CLOSED, HALF_OPEN, OPEN = "closed", "half_open", "open"
_STATE_GAUGE = {CLOSED: 0.0, HALF_OPEN: 1.0, OPEN: 2.0}


class BreakerOpenError(RuntimeError):
    """Raised (or mapped to a synthetic 503) when the breaker rejects a
    call locally. ``retry_after`` is the cooldown remaining in seconds —
    callers surfacing this over HTTP should emit it as ``Retry-After``."""

    def __init__(self, name: str, retry_after: float = 0.0):
        super().__init__(
            f"circuit breaker {name!r} is open (retry after "
            f"{retry_after:.3f}s)"
        )
        self.name = name
        self.retry_after = retry_after


class CircuitBreaker:
    """Closed/open/half-open breaker over a rolling failure window."""

    def __init__(
        self,
        name: str,
        failure_threshold: int = 5,
        window_s: float = 10.0,
        reset_timeout_s: float = 5.0,
        half_open_max: int = 1,
        clock: Callable[[], float] = time.monotonic,
        registry=None,
    ):
        if failure_threshold < 1:
            raise ValueError("failure_threshold must be >= 1")
        self.name = name
        self.failure_threshold = int(failure_threshold)
        self.window_s = float(window_s)
        self.reset_timeout_s = float(reset_timeout_s)
        self.half_open_max = int(half_open_max)
        self.clock = clock
        self._lock = threading.Lock()
        self._state = CLOSED
        self._failures: Deque[float] = collections.deque()
        self._opened_at = 0.0
        self._probes_inflight = 0
        #: number of closed->open transitions over the breaker's lifetime
        self.trips = 0
        if registry is None:
            from mmlspark_tpu.observability.registry import get_registry

            registry = get_registry()
        self._gauge = registry.gauge(
            "resilience_breaker_state",
            "Circuit breaker state (0=closed, 1=half-open, 2=open)",
        ).labels(breaker=name)
        self._trips_counter = registry.counter(
            "resilience_breaker_trips_total",
            "Closed->open breaker transitions",
        ).labels(breaker=name)
        self._rejected = registry.counter(
            "resilience_breaker_rejected_total",
            "Calls rejected locally by an open breaker",
        ).labels(breaker=name)
        self._gauge.set(_STATE_GAUGE[CLOSED])

    # -- state ---------------------------------------------------------------

    @property
    def state(self) -> str:
        with self._lock:
            self._advance(self.clock())
            return self._state

    def retry_after(self) -> float:
        """Seconds until the next probe would be admitted (0 when not open)."""
        with self._lock:
            now = self.clock()
            self._advance(now)
            if self._state != OPEN:
                return 0.0
            return max(0.0, self._opened_at + self.reset_timeout_s - now)

    def _advance(self, now: float) -> None:
        """Time-driven transitions; caller holds the lock."""
        if self._state == OPEN and now - self._opened_at >= self.reset_timeout_s:
            self._state = HALF_OPEN
            self._probes_inflight = 0
            self._gauge.set(_STATE_GAUGE[HALF_OPEN])
        while self._failures and now - self._failures[0] > self.window_s:
            self._failures.popleft()

    # -- call protocol -------------------------------------------------------

    def allow(self) -> bool:
        """True if a call may proceed now. Half-open admits at most
        ``half_open_max`` concurrent probes."""
        with self._lock:
            self._advance(self.clock())
            if self._state == OPEN:
                self._rejected.inc()
                return False
            if self._state == HALF_OPEN:
                if self._probes_inflight >= self.half_open_max:
                    self._rejected.inc()
                    return False
                self._probes_inflight += 1
            return True

    def record_success(self) -> None:
        with self._lock:
            if self._state == HALF_OPEN:
                self._state = CLOSED
                self._failures.clear()
                self._probes_inflight = 0
                self._gauge.set(_STATE_GAUGE[CLOSED])

    def record_failure(self) -> None:
        tripped = False
        with self._lock:
            now = self.clock()
            self._advance(now)
            if self._state == HALF_OPEN:
                # the probe failed: straight back to open, cooldown restarts
                self._state = OPEN
                self._opened_at = now
                self._probes_inflight = 0
                self._gauge.set(_STATE_GAUGE[OPEN])
                return
            self._failures.append(now)
            if (
                self._state == CLOSED
                and len(self._failures) >= self.failure_threshold
            ):
                self._state = OPEN
                self._opened_at = now
                self.trips += 1
                tripped = True
                self._gauge.set(_STATE_GAUGE[OPEN])
                self._trips_counter.inc()
        if tripped:
            logger.warning(
                "circuit breaker %r tripped open (%d failures in %.1fs)",
                self.name, self.failure_threshold, self.window_s,
            )
            from mmlspark_tpu.observability.events import BreakerTripped, get_bus

            bus = get_bus()
            if bus.active:
                bus.publish(BreakerTripped(
                    breaker=self.name,
                    failures=self.failure_threshold,
                    window_s=self.window_s,
                ))


class BreakerRegistry:
    """Get-or-create table of breakers keyed by dependency (host)."""

    def __init__(
        self,
        failure_threshold: int = 10,
        window_s: float = 30.0,
        reset_timeout_s: float = 5.0,
        clock: Callable[[], float] = time.monotonic,
        registry=None,
    ):
        self.failure_threshold = failure_threshold
        self.window_s = window_s
        self.reset_timeout_s = reset_timeout_s
        self.clock = clock
        self.registry = registry
        self._lock = threading.Lock()
        self._breakers: Dict[str, CircuitBreaker] = {}

    def get(self, key: str) -> CircuitBreaker:
        with self._lock:
            br = self._breakers.get(key)
            if br is None:
                br = CircuitBreaker(
                    key,
                    failure_threshold=self.failure_threshold,
                    window_s=self.window_s,
                    reset_timeout_s=self.reset_timeout_s,
                    clock=self.clock,
                    registry=self.registry,
                )
                self._breakers[key] = br
            return br

    def for_url(self, url: str) -> CircuitBreaker:
        """The per-host breaker for an outbound URL (host:port keying: two
        services on one box fail independently)."""
        return self.get(urlsplit(url).netloc or url)


_SHARED: Optional[BreakerRegistry] = None
_SHARED_LOCK = threading.Lock()


def shared_breakers() -> BreakerRegistry:
    """The process-global per-host registry the HTTP clients default to.
    Thresholds are deliberately lenient (10 failures / 30 s) so only a
    sustained outage trips; latency-sensitive callers construct their own
    tighter :class:`BreakerRegistry`."""
    global _SHARED
    with _SHARED_LOCK:
        if _SHARED is None:
            _SHARED = BreakerRegistry()
        return _SHARED
