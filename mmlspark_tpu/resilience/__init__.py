"""mmlspark_tpu.resilience — the request-plane fault-tolerance layer.

PR 1's runtime made the *compute* plane fault-tolerant (task retries,
lineage recompute); this package does the same for the *request* plane
(serving ingress, outbound HTTP, cognitive polling, model downloads).
Four cooperating pieces (``docs/resilience.md``):

- :mod:`~mmlspark_tpu.resilience.breaker`   — per-dependency circuit
  breakers (closed/open/half-open over a rolling failure window) so a
  down dependency is failed fast locally instead of retried into the
  ground;
- :mod:`~mmlspark_tpu.resilience.budget`    — ambient :class:`Deadline`
  propagated via the ``X-Deadline-Ms`` header, plus a token-bucket
  :class:`RetryBudget` bounding retries to a fraction of traffic;
- :mod:`~mmlspark_tpu.resilience.policy`    — the one
  :class:`RetryPolicy` (seeded exponential backoff with full jitter,
  Retry-After on 429 *and* 503 incl. HTTP-dates) shared by the HTTP
  clients, cognitive polling, and the model downloader;
- :mod:`~mmlspark_tpu.resilience.admission` — bounded serving admission
  that sheds overload with ``429`` + ``Retry-After`` instead of queueing
  forever.

Everything takes injectable clocks/sleeps, and
:class:`~mmlspark_tpu.runtime.faults.FaultPlan` grew seeded HTTP faults
(503 storms, latency spikes, connection resets), so the whole layer is
chaos-tested deterministically with zero real sleeps
(``tests/test_resilience.py``).
"""

from mmlspark_tpu.resilience.admission import AdmissionController
from mmlspark_tpu.resilience.breaker import (
    BreakerOpenError,
    BreakerRegistry,
    CircuitBreaker,
    shared_breakers,
)
from mmlspark_tpu.resilience.budget import (
    DEADLINE_HEADER,
    Deadline,
    DeadlineExceededError,
    RetryBudget,
    current_deadline,
    deadline_scope,
)
from mmlspark_tpu.resilience.policy import (
    RETRY_AFTER_STATUSES,
    RETRY_STATUSES,
    RetryPolicy,
    parse_retry_after,
)

__all__ = [
    "AdmissionController",
    "BreakerOpenError",
    "BreakerRegistry",
    "CircuitBreaker",
    "DEADLINE_HEADER",
    "Deadline",
    "DeadlineExceededError",
    "RETRY_AFTER_STATUSES",
    "RETRY_STATUSES",
    "RetryBudget",
    "RetryPolicy",
    "current_deadline",
    "deadline_scope",
    "parse_retry_after",
    "shared_breakers",
]
