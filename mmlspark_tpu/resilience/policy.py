"""RetryPolicy — one retry loop for the whole codebase.

The repo grew three ad-hoc retry loops (``io/http/clients.py``,
``cognitive/base.py`` polling, ``downloader/repository.py``
``retryWithTimeout``), each with its own backoff shape and its own bugs.
This is the single policy object they all now share — the
``HandlingUtils.advanced`` role (``io/http/HTTPClients.scala:64-151``)
done once:

- **seeded exponential backoff with full jitter**: attempt ``n`` sleeps
  ``U(0, min(cap, base * 2**n))`` drawn from a seeded RNG, so retries
  de-synchronize across callers (no thundering herd) while chaos tests
  replay the exact same schedule;
- a fixed ``delays`` schedule overrides the jitter for callers that need
  the legacy deterministic waits;
- ``Retry-After`` parsing handles both delta-seconds and HTTP-date
  (RFC 9110 §10.2.3) and is honored on 503 as well as 429 — a dependency
  saying "come back at T" is obeyed whatever status it said it with;
- an optional :class:`~mmlspark_tpu.resilience.budget.RetryBudget` caps
  retries to a fraction of traffic, and the ambient
  :class:`~mmlspark_tpu.resilience.budget.Deadline` clips every sleep.

``sleep``/``clock`` are injectable so every test runs with a fake clock.
"""

from __future__ import annotations

import email.utils
import logging
import time
from typing import Callable, Mapping, Optional, Sequence, Tuple, TypeVar

import numpy as np

from mmlspark_tpu.resilience.budget import RetryBudget, current_deadline

logger = logging.getLogger("mmlspark_tpu.resilience")

T = TypeVar("T")

#: statuses worth retrying (transient by contract)
RETRY_STATUSES: Tuple[int, ...] = (408, 429, 500, 502, 503, 504)
#: statuses that also carry a Retry-After worth honoring
RETRY_AFTER_STATUSES: Tuple[int, ...] = (429, 503)


def parse_retry_after(
    value: Optional[str], now_wall: Callable[[], float] = time.time
) -> Optional[float]:
    """``Retry-After`` -> seconds to wait: either delta-seconds ("120") or
    an HTTP-date ("Fri, 31 Dec 1999 23:59:59 GMT"). Returns None on
    garbage — an unparseable hint must not break the retry loop."""
    if value is None:
        return None
    value = value.strip()
    try:
        return max(0.0, float(value))
    except ValueError:
        pass
    try:
        dt = email.utils.parsedate_to_datetime(value)
    except (TypeError, ValueError):
        return None
    if dt is None:
        return None
    return max(0.0, dt.timestamp() - now_wall())


class RetryPolicy:
    """Bounded retry schedule: ``max_attempts`` total attempts, sleeps
    from a seeded full-jitter exponential (or a fixed ``delays`` list)."""

    def __init__(
        self,
        max_attempts: int = 4,
        base: float = 0.1,
        cap: float = 5.0,
        delays: Optional[Sequence[float]] = None,
        seed: Optional[int] = None,
        retry_statuses: Sequence[int] = RETRY_STATUSES,
        budget: Optional[RetryBudget] = None,
        sleep: Callable[[float], None] = time.sleep,
        clock: Callable[[], float] = time.monotonic,
        now_wall: Callable[[], float] = time.time,
    ):
        if max_attempts < 1:
            raise ValueError("max_attempts must be >= 1")
        self.max_attempts = int(max_attempts)
        self.base = float(base)
        self.cap = float(cap)
        self.delays = list(delays) if delays is not None else None
        self.retry_statuses = tuple(retry_statuses)
        self.budget = budget
        self.sleep = sleep
        self.clock = clock
        self.now_wall = now_wall
        self._rng = np.random.default_rng(seed)

    @classmethod
    def from_legacy_waits(cls, waits: Sequence[float], **kwargs) -> "RetryPolicy":
        """The old ``retries=(0.1, 0.5, 1.0)`` convention: N fixed waits
        means N+1 attempts with exactly those sleeps between them."""
        return cls(max_attempts=len(waits) + 1, delays=waits, **kwargs)

    # -- pieces (used by the HTTP clients' status-aware loop) ----------------

    def retryable(self, status: int) -> bool:
        return status in self.retry_statuses

    def delay(self, attempt: int) -> float:
        """Sleep before retry number ``attempt`` (0-based)."""
        if self.delays is not None:
            return self.delays[min(attempt, len(self.delays) - 1)]
        bound = min(self.cap, self.base * (2.0 ** attempt))
        return float(self._rng.uniform(0.0, bound))

    def retry_after(
        self, headers: Mapping[str, str], status: int
    ) -> Optional[float]:
        """The server's ``Retry-After`` hint, when the status carries one."""
        if status not in RETRY_AFTER_STATUSES:
            return None
        ci = {k.lower(): v for k, v in headers.items()}
        return parse_retry_after(ci.get("retry-after"), self.now_wall)

    def next_wait(
        self,
        attempt: int,
        status: Optional[int] = None,
        headers: Optional[Mapping[str, str]] = None,
    ) -> float:
        """The full wait computation for one retry: jitter/schedule,
        raised to the server's Retry-After, clipped to the ambient
        deadline's remaining budget."""
        wait = self.delay(attempt)
        if status is not None and headers is not None:
            hinted = self.retry_after(headers, status)
            if hinted is not None:
                wait = max(wait, hinted)
        dl = current_deadline()
        if dl is not None:
            wait = min(wait, max(0.0, dl.remaining()))
        return wait

    def allow_retry(self, attempt: int) -> bool:
        """Retry number ``attempt`` permitted? Checks the attempt bound,
        the retry budget, and the ambient deadline."""
        if attempt >= self.max_attempts - 1:
            return False
        dl = current_deadline()
        if dl is not None and dl.expired:
            return False
        if self.budget is not None and not self.budget.try_spend():
            logger.warning(
                "retry budget exhausted; giving up after attempt %d", attempt + 1
            )
            return False
        return True

    # -- the generic loop (downloader, arbitrary callables) ------------------

    def run(
        self,
        fn: Callable[[], T],
        retry_on: Tuple[type, ...] = (Exception,),
        describe: str = "",
    ) -> T:
        """Call ``fn`` under the policy, retrying on ``retry_on``
        exceptions. The last failure is re-raised once attempts (or the
        budget, or the deadline) run out."""
        if self.budget is not None:
            self.budget.record_request()
        last: Optional[BaseException] = None
        for attempt in range(self.max_attempts):
            try:
                return fn()
            except retry_on as e:
                last = e
                if not self.allow_retry(attempt):
                    break
                self.sleep(self.next_wait(attempt))
        assert last is not None
        raise last
